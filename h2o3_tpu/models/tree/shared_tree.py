"""SharedTree driver — hex/tree/SharedTree.java + gbm/GBM.java + drf/DRF.java.

Reference: SharedTree.java:208 (Driver), :440 (scoreAndBuildTrees), :507
(buildLayer — K concurrent MRTasks, one per tree/class), GBM.java:452
(buildNextKTrees), :981 (ComputePredAndRes), :1235 (GammaPass leaf refit),
:776 (fitBestConstants), DRF.java (mtries column sampling, 0.632 sampling).

TPU-native design: the driver is a controller loop dispatching async device
programs; each tree is max_depth fused level-programs + one residual pass +
one GammaPass — nothing synchronizes to the host except periodic scoring
(score_tree_interval), so the chips never idle on controller round-trips.
The K trees of a multinomial iteration run sequentially (one tree's
histograms already saturate the chips; H2O's tree-level concurrency bought
idle-CPU utilization, not algorithmic speedup). Training-frame predictions
are maintained incrementally: each grown tree's per-row terminal node comes
back from the router (val[heap]), so F-updates are gathers, not tree walks.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.models.model import ModelBase
from h2o3_tpu.models.tree import engine as E
from h2o3_tpu.obs.timeline import span as _span


class SharedTreeEstimator(ModelBase):
    """Common driver for GBM / DRF (and the histogram machinery IF shares)."""

    # mesh-sharded serving: ensembles (TreeArrays pytrees — `_trees` for
    # the single-output distributions, `_trees_k` per class for
    # multinomial) enter the scorer as shared device args. The per-node
    # arrays shard their TREE axis over the optional "model" mesh axis
    # (each model shard walks its tree slice; XLA inserts the cross-shard
    # sum); on the default rows-only mesh that spec degenerates to one
    # replicated copy. `_f0` stays a baked constant — the multinomial
    # scorer concretizes it (float(self._f0[c])) at trace time.
    _serving_param_attrs = ("_trees", "_trees_k")
    _partition_rules = (
        (r"^_trees", jax.sharding.PartitionSpec("model")),
    )

    _tree_defaults = {
        "ntrees": 50, "max_depth": 5, "min_rows": 10.0, "nbins": 20,
        "nbins_cats": 1024, "learn_rate": 0.1, "sample_rate": 1.0,
        "col_sample_rate": 1.0, "col_sample_rate_per_tree": 1.0,
        "min_split_improvement": 1e-5, "mtries": -2,
        "score_tree_interval": 5, "stopping_rounds": 0,
        "stopping_metric": "AUTO", "stopping_tolerance": 1e-3,
        "build_tree_one_node": False, "histogram_type": "AUTO",
        "calibrate_model": False, "balance_classes": False,
        "monotone_constraints": None,
        # nbins_top_level (DHistogram nbins halving): the binned engine
        # uses GLOBAL quantile codes, so an explicit top-level resolution
        # maps to the global bin count: b_val = max(nbins, value/4) capped
        # at 255 (a root histogram at 1024 bins halved 2 levels ≈ 256).
        # None = derive from nbins alone (the engine's own default).
        "nbins_top_level": None,
        # TPU extensions (None = auto: on wherever the kernel family's
        # probe compile passes and the shape qualifies; False = force the
        # dense/sequential reference paths): int8-quantized histogram
        # stats on the 2x-rate int8 MXU path; the radix-factored
        # shallow-window histogram kernel; the level-fused route+hist
        # kernel (ops/hist_pallas.py).
        "int8_hist": None,
        "radix_shallow": None,
        "fused_level": None,
    }

    def _cat_mode(self):
        return "label"  # trees bin label-encoded categoricals natively

    def _validate_early_stopping(self):
        """Fail fast on an unusable stopping_metric (H2O validates at
        build-parameter time, not 2*stopping_rounds scoring events in)."""
        if int(self.params.get("stopping_rounds") or 0) <= 0:
            return
        want = str(self.params.get("stopping_metric") or "AUTO").lower()
        want = {"aucpr": "pr_auc"}.get(want, want)
        if want in ("auto", ""):
            return
        known = {"auc", "pr_auc", "logloss", "rmse", "mae", "r2",
                 "classification_error"}
        cls_only = {"auc", "pr_auc", "logloss", "classification_error"}
        reg_only = {"mae", "r2"}
        if want not in known:
            raise ValueError(f"unknown stopping_metric {want!r}; "
                             f"supported: {sorted(known)}")
        if self._is_classifier and want in reg_only:
            raise ValueError(f"stopping_metric={want!r} is a regression "
                             "metric but the response is categorical")
        if not self._is_classifier and want in cls_only:
            raise ValueError(f"stopping_metric={want!r} is a "
                             "classification metric but the response is "
                             "numeric")

    # ---- shared plumbing -------------------------------------------------
    def _prep(self, frame: Frame):
        self._validate_early_stopping()
        di = self._dinfo
        X = di.matrix(frame)           # (pad, C) f32 NaN-NA (label cats)
        y = di.response(frame)
        w = di.weights(frame)
        w = jnp.where(jnp.isnan(y), 0.0, w)
        yz = jnp.where(jnp.isnan(y), 0.0, y)
        # balance_classes (hex/ModelBuilder class-balancing): reweight so
        # every class carries equal total weight — the weight-based
        # equivalent of the reference's minority over-sampling, with no
        # row duplication on device
        if self.params.get("balance_classes") and self._is_classifier:
            K = self.nclasses
            yi = yz.astype(jnp.int32)
            totals = jax.ops.segment_sum(w, yi, num_segments=K)
            wsum = totals.sum()
            factor = jnp.where(totals > 0, wsum / (K * totals), 1.0)
            w = w * factor[yi]
        return X, yz, w

    def _grower(self):
        p = self.params
        return E.TreeGrower(nbins=int(p["nbins"]),
                            max_depth=int(p["max_depth"]),
                            min_rows=float(p["min_rows"]),
                            min_split_improvement=float(p["min_split_improvement"]))

    def _sample_weights(self, w, key, rate):
        """Per-tree row sampling — on device (no host RNG round-trip)."""
        if rate >= 1.0:
            return w
        u = jax.random.uniform(key, w.shape)
        return w * (u < rate)

    def _col_mask(self, C, key):
        rate = float(self.params.get("col_sample_rate_per_tree") or 1.0)
        if rate >= 1.0:
            return None
        k = max(1, int(round(rate * C)))
        r = jax.random.uniform(key, (C,))
        kth = jnp.sort(r)[k - 1]
        return r <= kth

    def _per_level_mtries(self, C) -> int:
        """col_sample_rate (GBM) / colsample_bylevel (XGBoost) → per-level
        column subsampling, realized as the engine's per-(level,leaf) mtries
        draw. 0 = disabled."""
        rate = float(self.params.get("col_sample_rate") or 1.0)
        if rate >= 1.0:
            return 0
        return max(1, int(round(rate * C)))

    # ---- binned-engine shared setup (GBM + DRF + IF share the histogram
    # machinery, SharedTree.java:507 buildLayer) --------------------------
    def _binned_setup(self, frame: Frame):
        """Quantize the frame ONCE, form the mesh wiring and the grower.
        Returns a context dict used by the per-algo binned drivers."""
        from h2o3_tpu.models.tree import binned as BN
        from h2o3_tpu.parallel import mesh as MESH
        p = self.params
        di = self._dinfo
        X, y, w = self._prep(frame)
        n = int(frame.nrows)
        X, y, w = X[:n], y[:n], w[:n]
        C = X.shape[1]
        is_cat = np.array([c in di.cat_cols for c in di.predictors], bool)
        cards = [di.cardinalities[c] for c in di.cat_cols]
        nbins = int(p["nbins"])
        nbins_cats = int(p.get("nbins_cats") or 1024)
        nbins_top = int(p.get("nbins_top_level") or 0)
        b_val = max(nbins, nbins_top // 4,
                    min(nbins_cats, max(cards, default=0)))
        b_val = int(min(255, max(b_val, 4)))
        # bin edges come from a row sample: STRIDED device slice (a head
        # slice would bias quantiles on ordered data), tiny readback
        stride = max(1, n >> 18)
        from h2o3_tpu.parallel import mrtask as _mr
        Xs = _mr.host_fetch(X[::stride][: 1 << 18])
        spec = BN.make_bins(Xs, is_cat, b_val)

        cl = MESH.cloud()
        shards = cl.n_rows_shards
        multi = shards > 1

        mono = np.zeros(spec.c_pad, np.int32)
        mc = p.get("monotone_constraints") or {}
        for cname, v in mc.items():
            if cname in di.predictors:
                mono[di.predictors.index(cname)] = int(np.sign(v))
        grower = BN.BinnedGrower(
            spec, max_depth=int(p["max_depth"]),
            min_rows=float(p["min_rows"]),
            min_split_improvement=float(p["min_split_improvement"]),
            monotone=mono if mc else None,
            axis_name=MESH.ROWS if multi else None,
            int8_stats=p.get("int8_hist"),
            use_radix_shallow=p.get("radix_shallow"),
            fused_level=p.get("fused_level"))
        n_pad = grower.layout(n, shards=shards if multi else 1)
        # uint8 code plane (1 byte/code in HBM), packed to the Pallas
        # kernels' i32 word layout on TPU — the row axis is untouched so
        # the rows sharding spec below applies to either layout
        codes = BN.prepare_codes(BN.quantize(X, spec, n_pad=n_pad))
        y1 = BN.pad_rows(y, n_pad)
        w1 = BN.pad_rows(w, n_pad)
        if multi:
            from jax.sharding import PartitionSpec as P
            codes = jax.device_put(codes, cl.sharding(P(None, MESH.ROWS)))
            y1 = jax.device_put(y1, cl.rows_sharding(1))
            w1 = jax.device_put(w1, cl.rows_sharding(1))
        # register the code plane with the DKV tier pager: training
        # re-streams it every level, so it is pinned (never an LRU victim
        # mid-build) but now VISIBLE to the HBM accounting that budget
        # demotions are judged against (h2o3_dkv_tier_bytes) — and at
        # uint8/packed size it is 4x smaller than the old i32 planes.
        # The chunk dies with the training context (weakref reaping).
        codes_chunk = None
        from h2o3_tpu.core.tiering import PAGER
        if PAGER.enabled:
            codes_chunk = PAGER.new_chunk(codes, None, label="tree_codes",
                                          pinned=1)
        return dict(BN=BN, X=X, y=y, w=w, y1=y1, w1=w1, codes=codes, n=n,
                    C=C, is_cat=is_cat, spec=spec, grower=grower,
                    n_pad=n_pad, cl=cl, multi=multi,
                    mesh=cl.mesh if multi else None,
                    codes_chunk=codes_chunk)

    def _binned_tree_arrays(self, ctx, chunks, prev=None, lead=None):
        """Assemble E.TreeArrays from trainer chunk outputs (+ an optional
        checkpoint model's arrays prepended). `lead` flattens extra leading
        scan dims (the multinomial (iters, K) case picks class k)."""
        spec, C = ctx["spec"], ctx["C"]
        sel = (lambda a: a) if lead is None else lead
        colT = jnp.concatenate([sel(c[0]) for c in chunks])
        binT = jnp.concatenate([sel(c[1]) for c in chunks])
        nalT = jnp.concatenate([sel(c[2]) for c in chunks])
        wordsT = jnp.concatenate([sel(c[3]) for c in chunks])
        valT = jnp.concatenate([sel(c[4]) for c in chunks])
        gainsT = jnp.concatenate([sel(c[5]) for c in chunks]).sum(0)
        coverT = jnp.concatenate([sel(c[6]) for c in chunks])
        edges_j = jnp.asarray(spec.edges)
        safe_col = jnp.clip(colT, 0, C - 1)
        safe_bin = jnp.clip(binT, 0, spec.edges.shape[1] - 1)
        thrT = edges_j[safe_col, safe_bin]
        any_cat = bool(ctx["is_cat"].any())
        if prev is not None:
            colT = jnp.concatenate([prev.col, colT])
            thrT = jnp.concatenate([prev.thr, thrT])
            nalT = jnp.concatenate([prev.na_left, nalT])
            valT = jnp.concatenate([prev.value, valT])
            coverT = jnp.concatenate([prev.cover, coverT])
            if any_cat:
                pw = prev.catbits if prev.catbits is not None else \
                    jnp.zeros((prev.col.shape[0],) + wordsT.shape[1:],
                              wordsT.dtype)
                wordsT = jnp.concatenate([pw, wordsT])
        ta = E.TreeArrays(
            col=colT, thr=thrT, na_left=nalT, value=valT,
            depth=ctx["grower"].D, cover=coverT,
            catbits=wordsT if any_cat else None,
            col_is_cat=(np.pad(ctx["is_cat"],
                               (0, spec.c_pad - C)) if any_cat else None))
        return ta, gainsT

    # ---- SHAP contributions (Model.PredictContributions analog) ----------
    def predict_contributions(self, test_data: Frame) -> Frame:
        """Per-row TreeSHAP feature contributions + BiasTerm, in margin
        space; rows sum to the margin prediction (genmodel parity)."""
        from h2o3_tpu.models.tree import contrib
        assert getattr(self, "_trees", None) is not None, \
            "contributions supported for regression/binomial tree models"
        X = np.asarray(self._dinfo.matrix(test_data),
                       np.float64)[: test_data.nrows]
        phi = contrib.ensemble_shap(self._trees, X)
        scale, bias0 = self._contrib_scale_bias()
        phi *= scale
        phi[:, -1] += bias0
        names = list(self._dinfo.feature_names) + ["BiasTerm"]
        from h2o3_tpu.core.frame import Vec
        return Frame(names, [Vec.from_numpy(phi[:, j])
                             for j in range(phi.shape[1])])

    def _contrib_scale_bias(self):
        return 1.0, 0.0

    # ---- scoring history / early stopping -------------------------------
    def _record_history(self, ntrees, F, y, w, dist):
        mu = _link_inv_dist(dist, F, udf=getattr(self, "_udf_dist", None))
        from h2o3_tpu.models import metrics as M
        if self._is_classifier:
            m = M.binomial_metrics(y, mu[:, 1], w)
            h = {"number_of_trees": ntrees, "training_logloss": m.logloss,
                 "training_auc": m.auc, "training_pr_auc": m.pr_auc,
                 "training_rmse": m.rmse}
        else:
            m = M.regression_metrics(y, mu, w)
            h = {"number_of_trees": ntrees, "training_rmse": m.rmse,
                 "training_mae": m.mae, "training_r2": m.r2}
        h.update(self._valid_history_entry(dist))
        self._output.scoring_history.append(h)

    # ---- incremental validation scoring (ScoreKeeper valid series) -------
    def _valid_setup(self, f0):
        """Prepare incremental validation margins: the in-progress model
        scores the validation frame at every scoring event
        (SharedTree.doScoringAndSaveModel), so the margins are maintained
        chunk-by-chunk rather than rebuilt from the final ensemble."""
        vf = getattr(self, "_valid_for_scoring", None)
        self._vstate = None
        if vf is None:
            return
        di = self._dinfo
        nv = int(vf.nrows)
        Xv = di.matrix(vf)[:nv]
        yv = di.response(vf)[:nv]
        wv = di.weights(vf)[:nv]
        wv = jnp.where(jnp.isnan(yv), 0.0, wv)
        yv = jnp.where(jnp.isnan(yv), 0.0, yv)
        Fv = jnp.full(nv, float(np.asarray(f0).ravel()[0]), jnp.float32) \
            if np.ndim(f0) == 0 or np.size(f0) == 1 else \
            jnp.tile(jnp.asarray(f0, jnp.float32)[None, :], (nv, 1))
        self._vstate = {"X": Xv, "y": yv, "w": wv, "F": Fv}

    def _valid_advance(self, new_trees, lr):
        """Add a just-trained tree batch's contribution to the validation
        margins (one batched heap-walk over the valid rows)."""
        if self._vstate is None or new_trees.ntrees == 0:
            return
        self._vstate["F"] = self._vstate["F"] + \
            lr * E.predict_ensemble(self._vstate["X"], new_trees)

    def _valid_history_entry(self, dist="gaussian") -> dict:
        if getattr(self, "_vstate", None) is None:
            return {}
        vs = self._vstate
        mu = _link_inv_dist(dist, vs["F"],
                            udf=getattr(self, "_udf_dist", None))
        if self._is_classifier and mu.ndim == 1:
            mu = jnp.stack([1.0 - mu, mu], axis=1)
        vm = self._metrics_from_preds(vs["y"], mu, vs["w"])
        out = {}
        for k in ("logloss", "auc", "pr_auc", "rmse", "mae", "r2"):
            v = getattr(vm, k, None)
            if v is not None:
                out[f"validation_{k}"] = v
        return out

    def _record_history_multi(self, ntrees, F, y, w):
        from h2o3_tpu.models import metrics as M
        P = jax.nn.softmax(F, axis=1)
        m = M.multinomial_metrics(y, P, w)
        h = {"number_of_trees": ntrees, "training_logloss": m.logloss,
             "training_classification_error": m.error}
        h.update(self._valid_history_entry())
        self._output.scoring_history.append(h)

    def _should_stop(self) -> bool:
        """ScoreKeeper.stopEarly: stop when the chosen stopping_metric has
        not improved over the last `stopping_rounds` scoring events."""
        k = int(self.params.get("stopping_rounds") or 0)
        if k <= 0 or len(self._output.scoring_history) < 2 * k:
            return False
        hist = self._output.scoring_history
        want = str(self.params.get("stopping_metric") or "AUTO").lower()
        want = {"aucpr": "pr_auc"}.get(want, want)
        maximize = want in ("auc", "pr_auc", "r2")
        metric = None
        explicit = want not in ("auto", "")
        if explicit:
            # validation series wins when a validation frame was scored
            for prefix in ("validation_", "training_"):
                if prefix + want in hist[-1]:
                    metric = prefix + want
                    break
            if metric is None:
                for key in hist[-1]:
                    if key.endswith("_" + want):
                        metric = key
                        break
            if metric is None:
                raise ValueError(
                    f"stopping_metric={want!r} is not recorded for this "
                    f"problem type (available: {sorted(hist[-1])})")
        if metric is None:
            maximize = False
            for cand in ("validation_logloss", "validation_rmse",
                         "training_logloss", "training_rmse"):
                if cand in hist[-1]:
                    metric = cand
                    break
        if metric is None:
            return False
        vals = [h[metric] for h in hist]
        # tolerance 0 is a VALID value (stop on any non-improvement):
        # no falsy-or fallback; inclusive comparisons so an exact plateau
        # stops; tol scales with |past| so negative metrics (r2 < 0) keep
        # the intended direction (ScoreKeeper.stopEarly semantics)
        tol_raw = self.params.get("stopping_tolerance")
        tol = 1e-3 if tol_raw is None else float(tol_raw)
        if maximize:
            recent = max(vals[-k:])
            past = max(vals[:-k])
            return recent <= past + tol * abs(past)
        recent = min(vals[-k:])
        past = min(vals[:-k])
        return recent >= past - tol * abs(past)

    def _varimp_from_gains(self, gains: np.ndarray):
        names = self._dinfo.feature_names
        tot = gains.sum() or 1.0
        order = np.argsort(-gains)
        self._output.variable_importances = [
            {"variable": names[i], "relative_importance": float(gains[i]),
             "scaled_importance": float(gains[i] / (gains[order[0]] or 1.0)),
             "percentage": float(gains[i] / tot)}
            for i in order]


# ===========================================================================
class H2OGradientBoostingEstimator(SharedTreeEstimator):
    algo = "gbm"
    _defaults = dict(SharedTreeEstimator._tree_defaults)

    # ---- distributions (ComputePredAndRes + GammaPass per family) --------
    def _resolve_dist(self) -> str:
        d = (self.params.get("distribution") or "AUTO").lower()
        if d != "auto":
            return d
        dom = self._dinfo.response_domain
        if dom is None:
            return "gaussian"
        return "bernoulli" if len(dom) == 2 else "multinomial"

    def _fit(self, frame: Frame, job):
        dist = self._resolve_dist()
        self._dist = dist
        # custom distribution UDF (water/udf CDistributionFunc)
        self._udf_dist = None
        if dist == "custom":
            from h2o3_tpu.udf import resolve_udf
            self._udf_dist = resolve_udf(
                self.params.get("custom_distribution_func"))
        if self._binned_ok(dist):
            return self._fit_binned(frame, job, dist)
        X, y, w = self._prep(frame)
        if dist == "multinomial":
            return self._fit_multinomial(X, y, w, job)
        ntrees = int(self.params["ntrees"])
        lr = float(self.params["learn_rate"])
        seed = int(self.params.get("seed") or -1)
        key = jax.random.PRNGKey(seed if seed > 0 else 42)
        grower = self._grower()
        wsum = float(np.asarray(jnp.sum(w)))
        ysum = float(np.asarray(jnp.sum(w * y)))
        ybar = ysum / max(wsum, 1e-30)
        # init F0 (SharedTree init + DistributionFactory links)
        if dist == "custom":
            f0 = float(self._udf_dist.init_f0(ybar))
        elif dist == "bernoulli":
            p0 = min(max(ybar, 1e-10), 1 - 1e-10)
            f0 = math.log(p0 / (1 - p0))
        elif dist in ("poisson", "gamma", "tweedie"):
            f0 = math.log(max(ybar, 1e-10))
        else:
            f0 = ybar
        self._f0 = f0
        F = jnp.full(X.shape[0], f0, jnp.float32)
        sample_rate = float(self.params["sample_rate"])
        trees = []
        # checkpoint restart (ModelBuilder.java:1401, SharedTree.java:132):
        # resume boosting from a prior model's trees
        ckpt = self.params.get("checkpoint")
        if ckpt:
            from h2o3_tpu.core.kvstore import DKV
            prev = DKV.get(ckpt) if isinstance(ckpt, str) else ckpt
            assert prev is not None and prev.algo == self.algo, \
                f"checkpoint {ckpt} not found or wrong algo"
            pt = prev._trees
            assert pt.depth == grower.D, \
                "checkpoint restart requires identical max_depth"
            if pt.cover is not None:
                pcov = pt.cover
            else:
                # prior model predates cover recording: rebuild covers by
                # routing the current training rows through its trees (an
                # approximation of the original in-sample weights, but keeps
                # TreeSHAP's sum-to-margin property intact)
                heaps, _ = E.predict_leaf_ids(X, pt)
                pcov = [E.node_covers(heaps[i], w, nodes=grower.nodes,
                                      D=grower.D) for i in range(pt.ntrees)]
            for i in range(pt.ntrees):
                trees.append((jnp.asarray(pt.col[i]), jnp.asarray(pt.thr[i]),
                              jnp.asarray(pt.na_left[i]),
                              jnp.asarray(pt.value[i]),
                              jnp.asarray(pcov[i])))
            self._f0 = f0 = prev._f0
            F = f0 + lr * E.predict_ensemble(X, pt)
        gains_tot = jnp.zeros(X.shape[1], jnp.float32)
        interval = max(1, int(self.params.get("score_tree_interval") or 5))
        self._valid_setup(f0)
        if trees:   # checkpoint restart: prior ensemble scores valid too
            self._valid_advance(E.stack_trees(trees, grower.D), lr)
        last_scored = len(trees)
        for t in range(len(trees), ntrees):
            with job.phase("grow"):
                key, k1, k2, k3 = jax.random.split(key, 4)
                res, hess = _grad_hess(dist, F, y, udf=self._udf_dist)
                wt = self._sample_weights(w, k1, sample_rate)
                cmask = self._col_mask(X.shape[1], k2)
                col, thr, nal, val, heap, g = grower.grow(
                    X, wt, res, col_mask=cmask, key=k3,
                    mtries=self._per_level_mtries(X.shape[1]))
                gains_tot = gains_tot + g
                if dist != "gaussian":   # GammaPass Newton refit (device)
                    val = E.gamma_pass(heap, wt, res, hess, val,
                                       nodes=grower.nodes)
                cover = E.node_covers(heap, wt, nodes=grower.nodes,
                                      D=grower.D)
                trees.append((col, thr, nal, val, cover))
                F = F + lr * val[heap]
            if (t + 1) % interval == 0 or t == ntrees - 1:
                with job.phase("score"):
                    if self._vstate is not None and len(trees) > last_scored:
                        self._valid_advance(
                            E.stack_trees(trees[last_scored:], grower.D), lr)
                        last_scored = len(trees)
                    self._record_history(t + 1, F, y, w, dist)
                if self._should_stop():
                    break
            job.update(0.1 + 0.8 * (t + 1) / ntrees, f"tree {t+1}")
        self._trees = E.stack_trees(trees, grower.D)
        self._varimp_from_gains(np.asarray(gains_tot, np.float64))
        self._output.model_summary = {
            "number_of_trees": self._trees.ntrees, "max_depth": grower.D,
            "distribution": dist, "learn_rate": lr, "init_f": f0,
        }

    # ---- binned fast path (GlobalQuantilesCalc / tree_method=hist) -------
    def _binned_ok(self, dist) -> bool:
        """Default engine: globally pre-binned codes + the Pallas histogram
        kernel (SURVEY §2.4 row 1). `histogram_type="UniformAdaptive"`
        selects the H2O-exact per-level adaptive engine instead.
        Multinomial, checkpoint restart and col_sample_rate_per_tree all
        run on the binned path now (VERDICT r2 weak #5)."""
        ht = str(self.params.get("histogram_type") or "AUTO").lower()
        if ht not in ("auto", "quantilesglobal", "binned"):
            return False
        if dist not in ("gaussian", "bernoulli", "quasibinomial", "poisson",
                        "gamma", "tweedie", "laplace", "multinomial"):
            return False
        if int(self.params["max_depth"]) > 10:
            return False      # static 2^D leaf arrays: deep trees adaptive
        ckpt = self.params.get("checkpoint")
        if ckpt:
            prev = self._resolve_checkpoint(ckpt)
            # binned restart needs a binned prior (array-stacked trees)
            if (prev._output.model_summary or {}).get("engine") \
                    != "binned_pallas":
                return False
        return True

    def _resolve_checkpoint(self, ckpt):
        from h2o3_tpu.core.kvstore import DKV
        prev = DKV.get(ckpt) if isinstance(ckpt, str) else ckpt
        assert prev is not None and prev.algo == self.algo, \
            f"checkpoint {ckpt} not found or wrong algo"
        return prev

    def _fit_binned(self, frame: Frame, job, dist):
        if dist == "multinomial":
            return self._fit_binned_multinomial(frame, job)
        p = self.params
        with job.phase("setup"):   # quantile spec + codes + device_put
            ctx = self._binned_setup(frame)
        BN, grower, cl = ctx["BN"], ctx["grower"], ctx["cl"]
        X, y, w, y1, w1 = ctx["X"], ctx["y"], ctx["w"], ctx["y1"], ctx["w1"]
        n, C, n_pad = ctx["n"], ctx["C"], ctx["n_pad"]

        ntrees = int(p["ntrees"])
        lr = float(p["learn_rate"])
        seed = int(p.get("seed") or -1)
        key = jax.random.PRNGKey(seed if seed >= 0 else 42)
        wsum = float(np.asarray(jnp.sum(w)))
        ybar = float(np.asarray(jnp.sum(w * y))) / max(wsum, 1e-30)
        if dist == "bernoulli":
            p0 = min(max(ybar, 1e-10), 1 - 1e-10)
            f0 = math.log(p0 / (1 - p0))
        elif dist in ("poisson", "gamma", "tweedie"):
            f0 = math.log(max(ybar, 1e-10))
        else:
            f0 = ybar

        prev = None
        ckpt = p.get("checkpoint")
        if ckpt:
            # binned restart (SharedTree.java:132): resume margins from the
            # prior ensemble's predictions on the training rows
            prev_model = self._resolve_checkpoint(ckpt)
            prev = prev_model._trees
            assert prev.depth == grower.D, \
                "checkpoint restart requires identical max_depth"
            f0 = prev_model._f0
            Fp = f0 + lr * E.predict_ensemble(X, prev)
            F = BN.pad_rows(Fp.astype(jnp.float32), n_pad)
        else:
            F = jnp.where(jnp.arange(n_pad) < n, f0, 0.0) \
                .astype(jnp.float32)
        self._f0 = f0
        if ctx["multi"]:
            F = jax.device_put(F, cl.rows_sharding(1))

        interval = max(1, int(p.get("score_tree_interval") or 5))
        mtries = self._per_level_mtries(C)
        sample_rate = float(p["sample_rate"])
        col_rate_tree = float(p.get("col_sample_rate_per_tree") or 1.0)
        self._valid_setup(f0)
        if prev is not None:
            # validation margins must include the checkpoint ensemble too
            self._valid_advance(prev, lr)
        chunks = []
        done = prev.ntrees if prev is not None else 0
        if prev is not None and done >= ntrees:
            raise ValueError(
                f"checkpoint model already has {done} trees; ntrees "
                f"({ntrees}) must exceed it to continue training "
                "(ModelBuilder checkpoint validation)")
        while done < ntrees:
            k = min(interval, ntrees - done)
            with job.phase("grow"), \
                    _span("gbm.chunk", trees=k, rows=n, engine="binned"):
                trainer = BN.gbm_chunk_trainer(
                    grower, n, dist=dist, eta=lr, sample_rate=sample_rate,
                    mtries=mtries, k_trees=k, col_rate_tree=col_rate_tree,
                    mesh=ctx["mesh"])
                key, kc = jax.random.split(key)
                F, trees = trainer(ctx["codes"], y1, w1, F, kc)
            E.ROW_TREES.inc(n * k, engine="binned")
            chunks.append(trees)
            done += k
            with job.phase("score"):
                if self._vstate is not None:
                    ta_chunk, _ = self._binned_tree_arrays(ctx, [trees])
                    self._valid_advance(ta_chunk, lr)
                self._record_history(done, F[:n], y, w, dist)
            job.update(0.1 + 0.8 * done / ntrees, f"tree {done}")
            if self._should_stop() or job.budget_exhausted:
                break

        self._trees, gainsT = self._binned_tree_arrays(ctx, chunks,
                                                       prev=prev)
        self._varimp_from_gains(np.asarray(gainsT[:C], np.float64))
        self._output.model_summary = {
            "number_of_trees": int(self._trees.ntrees),
            "max_depth": grower.D, "distribution": dist, "learn_rate": lr,
            "init_f": f0, "engine": "binned_pallas",
            "nbins_effective": ctx["spec"].b_val,
        }

    def _fit_binned_multinomial(self, frame: Frame, job):
        """K class trees per iteration through ONE jitted binned program
        (the SharedTree.java:548-561 K-tree layer)."""
        self._vstate = None   # no multinomial validation series (yet)
        p = self.params
        ctx = self._binned_setup(frame)
        BN, grower, cl = ctx["BN"], ctx["grower"], ctx["cl"]
        y, w, y1, w1 = ctx["y"], ctx["w"], ctx["y1"], ctx["w1"]
        n, C, n_pad = ctx["n"], ctx["C"], ctx["n_pad"]
        K = self.nclasses
        ntrees = int(p["ntrees"])
        lr = float(p["learn_rate"])
        seed = int(p.get("seed") or -1)
        key = jax.random.PRNGKey(seed if seed >= 0 else 42)
        from h2o3_tpu.parallel import mrtask as _mr
        wn = _mr.host_fetch(w).astype(np.float64)
        yin = _mr.host_fetch(y.astype(jnp.int32))
        f0 = np.zeros(K, np.float32)
        for c in range(K):
            pc = (wn * (yin == c)).sum() / max(wn.sum(), 1e-30)
            f0[c] = math.log(max(pc, 1e-10))

        prevs = None
        ckpt = p.get("checkpoint")
        if ckpt:
            prev_model = self._resolve_checkpoint(ckpt)
            prevs = prev_model._trees_k
            assert prevs[0].depth == grower.D, \
                "checkpoint restart requires identical max_depth"
            f0 = prev_model._f0
            Fc = jnp.stack(
                [f0[c] + lr * E.predict_ensemble(ctx["X"], prevs[c])
                 for c in range(K)], axis=1).astype(jnp.float32)
            F = jnp.zeros((n_pad, K), jnp.float32).at[:n].set(Fc)
        else:
            F = jnp.where((jnp.arange(n_pad) < n)[:, None],
                          jnp.asarray(f0)[None, :], 0.0) \
                .astype(jnp.float32)
        self._f0 = f0
        if ctx["multi"]:
            from jax.sharding import PartitionSpec as P
            from h2o3_tpu.parallel import mesh as MESH
            F = jax.device_put(F, cl.sharding(P(MESH.ROWS, None)))

        interval = max(1, int(p.get("score_tree_interval") or 5))
        mtries = self._per_level_mtries(C)
        sample_rate = float(p["sample_rate"])
        col_rate_tree = float(p.get("col_sample_rate_per_tree") or 1.0)
        chunks = []
        done = prevs[0].ntrees if prevs is not None else 0
        if prevs is not None and done >= ntrees:
            raise ValueError(
                f"checkpoint model already has {done} trees per class; "
                f"ntrees ({ntrees}) must exceed it to continue training")
        while done < ntrees:
            k = min(interval, ntrees - done)
            with job.phase("grow"), \
                    _span("gbm.chunk", trees=k * K, rows=n,  # h2o3-ok: R011 same stage as binomial path, engine= attr disambiguates
                          engine="binned_multinomial"):
                trainer = BN.gbm_multi_chunk_trainer(
                    grower, n, n_classes=K, eta=lr, sample_rate=sample_rate,
                    mtries=mtries, k_iters=k, col_rate_tree=col_rate_tree,
                    mesh=ctx["mesh"])
                key, kc = jax.random.split(key)
                F, trees = trainer(ctx["codes"], y1, w1, F, kc)
            E.ROW_TREES.inc(n * k * K, engine="binned")
            chunks.append(trees)
            done += k
            with job.phase("score"):
                self._record_history_multi(done, F[:n], y, w)
            job.update(0.1 + 0.8 * done / ntrees, f"iter {done}")
            if self._should_stop() or job.budget_exhausted:
                break

        # chunks hold (iters, K, ...) arrays; split into per-class ensembles
        self._trees_k = []
        gains_tot = None
        for c in range(K):
            sel = (lambda a, c=c: a[:, c])
            ta, g = self._binned_tree_arrays(
                ctx, chunks, prev=prevs[c] if prevs is not None else None,
                lead=sel)
            self._trees_k.append(ta)
            gains_tot = g if gains_tot is None else gains_tot + g
        self._varimp_from_gains(np.asarray(gains_tot[:C], np.float64))
        self._output.model_summary = {
            "number_of_trees": sum(t.ntrees for t in self._trees_k),
            "max_depth": grower.D, "distribution": "multinomial",
            "learn_rate": lr, "engine": "binned_pallas",
        }

    def _fit_multinomial(self, X, y, w, job):
        self._vstate = None   # no multinomial validation series (yet)
        K = self.nclasses
        ntrees = int(self.params["ntrees"])
        lr = float(self.params["learn_rate"])
        seed = int(self.params.get("seed") or -1)
        key = jax.random.PRNGKey(seed if seed > 0 else 42)
        grower = self._grower()
        yi = y.astype(jnp.int32)
        from h2o3_tpu.parallel import mrtask as _mr
        wn = _mr.host_fetch(w).astype(np.float64)
        yin = _mr.host_fetch(yi)
        f0 = np.zeros(K, np.float32)
        for c in range(K):
            pc = (wn * (yin == c)).sum() / max(wn.sum(), 1e-30)
            f0[c] = math.log(max(pc, 1e-10))
        self._f0 = f0
        F = jnp.tile(jnp.asarray(f0)[None, :], (X.shape[0], 1))
        trees_k = [[] for _ in range(K)]
        gains_tot = jnp.zeros(X.shape[1], jnp.float32)
        interval = max(1, int(self.params.get("score_tree_interval") or 5))
        onehot = jax.nn.one_hot(yi, K)
        sample_rate = float(self.params["sample_rate"])
        for t in range(ntrees):
            key, k1, k2 = jax.random.split(key, 3)
            P = jax.nn.softmax(F, axis=1)
            R = onehot - P                       # (n, K) residuals
            wt = self._sample_weights(w, k1, sample_rate)
            cmask = self._col_mask(X.shape[1], k2)
            newF = []
            for c in range(K):
                key, kc = jax.random.split(key)
                res = R[:, c]
                col, thr, nal, val, heap, g = grower.grow(
                    X, wt, res, col_mask=cmask, key=kc,
                    mtries=self._per_level_mtries(X.shape[1]))
                gains_tot = gains_tot + g
                absr = jnp.abs(res)
                val = E.gamma_pass(heap, wt, res, absr * (1 - absr), val,
                                   nodes=grower.nodes, scale=(K - 1) / K)
                cover = E.node_covers(heap, wt, nodes=grower.nodes,
                                      D=grower.D)
                trees_k[c].append((col, thr, nal, val, cover))
                newF.append(F[:, c] + lr * val[heap])
            F = jnp.stack(newF, axis=1)
            if (t + 1) % interval == 0 or t == ntrees - 1:
                self._record_history_multi(t + 1, F, y, w)
                if self._should_stop():
                    break
            job.update(0.1 + 0.8 * (t + 1) / ntrees, f"iter {t+1}")
        self._trees_k = [E.stack_trees(tl, grower.D) for tl in trees_k]
        self._varimp_from_gains(np.asarray(gains_tot, np.float64))
        self._output.model_summary = {
            "number_of_trees": sum(t.ntrees for t in self._trees_k),
            "max_depth": grower.D, "distribution": "multinomial",
        }

    # ---- scoring ---------------------------------------------------------
    def _score_matrix(self, X):
        lr = float(self.params["learn_rate"])
        if self._dist == "multinomial":
            Fs = [jnp.full(X.shape[0], float(self._f0[c]), jnp.float32)
                  + lr * E.predict_ensemble(X, ta)
                  for c, ta in enumerate(self._trees_k)]
            return jax.nn.softmax(jnp.stack(Fs, axis=1), axis=1)
        F = self._f0 + lr * E.predict_ensemble(X, self._trees)
        return _link_inv_dist(self._dist, F,
                              udf=getattr(self, "_udf_dist", None))

    def _contrib_scale_bias(self):
        return float(self.params["learn_rate"]), float(self._f0)



# ---------------------------------------------------------------------------
@jax.jit
def _bernoulli_grad(F, y):
    p = jax.nn.sigmoid(F)
    return y - p, p * (1 - p)


def _grad_hess(dist, F, y, udf=None):
    """ComputePredAndRes (GBM.java:981): per-row pseudo-residual + hessian."""
    if udf is not None:
        return udf.grad_hess(F, y)
    if dist == "gaussian":
        return y - F, jnp.ones_like(F)
    if dist == "bernoulli" or dist == "quasibinomial":
        return _bernoulli_grad(F, y)
    if dist == "poisson":
        mu = jnp.exp(F)
        return y - mu, mu
    if dist == "gamma":
        mu = jnp.exp(F)
        return y / mu - 1.0, y / mu
    if dist == "tweedie":
        mu = jnp.exp(F)
        return y * jnp.power(mu, -0.5) - jnp.power(mu, 0.5), \
            0.5 * (y * jnp.power(mu, -0.5) + jnp.power(mu, 0.5))
    if dist == "laplace":
        return jnp.sign(y - F), jnp.ones_like(F)
    raise NotImplementedError(f"GBM distribution {dist}")


def _link_inv_dist(dist, F, udf=None):
    if udf is not None:
        return udf.link_inv(F)
    if dist in ("bernoulli", "quasibinomial"):
        p = jax.nn.sigmoid(F)
        return jnp.stack([1 - p, p], axis=1)
    if dist in ("poisson", "gamma", "tweedie"):
        return jnp.exp(F)
    return F
