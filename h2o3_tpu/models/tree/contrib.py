"""SHAP predict_contributions for tree ensembles.

Reference surface: hex/Model.PredictContributions + the genmodel per-algo
contribution scorers (GBM/DRF/XGBoost MOJOs); h2o-py
`model.predict_contributions(frame)` returns one column per feature plus
`BiasTerm`, summing to the margin prediction per row.

Implementation: exact path-dependent TreeSHAP (Lundberg & Lee) over the dense
heap trees, in native C++ (native/treeshap.cpp, ctypes ABI like the CSV
parser) — scoring artifacts are host-side in the reference too; the TPU chips
stay on the training path. Node covers are recorded on device during
training (engine.node_covers)."""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB = None


def _lib():
    global _LIB
    if _LIB is None:
        from h2o3_tpu.io.fastcsv import native_dir
        path = os.path.join(native_dir(), "libtreeshap.so")
        try:
            lib = ctypes.CDLL(path)
        except OSError as e:
            raise RuntimeError(
                f"native TreeSHAP library not built ({path}); run "
                f"`make -C native` to build it") from e
        lib.treeshap_ensemble.restype = None
        lib.treeshap_ensemble.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double)]
        lib.treeshap_ensemble_cat.restype = None
        lib.treeshap_ensemble_cat.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double)]
        _LIB = lib
    return _LIB


def ensemble_shap(trees, X: np.ndarray) -> np.ndarray:
    """phi (n, C+1) for one TreeArrays ensemble; raw (unscaled) tree values.
    X: (n, C) float64, NaN = NA."""
    col = np.ascontiguousarray(np.asarray(trees.col), np.int32)
    thr = np.ascontiguousarray(np.asarray(trees.thr), np.float32)
    nal = np.ascontiguousarray(np.asarray(trees.na_left), np.uint8)
    val = np.ascontiguousarray(np.asarray(trees.value), np.float32)
    assert trees.cover is not None, \
        "model was trained before covers were recorded; retrain to get SHAP"
    cov = np.ascontiguousarray(np.asarray(trees.cover), np.float32)
    X = np.ascontiguousarray(X, np.float64)
    n, C = X.shape
    T, nodes = col.shape
    phi = np.zeros((n, C + 1), np.float64)
    has_cat = (trees.catbits is not None and trees.col_is_cat is not None
               and bool(np.any(np.asarray(trees.col_is_cat))))
    if has_cat:
        catb = np.ascontiguousarray(np.asarray(trees.catbits), np.uint32)
        iscat = np.zeros(C, np.uint8)
        flags = np.asarray(trees.col_is_cat, bool)
        iscat[: min(C, flags.size)] = flags[:C]
        _lib().treeshap_ensemble_cat(
            T, nodes, trees.depth, C, n,
            col.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            thr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            nal.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            val.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            cov.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            catb.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            iscat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            int(catb.shape[-1]),
            X.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            phi.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        return phi
    _lib().treeshap_ensemble(
        T, nodes, trees.depth, C, n,
        col.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        thr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        nal.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        val.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        cov.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        phi.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return phi
