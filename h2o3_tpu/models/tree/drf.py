"""DRF — hex/tree/drf/DRF.java: random forest on the shared histogram engine.

Reference: DRF.java (357 LoC): independent trees on bootstrap-ish samples
(sample_rate 0.632 without replacement), mtries column sampling (−1 → √C for
classification, C/3 for regression), leaves predict in-leaf response means
(class frequency for classification); ensemble prediction is the average.
OOB scoring (reference default) is replaced by on-sample metrics this round.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.models.tree import engine as E
from h2o3_tpu.models.tree.shared_tree import SharedTreeEstimator


class H2ORandomForestEstimator(SharedTreeEstimator):
    algo = "drf"
    _defaults = dict(SharedTreeEstimator._tree_defaults)
    _defaults.update({"sample_rate": 0.632, "max_depth": 20, "ntrees": 50,
                      "min_rows": 1.0, "binomial_double_trees": False})

    def _fit(self, frame: Frame, job):
        X, y, w = self._prep(frame)
        C = X.shape[1]
        K = self.nclasses
        ntrees = int(self.params["ntrees"])
        seed = int(self.params.get("seed") or -1)
        rng = np.random.default_rng(seed if seed > 0 else 42)
        grower = self._grower()
        mtries = int(self.params.get("mtries") or -1)
        if mtries == -1:
            mtries = max(1, int(math.sqrt(C))) if K > 1 else max(1, C // 3)
        elif mtries <= 0:
            mtries = C
        gains = np.zeros(C, np.float64)
        if K > 2:
            onehot = jax.nn.one_hot(y.astype(jnp.int32), K)
            trees_k = [[] for _ in range(K)]
            for t in range(ntrees):
                wt = self._sample_weights(w, rng,
                                          float(self.params["sample_rate"]))
                for c in range(K):
                    col, thr, nal, val, g = grower.grow(
                        X, wt, onehot[:, c], rng=rng, mtries=mtries)
                    gains += g
                    trees_k[c].append((col, thr, nal, val))
                job.update(0.1 + 0.8 * (t + 1) / ntrees, f"tree {t+1}")
            self._trees_k = [self._finish_trees(tl, grower.D)
                             for tl in trees_k]
        else:
            trees = []
            for t in range(ntrees):
                wt = self._sample_weights(w, rng,
                                          float(self.params["sample_rate"]))
                col, thr, nal, val, g = grower.grow(X, wt, y, rng=rng,
                                                    mtries=mtries)
                gains += g
                trees.append((col, thr, nal, val))
                job.update(0.1 + 0.8 * (t + 1) / ntrees, f"tree {t+1}")
            self._trees = self._finish_trees(trees, grower.D)
        self._varimp_from_gains(gains)
        self._output.model_summary = {
            "number_of_trees": ntrees, "max_depth": grower.D,
            "mtries": mtries, "sample_rate": self.params["sample_rate"],
        }

    def _score_matrix(self, X):
        K = self.nclasses
        if K > 2:
            Ps = [E.predict_ensemble(X, ta) / ta.ntrees
                  for ta in self._trees_k]
            P = jnp.clip(jnp.stack(Ps, axis=1), 0.0, 1.0)
            s = P.sum(axis=1, keepdims=True)
            return P / jnp.maximum(s, 1e-10)
        mean = E.predict_ensemble(X, self._trees) / self._trees.ntrees
        if self._is_classifier:
            p = jnp.clip(mean, 0.0, 1.0)
            return jnp.stack([1 - p, p], axis=1)
        return mean
