"""DRF — hex/tree/drf/DRF.java: random forest on the shared histogram engine.

Reference: DRF.java (357 LoC): independent trees on sampled rows (sample_rate
0.632 without replacement), mtries column sampling per node (−1 → √C for
classification, C/3 for regression), leaves predict in-leaf response means
(class frequency for classification); ensemble prediction is the average.
OOB scoring is the reference default (DRF.java:78 doOOBScoring()=true):
regression/binomial runs ride the binned engine's drf_chunk_trainer which
accumulates (oob_sum, oob_cnt) per row inside the jitted K-tree program,
and the reported training metrics come from those held-out rows.

TPU-native: per-node mtries is drawn per (level, leaf) inside the fused
level program from the tree's PRNG key — no host RNG; trees of a chunk run
in ONE lax.scan dispatch; multinomial (K>2) stays on the adaptive engine.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.models.tree import engine as E
from h2o3_tpu.models.tree.shared_tree import SharedTreeEstimator


class H2ORandomForestEstimator(SharedTreeEstimator):
    algo = "drf"
    _defaults = dict(SharedTreeEstimator._tree_defaults)
    _defaults.update({"sample_rate": 0.632, "max_depth": 20, "ntrees": 50,
                      "min_rows": 1.0, "binomial_double_trees": False})

    def _resolve_mtries(self, C, K):
        mtries = int(self.params.get("mtries") or -1)
        if mtries == -1:
            return max(1, int(math.sqrt(C))) if K > 1 else max(1, C // 3)
        if mtries <= 0:
            return C
        return mtries

    def _fit(self, frame: Frame, job):
        ht = str(self.params.get("histogram_type") or "AUTO").lower()
        if (self.nclasses <= 2 and int(self.params["max_depth"]) <= 10
                and ht in ("auto", "quantilesglobal", "binned")):
            return self._fit_binned_drf(frame, job)
        X, y, w = self._prep(frame)
        C = X.shape[1]
        K = self.nclasses
        ntrees = int(self.params["ntrees"])
        seed = int(self.params.get("seed") or -1)
        key = jax.random.PRNGKey(seed if seed > 0 else 42)
        grower = self._grower()
        mtries = self._resolve_mtries(C, K)
        sample_rate = float(self.params["sample_rate"])
        gains_tot = jnp.zeros(C, jnp.float32)
        oob_sum = jnp.zeros(X.shape[0], jnp.float32)
        oob_cnt = jnp.zeros(X.shape[0], jnp.float32)
        if K > 2:
            if int(self.params.get("stopping_rounds") or 0) > 0:
                raise NotImplementedError(
                    "stopping_rounds for multinomial DRF is not supported "
                    "yet (no per-class OOB vote series); set "
                    "stopping_rounds=0 or use binomial/regression DRF")
            onehot = jax.nn.one_hot(y.astype(jnp.int32), K)
            trees_k = [[] for _ in range(K)]
            for t in range(ntrees):
                key, k1 = jax.random.split(key)
                wt = self._sample_weights(w, k1, sample_rate)
                for c in range(K):
                    key, kc = jax.random.split(key)
                    col, thr, nal, val, heap, g = grower.grow(
                        X, wt, onehot[:, c], key=kc, mtries=mtries)
                    gains_tot = gains_tot + g
                    trees_k[c].append((col, thr, nal, val,
                                       E.node_covers(heap, wt,
                                                     nodes=grower.nodes,
                                                     D=grower.D)))
                job.update(0.1 + 0.8 * (t + 1) / ntrees, f"tree {t+1}")
                if job.budget_exhausted:
                    break
            self._trees_k = [E.stack_trees(tl, grower.D) for tl in trees_k]
        else:
            interval = max(1, int(self.params.get("score_tree_interval")
                                  or 5))
            self._valid_setup(0.0)
            trees = []
            scored_at = 0
            for t in range(ntrees):
                key, k1, k2 = jax.random.split(key, 3)
                u = jax.random.uniform(k1, w.shape)
                inbag = u < sample_rate
                wt = w * inbag
                col, thr, nal, val, heap, g = grower.grow(X, wt, y, key=k2,
                                                          mtries=mtries)
                gains_tot = gains_tot + g
                # OOB accumulation (doOOBScoring, DRF.java:78): rows held
                # out of this tree's bag vote with val[heap]
                oob = (~inbag) & (w > 0)
                oob_sum = oob_sum + jnp.where(oob, val[heap], 0.0)
                oob_cnt = oob_cnt + oob.astype(jnp.float32)
                trees.append((col, thr, nal, val,
                              E.node_covers(heap, wt, nodes=grower.nodes,
                                            D=grower.D)))
                job.update(0.1 + 0.8 * (t + 1) / ntrees, f"tree {t+1}")
                if (t + 1) % interval == 0 or t + 1 == ntrees:
                    if self._vstate is not None:
                        self._valid_advance(
                            E.stack_trees(trees[scored_at:], grower.D), 1.0)
                    scored_at = len(trees)
                    self._record_history_drf(t + 1, oob_sum, oob_cnt, y, w)
                    if self._should_stop():
                        break
                if job.budget_exhausted:
                    break
            self._trees = E.stack_trees(trees, grower.D)
            self._oob_metrics = self._metrics_from_oob(oob_sum, oob_cnt,
                                                       y, w)
        self._varimp_from_gains(np.asarray(gains_tot, np.float64))
        built = len(trees_k[0]) if K > 2 else int(self._trees.ntrees)
        self._output.model_summary = {
            "number_of_trees": built, "max_depth": grower.D,
            "mtries": mtries, "sample_rate": sample_rate,
            "oob_scored": K <= 2,
        }

    # ---- binned fast path (depth <= 10): OOB inside the jitted program ---
    def _fit_binned_drf(self, frame: Frame, job):
        p = self.params
        ctx = self._binned_setup(frame)
        BN, grower = ctx["BN"], ctx["grower"]
        y, w, y1, w1 = ctx["y"], ctx["w"], ctx["y1"], ctx["w1"]
        n, C, n_pad = ctx["n"], ctx["C"], ctx["n_pad"]
        K = self.nclasses
        ntrees = int(p["ntrees"])
        seed = int(p.get("seed") or -1)
        key = jax.random.PRNGKey(seed if seed >= 0 else 42)
        mtries = self._resolve_mtries(C, K)
        sample_rate = float(p["sample_rate"])
        col_rate_tree = float(p.get("col_sample_rate_per_tree") or 1.0)
        oob_sum = jnp.zeros(n_pad, jnp.float32)
        oob_cnt = jnp.zeros(n_pad, jnp.float32)
        if ctx["multi"]:
            oob_sum = jax.device_put(oob_sum, ctx["cl"].rows_sharding(1))
            oob_cnt = jax.device_put(oob_cnt, ctx["cl"].rows_sharding(1))
        interval = max(1, int(p.get("score_tree_interval") or 5))
        self._valid_setup(0.0)
        chunks = []
        done = 0
        while done < ntrees:
            k = min(interval, ntrees - done)
            trainer = BN.drf_chunk_trainer(
                grower, n, sample_rate=sample_rate, mtries=mtries,
                k_trees=k, col_rate_tree=col_rate_tree, mesh=ctx["mesh"])
            key, kc = jax.random.split(key)
            oob_sum, oob_cnt, trees = trainer(ctx["codes"], y1, w1,
                                              oob_sum, oob_cnt, kc)
            chunks.append(trees)
            done += k
            if self._vstate is not None:
                ta_chunk, _ = self._binned_tree_arrays(ctx, [trees])
                self._valid_advance(ta_chunk, 1.0)
            self._record_history_drf(done, oob_sum[:n], oob_cnt[:n], y, w)
            job.update(0.1 + 0.8 * done / ntrees, f"tree {done}")
            if self._should_stop() or job.budget_exhausted:
                break

        self._trees, gainsT = self._binned_tree_arrays(ctx, chunks)
        self._oob_metrics = self._metrics_from_oob(
            oob_sum[:n], oob_cnt[:n], y, w)
        self._varimp_from_gains(np.asarray(gainsT[:C], np.float64))
        self._output.model_summary = {
            "number_of_trees": int(self._trees.ntrees),
            "max_depth": grower.D, "mtries": mtries,
            "sample_rate": sample_rate, "engine": "binned_pallas",
            "oob_scored": True,
        }

    # ---- scoring history / early stopping (OOB series) -------------------
    # The reference DRF records its ScoreKeeper series from OOB predictions
    # (doOOBScoring()=true) and honors stopping_rounds on it; we mirror
    # that: history entries come from the OOB accumulators, validation
    # entries from incrementally advanced margins (sum of tree votes,
    # averaged at scoring time since DRF predicts the ensemble mean).
    def _record_history_drf(self, done, oob_sum, oob_cnt, y, w):
        m = self._metrics_from_oob(oob_sum, oob_cnt, y, w)
        if self._is_classifier:
            h = {"number_of_trees": done, "training_logloss": m.logloss,
                 "training_auc": m.auc, "training_pr_auc": m.pr_auc,
                 "training_rmse": m.rmse}
        else:
            h = {"number_of_trees": done, "training_rmse": m.rmse,
                 "training_mae": m.mae, "training_r2": m.r2}
        h.update(self._valid_history_entry_drf(done))
        self._output.scoring_history.append(h)

    def _valid_history_entry_drf(self, done) -> dict:
        if getattr(self, "_vstate", None) is None:
            return {}
        vs = self._vstate
        mu = vs["F"] / max(done, 1)          # vote sum → ensemble mean
        if self._is_classifier:
            mu = jnp.clip(mu, 1e-7, 1.0 - 1e-7)
            mu = jnp.stack([1.0 - mu, mu], axis=1)
        vm = self._metrics_from_preds(vs["y"], mu, vs["w"])
        out = {}
        for k in ("logloss", "auc", "pr_auc", "rmse", "mae", "r2"):
            v = getattr(vm, k, None)
            if v is not None:
                out[f"validation_{k}"] = v
        return out

    def _metrics_from_oob(self, oob_sum, oob_cnt, y, w):
        """Metrics over rows that were OOB for >= 1 tree, weighted as in
        training; the reference reports these as the model's training
        metrics when doOOBScoring() (ScoreBuildHistogram OOB rows)."""
        from h2o3_tpu.models import metrics as M
        has = oob_cnt > 0
        pred = oob_sum / jnp.maximum(oob_cnt, 1.0)
        wm = w * has
        if self._is_classifier:
            # clip away exact 0/1 votes so logloss stays finite (rows OOB
            # for few trees produce degenerate vote fractions)
            p = jnp.clip(pred, 1e-7, 1.0 - 1e-7)
            return M.binomial_metrics(y, p, wm,
                                      domain=self._dinfo.response_domain)
        return M.regression_metrics(y, pred, wm)

    def _score_train_valid(self, frame, valid):
        super()._score_train_valid(frame, valid)
        if getattr(self, "_oob_metrics", None) is not None:
            # doOOBScoring()=true: the reported training metrics are OOB
            self._output.training_metrics = self._oob_metrics

    def _contrib_scale_bias(self):
        # DRF prediction is the tree average (probability space for binomial)
        return 1.0 / self._trees.ntrees, 0.0

    def _score_matrix(self, X):
        K = self.nclasses
        if K > 2:
            Ps = [E.predict_ensemble(X, ta) / ta.ntrees
                  for ta in self._trees_k]
            P = jnp.clip(jnp.stack(Ps, axis=1), 0.0, 1.0)
            s = P.sum(axis=1, keepdims=True)
            return P / jnp.maximum(s, 1e-10)
        mean = E.predict_ensemble(X, self._trees) / self._trees.ntrees
        if self._is_classifier:
            p = jnp.clip(mean, 0.0, 1.0)
            return jnp.stack([1 - p, p], axis=1)
        return mean
