"""DRF — hex/tree/drf/DRF.java: random forest on the shared histogram engine.

Reference: DRF.java (357 LoC): independent trees on sampled rows (sample_rate
0.632 without replacement), mtries column sampling per node (−1 → √C for
classification, C/3 for regression), leaves predict in-leaf response means
(class frequency for classification); ensemble prediction is the average.
OOB scoring (reference default) is replaced by on-sample metrics this round.

TPU-native: per-node mtries is drawn per (level, leaf) inside the fused level
program (engine._level_step) from the tree's PRNG key — no host RNG.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.models.tree import engine as E
from h2o3_tpu.models.tree.shared_tree import SharedTreeEstimator


class H2ORandomForestEstimator(SharedTreeEstimator):
    algo = "drf"
    _defaults = dict(SharedTreeEstimator._tree_defaults)
    _defaults.update({"sample_rate": 0.632, "max_depth": 20, "ntrees": 50,
                      "min_rows": 1.0, "binomial_double_trees": False})

    def _fit(self, frame: Frame, job):
        X, y, w = self._prep(frame)
        C = X.shape[1]
        K = self.nclasses
        ntrees = int(self.params["ntrees"])
        seed = int(self.params.get("seed") or -1)
        key = jax.random.PRNGKey(seed if seed > 0 else 42)
        grower = self._grower()
        mtries = int(self.params.get("mtries") or -1)
        if mtries == -1:
            mtries = max(1, int(math.sqrt(C))) if K > 1 else max(1, C // 3)
        elif mtries <= 0:
            mtries = C
        sample_rate = float(self.params["sample_rate"])
        gains_tot = jnp.zeros(C, jnp.float32)
        if K > 2:
            onehot = jax.nn.one_hot(y.astype(jnp.int32), K)
            trees_k = [[] for _ in range(K)]
            for t in range(ntrees):
                key, k1 = jax.random.split(key)
                wt = self._sample_weights(w, k1, sample_rate)
                for c in range(K):
                    key, kc = jax.random.split(key)
                    col, thr, nal, val, heap, g = grower.grow(
                        X, wt, onehot[:, c], key=kc, mtries=mtries)
                    gains_tot = gains_tot + g
                    trees_k[c].append((col, thr, nal, val,
                                       E.node_covers(heap, wt,
                                                     nodes=grower.nodes,
                                                     D=grower.D)))
                job.update(0.1 + 0.8 * (t + 1) / ntrees, f"tree {t+1}")
            self._trees_k = [E.stack_trees(tl, grower.D) for tl in trees_k]
        else:
            trees = []
            for t in range(ntrees):
                key, k1, k2 = jax.random.split(key, 3)
                wt = self._sample_weights(w, k1, sample_rate)
                col, thr, nal, val, heap, g = grower.grow(X, wt, y, key=k2,
                                                          mtries=mtries)
                gains_tot = gains_tot + g
                trees.append((col, thr, nal, val,
                              E.node_covers(heap, wt, nodes=grower.nodes,
                                            D=grower.D)))
                job.update(0.1 + 0.8 * (t + 1) / ntrees, f"tree {t+1}")
            self._trees = E.stack_trees(trees, grower.D)
        self._varimp_from_gains(np.asarray(gains_tot, np.float64))
        self._output.model_summary = {
            "number_of_trees": ntrees, "max_depth": grower.D,
            "mtries": mtries, "sample_rate": sample_rate,
        }

    def _contrib_scale_bias(self):
        # DRF prediction is the tree average (probability space for binomial)
        return 1.0 / self._trees.ntrees, 0.0

    def _score_matrix(self, X):
        K = self.nclasses
        if K > 2:
            Ps = [E.predict_ensemble(X, ta) / ta.ntrees
                  for ta in self._trees_k]
            P = jnp.clip(jnp.stack(Ps, axis=1), 0.0, 1.0)
            s = P.sum(axis=1, keepdims=True)
            return P / jnp.maximum(s, 1e-10)
        mean = E.predict_ensemble(X, self._trees) / self._trees.ntrees
        if self._is_classifier:
            p = jnp.clip(mean, 0.0, 1.0)
            return jnp.stack([1 - p, p], axis=1)
        return mean
