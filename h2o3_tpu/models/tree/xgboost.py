"""XGBoost — the TPU-native replacement for H2O's XGBoost extension.

Reference: h2o-extensions/xgboost/ (~15k LoC Java glue around the native
xgboost4j C++/CUDA booster): frame→DMatrix conversion
(matrix/DenseMatrixFactory.java), per-node native boosters driven by node
tasks (task/XGBoostUpdateTask.java:7 — booster.update per iteration :20),
Rabit ring-allreduce histogram sync (rabit/RabitTrackerH2O.java:14), backend
and tree_method selection (XGBoostModel.java:125,143,239-263). SURVEY.md §2.4
names this the BASELINE "gpu_hist → TPU" target.

TPU-native design: there is no external booster and no Rabit tracker — the
same fused histogram level-programs that power GBM/DRF run XGBoost's
`tree_method=hist` math directly on the MXU, and every histogram reduction is
an XLA psum over ICI (the ring-allreduce is the compiler's problem, not a
tracker process). The split objective is exact: engine.find_best_splits with
reg_lambda feeds hessian-weighted stats (w=Σh, wy=Σg), making the gain
argmax Σ G²/(H+λ) — hist-mode XGBoost's structure score — and leaf weights
are sign(G)·max(|G|−α,0)/(H+λ) via engine.gamma_pass.

Parameter surface mirrors h2o-py's H2OXGBoostEstimator (xgboost-style
aliases accepted: eta, min_child_weight, colsample_bytree, max_bins,
min_split_loss / gamma via min_split_improvement).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.models.tree import engine as E
from h2o3_tpu.models.tree.shared_tree import (
    H2OGradientBoostingEstimator, SharedTreeEstimator, _link_inv_dist)


def _objective_grad_hess(dist, F, y):
    """True second-order (g, h) per objective — hist-mode booster math.
    Sign convention follows the engine: res = −g (descent direction)."""
    if dist == "gaussian":                       # reg:squarederror
        return y - F, jnp.ones_like(F)
    if dist == "bernoulli":                      # binary:logistic
        p = jax.nn.sigmoid(F)
        return y - p, jnp.maximum(p * (1 - p), 1e-6)
    if dist == "poisson":                        # count:poisson
        mu = jnp.exp(F)
        return y - mu, jnp.maximum(mu, 1e-6)
    if dist == "gamma":                          # reg:gamma
        mu = jnp.exp(F)
        return y / mu - 1.0, jnp.maximum(y / mu, 1e-6)
    if dist == "tweedie":
        mu = jnp.exp(F)
        return (y * jnp.power(mu, -0.5) - jnp.power(mu, 0.5),
                jnp.maximum(0.5 * (y * jnp.power(mu, -0.5)
                                   + jnp.power(mu, 0.5)), 1e-6))
    raise NotImplementedError(f"XGBoost objective for {dist}")


class H2OXGBoostEstimator(H2OGradientBoostingEstimator):
    """Inherits the GBM driver's scoring-history/early-stop machinery; the
    boosting loop itself is replaced with hessian-weighted hist updates."""
    algo = "xgboost"
    _defaults = dict(SharedTreeEstimator._tree_defaults)
    _defaults.update({
        # xgboost defaults (XGBoostModel.XGBoostParameters)
        "ntrees": 50, "max_depth": 6, "min_rows": 1.0, "learn_rate": 0.3,
        "sample_rate": 1.0, "col_sample_rate": 1.0,
        "col_sample_rate_per_tree": 1.0, "nbins": 256,
        "reg_lambda": 1.0, "reg_alpha": 0.0, "min_split_improvement": 0.0,
        "tree_method": "hist", "booster": "gbtree", "backend": "auto",
        "scale_pos_weight": 1.0,
        # accepted xgboost-style aliases (resolved in __init__)
        "eta": None, "min_child_weight": None, "colsample_bytree": None,
        "colsample_bylevel": None, "subsample": None, "max_bins": None,
        "min_split_loss": None, "gamma": None, "max_leaves": 0,
        "grow_policy": "depthwise", "dmatrix_type": "auto",
        # DART (booster="dart"): per-iteration tree dropout
        "rate_drop": 0.0, "skip_drop": 0.0, "one_drop": False,
    })
    _ALIASES = {
        "eta": "learn_rate", "min_child_weight": "min_rows",
        "colsample_bytree": "col_sample_rate_per_tree",
        "colsample_bylevel": "col_sample_rate",
        "subsample": "sample_rate", "max_bins": "nbins",
        "min_split_loss": "min_split_improvement",
        "gamma": "min_split_improvement",
    }

    def __init__(self, **params):
        super().__init__(**params)
        for alias, target in self._ALIASES.items():
            v = self.params.get(alias)
            if v is not None:
                self.params[target] = v
        tm = self.params.get("tree_method", "hist")
        assert tm in ("auto", "hist", "approx", "exact"), \
            f"tree_method must be auto/hist/approx/exact, got {tm!r}"
        assert self.params.get("booster", "gbtree") in ("gbtree", "dart"), \
            "gblinear: use H2OGeneralizedLinearEstimator"
        if self.params.get("custom_distribution_func"):
            # parity: the reference XGBoost builder rejects custom
            # distributions too (hex/tree/xgboost has no custom-objective
            # seam) — use H2OGradientBoostingEstimator for UDF objectives
            raise NotImplementedError(
                "custom_distribution_func is not supported by the xgboost "
                "builder (same as the reference); use "
                "H2OGradientBoostingEstimator")
        if self.params.get("checkpoint") and \
                self.params.get("booster") == "dart":
            raise NotImplementedError(
                "checkpoint restart of a DART booster is not supported "
                "(per-tree weight state is folded into leaves at export)")

    def _grower(self):
        p = self.params
        return E.TreeGrower(
            nbins=int(p["nbins"]), max_depth=int(p["max_depth"]),
            min_rows=float(p["min_rows"]),           # on Σhess = min_child_weight
            # engine gain is the un-halved SE reduction = 2× xgboost's
            # ½·[G_L²/(H_L+λ)+G_R²/(H_R+λ)−G_P²/(H_P+λ)] — double γ to match
            min_split_improvement=2.0 * float(p["min_split_improvement"]),
            reg_lambda=float(p["reg_lambda"]))

    # ---- boosting driver (_resolve_dist inherited from GBM) --------------
    def _fit(self, frame: Frame, job):
        dist = self._resolve_dist()
        self._dist = dist
        X, y, w = self._prep(frame)
        if dist == "multinomial":
            return self._fit_multinomial(X, y, w, job)
        ntrees = int(self.params["ntrees"])
        eta = float(self.params["learn_rate"])
        lam = float(self.params["reg_lambda"])
        alpha = float(self.params["reg_alpha"])
        spw = float(self.params.get("scale_pos_weight") or 1.0)
        seed = int(self.params.get("seed") or -1)
        key = jax.random.PRNGKey(seed if seed >= 0 else 42)
        grower = self._grower()
        w_metric = w      # scale_pos_weight reweights the OBJECTIVE only
        if dist == "bernoulli" and spw != 1.0:
            w = w * jnp.where(y > 0.5, spw, 1.0)
        # xgboost base_score=0.5: margin F0 = 0 for logistic; for
        # reg:squarederror the 0.5 IS the raw prediction (not the mean)
        self._f0 = f0 = 0.5 if dist == "gaussian" else 0.0
        F = jnp.full(X.shape[0], f0, jnp.float32)
        sample_rate = float(self.params["sample_rate"])
        # DART (arXiv:1505.01866 + xgboost gbm/gbtree.cc dart): drop a
        # random subset of existing trees before computing gradients, then
        # normalize (normalize_type="tree"): new tree weight eta/(k+eta),
        # dropped trees rescaled by k/(k+eta). Per-tree weights are folded
        # into the stored leaf VALUES at the end (w_t/eta) so standard
        # scoring (lr * sum of trees), MOJO and TreeSHAP stay exact.
        dart = self.params.get("booster") == "dart"
        rate_drop = float(self.params.get("rate_drop") or 0.0)
        one_drop = bool(self.params.get("one_drop"))
        skip_drop = float(self.params.get("skip_drop") or 0.0)
        tree_w: list = []          # per-tree weights (eta for plain boosting)
        tree_pred: list = []       # per-tree per-row predictions (device)
        rng = np.random.default_rng(seed if seed >= 0 else 42)
        trees = []
        # checkpoint restart (ModelBuilder.java:1401): resume boosting
        # from a prior xgboost model's trees; prior leaf values rescale by
        # eta_prev/eta so `lr * sum(trees)` stays exact under the NEW lr
        ckpt = self.params.get("checkpoint")
        if ckpt:
            from h2o3_tpu.core.kvstore import DKV
            prev = DKV.get(ckpt) if isinstance(ckpt, str) else ckpt
            assert prev is not None and prev.algo == self.algo, \
                f"checkpoint {ckpt} not found or wrong algo"
            pt = prev._trees
            assert pt.depth == grower.D, \
                "checkpoint restart requires identical max_depth"
            assert prev._dinfo.predictors == self._dinfo.predictors, \
                ("checkpoint restart requires the SAME predictor columns "
                 "in the same order (tree col indices address the design "
                 "matrix positionally; ModelBuilder.java checkpoint "
                 "training-frame validation)")
            assert ntrees > pt.ntrees, \
                (f"checkpoint restart: ntrees ({ntrees}) must exceed the "
                 f"checkpoint's tree count ({pt.ntrees}) — ntrees is the "
                 f"TOTAL (ModelBuilder.java checkpoint validation)")
            eta_prev = float(prev.params["learn_rate"])
            scale = eta_prev / eta
            for i in range(pt.ntrees):
                cov_i = (jnp.asarray(pt.cover[i]) if pt.cover is not None
                         else jnp.zeros_like(jnp.asarray(pt.value[i])))
                trees.append((jnp.asarray(pt.col[i]),
                              jnp.asarray(pt.thr[i]),
                              jnp.asarray(pt.na_left[i]),
                              jnp.asarray(pt.value[i]) * scale, cov_i))
            self._f0 = f0 = prev._f0
            F = f0 + eta_prev * E.predict_ensemble(X, pt)
        gains_tot = jnp.zeros(X.shape[1], jnp.float32)
        if ckpt:
            # seed varimp with the checkpoint's per-feature gains so the
            # continued model's importances cover the WHOLE ensemble
            fidx = {n: i for i, n in enumerate(self._dinfo.predictors)}
            seed_g = np.zeros(X.shape[1], np.float32)
            for row in (prev._output.variable_importances or []):
                if row["variable"] in fidx:
                    seed_g[fidx[row["variable"]]] = row["relative_importance"]
            gains_tot = gains_tot + jnp.asarray(seed_g)
        interval = max(1, int(self.params.get("score_tree_interval") or 5))
        for t in range(len(trees), ntrees):
            key, k1, k2, k3 = jax.random.split(key, 4)
            F_use = F
            dropped: list = []
            if dart and tree_pred and rate_drop > 0 \
                    and rng.random() >= skip_drop:
                dmask = rng.random(len(tree_pred)) < rate_drop
                if one_drop and not dmask.any():
                    dmask[rng.integers(len(tree_pred))] = True
                dropped = list(np.nonzero(dmask)[0])
                for i in dropped:
                    F_use = F_use - tree_w[i] * tree_pred[i]
            g, h = _objective_grad_hess(dist, F_use, y)
            wt = self._sample_weights(w, k1, sample_rate)
            cmask = self._col_mask(X.shape[1], k2)
            # hessian-weighted stats: w_stat=Σwh (→H), wy=Σwg (→G)
            col, thr, nal, val, heap, gn = grower.grow(
                X, wt * h, g / h, col_mask=cmask, key=k3,
                mtries=self._per_level_mtries(X.shape[1]))
            gains_tot = gains_tot + gn
            val = E.gamma_pass(heap, wt, g, h, val, nodes=grower.nodes,
                               reg_lambda=lam, reg_alpha=alpha)
            cover = E.node_covers(heap, wt * h, nodes=grower.nodes,
                                  D=grower.D)
            trees.append((col, thr, nal, val, cover))
            p_new = val[heap]
            kdrop = len(dropped)
            if dart:
                if kdrop:
                    scale = kdrop / (kdrop + eta)
                    new_w = eta / (kdrop + eta)
                    # rescale the dropped trees toward the new ensemble
                    for i in dropped:
                        F = F + (scale - 1.0) * tree_w[i] * tree_pred[i]
                        tree_w[i] *= scale
                else:
                    new_w = eta
                tree_w.append(new_w)
                tree_pred.append(p_new)
                F = F + new_w * p_new
            else:
                F = F + eta * p_new
            if (t + 1) % interval == 0 or t == ntrees - 1:
                self._record_history(t + 1, F, y, w_metric, dist)
                if self._should_stop():
                    break
            job.update(0.1 + 0.8 * (t + 1) / ntrees, f"tree {t+1}")
        if dart and tree_w:
            # fold DART weights into leaf values: lr * sum matches F
            trees = [(c, th, na, v * (tw / eta), cv)
                     for (c, th, na, v, cv), tw in zip(trees, tree_w)]
        self._trees = E.stack_trees(trees, grower.D)
        self._varimp_from_gains(np.asarray(gains_tot, np.float64))
        self._output.model_summary = {
            "number_of_trees": self._trees.ntrees, "max_depth": grower.D,
            "objective": {"gaussian": "reg:squarederror",
                          "bernoulli": "binary:logistic",
                          "poisson": "count:poisson",
                          "gamma": "reg:gamma",
                          "tweedie": "reg:tweedie"}[dist],
            "tree_method": "hist", "eta": eta, "reg_lambda": lam,
        }

    def _fit_multinomial(self, X, y, w, job):
        if self.params.get("checkpoint"):
            raise NotImplementedError(
                "xgboost checkpoint restart covers binomial/regression "
                "boosters; multinomial restart is not wired")
        K = self.nclasses
        ntrees = int(self.params["ntrees"])
        eta = float(self.params["learn_rate"])
        lam = float(self.params["reg_lambda"])
        alpha = float(self.params["reg_alpha"])
        seed = int(self.params.get("seed") or -1)
        key = jax.random.PRNGKey(seed if seed >= 0 else 42)
        grower = self._grower()
        yi = y.astype(jnp.int32)
        onehot = jax.nn.one_hot(yi, K)
        self._f0 = np.zeros(K, np.float32)
        F = jnp.zeros((X.shape[0], K), jnp.float32)
        sample_rate = float(self.params["sample_rate"])
        trees_k = [[] for _ in range(K)]
        gains_tot = jnp.zeros(X.shape[1], jnp.float32)
        interval = max(1, int(self.params.get("score_tree_interval") or 5))
        # multinomial DART: one iteration grows a GROUP of K class trees;
        # dropout operates on whole groups (the K trees of an iteration
        # share one weight), matching the binomial path's normalize_type
        # "tree" arithmetic with (n, K) round predictions.
        dart = self.params.get("booster") == "dart"
        rate_drop = float(self.params.get("rate_drop") or 0.0)
        one_drop = bool(self.params.get("one_drop"))
        skip_drop = float(self.params.get("skip_drop") or 0.0)
        tree_w: list = []
        tree_pred: list = []          # per round: (n, K) device array
        rng = np.random.default_rng(seed if seed >= 0 else 42)
        for t in range(ntrees):
            key, k1, k2 = jax.random.split(key, 3)
            F_use = F
            dropped: list = []
            if dart and tree_pred and rate_drop > 0 \
                    and rng.random() >= skip_drop:
                dmask = rng.random(len(tree_pred)) < rate_drop
                if one_drop and not dmask.any():
                    dmask[rng.integers(len(tree_pred))] = True
                dropped = list(np.nonzero(dmask)[0])
                for i in dropped:
                    F_use = F_use - tree_w[i] * tree_pred[i]
            P = jax.nn.softmax(F_use, axis=1)
            wt = self._sample_weights(w, k1, sample_rate)
            cmask = self._col_mask(X.shape[1], k2)
            p_round = []
            for c in range(K):
                key, kc = jax.random.split(key)
                g = onehot[:, c] - P[:, c]
                h = jnp.maximum(2.0 * P[:, c] * (1 - P[:, c]), 1e-6)
                col, thr, nal, val, heap, gn = grower.grow(
                    X, wt * h, g / h, col_mask=cmask, key=kc,
                    mtries=self._per_level_mtries(X.shape[1]))
                gains_tot = gains_tot + gn
                val = E.gamma_pass(heap, wt, g, h, val, nodes=grower.nodes,
                                   reg_lambda=lam, reg_alpha=alpha)
                cover = E.node_covers(heap, wt * h, nodes=grower.nodes,
                                      D=grower.D)
                trees_k[c].append((col, thr, nal, val, cover))
                p_round.append(val[heap])
            p_new = jnp.stack(p_round, axis=1)          # (n, K)
            kdrop = len(dropped)
            if dart:
                if kdrop:
                    scale = kdrop / (kdrop + eta)
                    new_w = eta / (kdrop + eta)
                    for i in dropped:
                        F = F + (scale - 1.0) * tree_w[i] * tree_pred[i]
                        tree_w[i] *= scale
                else:
                    new_w = eta
                tree_w.append(new_w)
                tree_pred.append(p_new)
                F = F + new_w * p_new
            else:
                F = F + eta * p_new
            if (t + 1) % interval == 0 or t == ntrees - 1:
                self._record_history_multi(t + 1, F, y, w)
                if self._should_stop():
                    break
            job.update(0.1 + 0.8 * (t + 1) / ntrees, f"iter {t+1}")
        if dart and tree_w:
            # fold round weights into leaf values so lr * sum matches F
            for c in range(K):
                trees_k[c] = [
                    (cl, th, na, v * (tw / eta), cv)
                    for (cl, th, na, v, cv), tw in zip(trees_k[c], tree_w)]
        self._trees_k = [E.stack_trees(tl, grower.D) for tl in trees_k]
        self._varimp_from_gains(np.asarray(gains_tot, np.float64))
        self._output.model_summary = {
            "number_of_trees": sum(t.ntrees for t in self._trees_k),
            "max_depth": grower.D, "objective": "multi:softprob",
        }

    # ---- scoring ---------------------------------------------------------
    def _score_matrix(self, X):
        eta = float(self.params["learn_rate"])
        if self._dist == "multinomial":
            Fs = [eta * E.predict_ensemble(X, ta) for ta in self._trees_k]
            return jax.nn.softmax(jnp.stack(Fs, axis=1), axis=1)
        F = self._f0 + eta * E.predict_ensemble(X, self._trees)
        return _link_inv_dist(self._dist, F)

    @staticmethod
    def available() -> bool:
        """h2o.estimators.xgboost.H2OXGBoostEstimator.available() parity —
        always true here: the booster is the in-tree TPU engine."""
        return True
