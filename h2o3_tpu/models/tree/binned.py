"""Binned (pre-quantized) tree engine — the TPU rebuild of the reference's
global-quantile histogram path, designed for MXU/VPU throughput.

Reference mapping:
  * hex/tree/GlobalQuantilesCalc.java — quantize features ONCE per training
    run into small-integer bin codes against global quantile edges (the
    `histogram_type="QuantilesGlobal"` mode; also xgboost `tree_method=hist`
    semantics, the BASELINE.json comparison target).
  * hex/tree/ScoreBuildHistogram2.java:20-60 — the fused score+build pass.
    Here rows are kept PARTITIONED by leaf (stable partition maintained per
    level entirely on device), so histogram accumulation is leaf-local and
    rides the Pallas kernel in ops/hist_pallas.py.
  * hex/tree/DTree.java:514 (DecidedNode.bestCol) — vectorized split search
    over (leaf, col, threshold, NA-direction), plus categorical SET splits:
    bins sorted by mean gradient and split on the best prefix (the optimal
    subset search for 1-D loss, replacing IcedBitSet group splits
    water/util/IcedBitSet.java) with the decision stored as a 256-bit mask.
  * hex/tree/Constraints.java — monotone constraints: sign-violating splits
    are rejected and child values are clamped to propagated bounds.
  * hex/tree/SharedTree.java:548-561 — task parallelism over trees becomes
    a lax.scan over trees inside ONE jitted program (a dispatch through the
    controller costs ~10ms; per-level dispatch would dominate runtime).

Everything per level is static-shaped: leaf arrays are sized L_MAX = 2^D,
the slot count n_pad = (ceil(n/R) + L_MAX) * R never changes, and empty
leaves own one all-dummy block. No host synchronization inside training.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from h2o3_tpu.ops import hist_pallas as HP

R = HP.BLOCK_ROWS


# ===========================================================================
# Quantization (GlobalQuantilesCalc analog)
@dataclass
class BinSpec:
    """Per-column binning of a training frame."""
    edges: np.ndarray        # (C, B_val-1) f32 — ascending cut points
    is_cat: np.ndarray       # (C,) bool — categorical column (codes = level)
    b_val: int               # number of value bins; NA code == b_val
    n_bins: int              # padded bin count used by the kernel (mult 128)
    c_pad: int               # padded column count (mult COL_TILE)

    @property
    def na_code(self):
        return self.b_val


def make_bins(X, is_cat, nbins: int, sample: int = 1 << 18) -> BinSpec:
    """Global quantile edges from a row sample. X: (n, C) f32 with NaN NAs.
    Categorical columns are identity-binned (code == level id, capped)."""
    n, C = X.shape
    b_val = int(min(nbins, 255))
    stride = max(1, n // sample)
    Xs = np.asarray(X[::stride][:sample], np.float32)
    edges = np.zeros((C, b_val - 1), np.float32)
    qs = np.linspace(0.0, 1.0, b_val + 1)[1:-1]
    for c in range(C):
        if is_cat[c]:
            # identity binning: edge k at k+0.5 so code(level k)=k
            edges[c] = np.arange(1, b_val, dtype=np.float32) - 0.5
            continue
        col = Xs[:, c]
        col = col[~np.isnan(col)]
        if col.size == 0:
            edges[c] = np.arange(1, b_val, dtype=np.float32)
            continue
        e = np.quantile(col, qs).astype(np.float32)
        # strictly non-decreasing is fine: duplicate edges => empty bins
        edges[c] = e
    nb = max(128, -(-(b_val + 1) // 128) * 128)
    cp = -(-C // HP.COL_TILE) * HP.COL_TILE
    return BinSpec(edges=edges, is_cat=np.asarray(is_cat, bool),
                   b_val=b_val, n_bins=nb, c_pad=cp)


def row_granule() -> int:
    """Per-shard row-count granularity: the Pallas kernels sweep rows in
    BLOCK_ROWS tiles; the XLA fallbacks (CPU tests) have no tiling constraint
    so a smaller granule keeps tiny sharded test frames cheap."""
    return R if HP.use_pallas() else 512


def padded_rows(n: int, shards: int = 1) -> int:
    """Slots for n data rows + 1 dummy, padded so every shard's local block
    is a granule multiple (the rows axis splits evenly over the mesh)."""
    blk = row_granule() * max(1, shards)
    return -(-(n + 1) // blk) * blk


@functools.partial(jax.jit, static_argnames=("b_val", "c_pad", "n_pad"))
def _quantize(X, edges, *, b_val, c_pad, n_pad):
    """codes[r,c] = #edges < x (0..b_val-1), NA -> b_val. Rows are padded to
    the kernel block multiple with dummy rows (code 0, zero stats) and dummy
    columns for the kernel's column tiling. Codes are uint8 END-TO-END
    (b_val <= 255 so the NA code fits): the code plane is the per-level
    HBM bandwidth floor (ops/PERF_NOTES.md) and one byte per code is 4x
    less stream than the old i32 planes."""
    n, C = X.shape

    def one_col(x, e):
        code = jnp.searchsorted(e, x, side="left").astype(jnp.int32)
        return jnp.where(jnp.isnan(x), b_val, code)

    codes = jax.vmap(one_col, in_axes=(1, 0), out_axes=0)(X, edges)
    codes = jnp.clip(codes, 0, b_val).astype(jnp.uint8)  # (C, n)
    out = jnp.zeros((c_pad, n_pad), jnp.uint8)
    return lax.dynamic_update_slice(out, codes, (0, 0))


def quantize(X, spec: BinSpec, n_pad: int | None = None):
    """(n, C) f32 -> (C_pad, n_pad) uint8 code plane (the XLA-fallback /
    canonical layout; `prepare_codes` derives the TPU kernel layout)."""
    n = X.shape[0]
    if n_pad is None:
        n_pad = padded_rows(n)
    return _quantize(X, jnp.asarray(spec.edges),
                     b_val=spec.b_val, c_pad=spec.c_pad, n_pad=n_pad)


def prepare_codes(codes_u8):
    """Backend-appropriate kernel layout for a quantized plane: the packed
    i32 word plane (4 codes/word, HP.pack_codes) on the Pallas backend,
    the uint8 plane unchanged everywhere else. Row axis untouched — row
    sharding specs carry over."""
    return HP.prepare_codes(codes_u8)


def pad_rows(x, n_pad: int):
    """Zero-pad a per-row vector to the quantize() row layout."""
    return jnp.pad(x, (0, n_pad - x.shape[0]))


# ===========================================================================
# Split search over binned histograms
def _se_gain(wl, gl, wr, gr_, wp, gp, lam):
    """Un-halved SE / structure-score reduction (same objective family as
    engine.find_best_splits; lam>0 = XGBoost G^2/(H+lambda))."""
    def score(w_, g_):
        return jnp.where(w_ > 0, g_ * g_ / jnp.maximum(w_ + lam, 1e-30), 0.0)
    return score(wl, gl) + score(wr, gr_) - score(wp, gp)


@functools.partial(
    jax.jit,
    static_argnames=("b_val", "use_hess", "any_cat"))
def find_splits_binned(hist, is_cat, mono, cmask, lo, hi, *, b_val,
                       min_rows, msi, lam, use_hess, any_cat=True):
    """Vectorized bestCol over every (leaf, col, threshold/subset, NA-dir).

    hist: (L, C_pad, 4, BP) — stats rows 0=w 1=wg 2=wh (3 spare)
    is_cat: (C_pad,) bool; mono: (C_pad,) int32 in {-1,0,1}
    cmask: (L, C_pad) bool column availability (mtries / padding)
    lo, hi: (L,) f32 monotone value bounds for each leaf

    Returns dict of per-leaf arrays: did, col, bin, nal, route (L, BP) bool,
    val_l, val_r (clamped), gain, plus per-leaf totals (w_t, val_t).
    """
    L, C, _, BP = hist.shape
    w = hist[:, :, 0, :]
    wg = hist[:, :, 1, :]
    wh = hist[:, :, 2, :]
    den = wh if use_hess else w

    B = b_val
    v_w, na_w = w[..., :B], w[..., B]
    v_wg, na_wg = wg[..., :B], wg[..., B]
    v_wh, na_wh = wh[..., :B], wh[..., B]
    v_den, na_den = den[..., :B], den[..., B]

    # ---- parent totals (identical for every real column; col 0 is real) --
    w_t = v_w[:, 0].sum(-1) + na_w[:, 0]
    wg_t = v_wg[:, 0].sum(-1) + na_wg[:, 0]
    wh_t = v_wh[:, 0].sum(-1) + na_wh[:, 0]
    den_t = v_den[:, 0].sum(-1) + na_den[:, 0]
    # leaf VALUES are always the Newton step wg/wh (GammaPass,
    # GBM.java:1235); `den`/use_hess only selects the split-gain objective
    val_t = wg_t / jnp.maximum(wh_t, 1e-30)

    # ---- categorical: sort bins by mean gradient (optimal-subset order) --
    # (statically skipped when the frame has no categorical columns)
    if any_cat:
        ratio = jnp.where(v_den > 1e-30, v_wg / jnp.maximum(v_den, 1e-30),
                          jnp.inf)                          # empty bins last
        order = jnp.argsort(ratio, axis=-1)                 # (L, C, B)
        sc_w = jnp.take_along_axis(v_w, order, -1)
        sc_wg = jnp.take_along_axis(v_wg, order, -1)
        sc_den = jnp.take_along_axis(v_den, order, -1)

    def eval_axis(aw, awg, aden):
        """Prefix-split gains along the (possibly re-ordered) bin axis.
        Returns (gain, nal) each (L, C, B-1)."""
        cl_w = jnp.cumsum(aw, -1)[..., :-1]
        cl_wg = jnp.cumsum(awg, -1)[..., :-1]
        cl_den = jnp.cumsum(aden, -1)[..., :-1]

        def gains(nal):
            lw = cl_w + (na_w[..., None] if nal else 0.0)
            lg = cl_wg + (na_wg[..., None] if nal else 0.0)
            ld = cl_den + (na_den[..., None] if nal else 0.0)
            rw = w_t[:, None, None] - lw
            rg = wg_t[:, None, None] - lg
            rd = den_t[:, None, None] - ld
            g = _se_gain(ld, lg, rd, rg, den_t[:, None, None],
                         wg_t[:, None, None], lam)
            ok = (lw >= min_rows) & (rw >= min_rows)
            # monotone: reject sign-violating splits on constrained columns
            vl = lg / jnp.maximum(ld, 1e-30)
            vr = rg / jnp.maximum(rd, 1e-30)
            mok = (mono[None, :, None] == 0) | \
                  ((vr - vl) * mono[None, :, None] >= 0)
            return jnp.where(ok & mok, g, -jnp.inf)

        g0, g1 = gains(False), gains(True)
        return jnp.maximum(g0, g1), g1 > g0

    gn_num, nal_num = eval_axis(v_w, v_wg, v_den)           # natural order
    if any_cat:
        gn_cat, nal_cat = eval_axis(sc_w, sc_wg, sc_den)    # sorted order
        catC = is_cat[None, :, None]
        gain_all = jnp.where(catC, gn_cat, gn_num)          # (L, C, B-1)
        nal_all = jnp.where(catC, nal_cat, nal_num)
    else:
        gain_all, nal_all = gn_num, nal_num
    gain_all = jnp.where(cmask[:, :, None], gain_all, -jnp.inf)

    flat = gain_all.reshape(L, C * (B - 1))
    best = jnp.argmax(flat, axis=1)
    bgain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
    bcol = (best // (B - 1)).astype(jnp.int32)
    bbin = (best % (B - 1)).astype(jnp.int32)               # threshold index
    bnal = jnp.take_along_axis(nal_all.reshape(L, C * (B - 1)),
                               best[:, None], 1)[:, 0]
    did = jnp.isfinite(bgain) & (bgain > jnp.maximum(msi, 0.0))

    # ---- routing table: route[l, code] = goes-right ----------------------
    takeL = lambda a: jnp.take_along_axis(    # noqa: E731  (L,C,X)->(L,X)
        a, bcol[:, None, None], 1)[:, 0]
    bin_ids = jnp.arange(BP)[None, :]                       # (1, BP)
    num_right = bin_ids > bbin[:, None]                     # natural order
    if any_cat:
        rank_of_bin = jnp.argsort(takeL(order), axis=-1)    # (L, B)
        rank_pad = jnp.pad(rank_of_bin, ((0, 0), (0, BP - B)),
                           constant_values=BP)
        cat_right = rank_pad > bbin[:, None]
        leaf_cat = is_cat[bcol]
        route = jnp.where(leaf_cat[:, None], cat_right, num_right)
    else:
        route = num_right
    # NA code: by chosen NA direction
    route = route.at[:, B].set(~bnal)
    route = jnp.where(did[:, None], route, False)           # frozen: stay

    # ---- child values (Newton wg/wh) with monotone clamping --------------
    bw = takeL(v_w)
    bg = takeL(v_wg)
    bh = takeL(v_wh)
    goes_left = ~route[:, :B]
    # NA-bin mass of the CHOSEN column (each column sees different NA rows)
    takeL1 = lambda a: jnp.take_along_axis(   # noqa: E731  (L,C)->(L,)
        a, bcol[:, None], 1)[:, 0]
    w_l = (bw * goes_left).sum(-1) + jnp.where(bnal, takeL1(na_w), 0.0)
    g_l = (bg * goes_left).sum(-1) + jnp.where(bnal, takeL1(na_wg), 0.0)
    h_l = (bh * goes_left).sum(-1) + jnp.where(bnal, takeL1(na_wh), 0.0)
    val_l = g_l / jnp.maximum(h_l, 1e-30)
    g_r = wg_t - g_l
    h_r = wh_t - h_l
    val_r = g_r / jnp.maximum(h_r, 1e-30)
    val_l = jnp.clip(val_l, lo, hi)
    val_r = jnp.clip(val_r, lo, hi)
    val_tc = jnp.clip(val_t, lo, hi)

    return dict(did=did, col=bcol, bin=bbin, nal=bnal, route=route,
                gain=jnp.where(did, jnp.maximum(bgain, 0.0), 0.0),
                val_l=val_l, val_r=val_r, val_t=val_tc,
                w_t=w_t, w_l=w_l, wg_l=g_l, wh_l=h_l)


# ===========================================================================
# The grower: one jitted program per chunk of trees
class BinnedGrower:
    """Grows trees level-by-level on pre-binned codes with device-resident
    leaf partitioning. One lax.scan over K trees per dispatch."""

    def __init__(self, spec: BinSpec, *, max_depth: int, min_rows: float,
                 min_split_improvement: float, reg_lambda: float = 0.0,
                 reg_alpha: float = 0.0, use_hess_denom: bool = False,
                 monotone: np.ndarray | None = None,
                 axis_name: str | None = None,
                 int8_stats: bool | None = None,
                 use_radix_shallow: bool | None = None,
                 fused_level: bool | None = None):
        # axis_name: mesh axis the row dimension is sharded over. grow() then
        # runs shard-local and merges per-level histograms with ONE psum —
        # the reduce-tree of ScoreBuildHistogram.java:98 / MRTask.java:907
        # riding ICI. Split search stays replicated (identical on all shards).
        self.axis_name = axis_name
        # int8_stats: quantize (w, wg, wh) to int8 per tree and accumulate
        # histograms on the 2x-rate int8 MXU path with exact i32 sums
        # (PERF_NOTES item 2; quantum |g|max/127). EXPLICIT OPT-IN: the
        # compile probe (i8_supported) proves the kernel builds, not that
        # end-to-end model accuracy matches the f32 path; until the on-chip
        # AUC-parity measurement lands (bench --int8), default stays off.
        self.int8 = False if int8_stats is None else bool(int8_stats)
        # use_radix_shallow / fused_level: AUTO-ON (None) the way
        # int8_stats=auto gates — each kernel family carries its own
        # probe compile (HP.radix_supported / HP.fused_supported) and its
        # own shape gate, so auto engages exactly where the Pallas
        # program compiles and the level qualifies; False forces the
        # dense/sequential reference paths (the parity baselines).
        self.use_radix = None if use_radix_shallow in (None, True) \
            else False
        self.fused = None if fused_level in (None, True) else False
        self.spec = spec
        self.D = int(max_depth)
        self.L = 2 ** self.D
        self.nodes = 2 ** (self.D + 1) - 1
        self.min_rows = float(min_rows)
        self.msi = float(min_split_improvement)
        self.lam = float(reg_lambda)
        self.alpha = float(reg_alpha)
        self.use_hess = bool(use_hess_denom)
        mono = np.zeros(spec.c_pad, np.int32) if monotone is None else \
            np.asarray(monotone, np.int32)
        self.mono = jnp.asarray(mono)
        self.is_cat_dev = jnp.asarray(
            np.pad(spec.is_cat, (0, spec.c_pad - spec.is_cat.size)))

    # ---- static layout ---------------------------------------------------
    def layout(self, n: int, shards: int = 1):
        """Slots for n data rows + 1 dummy, padded to the kernel block
        (per-shard when the rows axis is sharded over `shards` devices)."""
        return padded_rows(n, shards)

    def grow(self, codes, stats, F, *, eta, clip_val, key, mtries: int = 0,
             tree_mask=None, level_cb=None):
        """Grow ONE tree and apply its margin update — all device-resident.

        codes: uint8 (C_pad, n_pad) code plane from `quantize`, or the
               packed i32 (W_pad, n_pad) plane from `prepare_codes` on the
               Pallas backend — COLUMN-major either way (dummy rows carry
               zero stats)
        stats: (S_STATS, n_pad) f32 — rows 0=w 1=w*grad 2=w*hess 3=0
        F:     (n_pad,) f32 margins (updated in the terminal route pass)
        level_cb: optional host callback `cb(d, sync_array)` invoked after
               each level's dispatches — ONLY for the eager per-level
               instrumentation path (bench measure_level_seconds); must be
               None under jit.

        Returns dict(col, bin, nal, route, val, cover, gains, F).
        Per-row state is ONE heap-id int32 array; no row reordering ever
        happens (measured: TPU gathers are 10x slower than the histogram
        kernel — see ops/hist_pallas.py header).
        """
        spec, D = self.spec, self.D
        C = spec.c_pad
        n_pad = codes.shape[1]
        BP = spec.n_bins
        big = jnp.float32(3e38)
        nodes_p = -(-(self.nodes + 1) // 128) * 128
        heap = jnp.zeros(n_pad, jnp.int32)
        colA = jnp.full(self.nodes, -1, jnp.int32)
        binA = jnp.full(self.nodes, -1, jnp.int32)
        nalA = jnp.zeros(self.nodes, bool)
        routeA = jnp.zeros((self.nodes, BP), bool)
        valA = jnp.zeros(self.nodes, jnp.float32)
        coverA = jnp.zeros(self.nodes, jnp.float32)
        gains = jnp.zeros(C + 1, jnp.float32)
        c_real = int(spec.is_cat.size)

        lo = jnp.full(1, -big)
        hi = jnp.full(1, big)
        any_cat = bool(spec.is_cat.any())
        if self.int8:
            # per-tree, per-stat-row symmetric quantization: stats are fixed
            # for the whole tree, so ONE quantization pass serves every level
            absmax = jnp.max(jnp.abs(stats), axis=1, keepdims=True)  # (S,1)
            if self.axis_name:
                # the quantum must be GLOBAL or shards' i32 sums would mix
                # incompatible scales inside the psum
                absmax = lax.pmax(absmax, self.axis_name)
            scale = 127.0 / jnp.maximum(absmax, 1e-30)
            stats_in = jnp.clip(jnp.round(stats * scale),
                                -127, 127).astype(jnp.int32)
            inv = jnp.maximum(absmax, 1e-30)[:, 0] / 127.0           # (S,)
            hist_fn = HP.sbh_hist_i8
        else:
            stats_in = stats
            hist_fn = HP.sbh_hist
        prev = None                    # routing tables of level d-1
        hist_prev = None               # full histogram of level d-1 (native
        #                                dtype: i32 when int8 — sibling
        #                                subtraction stays exact)
        did_prev = None                # split mask of level d-1
        for d in range(D):
            L = 1 << d
            base = L - 1
            if d == 0:
                hacc = hist_fn(codes, heap, stats_in, base=base, L=L,
                               n_bins=BP, radix=self.use_radix)[:L, :C]
                if self.axis_name:
                    # the ScoreBuildHistogram reduce: merge shard-local
                    # histograms in one collective per level
                    hacc = lax.psum(hacc, self.axis_name)
            else:
                # ONE fused-or-sequential pass: route the previous level's
                # splits, then (sibling subtraction) histogram LEFT
                # children only over the UPDATED heap — half the leaf
                # window -> half the MXU dot, and on the fused Pallas path
                # the code tile is read ONCE for both phases. Right =
                # parent - left: routing moves every row of a split leaf,
                # so parent = left + right exactly; unsplit parents are
                # masked to zero (their child slots are dead).
                heap, left = HP.sbh_route_hist(
                    codes, heap, prev["tbl"], prev["route_f"], stats_in,
                    base_r=(L >> 1) - 1, L_r=L >> 1, base_h=base, L_h=L,
                    n_bins=BP, any_cat=any_cat, na_code=spec.b_val,
                    int8=self.int8, fused=self.fused,
                    radix=self.use_radix)
                left = left[: L >> 1, :C]
                if self.axis_name:
                    # psum BEFORE subtraction: hist_prev is already global
                    left = lax.psum(left, self.axis_name)
                par = jnp.where(did_prev[:, None, None, None],
                                hist_prev, jnp.zeros_like(hist_prev))
                right = par - left
                hacc = jnp.stack([left, right], axis=1) \
                    .reshape(L, *left.shape[1:])
            hist_prev = hacc
            hist = hacc.astype(jnp.float32) * inv[None, None, :, None] \
                if self.int8 else hacc

            if mtries and mtries < c_real:
                r = jax.random.uniform(jax.random.fold_in(key, d),
                                       (L, C))
                r = jnp.where(jnp.arange(C) < c_real, r, 2.0)
                kth = jnp.sort(r, axis=1)[:, mtries - 1:mtries]
                cmask = r <= kth
            else:
                cmask = jnp.broadcast_to(
                    (jnp.arange(C) < c_real)[None], (L, C))
            if tree_mask is not None:
                # col_sample_rate_per_tree: a whole-tree column subset drawn
                # by the caller (SharedTree _rand per-tree cols analog)
                cmask = cmask & tree_mask[None, :]

            s = find_splits_binned(
                hist, self.is_cat_dev, self.mono, cmask, lo, hi,
                b_val=spec.b_val, min_rows=self.min_rows, msi=self.msi,
                lam=self.lam, use_hess=self.use_hess, any_cat=any_cat)

            did = s["did"]
            did_prev = did
            ids = jnp.arange(L)
            tgt = base + ids
            colA = colA.at[tgt].set(jnp.where(did, s["col"], -1))
            binA = binA.at[tgt].set(jnp.where(did, s["bin"], -1))
            nalA = nalA.at[tgt].set(s["nal"])
            routeA = routeA.at[tgt].set(s["route"])
            valA = valA.at[tgt].set(s["val_t"])
            coverA = coverA.at[tgt].set(s["w_t"])
            kidL = jnp.where(did, 2 * tgt + 1, self.nodes)
            kidR = jnp.where(did, 2 * tgt + 2, self.nodes)
            valA = valA.at[kidL].set(s["val_l"], mode="drop")
            valA = valA.at[kidR].set(s["val_r"], mode="drop")
            coverA = coverA.at[kidL].set(s["w_l"], mode="drop")
            coverA = coverA.at[kidR].set(s["w_t"] - s["w_l"], mode="drop")
            gains = gains.at[jnp.where(did, s["col"], C)].add(s["gain"])

            # ---- routing tables for the next level -----------------------
            Lp = max(8, L)
            tbl = jnp.zeros((8, Lp), jnp.float32)
            tbl = tbl.at[0, :L].set(s["col"].astype(jnp.float32))
            tbl = tbl.at[1, :L].set(did.astype(jnp.float32))
            tbl = tbl.at[2, :L].set(s["bin"].astype(jnp.float32))
            tbl = tbl.at[3, :L].set(s["nal"].astype(jnp.float32))
            route_f = jnp.zeros((Lp, BP), jnp.float32)
            route_f = route_f.at[:L].set(s["route"].astype(jnp.float32))
            prev = dict(tbl=tbl, route_f=route_f)

            # ---- monotone bounds for children ----------------------------
            mc = self.mono[s["col"]]
            mid = 0.5 * (s["val_l"] + s["val_r"])
            lo_l = jnp.where(mc < 0, jnp.maximum(lo, mid), lo)
            hi_l = jnp.where(mc > 0, jnp.minimum(hi, mid), hi)
            lo_r = jnp.where(mc > 0, jnp.maximum(lo, mid), lo)
            hi_r = jnp.where(mc < 0, jnp.minimum(hi, mid), hi)
            lo = jnp.stack([jnp.where(did, lo_l, lo),
                            jnp.where(did, lo_r, lo)], 1).reshape(2 * L)
            hi = jnp.stack([jnp.where(did, hi_l, hi),
                            jnp.where(did, hi_r, hi)], 1).reshape(2 * L)

            if level_cb is not None:
                # eager instrumentation only (bench per-level breakdown):
                # the callback syncs on the level's routing table — the
                # array downstream of hist + find_splits
                level_cb(d, prev["tbl"])

        # terminal pass: route the last level + fused F update
        L = 1 << D
        valt = jnp.clip(valA, -clip_val, clip_val) if clip_val else valA
        valtab = jnp.zeros((8, nodes_p), jnp.float32).at[0, : self.nodes]             .set(valt)
        heap, F = HP.sbh_route(codes, heap, prev["tbl"], prev["route_f"],
                               valtab, F, base=(L >> 1) - 1, L=L >> 1,
                               eta=eta, emit_f=True, any_cat=any_cat,
                               na_code=spec.b_val)
        return dict(col=colA, bin=binA, nal=nalA, route=routeA, val=valt,
                    cover=coverA, gains=gains[:C], F=F, heap=heap)


# ===========================================================================
def measure_level_seconds(grower: BinnedGrower, codes, stats, F, *,
                          eta=0.1, clip_val=0.0, key=None):
    """Grow ONE tree EAGERLY with a host sync after every level and record
    each level's wall time into `h2o3_tree_level_seconds{engine="binned",
    level=d}` — the ISSUE-1 arbiter for the per-level cost breakdown (the
    jitted K-tree trainer is one opaque program; ad-hoc timers inside it
    cannot attribute the residual cost to a level). Returns
    [{"level": d, "seconds": s}, ...] for the bench record."""
    import time as _time
    from h2o3_tpu.models.tree import engine as _E

    rows: list[dict] = []
    last = [0.0]

    def sync_cb(d, sync_arr):
        # scalar readback: through the TPU relay block_until_ready can
        # return early; a float() readback is the reliable sync
        # (ops/PERF_NOTES.md relay gotchas)
        float(jnp.sum(sync_arr))

    def cb(d, sync_arr):
        sync_cb(d, sync_arr)
        now = _time.perf_counter()
        dt = now - last[0]
        last[0] = now
        _E._LEVEL_SECONDS.observe(dt, engine="binned", level=str(d))
        rows.append({"level": d, "seconds": round(dt, 6)})

    k = key if key is not None else jax.random.PRNGKey(0)
    # warmup pass, synced but untimed: every level's static L compiles
    # its own programs on first dispatch, and a compile (0.1-10 s) would
    # swamp the ms-scale device cost the arbiter exists to expose
    grower.grow(codes, stats, F, eta=eta, clip_val=clip_val, key=k,
                level_cb=sync_cb)
    last[0] = _time.perf_counter()
    grower.grow(codes, stats, F, eta=eta, clip_val=clip_val, key=k,
                level_cb=cb)
    return rows


# ===========================================================================
# Chunked boosting driver: ONE dispatch trains K trees (lax.scan); the host
# only sees tree arrays + updated margins between chunks (scoring / early
# stopping cadence — SharedTree.doScoringAndSaveModel analog).
def _grad_hess_binned(dist, F, y):
    """ComputePredAndRes on the padded margin vector (GBM.java:981)."""
    if dist == "gaussian":
        return y - F, jnp.ones_like(F)
    if dist in ("bernoulli", "quasibinomial"):
        p = jax.nn.sigmoid(F)
        return y - p, p * (1 - p)
    if dist == "poisson":
        mu = jnp.exp(jnp.clip(F, -30, 30))
        return y - mu, mu
    if dist == "gamma":
        mu = jnp.exp(jnp.clip(F, -30, 30))
        return y / mu - 1.0, y / mu
    if dist == "tweedie":
        mu = jnp.exp(jnp.clip(F, -30, 30))
        rmu = jnp.sqrt(mu)
        return y / rmu - rmu, 0.5 * (y / rmu + rmu)
    if dist == "laplace":
        return jnp.sign(y - F), jnp.ones_like(F)
    raise NotImplementedError(f"binned engine distribution {dist}")


def pack_route(route, n_bins, b_val=None):
    """(nodes, BP) bool -> (nodes, BP//32) uint32 bitset (IcedBitSet analog,
    water/util/IcedBitSet.java). With b_val given, slots >= b_val-1 replicate
    slot b_val-1 so float-scoring code clipping of high-cardinality
    categorical levels routes like training's capped codes (the NA slot is
    never consulted by the scorer — NaN takes the nal path first)."""
    nodes = route.shape[0]
    r = route[:, :n_bins]
    if b_val is not None and b_val < n_bins:
        r = jnp.concatenate(
            [r[:, : b_val - 1],
             jnp.broadcast_to(r[:, b_val - 1: b_val],
                              (nodes, n_bins - b_val + 1))], axis=1)
    r = r.reshape(nodes, n_bins // 32, 32)
    return (r.astype(jnp.uint32) <<
            jnp.arange(32, dtype=jnp.uint32)[None, None, :]).sum(
        -1, dtype=jnp.uint32)


def _memo_trainer(grower: BinnedGrower, cache_key, build_run, mesh,
                  in_specs, out_specs):
    """Shared trainer finalization: memoize the jitted program on the
    grower INSTANCE (a global id()-keyed cache can hand a recycled id a
    stale closure over another grower's bin edges), shard_map over the
    rows axis when a mesh is given. One definition so an in/out-spec or
    check_vma change cannot silently diverge across the three trainers."""
    cache = getattr(grower, "_trainer_cache", None)
    if cache is None:
        cache = grower._trainer_cache = {}
    fn = cache.get(cache_key)
    if fn is not None:
        return fn
    run = build_run()
    if mesh is not None:
        if grower.axis_name is None:
            raise ValueError("mesh given but grower has no axis_name")
        from h2o3_tpu.parallel.compat import shard_map as _shard_map
        fn = jax.jit(_shard_map(run, mesh=mesh, in_specs=in_specs,
                                out_specs=out_specs, check_vma=False))
    else:
        fn = jax.jit(run)
    cache[cache_key] = fn
    return fn


def _tree_col_mask(grower: BinnedGrower, key, col_rate_tree: float):
    """Per-tree column subset (col_sample_rate_per_tree): common key across
    shards so every shard draws the SAME mask. Returns None when disabled."""
    if col_rate_tree >= 1.0:
        return None
    c_real = int(grower.spec.is_cat.size)
    C = grower.spec.c_pad
    k = max(1, int(round(col_rate_tree * c_real)))
    r = jax.random.uniform(key, (C,))
    r = jnp.where(jnp.arange(C) < c_real, r, 2.0)
    kth = jnp.sort(r)[k - 1]
    return r <= kth


def gbm_chunk_trainer(grower: BinnedGrower, n: int, *, dist: str, eta: float,
                      sample_rate: float, mtries: int, k_trees: int,
                      clip_val: float = 19.0, col_rate_tree: float = 1.0,
                      mesh=None):
    """Build (and cache) the jitted K-tree training program.

    Contract: codes from `quantize` (uint8 (C_pad, n_pad)) run through
    `prepare_codes` (the packed i32 plane on the Pallas backend; n real
    rows, the rest dummies); y1/w1/F are (n_pad,) f32 with zeros beyond
    row n. Returns (new F, stacked tree arrays) per call.

    With `mesh` given (and grower.axis_name set) the program is shard_mapped
    over the rows axis: codes/y1/w1/F are row-sharded, each shard grows the
    tree on its local rows, and grow()'s per-level psum merges histograms —
    the MRTask reduce tree (MRTask.java:907-921) as ONE ICI collective per
    level. Split search and the tree arrays are replicated by construction
    (identical on every shard given the global histograms).
    """
    from jax.sharding import PartitionSpec as P
    axis = grower.axis_name if mesh is not None else None
    key_ = (n, dist, eta, sample_rate, mtries, k_trees, clip_val,
            col_rate_tree, axis, id(mesh) if mesh is not None else 0)

    gaussian = dist == "gaussian"
    cv = 0.0 if gaussian else clip_val

    # NOTE: keep the inner function literally named `run` — the persistent
    # XLA compile cache keys include the jitted function name, and the big
    # K-tree program costs minutes to recompile through the relay
    def build():
        def run(codes, y1, w1, F, key):
            def per_tree(carry, k):
                F, key = carry
                key, ks, kt = jax.random.split(key, 3)
                if axis:
                    # decorrelate row sampling across shards; the mtries key
                    # kt stays common so every shard draws the SAME col masks
                    ks = jax.random.fold_in(ks, lax.axis_index(axis))
                g, h = _grad_hess_binned(dist, F, y1)
                if sample_rate < 1.0:
                    u = jax.random.uniform(ks, w1.shape)
                    wt = w1 * (u < sample_rate)
                else:
                    wt = w1
                stats = jnp.stack(
                    [wt, wt * g, wt * h, jnp.zeros_like(wt)], axis=0)
                tmask = _tree_col_mask(grower, jax.random.fold_in(kt, 7),
                                       col_rate_tree)
                out = grower.grow(codes, stats, F, eta=eta, clip_val=cv,
                                  key=kt, mtries=mtries, tree_mask=tmask)
                F = out["F"]
                tree = (out["col"], out["bin"], out["nal"],
                        pack_route(out["route"], grower.spec.n_bins,
                                   grower.spec.b_val),
                        out["val"], out["gains"], out["cover"])
                return (F, key), tree

            (F, _), trees = lax.scan(per_tree, (F, key),
                                     jnp.arange(k_trees))
            return F, trees
        return run

    return _memo_trainer(
        grower, key_, build, mesh,
        in_specs=(P(None, axis), P(axis), P(axis), P(axis), P()),
        out_specs=(P(axis), P()))


# ===========================================================================
# Multinomial boosting: K class trees per iteration through the binned
# engine (SharedTree.java:548-561 builds the K trees of an iteration as one
# fused layer; here a lax.scan over classes inside ONE jitted program —
# codes stay device-resident, each class tree rides every binned
# optimization incl. the histogram psum and int8 stats).
def gbm_multi_chunk_trainer(grower: BinnedGrower, n: int, *, n_classes: int,
                            eta: float, sample_rate: float, mtries: int,
                            k_iters: int, clip_val: float = 19.0,
                            col_rate_tree: float = 1.0, mesh=None):
    """K-class K-tree-per-iteration program. F is (n_pad, K) margins;
    y1 is (n_pad,) class ids (f32); returns (F, stacked trees with leading
    dims (k_iters, K, ...))."""
    from jax.sharding import PartitionSpec as P
    axis = grower.axis_name if mesh is not None else None
    key_ = ("multi", n, n_classes, eta, sample_rate, mtries, k_iters,
            clip_val, col_rate_tree, axis, id(mesh) if mesh is not None else 0)

    K = int(n_classes)
    kscale = (K - 1) / K       # GammaPass multinomial leaf scale (GBM.java)

    def build():
        def run(codes, y1, w1, F, key):
            onehot = jax.nn.one_hot(y1.astype(jnp.int32), K)   # (n_pad, K)

            def per_iter(carry, it):
                F, key = carry
                key, ks, kt = jax.random.split(key, 3)
                if axis:
                    ks = jax.random.fold_in(ks, lax.axis_index(axis))
                probs = jax.nn.softmax(F, axis=1)
                RK = onehot - probs                            # residuals
                if sample_rate < 1.0:
                    u = jax.random.uniform(ks, w1.shape)
                    wt = w1 * (u < sample_rate)
                else:
                    wt = w1
                tmask = _tree_col_mask(grower, jax.random.fold_in(kt, 7),
                                       col_rate_tree)

                def per_class(_, k):
                    res = jnp.take_along_axis(RK, k[None, None], 1)[:, 0]
                    absr = jnp.abs(res)
                    hess = absr * (1.0 - absr)   # |res|(1-|res|) GammaPass
                    stats = jnp.stack([wt, wt * res * kscale, wt * hess,
                                       jnp.zeros_like(wt)], axis=0)
                    out = grower.grow(codes, stats, jnp.zeros_like(wt),
                                      eta=1.0, clip_val=clip_val,
                                      key=jax.random.fold_in(kt, k),
                                      mtries=mtries, tree_mask=tmask)
                    tree = (out["col"], out["bin"], out["nal"],
                            pack_route(out["route"], grower.spec.n_bins,
                                       grower.spec.b_val),
                            out["val"], out["gains"], out["cover"])
                    return None, (tree, out["F"])  # F==val[heap]: row pred

                _, (trees, dF) = lax.scan(per_class, None, jnp.arange(K))
                F = F + eta * dF.T                              # (n_pad, K)
                return (F, key), trees

            (F, _), trees = lax.scan(per_iter, (F, key),
                                     jnp.arange(k_iters))
            return F, trees
        return run

    return _memo_trainer(
        grower, key_, build, mesh,
        in_specs=(P(None, axis), P(axis), P(axis), P(axis), P()),
        out_specs=(P(axis), P()))


# ===========================================================================
# DRF: independent trees, leaf = in-bag response mean, OOB accumulation
# (hex/tree/drf/DRF.java:78 doOOBScoring()=true — the reference default).
def drf_chunk_trainer(grower: BinnedGrower, n: int, *, sample_rate: float,
                      mtries: int, k_trees: int, col_rate_tree: float = 1.0,
                      mesh=None):
    """Per tree: Bernoulli(sample_rate) in-bag mask; stats (w, w*y, w) so
    the Newton leaf value wg/wh is exactly the in-bag mean response (class
    frequency for 0/1 targets — ScoreBuildHistogram response-mean leaves);
    grow() with F=0, eta=1 returns per-row leaf values, accumulated into
    (oob_sum, oob_cnt) on OOB rows only. Returns (oob_sum, oob_cnt, trees)."""
    from jax.sharding import PartitionSpec as P
    axis = grower.axis_name if mesh is not None else None
    key_ = ("drf", n, sample_rate, mtries, k_trees, col_rate_tree, axis,
            id(mesh) if mesh is not None else 0)

    def build():
        def run(codes, y1, w1, oob_sum, oob_cnt, key):
            def per_tree(carry, t):
                oob_sum, oob_cnt, key = carry
                key, ks, kt = jax.random.split(key, 3)
                if axis:
                    ks = jax.random.fold_in(ks, lax.axis_index(axis))
                u = jax.random.uniform(ks, w1.shape)
                inbag = u < sample_rate
                wt = w1 * inbag
                stats = jnp.stack([wt, wt * y1, wt, jnp.zeros_like(wt)],
                                  axis=0)
                tmask = _tree_col_mask(grower, jax.random.fold_in(kt, 7),
                                       col_rate_tree)
                out = grower.grow(codes, stats, jnp.zeros_like(wt),
                                  eta=1.0, clip_val=0.0,
                                  key=kt, mtries=mtries, tree_mask=tmask)
                pred = out["F"]                       # per-row leaf value
                oob = (~inbag) & (w1 > 0)
                oob_sum = oob_sum + jnp.where(oob, pred, 0.0)
                oob_cnt = oob_cnt + oob.astype(jnp.float32)
                tree = (out["col"], out["bin"], out["nal"],
                        pack_route(out["route"], grower.spec.n_bins,
                                   grower.spec.b_val),
                        out["val"], out["gains"], out["cover"])
                return (oob_sum, oob_cnt, key), tree

            (oob_sum, oob_cnt, _), trees = lax.scan(
                per_tree, (oob_sum, oob_cnt, key), jnp.arange(k_trees))
            return oob_sum, oob_cnt, trees
        return run

    return _memo_trainer(
        grower, key_, build, mesh,
        in_specs=(P(None, axis), P(axis), P(axis), P(axis), P(axis), P()),
        out_specs=(P(axis), P(axis), P()))
