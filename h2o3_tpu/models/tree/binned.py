"""Binned (pre-quantized) tree engine — the TPU rebuild of the reference's
global-quantile histogram path, designed for MXU/VPU throughput.

Reference mapping:
  * hex/tree/GlobalQuantilesCalc.java — quantize features ONCE per training
    run into small-integer bin codes against global quantile edges (the
    `histogram_type="QuantilesGlobal"` mode; also xgboost `tree_method=hist`
    semantics, the BASELINE.json comparison target).
  * hex/tree/ScoreBuildHistogram2.java:20-60 — the fused score+build pass.
    Here rows are kept PARTITIONED by leaf (stable partition maintained per
    level entirely on device), so histogram accumulation is leaf-local and
    rides the Pallas kernel in ops/hist_pallas.py.
  * hex/tree/DTree.java:514 (DecidedNode.bestCol) — vectorized split search
    over (leaf, col, threshold, NA-direction), plus categorical SET splits:
    bins sorted by mean gradient and split on the best prefix (the optimal
    subset search for 1-D loss, replacing IcedBitSet group splits
    water/util/IcedBitSet.java) with the decision stored as a 256-bit mask.
  * hex/tree/Constraints.java — monotone constraints: sign-violating splits
    are rejected and child values are clamped to propagated bounds.
  * hex/tree/SharedTree.java:548-561 — task parallelism over trees becomes
    a lax.scan over trees inside ONE jitted program (a dispatch through the
    controller costs ~10ms; per-level dispatch would dominate runtime).

Everything per level is static-shaped: leaf arrays are sized L_MAX = 2^D,
the slot count n_pad = (ceil(n/R) + L_MAX) * R never changes, and empty
leaves own one all-dummy block. No host synchronization inside training.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from h2o3_tpu.ops import hist_pallas as HP

R = HP.BLOCK_ROWS


# ===========================================================================
# Quantization (GlobalQuantilesCalc analog)
@dataclass
class BinSpec:
    """Per-column binning of a training frame."""
    edges: np.ndarray        # (C, B_val-1) f32 — ascending cut points
    is_cat: np.ndarray       # (C,) bool — categorical column (codes = level)
    b_val: int               # number of value bins; NA code == b_val
    n_bins: int              # padded bin count used by the kernel (mult 128)
    c_pad: int               # padded column count (mult COL_TILE)

    @property
    def na_code(self):
        return self.b_val


def make_bins(X, is_cat, nbins: int, sample: int = 1 << 18) -> BinSpec:
    """Global quantile edges from a row sample. X: (n, C) f32 with NaN NAs.
    Categorical columns are identity-binned (code == level id, capped)."""
    n, C = X.shape
    b_val = int(min(nbins, 255))
    stride = max(1, n // sample)
    Xs = np.asarray(X[::stride][:sample], np.float32)
    edges = np.zeros((C, b_val - 1), np.float32)
    qs = np.linspace(0.0, 1.0, b_val + 1)[1:-1]
    for c in range(C):
        if is_cat[c]:
            # identity binning: edge k at k+0.5 so code(level k)=k
            edges[c] = np.arange(1, b_val, dtype=np.float32) - 0.5
            continue
        col = Xs[:, c]
        col = col[~np.isnan(col)]
        if col.size == 0:
            edges[c] = np.arange(1, b_val, dtype=np.float32)
            continue
        e = np.quantile(col, qs).astype(np.float32)
        # strictly non-decreasing is fine: duplicate edges => empty bins
        edges[c] = e
    nb = max(128, -(-(b_val + 1) // 128) * 128)
    cp = -(-C // HP.COL_TILE) * HP.COL_TILE
    return BinSpec(edges=edges, is_cat=np.asarray(is_cat, bool),
                   b_val=b_val, n_bins=nb, c_pad=cp)


@functools.partial(jax.jit, static_argnames=("b_val", "c_pad"))
def _quantize(X, edges, *, b_val, c_pad):
    """codes[r,c] = #edges < x (0..b_val-1), NA -> b_val. Output is padded
    with a trailing dummy row (code 0) and dummy columns for the kernel."""
    n, C = X.shape

    def one_col(x, e):
        code = jnp.searchsorted(e, x, side="left").astype(jnp.int32)
        return jnp.where(jnp.isnan(x), b_val, code)

    codes = jax.vmap(one_col, in_axes=(1, 1), out_axes=1)(X, edges)
    codes = jnp.clip(codes, 0, b_val)
    out = jnp.zeros((n + 1, c_pad), jnp.int32)
    return lax.dynamic_update_slice(out, codes, (0, 0))


def quantize(X, spec: BinSpec):
    return _quantize(X, jnp.asarray(spec.edges),
                     b_val=spec.b_val, c_pad=spec.c_pad)


# ===========================================================================
# Split search over binned histograms
def _se_gain(wl, gl, wr, gr_, wp, gp, lam):
    """Un-halved SE / structure-score reduction (same objective family as
    engine.find_best_splits; lam>0 = XGBoost G^2/(H+lambda))."""
    def score(w_, g_):
        return jnp.where(w_ > 0, g_ * g_ / jnp.maximum(w_ + lam, 1e-30), 0.0)
    return score(wl, gl) + score(wr, gr_) - score(wp, gp)


@functools.partial(
    jax.jit,
    static_argnames=("b_val", "use_hess", "l_max"))
def find_splits_binned(hist, is_cat, mono, cmask, lo, hi, *, b_val,
                       min_rows, msi, lam, use_hess, l_max):
    """Vectorized bestCol over every (leaf, col, threshold/subset, NA-dir).

    hist: (L, C_pad, 8, BP) — stats rows 0=cnt 1=w 2=wg 3=wh
    is_cat: (C_pad,) bool; mono: (C_pad,) int32 in {-1,0,1}
    cmask: (L, C_pad) bool column availability (mtries / padding)
    lo, hi: (L,) f32 monotone value bounds for each leaf

    Returns dict of per-leaf arrays: did, col, bin, nal, route (L, BP) bool,
    val_l, val_r (clamped), gain, plus per-leaf totals (cnt_t, w_t, val_t).
    """
    L, C, _, BP = hist.shape
    cnt = hist[:, :, 0, :]
    w = hist[:, :, 1, :]
    wg = hist[:, :, 2, :]
    wh = hist[:, :, 3, :]
    den = wh if use_hess else w

    B = b_val
    v_cnt, na_cnt = cnt[..., :B], cnt[..., B]
    v_w, na_w = w[..., :B], w[..., B]
    v_wg, na_wg = wg[..., :B], wg[..., B]
    v_den, na_den = den[..., :B], den[..., B]

    # ---- parent totals (identical for every real column; col 0 is real) --
    cnt_t = v_cnt[:, 0].sum(-1) + na_cnt[:, 0]
    w_t = v_w[:, 0].sum(-1) + na_w[:, 0]
    wg_t = v_wg[:, 0].sum(-1) + na_wg[:, 0]
    den_t = v_den[:, 0].sum(-1) + na_den[:, 0]
    val_t = wg_t / jnp.maximum(den_t, 1e-30)

    # ---- categorical: sort bins by mean gradient (optimal-subset order) --
    ratio = jnp.where(v_den > 1e-30, v_wg / jnp.maximum(v_den, 1e-30),
                      jnp.inf)                              # empty bins last
    order = jnp.argsort(ratio, axis=-1)                     # (L, C, B)
    sc_w = jnp.take_along_axis(v_w, order, -1)
    sc_wg = jnp.take_along_axis(v_wg, order, -1)
    sc_den = jnp.take_along_axis(v_den, order, -1)

    def eval_axis(aw, awg, aden):
        """Prefix-split gains along the (possibly re-ordered) bin axis.
        Returns (gain, nal) each (L, C, B-1)."""
        cl_w = jnp.cumsum(aw, -1)[..., :-1]
        cl_wg = jnp.cumsum(awg, -1)[..., :-1]
        cl_den = jnp.cumsum(aden, -1)[..., :-1]

        def gains(nal):
            lw = cl_w + (na_w[..., None] if nal else 0.0)
            lg = cl_wg + (na_wg[..., None] if nal else 0.0)
            ld = cl_den + (na_den[..., None] if nal else 0.0)
            rw = w_t[:, None, None] - lw
            rg = wg_t[:, None, None] - lg
            rd = den_t[:, None, None] - ld
            g = _se_gain(ld, lg, rd, rg, den_t[:, None, None],
                         wg_t[:, None, None], lam)
            ok = (lw >= min_rows) & (rw >= min_rows)
            # monotone: reject sign-violating splits on constrained columns
            vl = lg / jnp.maximum(ld, 1e-30)
            vr = rg / jnp.maximum(rd, 1e-30)
            mok = (mono[None, :, None] == 0) | \
                  ((vr - vl) * mono[None, :, None] >= 0)
            return jnp.where(ok & mok, g, -jnp.inf)

        g0, g1 = gains(False), gains(True)
        return jnp.maximum(g0, g1), g1 > g0

    gn_num, nal_num = eval_axis(v_w, v_wg, v_den)           # natural order
    gn_cat, nal_cat = eval_axis(sc_w, sc_wg, sc_den)        # sorted order

    catC = is_cat[None, :, None]
    gain_all = jnp.where(catC, gn_cat, gn_num)              # (L, C, B-1)
    nal_all = jnp.where(catC, nal_cat, nal_num)
    gain_all = jnp.where(cmask[:, :, None], gain_all, -jnp.inf)

    flat = gain_all.reshape(L, C * (B - 1))
    best = jnp.argmax(flat, axis=1)
    bgain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
    bcol = (best // (B - 1)).astype(jnp.int32)
    bbin = (best % (B - 1)).astype(jnp.int32)               # threshold index
    bnal = jnp.take_along_axis(nal_all.reshape(L, C * (B - 1)),
                               best[:, None], 1)[:, 0]
    did = jnp.isfinite(bgain) & (bgain > jnp.maximum(msi, 0.0))

    # ---- routing table: route[l, code] = goes-right ----------------------
    takeL = lambda a: jnp.take_along_axis(    # noqa: E731  (L,C,X)->(L,X)
        a, bcol[:, None, None], 1)[:, 0]
    bin_ids = jnp.arange(BP)[None, :]                       # (1, BP)
    num_right = bin_ids > bbin[:, None]                     # natural order
    rank_of_bin = jnp.argsort(takeL(order), axis=-1)        # (L, B)
    rank_pad = jnp.pad(rank_of_bin, ((0, 0), (0, BP - B)),
                       constant_values=BP)
    cat_right = rank_pad > bbin[:, None]
    leaf_cat = is_cat[bcol]
    route = jnp.where(leaf_cat[:, None], cat_right, num_right)
    # NA code: by chosen NA direction
    route = route.at[:, B].set(~bnal)
    route = jnp.where(did[:, None], route, False)           # frozen: stay

    # ---- child values (Newton wg/wh) with monotone clamping --------------
    bw = takeL(v_w)
    bg = takeL(v_wg)
    bd = takeL(v_den)
    bc = takeL(v_cnt)
    ncl = jnp.pad(na_cnt[:, 0:1], ((0, 0), (0, 0)))
    goes_left = ~route[:, :B]
    cnt_l = (bc * goes_left).sum(-1) + jnp.where(bnal, na_cnt[:, 0], 0.0)
    w_l = (bw * goes_left).sum(-1) + jnp.where(bnal, na_w[:, 0], 0.0)
    g_l = (bg * goes_left).sum(-1) + jnp.where(bnal, na_wg[:, 0], 0.0)
    d_l = (bd * goes_left).sum(-1) + jnp.where(bnal, na_den[:, 0], 0.0)
    val_l = g_l / jnp.maximum(d_l, 1e-30)
    g_r = wg_t - g_l
    d_r = den_t - d_l
    val_r = g_r / jnp.maximum(d_r, 1e-30)
    val_l = jnp.clip(val_l, lo, hi)
    val_r = jnp.clip(val_r, lo, hi)
    val_tc = jnp.clip(val_t, lo, hi)

    return dict(did=did, col=bcol, bin=bbin, nal=bnal, route=route,
                gain=jnp.where(did, jnp.maximum(bgain, 0.0), 0.0),
                cnt_l=cnt_l, cnt_r=cnt_t - cnt_l,
                val_l=val_l, val_r=val_r, val_t=val_tc,
                w_t=w_t, wg_l=g_l, wh_l=d_l, _unused=ncl)


# ===========================================================================
# The grower: one jitted program per chunk of trees
class BinnedGrower:
    """Grows trees level-by-level on pre-binned codes with device-resident
    leaf partitioning. One lax.scan over K trees per dispatch."""

    def __init__(self, spec: BinSpec, *, max_depth: int, min_rows: float,
                 min_split_improvement: float, reg_lambda: float = 0.0,
                 reg_alpha: float = 0.0, use_hess_denom: bool = False,
                 monotone: np.ndarray | None = None):
        self.spec = spec
        self.D = int(max_depth)
        self.L = 2 ** self.D
        self.nodes = 2 ** (self.D + 1) - 1
        self.min_rows = float(min_rows)
        self.msi = float(min_split_improvement)
        self.lam = float(reg_lambda)
        self.alpha = float(reg_alpha)
        self.use_hess = bool(use_hess_denom)
        mono = np.zeros(spec.c_pad, np.int32) if monotone is None else \
            np.asarray(monotone, np.int32)
        self.mono = jnp.asarray(mono)
        self.is_cat_dev = jnp.asarray(
            np.pad(spec.is_cat, (0, spec.c_pad - spec.is_cat.size)))

    # ---- static layout ---------------------------------------------------
    def layout(self, n: int):
        nblk = -(-n // R) + self.L
        return nblk, nblk * R

    def _init_partition(self, n: int):
        nblk, n_pad = self.layout(n)
        data_blocks = -(-n // R)
        # leaf 0 owns the data blocks; every other leaf owns one pad block
        offb0 = np.concatenate([[0], [data_blocks],
                                data_blocks + np.arange(1, self.L + 1)])
        perm0 = np.full(n_pad, n, np.int32)
        perm0[:n] = np.arange(n, dtype=np.int32)
        return jnp.asarray(perm0), jnp.asarray(offb0[:self.L + 1],
                                               jnp.int32)

    # ---- one level (traced inside fori_loop) -----------------------------
    def _level(self, d, state, codes, stats8, n, mtries_key=None,
               mtries: int = 0):
        (perm, offb, hm, froz, lo, hi, colA, binA, nalA, routeA, valA,
         gains) = state
        L, D, BP = self.L, self.D, self.spec.n_bins
        nblk, n_pad = self.layout(n)
        C = self.spec.c_pad

        codes_p = codes[perm]                          # (n_pad, C) int32
        stats_p = stats8[:, perm]                      # (8, n_pad) f32
        block_leaf = (jnp.searchsorted(offb, jnp.arange(nblk),
                                       side="right") - 1).astype(jnp.int32)
        hist = HP.build_hist(codes_p, stats_p, block_leaf,
                             n_leaves=L, n_bins=BP)

        c_real = int(self.spec.is_cat.size)
        if mtries and mtries < c_real:
            # per-(leaf, level) column sampling (DRF per-node semantics)
            r = jax.random.uniform(jax.random.fold_in(mtries_key, d), (L, C))
            r = jnp.where(jnp.arange(C) < c_real, r, 2.0)
            kth = jnp.sort(r, axis=1)[:, mtries - 1:mtries]
            cmask = r <= kth
        else:
            cmask = jnp.broadcast_to(
                (jnp.arange(C) < c_real)[None], (L, C))

        s = find_splits_binned(
            hist, self.is_cat_dev, self.mono, cmask, lo, hi,
            b_val=self.spec.b_val, min_rows=self.min_rows, msi=self.msi,
            lam=self.lam, use_hess=self.use_hess, l_max=L)

        live = jnp.arange(L) < (1 << d)                # leaves of this level
        valid_hm = live & (hm < self.nodes)
        did = s["did"] & valid_hm & ~froz

        # ---- write node arrays at heap ids -------------------------------
        tgt = jnp.where(valid_hm, hm, self.nodes)      # OOB -> dropped
        colA = colA.at[tgt].set(jnp.where(did, s["col"], -1), mode="drop")
        binA = binA.at[tgt].set(jnp.where(did, s["bin"], -1), mode="drop")
        nalA = nalA.at[tgt].set(s["nal"], mode="drop")
        routeA = routeA.at[tgt].set(s["route"], mode="drop")
        valA = valA.at[tgt].set(s["val_t"], mode="drop")
        kidL = jnp.where(did, 2 * hm + 1, self.nodes)
        kidR = jnp.where(did, 2 * hm + 2, self.nodes)
        valA = valA.at[kidL].set(s["val_l"], mode="drop")
        valA = valA.at[kidR].set(s["val_r"], mode="drop")
        gains = gains.at[jnp.where(did, s["col"], C)].add(
            s["gain"], mode="drop")

        # ---- route rows: stable partition --------------------------------
        leaf_slot = jnp.repeat(block_leaf, R)          # (n_pad,)
        col_slot = s["col"][leaf_slot]
        code_s = jnp.take_along_axis(
            codes_p, col_slot[:, None], axis=1)[:, 0]
        gr = s["route"].reshape(L * BP)[leaf_slot * BP + code_s]
        real = perm < n
        child = 2 * leaf_slot + gr.astype(jnp.int32)

        # child counts straight from the histogram (no row scatter); a
        # non-split leaf keeps everything in its "left" slot 2l
        l_ids = jnp.arange(L)
        idxL = jnp.where(valid_hm, 2 * l_ids, L)       # OOB -> dropped
        idxR = jnp.where(did, 2 * l_ids + 1, L)
        cnt_tot = s["cnt_l"] + s["cnt_r"]
        cnt2 = jnp.zeros(L, jnp.float32) \
            .at[idxL].add(jnp.where(did, s["cnt_l"], cnt_tot),
                          mode="drop") \
            .at[idxR].add(s["cnt_r"], mode="drop")
        cnt2i = jnp.round(cnt2).astype(jnp.int32)

        blocks2 = jnp.maximum(1, -(-cnt2i // R))
        offb2 = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                 jnp.cumsum(blocks2)]).astype(jnp.int32)

        # stable rank within child via segmented exclusive cumsums
        xl = (real & ~gr).astype(jnp.int32)
        xr = (real & gr).astype(jnp.int32)
        exl = jnp.cumsum(xl) - xl
        exr = jnp.cumsum(xr) - xr
        offs = offb * R                                # (L+1,) slot offsets
        basel = exl[jnp.minimum(offs[:-1], n_pad - 1)]
        baser = exr[jnp.minimum(offs[:-1], n_pad - 1)]
        rank = jnp.where(gr, exr - baser[leaf_slot], exl - basel[leaf_slot])
        # frozen/unsplit leaves: everyone is a "left" child of slot 2l
        pos = offb2[jnp.minimum(child, L)] * R + rank
        pos = jnp.where(real, pos, n_pad)              # pads dropped
        perm2 = jnp.full(n_pad, n, jnp.int32).at[pos].set(
            jnp.where(real, perm, n), mode="drop")

        # ---- heap map / frozen / bounds for next level -------------------
        l2 = jnp.arange(L)
        parent = l2 // 2
        is_r = (l2 % 2) == 1
        pd = did[parent]
        pvalid = hm[parent] < self.nodes
        # split parent: children get real heap ids; unsplit parent: rows
        # stay at the parent's terminal node via the left slot; right slot
        # and invalid parents get the OOB sentinel
        hm2 = jnp.where(pd, 2 * hm[parent] + 1 + is_r.astype(jnp.int32),
                        jnp.where(is_r, self.nodes, hm[parent]))
        hm2 = jnp.where(pvalid, hm2, self.nodes)
        froz2 = ~pd | ~pvalid                         # terminal continuation
        # monotone bounds: children of a monotone split get a shared midpoint
        mc = self.mono[s["col"]]                       # (L,) constraint sign
        mid = 0.5 * (s["val_l"] + s["val_r"])
        lo2 = jnp.where(pd,
                        jnp.where(is_r & (mc[parent] > 0), mid[parent],
                                  jnp.where(~is_r & (mc[parent] < 0),
                                            mid[parent], lo[parent])),
                        lo[parent])
        hi2 = jnp.where(pd,
                        jnp.where(~is_r & (mc[parent] > 0), mid[parent],
                                  jnp.where(is_r & (mc[parent] < 0),
                                            mid[parent], hi[parent])),
                        hi[parent])

        return (perm2, offb2, hm2, froz2, lo2, hi2, colA, binA, nalA,
                routeA, valA, gains), block_leaf

    # ---- grow one tree (D fused levels), return node arrays + row preds --
    def grow(self, codes, stats8, n: int, key, mtries: int = 0):
        L, D = self.L, self.D
        nblk, n_pad = self.layout(n)
        perm0, offb0 = self._init_partition(n)
        hm0 = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.full(L - 1, self.nodes, jnp.int32)])
        froz0 = jnp.arange(L) != 0
        big = jnp.float32(3e38)
        state = (perm0, offb0, hm0, froz0,
                 jnp.full(L, -big), jnp.full(L, big),
                 jnp.full(self.nodes, -1, jnp.int32),
                 jnp.full(self.nodes, -1, jnp.int32),
                 jnp.zeros(self.nodes, bool),
                 jnp.zeros((self.nodes, self.spec.n_bins), bool),
                 jnp.zeros(self.nodes, jnp.float32),
                 jnp.zeros(self.spec.c_pad + 1, jnp.float32))

        def body(d, st):
            st2, _ = self._level(d, st, codes, stats8, n,
                                 mtries_key=key, mtries=mtries)
            return st2

        state = lax.fori_loop(0, D, body, state)
        (perm, offb, hm, froz, lo, hi, colA, binA, nalA, routeA, valA,
         gains) = state
        # terminal heap id per slot (for the F update / leaf preds)
        block_leaf = (jnp.searchsorted(offb, jnp.arange(nblk),
                                       side="right") - 1).astype(jnp.int32)
        leaf_slot = jnp.repeat(block_leaf, R)
        heap_slot = hm[jnp.minimum(leaf_slot, L - 1)]
        heap_slot = jnp.minimum(heap_slot, self.nodes - 1)
        return dict(col=colA, bin=binA, nal=nalA, route=routeA, val=valA,
                    gains=gains[:self.spec.c_pad], perm=perm,
                    heap_slot=heap_slot)


# ===========================================================================
# Chunked boosting driver: ONE dispatch trains K trees (lax.scan), the host
# only sees tree arrays + updated margins between chunks (scoring / early
# stopping cadence — SharedTree.doScoringAndSaveModel analog).
def _grad_hess_binned(dist, F, y):
    """ComputePredAndRes on the padded margin vector (GBM.java:981)."""
    if dist == "gaussian":
        return y - F, jnp.ones_like(F)
    if dist in ("bernoulli", "quasibinomial"):
        p = jax.nn.sigmoid(F)
        return y - p, p * (1 - p)
    if dist == "poisson":
        mu = jnp.exp(jnp.clip(F, -30, 30))
        return y - mu, mu
    if dist == "gamma":
        mu = jnp.exp(jnp.clip(F, -30, 30))
        return y / mu - 1.0, y / mu
    if dist == "tweedie":
        mu = jnp.exp(jnp.clip(F, -30, 30))
        rmu = jnp.sqrt(mu)
        return y / rmu - rmu, 0.5 * (y / rmu + rmu)
    if dist == "laplace":
        return jnp.sign(y - F), jnp.ones_like(F)
    raise NotImplementedError(f"binned engine distribution {dist}")


_TRAINER_CACHE: dict = {}


def pack_route(route, n_bins):
    """(nodes, BP) bool -> (nodes, BP//32) uint32 bitset (IcedBitSet analog,
    water/util/IcedBitSet.java)."""
    nodes = route.shape[0]
    r = route[:, :n_bins].reshape(nodes, n_bins // 32, 32)
    return (r.astype(jnp.uint32) <<
            jnp.arange(32, dtype=jnp.uint32)[None, None, :]).sum(
        -1, dtype=jnp.uint32)


def gbm_chunk_trainer(grower: BinnedGrower, n: int, *, dist: str, eta: float,
                      sample_rate: float, mtries: int, k_trees: int,
                      clip_val: float = 19.0):
    """Build (and cache) the jitted K-tree training program."""
    key_ = (id(grower.spec), grower.D, grower.min_rows, grower.msi,
            grower.lam, grower.use_hess, n, dist, eta, sample_rate,
            mtries, k_trees, clip_val)
    fn = _TRAINER_CACHE.get(key_)
    if fn is not None:
        return fn

    gaussian = dist == "gaussian"

    @jax.jit
    def run(codes, y1, w1, F, key):
        """codes (n+1, C_pad) int32; y1/w1/F (n+1,) f32 (slot n = dummy)."""
        def per_tree(carry, k):
            F, key = carry
            key, ks, kt = jax.random.split(key, 3)
            g, h = _grad_hess_binned(dist, F, y1)
            if sample_rate < 1.0:
                u = jax.random.uniform(ks, w1.shape)
                wt = w1 * (u < sample_rate)
            else:
                wt = w1
            stats8 = jnp.zeros((8, n + 1), jnp.float32)
            stats8 = stats8.at[0, :n].set(1.0)            # partition counts
            stats8 = stats8.at[1].set(wt)                 # min_rows weight
            stats8 = stats8.at[2].set(wt * g)             # Newton numerator
            stats8 = stats8.at[3].set(wt * h)             # Newton denominator
            out = grower.grow(codes, stats8, n, kt, mtries=mtries)
            val = out["val"] if gaussian else \
                jnp.clip(out["val"], -clip_val, clip_val)
            F = F.at[out["perm"]].add(
                eta * val[out["heap_slot"]], mode="drop")
            F = F.at[n].set(0.0)
            tree = (out["col"], out["bin"], out["nal"],
                    pack_route(out["route"], grower.spec.n_bins), val,
                    out["gains"])
            return (F, key), tree

        (F, _), trees = lax.scan(per_tree, (F, key),
                                 jnp.arange(k_trees))
        return F, trees

    _TRAINER_CACHE[key_] = run
    return run
