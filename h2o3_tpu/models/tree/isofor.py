"""Isolation Forest — hex/tree/isofor/IsolationForest.java.

Reference: random-split trees on row samples; isolation depth → anomaly score.
H2O grows trees choosing a random column and a random threshold inside the
node's observed [min,max] and scores rows by normalized mean path length.

TPU-native design: no histograms needed — per level we only need per-(leaf,
col) min/max (one segment reduction) to draw random thresholds; routing reuses
the shared apply_splits kernel. Path length is encoded INTO the tree's value
array (value[node] = depth(node) + c(node_size)), so scoring the ensemble is
the same fixed-depth gather walk as GBM — mean path length = average of tree
"predictions"."""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.models.tree import engine as E
from h2o3_tpu.models.tree.shared_tree import SharedTreeEstimator


def _avg_path(n: float) -> float:
    """c(n): average unsuccessful-search path length in a BST of n nodes."""
    if n <= 1:
        return 0.0
    if n == 2:
        return 1.0
    h = math.log(n - 1) + 0.5772156649
    return 2.0 * h - 2.0 * (n - 1) / n


class H2OIsolationForestEstimator(SharedTreeEstimator):
    algo = "isolationforest"
    supervised = False
    _defaults = dict(SharedTreeEstimator._tree_defaults)
    _defaults.update({"ntrees": 50, "max_depth": 8, "sample_size": 256,
                      "sample_rate": -1.0, "contamination": -1.0})

    def _fit(self, frame: Frame, job):
        di = self._dinfo
        X = di.matrix(frame)
        w = di.weights(frame)
        n = frame.nrows
        C = X.shape[1]
        D = int(self.params["max_depth"])
        ntrees = int(self.params["ntrees"])
        seed = int(self.params.get("seed") or -1)
        rng = np.random.default_rng(seed if seed > 0 else 42)
        sample_size = int(self.params.get("sample_size") or 256)
        sample_rate = float(self.params.get("sample_rate") or -1.0)
        psi = (max(2, int(sample_rate * n)) if sample_rate > 0
               else min(sample_size, n))
        nodes = 2 ** (D + 1) - 1
        wh = np.asarray(w)
        live = np.nonzero(wh > 0)[0]
        trees = []
        for t in range(ntrees):
            idx = rng.choice(live, size=min(psi, len(live)), replace=False)
            wt = np.zeros(len(wh), np.float32)
            wt[idx] = 1.0
            wtj = jnp.asarray(wt)
            col, thr, nal, val = self._grow_random_tree(X, wtj, C, D, nodes, rng)
            trees.append((col, thr, nal, val))
            job.update(0.1 + 0.8 * (t + 1) / ntrees, f"tree {t+1}")
        self._trees = self._finish_trees(trees, D)
        self._psi = psi
        # score training data to calibrate min/max path length (H2O exposes
        # normalized score via observed min/max mean lengths)
        ml = np.asarray(self._mean_length(X))[:n]
        self._min_len, self._max_len = float(ml.min()), float(ml.max())
        self._output.model_summary = {
            "number_of_trees": ntrees, "max_depth": D, "sample_size": psi,
        }

    def _grow_random_tree(self, X, w, C, D, nodes, rng):
        col_arr = np.full(nodes, -1, np.int32)
        thr_arr = np.zeros(nodes, np.float32)
        nal_arr = np.zeros(nodes, bool)
        val_arr = np.zeros(nodes, np.float32)
        leaf = jnp.zeros(X.shape[0], jnp.int32)
        active = w > 0
        import jax
        for d in range(D):
            L = 2 ** d
            lv = jnp.where(active, leaf, L)
            mn, mx = E.leaf_ranges(X, lv, L)
            cnt = jax.ops.segment_sum(w, lv, num_segments=L + 1)[:L]
            mn_np = np.asarray(mn)
            mx_np = np.asarray(mx)
            cnt_np = np.asarray(cnt)
            base = 2 ** d - 1
            did = np.zeros(L, bool)
            cols = np.zeros(L, np.int32)
            thrs = np.zeros(L, np.float32)
            for l in range(L):
                # record path-length value in case this node terminalizes
                val_arr[base + l] = d + _avg_path(cnt_np[l])
                span = mx_np[l] - mn_np[l]
                cand = np.nonzero(span > 0)[0]
                if cnt_np[l] > 1 and len(cand) > 0 and d < D:
                    c = int(rng.choice(cand))
                    u = rng.random()
                    cols[l] = c
                    thrs[l] = mn_np[l, c] + u * span[c]
                    did[l] = True
            col_arr[base:base + L] = np.where(did, cols, -1)
            thr_arr[base:base + L] = thrs
            if not did.any():
                break
            leaf, active = E.apply_splits(
                X, leaf, active, jnp.asarray(did), jnp.asarray(cols),
                jnp.asarray(thrs), jnp.asarray(np.zeros(L, bool)))
        # deepest level values
        L = 2 ** D
        import jax
        lv = jnp.where(active, leaf, L)
        cnt = jax.ops.segment_sum(w, lv, num_segments=L + 1)[:L]
        cnt_np = np.asarray(cnt)
        for l in range(L):
            val_arr[2 ** D - 1 + l] = D + _avg_path(cnt_np[l])
        return col_arr, thr_arr, nal_arr, val_arr

    # ---- scoring ---------------------------------------------------------
    def _mean_length(self, X):
        return E.predict_ensemble(X, self._trees) / self._trees.ntrees

    def _score_matrix(self, X):
        return self._mean_length(X)

    def predict(self, test_data: Frame) -> Frame:
        X = self._dinfo.matrix(test_data)
        ml = np.asarray(self._mean_length(X))[: test_data.nrows].astype(np.float64)
        span = max(self._max_len - self._min_len, 1e-12)
        score = (self._max_len - ml) / span   # H2O's observed-range normalization
        return Frame(["predict", "mean_length"],
                     [Vec.from_numpy(score), Vec.from_numpy(ml)])
