"""Isolation Forest — hex/tree/isofor/IsolationForest.java.

Reference: random-split trees on row samples; isolation depth → anomaly score.
H2O grows trees choosing a random column and a random threshold inside the
node's observed [min,max] and scores rows by normalized mean path length.

TPU-native design: no histograms — per level we need only per-(leaf,col)
min/max (a segment reduction) to draw random (column, threshold) pairs from
the tree's PRNG key, all inside ONE fused jitted level program (no host RNG,
no round-trips). Path length is encoded INTO the tree's value array
(value[node] = depth(node) + c(node_size)), so scoring the ensemble is the
same fixed-depth gather walk as GBM — mean path length = average of tree
"predictions"."""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.models.tree import engine as E
from h2o3_tpu.models.tree.shared_tree import SharedTreeEstimator

_EULER = 0.5772156649


def _avg_path_jnp(n):
    """c(n): average unsuccessful-search path length in a BST of n points."""
    h = jnp.log(jnp.maximum(n - 1, 1.0)) + _EULER
    c = 2.0 * h - 2.0 * (n - 1) / jnp.maximum(n, 1.0)
    return jnp.where(n <= 1, 0.0, jnp.where(n < 2.5, 1.0, c))


@functools.partial(jax.jit, static_argnames=("d",))
def _iso_level(X, w, leaf, heap, active, colA, thrA, valA, key, *, d):
    L = 2 ** d
    C = X.shape[1]
    lv = jnp.where(active & (w > 0), leaf, L)
    mn, mx = E.leaf_ranges(X, lv, L)
    cnt = jax.ops.segment_sum(w, lv, num_segments=L + 1)[:L]
    span = mx - mn
    valid = span > 0
    r = jax.random.uniform(jax.random.fold_in(key, 2 * d), (L, C))
    c_sel = jnp.argmax(jnp.where(valid, r, -1.0), axis=1).astype(jnp.int32)
    has = valid.any(axis=1)
    u = jax.random.uniform(jax.random.fold_in(key, 2 * d + 1), (L,))
    mn_s = jnp.take_along_axis(mn, c_sel[:, None], 1)[:, 0]
    mx_s = jnp.take_along_axis(mx, c_sel[:, None], 1)[:, 0]
    thr = mn_s + u * (mx_s - mn_s)
    did = has & (cnt > 1.5)
    base = 2 ** d - 1
    val_lvl = (d + _avg_path_jnp(cnt)).astype(jnp.float32)
    valA = jax.lax.dynamic_update_slice(valA, val_lvl, (base,))
    colA = jax.lax.dynamic_update_slice(
        colA, jnp.where(did, c_sel, -1).astype(jnp.int32), (base,))
    thrA = jax.lax.dynamic_update_slice(thrA, thr.astype(jnp.float32), (base,))
    # route
    c = c_sel[leaf]
    t = thr[leaf]
    x = jnp.take_along_axis(X, c[:, None], axis=1)[:, 0]
    go_right = jnp.where(jnp.isnan(x), False, x > t)
    splits = did[leaf] & active
    leaf = jnp.where(splits, 2 * leaf + go_right.astype(jnp.int32), 0)
    heap = jnp.where(splits, 2 * heap + 1 + go_right.astype(jnp.int32), heap)
    return leaf, heap, splits, colA, thrA, valA


@functools.partial(jax.jit, static_argnames=("D",))
def _iso_final(w, leaf, active, valA, *, D):
    L = 2 ** D
    lv = jnp.where(active & (w > 0), leaf, L)
    cnt = jax.ops.segment_sum(w, lv, num_segments=L + 1)[:L]
    vals = (D + _avg_path_jnp(cnt)).astype(jnp.float32)
    return jax.lax.dynamic_update_slice(valA, vals, (2 ** D - 1,))


class H2OIsolationForestEstimator(SharedTreeEstimator):
    algo = "isolationforest"
    supervised = False
    _defaults = dict(SharedTreeEstimator._tree_defaults)
    _defaults.update({"ntrees": 50, "max_depth": 8, "sample_size": 256,
                      "sample_rate": -1.0, "contamination": -1.0})

    def _fit(self, frame: Frame, job):
        di = self._dinfo
        X = di.matrix(frame)
        w = di.weights(frame)
        n = frame.nrows
        D = int(self.params["max_depth"])
        ntrees = int(self.params["ntrees"])
        seed = int(self.params.get("seed") or -1)
        key = jax.random.PRNGKey(seed if seed > 0 else 42)
        sample_size = int(self.params.get("sample_size") or 256)
        sample_rate = float(self.params.get("sample_rate") or -1.0)
        psi = (max(2, int(sample_rate * n)) if sample_rate > 0
               else min(sample_size, n))
        nodes = 2 ** (D + 1) - 1
        rate = psi / max(n, 1)
        trees = []
        for t in range(ntrees):
            key, k1, k2 = jax.random.split(key, 3)
            # ψ-row subsample via bernoulli rate (device-side; avoids a host
            # choice() round-trip; E[rows] = ψ like the reference's sampler)
            wt = w * (jax.random.uniform(k1, w.shape) < rate)
            leaf = jnp.zeros(X.shape[0], jnp.int32)
            heap = jnp.zeros(X.shape[0], jnp.int32)
            active = jnp.ones(X.shape[0], bool)
            colA = jnp.full(nodes, -1, jnp.int32)
            thrA = jnp.zeros(nodes, jnp.float32)
            valA = jnp.zeros(nodes, jnp.float32)
            for d in range(D):
                leaf, heap, active, colA, thrA, valA = _iso_level(
                    X, wt, leaf, heap, active, colA, thrA, valA, k2, d=d)
            valA = _iso_final(wt, leaf, active, valA, D=D)
            trees.append((colA, thrA, jnp.zeros(nodes, bool), valA))
            job.update(0.1 + 0.8 * (t + 1) / ntrees, f"tree {t+1}")
        self._trees = E.stack_trees(trees, D)
        self._psi = psi
        # calibrate observed min/max mean path length (one sync, end of fit)
        ml = np.asarray(self._mean_length(X))[:n]
        self._min_len, self._max_len = float(ml.min()), float(ml.max())
        self._output.model_summary = {
            "number_of_trees": ntrees, "max_depth": D, "sample_size": psi,
        }

    # ---- scoring ---------------------------------------------------------
    def _mean_length(self, X):
        return E.predict_ensemble(X, self._trees) / self._trees.ntrees

    def _score_matrix(self, X):
        return self._mean_length(X)

    def predict(self, test_data: Frame) -> Frame:
        # _score_host prefers the serving compiled-scorer cache (bucketed,
        # recompile-free); large frames fall back to the sharded path
        ml = np.asarray(self._score_host(test_data),
                        np.float64)[: test_data.nrows]
        span = max(self._max_len - self._min_len, 1e-12)
        score = (self._max_len - ml) / span   # H2O's observed-range normalization
        return Frame(["predict", "mean_length"],
                     [Vec.from_numpy(score), Vec.from_numpy(ml)])
