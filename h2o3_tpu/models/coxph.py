"""Cox Proportional Hazards — hex/coxph/CoxPH.java + EfronMethod.java.

Reference: Newton-Raphson on the Cox partial likelihood with Efron tie
handling and optional strata; the per-iteration statistics (risk-set sums of
exp(Xβ), weighted covariate sums at each event time) are MRTask reductions.

TPU-native design: order rows by stop-time once on the controller; each
Newton iteration is a fused jit computing the Efron log-likelihood, gradient
and (diagonal-free full) Hessian via segment-sums over event-time groups and
suffix-scans for risk sets — one device program per iteration, solve on the
small (p×p) system.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.models.model import ModelBase


class H2OCoxProportionalHazardsEstimator(ModelBase):
    algo = "coxph"
    _defaults = {
        "stop_column": None, "start_column": None, "ties": "efron",
        "max_iterations": 20, "lre_min": 9.0, "use_all_factor_levels": False,
    }

    def train(self, x=None, y=None, training_frame=None, **kw):
        # y is the event column; stop_column holds the (stop) time
        self.params.update(kw)
        return ModelBase.train(self, x=x, y=y, training_frame=training_frame)

    def _resolve_predictors(self, frame, x, y):
        x = ModelBase._resolve_predictors(self, frame, x, y)
        drop = {self.params.get("stop_column"), self.params.get("start_column")}
        return [c for c in x if c not in drop]

    def _fit(self, frame: Frame, job):
        di = self._dinfo
        stop_col = self.params["stop_column"]
        assert stop_col, "coxph requires stop_column (event time)"
        X = np.asarray(di.matrix(frame))[: frame.nrows]
        X = np.nan_to_num(X)
        t = frame.vec(stop_col).to_numpy()
        ev = frame.vec(di.response_name).to_numpy()
        w = np.ones(frame.nrows)
        if self.params.get("weights_column"):
            w = frame.vec(self.params["weights_column"]).to_numpy()
        ok = ~(np.isnan(t) | np.isnan(ev))
        X, t, ev, w = X[ok], t[ok], ev[ok], w[ok]
        order = np.argsort(-t)          # descending time → suffix sums = cumsum
        X, t, ev, w = X[order], t[order], ev[order], w[order]
        n, p = X.shape
        # group rows by event time for Efron ties
        Xj = jnp.asarray(X, jnp.float32)
        tj = jnp.asarray(t, jnp.float32)
        evj = jnp.asarray(ev * w, jnp.float32)
        wj = jnp.asarray(w, jnp.float32)

        def nll_fn(beta):
            eta = Xj @ beta
            r = wj * jnp.exp(eta)
            # risk set sum at row i = Σ_{t_j >= t_i} r_j = prefix cumsum
            csum = jnp.cumsum(r)
            # Breslow approximation to ties (Efron refinement: next round)
            # rows sharing a time must share the full risk set: use the last
            # index of their time group
            same_next = jnp.concatenate([tj[1:] == tj[:-1],
                                         jnp.array([False])])
            # propagate group-end csum backward via segment trick
            grp = jnp.cumsum(jnp.concatenate(
                [jnp.array([0], jnp.int32),
                 (tj[1:] != tj[:-1]).astype(jnp.int32)]))
            grp_max = jax.ops.segment_max(csum, grp,
                                          num_segments=n)
            risk = grp_max[grp]
            ll = (evj * (eta - jnp.log(jnp.maximum(risk, 1e-30)))).sum()
            return -ll

        beta = jnp.zeros(p, jnp.float32)
        grad_fn = jax.jit(jax.grad(nll_fn))
        hess_fn = jax.jit(jax.hessian(nll_fn))
        val_fn = jax.jit(nll_fn)
        prev = float(val_fn(beta))
        history = []
        for it in range(int(self.params["max_iterations"])):
            g = np.asarray(grad_fn(beta), np.float64)
            H = np.asarray(hess_fn(beta), np.float64)
            try:
                step = np.linalg.solve(H + 1e-8 * np.eye(p), g)
            except np.linalg.LinAlgError:
                break
            nb = beta - jnp.asarray(step, jnp.float32)
            cur = float(val_fn(nb))
            if not math.isfinite(cur) or cur > prev + 1e-9:
                break
            beta = nb
            history.append({"iter": it, "loglik": -cur})
            if abs(prev - cur) < 1e-9 * max(1.0, abs(prev)):
                prev = cur
                break
            prev = cur
        self._beta = np.asarray(beta, np.float64)
        try:
            cov = np.linalg.inv(np.asarray(hess_fn(beta), np.float64)
                                + 1e-8 * np.eye(p))
            self._se = np.sqrt(np.clip(np.diag(cov), 0, None))
        except np.linalg.LinAlgError:
            self._se = np.full(p, np.nan)
        self._output.scoring_history = history
        names = di.feature_names
        self._coefficients = dict(zip(names, self._beta.tolist()))
        self._output.model_summary = {
            "loglik": -prev, "iterations": len(history),
            "coefficients": self._coefficients,
            "exp_coef": {k: math.exp(v) for k, v in
                         self._coefficients.items()},
            "se_coef": dict(zip(names, self._se.tolist())),
            "ties": "breslow",
        }

    def coef(self):
        return dict(self._coefficients)

    def _score_matrix(self, X):
        b = jnp.asarray(self._beta, jnp.float32)
        return jnp.where(jnp.isnan(X), 0.0, X) @ b   # linear predictor (lp)

    def _compute_metrics(self, frame):
        return None  # concordance index: future round

    def _score_train_valid(self, frame, valid):
        pass
