"""Cox Proportional Hazards — hex/coxph/CoxPH.java + EfronMethod.java.

Reference: Newton-Raphson on the Cox partial likelihood with Efron or
Breslow tie handling and optional strata (CoxPH.java:128-136
`stratify_by`: risk sets form within each stratum; the baseline hazard is
stratum-specific while beta is shared). The per-iteration statistics
(risk-set sums of exp(Xbeta), covariate sums at event times) are MRTask
reductions in the reference.

TPU-native design: order rows by (stratum, -stop_time) once on the
controller and precompute the (stratum, time)-group index arrays as
constants; each Newton iteration is ONE fused jit computing the partial
log-likelihood via cumsum + segment reductions (risk sets never
materialize), with gradient/Hessian by autodiff on the same program;
the p x p solve happens on the controller. Ties: Efron (default) via
per-event-row rank within its tie group, Breslow via the plain group
risk sum. Model metrics report the concordance index (CoxPH.java
concordance on the training frame).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.models.model import ModelBase
from h2o3_tpu.parallel import compat as _compat


class H2OCoxProportionalHazardsEstimator(ModelBase):
    algo = "coxph"
    # mesh-sharded serving: hazard coefficients as one shared device copy
    _serving_param_attrs = ("_beta",)
    _defaults = {
        "stop_column": None, "start_column": None, "ties": "efron",
        "stratify_by": None, "max_iterations": 20, "lre_min": 9.0,
        "use_all_factor_levels": False,
    }

    def train(self, x=None, y=None, training_frame=None, **kw):
        # y is the event column; stop_column holds the (stop) time
        self.params.update(kw)
        return ModelBase.train(self, x=x, y=y, training_frame=training_frame)

    def _resolve_predictors(self, frame, x, y):
        x = ModelBase._resolve_predictors(self, frame, x, y)
        drop = {self.params.get("stop_column"),
                self.params.get("start_column")}
        drop.update(self._strata_cols())
        return [c for c in x if c not in drop]

    def _strata_cols(self):
        s = self.params.get("stratify_by")
        if not s:
            return []
        return [s] if isinstance(s, str) else list(s)

    def _fit(self, frame: Frame, job):
        di = self._dinfo
        stop_col = self.params["stop_column"]
        assert stop_col, "coxph requires stop_column (event time)"
        ties = str(self.params.get("ties") or "efron").lower()
        if ties not in ("efron", "breslow"):
            raise ValueError(f"ties must be efron|breslow, got {ties!r}")
        X = np.asarray(di.matrix(frame))[: frame.nrows]
        X = np.nan_to_num(X)
        t = frame.vec(stop_col).to_numpy()
        ev = frame.vec(di.response_name).to_numpy()
        w = np.ones(frame.nrows)
        if self.params.get("weights_column"):
            w = frame.vec(self.params["weights_column"]).to_numpy()

        # strata: integer id per row from the cross of stratify_by columns
        # (CoxPH.java: strata columns must be categorical)
        strat = np.zeros(frame.nrows, np.int64)
        for c in self._strata_cols():
            v = frame.vec(c)
            if v.type != "enum":
                raise ValueError(
                    f"stratify_by column {c!r} must be categorical "
                    "(CoxPH strata are enum crosses)")
            codes = np.nan_to_num(v.to_numpy(), nan=-1).astype(np.int64)
            strat = strat * (v.cardinality + 1) + (codes + 1)

        ok = ~(np.isnan(t) | np.isnan(ev))
        X, t, ev, w, strat = X[ok], t[ok], ev[ok], w[ok], strat[ok]
        # renumber strata densely, order rows (stratum asc, time desc):
        # within a stratum the prefix cumsum of r is the risk-set sum
        _, strat = np.unique(strat, return_inverse=True)
        order = np.lexsort((-t, strat))
        X, t, ev, w, strat = (X[order], t[order], ev[order], w[order],
                              strat[order])
        n, p = X.shape

        # (stratum, time) tie groups + per-group constants, all host-side
        new_grp = np.ones(n, bool)
        new_grp[1:] = (strat[1:] != strat[:-1]) | (t[1:] != t[:-1])
        grp = np.cumsum(new_grp) - 1                     # (n,) group id
        n_grp = int(grp[-1]) + 1 if n else 0
        new_strat = np.ones(n, bool)
        new_strat[1:] = strat[1:] != strat[:-1]
        strat_id = np.cumsum(new_strat) - 1              # stratum id per row
        first_idx = np.where(new_strat)[0]               # row idx per stratum
        # Efron rank among EVENT rows of the tie group and group event count
        is_ev = ev > 0
        gs_idx = np.where(new_grp)[0]                    # start row per group
        evcum = np.cumsum(is_ev)
        before_grp = np.where(gs_idx > 0, evcum[np.maximum(gs_idx - 1, 0)], 0)
        rank = np.where(is_ev, evcum - 1 - before_grp[grp], 0.0)
        dcount = np.bincount(grp[is_ev], minlength=n_grp).astype(np.float64)

        Xj = jnp.asarray(X, jnp.float32)
        evj = jnp.asarray(ev * w, jnp.float32)
        wj = jnp.asarray(w, jnp.float32)
        grp_j = jnp.asarray(grp, jnp.int32)
        strat_j = jnp.asarray(strat_id, jnp.int32)
        base_j = jnp.asarray(first_idx - 1, jnp.int32)   # (-1 for stratum 0)
        rank_j = jnp.asarray(rank, jnp.float32)
        d_j = jnp.asarray(np.maximum(dcount, 1.0), jnp.float32)
        isev_j = jnp.asarray(is_ev, jnp.float32) * wj

        def nll_fn(beta):
            eta = Xj @ beta
            r = wj * jnp.exp(eta)
            csum = jnp.cumsum(r)
            # per-group end cumsum, minus the cumsum before this stratum —
            # risk sets never cross strata (CoxPH.java:128-136)
            grp_max = jax.ops.segment_max(csum, grp_j, num_segments=n_grp)
            strat_base = jnp.where(base_j >= 0,
                                   csum[jnp.maximum(base_j, 0)], 0.0)
            risk = grp_max[grp_j] - strat_base[strat_j]
            if ties == "efron":
                # tie-group event risk sum T_g; k-th event in the group sees
                # denominator R_g - (k/d_g) * T_g (EfronMethod.java)
                tie_r = jax.ops.segment_sum(
                    r * (isev_j > 0), grp_j, num_segments=n_grp)[grp_j]
                denom = risk - (rank_j / d_j[grp_j]) * tie_r
            else:
                denom = risk
            ll = (evj * eta).sum() - (
                isev_j * jnp.log(jnp.maximum(denom, 1e-30))).sum()
            return -ll

        beta = jnp.zeros(p, jnp.float32)
        grad_fn = _compat.guard_collective(jax.jit(jax.grad(nll_fn)))
        hess_fn = _compat.guard_collective(jax.jit(jax.hessian(nll_fn)))
        val_fn = _compat.guard_collective(jax.jit(nll_fn))
        prev = float(val_fn(beta))
        history = []
        for it in range(int(self.params["max_iterations"])):
            g = np.asarray(grad_fn(beta), np.float64)
            H = np.asarray(hess_fn(beta), np.float64)
            try:
                step = np.linalg.solve(H + 1e-8 * np.eye(p), g)
            except np.linalg.LinAlgError:
                break
            nb = beta - jnp.asarray(step, jnp.float32)
            cur = float(val_fn(nb))
            if not math.isfinite(cur) or cur > prev + 1e-9:
                break
            beta = nb
            history.append({"iter": it, "loglik": -cur})
            if abs(prev - cur) < 1e-9 * max(1.0, abs(prev)):
                prev = cur
                break
            prev = cur
        self._beta = np.asarray(beta, np.float64)
        try:
            cov = np.linalg.inv(np.asarray(hess_fn(beta), np.float64)
                                + 1e-8 * np.eye(p))
            self._se = np.sqrt(np.clip(np.diag(cov), 0, None))
        except np.linalg.LinAlgError:
            self._se = np.full(p, np.nan)
        self._output.scoring_history = history
        names = di.feature_names
        self._coefficients = dict(zip(names, self._beta.tolist()))
        conc = _concordance(t, ev, strat,
                            np.asarray(X @ np.asarray(beta, np.float64)))
        self._output.model_summary = {
            "loglik": -prev, "iterations": len(history),
            "coefficients": self._coefficients,
            "exp_coef": {k: math.exp(v) for k, v in
                         self._coefficients.items()},
            "se_coef": dict(zip(names, self._se.tolist())),
            "ties": ties, "concordance": conc,
            "strata": self._strata_cols() or None,
            "n_strata": int(strat.max()) + 1 if n else 0,
        }

    def coef(self):
        return dict(self._coefficients)

    def _score_matrix(self, X):
        b = jnp.asarray(self._beta, jnp.float32)
        return jnp.where(jnp.isnan(X), 0.0, X) @ b   # linear predictor (lp)

    def _compute_metrics(self, frame):
        return None

    def _score_train_valid(self, frame, valid):
        pass


def _concordance(t, ev, strat, lp, cap: int = 8000) -> float:
    """Concordance index over comparable pairs within strata (the
    reference's MetricsCoxPH concordance). O(n^2) with broadcasting,
    subsampled beyond `cap` rows for boundedness."""
    n = len(t)
    if n == 0:
        return float("nan")
    if n > cap:
        rng = np.random.default_rng(0)
        idx = rng.choice(n, cap, replace=False)
        t, ev, strat, lp = t[idx], ev[idx], strat[idx], lp[idx]
    # pair (i, j) comparable when t_i < t_j, ev_i = 1, same stratum
    ti, tj = t[:, None], t[None, :]
    comp = (ti < tj) & (ev[:, None] > 0) & \
        (strat[:, None] == strat[None, :])
    li, ljj = lp[:, None], lp[None, :]
    conc = comp & (li > ljj)
    tied = comp & (li == ljj)
    n_comp = comp.sum()
    if n_comp == 0:
        return float("nan")
    return float((conc.sum() + 0.5 * tied.sum()) / n_comp)
