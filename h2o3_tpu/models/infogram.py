"""Infogram (admissible ML) — h2o-admissibleml / ai.h2o.admissibleml.

Reference: h2o-admissibleml wraps hex Infogram: for every predictor compute
(1) a relevance index — normalized variable importance from a supervised
model on all predictors — and (2) an information index — normalized
conditional mutual information of the predictor with the response, estimated
by model performance. Features above both thresholds (default 0.1) are
"admissible". The fair ("safety") variant conditions on protected columns:
the information index becomes the predictor's information about the response
NOT carried through the protected columns.

TPU-native design: the CMI estimates are per-feature GBM fits on the shared
histogram engine — each a short chips-resident training run; relevance comes
from the full model's gain importances. No separate native library."""

from __future__ import annotations

import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.core.kvstore import DKV


class H2OInfogram:
    algo = "infogram"

    def __init__(self, protected_columns=None, net_information_threshold=0.1,
                 relevance_index_threshold=0.1, safety_index_threshold=0.1,
                 total_information_threshold=0.1, ntrees=20, max_depth=5,
                 nbins=20, seed=-1, algorithm="gbm"):
        self.protected_columns = list(protected_columns or [])
        self.rel_thresh = relevance_index_threshold
        self.info_thresh = (safety_index_threshold if protected_columns
                            else net_information_threshold
                            if net_information_threshold != 0.1
                            else total_information_threshold)
        self.ntrees = ntrees
        self.max_depth = max_depth
        self.nbins = nbins
        self.seed = seed
        self.algorithm = algorithm
        self._result = None
        self.key = None

    # ------------------------------------------------------------------
    def _perf(self, frame, x, y, is_cls):
        """Normalized predictive performance of x → y (CMI estimate)."""
        from h2o3_tpu.models import H2OGradientBoostingEstimator
        m = H2OGradientBoostingEstimator(
            ntrees=self.ntrees, max_depth=self.max_depth, nbins=self.nbins,
            seed=self.seed if self.seed > 0 else 7)
        m.train(x=x, y=y, training_frame=frame)
        tm = m._output.training_metrics
        DKV.remove(m.key)
        if is_cls and getattr(tm, "auc", None) is not None:
            return max(0.0, 2.0 * tm.auc - 1.0)          # Gini ∈ [0,1]
        # regression: explained variance (R²) as the information proxy
        yv = frame.vec(y).to_numpy()
        r2 = 1.0 - tm.mse / max(float(np.nanvar(yv)), 1e-30)
        return max(0.0, min(1.0, r2))

    def train(self, x=None, y=None, training_frame=None):
        f = training_frame
        assert isinstance(f, Frame) and y is not None
        prot = self.protected_columns
        if x is None:
            x = [c for c in f.names if c != y and c not in prot]
        is_cls = f.vec(y).type == "enum"
        # --- relevance: varimp of the full (non-protected) model ----------
        from h2o3_tpu.models import H2OGradientBoostingEstimator
        full = H2OGradientBoostingEstimator(
            ntrees=self.ntrees, max_depth=self.max_depth, nbins=self.nbins,
            seed=self.seed if self.seed > 0 else 7)
        full.train(x=x, y=y, training_frame=f)
        vi = {r["variable"]: r["relative_importance"]
              for r in (full.varimp() or [])}
        DKV.remove(full.key)
        mx = max(vi.values()) if vi else 1.0
        relevance = {c: vi.get(c, 0.0) / max(mx, 1e-30) for c in x}
        # --- information index --------------------------------------------
        info = {}
        base = self._perf(f, prot, y, is_cls) if prot else 0.0
        for c in x:
            perf = self._perf(f, prot + [c], y, is_cls)
            info[c] = max(0.0, perf - base)
        mx = max(info.values()) if info else 1.0
        info = {c: v / max(mx, 1e-30) for c, v in info.items()}
        rows = []
        for c in x:
            admissible = (relevance[c] >= self.rel_thresh
                          and info[c] >= self.info_thresh)
            rows.append({
                "column": c,
                "relevance_index": float(relevance[c]),
                ("safety_index" if prot else "total_information_index"):
                    float(info[c]),
                "admissible": bool(admissible),
            })
        ikey = "safety_index" if prot else "total_information_index"
        rows.sort(key=lambda r: -(r["relevance_index"] + r[ikey]))
        self._result = rows
        self.key = DKV.make_key("infogram")
        DKV.put(self.key, self)
        return self

    # ------------------------------------------------------------------
    def get_admissible_features(self):
        return [r["column"] for r in self._result if r["admissible"]]

    def get_admissible_score_frame(self):
        cols = list(self._result[0].keys()) if self._result else []
        data = {k: np.array([r[k] for r in self._result],
                            object if k in ("column",) else np.float64)
                for k in cols}
        data["admissible"] = data["admissible"].astype(np.float64)
        return Frame.from_dict(data)

    @property
    def result(self):
        return self._result
