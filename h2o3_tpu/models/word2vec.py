"""Word2Vec — hex/word2vec rebuilt as batched negative-sampling SGD.

Reference: hex/word2vec/WordVectorTrainer.java:17 (hierarchical-softmax
skip-gram over shared _syn0/_syn1 with per-node Hogwild updates and
cross-node weight averaging in reduce :152,174), WordCountTask.java (vocab),
HBWTree.java (Huffman tree).

TPU-native design: skip-gram with NEGATIVE SAMPLING (the standard
mini-batch-able formulation) instead of hierarchical softmax — HS exists in
the reference because per-row tree walks were cheap on CPU Hogwild; on TPU
the batched dot-product formulation is the hardware-shaped equivalent, and
synchronous allreduce SGD replaces Hogwild+averaging (same swap the
DeepLearning port makes, BASELINE.json). Outputs the same artifact: a
word→vector frame usable by transform()/find_synonyms().
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame, Vec, T_STR
from h2o3_tpu.models.model import ModelBase
from h2o3_tpu.parallel import compat as _compat


class H2OWord2vecEstimator(ModelBase):
    algo = "word2vec"
    supervised = False
    _defaults = {
        "vec_size": 100, "window_size": 5, "sent_sample_rate": 1e-3,
        "norm_model": "HSM", "epochs": 5, "min_word_freq": 5,
        "init_learning_rate": 0.025, "negative_samples": 5,
        "max_runtime_secs": 0.0,
    }

    def train(self, training_frame=None, **kw):
        self.params.update(kw)
        f = training_frame
        self.key = self.params.get("model_id") or \
            __import__("h2o3_tpu.core.kvstore", fromlist=["DKV"]).DKV.make_key("word2vec")
        # corpus: one string column; sentences separated by NA rows
        v = f.vecs[0]
        if v.type == T_STR:
            words = [w for w in v.host_data]
        else:
            dom = v.levels()
            words = [None if np.isnan(c) else dom[int(c)]
                     for c in v.to_numpy()]
        self._fit_corpus(words)
        from h2o3_tpu.core.kvstore import DKV
        DKV.put(self.key, self)
        return self

    def _fit_corpus(self, words):
        min_freq = int(self.params["min_word_freq"])
        dim = int(self.params["vec_size"])
        win = int(self.params["window_size"])
        neg = int(self.params["negative_samples"])
        epochs = int(self.params["epochs"])
        lr = float(self.params["init_learning_rate"])
        seed = int(self.params.get("seed") or -1)
        # vocab (WordCountTask)
        from collections import Counter
        counts = Counter(w for w in words if w is not None)
        vocab = [w for w, c in counts.most_common() if c >= min_freq]
        self._vocab = {w: i for i, w in enumerate(vocab)}
        V = len(vocab)
        if V == 0:
            raise ValueError("empty vocabulary (lower min_word_freq?)")
        # training pairs from windows within sentences
        sents, cur = [], []
        for w in words:
            if w is None:
                if cur:
                    sents.append(cur)
                cur = []
            elif w in self._vocab:
                cur.append(self._vocab[w])
        if cur:
            sents.append(cur)
        centers, contexts = [], []
        for s in sents:
            for i, c in enumerate(s):
                for j in range(max(0, i - win), min(len(s), i + win + 1)):
                    if j != i:
                        centers.append(c)
                        contexts.append(s[j])
        if not centers:
            raise ValueError("no training pairs")
        centers = np.asarray(centers, np.int32)
        contexts = np.asarray(contexts, np.int32)
        # unigram^0.75 negative table
        freq = np.array([counts[w] for w in vocab], np.float64) ** 0.75
        freq /= freq.sum()
        rng = np.random.default_rng(seed if seed > 0 else 0)
        key = jax.random.PRNGKey(seed if seed > 0 else 0)
        syn0 = jnp.asarray(rng.uniform(-0.5 / dim, 0.5 / dim, (V, dim)),
                           jnp.float32)
        syn1 = jnp.zeros((V, dim), jnp.float32)

        @_compat.guard_collective

        @jax.jit
        def step(syn0, syn1, c_idx, ctx_idx, neg_idx, lr):
            def loss(params):
                s0, s1 = params
                vc = s0[c_idx]                       # (B, d)
                vpos = s1[ctx_idx]                   # (B, d)
                vneg = s1[neg_idx]                   # (B, neg, d)
                pos = jax.nn.log_sigmoid((vc * vpos).sum(-1))
                negs = jax.nn.log_sigmoid(-(vc[:, None, :] * vneg).sum(-1))
                # SUM over pairs: a batch of row-sparse per-pair grads is
                # (approximately) the same as word2vec's sequential SGD
                # updates — the MEAN formulation moved vectors ~1/B as far
                # per epoch and left embeddings untrained at any sane
                # epoch count
                return -(pos.sum() + negs.sum())

            l, g = jax.value_and_grad(loss)((syn0, syn1))
            # clip per-element: small vocabularies collide many pairs on
            # the same row inside a batch; unclipped sum-updates diverge
            g0 = jnp.clip(g[0], -1.0, 1.0)
            g1 = jnp.clip(g[1], -1.0, 1.0)
            return syn0 - lr * g0, syn1 - lr * g1, l

        B = min(1024, len(centers))
        nsteps = max(1, epochs * len(centers) // B)
        # init_learning_rate is the reference's PER-PAIR rate; the summed
        # batch step applies ~B pair-updates at once, so scale down
        step_lr = lr * 0.1
        for s in range(nsteps):
            idx = rng.integers(0, len(centers), B)
            negs = rng.choice(V, size=(B, neg), p=freq)
            cur_lr = step_lr * max(0.1, 1 - s / nsteps)
            syn0, syn1, l = step(syn0, syn1,
                                 jnp.asarray(centers[idx]),
                                 jnp.asarray(contexts[idx]),
                                 jnp.asarray(negs), cur_lr)
        self._vectors = np.asarray(syn0)
        self._vocab_list = vocab

    # ---- public surface (h2o-py H2OWord2vecEstimator) --------------------
    def find_synonyms(self, word: str, count: int = 20):
        if word not in self._vocab:
            return {}
        v = self._vectors[self._vocab[word]]
        sims = self._vectors @ v / (
            np.linalg.norm(self._vectors, axis=1) * np.linalg.norm(v) + 1e-12)
        order = np.argsort(-sims)
        out = {}
        for i in order:
            w = self._vocab_list[i]
            if w != word:
                out[w] = float(sims[i])
            if len(out) >= count:
                break
        return out

    def transform(self, frame: Frame, aggregate_method: str = "NONE") -> Frame:
        """words → vectors; AVERAGE pools per sentence (NA-separated)."""
        v = frame.vecs[0]
        if v.type == T_STR:
            words = list(v.host_data)
        else:
            dom = v.levels()
            words = [None if np.isnan(c) else dom[int(c)]
                     for c in v.to_numpy()]
        dim = self._vectors.shape[1]
        if aggregate_method.upper() == "AVERAGE":
            rows, acc, cnt = [], np.zeros(dim), 0
            for w in words + [None]:
                if w is None:
                    rows.append(acc / cnt if cnt else np.full(dim, np.nan))
                    acc, cnt = np.zeros(dim), 0
                elif w in self._vocab:
                    acc = acc + self._vectors[self._vocab[w]]
                    cnt += 1
            mat = np.vstack(rows[:-1]) if len(rows) > 1 else np.vstack(rows)
        else:
            mat = np.vstack([
                self._vectors[self._vocab[w]] if w in self._vocab
                else np.full(dim, np.nan) for w in words])
        return Frame([f"V{i+1}" for i in range(dim)],
                     [Vec.from_numpy(mat[:, i]) for i in range(dim)])

    def to_frame(self) -> Frame:
        cols = {"Word": np.asarray(self._vocab_list, object)}
        for i in range(self._vectors.shape[1]):
            cols[f"V{i+1}"] = self._vectors[:, i].astype(np.float64)
        return Frame.from_dict(cols)
