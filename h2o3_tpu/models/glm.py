"""GLM — hex/glm/GLM.java rebuilt: IRLS where the Gram is one MXU matmul.

Reference: hex/glm/GLM.java (3775 LoC; fitIRLSM :1733, ADMM :1184, COD :1870,
multinomial COD :1228, lambda search), hex/glm/GLMTask.java (GLMIterationTask
:1502 — ONE distributed pass building the weighted Gram XᵀWX and XᵀWz),
hex/gram/Gram.java (hand-parallelized in-core Cholesky :473),
hex/optimization/ADMM.java, L_BFGS.java.

TPU-native design:
  * GLMIterationTask becomes a single jit: Xw = X·w; G = XᵀXw; q = Xᵀ(wz) —
    blocked dot_generals on the MXU, cross-shard psum by XLA (replacing the
    MRTask reduce + hand-written Gram accumulation).
  * Gram.cholesky becomes jnp.linalg solve on the controller-visible (p×p)
    Gram — p is small; no distributed Cholesky needed.
  * L1/elastic-net is solved by cyclic coordinate descent ON THE GRAM
    (the reference's COD solver, GLM.java:1870): O(p²) per sweep on host,
    no extra device passes.
  * Multinomial follows the reference's per-class block-coordinate IRLS
    (GLM.java:1228): per class, softmax working weights/response, one Gram
    pass per class per sweep.
  * Lambda search warm-starts down a geometric path from λ_max, like
    GLM's lambda search.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.models import metrics as M
from h2o3_tpu.models.model import ModelBase

# ---------------------------------------------------------------------------
# Families / links (hex/glm/GLMModel.GLMParameters.Family)
GAUSSIAN, BINOMIAL, QUASIBINOMIAL, POISSON, GAMMA, TWEEDIE, NEGBINOMIAL, \
    MULTINOMIAL, ORDINAL = ("gaussian", "binomial", "quasibinomial", "poisson",
                            "gamma", "tweedie", "negativebinomial",
                            "multinomial", "ordinal")

_CANONICAL_LINK = {GAUSSIAN: "identity", BINOMIAL: "logit",
                   QUASIBINOMIAL: "logit", POISSON: "log", GAMMA: "inverse",
                   TWEEDIE: "tweedie", NEGBINOMIAL: "log",
                   MULTINOMIAL: "multinomial"}


def _linkinv(link, eta, tweedie_link_power=1.0):
    if link == "identity":
        return eta
    if link == "logit":
        return jax.nn.sigmoid(eta)
    if link == "log":
        return jnp.exp(eta)
    if link == "inverse":
        safe = jnp.where(jnp.abs(eta) < 1e-8, jnp.sign(eta) * 1e-8 + 1e-12, eta)
        return 1.0 / safe
    if link == "tweedie":
        lp = tweedie_link_power
        return jnp.exp(eta) if lp == 0 else jnp.power(jnp.clip(eta, 1e-10), 1.0 / lp)
    raise ValueError(link)


# ---------------------------------------------------------------------------
@jax.jit
def _gram_pass(X, w, z):
    """GLMIterationTask: G = XᵀWX, q = XᵀWz in one fused device program."""
    Xw = X * w[:, None]
    G = X.T @ Xw
    q = Xw.T @ z
    return G, q


def _irls_weights(family, link, eta, y, w_obs, tweedie_var_power=1.5,
                  theta=1.0):
    """Working weights and response for one IRLS step (GLMTask computeWeights)."""
    mu = _linkinv(link, eta)
    if family == GAUSSIAN:
        return w_obs, y if link == "identity" else eta + (y - mu)
    if family in (BINOMIAL, QUASIBINOMIAL):
        # f32-safe clip: 1-1e-8 rounds to 1.0 in f32 and zeroes the variance
        mu = jnp.clip(mu, 1e-6, 1 - 1e-6)
        d = jnp.maximum(mu * (1 - mu), 1e-6)
        wi = w_obs * d
        z = eta + (y - mu) / d
        return wi, z
    if family == POISSON:
        mu = jnp.clip(mu, 1e-8)
        wi = w_obs * mu
        return wi, eta + (y - mu) / mu
    if family == GAMMA:  # log link path
        mu = jnp.clip(mu, 1e-8)
        if link == "log":
            return w_obs, eta + (y - mu) / mu
        wi = w_obs * mu * mu
        return wi, eta - (y - mu) / (mu * mu)
    if family == TWEEDIE:
        p = tweedie_var_power
        mu = jnp.clip(mu, 1e-8)
        wi = w_obs * jnp.power(mu, 2.0 - p)
        return wi, eta + (y - mu) / mu
    if family == NEGBINOMIAL:
        mu = jnp.clip(mu, 1e-8)
        wi = w_obs * mu / (1.0 + theta * mu)
        return wi, eta + (y - mu) / mu
    raise ValueError(family)


@jax.jit
def _eta_pass(X, beta):
    return X @ beta


def _soft(x, t):
    return math.copysign(max(abs(x) - t, 0.0), x)


def _cod_solve(G, q, lam, alpha, p_pen, beta0, tol=1e-8, max_sweeps=1000):
    """Cyclic coordinate descent on the Gram (GLM.java:1870 COD solver).

    Minimizes ½βᵀGβ − qᵀβ + λα‖β_pen‖₁ + ½λ(1−α)‖β_pen‖² — host-side, p small.
    Column p_pen.. (intercept) unpenalized.
    """
    p = len(q)
    beta = beta0.copy()
    l1 = lam * alpha
    l2 = lam * (1 - alpha)
    for _ in range(max_sweeps):
        delta = 0.0
        for j in range(p):
            gj = q[j] - G[j] @ beta + G[j, j] * beta[j]
            denom = G[j, j] + (l2 if j < p_pen else 0.0)
            if denom <= 0:
                continue
            nb = _soft(gj, l1) / denom if j < p_pen else gj / denom
            delta = max(delta, abs(nb - beta[j]))
            beta[j] = nb
        if delta < tol:
            break
    return beta


@dataclass
class _GLMState:
    beta: np.ndarray            # (p+1,) or (K, p+1) for multinomial
    link: str
    family: str


class H2OGeneralizedLinearEstimator(ModelBase):
    algo = "glm"
    _defaults = {
        "family": "AUTO", "link": "family_default", "solver": "AUTO",
        "alpha": None, "lambda_": None, "lambda_search": False, "nlambdas": 30,
        "lambda_min_ratio": 1e-4, "max_iterations": 50,
        "beta_epsilon": 1e-4, "objective_epsilon": 1e-6,
        "gradient_epsilon": 1e-6, "intercept": True,
        "tweedie_variance_power": 0.0, "tweedie_link_power": 1.0,
        "theta": 1e-10, "compute_p_values": False, "remove_collinear_columns": False,
        "missing_values_handling": "MeanImputation", "non_negative": False,
        "standardize": True, "prior": -1.0, "max_active_predictors": -1,
    }

    # ------------------------------------------------------------------
    def _fit(self, frame: Frame, job):
        di = self._dinfo
        fam = self._resolve_family()
        self._family = fam
        link = self.params.get("link") or "family_default"
        if link in ("family_default", None, "AUTO"):
            link = _CANONICAL_LINK[fam]
        self._link = link
        X = di.matrix(frame)                       # standardized, imputed
        y = di.response(frame)
        w = di.weights(frame)
        w = jnp.where(jnp.isnan(y), 0.0, w)
        yz = jnp.where(jnp.isnan(y), 0.0, y)
        ones = jnp.ones((X.shape[0], 1), X.dtype)
        Xi = jnp.concatenate([X, ones], axis=1)    # intercept column last
        if fam == MULTINOMIAL or (fam == "AUTO_MULTI"):
            self._fit_multinomial(Xi, yz, w, job)
        else:
            self._fit_irls(Xi, yz, w, job)
        self._build_output(frame)

    def _resolve_family(self) -> str:
        fam = self.params.get("family", "AUTO")
        if fam and fam != "AUTO":
            return fam
        if self._dinfo.response_domain is None:
            return GAUSSIAN
        return BINOMIAL if len(self._dinfo.response_domain) == 2 else MULTINOMIAL

    def _alpha_lambda(self, G, q, p_pen):
        alpha = self.params.get("alpha")
        alpha = 0.5 if alpha is None else (alpha[0] if isinstance(alpha, (list, tuple)) else float(alpha))
        lam = self.params.get("lambda_")
        if isinstance(lam, (list, tuple)):
            lam = lam[0]
        if self.params.get("lambda_search"):
            lam_max = np.abs(q[:p_pen]).max() / max(alpha, 1e-3)
            lams = np.geomspace(lam_max,
                                lam_max * self.params["lambda_min_ratio"],
                                int(self.params["nlambdas"]))
            return alpha, list(lams)
        if lam is None:
            lam = 0.0 if not self.params.get("lambda_search") else None
        return alpha, [float(lam)]

    # ------------------------------------------------------------------
    def _fit_irls(self, Xi, y, w, job):
        fam, link = self._family, self._link
        p1 = Xi.shape[1]
        p_pen = p1 - 1 if self.params.get("intercept", True) else p1
        beta = np.zeros(p1, np.float64)
        # sensible intercept start
        wn = np.asarray(w, np.float64)
        yn = np.asarray(y, np.float64)
        ybar = float((wn * yn).sum() / max(wn.sum(), 1e-12))
        if fam in (BINOMIAL, QUASIBINOMIAL):
            yb = min(max(ybar, 1e-6), 1 - 1e-6)
            beta[-1] = math.log(yb / (1 - yb))
        elif fam in (POISSON, GAMMA, TWEEDIE, NEGBINOMIAL):
            beta[-1] = math.log(max(ybar, 1e-8)) if link == "log" else (
                1.0 / max(ybar, 1e-8) if link == "inverse" else ybar)
        else:
            beta[-1] = ybar
        # first pass for lambda_max needs the null-model gram
        eta = _eta_pass(Xi, jnp.asarray(beta, jnp.float32))
        wi, z = _irls_weights(fam, link, eta, y, w,
                              self.params["tweedie_variance_power"] or 1.5,
                              self.params["theta"])
        G, q = _gram_pass(Xi, wi, z)
        Gn, qn = np.asarray(G, np.float64), np.asarray(q, np.float64)
        alpha, lams = self._alpha_lambda(Gn, qn - Gn @ beta, p_pen)
        max_it = int(self.params["max_iterations"])
        beps = float(self.params["beta_epsilon"])
        path = []
        for lam in lams:
            for it in range(max(1, max_it)):
                eta = _eta_pass(Xi, jnp.asarray(beta, jnp.float32))
                wi, z = _irls_weights(fam, link, eta, y, w,
                                      self.params["tweedie_variance_power"] or 1.5,
                                      self.params["theta"])
                G, q = _gram_pass(Xi, wi, z)
                Gn = np.asarray(G, np.float64)
                qn = np.asarray(q, np.float64)
                if alpha > 0 and lam > 0:
                    # objective is (1/N)·deviance + λ·pen ⇒ scale λ by Σw
                    nb = _cod_solve(Gn, qn, lam * wn.sum(), alpha, p_pen, beta)
                else:
                    A = Gn + lam * wn.sum() * (1 - alpha) * np.eye(p1)
                    if p_pen < p1:
                        A[p1 - 1, p1 - 1] = Gn[p1 - 1, p1 - 1]
                    nb = np.linalg.solve(A + 1e-10 * np.eye(p1), qn)
                if self.params.get("non_negative"):
                    nb[:p_pen] = np.maximum(nb[:p_pen], 0.0)
                dmax = float(np.max(np.abs(nb - beta)))
                beta = nb
                if fam == GAUSSIAN and link == "identity":
                    break
                if dmax < beps:
                    break
            path.append((lam, beta.copy()))
            job.update(0.6, f"lambda {lam:.4g}")
        self._lambda_path = path
        self._state = _GLMState(beta=beta, link=link, family=fam)
        self._Gram = Gn
        self._wsum = float(wn.sum())

    # ------------------------------------------------------------------
    def _fit_multinomial(self, Xi, y, w, job):
        """Block-coordinate per-class IRLS (GLM.java:1228)."""
        K = self.nclasses
        p1 = Xi.shape[1]
        p_pen = p1 - 1
        beta = np.zeros((K, p1), np.float64)
        wn = np.asarray(w, np.float64)
        # class priors → intercept init
        yi = np.asarray(y, np.float64).astype(int)
        for c in range(K):
            pc = (wn * (yi == c)).sum() / max(wn.sum(), 1e-12)
            beta[c, -1] = math.log(max(pc, 1e-6))
        alpha = self.params.get("alpha")
        alpha = 0.5 if alpha is None else (alpha[0] if isinstance(alpha, (list, tuple)) else float(alpha))
        lam = self.params.get("lambda_") or 0.0
        if isinstance(lam, (list, tuple)):
            lam = lam[0]
        max_it = int(self.params["max_iterations"])
        beps = float(self.params["beta_epsilon"])

        @jax.jit
        def probs_fn(B):
            return jax.nn.softmax(Xi @ B.T, axis=1)

        @jax.jit
        def class_gram(B, c, yk):
            P = jax.nn.softmax(Xi @ B.T, axis=1)
            pc = jnp.clip(P[:, c], 1e-6, 1 - 1e-6)   # f32-safe
            d = jnp.maximum(pc * (1 - pc), 1e-6)
            wi = w * d
            eta_c = Xi @ B[c]
            z = eta_c + (yk - pc) / d
            Xw = Xi * wi[:, None]
            return Xi.T @ Xw, Xw.T @ z

        @jax.jit
        def obj_fn(B):
            P = jax.nn.softmax(Xi @ B.T, axis=1)
            py = jnp.take_along_axis(P, jnp.asarray(yi)[:, None], 1)[:, 0]
            return -(w * jnp.log(jnp.clip(py, 1e-12, 1.0))).sum()

        prev_obj = float(obj_fn(jnp.asarray(beta, jnp.float32)))
        for sweep in range(max_it):
            dmax = 0.0
            last_good = beta.copy()
            for c in range(K):
                yk = jnp.asarray((yi == c).astype(np.float32))
                G, q = class_gram(jnp.asarray(beta, jnp.float32),
                                  c, yk)
                Gn, qn = np.asarray(G, np.float64), np.asarray(q, np.float64)
                if alpha > 0 and lam > 0:
                    nb = _cod_solve(Gn, qn, lam * wn.sum(), alpha, p_pen,
                                    beta[c].copy())
                else:
                    A = Gn + lam * wn.sum() * (1 - alpha) * np.eye(p1)
                    A[p1 - 1, p1 - 1] = Gn[p1 - 1, p1 - 1]
                    nb = np.linalg.solve(A + 1e-8 * np.eye(p1), qn)
                dmax = max(dmax, float(np.max(np.abs(nb - beta[c]))))
                beta[c] = nb
            job.update(0.6, f"multinomial sweep {sweep}")
            obj = float(obj_fn(jnp.asarray(beta, jnp.float32)))
            if not math.isfinite(obj) or obj > prev_obj + 1e-6 * abs(prev_obj):
                beta = last_good    # separable-data divergence guard
                break
            prev_obj = obj
            if dmax < beps:
                break
        self._state = _GLMState(beta=beta, link="multinomial",
                                family=MULTINOMIAL)

    # ------------------------------------------------------------------
    def _score_matrix(self, X):
        st = self._state
        ones = jnp.ones((X.shape[0], 1), X.dtype)
        Xi = jnp.concatenate([jnp.where(jnp.isnan(X), 0.0, X), ones], axis=1)
        if st.family == MULTINOMIAL:
            B = jnp.asarray(st.beta, jnp.float32)
            return jax.jit(lambda Xi: jax.nn.softmax(Xi @ B.T, axis=1))(Xi)
        b = jnp.asarray(st.beta, jnp.float32)
        eta = jax.jit(lambda Xi: Xi @ b)(Xi)
        mu = _linkinv(st.link, eta,
                      self.params.get("tweedie_link_power") or 1.0)
        if st.family in (BINOMIAL, QUASIBINOMIAL):
            return jnp.stack([1.0 - mu, mu], axis=1)
        return mu

    # ------------------------------------------------------------------
    def _build_output(self, frame):
        di = self._dinfo
        st = self._state
        names = di.feature_names + ["Intercept"]
        if st.family == MULTINOMIAL:
            coefs = {n: st.beta[:, j].tolist() for j, n in enumerate(names)}
        else:
            coefs = dict(zip(names, st.beta.tolist()))
        self._coefficients_std = coefs
        # de-standardize for user-facing coefficients (H2O reports both)
        if di.standardize and st.family != MULTINOMIAL:
            raw = {}
            icept = st.beta[-1]
            ncat = sum(di.cardinalities.get(c, 0) for c in di.cat_cols)
            for j, n in enumerate(di.feature_names):
                b = st.beta[j]
                if j >= ncat:  # numeric, was standardized
                    cname = di.num_cols[j - ncat]
                    s = max(di.sigmas[cname], 1e-10)
                    raw[n] = b / s
                    icept -= b * di.means[cname] / s
                else:
                    raw[n] = b
            raw["Intercept"] = icept
            self._coefficients = raw
        else:
            self._coefficients = coefs
        self._output.model_summary = {
            "family": st.family, "link": st.link,
            "number_of_predictors_total": len(names) - 1,
            "number_of_active_predictors": int(sum(
                1 for v in (st.beta.flatten() if st.family == MULTINOMIAL
                            else st.beta[:-1]) if abs(v) > 1e-10)),
        }
        if self.params.get("compute_p_values") and st.family != MULTINOMIAL:
            self._compute_p_values()

    def _compute_p_values(self):
        """z-scores/p-values from the inverse Fisher information (GLM.java
        computePValues) — valid for lambda=0 IRLS."""
        try:
            from scipy import stats as sps  # optional
            have_scipy = True
        except ImportError:
            have_scipy = False
        G = self._Gram
        try:
            cov = np.linalg.inv(G + 1e-10 * np.eye(len(G)))
        except np.linalg.LinAlgError:
            return
        se = np.sqrt(np.clip(np.diag(cov), 0, None))
        z = self._state.beta / np.where(se > 0, se, np.inf)
        self._std_errors = se
        self._z_values = z
        if have_scipy:
            self._p_values = 2 * (1 - sps.norm.cdf(np.abs(z)))
        else:
            self._p_values = 2 * (1 - 0.5 * (1 + np.vectorize(math.erf)(np.abs(z) / math.sqrt(2))))

    # ---- public accessors (h2o-py parity) --------------------------------
    def coef(self) -> dict:
        return dict(self._coefficients)

    def coef_norm(self) -> dict:
        return dict(self._coefficients_std)
