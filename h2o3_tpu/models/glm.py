"""GLM — hex/glm/GLM.java rebuilt: IRLS where the Gram is one MXU matmul.

Reference: hex/glm/GLM.java (3775 LoC; fitIRLSM :1733, ADMM :1184, COD :1870,
multinomial COD :1228, lambda search), hex/glm/GLMTask.java (GLMIterationTask
:1502 — ONE distributed pass building the weighted Gram XᵀWX and XᵀWz),
hex/gram/Gram.java (hand-parallelized in-core Cholesky :473),
hex/optimization/ADMM.java, L_BFGS.java.

TPU-native design:
  * GLMIterationTask becomes a single jit: Xw = X·w; G = XᵀXw; q = Xᵀ(wz) —
    blocked dot_generals on the MXU, cross-shard psum by XLA (replacing the
    MRTask reduce + hand-written Gram accumulation).
  * Gram.cholesky becomes jnp.linalg solve on the controller-visible (p×p)
    Gram — p is small; no distributed Cholesky needed.
  * L1/elastic-net is solved by cyclic coordinate descent ON THE GRAM
    (the reference's COD solver, GLM.java:1870): O(p²) per sweep on host,
    no extra device passes.
  * Multinomial follows the reference's per-class block-coordinate IRLS
    (GLM.java:1228): per class, softmax working weights/response, one Gram
    pass per class per sweep.
  * Lambda search warm-starts down a geometric path from λ_max, like
    GLM's lambda search.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.models import metrics as M
from h2o3_tpu.models.model import ModelBase
from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.parallel import compat as _compat
from h2o3_tpu.obs.timeline import span as _span

_IRLSM_ITERS = _om.counter("h2o3_glm_irlsm_iterations_total",
                           "IRLSM iterations across all GLM fits")

# ---------------------------------------------------------------------------
# Families / links (hex/glm/GLMModel.GLMParameters.Family)
GAUSSIAN, BINOMIAL, QUASIBINOMIAL, POISSON, GAMMA, TWEEDIE, NEGBINOMIAL, \
    MULTINOMIAL, ORDINAL = ("gaussian", "binomial", "quasibinomial", "poisson",
                            "gamma", "tweedie", "negativebinomial",
                            "multinomial", "ordinal")

_CANONICAL_LINK = {GAUSSIAN: "identity", BINOMIAL: "logit",
                   QUASIBINOMIAL: "logit", POISSON: "log", GAMMA: "inverse",
                   TWEEDIE: "tweedie", NEGBINOMIAL: "log",
                   MULTINOMIAL: "multinomial", ORDINAL: "ologit"}


def _linkinv(link, eta, tweedie_link_power=1.0):
    if link == "identity":
        return eta
    if link == "logit":
        return jax.nn.sigmoid(eta)
    if link == "log":
        return jnp.exp(eta)
    if link == "inverse":
        safe = jnp.where(jnp.abs(eta) < 1e-8, jnp.sign(eta) * 1e-8 + 1e-12, eta)
        return 1.0 / safe
    if link == "tweedie":
        lp = tweedie_link_power
        return jnp.exp(eta) if lp == 0 else jnp.power(jnp.clip(eta, 1e-10), 1.0 / lp)
    raise ValueError(link)


# ---------------------------------------------------------------------------
@_compat.guarded_jit
def _gram_pass(X, w, z):
    """GLMIterationTask: G = XᵀWX, q = XᵀWz in one fused device program."""
    Xw = X * w[:, None]
    G = X.T @ Xw
    q = Xw.T @ z
    return G, q


def _irls_weights(family, link, eta, y, w_obs, tweedie_var_power=1.5,
                  theta=1.0):
    """Working weights and response for one IRLS step (GLMTask computeWeights)."""
    mu = _linkinv(link, eta)
    if family == GAUSSIAN:
        return w_obs, y if link == "identity" else eta + (y - mu)
    if family in (BINOMIAL, QUASIBINOMIAL):
        # f32-safe clip: 1-1e-8 rounds to 1.0 in f32 and zeroes the variance
        mu = jnp.clip(mu, 1e-6, 1 - 1e-6)
        d = jnp.maximum(mu * (1 - mu), 1e-6)
        wi = w_obs * d
        z = eta + (y - mu) / d
        return wi, z
    if family == POISSON:
        mu = jnp.clip(mu, 1e-8)
        wi = w_obs * mu
        return wi, eta + (y - mu) / mu
    if family == GAMMA:  # log link path
        mu = jnp.clip(mu, 1e-8)
        if link == "log":
            return w_obs, eta + (y - mu) / mu
        wi = w_obs * mu * mu
        return wi, eta - (y - mu) / (mu * mu)
    if family == TWEEDIE:
        p = tweedie_var_power
        mu = jnp.clip(mu, 1e-8)
        wi = w_obs * jnp.power(mu, 2.0 - p)
        return wi, eta + (y - mu) / mu
    if family == NEGBINOMIAL:
        mu = jnp.clip(mu, 1e-8)
        wi = w_obs * mu / (1.0 + theta * mu)
        return wi, eta + (y - mu) / mu
    raise ValueError(family)


@_compat.guarded_jit
def _eta_pass(X, beta):
    return X @ beta


def _soft(x, t):
    return math.copysign(max(abs(x) - t, 0.0), x)


def _cod_solve(G, q, lam, alpha, p_pen, beta0, tol=1e-8, max_sweeps=1000,
               lo=None, hi=None):
    """Cyclic coordinate descent on the Gram (GLM.java:1870 COD solver).

    Minimizes ½βᵀGβ − qᵀβ + λα‖β_pen‖₁ + ½λ(1−α)‖β_pen‖² — host-side, p small.
    Column p_pen.. (intercept) unpenalized. With lo/hi given, each
    coordinate update is clipped into its box — projected coordinate
    descent, the beta_constraints solver (GLM.java betaConstraints +
    ADMM.L1Solver bounds; coordinate-wise projection is exact for
    separable boxes).
    """
    p = len(q)
    beta = beta0.copy()
    if lo is not None:
        # a warm start outside the box must not survive (coordinates whose
        # denom<=0 are never updated below and would keep the stale value)
        beta = np.minimum(np.maximum(beta, lo), hi)
    l1 = lam * alpha
    l2 = lam * (1 - alpha)
    for _ in range(max_sweeps):
        delta = 0.0
        for j in range(p):
            gj = q[j] - G[j] @ beta + G[j, j] * beta[j]
            denom = G[j, j] + (l2 if j < p_pen else 0.0)
            if denom <= 0:
                continue
            nb = _soft(gj, l1) / denom if j < p_pen else gj / denom
            if lo is not None:
                nb = min(max(nb, lo[j]), hi[j])
            delta = max(delta, abs(nb - beta[j]))
            beta[j] = nb
        if delta < tol:
            break
    return beta


# ---------------------------------------------------------------------------
# L-BFGS (hex/optimization/L_BFGS.java): limited-memory quasi-Newton on the
# penalized negative log-likelihood. The gradient is ONE device pass over X
# (value_and_grad of a fused jitted NLL); the two-loop recursion runs on the
# controller over (m=10)-deep histories of p-sized vectors. The reference
# uses L-BFGS for wide problems and multinomial (GLM.java:1787 defaults);
# like the reference, only the L2 part of the penalty is handled (alpha's
# L1 requires the COD/IRLS path).
def _lbfgs(value_grad, x0, max_iter=200, m=10, tol=1e-7):
    x = np.asarray(x0, np.float64)
    f, g = value_grad(x)
    hs, hy, rho = [], [], []
    for _ in range(max_iter):
        # two-loop recursion
        qv = g.copy()
        al = []
        for s, yv, r in zip(reversed(hs), reversed(hy), reversed(rho)):
            a = r * s.dot(qv)
            al.append(a)
            qv -= a * yv
        gamma = (hs[-1].dot(hy[-1]) / max(hy[-1].dot(hy[-1]), 1e-12)
                 if hs else 1.0)
        qv *= gamma
        for (s, yv, r), a in zip(zip(hs, hy, rho), reversed(al)):
            b = r * yv.dot(qv)
            qv += (a - b) * s
        d = -qv
        gtd = g.dot(d)
        if gtd > -1e-14:        # not a descent direction: restart steepest
            d = -g
            gtd = -g.dot(g)
        # backtracking Armijo line search
        t = 1.0
        for _ls in range(30):
            fn, gn = value_grad(x + t * d)
            if math.isfinite(fn) and fn <= f + 1e-4 * t * gtd:
                break
            t *= 0.5
        else:
            break
        xn = x + t * d
        s = xn - x
        yv = gn - g
        if abs(f - fn) < tol * max(1.0, abs(f)):
            x, f, g = xn, fn, gn
            break
        sy = s.dot(yv)
        if sy > 1e-10:
            hs.append(s)
            hy.append(yv)
            rho.append(1.0 / sy)
            if len(hs) > m:
                hs.pop(0)
                hy.pop(0)
                rho.pop(0)
        x, f, g = xn, fn, gn
        if np.max(np.abs(g)) < tol:
            break
    return x, f


def _nll_value_grad(fam, Xi, y, w, *, K=1, l2=0.0, p_pen=0,
                    theta=1.0):
    """Jitted penalized NLL value+grad over flat params (one device pass).
    Multinomial params are (K*p1,); others (p1,). Likelihoods are the
    canonical/log-link forms — _resolve_solver only routes those (fam,
    link) pairs here; every other link stays on IRLS."""
    p1 = Xi.shape[1]
    yi = y.astype(jnp.int32)

    @jax.jit
    def vg(flat):
        flat = flat.astype(jnp.float32)
        if fam == MULTINOMIAL:
            B = flat.reshape(K, p1)
            logits = Xi @ B.T
            lse = jax.nn.logsumexp(logits, axis=1)
            py = jnp.take_along_axis(logits, yi[:, None], 1)[:, 0]
            nll = (w * (lse - py)).sum()
            pen = 0.5 * l2 * (B[:, :p_pen] ** 2).sum()
        else:
            eta = Xi @ flat
            if fam in (BINOMIAL, QUASIBINOMIAL):
                nll = (w * (jax.nn.softplus(eta) - y * eta)).sum()
            elif fam == POISSON:
                nll = (w * (jnp.exp(eta) - y * eta)).sum()
            elif fam == GAMMA:
                mu = jnp.exp(eta)
                nll = (w * (y / jnp.clip(mu, 1e-8) + eta)).sum()
            elif fam == NEGBINOMIAL:
                mu = jnp.exp(eta)
                nll = (w * ((y + 1.0 / theta)
                            * jnp.log1p(theta * mu) - y * eta)).sum()
            else:                       # gaussian / tweedie quad approx
                nll = 0.5 * (w * (y - eta) ** 2).sum()
            pen = 0.5 * l2 * (flat[:p_pen] ** 2).sum()
        return nll + pen

    gv = _compat.guard_collective(jax.jit(jax.value_and_grad(vg)))

    def value_grad(x):
        f, g = gv(jnp.asarray(x, jnp.float32))
        return float(f), np.asarray(g, np.float64)

    return value_grad


def _ordinal_value_grad(Xi, yi_np, w, K, l2=0.0, p_pen=0):
    """Cumulative-logit (proportional odds) NLL: P(y<=k) = sigmoid(t_k - eta)
    with ordered thresholds t_0 < ... < t_{K-2} parameterized as
    t_0, t_0+exp(d_1), ... so ordering holds by construction
    (GLM.java ordinal family — here an exact MLE via L-BFGS, TPU-jitted)."""
    p = Xi.shape[1] - 1                  # ordinal model has NO free
    Xb = Xi[:, :p]                       # intercept: thresholds play t_k
    yi = jnp.asarray(yi_np.astype(np.int32))

    @jax.jit
    def vg(flat):
        flat = flat.astype(jnp.float32)
        beta = flat[:p]
        t0 = flat[p]
        steps = jnp.exp(jnp.clip(flat[p + 1:], -30, 30))
        thr = t0 + jnp.concatenate([jnp.zeros(1), jnp.cumsum(steps)])
        eta = Xb @ beta                                  # (n,)
        cum = jax.nn.sigmoid(thr[None, :] - eta[:, None])   # (n, K-1)
        cum_full = jnp.concatenate(
            [jnp.zeros((cum.shape[0], 1)), cum,
             jnp.ones((cum.shape[0], 1))], axis=1)       # (n, K+1)
        pk = jnp.clip(jnp.diff(cum_full, axis=1), 1e-12, 1.0)
        py = jnp.take_along_axis(pk, yi[:, None], 1)[:, 0]
        nll = -(w * jnp.log(py)).sum()
        return nll + 0.5 * l2 * (beta[:p_pen] ** 2).sum()

    gv = _compat.guard_collective(jax.jit(jax.value_and_grad(vg)))

    def value_grad(x):
        f, g = gv(jnp.asarray(x, jnp.float32))
        return float(f), np.asarray(g, np.float64)

    return value_grad


@dataclass
class _GLMState:
    beta: np.ndarray            # (p+1,) or (K, p+1) for multinomial
    link: str
    family: str


# _GLMState is a pytree (beta is the leaf; link/family are static trace
# structure) so the mesh-sharded serving fast path can pass a fitted
# state as a shared device argument instead of a baked constant.
jax.tree_util.register_pytree_node(
    _GLMState,
    lambda s: ((s.beta,), (s.link, s.family)),
    lambda aux, ch: _GLMState(beta=ch[0], link=aux[0], family=aux[1]))


class H2OGeneralizedLinearEstimator(ModelBase):
    algo = "glm"
    # mesh-sharded serving: coefficients (and the ordinal thresholds)
    # ride as one shared device copy; small enough to replicate (the
    # default rule), shared across every row bucket.
    _serving_param_attrs = ("_state", "_ord_beta", "_ord_thr")
    _defaults = {
        "family": "AUTO", "link": "family_default", "solver": "AUTO",
        "alpha": None, "lambda_": None, "lambda_search": False, "nlambdas": 30,
        "lambda_min_ratio": 1e-4, "max_iterations": 50,
        "beta_epsilon": 1e-4, "objective_epsilon": 1e-6,
        "gradient_epsilon": 1e-6, "intercept": True,
        "tweedie_variance_power": 0.0, "tweedie_link_power": 1.0,
        "theta": 1e-10, "compute_p_values": False, "remove_collinear_columns": False,
        "missing_values_handling": "MeanImputation", "non_negative": False,
        "standardize": True, "prior": -1.0, "max_active_predictors": -1,
        # beta_constraints: list of {names, lower_bounds, upper_bounds}
        # rows or a dict {col: (lo, hi)} (GLM.java betaConstraints)
        "beta_constraints": None,
        # interactions: numeric columns whose pairwise products enter the
        # design (hex/DataInfo interactions; categorical pairs rejected)
        "interactions": None,
        # quadratic_penalty: (p, p) matrix P adding ½·βᵀPβ to the
        # objective Σw·nll(β) — the GAM spline-smoothness channel
        # (hex/gam penalty matrix on the expanded design). Entries are in
        # EXPANDED-FEATURE order (feature_names); the intercept row/col is
        # appended as zeros when P is (p_pen, p_pen).
        "quadratic_penalty": None,
    }

    # ------------------------------------------------------------------
    def _fit(self, frame: Frame, job):
        di = self._dinfo
        fam = self._resolve_family()
        self._family = fam
        link = self.params.get("link") or "family_default"
        if link in ("family_default", None, "AUTO"):
            link = _CANONICAL_LINK[fam]
        self._link = link
        # sparse rows (hex/DataInfo.java:23 _sparse): all-SparseVec
        # predictors never materialize the dense design matrix. The sparse
        # solver is L-BFGS (L2 only, intercept on): L1 / bounds /
        # lambda_search / intercept=False / explicit IRLSM fall back to the
        # dense path, which honors them (and densifies — the user asked
        # for features the sparse solver cannot provide).
        if frame.is_sparse(di.predictors) and fam in (
                GAUSSIAN, BINOMIAL, QUASIBINOMIAL, POISSON) \
                and self._sparse_path_ok():
            self._fit_sparse(frame, job)
            self._build_output(frame)
            return
        X = di.matrix(frame)                       # standardized, imputed
        y = di.response(frame)
        w = di.weights(frame)
        w = jnp.where(jnp.isnan(y), 0.0, w)
        yz = jnp.where(jnp.isnan(y), 0.0, y)
        ones = jnp.ones((X.shape[0], 1), X.dtype)
        Xi = jnp.concatenate([X, ones], axis=1)    # intercept column last
        solver = self._resolve_solver(fam, Xi.shape[1])
        self._solver = solver
        if fam == ORDINAL:
            self._fit_ordinal(Xi, yz, w, job)
        elif solver == "L_BFGS":
            self._fit_lbfgs(Xi, yz, w, job)
        elif fam == MULTINOMIAL:
            self._fit_multinomial(Xi, yz, w, job)
        else:
            self._fit_irls(Xi, yz, w, job)
        self._build_output(frame)

    def _resolve_solver(self, fam, p1) -> str:
        """GLM.java:1787 defaultSolver: IRLSM for narrow problems, L_BFGS
        for wide ones and multinomial with many predictors; explicit
        `solver` wins. L-BFGS carries only the L2 penalty (like the
        reference) — L1 requests stay on the COD/IRLS path."""
        alpha = self.params.get("alpha")
        alpha = 0.5 if alpha is None else (
            alpha[0] if isinstance(alpha, (list, tuple)) else float(alpha))
        lam = self.params.get("lambda_") or 0.0
        if isinstance(lam, (list, tuple)):
            lam = lam[0] or 0.0
        has_l1 = (alpha > 0 and (lam or 0) > 0) or \
            self.params.get("lambda_search")
        constrained = (has_l1
                       or self.params.get("beta_constraints") is not None
                       or self.params.get("non_negative"))
        # the jitted L-BFGS NLLs cover the canonical/log-link likelihoods;
        # other links stay on IRLS (which handles any _irls_weights link)
        lbfgs_link_ok = fam in (MULTINOMIAL,) or (fam, self._link) in {
            (GAUSSIAN, "identity"), (BINOMIAL, "logit"),
            (QUASIBINOMIAL, "logit"), (POISSON, "log"), (GAMMA, "log"),
            (NEGBINOMIAL, "log")}
        s = str(self.params.get("solver") or "AUTO").upper()
        if self.params.get("quadratic_penalty") is not None:
            if s in ("L_BFGS", "LBFGS"):
                raise ValueError(
                    "quadratic_penalty requires the IRLSM solver (the "
                    "L-BFGS NLLs carry only the scalar L2 penalty)")
            if fam in (MULTINOMIAL, ORDINAL):
                raise NotImplementedError(
                    "quadratic_penalty is implemented for the "
                    "single-response IRLS families only; "
                    f"family={fam} would silently drop the penalty")
            if not self.params.get("intercept", True):
                raise NotImplementedError(
                    "quadratic_penalty requires intercept=True (the "
                    "penalty block indexing assumes the appended "
                    "intercept column)")
            return "IRLSM"
        if s in ("L_BFGS", "LBFGS"):
            if constrained:
                raise ValueError(
                    "solver=L_BFGS carries only the L2 penalty: it cannot "
                    "honor L1 (alpha>0 with lambda), beta_constraints or "
                    "non_negative — use IRLSM/COORDINATE_DESCENT "
                    "(GLM.java L_BFGS solver restriction)")
            if fam != ORDINAL and not lbfgs_link_ok:
                raise ValueError(
                    f"solver=L_BFGS does not support family={fam} with "
                    f"link={self._link}; use IRLSM")
            return "L_BFGS"
        if s in ("IRLSM", "COORDINATE_DESCENT", "COORDINATE_DESCENT_NAIVE"):
            return "IRLSM"
        if fam == ORDINAL:
            return "L_BFGS"
        if constrained or not lbfgs_link_ok:
            return "IRLSM"              # L1/bounds need coordinate descent
        K = self.nclasses if fam == MULTINOMIAL else 1
        return "L_BFGS" if p1 * K > 500 else "IRLSM"

    def _beta_bounds(self, p1, p_pen):
        """Resolve beta_constraints into (lo, hi) arrays or (None, None)."""
        bc = self.params.get("beta_constraints")
        nn = self.params.get("non_negative")
        if bc is None and not nn:
            return None, None
        lo = np.full(p1, -np.inf)
        hi = np.full(p1, np.inf)
        names = self._dinfo.feature_names
        if isinstance(bc, Frame):
            rows = {bc.vec("names").to_numpy()[i]: i
                    for i in range(bc.nrows)}
            lob = (bc.vec("lower_bounds").to_numpy()
                   if "lower_bounds" in bc.names else None)
            hib = (bc.vec("upper_bounds").to_numpy()
                   if "upper_bounds" in bc.names else None)
            for nm, i in rows.items():
                if nm in names:
                    j = names.index(nm)
                    if lob is not None and lob[i] == lob[i]:
                        lo[j] = lob[i]
                    if hib is not None and hib[i] == hib[i]:
                        hi[j] = hib[i]
        elif isinstance(bc, dict):
            for nm, (lo_v, hi_v) in bc.items():
                if nm in names:
                    j = names.index(nm)
                    lo[j], hi[j] = lo_v, hi_v
        elif bc is not None:
            for row in bc:              # list of dicts (h2o-py style)
                nm = row.get("names")
                if nm in names:
                    j = names.index(nm)
                    lo[j] = row.get("lower_bounds", -np.inf)
                    hi[j] = row.get("upper_bounds", np.inf)
        if nn:
            # intersect with the non_negative floor (GLM.java combines the
            # two constraint sources; a user lower bound must not loosen it)
            lo[:p_pen] = np.maximum(lo[:p_pen], 0.0)
        return lo, hi

    def _resolve_quadratic_penalty(self, p1, p_pen):
        """Materialize `quadratic_penalty` against THIS fit's expanded
        design. Accepted forms:
          * list of (feature_names, S) blocks — indexed into the model's
            own DataInfo feature order (so interactions/standardization
            cannot desynchronize caller-side assembly; the GAM path);
          * a dense (p_pen, p_pen) or (p1, p1) matrix in expanded-feature
            order (intercept block appended as zeros when absent).
        Standardized designs rescale named blocks by 1/σᵢσⱼ
        (β_std = σ·β_raw ⇒ P_std = diag(1/σ)·P·diag(1/σ))."""
        P = self.params.get("quadratic_penalty")
        if P is None:
            return None
        if isinstance(P, (list, tuple)):
            feats = self._dinfo.feature_names
            full = np.zeros((p1, p1))
            for names, S in P:
                idx = np.asarray([feats.index(nm) for nm in names])
                S = np.asarray(S, np.float64)
                if self._dinfo.standardize:
                    sig = np.asarray(
                        [max(self._dinfo.sigmas.get(nm, 1.0), 1e-10)
                         for nm in names])
                    S = S / np.outer(sig, sig)
                full[np.ix_(idx, idx)] += S
            return full
        P = np.asarray(P, np.float64)
        if P.shape == (p_pen, p_pen):           # append zero intercept block
            Pf = np.zeros((p1, p1))
            Pf[:p_pen, :p_pen] = P
            P = Pf
        if P.shape != (p1, p1):
            raise ValueError(
                f"quadratic_penalty shape {P.shape} does not match the "
                f"expanded design ({p1} columns incl. intercept); pass "
                "(feature_names, S) blocks to let the model index them")
        return P

    def _sparse_path_ok(self) -> bool:
        if self.params.get("interactions"):
            return False        # interaction columns need the dense design
        if self.params.get("quadratic_penalty") is not None:
            return False        # P folds into the dense IRLS Gram only
        # the sparse NLLs are the canonical-link likelihoods only
        if (self._family, self._link) not in {
                (GAUSSIAN, "identity"), (BINOMIAL, "logit"),
                (QUASIBINOMIAL, "logit"), (POISSON, "log")}:
            return False
        alpha = self.params.get("alpha")
        alpha = 0.5 if alpha is None else (
            alpha[0] if isinstance(alpha, (list, tuple)) else float(alpha))
        lam = self.params.get("lambda_") or 0.0
        if isinstance(lam, (list, tuple)):
            lam = lam[0] or 0.0
        has_l1 = alpha > 0 and (lam or 0) > 0
        s = str(self.params.get("solver") or "AUTO").upper()
        return not (has_l1
                    or self.params.get("lambda_search")
                    or self.params.get("beta_constraints") is not None
                    or self.params.get("non_negative")
                    or not self.params.get("intercept", True)
                    or s in ("IRLSM", "COORDINATE_DESCENT",
                             "COORDINATE_DESCENT_NAIVE"))

    # ------------------------------------------------------------------
    def _fit_sparse(self, frame, job):
        """Sparse-rows GLM (DataInfo sparse + GLMTask sparse iterators):
        L-BFGS on the COO representation — eta and the gradient are
        segment-sum passes over the nonzeros; neither the dense X nor the
        Gram is ever materialized (a 1M x 10k 0.1%-dense design stays
        nnz-sized). Standardization is skipped like the reference's
        sparse mode (mean-centering would densify)."""
        di = self._dinfo
        fam, link = self._family, self._link
        # the sparse fit is in RAW feature space — dense scoring through
        # di.matrix must not standardize or every prediction is computed
        # against coordinates the coefficients never saw
        di.standardize = False
        ri, ci, vals, (n, C) = frame.sparse_coo(di.predictors)
        # NA -> 0: sparse-mode zero imputation (consistent with the
        # implicit zeros; mean imputation would break sparsity)
        vals = jnp.where(jnp.isnan(vals), 0.0, vals)
        y_full = di.response(frame)
        w_full = di.weights(frame)
        y = y_full[:n]
        w = jnp.where(jnp.isnan(y), 0.0, w_full[:n])
        y = jnp.where(jnp.isnan(y), 0.0, y)
        wn = float(np.asarray(jnp.sum(w)))
        lam = self.params.get("lambda_") or 0.0
        if isinstance(lam, (list, tuple)):
            lam = lam[0] or 0.0
        alpha = self.params.get("alpha")
        alpha = 0.5 if alpha is None else (
            alpha[0] if isinstance(alpha, (list, tuple)) else float(alpha))
        l2 = float(lam) * (1 - alpha) * wn

        @_compat.guarded_jit
        def nll(flat):
            flat = flat.astype(jnp.float32)
            beta, b0 = flat[:C], flat[C]
            contrib = vals * beta[ci]
            eta = jax.ops.segment_sum(contrib, ri, num_segments=n) + b0
            if fam in (BINOMIAL, QUASIBINOMIAL):
                ll = (w * (jax.nn.softplus(eta) - y * eta)).sum()
            elif fam == POISSON:
                ll = (w * (jnp.exp(eta) - y * eta)).sum()
            else:
                ll = 0.5 * (w * (y - eta) ** 2).sum()
            return ll + 0.5 * l2 * (beta ** 2).sum()

        gv = _compat.guard_collective(jax.jit(jax.value_and_grad(nll)))

        def value_grad(x):
            f, g = gv(jnp.asarray(x, jnp.float32))
            return float(f), np.asarray(g, np.float64)

        x0 = np.zeros(C + 1)
        ybar = float(np.asarray(jnp.sum(w * y))) / max(wn, 1e-12)
        if fam in (BINOMIAL, QUASIBINOMIAL):
            yb = min(max(ybar, 1e-6), 1 - 1e-6)
            x0[-1] = math.log(yb / (1 - yb))
        elif fam == POISSON:
            x0[-1] = math.log(max(ybar, 1e-8))
        else:
            x0[-1] = ybar
        x, f = _lbfgs(value_grad, x0,
                      max_iter=int(self.params["max_iterations"]) * 4)
        self._state = _GLMState(beta=x, link=link, family=fam)
        self._solver = "L_BFGS"
        self._sparse_fit = True
        job.update(0.7, "sparse L-BFGS converged")

    def _compute_metrics(self, frame):
        # sparse fits score sparsely too — metrics must not densify either
        if getattr(self, "_sparse_fit", False) \
                and frame.is_sparse(self._dinfo.predictors):
            di = self._dinfo
            n = frame.nrows
            mu = jnp.asarray(self.predict_sparse(frame))
            y = di.response(frame)[:n]
            w = di.weights(frame)[:n]
            w = jnp.where(jnp.isnan(y), 0.0, w)
            y = jnp.where(jnp.isnan(y), 0.0, y)
            out = (jnp.stack([1.0 - mu, mu], axis=1)
                   if self._is_classifier else mu)
            return self._metrics_from_preds(y, out, w)
        return super()._compute_metrics(frame)

    def predict_sparse(self, frame) -> np.ndarray:
        """Score a sparse frame without densifying: mu per row."""
        st = self._state
        di = self._dinfo
        ri, ci, vals, (n, C) = frame.sparse_coo(di.predictors)
        vals = jnp.where(jnp.isnan(vals), 0.0, vals)
        beta = jnp.asarray(st.beta[:C], jnp.float32)

        @_compat.guarded_jit
        def sc(vals):
            eta = jax.ops.segment_sum(vals * beta[ci], ri,
                                      num_segments=n) + float(st.beta[C])
            return _linkinv(st.link, eta)

        return np.asarray(sc(vals))

    # ------------------------------------------------------------------
    def _fit_lbfgs(self, Xi, y, w, job):
        """hex/optimization/L_BFGS.java path: exact penalized MLE by
        limited-memory quasi-Newton; gradients are one fused device pass."""
        fam, link = self._family, self._link
        p1 = Xi.shape[1]
        p_pen = p1 - 1 if self.params.get("intercept", True) else p1
        wn = np.asarray(w, np.float64)
        lam = self.params.get("lambda_") or 0.0
        if isinstance(lam, (list, tuple)):
            lam = lam[0] or 0.0
        alpha = self.params.get("alpha")
        alpha = 0.5 if alpha is None else (
            alpha[0] if isinstance(alpha, (list, tuple)) else float(alpha))
        l2 = float(lam) * (1 - alpha) * wn.sum()
        max_it = int(self.params["max_iterations"]) * 4
        if fam == MULTINOMIAL:
            K = self.nclasses
            vg = _nll_value_grad(fam, Xi, y, w, K=K, l2=l2,
                                 p_pen=p_pen)
            x0 = np.zeros(K * p1)
            yi = np.asarray(y, np.float64).astype(int)
            for c in range(K):
                pc = (wn * (yi == c)).sum() / max(wn.sum(), 1e-12)
                x0[c * p1 + p1 - 1] = math.log(max(pc, 1e-6))
            x, f = _lbfgs(vg, x0, max_iter=max_it)
            beta = x.reshape(K, p1)
            self._state = _GLMState(beta=beta, link="multinomial",
                                    family=MULTINOMIAL)
        else:
            vg = _nll_value_grad(fam, Xi, y, w, l2=l2, p_pen=p_pen,
                                 theta=float(self.params["theta"] or 1.0))
            x0 = np.zeros(p1)
            ybar = float((wn * np.asarray(y, np.float64)).sum()
                         / max(wn.sum(), 1e-12))
            if fam in (BINOMIAL, QUASIBINOMIAL):
                yb = min(max(ybar, 1e-6), 1 - 1e-6)
                x0[-1] = math.log(yb / (1 - yb))
            elif link == "log":
                x0[-1] = math.log(max(ybar, 1e-8))
            else:
                x0[-1] = ybar
            x, f = _lbfgs(vg, x0, max_iter=max_it)
            self._state = _GLMState(beta=x, link=link, family=fam)
            # Fisher information at the optimum for p-values
            eta = _eta_pass(Xi, jnp.asarray(x, jnp.float32))
            wi, _ = _irls_weights(fam, link, eta, y, w,
                                  self.params["tweedie_variance_power"]
                                  or 1.5, self.params["theta"])
            G, _ = _gram_pass(Xi, wi, jnp.zeros_like(eta))
            self._Gram = np.asarray(G, np.float64)
            self._wsum = float(wn.sum())
        job.update(0.7, "L-BFGS converged")

    # ------------------------------------------------------------------
    def _fit_ordinal(self, Xi, y, w, job):
        """Proportional-odds cumulative-logit model (ordinal family)."""
        K = self.nclasses
        assert K >= 2, "ordinal family needs an ordered factor response"
        p1 = Xi.shape[1]
        p = p1 - 1
        wn = np.asarray(w, np.float64)
        yi = np.asarray(y, np.float64).astype(int)
        lam = self.params.get("lambda_") or 0.0
        if isinstance(lam, (list, tuple)):
            lam = lam[0] or 0.0
        l2 = float(lam) * wn.sum()
        vg = _ordinal_value_grad(Xi, yi, w, K, l2=l2, p_pen=p)
        # init: thresholds at the empirical cumulative logits
        x0 = np.zeros(p + K - 1)
        cum = 0.0
        prev_t = None
        for k in range(K - 1):
            cum += (wn * (yi == k)).sum() / max(wn.sum(), 1e-12)
            cumc = min(max(cum, 1e-6), 1 - 1e-6)
            tk = math.log(cumc / (1 - cumc))
            if k == 0:
                x0[p] = tk
            else:
                x0[p + k] = math.log(max(tk - prev_t, 1e-3))
            prev_t = tk
        x, f = _lbfgs(vg, x0, max_iter=int(self.params["max_iterations"]) * 4)
        self._ord_beta = x[:p]
        t0 = x[p]
        self._ord_thr = t0 + np.concatenate(
            [[0.0], np.cumsum(np.exp(x[p + 1:]))])
        # store beta in the common shape (intercept slot carries t_0)
        beta = np.concatenate([x[:p], [t0]])
        self._state = _GLMState(beta=beta, link="ologit", family=ORDINAL)
        job.update(0.7, "ordinal converged")

    def _resolve_family(self) -> str:
        fam = self.params.get("family", "AUTO")
        if fam and str(fam).lower() in ("hglm", "fractionalbinomial"):
            raise NotImplementedError(
                f"family={fam} is not implemented (no silent fallback); "
                "supported: gaussian/binomial/quasibinomial/poisson/gamma/"
                "tweedie/negativebinomial/multinomial/ordinal")
        if fam and fam != "AUTO":
            return fam
        if self._dinfo.response_domain is None:
            return GAUSSIAN
        return BINOMIAL if len(self._dinfo.response_domain) == 2 else MULTINOMIAL

    def _alpha_lambda(self, G, q, p_pen):
        alpha = self.params.get("alpha")
        alpha = 0.5 if alpha is None else (alpha[0] if isinstance(alpha, (list, tuple)) else float(alpha))
        lam = self.params.get("lambda_")
        if isinstance(lam, (list, tuple)):
            lam = lam[0]
        if self.params.get("lambda_search"):
            lam_max = np.abs(q[:p_pen]).max() / max(alpha, 1e-3)
            lams = np.geomspace(lam_max,
                                lam_max * self.params["lambda_min_ratio"],
                                int(self.params["nlambdas"]))
            return alpha, list(lams)
        if lam is None:
            lam = 0.0 if not self.params.get("lambda_search") else None
        return alpha, [float(lam)]

    # ------------------------------------------------------------------
    def _fit_irls(self, Xi, y, w, job):
        fam, link = self._family, self._link
        p1 = Xi.shape[1]
        p_pen = p1 - 1 if self.params.get("intercept", True) else p1
        beta = np.zeros(p1, np.float64)
        # sensible intercept start
        wn = np.asarray(w, np.float64)
        yn = np.asarray(y, np.float64)
        ybar = float((wn * yn).sum() / max(wn.sum(), 1e-12))
        if fam in (BINOMIAL, QUASIBINOMIAL):
            yb = min(max(ybar, 1e-6), 1 - 1e-6)
            beta[-1] = math.log(yb / (1 - yb))
        elif fam in (POISSON, GAMMA, TWEEDIE, NEGBINOMIAL):
            beta[-1] = math.log(max(ybar, 1e-8)) if link == "log" else (
                1.0 / max(ybar, 1e-8) if link == "inverse" else ybar)
        else:
            beta[-1] = ybar
        # first pass for lambda_max needs the null-model gram
        eta = _eta_pass(Xi, jnp.asarray(beta, jnp.float32))
        wi, z = _irls_weights(fam, link, eta, y, w,
                              self.params["tweedie_variance_power"] or 1.5,
                              self.params["theta"])
        G, q = _gram_pass(Xi, wi, z)
        Gn, qn = np.asarray(G, np.float64), np.asarray(q, np.float64)
        alpha, lams = self._alpha_lambda(Gn, qn - Gn @ beta, p_pen)
        lo, hi = self._beta_bounds(p1, p_pen)
        P = self._resolve_quadratic_penalty(p1, p_pen)
        max_it = int(self.params["max_iterations"])
        beps = float(self.params["beta_epsilon"])
        path = []
        for lam in lams:
            for it in range(max(1, max_it)):
                # h2o3-ok: R011 same IRLSM phase as the multinomial sweep below — family= attr disambiguates
                with _span("glm.irlsm", iter=it, lam=float(lam),
                           family=fam):
                    _IRLSM_ITERS.inc()
                    eta = _eta_pass(Xi, jnp.asarray(beta, jnp.float32))
                    wi, z = _irls_weights(
                        fam, link, eta, y, w,
                        self.params["tweedie_variance_power"] or 1.5,
                        self.params["theta"])
                    G, q = _gram_pass(Xi, wi, z)
                    Gn = np.asarray(G, np.float64)
                    qn = np.asarray(q, np.float64)
                    # quadratic (spline-smoothness) penalty: ∇½βᵀPβ = Pβ
                    # folds into the Gram exactly, for both solvers
                    Gs = Gn if P is None else Gn + P
                    if (alpha > 0 and lam > 0) or lo is not None:
                        # objective is (1/N)·deviance + λ·pen ⇒ scale λ by
                        # Σw; bounds force the projected-COD solver too
                        nb = _cod_solve(Gs, qn, lam * wn.sum(), alpha,
                                        p_pen, beta, lo=lo, hi=hi)
                    else:
                        A = Gs + lam * wn.sum() * (1 - alpha) * np.eye(p1)
                        if p_pen < p1:
                            A[p1 - 1, p1 - 1] = Gs[p1 - 1, p1 - 1]
                        nb = np.linalg.solve(A + 1e-10 * np.eye(p1), qn)
                    dmax = float(np.max(np.abs(nb - beta)))
                    beta = nb
                if fam == GAUSSIAN and link == "identity":
                    break
                if dmax < beps:
                    break
            path.append((lam, beta.copy()))
            job.update(0.6, f"lambda {lam:.4g}")
        self._lambda_path = path
        self._state = _GLMState(beta=beta, link=link, family=fam)
        self._Gram = Gn
        self._wsum = float(wn.sum())

    # ------------------------------------------------------------------
    def _fit_multinomial(self, Xi, y, w, job):
        """Block-coordinate per-class IRLS (GLM.java:1228)."""
        K = self.nclasses
        p1 = Xi.shape[1]
        p_pen = p1 - 1
        beta = np.zeros((K, p1), np.float64)
        wn = np.asarray(w, np.float64)
        # class priors → intercept init
        yi = np.asarray(y, np.float64).astype(int)
        for c in range(K):
            pc = (wn * (yi == c)).sum() / max(wn.sum(), 1e-12)
            beta[c, -1] = math.log(max(pc, 1e-6))
        alpha = self.params.get("alpha")
        alpha = 0.5 if alpha is None else (alpha[0] if isinstance(alpha, (list, tuple)) else float(alpha))
        lam = self.params.get("lambda_") or 0.0
        if isinstance(lam, (list, tuple)):
            lam = lam[0]
        max_it = int(self.params["max_iterations"])
        beps = float(self.params["beta_epsilon"])

        @_compat.guarded_jit
        def probs_fn(B):
            return jax.nn.softmax(Xi @ B.T, axis=1)

        @_compat.guarded_jit
        def class_gram(B, c, yk):
            P = jax.nn.softmax(Xi @ B.T, axis=1)
            pc = jnp.clip(P[:, c], 1e-6, 1 - 1e-6)   # f32-safe
            d = jnp.maximum(pc * (1 - pc), 1e-6)
            wi = w * d
            eta_c = Xi @ B[c]
            z = eta_c + (yk - pc) / d
            Xw = Xi * wi[:, None]
            return Xi.T @ Xw, Xw.T @ z

        @_compat.guarded_jit
        def obj_fn(B):
            P = jax.nn.softmax(Xi @ B.T, axis=1)
            py = jnp.take_along_axis(P, jnp.asarray(yi)[:, None], 1)[:, 0]
            return -(w * jnp.log(jnp.clip(py, 1e-12, 1.0))).sum()

        prev_obj = float(obj_fn(jnp.asarray(beta, jnp.float32)))
        for sweep in range(max_it):
            dmax = 0.0
            last_good = beta.copy()
            with _span("glm.irlsm", iter=sweep, family=MULTINOMIAL):
                _IRLSM_ITERS.inc()
                for c in range(K):
                    yk = jnp.asarray((yi == c).astype(np.float32))
                    G, q = class_gram(jnp.asarray(beta, jnp.float32),
                                      c, yk)
                    Gn, qn = (np.asarray(G, np.float64),
                              np.asarray(q, np.float64))
                    if alpha > 0 and lam > 0:
                        nb = _cod_solve(Gn, qn, lam * wn.sum(), alpha,
                                        p_pen, beta[c].copy())
                    else:
                        A = Gn + lam * wn.sum() * (1 - alpha) * np.eye(p1)
                        A[p1 - 1, p1 - 1] = Gn[p1 - 1, p1 - 1]
                        nb = np.linalg.solve(A + 1e-8 * np.eye(p1), qn)
                    dmax = max(dmax, float(np.max(np.abs(nb - beta[c]))))
                    beta[c] = nb
            job.update(0.6, f"multinomial sweep {sweep}")
            obj = float(obj_fn(jnp.asarray(beta, jnp.float32)))
            if not math.isfinite(obj) or obj > prev_obj + 1e-6 * abs(prev_obj):
                beta = last_good    # separable-data divergence guard
                break
            prev_obj = obj
            if dmax < beps:
                break
        self._state = _GLMState(beta=beta, link="multinomial",
                                family=MULTINOMIAL)

    # ------------------------------------------------------------------
    def _score_matrix(self, X):
        st = self._state
        ones = jnp.ones((X.shape[0], 1), X.dtype)
        Xi = jnp.concatenate([jnp.where(jnp.isnan(X), 0.0, X), ones], axis=1)
        if st.family == ORDINAL:
            b = jnp.asarray(self._ord_beta, jnp.float32)
            thr = jnp.asarray(self._ord_thr, jnp.float32)
            eta = Xi[:, :-1] @ b
            cum = jax.nn.sigmoid(thr[None, :] - eta[:, None])
            cum_full = jnp.concatenate(
                [jnp.zeros((cum.shape[0], 1)), cum,
                 jnp.ones((cum.shape[0], 1))], axis=1)
            return jnp.clip(jnp.diff(cum_full, axis=1), 0.0, 1.0)
        if st.family == MULTINOMIAL:
            # plain jnp: a fresh jit(lambda) here had a new function
            # identity per call and recompiled on EVERY predict; the
            # serving fast path traces this whole method into one cached
            # program anyway
            B = jnp.asarray(st.beta, jnp.float32)
            return jax.nn.softmax(Xi @ B.T, axis=1)
        b = jnp.asarray(st.beta, jnp.float32)
        eta = Xi @ b
        mu = _linkinv(st.link, eta,
                      self.params.get("tweedie_link_power") or 1.0)
        if st.family in (BINOMIAL, QUASIBINOMIAL) and self._is_classifier:
            return jnp.stack([1.0 - mu, mu], axis=1)
        # numeric 0/1 response (quasibinomial style): one probability column
        return mu

    # ------------------------------------------------------------------
    def _build_output(self, frame):
        di = self._dinfo
        st = self._state
        names = di.feature_names + ["Intercept"]
        if st.family == MULTINOMIAL:
            coefs = {n: st.beta[:, j].tolist() for j, n in enumerate(names)}
        else:
            coefs = dict(zip(names, st.beta.tolist()))
        self._coefficients_std = coefs
        # de-standardize for user-facing coefficients (H2O reports both);
        # ordinal keeps standardized coefs (its "Intercept" is threshold t0
        # whose de-standardization has the opposite sign convention)
        if di.standardize and st.family not in (MULTINOMIAL, ORDINAL) \
                and not getattr(self, "_sparse_fit", False):
            raw = {}
            icept = st.beta[-1]
            for j, n in enumerate(di.feature_names):
                b = st.beta[j]
                if n in di.means:      # numeric (incl. interaction cols):
                    s = max(di.sigmas[n], 1e-10)    # was standardized
                    raw[n] = b / s
                    icept -= b * di.means[n] / s
                else:
                    raw[n] = b
            raw["Intercept"] = icept
            self._coefficients = raw
        else:
            self._coefficients = coefs
        # variable importances = |standardized coefficient| magnitudes
        # (hex/glm GLMModel.GLMOutput getVariableImportances: abs of the
        # standardized betas, multinomial takes the per-class max)
        mags = {}
        for j, n in enumerate(di.feature_names):
            b = st.beta[:, j] if st.family == MULTINOMIAL else st.beta[j]
            mags[n] = float(np.max(np.abs(b)))
        order = sorted(mags, key=mags.get, reverse=True)
        top = mags[order[0]] if order else 0.0
        tot = sum(mags.values()) or 1.0
        self._output.variable_importances = [
            {"variable": n, "relative_importance": mags[n],
             "scaled_importance": mags[n] / (top or 1.0),
             "percentage": mags[n] / tot}
            for n in order]
        self._output.model_summary = {
            "family": st.family, "link": st.link,
            "number_of_predictors_total": len(names) - 1,
            "number_of_active_predictors": int(sum(
                1 for v in (st.beta.flatten() if st.family == MULTINOMIAL
                            else st.beta[:-1]) if abs(v) > 1e-10)),
        }
        if self.params.get("compute_p_values") \
                and st.family not in (MULTINOMIAL, ORDINAL) \
                and getattr(self, "_Gram", None) is not None:
            self._compute_p_values()

    def _compute_p_values(self):
        """z-scores/p-values from the inverse Fisher information (GLM.java
        computePValues) — valid for lambda=0 IRLS."""
        try:
            from scipy import stats as sps  # optional
            have_scipy = True
        except ImportError:
            have_scipy = False
        G = self._Gram
        try:
            cov = np.linalg.inv(G + 1e-10 * np.eye(len(G)))
        except np.linalg.LinAlgError:
            return
        se = np.sqrt(np.clip(np.diag(cov), 0, None))
        z = self._state.beta / np.where(se > 0, se, np.inf)
        self._std_errors = se
        self._z_values = z
        if have_scipy:
            self._p_values = 2 * (1 - sps.norm.cdf(np.abs(z)))
        else:
            self._p_values = 2 * (1 - 0.5 * (1 + np.vectorize(math.erf)(np.abs(z) / math.sqrt(2))))

    # ---- public accessors (h2o-py parity) --------------------------------
    def coef(self) -> dict:
        return dict(self._coefficients)

    def coef_norm(self) -> dict:
        return dict(self._coefficients_std)
