"""Distributed quantiles — hex/quantile/Quantile.java rebuilt TPU-native.

Reference: Quantile.java (~700 LoC): an MRTask histogram pass over chunks,
then iterative range refinement until the target rank's bin is exact, with
combine_method interpolation (Type-7-style) and observation weights; used by
`h2o.quantile`, summary, and GBM's quantile-based binning
(hex/tree/GlobalQuantilesCalc.java).

TPU-native design: NO data-dependent iteration count — a FIXED number of
histogram-refinement rounds (4 × 256 bins resolves the range to ~2^-32,
below float32 ulp) inside ONE jitted program; every round's bin-count is a
segment-sum over the row-sharded values whose cross-shard reduction is an
ICI psum; all requested probabilities (and both bracketing order-statistic
ranks of each) refine in parallel via vmap. The final value is the observed
in-bin minimum (segment_min), i.e. an exact order statistic, and Type-7
interpolation combines the two ranks.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from h2o3_tpu.parallel import compat as _compat

_B = 256          # bins per refinement round
_ITERS = 4        # 256^4 = 2^32 range resolution


@_compat.guard_collective


@functools.partial(jax.jit, static_argnames=("iters",))
def _order_stats(x, w, ks, *, iters=_ITERS):
    """k-th smallest (0-based, by cumulative weight) for each k in ks.

    x: (n,) f32 with NaN for NA/padding (excluded via w=0)
    w: (n,) f32 weights (0 = excluded)
    ks: (P,) f32 target cumulative-weight ranks
    """
    valid = (w > 0) & ~jnp.isnan(x)
    wv = jnp.where(valid, w, 0.0)
    big = jnp.float32(3.0e38)
    xs = jnp.where(valid, x, big)
    lo0 = jnp.min(jnp.where(valid, x, big))
    hi0 = jnp.max(jnp.where(valid, x, -big))

    def one_rank(k):
        def round_(c, _):
            lo, hi, below = c
            span = jnp.maximum(hi - lo, 1e-37)
            b = jnp.floor((xs - lo) / span * _B).astype(jnp.int32)
            b = jnp.clip(b, 0, _B - 1)
            inr = valid & (xs >= lo) & (xs <= hi)
            bi = jnp.where(inr, b, _B)
            counts = jax.ops.segment_sum(jnp.where(inr, wv, 0.0), bi,
                                         num_segments=_B + 1)[:_B]
            mins = jax.ops.segment_min(jnp.where(inr, xs, big), bi,
                                       num_segments=_B + 1)[:_B]
            cum = below + jnp.cumsum(counts)
            # first bin whose cumulative weight exceeds k
            hit = (cum > k) & (counts > 0)
            idx = jnp.argmax(hit)
            nlo = lo + span * idx / _B
            nhi = lo + span * (idx + 1) / _B
            nbelow = jnp.where(idx > 0, cum[idx - 1], below)
            # once the bin holds a single observed value we are exact:
            # keep the observed min as the candidate
            cand = mins[idx]
            return (jnp.maximum(nlo, lo), jnp.minimum(nhi, hi), nbelow), cand

        (_, _, _), cands = jax.lax.scan(round_, (lo0, hi0, 0.0),
                                        None, length=iters)
        return cands[-1]

    return jax.vmap(one_rank)(ks)


def quantile(values, probs, weights=None, combine_method="interpolate"):
    """Weighted distributed quantiles of a device vector.

    Type-7 interpolation on cumulative-weight ranks h = p·(W−1); with unit
    weights this matches numpy's default. combine_method: "interpolate",
    "low", "high", "average" (Quantile.java's combine modes).
    """
    x = jnp.asarray(values, jnp.float32)
    w = (jnp.ones_like(x) if weights is None
         else jnp.asarray(weights, jnp.float32))
    w = jnp.where(jnp.isnan(x), 0.0, w)
    W = float(np.asarray(jnp.sum(w)))
    if W <= 0:
        return np.full(len(probs), np.nan)
    probs = np.asarray(probs, np.float64)
    if np.any((probs < 0) | (probs > 1)):
        raise ValueError(f"probabilities must be in [0, 1], got {probs}")
    h = probs * (W - 1.0)
    klo = np.floor(h)
    khi = np.ceil(h)
    ks = jnp.asarray(np.concatenate([klo, khi]), jnp.float32)
    vals = np.asarray(_order_stats(x, w, ks), np.float64)
    vlo, vhi = vals[: len(probs)], vals[len(probs):]
    if combine_method in ("interpolate", "interpolated", None, "AUTO"):
        g = h - klo
        return vlo + g * (vhi - vlo)
    if combine_method == "low":
        return vlo
    if combine_method == "high":
        return vhi
    if combine_method == "average":
        return 0.5 * (vlo + vhi)
    raise ValueError(f"combine_method {combine_method!r}")


DEFAULT_PROBS = (0.01, 0.1, 0.25, 1 / 3, 0.5, 2 / 3, 0.75, 0.9, 0.99)


def frame_quantiles(frame, probs=None, weights_column=None,
                    combine_method="interpolate"):
    """h2o.quantile surface: per-numeric-column quantiles → column dict.
    Mirrors water/api QuantilesHandler + rapids (quantile ...)."""
    from h2o3_tpu.core.frame import T_NUM, T_TIME
    probs = list(probs) if probs is not None else list(DEFAULT_PROBS)
    w = None
    if weights_column:
        w = frame.matrix([weights_column])[:, 0]
    out = {}
    for name in frame.names:
        v = frame.vec(name)
        if v.type not in (T_NUM, T_TIME, "int", "real"):
            continue
        if name == weights_column:
            continue
        col = frame.matrix([name])[:, 0]
        out[name] = quantile(col, probs, weights=w,
                             combine_method=combine_method)
    return probs, out


def global_quantile_edges(X, w, nbins: int):
    """GlobalQuantilesCalc.java analog: per-column bin edges at uniform
    quantile probabilities, for histogram_type=QuantilesGlobal tree binning.
    Returns (C, nbins-1) edges (device)."""
    C = X.shape[1]
    probs = np.linspace(0.0, 1.0, nbins + 1)[1:-1]
    cols = []
    for c in range(C):
        cols.append(quantile(X[:, c], probs, weights=w))
    return jnp.asarray(np.stack(cols, axis=0), jnp.float32)
