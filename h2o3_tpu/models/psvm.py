"""PSVM — hex/psvm/PSVM.java: support vector machine for binary targets.

Reference: primal kernel SVM solved by block minimization over an Incomplete
Cholesky Factorization of the Gram matrix (hex/psvm), with a bulk scorer.

TPU-native design: the primal squared-hinge objective is minimized directly
with full-batch gradient steps on device (the blocked ICF exists to make CPU
kernel evaluations tractable; on TPU the factorized feature map is the
hardware-shaped equivalent). `kernel_type="gaussian"` uses a random Fourier
feature map Z(x) so the "kernel" path is still two matmuls — the same
low-rank-approximation role ICF plays in the reference.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.models.model import ModelBase
from h2o3_tpu.parallel import compat as _compat


class H2OSupportVectorMachineEstimator(ModelBase):
    algo = "psvm"
    # mesh-sharded serving: (beta, bias) as shared device args; the
    # kernel feature map stays a closure (it may embed training points)
    _serving_param_attrs = ("_params_svm",)
    _defaults = {
        "hyper_param": 1.0,            # C
        "kernel_type": "gaussian", "gamma": -1.0, "rank_ratio": -1.0,
        "positive_weight": 1.0, "negative_weight": 1.0,
        "max_iterations": 200, "feature_dim": 256,
    }

    def _fit(self, frame: Frame, job):
        di = self._dinfo
        X = di.matrix(frame)
        y = di.response(frame)
        w = di.weights(frame)
        w = jnp.where(jnp.isnan(y), 0.0, w)
        assert self.nclasses == 2, "psvm requires a binary response"
        ysvm = jnp.where(y > 0.5, 1.0, -1.0)      # {-1, +1}
        pw = float(self.params["positive_weight"])
        nw = float(self.params["negative_weight"])
        w = w * jnp.where(ysvm > 0, pw, nw)
        Xz = jnp.where(jnp.isnan(X), 0.0, X)
        p = X.shape[1]
        kernel = (self.params.get("kernel_type") or "gaussian").lower()
        seed = int(self.params.get("seed") or -1)
        rng = np.random.default_rng(seed if seed > 0 else 0)
        if kernel == "gaussian":
            gamma = float(self.params.get("gamma") or -1.0)
            if gamma <= 0:
                gamma = 1.0 / max(p, 1)
            Drff = int(self.params.get("feature_dim") or 256)
            W = rng.normal(0, math.sqrt(2 * gamma), (p, Drff))
            b = rng.uniform(0, 2 * np.pi, Drff)
            self._rff = (jnp.asarray(W, jnp.float32),
                         jnp.asarray(b, jnp.float32))
            feat_dim = Drff
        else:
            self._rff = None
            feat_dim = p
        C = float(self.params["hyper_param"])
        rff = self._rff

        def features(Xz):
            if rff is None:
                return Xz
            Wr, br = rff
            return jnp.sqrt(2.0 / Wr.shape[1]) * jnp.cos(Xz @ Wr + br)

        @_compat.guard_collective

        @jax.jit
        def loss(params, Xz, ysvm, w):
            beta, b0 = params
            Z = features(Xz)
            m = ysvm * (Z @ beta + b0)
            hinge = jnp.maximum(0.0, 1.0 - m)
            return 0.5 * (beta @ beta) + \
                C * (w * hinge * hinge).sum() / jnp.maximum(w.sum(), 1.0)

        params = (jnp.zeros(feat_dim, jnp.float32), jnp.float32(0.0))
        import optax
        opt = optax.lbfgs()
        opt_state = opt.init(params)
        vg = _compat.guard_collective(jax.jit(jax.value_and_grad(loss)))

        @_compat.guard_collective

        @jax.jit
        def step(params, opt_state, Xz, ysvm, w):
            l, g = vg(params, Xz, ysvm, w)
            updates, opt_state = opt.update(
                g, opt_state, params, value=l, grad=g,
                value_fn=lambda pr: loss(pr, Xz, ysvm, w))
            return optax.apply_updates(params, updates), opt_state, l

        prev = np.inf
        for it in range(int(self.params["max_iterations"])):
            params, opt_state, l = step(params, opt_state, Xz, ysvm, w)
            lv = float(l)
            if abs(prev - lv) < 1e-8 * max(1.0, abs(prev)):
                break
            prev = lv
            if it % 20 == 0:
                job.update(0.1 + 0.8 * it / int(self.params["max_iterations"]),
                           f"iter {it}")
        self._params_svm = params
        self._features = features
        # decision margins on training data → support vector count
        Z = features(Xz)
        m = np.asarray(ysvm * (Z @ params[0] + params[1]))
        wn = np.asarray(w)
        self._output.model_summary = {
            "svs_count": int(((m < 1.0) & (wn > 0)).sum()),
            "kernel": kernel, "C": C, "final_objective": prev,
        }

    def _score_matrix(self, X):
        beta, b0 = self._params_svm
        Xz = jnp.where(jnp.isnan(X), 0.0, X)
        dec = self._features(Xz) @ beta + b0
        # probability-ish output via logistic link on the margin
        pp = jax.nn.sigmoid(2.0 * dec)
        return jnp.stack([1 - pp, pp], axis=1)
