"""KMeans — hex/kmeans/KMeans.java rebuilt as jitted Lloyd iterations.

Reference: hex/kmeans/KMeans.java:688 (IterationTask), :725
(LloydsIterationTask — one MRTask pass: per-row nearest centroid + per-cluster
{count, sum, wss} reduction), :557 (TotSS), k-means|| / PlusPlus / Furthest
init, standardization on by default.

TPU-native design: one Lloyd step is ONE jitted program: the distance matrix
is X²+C²−2·X@Cᵀ — a (rows × k) matmul that rides the MXU — followed by argmin
and segment-sums; the cross-shard reduction of {sums, counts, wss} is XLA's
all-reduce over ICI (replacing the MRTask reduce tree). The iteration loop
stays on the controller for convergence checks, matching the reference's
driver loop.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.models import metrics as M
from h2o3_tpu.models.model import ModelBase
from h2o3_tpu.parallel import compat as _compat


@_compat.guard_collective


@jax.jit
def _lloyd_step(X, C, w):
    """One Lloyd iteration: assignments + new centroid sums + wss."""
    k = C.shape[0]
    x2 = (X * X).sum(axis=1, keepdims=True)
    c2 = (C * C).sum(axis=1)
    d = x2 + c2[None, :] - 2.0 * X @ C.T            # (n, k) — MXU
    d = jnp.maximum(d, 0.0)
    assign = jnp.argmin(d, axis=1)
    best = jnp.min(d, axis=1)
    sums = jax.ops.segment_sum(w[:, None] * X, assign, k)
    counts = jax.ops.segment_sum(w, assign, k)
    wss = jax.ops.segment_sum(w * best, assign, k)
    return assign, sums, counts, wss


@_compat.guard_collective


@jax.jit
def _totss(X, w):
    n = w.sum()
    mean = (w[:, None] * X).sum(axis=0) / n
    d = X - mean[None, :]
    return (w[:, None] * d * d).sum()


@_compat.guard_collective


@jax.jit
def _assign_only(X, C):
    x2 = (X * X).sum(axis=1, keepdims=True)
    c2 = (C * C).sum(axis=1)
    d = x2 + c2[None, :] - 2.0 * X @ C.T
    return jnp.argmin(d, axis=1), jnp.maximum(jnp.min(d, axis=1), 0.0)


class H2OKMeansEstimator(ModelBase):
    algo = "kmeans"
    supervised = False
    # mesh-sharded serving: centroids as one shared device copy
    _serving_param_attrs = ("_centroids",)
    _defaults = {
        "k": 1, "max_iterations": 10, "init": "Furthest", "estimate_k": False,
        "user_points": None, "standardize": True, "max_runtime_secs": 0.0,
    }

    def _fit(self, frame: Frame, job):
        di = self._dinfo
        X = di.matrix(frame)
        w = di.weights(frame)
        Xz = jnp.where(jnp.isnan(X), 0.0, X)  # padding rows zeroed; w==0 there
        k = int(self.params["k"])
        seed = int(self.params.get("seed") or -1)
        rng = np.random.default_rng(seed if seed > 0 else 12345)
        C = self._init_centroids(Xz, w, k, rng)
        max_it = int(self.params["max_iterations"])
        prev_twss = math.inf
        history = []
        for it in range(max_it):
            assign, sums, counts, wss = _lloyd_step(Xz, C, w)
            counts_np = np.asarray(counts)
            newC = np.array(sums)
            nz = counts_np > 0
            newC[nz] = newC[nz] / counts_np[nz, None]
            newC[~nz] = np.asarray(C)[~nz]      # keep empty clusters in place
            C = jnp.asarray(newC)
            twss = float(np.asarray(wss).sum())
            history.append({"iteration": it, "tot_withinss": twss})
            job.update(0.5 + 0.5 * (it + 1) / max_it, f"iter {it}")
            if abs(prev_twss - twss) < 1e-7 * max(1.0, abs(prev_twss)):
                break
            prev_twss = twss
        # final stats
        assign, sums, counts, wss = _lloyd_step(Xz, C, w)
        totss = float(_totss(Xz, w))
        twss = float(np.asarray(wss).sum())
        self._centroids = C
        self._output.scoring_history = history
        sizes = np.asarray(counts).tolist()
        self._output.training_metrics = M.ClusteringMetrics(
            tot_withinss=twss, totss=totss, betweenss=totss - twss,
            size=sizes, withinss=np.asarray(wss).tolist(),
            nobs=int(float(np.asarray(w).sum())))
        self._output.model_summary = {
            "k": k, "iterations": len(history), "tot_withinss": twss,
            "totss": totss, "betweenss": totss - twss,
        }

    def _init_centroids(self, Xz, w, k, rng) -> jnp.ndarray:
        """Furthest / PlusPlus / Random init (KMeans.java init modes).

        Runs on a host sample (≤100k rows) like the reference's init which
        samples candidate points; the heavy Lloyd loop is device-side.
        """
        mode = (self.params.get("init") or "Furthest").lower()
        if self.params.get("user_points") is not None:
            up = self.params["user_points"]
            pts = up.to_numpy() if isinstance(up, Frame) else np.asarray(up)
            return jnp.asarray(pts, jnp.float32)
        Xh = np.asarray(Xz)
        wh = np.asarray(w)
        live = np.where(wh > 0)[0]
        if len(live) > 100_000:
            live = rng.choice(live, 100_000, replace=False)
        Xs = Xh[live]
        if mode == "random":
            idx = rng.choice(len(Xs), size=min(k, len(Xs)), replace=False)
            return jnp.asarray(Xs[idx], jnp.float32)
        # Furthest & PlusPlus share the D² machinery
        first = rng.integers(len(Xs))
        cents = [Xs[first]]
        d2 = ((Xs - cents[0]) ** 2).sum(axis=1)
        for _ in range(1, min(k, len(Xs))):
            if mode == "plusplus":
                p = d2 / d2.sum() if d2.sum() > 0 else None
                nxt = rng.choice(len(Xs), p=p)
            else:  # furthest
                nxt = int(np.argmax(d2))
            cents.append(Xs[nxt])
            d2 = np.minimum(d2, ((Xs - Xs[nxt]) ** 2).sum(axis=1))
        return jnp.asarray(np.stack(cents), jnp.float32)

    # ---- scoring ---------------------------------------------------------
    def _score_matrix(self, X):
        Xz = jnp.where(jnp.isnan(X), 0.0, X)
        assign, _ = _assign_only(Xz, self._centroids)
        return assign

    def predict(self, test_data: Frame) -> Frame:
        # bucketed compiled-scorer cache via _score_host (legacy for big n)
        assign = np.asarray(self._score_host(test_data))[: test_data.nrows]
        return Frame(["predict"], [Vec.from_numpy(assign.astype(np.float64))])

    def centers(self) -> np.ndarray:
        """Centroids in the (possibly standardized) model space."""
        return np.asarray(self._centroids)

    def centroid_stats(self):
        return self._output.training_metrics

    def tot_withinss(self):
        return self._output.training_metrics.tot_withinss

    def totss(self):
        return self._output.training_metrics.totss

    def betweenss(self):
        return self._output.training_metrics.betweenss
