"""REST API server — water/api/RequestServer.java rebuilt on stdlib http.

Reference: RequestServer.java:56 (route tree, ~150 routes :75-80), versioned
Schema system (water/api/Schema.java, schemas3/*), handlers (ParseHandler,
ModelBuilderHandler, FramesHandler, RapidsHandler, JobsHandler…), served by
Jetty through h2o-webserver-iface. Clients (h2o-py/h2o-r/Flow) are pure REST
consumers — this surface is the compatibility seam.

TPU-native design: one controller process serves the API (every H2O node
serves it; here the controller IS the cluster). Threaded stdlib HTTPServer, no
Jetty; routes mirror the /3 and /99 paths and schema field names the clients
expect. Model builds run as background Jobs, polled via /3/Jobs like the
reference.
"""

from __future__ import annotations

import json
import os as _os_mod
import re
import threading
import time as _time_mod
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

import h2o3_tpu
from h2o3_tpu.analysis import divergence as _dvg
from h2o3_tpu.analysis import leaktrack as _ltk
from h2o3_tpu.core.frame import Frame
from h2o3_tpu.core.jobs import Job, jobs_list
from h2o3_tpu.core.kvstore import DKV
from h2o3_tpu.io import parser as io_parser
from h2o3_tpu.obs import metrics as _obs_metrics
from h2o3_tpu.obs import tracing as _tracing
from h2o3_tpu.obs import usage as _usage
from h2o3_tpu.obs.timeline import span as _span
from h2o3_tpu.rapids import rapids_exec, Session
from h2o3_tpu.utils import env as _env

# per-request REST latency, labeled by ROUTE PATTERN (bounded cardinality),
# method and status — the ROADMAP observability gap this closes
REQUEST_SECONDS = _obs_metrics.histogram(
    "h2o3_rest_request_seconds",
    "REST request wall time by route pattern, method and status")


def _frame_schema(f: Frame, with_summary=False) -> dict:
    d = {
        "frame_id": {"name": f.key},
        "rows": f.nrows, "column_count": f.ncols,
        "columns": [{"label": n, "type": v.type,
                     "missing_count": (v.na_cnt() if v.type != "str" else 0),
                     "domain": v.levels()}
                    for n, v in zip(f.names, f.vecs)],
    }
    if with_summary:
        d["summary"] = f.summary()
    return d


def _model_schema(m) -> dict:
    return m.to_dict()


class _Handler(BaseHTTPRequestHandler):
    server_version = "h2o3-tpu/0.1"

    def send_response(self, code, message=None):
        # remember the status for the request-latency histogram labels
        self._status = code
        super().send_response(code, message)

    def end_headers(self):
        # echo the request's trace id on EVERY response path (JSON,
        # errors, auth challenges, byte downloads) — the client-side
        # handle for GET /3/Trace/{id}
        tid = getattr(self, "_trace_id", None)
        if tid:
            self.send_header("X-H2O3-Trace-Id", tid)
        super().end_headers()

    # ---- security (water/H2OSecurityManager.java + webserver auth) ------
    def _check_auth(self):
        """HTTP Basic credentials checked against the configured
        authenticator (utils/auth: basic file, LDAP simple bind, custom
        LoginModule — the -basic_auth/-ldap_login surface).

        Returns the authenticated USER NAME (the QoS principal seed) on
        success, "" on an unauthenticated server (every caller lands in
        the stable `anonymous` principal — the QoS path never branches
        on auth mode), or None after answering 401. This runs BEFORE
        any QoS admission or queue accounting: an unauthenticated flood
        burns nothing but the 401 itself."""
        authn = getattr(self.server, "authenticator", None)
        if authn is None:
            return ""
        import base64
        hdr = self.headers.get("Authorization", "")
        if hdr.startswith("Basic "):
            try:
                got = base64.b64decode(hdr[6:]).decode()
            except Exception:
                got = ""
            user, _, pwd = got.partition(":")
            try:
                # a crafted pre-auth header must yield 401, never a
                # handler crash — custom LoginModules may raise
                if authn.authenticate(user, pwd):
                    return user
            except Exception:
                pass
        self.send_response(401)
        self.send_header("WWW-Authenticate",
                         'Basic realm="h2o3-tpu"')
        self.send_header("Content-Length", "0")
        self.end_headers()
        return None

    # ---- plumbing -------------------------------------------------------
    def _send(self, obj, code=200, extra_headers=None):
        body = json.dumps(obj, default=_json_default).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        # per-request latency decomposition: close the stage recorder
        # against the route's wall clock (the remainder becomes `app`,
        # so the emitted stages always sum to the measured wall) and
        # hand the waterfall back as a standard Server-Timing header
        t0 = getattr(self, "_route_t0", None)
        timings = _usage.finish_request(
            _time_mod.perf_counter() - t0 if t0 is not None else None)
        if timings:
            self._timings = timings     # → rest.request span attrs
            self.send_header("Server-Timing",
                             _usage.server_timing(timings))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if getattr(self, "command", "") != "HEAD":   # RFC 9110: no body
            self.wfile.write(body)

    def _error(self, msg, code=400):
        self._send({"__meta": {"schema_type": "H2OError"},
                    "msg": str(msg), "http_status": code}, code)

    def _unavailable(self, qf):
        """503 + Retry-After for micro-batch queue-depth backpressure:
        well-behaved clients (and load balancers) back off instead of
        re-queueing onto a stalled accelerator."""
        self._send({"__meta": {"schema_type": "H2OError"},
                    "msg": str(qf), "http_status": 503}, 503,
                   extra_headers={"Retry-After":
                                  str(getattr(qf, "retry_after_s", 1))})

    def _rate_limited(self, ex):
        """429 + Retry-After: the CALLER is over its configured rate or
        quota (serving/qos token buckets / job quotas) — deliberately
        distinct from 503, where the server is out of capacity."""
        self._send({"__meta": {"schema_type": "H2OError"},
                    "msg": str(ex), "http_status": 429}, 429,
                   extra_headers={"Retry-After":
                                  str(getattr(ex, "retry_after_s", 1))})

    def _deadline_exceeded(self, ex):
        """504: the request's X-H2O3-Deadline-Ms budget elapsed before
        the work would have run — shed instead of computing an answer
        nobody is waiting for (counted in h2o3_qos_shed_total)."""
        self._send({"__meta": {"schema_type": "H2OError"},
                    "msg": str(ex), "http_status": 504}, 504)

    def _params(self) -> dict:
        cached = getattr(self, "_cached_params", None)
        if cached is not None:   # body already consumed by the broadcaster
            return dict(cached)
        parsed = urllib.parse.urlparse(self.path)
        q = {k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        ln = int(self.headers.get("Content-Length") or 0)
        if ln:
            # errors="replace": a stray binary body must yield a clean
            # 4xx from the route, not an escaping UnicodeDecodeError
            body = self.rfile.read(ln).decode(errors="replace")
            ctype = self.headers.get("Content-Type", "")
            if "json" in ctype:
                q.update(json.loads(body))
            else:
                q.update({k: v[0] for k, v in
                          urllib.parse.parse_qs(body).items()})
        return q

    def log_message(self, fmt, *args):
        pass  # quiet; Log module handles observability

    # ---- routing --------------------------------------------------------
    def do_GET(self):
        self._route("GET")

    def do_POST(self):
        self._route("POST")

    def do_DELETE(self):
        self._route("DELETE")

    def do_HEAD(self):
        # HEAD mirrors GET headers with the body suppressed in _send;
        # paths with only a GET route still resolve
        path = urllib.parse.urlparse(self.path).path
        if any(m == "HEAD" and pat.fullmatch(path)
               for pat, m, fn in ROUTES):
            self._route("HEAD")
        else:
            self._route("GET")

    def _route(self, method):
        t0 = _time_mod.perf_counter()
        self._status = 0
        self._route_label = "unmatched"
        # latency decomposition: open the per-thread stage recorder (the
        # serving path feeds it; _send closes it into Server-Timing).
        # The route t0 anchors the `app` remainder computation.
        self._route_t0 = t0
        self._timings = None
        _usage.begin_request()
        # distributed tracing: honor the caller's X-H2O3-Trace-Id, mint
        # one otherwise; current for the whole dispatch so every span the
        # request opens (and every job/broadcast it starts) carries it
        tid = None
        if _tracing.enabled():
            tid = _tracing.sanitize(self.headers.get("X-H2O3-Trace-Id")) \
                or _tracing.new_trace_id()
        self._trace_id = tid
        prev_trace = _tracing.set_current(tid)
        # stall sentinel: a handler wedged past H2O3_WATCHDOG_STALL_S
        # (a collective-rendezvous deadlock under a dispatch, a replay
        # barrier that never acks) trips a pinned diagnostic trace with
        # a cluster JStack instead of hanging silently
        from h2o3_tpu.obs import watchdog as _wd
        try:
            with _wd.watch("rest", desc=f"{method} {self.path}", trace=tid):
                self._route_traced(method, tid, prev_trace, t0)
        finally:
            # leaktrack sweep: the one instant every request-scoped pair
            # this thread opened MUST be closed again. It has to sit
            # OUTSIDE the watchdog watch — the watch is itself a tracked
            # scoped pair and is legitimately still open anywhere inside
            # the with block, so an inner sweep reports a false leak on
            # every request
            if _ltk.active():
                _ltk.sweep_request()

    def _route_traced(self, method, tid, prev_trace, t0):
        try:
            if tid is not None:
                with _span("rest.request", method=method) as sp:
                    # X-H2O3-Sample: 1 pins this trace through the flight
                    # recorder's tail sampler regardless of outcome — both
                    # via the root attr (read at trace completion) and via
                    # pin() at ENTRY, so a fragment finalized while the
                    # root is still open (linger expiry, span-count
                    # overflow) is retained too
                    if self.headers.get("X-H2O3-Sample") == "1":
                        sp.attrs["sampled"] = 1
                        from h2o3_tpu.obs import recorder as _obs_rec
                        _obs_rec.RECORDER.pin(tid)
                    self._route_inner(method)
                    sp.attrs["route"] = self._route_label
                    sp.attrs["status"] = self._status or 0
                    # the response's Server-Timing breakdown rides the
                    # root span too, so a stored trace explains its
                    # own latency without the caller keeping the header
                    if getattr(self, "_timings", None):
                        sp.attrs["stages"] = {
                            k: round(v, 6)
                            for k, v in self._timings.items()}
            else:
                self._route_inner(method)
        finally:
            _usage.clear_request()   # 401s/handler crashes: no leak into
            _tracing.set_current(prev_trace)  # the next keep-alive request
            # the trace id rides the histogram as an OpenMetrics exemplar:
            # a Grafana latency spike clicks through to GET /3/Trace/{id}
            dt = _time_mod.perf_counter() - t0
            REQUEST_SECONDS.observe(
                dt, exemplar=tid,
                route=self._route_label, method=method,
                status=str(self._status or 0))
            # per-tenant SLI: scoring requests also land in the
            # principal-labeled histogram the per-tenant SLO specs
            # (obs/slo.py `principal` filter) burn against. Keyed on the
            # matched handler's @scores mark (stashed by _route_inner
            # before the entry-deadline shed, so edge 504s still count)
            # — one registration-site source of truth, not a parallel
            # path-prefix list that drifts when a scoring route is added.
            if getattr(self, "_principal", None) \
                    and getattr(self, "_scores_route", False):
                from h2o3_tpu.serving import qos as _qos
                _qos.observe_request(
                    dt, exemplar=tid, principal=self._principal,
                    status=str(self._status or 0))

    def _route_inner(self, method):
        # ORDER MATTERS: authentication runs before any QoS admission or
        # queue accounting, so an unauthenticated flood is rejected at
        # 401 without consuming queue depth, tokens or principal state.
        edge_t0 = _time_mod.perf_counter()
        user = self._check_auth()
        if user is None:
            self._route_label = "auth"
            return
        from h2o3_tpu.serving import qos as _qos
        # multi-tenant QoS context: the principal (authenticated user,
        # else the stable `anonymous` bucket) and the caller's optional
        # deadline budget ride the obs TLS alongside the trace id —
        # admission, the micro-batcher and Job quotas all read them
        # from there
        principal = _qos.resolve_principal(user)
        self._principal = principal
        deadline = None
        hdr = self.headers.get("X-H2O3-Deadline-Ms")
        if hdr:
            try:
                ms = float(hdr)
            except ValueError:
                ms = None       # a junk header is "no deadline", not 400
            if ms is not None:
                deadline = _time_mod.monotonic() + ms / 1e3
        # one route match per request: the pre-broadcast QoS marks, the
        # route label and the dispatch below all reuse this result
        path = urllib.parse.urlparse(self.path).path
        self._req_path = path
        pat, fn, groups = _match_route(method, path)
        # the per-tenant SLI emit in _route's finally keys on this:
        # matched BEFORE the entry shed, so an edge 504 still counts
        self._scores_route = fn is not None and \
            getattr(fn, "_scores", False)
        with _tracing.request_context(principal, deadline):
            try:
                # leaktrack (raise mode): a token that died unreleased
                # since the last dispatch fails THIS request — loud and
                # attributable, where the GC-thread finalizer is neither
                if _ltk.active():
                    _ltk.raise_if_pending()
                # a budget that arrived already spent is shed at the
                # edge — before params parse, broadcast or handler work
                if _qos.enabled():
                    _qos.check_deadline("entry")
                    # PRE-BROADCAST rejections (multi-host divergence
                    # guard): a 429 after the replay broadcast would
                    # leave the workers running work the coordinator
                    # refused — a build for job routes, a lone
                    # collective scoring dispatch for scoring routes.
                    # Job-starting handlers (marked @starts_job) charge
                    # the concurrent-job quota here; scoring handlers
                    # (marked @scores) pay deadline + token admission
                    # here (the in-pipeline admit() sees the TLS flag
                    # and skips the double charge).
                    if method != "GET" and fn is not None:
                        if getattr(fn, "_starts_job", False):
                            _qos.prepay_job_slot()
                        if getattr(fn, "_scores", False):
                            _qos.edge_admit()
                # everything up to here — auth, principal resolve, route
                # match, deadline parse, pre-broadcast QoS admission —
                # is the request's edge-admission stage
                _usage.add_stage(
                    "edge", _time_mod.perf_counter() - edge_t0)
                self._dispatch_routed(method, path, pat, fn, groups)
            except _qos.RateLimited as ex:
                self._rate_limited(ex)
            except _qos.QuotaExceeded as ex:
                self._rate_limited(ex)
            except _qos.DeadlineExceeded as ex:
                self._deadline_exceeded(ex)
            finally:
                # clear the edge-admission flag and return a prepaid
                # charge no Job adopted (the handler 4xx'd first); the
                # leaktrack sweep runs further out, in _route, once the
                # watchdog watch (itself a tracked pair) has closed
                _qos.end_request()

    def _dispatch_routed(self, method, path, pat, fn, groups):
        # SPMD replay (deploy/multihost): requests broadcast to every
        # worker BEFORE local dispatch so all hosts issue the same device
        # programs (a lone host in a collective would deadlock). GETs are
        # included — frame rollups, dataset downloads and diagnostics all
        # jit/readback over globally sharded arrays, and in a
        # multi-controller runtime those launches must be collective too;
        # replaying an idempotent GET is free, deadlocking the cloud isn't.
        bc = getattr(self.server, "broadcaster", None)
        try:
            if bc is not None and not _is_static_path(path) \
                    and not _is_obs_path(path) \
                    and not path.startswith("/3/PostFile") \
                    and not path.startswith("/3/ParseDistributed"):
                # PostFile is excluded: its body is raw (often binary)
                # bytes that neither parse as params nor replay through
                # the channel. ParseDistributed is excluded because the
                # workers participate through the parse fan-out collect
                # ops instead — replaying the request would have every
                # host ALSO parse the whole file (and deadlock the
                # fan-out behind their replays). Inside the try: a
                # wedged replay channel (broadcast RuntimeError after
                # the ack deadline) must answer a 500 H2OError, not
                # drop the connection.
                params = self._params()
                self._cached_params = params
                # the trace id rides the replay channel so every worker
                # tags its replayed spans with the ORIGINATING request's
                # trace
                seq = bc.broadcast(method, path, params,
                                   trace=getattr(self, "_trace_id", None),
                                   sampled=self.headers.get(
                                       "X-H2O3-Sample") == "1")
            else:
                seq = None
            if fn is not None:
                self._route_label = pat.pattern
                if seq is not None and _dvg.active():
                    # divergence sanitizer: surface any mismatch a prior
                    # request's ack riders proved (deferred out of the
                    # broadcaster's loops), then digest this handler's
                    # replicated-state mutations under the broadcast seq
                    # for comparison against each worker's replay
                    _dvg.raise_if_pending()
                    _dvg.local_begin(seq, path)
                    try:
                        fn(self, *groups)
                    finally:
                        _dvg.local_end()
                else:
                    fn(self, *groups)
                return
            self._error(f"no route {method} {path}", 404)
        except Exception as ex:  # noqa: BLE001 — handler errors → H2OError
            # QoS rejections raised inside handlers (rate limit at
            # admission, job quota at Job.start, deadline shed) are not
            # handler errors: let _route_inner map them to 429/504
            from h2o3_tpu.serving import qos as _qos
            if isinstance(ex, (_qos.RateLimited, _qos.QuotaExceeded,
                               _qos.DeadlineExceeded)):
                raise
            self._error(repr(ex), 500)


def starts_job(fn):
    """Marks a handler that starts a background Job. The REST layer
    prepays the concurrent-job quota for marked handlers BEFORE the
    replay broadcast (qos.prepay_job_slot) — a registration-site flag,
    so new job routes can't silently miss the pre-broadcast charge the
    way a hand-kept path list would."""
    fn._starts_job = True
    return fn


def scores(fn):
    """Marks a scoring handler. The REST layer runs QoS admission
    (deadline shed + token charge) for marked handlers at the edge,
    BEFORE the replay broadcast (qos.edge_admit) — a 429 raised after
    the broadcast would leave every worker dispatching a collective
    scoring program the coordinator refused."""
    fn._scores = True
    return fn


def _match_route(method: str, path: str):
    """One ROUTES scan per request: (pattern, handler, match groups) for
    (method, path), or (None, None, None). The pre-broadcast QoS marks
    (`_starts_job` / `_scores`), the route label and the dispatch all
    reuse this single result."""
    for pat, m, fn in ROUTES:
        if m != method:
            continue
        mm = pat.fullmatch(path)
        if mm:
            return pat, fn, mm.groups()
    return None, None, None


def _is_static_path(path: str) -> bool:
    """Static Flow-UI assets never touch device arrays — broadcasting
    them would serialize page loads behind the cluster replay barrier."""
    return path == "/" or path.startswith("/flow")


def _is_obs_path(path: str) -> bool:
    """Observability endpoints launch no device programs (registry reads +
    memory_stats are host-local), and /3/Timeline, /3/Trace and
    cluster-scope /metrics do their own explicit cloud-wide collects —
    replaying them would put every Prometheus scrape behind the replay
    barrier. /3/Profiler is deliberately host-local too: a capture
    profiles THIS node, and the jax profiler is process-global state the
    replay barrier must not serialize behind."""
    return path in ("/metrics", "/3/Timeline", "/3/WaterMeter",
                    "/3/Profiler", "/3/Traces", "/3/Alerts",
                    "/3/JStack", "/3/Usage", "/3/CloudHealth") \
        or path.startswith("/3/Logs") or path.startswith("/3/Trace/") \
        or path.startswith("/3/Cloud/") \
        or path.startswith("/3/ModelMonitor/")


def _json_default(o):
    if isinstance(o, (np.floating, np.integer)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    return str(o)


# ---------------------------------------------------------------------------
# handlers
def _h_cloud(h: _Handler):
    """GET /3/Cloud — device census plus the elastic-membership view:
    the cloud EPOCH (bumps on every excision/join/drain), per-worker
    states, and the DKV re-home status. `locked` is the reference's
    Paxos.lockCloud flag — false here whenever an elastic broadcaster
    can still admit joiners."""
    from h2o3_tpu.core.kvstore import DKV as _dkv
    from h2o3_tpu.deploy.membership import MEMBERSHIP as _mb
    info = h2o3_tpu.cluster_info()
    # getattr chain: worker-side replays dispatch through _ReplayHandler,
    # which carries no HTTP server object
    bc = getattr(getattr(h, "server", None), "broadcaster", None)
    elastic = bc is not None and hasattr(bc, "drain")
    workers = _mb.nodes()
    # healthy = no UNRESOLVED death: a worker dead at the CURRENT epoch
    # is a live incident; once a later membership change (replacement
    # join, drain) moves the epoch past it, the death is history and the
    # cloud reports healthy again
    healthy = not any(w["state"] == "dead" and w["epoch"] == _mb.epoch
                      for w in workers)
    h._send({"__meta": {"schema_type": "CloudV3"},
             "cloud_name": info["cloud_name"],
             "cloud_size": info["cloud_size"],
             "cloud_healthy": healthy,
             "consensus": True, "locked": not elastic,
             "epoch": _mb.epoch,
             "workers": workers,
             "rehome": _dkv.rehome_status(),
             "version": h2o3_tpu.__version__,
             "nodes": [{"h2o": d, "healthy": True}
                       for d in info["devices"]]})


# membership is coordinator-owned: drain IS the protocol that tells
# workers about the epoch change (leave + bump), not a replicated write
# that needed replaying
# h2o3-ok: R018 coordinator-owned membership control surface
def _h_cloud_drain(h: _Handler):
    """POST /3/Cloud/drain?node=N — graceful worker departure: finish
    in-flight jobs and micro-batches (bounded by H2O3_DRAIN_TIMEOUT_S),
    send the worker a clean leave, bump the epoch. Coordinator-control
    only: never broadcast (workers hold no broadcaster)."""
    bc = getattr(h.server, "broadcaster", None)
    if bc is None or not hasattr(bc, "drain"):
        return h._error("drain requires an elastic multi-host cloud", 400)
    p = h._params()
    try:
        node = int(p.get("node", ""))
    except ValueError:
        return h._error("node must be a worker id", 400)
    try:
        out = bc.drain(node)
    except ValueError as ex:
        return h._error(str(ex), 404)
    h._send({"__meta": {"schema_type": "CloudDrainV3"}, **out})


def _h_import(h: _Handler):
    p = h._params()
    path = p.get("path")
    h._send({"__meta": {"schema_type": "ImportFilesV3"},
             "files": [path], "destination_frames": [path], "fails": []})


def _h_parse_setup(h: _Handler):
    p = h._params()
    src = p.get("source_frames")
    if isinstance(src, str):
        src = json.loads(src) if src.startswith("[") else [src]
    path = src[0].strip('"')
    # PostFile-staged uploads: the h2o-py upload flow calls ParseSetup on
    # the pseudo-key returned by /3/PostFile before /3/Parse
    from h2o3_tpu.api import routes_ext3 as _up
    staged = _up.staged_upload_path(path)
    probe = staged or path
    s = io_parser.parse_setup(probe)
    h._send({"__meta": {"schema_type": "ParseSetupV3"},
             "source_frames": src,
             "separator": ord(s.separator), "check_header": 1 if s.header else -1,
             "column_names": s.column_names, "column_types": s.column_types,
             "parse_type": s.parse_type,
             "destination_frame": path.split("/")[-1] + ".hex"})


def _canon_col_types(ct: dict) -> dict:
    """Map ParseV3 type names (Vec.java TYPE_STR values) to internal codes."""
    alias = {"numeric": "num", "real": "num", "int": "num", "float": "num",
             "enum": "enum", "categorical": "enum", "factor": "enum",
             "string": "str", "str": "str", "time": "time",
             "uuid": "uuid", "num": "num"}
    return {k: alias.get(str(v).lower(), v) for k, v in ct.items()}


@starts_job
def _h_parse(h: _Handler):
    p = h._params()
    src = p.get("source_frames")
    if isinstance(src, str):
        src = json.loads(src) if src.startswith("[") else [src]
    path = src[0].strip('"')
    # PostFile-staged uploads resolve their pseudo-key to the temp file,
    # consumed (deleted) once the parse finishes
    from h2o3_tpu.api import routes_ext3 as _up
    upload_key = None
    staged = _up.staged_upload_path(path)
    if staged:
        upload_key, path = path, staged
    dest = p.get("destination_frame") or None
    # ParseV3 column_types: either a dict {name: type} or the reference's
    # list aligned with ParseSetup's column order
    ctypes = p.get("column_types")
    if isinstance(ctypes, str) and ctypes:
        ctypes = json.loads(ctypes)
    if isinstance(ctypes, list):
        names = p.get("column_names")
        if isinstance(names, str) and names:
            names = json.loads(names)
        if not names:
            names = io_parser.parse_setup(path).column_names
        ctypes = {n: t for n, t in zip(names, ctypes) if t}
    ctypes = _canon_col_types(ctypes) if ctypes else None
    job = Job(description=f"Parse {path}", dest=dest or "parsed")

    def work(job):
        try:
            f = io_parser.import_file(path, destination_frame=dest,
                                      col_types=ctypes)
        finally:
            if upload_key is not None:
                _up.consume_upload(upload_key)
        job.dest = f.key
        return f

    job.start(work)
    h._send({"__meta": {"schema_type": "ParseV3"},
             "job": job.to_dict(), "destination_frame": {"name": dest}})


@starts_job
# the job record is coordinator-owned control state; the FRAME planes
# workers produce ship back over the parse: fan-out and land under the
# coordinator's dest key, not via replay
# h2o3-ok: R018 coordinator-owned job record; frames ship via fan-out
def _h_parse_distributed(h: _Handler):
    """POST /3/ParseDistributed — the cloud-wide chunked parse: the
    coordinator plans byte ranges and fans shares out over the replay
    channel (io/dparse `parse:` collect op); each host tokenizes its
    consistent-hash share and ships codec-byte planes back. NOT
    broadcast-replayed (see _route_inner): the workers participate
    through the fan-out, so replaying the request would have every host
    also parse the whole file. On a single-host cloud this is simply
    the local pipelined parse.

    Topology contract: the merged frame lives in the COORDINATOR's DKV
    (host codec planes, born cold) — the elastic/serving topology,
    where DKV re-home ships codec bytes and replacement workers run
    single-process jax. On a fixed multi-controller SPMD device
    runtime, frames destined for collective training must go through
    the broadcast-replayed /3/Parse instead (every host parses, every
    host holds its device shards)."""
    p = h._params()
    src = p.get("source_frames")
    if isinstance(src, str):
        src = json.loads(src) if src.startswith("[") else [src]
    paths = [s.strip('"') for s in src]
    dest = p.get("destination_frame") or None
    bc = getattr(h.server, "broadcaster", None)
    job = Job(description=f"ParseDistributed {paths[0]}",
              dest=dest or "parsed")

    def work(job):
        from h2o3_tpu.io import dparse
        f = dparse.parse_files(paths, destination_frame=dest,
                               broadcaster=bc)
        job.dest = f.key
        return f

    job.start(work)
    h._send({"__meta": {"schema_type": "ParseV3"},
             "job": job.to_dict(), "destination_frame": {"name": dest}})


def _h_frames(h: _Handler):
    frames = [DKV.get(k) for k in DKV.keys()]
    frames = [f for f in frames if isinstance(f, Frame)]
    h._send({"__meta": {"schema_type": "FramesV3"},
             "frames": [_frame_schema(f) for f in frames]})


def _h_frame(h: _Handler, fid):
    f = DKV.get(fid)
    if not isinstance(f, Frame):
        return h._error(f"frame {fid} not found", 404)
    h._send({"__meta": {"schema_type": "FramesV3"},
             "frames": [_frame_schema(f, with_summary=True)]})


def _h_frame_delete(h: _Handler, fid):
    DKV.remove(fid)
    h._send({"__meta": {"schema_type": "FramesV3"}})


def _h_model_builders(h: _Handler):
    from h2o3_tpu.models import ESTIMATORS
    h._send({"__meta": {"schema_type": "ModelBuildersV3"},
             "model_builders": {k: {"algo": k, "visibility": "Stable"}
                                for k in ESTIMATORS}})


@starts_job
def _h_build_model(h: _Handler, algo):
    from h2o3_tpu.models import ESTIMATORS
    cls = ESTIMATORS.get(algo)
    if cls is None:
        return h._error(f"unknown algo {algo}", 404)
    p = h._params()
    tf = DKV.get(p.pop("training_frame", None))
    vf = DKV.get(p.pop("validation_frame", None)) if p.get(
        "validation_frame") else None
    y = p.pop("response_column", None)
    x = p.pop("x", None)
    if isinstance(x, str):
        x = json.loads(x)
    p.pop("_rest_version", None)
    params = {}
    for k, v in p.items():
        if k in cls._COMMON or k in cls._defaults:
            params[k] = _coerce_param(v)
    est = cls(**params)
    job = Job(description=f"{algo} model build",
              dest=params.get("model_id") or DKV.make_key(algo))

    def work(job):
        est.train(x=x, y=y, training_frame=tf, validation_frame=vf)
        job.dest = est.key
        return est

    job.start(work)
    h._send({"__meta": {"schema_type": "ModelBuilderJobV3"},
             "job": job.to_dict()})


def _coerce_param(v):
    if isinstance(v, str):
        if v.lower() in ("true", "false"):
            return v.lower() == "true"
        if v.startswith("["):
            return json.loads(v)
        try:
            fv = float(v)
            return int(fv) if fv.is_integer() and "." not in v else fv
        except ValueError:
            return v
    return v


def _h_models(h: _Handler):
    from h2o3_tpu.models.model import ModelBase
    ms = [DKV.get(k) for k in DKV.keys()]
    ms = [m for m in ms if isinstance(m, ModelBase)]
    h._send({"__meta": {"schema_type": "ModelsV3"},
             "models": [_model_schema(m) for m in ms]})


def _h_model(h: _Handler, mid):
    m = DKV.get(mid)
    if m is None:
        return h._error(f"model {mid} not found", 404)
    h._send({"__meta": {"schema_type": "ModelsV3"},
             "models": [_model_schema(m)]})


def _h_model_delete(h: _Handler, mid):
    DKV.remove(mid)
    # drop the serving cache's compiled programs so their closures stop
    # pinning the deleted model (and its device arrays)
    from h2o3_tpu import serving
    serving.CACHE.invalidate_key(mid)
    h._send({"__meta": {"schema_type": "ModelsV3"}})


@scores
def _h_predict(h: _Handler, mid, fid):
    m = DKV.get(mid)
    f = DKV.get(fid)
    if m is None or f is None:
        return h._error("model or frame not found", 404)
    p = h._params()
    dest = p.get("predictions_frame")
    # micro-batched serving fast path: concurrent predictions against the
    # same model coalesce into one padded device dispatch per bucket
    from h2o3_tpu import serving
    try:
        pred = serving.predict_via_rest(m, f)
    except serving.QueueFull as qf:
        return h._unavailable(qf)
    if dest:
        DKV.remove(pred.key)
        pred.key = dest
        DKV.put(dest, pred)
    # metrics alongside the predictions when the frame carries the response
    # (hex/Model.java:2077 BigScore + ModelMetricsHandler). Metric errors
    # surface in the response rather than being swallowed.
    mm_json = []
    resp = (m._dinfo.response_name if getattr(m, "_dinfo", None) else None)
    if resp and resp in f.names:
        try:
            perf = m.model_performance(f)
            if perf is not None and hasattr(perf, "to_dict"):
                mm_json = [dict(perf.to_dict(),
                                frame={"name": f.key},
                                model={"name": m.key})]
        except Exception as ex:      # noqa: BLE001
            mm_json = [{"error": repr(ex)}]
    h._send({"__meta": {"schema_type": "ModelMetricsListSchemaV3"},
             "predictions_frame": {"name": pred.key},
             "model_metrics": mm_json})


@scores
def _h_predict_rows(h: _Handler, mid):
    """POST /3/Predictions/models/{m} — lightweight row-payload scoring:
    JSON rows in, per-row predictions out, no DKV frame round-trip.
    Body: {"rows": [[..] | {col: val}, ...], "columns": [names]?}.
    Rides the micro-batch queue, so concurrent callers share one padded
    device dispatch per bucket."""
    m = DKV.get(mid)
    if m is None or getattr(m, "_dinfo", None) is None:
        return h._error(f"model {mid} not found", 404)
    p = h._params()
    rows = p.get("rows")
    if isinstance(rows, str):
        rows = json.loads(rows) if rows else []
    if not isinstance(rows, list):
        return h._error("rows must be a JSON list", 400)
    cols = p.get("columns")
    if isinstance(cols, str) and cols:
        cols = json.loads(cols)
    from h2o3_tpu import serving
    try:
        preds = serving.score_payload(m, rows, cols)
    except serving.QueueFull as qf:
        return h._unavailable(qf)
    h._send({"__meta": {"schema_type": "PredictionsRowsV3"},
             "model": {"name": mid}, "predictions": preds,
             "row_count": len(preds)})


def _h_jobs(h: _Handler):
    h._send({"__meta": {"schema_type": "JobsV3"}, "jobs": jobs_list()})


def _h_job(h: _Handler, jid):
    j = DKV.get(jid)
    if not isinstance(j, Job):
        return h._error(f"job {jid} not found", 404)
    h._send({"__meta": {"schema_type": "JobsV3"}, "jobs": [j.to_dict()]})


_sessions: dict = {}


def _h_rapids(h: _Handler):
    p = h._params()
    ast = p.get("ast")
    sid = p.get("session_id", "default")
    sess = _sessions.setdefault(sid, Session(sid))
    val = rapids_exec(ast, sess)
    if isinstance(val, Frame):
        h._send({"__meta": {"schema_type": "RapidsFrameV3"},
                 "key": {"name": val.key}, "num_rows": val.nrows,
                 "num_cols": val.ncols})
    elif isinstance(val, (int, float)):
        h._send({"__meta": {"schema_type": "RapidsNumberV3"},
                 "scalar": val})
    elif isinstance(val, list):
        h._send({"__meta": {"schema_type": "RapidsStringsV3"},
                 "string": [str(s) for s in val]})
    else:
        h._send({"__meta": {"schema_type": "RapidsStringV3"},
                 "string": str(val)})


def _h_init_session(h: _Handler):
    sid = DKV.make_key("session")
    _sessions[sid] = Session(sid)
    h._send({"__meta": {"schema_type": "InitIDV3"}, "session_key": sid})


def _h_end_session(h: _Handler):
    p = h._params()
    sid = p.get("session_id", "default")
    s = _sessions.pop(sid, None)
    if s:
        s.end()
    h._send({"__meta": {"schema_type": "InitIDV3"}, "session_key": sid})


def _h_shutdown(h: _Handler):
    h._send({"__meta": {"schema_type": "ShutdownV3"}})
    threading.Thread(target=h.server.shutdown, daemon=True).start()


def _h_about(h: _Handler):
    h._send({"__meta": {"schema_type": "AboutV3"},
             "entries": [{"name": "Build version",
                          "value": h2o3_tpu.__version__},
                         {"name": "Backend", "value": "jax/tpu"}]})


def _h_model_metrics(h: _Handler, mid, fid=None):
    """/3/ModelMetrics/models/{m}[/frames/{f}] — ModelMetricsHandler."""
    m = DKV.get(mid)
    if m is None:
        return h._error("model not found", 404)
    if fid is not None:
        f = DKV.get(fid)
        if f is None:
            return h._error("frame not found", 404)
        perf = m.model_performance(f)
    else:
        perf = m.model_performance()
    mm = [dict(perf.to_dict(), model={"name": mid})] \
        if perf is not None and hasattr(perf, "to_dict") else []
    h._send({"__meta": {"schema_type": "ModelMetricsListSchemaV3"},
             "model_metrics": mm})


def _h_grids(h: _Handler):
    grids = [k for k in DKV.keys()
             if getattr(DKV.get(k), "grid_id", None) == k]
    h._send({"__meta": {"schema_type": "GridsV99"},
             "grids": [{"grid_id": {"name": g}} for g in grids]})


def _h_grid(h: _Handler, gid):
    g = DKV.get(gid)
    if g is None or not hasattr(g, "models"):
        return h._error("grid not found", 404)
    h._send({"__meta": {"schema_type": "GridSchemaV99"},
             "grid_id": {"name": gid},
             "model_ids": [{"name": m.key} for m in g.models],
             "hyper_names": list(getattr(g, "hyper_params", {}).keys())})


@starts_job
def _h_automl_build(h: _Handler):
    """POST /99/AutoMLBuilder — AutoMLBuilderHandler analog."""
    from h2o3_tpu.automl.automl import H2OAutoML
    p = h._params()
    spec = p.get("build_control", {})
    if isinstance(spec, str):
        spec = json.loads(spec)
    inp = p.get("input_spec", {})
    if isinstance(inp, str):
        inp = json.loads(inp)
    stop = spec.get("stopping_criteria", {})

    def _get_tf(d):
        v = d.get("training_frame", "")
        return v.get("name") if isinstance(v, dict) else v

    train = DKV.get(p.get("training_frame") or _get_tf(inp) or "")
    if train is None:
        return h._error("training_frame not found", 404)
    y = p.get("response_column") or inp.get("response_column")
    if isinstance(y, dict):
        y = y.get("column_name")
    aml = H2OAutoML(
        max_models=int(p.get("max_models") or stop.get("max_models") or 5),
        seed=int(p.get("seed") or stop.get("seed") or 42),
        project_name=p.get("project_name") or spec.get("project_name"))
    from h2o3_tpu.core.jobs import Job
    job = Job(description="AutoML build", dest=aml.project_name)
    job.start(lambda j: aml.train(y=y, training_frame=train))
    job.join()
    h._send({"__meta": {"schema_type": "AutoMLBuilderV99"},
             "job": {"key": {"name": job.key}},
             "automl_id": {"name": aml.project_name}})


def _h_automl(h: _Handler, pid):
    aml = DKV.get(pid)
    if aml is None or not hasattr(aml, "leaderboard_obj"):
        return h._error("automl not found", 404)
    lb = aml.leaderboard_obj
    rows = lb.rows if lb is not None and hasattr(lb, "rows") else []
    h._send({"__meta": {"schema_type": "AutoMLV99"},
             "automl_id": {"name": pid},
             "leaderboard_table": {"rows": rows},
             "leader": rows[0] if rows else None})


def _h_logs_download(h: _Handler):
    """GET /3/Logs/download — the legacy one-shot dump: this host's
    recent formatted log lines (water/util/GetLogsFromNode analog)."""
    from h2o3_tpu.utils import log as _log
    h._send({"__meta": {"schema_type": "LogsV3"},
             "log": "\n".join(_log.recent(500))})


def _h_logs_search(h: _Handler):
    """GET /3/Logs?level=&since=&trace=&grep=&limit= — structured log
    search over ring + durable segments, CLUSTER-scoped: the same
    filters fan out to every worker over the `logs:` collect op and the
    records merge time-sorted (newest first) with host labels already on
    each record. A lagging host is flagged, never waited on."""
    import json as _json
    from h2o3_tpu.obs import timeline as _obs_tl
    from h2o3_tpu.utils import log as _log
    p = h._params()
    try:
        since = float(p["since"]) if p.get("since") else None
        limit = int(p.get("limit") or 200)
    except ValueError:
        return h._error("since/limit must be numeric", 400)
    filters = {"level": p.get("level") or None, "since": since,
               "trace": p.get("trace") or None,
               "grep": p.get("grep") or None, "limit": limit}
    recs = _log.search(**filters)
    hosts = [{"host": _obs_tl.host_id(), "n_records": len(recs),
              "files": [f["name"] for f in _log.list_files()]}]
    bc = getattr(h.server, "broadcaster", None)
    if bc is not None and str(p.get("scope", "")).lower() != "local":
        op = "logs:search:" + _json.dumps(filters)
        seen = {(r.get("host"), r.get("id")) for r in recs}
        for i, remote in enumerate(bc.collect(op,
                                              timeout=_collect_timeout())):
            if isinstance(remote, dict):
                rr = [r for r in remote.get("records", [])
                      if (r.get("host"), r.get("id")) not in seen]
                seen.update((r.get("host"), r.get("id")) for r in rr)
                recs.extend(rr)
                hosts.append({"host": remote.get("host", i + 1),
                              "n_records": len(rr),
                              "files": remote.get("files", [])})
            else:
                hosts.append({"host": i + 1, "n_records": None,
                              "lagging": True})
    recs.sort(key=lambda r: r.get("t") or 0.0, reverse=True)
    h._send({"__meta": {"schema_type": "LogsV3"},
             "records": recs[:limit], "n_records": min(len(recs), limit),
             "hosts": hosts})


def _h_logs_node_file(h: _Handler, node, name):
    """GET /3/Logs/nodes/{node}/files/{name} — the named NODE's durable
    log file content (GetLogsFromNode routed over the replay channel),
    not the coordinator's ring. `node` is a host rank or "self"; `name`
    a file basename from GET /3/Logs hosts[].files, or "default" for
    the node's newest file."""
    from h2o3_tpu.obs import timeline as _obs_tl
    from h2o3_tpu.utils import log as _log
    local = _obs_tl.host_id()
    if node in ("self", "-1", str(local)):
        content = _log.read_file(name)
        if content is None:
            return h._error(f"log file {name!r} not found on node "
                            f"{local}", 404)
        return h._send({"__meta": {"schema_type": "LogsV3"},
                        "node": local, "name": name, "log": content})
    bc = getattr(h.server, "broadcaster", None)
    if bc is None:
        return h._error(f"unknown node {node!r} (single-host cloud)", 404)
    for remote in bc.collect(f"logs:file:{node}:{name}",
                             timeout=_collect_timeout()):
        if isinstance(remote, dict) and remote.get("log") is not None:
            return h._send({"__meta": {"schema_type": "LogsV3"},
                            "node": remote.get("host"),
                            "name": remote.get("name", name),
                            "log": remote["log"]})
    return h._error(f"log file {name!r} not found on node {node!r} "
                    "(host absent, lagging, or no such file)", 404)


def _h_jstack(h: _Handler):
    """GET /3/JStack — all-thread stack dumps per node with a cluster
    merge (water/api/JStackHandler analog): this host's threads plus
    every worker's over the `jstack` collect op, and the watchdog's
    currently-stalled operations so a live hang is visible in the same
    response that shows the threads stuck in it."""
    from h2o3_tpu.obs import timeline as _obs_tl
    from h2o3_tpu.obs import watchdog as _wd
    traces = [{"node": f"h2o3-{_obs_tl.host_id()}",
               "host": _obs_tl.host_id(),
               "thread_traces": _wd.thread_dump()}]
    lagging = []
    bc = getattr(h.server, "broadcaster", None)
    if bc is not None:
        for i, remote in enumerate(bc.collect("jstack",
                                              timeout=_collect_timeout())):
            if isinstance(remote, dict):
                traces.append({"node": f"h2o3-{remote.get('host', i + 1)}",
                               "host": remote.get("host", i + 1),
                               "thread_traces": remote.get("threads", [])})
            else:
                lagging.append(i + 1)
    h._send({"__meta": {"schema_type": "JStackV3"},
             "traces": traces, "lagging_hosts": lagging,
             "stalled": _wd.WATCHDOG.stalled(),
             "trips": _wd.WATCHDOG.trips()})


def _collect_timeout() -> float:
    """Per-host deadline for cluster-wide observability collects
    (timeline/trace/metrics). The ISSUE-4 discipline: every wait the
    coordinator performs while holding the broadcast lock is bounded —
    a stalled worker costs one deadline, never a frozen scrape."""
    return _env.env_float("H2O3_OBS_COLLECT_TIMEOUT_S", 2.0)


def _h_timeline(h: _Handler):
    """GET /3/Timeline — the TimelineSnapshot analog: this host's span
    ring plus every worker's, collected through the multihost replay
    channel so the response covers the whole cloud."""
    import time as _time
    from h2o3_tpu.obs import timeline as _obs_tl
    spans = _obs_tl.SPANS.snapshot(limit=512)
    hosts = [{"host": _obs_tl.host_id(), "n_spans": len(spans)}]
    bc = getattr(h.server, "broadcaster", None)
    if bc is not None:
        # one flat merged list; hosts[] summarizes who answered (a None
        # entry is a worker that outwaited the collect timeout)
        for i, remote in enumerate(bc.collect("timeline",
                                              timeout=_collect_timeout())):
            if isinstance(remote, dict):
                rs = remote.get("spans", [])
                spans.extend(rs)
                hosts.append({"host": remote.get("host", i + 1),
                              "n_spans": len(rs)})
            else:
                hosts.append({"host": i + 1, "n_spans": None,
                              "lagging": True})
        spans.sort(key=lambda s: s.get("start") or 0.0)
    # legacy dispatch-event ring (utils/timeline) rides along
    from h2o3_tpu.utils.timeline import TIMELINE
    try:
        events = TIMELINE.snapshot()
    except Exception:
        events = []
    h._send({"__meta": {"schema_type": "TimelineV3"},
             "now": _time.time(), "spans": spans, "hosts": hosts,
             "events": events[-512:]})


def _h_trace(h: _Handler, tid):
    """GET /3/Trace/{id} — the Dapper-style stitched view of one request,
    read through ring → disk → cluster: this host's timeline ring, then
    the flight recorder's durable segments (so a trace evicted from the
    ring — or recorded by a PREVIOUS process over the same ice_root — is
    still answerable), then every worker's fragments over the replay
    channel. Correlated structured LOG records (utils/log, matched by
    trace id cluster-wide) interleave into the view as a time-sorted
    `logs` array. Bounded by the same collect deadline as /3/Timeline."""
    from h2o3_tpu.obs import recorder as _obs_rec
    from h2o3_tpu.obs import timeline as _obs_tl
    from h2o3_tpu.utils import log as _log
    spans, disk = _obs_rec.RECORDER.read_through(
        tid, _obs_tl.SPANS.trace_snapshot(tid))
    seen = {(s.get("host"), s.get("id")) for s in spans}
    logs = _log.trace_records(tid)
    seen_logs = {(r.get("host"), r.get("id")) for r in logs}
    hosts = [{"host": _obs_tl.host_id(), "n_spans": len(spans),
              "from_disk": disk}]
    bc = getattr(h.server, "broadcaster", None)
    if bc is not None:
        for i, remote in enumerate(bc.collect(f"trace:{tid}",
                                              timeout=_collect_timeout())):
            if isinstance(remote, dict):
                # dedup against what the shared-ice_root disk read already
                # loaded: a worker's collect reply re-reads the same
                # segments its own recorder wrote
                rs = [s for s in remote.get("spans", [])
                      if (s.get("host"), s.get("id")) not in seen]
                seen.update((s.get("host"), s.get("id")) for s in rs)
                spans.extend(rs)
                rl = [r for r in remote.get("logs", [])
                      if (r.get("host"), r.get("id")) not in seen_logs]
                seen_logs.update((r.get("host"), r.get("id")) for r in rl)
                logs.extend(rl)
                hosts.append({"host": remote.get("host", i + 1),
                              "n_spans": len(rs)})
            else:
                hosts.append({"host": i + 1, "n_spans": None,
                              "lagging": True})
    spans.sort(key=lambda s: s.get("start") or 0.0)
    logs.sort(key=lambda r: r.get("t") or 0.0)
    h._send({"__meta": {"schema_type": "TraceV3"},
             "trace_id": tid, "spans": spans, "hosts": hosts,
             "n_spans": len(spans), "logs": logs, "n_logs": len(logs)})


def _h_traces(h: _Handler):
    """GET /3/Traces — flight-recorder trace search: the timeline ring
    plus the durable segments under ice_root, grouped into per-trace
    summaries. Filters: route= (substring of the rest.request route),
    name= (substring of any span name), status= ("error", a code, or
    "all"), min_ms= (min span duration), since=/until= (unix seconds on
    trace start), limit= (default 50)."""
    from h2o3_tpu.obs import recorder as _obs_rec
    from h2o3_tpu.obs import timeline as _obs_tl
    p = h._params()

    def _f(key):
        v = p.get(key)
        return float(v) if v not in (None, "") else None

    try:
        min_ms, since, until = _f("min_ms"), _f("since"), _f("until")
        limit = int(p.get("limit") or 50)
    except ValueError:
        # a client typo is a 400, never a 5xx: a 500 here would itself be
        # tail-retained as an error trace and burn the availability SLO
        return h._error("min_ms/since/until/limit must be numeric", 400)
    out = _obs_rec.RECORDER.search(
        name=p.get("name") or None, route=p.get("route") or None,
        status=p.get("status") or None, min_ms=min_ms,
        since=since, until=until, limit=limit,
        extra_spans=_obs_tl.SPANS.snapshot())
    h._send({"__meta": {"schema_type": "TracesV3"},
             "traces": out, "n_traces": len(out),
             "recorder_bytes": _obs_rec.RECORDER.disk_bytes()})


def _h_alerts(h: _Handler):
    """GET /3/Alerts — the SLO engine's live view: declared specs, fresh
    burn rates (an evaluate() runs on every call, so the response never
    trails the background period), and per-SLO alert states with the
    episode trace id each firing recorded."""
    from h2o3_tpu.obs import slo as _slo
    alerts = _slo.ENGINE.evaluate()
    h._send({"__meta": {"schema_type": "AlertsV3"},
             "slos": [s.to_dict() for s in _slo.ENGINE.specs()],
             "alerts": alerts,
             "firing": [a["slo"] for a in alerts if a.get("firing")]})


def _h_usage(h: _Handler):
    """GET /3/Usage — the per-tenant/per-model cost table: device-second
    attribution from the dispatch-funnel ledger plus HBM occupancy
    (ParamStore placements, tier-pager budgets), merged cluster-wide over
    the `usage` collect op with the same lagging-host absorption as the
    federated /metrics scrape."""
    from h2o3_tpu.obs import usage as _us
    snaps = [_us.usage_snapshot()]
    lagging = []
    bc = getattr(h.server, "broadcaster", None)
    if bc is not None:
        for i, remote in enumerate(bc.collect("usage",
                                              timeout=_collect_timeout())):
            if isinstance(remote, dict):
                snaps.append(remote)
            else:
                lagging.append(i + 1)
    body = _us.merge_usage(snaps)
    body["__meta"] = {"schema_type": "UsageV3"}
    body["lagging_hosts"] = lagging
    h._send(body)


def _h_cloudhealth(h: _Handler):
    """GET /3/CloudHealth — one synthesized pressure document for the
    cloud (HPA external-metric shape: every dimension normalized so 1.0
    means saturated, merged as a max across hosts). A fresh evaluation
    runs on every call — the response never trails a background period —
    and refreshes the h2o3_pressure{dimension} gauges as a side effect."""
    from h2o3_tpu.obs import usage as _us
    snaps = [_us.evaluate_pressure()]
    lagging = []
    bc = getattr(h.server, "broadcaster", None)
    if bc is not None:
        for i, remote in enumerate(
                bc.collect("cloudhealth", timeout=_collect_timeout())):
            if isinstance(remote, dict):
                snaps.append(remote)
            else:
                lagging.append(i + 1)
    body = _us.merge_cloudhealth(snaps)
    body["__meta"] = {"schema_type": "CloudHealthV3"}
    body["lagging_hosts"] = lagging
    h._send(body)


def _h_model_monitor(h: _Handler, mid):
    """GET /3/ModelMonitor/{model} — baseline-vs-live distribution
    profiles and drift scores for one monitored model, merged
    cluster-wide over the `modelmon:` collect op: every host ships its
    integer count sketches, the coordinator folds them and scores ONCE
    over the sums, so host count and merge order never change a drift
    score bit-for-bit. Lagging workers are absorbed within the collect
    deadline like every other obs merge."""
    from h2o3_tpu.obs import modelmon as _mm
    snaps = [_mm.snapshot(mid)]
    lagging = []
    bc = getattr(h.server, "broadcaster", None)
    if bc is not None:
        for i, remote in enumerate(
                bc.collect(f"modelmon:{mid}",
                           timeout=_collect_timeout())):
            if isinstance(remote, dict):
                snaps.append(remote)
            elif remote is None:
                lagging.append(i + 1)
    body = _mm.merged_report(mid, [s for s in snaps if s is not None])
    if not body.get("monitored"):
        from h2o3_tpu.core.kvstore import DKV
        if DKV.get(mid) is None:
            return h._error(f"model {mid} not found", 404)
    body["__meta"] = {"schema_type": "ModelMonitorV3"}
    body["lagging_hosts"] = lagging
    h._send(body)


def _cluster_metric_snapshots(h: _Handler):
    """[(host, registry-snapshot)] for every answering host, local first.
    A lagging worker is absorbed within the collect deadline: its slot is
    skipped, counted in h2o3_cluster_scrape_timeouts_total and reported
    in the second return value."""
    from h2o3_tpu.obs import metrics as _obs_m
    from h2o3_tpu.obs import timeline as _obs_tl
    snaps = [(_obs_tl.host_id(), _obs_m.REGISTRY.to_dict())]
    lagging = []
    bc = getattr(h.server, "broadcaster", None)
    if bc is not None:
        for i, remote in enumerate(bc.collect("metrics",
                                              timeout=_collect_timeout())):
            if isinstance(remote, dict) \
                    and isinstance(remote.get("metrics"), dict):
                snaps.append((remote.get("host", i + 1), remote["metrics"]))
            else:
                _obs_m.CLUSTER_SCRAPE_TIMEOUTS.inc()
                lagging.append(i + 1)
    return snaps, lagging


def _h_metrics(h: _Handler):
    """GET /metrics — Prometheus text exposition of the process registry.
    `?scope=cluster` federates: every host's snapshot is collected over
    the replay channel and merged under a per-host host= label (counters/
    histograms stay summable; gauges stay per-host). When the scraper
    negotiates OpenMetrics (Accept: application/openmetrics-text, or
    ?format=openmetrics), the single-host body carries histogram
    EXEMPLARS — the trace ids latency observations recorded — which
    Prometheus stores under --enable-feature=exemplar-storage; the
    cluster merge propagates them too (host-tagged), so click-through
    works on the federated scrape as well as the per-host one."""
    from h2o3_tpu.obs import metrics as _obs_m
    _obs_m.install_runtime_gauges()
    p = h._params()
    ctype = "text/plain; version=0.0.4; charset=utf-8"
    openmetrics = "openmetrics" in (h.headers.get("Accept") or "") \
        or p.get("format") == "openmetrics"
    if p.get("scope") == "cluster":
        snaps, _ = _cluster_metric_snapshots(h)
        if openmetrics:
            body = _obs_m.cluster_openmetrics_text(snaps).encode()
            ctype = ("application/openmetrics-text; version=1.0.0; "
                     "charset=utf-8")
        else:
            body = _obs_m.cluster_prometheus_text(snaps).encode()
    elif openmetrics:
        body = _obs_m.REGISTRY.openmetrics_text().encode()
        ctype = "application/openmetrics-text; version=1.0.0; charset=utf-8"
    else:
        body = _obs_m.REGISTRY.prometheus_text().encode()
    h.send_response(200)
    h.send_header("Content-Type", ctype)
    h.send_header("Content-Length", str(len(body)))
    h.end_headers()
    if getattr(h, "command", "") != "HEAD":
        h.wfile.write(body)


def _h_watermeter(h: _Handler):
    """GET /3/WaterMeter — the registry as JSON (WaterMeterCpuTicks/
    WaterMeterIo's REST shape, generalized to the whole registry).
    `?cluster=1` answers for the whole cloud: per-host snapshots merged
    with host= labels, lagging hosts listed instead of waited on."""
    from h2o3_tpu.obs import metrics as _obs_m
    _obs_m.install_runtime_gauges()
    p = h._params()
    if str(p.get("cluster", "")).lower() in ("1", "true", "yes"):
        snaps, lagging = _cluster_metric_snapshots(h)
        h._send({"__meta": {"schema_type": "WaterMeterV3"},
                 "metrics": _obs_m.merge_cluster_snapshots(snaps),
                 "hosts": [hst for hst, _ in snaps],
                 "lagging_hosts": lagging})
        return
    h._send({"__meta": {"schema_type": "WaterMeterV3"},
             "metrics": _obs_m.REGISTRY.to_dict()})


def _h_profiler(h: _Handler):
    """POST /3/Profiler — on-demand profiling (ProfilerHandler analog):
    action=start [kind=auto|jax|sampling] [trace_dir=...] starts a
    capture (jax.profiler device trace, or the pure-Python sampling
    fallback when unavailable); action=stop ends it and returns the
    artifact dir. One session at a time — a concurrent start answers
    409.

    `cluster=1` fans the action out over the replay channel: every
    worker starts/stops its OWN session, stop gathers each host's
    sampling flamegraph within the collect deadline (a stalled host is
    listed in lagging_hosts, never waited on), and the collapsed stacks
    merge into ONE host-prefixed pyprof.merged.collapsed under the
    coordinator's artifact dir (the local raw capture stays intact)."""
    from h2o3_tpu.obs import profiler as _prof
    from h2o3_tpu.obs import timeline as _obs_tl
    p = h._params()
    action = str(p.get("action") or "").lower()
    cluster = str(p.get("cluster", "")).lower() in ("1", "true", "yes")
    bc = getattr(h.server, "broadcaster", None)
    kind = str(p.get("kind") or "auto")
    try:
        if action == "start":
            out = _prof.PROFILER.start(trace_dir=p.get("trace_dir") or None,
                                       kind=kind)
        elif action == "stop":
            out = _prof.PROFILER.stop()
        else:
            return h._error("action must be start|stop", 400)
    except _prof.ProfilerBusy as ex:
        return h._error(str(ex), 409)
    except _prof.ProfilerIdle as ex:
        if not (cluster and bc is not None and action == "stop"):
            return h._error(str(ex), 400)
        # a locally-dead session (out-of-band stop, coordinator restart)
        # must not strand the workers' sessions sampling forever — fan
        # the stop out anyway and answer with their artifacts
        out = {"status": "idle", "error": str(ex)}
    except ValueError as ex:
        return h._error(str(ex), 400)
    if cluster and bc is not None:
        op = f"profiler:start:{kind}" if action == "start" \
            else "profiler:stop"
        hosts = [{"host": _obs_tl.host_id(), **out}]
        lagging = []
        parts = []      # (host, collapsed_text) for the merged flamegraph
        if action == "stop" and out.get("artifact"):
            parts.append((_obs_tl.host_id(),
                          _prof.read_collapsed(out["artifact"])))
        for i, remote in enumerate(bc.collect(op,
                                              timeout=_collect_timeout())):
            if isinstance(remote, dict):
                if remote.get("collapsed"):
                    parts.append((remote.get("host", i + 1),
                                  remote["collapsed"]))
                hosts.append({k: v for k, v in remote.items()
                              if k != "collapsed"})
            else:
                lagging.append(i + 1)
        out = dict(out, hosts=hosts, lagging_hosts=lagging)
        if action == "stop" and parts:
            dest = out.get("dir")
            if not dest:        # local session was idle: workers' artifacts
                import tempfile  # still need a home for the merge
                dest = out["dir"] = tempfile.mkdtemp(prefix="h2o3-profile-")
            merged = _prof.merge_collapsed(parts, dest)
            if merged:
                out["merged_flamegraph"] = merged
    h._send({"__meta": {"schema_type": "ProfilerV3"}, **out})


# (GET /3/Profiler lives in routes_ext4: the legacy JProfile one-shot
# stack sample, now merged with PROFILER.status() so the same GET reports
# whether an on-demand session is running.)


def _h_metadata_endpoints(h: _Handler):
    """/3/Metadata/endpoints — SchemaServer.java analog: live route
    metadata that client-bindings codegen consumes."""
    routes = []
    for pat, m, fn in ROUTES:
        routes.append({
            "url_pattern": pat.pattern,
            "http_method": m,
            "handler_method": fn.__name__,
            "summary": (fn.__doc__ or "").strip().split("\n")[0],
        })
    h._send({"__meta": {"schema_type": "EndpointsListV3"},
             "routes": routes, "num_routes": len(routes)})


ROUTES = [
    (re.compile(r"/3/Cloud"), "GET", _h_cloud),
    (re.compile(r"/3/Cloud/drain"), "POST", _h_cloud_drain),
    (re.compile(r"/3/About"), "GET", _h_about),
    (re.compile(r"/3/ImportFiles"), "GET", _h_import),
    (re.compile(r"/3/ParseSetup"), "POST", _h_parse_setup),
    (re.compile(r"/3/Parse"), "POST", _h_parse),
    (re.compile(r"/3/ParseDistributed"), "POST", _h_parse_distributed),
    (re.compile(r"/3/Frames"), "GET", _h_frames),
    (re.compile(r"/3/Frames/([^/]+)"), "GET", _h_frame),
    (re.compile(r"/3/Frames/([^/]+)"), "DELETE", _h_frame_delete),
    (re.compile(r"/3/ModelBuilders"), "GET", _h_model_builders),
    (re.compile(r"/3/ModelBuilders/([^/]+)"), "POST", _h_build_model),
    (re.compile(r"/99/ModelBuilders/([^/]+)"), "POST", _h_build_model),
    (re.compile(r"/3/Models"), "GET", _h_models),
    (re.compile(r"/3/Models/([^/]+)"), "GET", _h_model),
    (re.compile(r"/3/Models/([^/]+)"), "DELETE", _h_model_delete),
    (re.compile(r"/3/Predictions/models/([^/]+)/frames/([^/]+)"), "POST",
     _h_predict),
    (re.compile(r"/3/Predictions/models/([^/]+)"), "POST", _h_predict_rows),
    (re.compile(r"/3/Jobs"), "GET", _h_jobs),
    (re.compile(r"/3/Jobs/([^/]+)"), "GET", _h_job),
    (re.compile(r"/99/Rapids"), "POST", _h_rapids),
    (re.compile(r"/3/ModelMetrics/models/([^/]+)/frames/([^/]+)"), "POST",
     _h_model_metrics),
    (re.compile(r"/3/ModelMetrics/models/([^/]+)/frames/([^/]+)"), "GET",
     _h_model_metrics),
    (re.compile(r"/3/ModelMetrics/models/([^/]+)"), "GET", _h_model_metrics),
    (re.compile(r"/99/Grids"), "GET", _h_grids),
    (re.compile(r"/99/Grids/([^/]+)"), "GET", _h_grid),
    (re.compile(r"/99/AutoMLBuilder"), "POST", _h_automl_build),
    (re.compile(r"/99/AutoML/([^/]+)"), "GET", _h_automl),
    (re.compile(r"/3/Logs"), "GET", _h_logs_search),
    (re.compile(r"/3/Logs/download"), "GET", _h_logs_download),
    (re.compile(r"/3/Logs/nodes/([^/]+)/files/([^/]+)"), "GET",
     _h_logs_node_file),
    (re.compile(r"/3/JStack"), "GET", _h_jstack),
    (re.compile(r"/3/Timeline"), "GET", _h_timeline),
    (re.compile(r"/3/Trace/([^/]+)"), "GET", _h_trace),
    (re.compile(r"/3/Traces"), "GET", _h_traces),
    (re.compile(r"/3/Alerts"), "GET", _h_alerts),
    (re.compile(r"/3/Usage"), "GET", _h_usage),
    (re.compile(r"/3/CloudHealth"), "GET", _h_cloudhealth),
    (re.compile(r"/3/ModelMonitor/([^/]+)"), "GET", _h_model_monitor),
    (re.compile(r"/metrics"), "GET", _h_metrics),
    (re.compile(r"/3/WaterMeter"), "GET", _h_watermeter),
    (re.compile(r"/3/Profiler"), "POST", _h_profiler),
    (re.compile(r"/3/Metadata/endpoints"), "GET", _h_metadata_endpoints),
    (re.compile(r"/3/InitID"), "GET", _h_init_session),
    (re.compile(r"/3/InitID"), "DELETE", _h_end_session),
    (re.compile(r"/3/Shutdown"), "POST", _h_shutdown),
]

# extended surface (frame munging, diagnostics, artifacts, validation —
# RequestServer.java:76 registers ~150 routes; the long tail lives there)
from h2o3_tpu.api import routes_ext as _ext  # noqa: E402

ROUTES += _ext.build_routes()

from h2o3_tpu.api import routes_ext2 as _ext2  # noqa: E402

ROUTES += _ext2.build_routes()

from h2o3_tpu.api import routes_ext3 as _ext3  # noqa: E402

ROUTES += _ext3.build_routes()

from h2o3_tpu.api import routes_ext4 as _ext4  # noqa: E402

ROUTES += _ext4.build_routes()

# Flow-lite UI (h2o-web analog) at / and /flow/index.html
from h2o3_tpu.api import flow as _flow  # noqa: E402

ROUTES += [
    (re.compile(r"/"), "GET", _flow.h_flow),
    (re.compile(r"/flow/index\.html"), "GET", _flow.h_flow),
    (re.compile(r"/flow/notebook\.html"), "GET", _flow.h_notebook),
]


class H2OServer:
    """Controller-side API server (h2o.init() + jetty in one).

    Security (H2OSecurityManager / h2o-security analog for a
    single-controller runtime):
      * auth: {user: password} dict or a "user:password"-lines file path
        (-basic_auth / realm.properties) — enforced on every route with a
        constant-time compare.
      * ssl_cert/ssl_key: PEM pair → serve HTTPS (-jks/-ssl internode;
        there is no internode traffic here — ICI transfers never leave
        the pod — so TLS terminates at the one REST boundary).
    Config-file equivalents: ai.h2o.api.auth_file / ssl_cert / ssl_key
    via utils/config properties.
    """

    def __init__(self, port: int = 54321, auth=None, ssl_cert=None,
                 ssl_key=None, host: str | None = None):
        from h2o3_tpu.utils import config as _cfg
        # loopback by default (local dev); deployments bind all interfaces
        # (deploy/multihost serve + ai.h2o.api.bind_all property)
        if host is None:
            host = "0.0.0.0" if _cfg.get_bool("api.bind_all") \
                else "127.0.0.1"
        if host not in ("127.0.0.1", "localhost", "::1"):
            # binding beyond loopback without credentials exposes the
            # whole modeling surface; require auth unless explicitly
            # waived (the reference's -hash_login posture)
            import os as _os
            has_auth = (auth
                        or _cfg.get_property("api.auth_file", None)
                        or str(_cfg.get_property("api.auth_method", "")
                               or "").lower() in ("ldap", "custom"))
            if not has_auth and \
                    not _env.env_bool("H2O3_INSECURE_BIND_ALL", False):
                raise RuntimeError(
                    f"refusing to bind {host} without authentication: "
                    "configure -basic_auth/ai.h2o.api.auth_file, "
                    "api.auth_method=ldap|custom, or set "
                    "H2O3_INSECURE_BIND_ALL=1 to waive")
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        auth = auth if auth is not None else \
            _cfg.get_property("api.auth_file", None)
        if isinstance(auth, str):
            creds = {}
            with open(auth) as fh:
                for line in fh:
                    line = line.strip()
                    if line and not line.startswith("#"):
                        u, _, p = line.partition(":")
                        creds[u] = p
            auth = creds
        from h2o3_tpu.utils import auth as _auth
        if auth:
            # explicit caller credentials win over the configured method
            self.httpd.authenticator = _auth.BasicAuthenticator(auth)
        else:
            self.httpd.authenticator = _auth.resolve_authenticator(None)
        ssl_cert = ssl_cert or _cfg.get_property("api.ssl_cert", None)
        ssl_key = ssl_key or _cfg.get_property("api.ssl_key", None)
        if ssl_cert and ssl_key:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(ssl_cert, ssl_key)
            self.httpd.socket = ctx.wrap_socket(self.httpd.socket,
                                                server_side=True)
        self.port = self.httpd.server_address[1]
        self.thread: threading.Thread | None = None

    def start(self, background=True):
        h2o3_tpu.cloud()  # form the device mesh before serving
        from h2o3_tpu.obs import metrics as _obs_m
        _obs_m.install_runtime_gauges()
        # env-gated runtime sanitizers (H2O3_DEBUG_NANS,
        # H2O3_TRANSFER_GUARD) — no-op unless a deployment flips them
        from h2o3_tpu.analysis import sanitizers as _san
        _san.install_from_env()
        # SLO engine: load H2O3_SLO_FILE specs and start the background
        # burn-rate evaluator (idle when the env is unset)
        from h2o3_tpu.obs import slo as _slo
        _slo.install_from_env()
        # stall watchdog: start the sentinel and hand it the cluster
        # fan-out (read dynamically — the multihost bootstrap and the
        # test harness both attach the broadcaster around start())
        from h2o3_tpu.obs import watchdog as _wd

        def _wd_collect(op, timeout):
            bc = getattr(self.httpd, "broadcaster", None)
            return bc.collect(op, timeout=timeout) if bc is not None \
                else []

        _wd.WATCHDOG.set_collector(_wd_collect)
        _wd.WATCHDOG.start()
        if background:
            self.thread = threading.Thread(target=self.httpd.serve_forever,
                                           daemon=True, name="h2o3-rest")
            self.thread.start()
        else:
            self.httpd.serve_forever()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def start_server(port: int = 54321) -> H2OServer:
    return H2OServer(port).start()


if __name__ == "__main__":
    import sys
    port = int(sys.argv[1]) if len(sys.argv) > 1 else 54321
    from h2o3_tpu.utils import log as _ulog
    _ulog.info("h2o3-tpu REST server on :%s", port)
    H2OServer(port).start(background=False)
