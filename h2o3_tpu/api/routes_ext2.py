"""REST long tail, part 2 — closing toward RequestServer.java's ~150-route
surface (water/api/RequestServer.java:75-80). Families here: frame
introspection (light/domain/chunks), job control, model-artifact and
model-construction routes (MakeGLMModel, GLMRegPath, DataInfoFrame),
NodePersistentStorage (Flow's clip store), segment-model builders,
Tabulate, leaderboards, metrics-from-predictions, v4 experimental info
routes, and the loud-reject Hadoop/Hive/decryption surface.

Handlers duck-type routes_ext.py's contract (h._send/_error/_params)."""

from __future__ import annotations

import json
import os
import re
import time

import numpy as np

from h2o3_tpu.core.frame import Frame, Vec, rebalance_frame
from h2o3_tpu.core.jobs import Job
from h2o3_tpu.core.kvstore import DKV


# ===========================================================================
# Frames family
def _h_frame_light(h, key):
    """FramesHandler.fetchLight (GET /3/Frames/{id}/light): metadata only —
    no column data, the cheap poll Flow uses."""
    f = DKV.get(key)
    if not isinstance(f, Frame):
        return h._error(f"frame {key} not found", 404)
    h._send({"__meta": {"schema_type": "FramesListV3"},
             "frames": [{"frame_id": {"name": key}, "rows": f.nrows,
                         "columns": f.ncols,
                         "byte_size": sum(v.padded_len * 4
                                          for v in f.vecs),
                         "is_text": False}]})


def _h_frame_col_domain(h, key, col):
    """GET /3/Frames/{id}/columns/{col}/domain (FramesHandler.columnDomain)."""
    f = DKV.get(key)
    if not isinstance(f, Frame):
        return h._error(f"frame {key} not found", 404)
    if col not in f.names:
        return h._error(f"column {col} not found", 404)
    v = f.vec(col)
    h._send({"__meta": {"schema_type": "FrameV3"},
             "domain": [v.levels()],
             "cardinality": v.cardinality if v.type == "enum" else 0})


def _h_frame_chunks(h, key):
    """GET /3/FrameChunks/{id} (FrameChunksHandler): per-shard row layout —
    the chunk-distribution view, with mesh shards standing in for nodes."""
    from h2o3_tpu.parallel import mesh as MESH
    f = DKV.get(key)
    if not isinstance(f, Frame):
        return h._error(f"frame {key} not found", 404)
    cl = MESH.cloud()
    shards = max(1, cl.n_rows_shards if hasattr(cl, "n_rows_shards")
                 else cl.n_devices)
    per = -(-f.padded_len // shards)
    chunks = [{"chunk_id": i, "node_idx": i,
               "row_count": max(0, min(per, f.nrows - i * per))}
              for i in range(shards)]
    h._send({"__meta": {"schema_type": "FrameChunksV3"},
             "frame_id": {"name": key}, "chunks": chunks})


def _h_frames_delete_all(h):
    """DELETE /3/Frames (FramesHandler.deleteAll)."""
    n = 0
    for k in list(DKV.keys()):
        if isinstance(DKV.get(k), Frame):
            DKV.remove(k)
            n += 1
    h._send({"__meta": {"schema_type": "FramesListV3"}, "deleted": n})


def _h_models_delete_all(h):
    """DELETE /3/Models (ModelsHandler.deleteAll)."""
    from h2o3_tpu.models.model import ModelBase
    n = 0
    for k in list(DKV.keys()):
        if isinstance(DKV.get(k), ModelBase):
            DKV.remove(k)
            n += 1
    h._send({"__meta": {"schema_type": "ModelsV3"}, "deleted": n})


def _h_rebalance(h):
    """POST /3/Rebalance (RebalanceDataSet.java): re-shard a frame against
    the current cloud layout."""
    p = h._params()
    f = DKV.get(p.get("dataset") or p.get("frame"))
    if not isinstance(f, Frame):
        return h._error("dataset not found", 404)
    dest = p.get("dest") or DKV.make_key("rebalanced")
    out = rebalance_frame(f, key=dest)
    DKV.put(dest, out)
    h._send({"__meta": {"schema_type": "RebalanceV3"},
             "dest": {"name": dest}})


def _h_find(h):
    """GET /3/Find (FindHandler): locate a value in a frame column."""
    p = h._params()
    f = DKV.get(p.get("key") or p.get("frame"))
    if not isinstance(f, Frame):
        return h._error("frame not found", 404)
    col = p.get("column")
    if col not in f.names:
        return h._error(f"column {col} not found", 404)
    row = int(p.get("row") or 0)
    match = p.get("match")
    v = f.vec(col)
    n = f.nrows
    if v.type == "enum":
        dom = v.levels() or []
        x = v.to_numpy()[:n]
        vals = [None if xx != xx else dom[int(xx)] for xx in x]
        hits = [i for i in range(row, n) if vals[i] == match]
    elif v.type == "str":
        vals = v.host_data[:n]
        hits = [i for i in range(row, n) if vals[i] == match]
    else:
        x = v.to_numpy()[:n]
        if match is None or match in ("", "NA", "nan"):
            hits = np.nonzero(np.isnan(x[row:]))[0] + row
        else:
            hits = np.nonzero(x[row:] == float(match))[0] + row
        hits = hits.tolist()
    h._send({"__meta": {"schema_type": "FindV3"},
             "prev": -1, "next": int(hits[0]) if len(hits) else -1})


# ===========================================================================
# Jobs
def _h_job_cancel(h, key):
    """POST /3/Jobs/{id}/cancel (JobsHandler.cancel): cooperative stop."""
    j = DKV.get(key)
    if not isinstance(j, Job):
        return h._error(f"job {key} not found", 404)
    j.stop()
    h._send({"__meta": {"schema_type": "JobsV3"}, "jobs": [j.to_dict()]})


# ===========================================================================
# Model construction / artifacts
def _h_make_glm_model(h):
    """POST /3/MakeGLMModel (MakeGLMModelHandler): build a scoring-only GLM
    from an existing model's structure + user-supplied coefficients."""
    p = h._params()
    src = DKV.get(p.get("model"))
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
    if not isinstance(src, H2OGeneralizedLinearEstimator):
        return h._error("model must be an existing GLM", 400)
    names = p.get("names")
    names = json.loads(names) if isinstance(names, str) else names
    beta = p.get("beta")
    beta = json.loads(beta) if isinstance(beta, str) else beta
    import copy
    dst = copy.copy(src)
    dst._coefficients = dict(src._coefficients)
    for nm, b in zip(names or [], beta or []):
        if nm in dst._coefficients or nm == "Intercept":
            dst._coefficients[nm] = float(b)
    # rebuild the packed beta in feature order
    feats = src._dinfo.feature_names
    dst._beta = np.array([dst._coefficients.get(f, 0.0) for f in feats]
                         + [dst._coefficients.get("Intercept", 0.0)])
    dest = p.get("dest") or DKV.make_key("glm_custom")
    dst.key = dest
    DKV.put(dest, dst)
    h._send({"__meta": {"schema_type": "GLMModelV3"},
             "model_id": {"name": dest}})


def _h_glm_reg_path(h):
    """GET /3/GetGLMRegPath (GLMRegularizationPath): the lambda-search
    path of a trained GLM."""
    p = h._params()
    m = DKV.get(p.get("model"))
    path = getattr(m, "_lambda_path", None)
    if path is None:
        return h._error(
            "model has no regularization path (train with "
            "lambda_search=True)", 400)
    feats = m._dinfo.feature_names + ["Intercept"]
    h._send({"__meta": {"schema_type": "GLMRegularizationPathV3"},
             "lambdas": [float(lam) for lam, _ in path],
             "coefficient_names": feats,
             "coefficients": [[float(b) for b in beta]
                              for _, beta in path]})


def _h_data_info_frame(h):
    """POST /99/DataInfoFrame (hex/schemas DataInfoFrame): materialize the
    expanded (one-hot / standardized / interactions) design matrix as a
    frame — what the GLM MOJO pipeline tests consume."""
    p = h._params()
    f = DKV.get(p.get("frame"))
    if not isinstance(f, Frame):
        return h._error("frame not found", 404)
    from h2o3_tpu.models.model import DataInfo
    inter = p.get("interactions")
    inter = json.loads(inter) if isinstance(inter, str) else inter
    std = str(p.get("standardize", "false")).lower() == "true"
    use_all = str(p.get("use_all", "true")).lower() == "true"
    y = p.get("response_column")
    x = [c for c in f.names if c != y]
    di = DataInfo(f, x, y, cat_mode="onehot", standardize=std,
                  interactions=inter)
    M = np.asarray(di.matrix(f))[: f.nrows]
    dest = p.get("dest") or DKV.make_key("datainfo")
    out = Frame(di.feature_names,
                [Vec.from_numpy(M[:, j])
                 for j in range(M.shape[1])], key=dest)
    DKV.put(dest, out)
    h._send({"__meta": {"schema_type": "DataInfoFrameV3"},
             "result": {"name": dest},
             "num_features": di.n_features})


def _h_mojo_export(h, key):
    """POST /99/Models.mojo/{id} (ModelsHandler.exportMojo): write the
    MOJO artifact to a server-side path."""
    from h2o3_tpu.models.model import ModelBase
    m = DKV.get(key)
    if not isinstance(m, ModelBase):
        return h._error(f"model {key} not found", 404)
    p = h._params()
    d = p.get("dir") or "."
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{key}.zip")
    from h2o3_tpu.genmodel import mojo as MJ
    MJ.export_mojo(m, path)
    h._send({"__meta": {"schema_type": "ModelExportV3"},
             "dir": path})


def _h_pojo_preview(h, key):
    """GET /3/Models.java/{id}/preview: first lines of the POJO source."""
    from h2o3_tpu.models.model import ModelBase
    m = DKV.get(key)
    if not isinstance(m, ModelBase):
        return h._error(f"model {key} not found", 404)
    import tempfile
    from h2o3_tpu.genmodel import pojo as PJ
    with tempfile.TemporaryDirectory() as td:
        src = open(PJ.export_pojo(m, td)).read()
    h._send({"__meta": {"schema_type": "ModelPreviewV3"},
             "preview": "\n".join(src.split("\n")[:64])})


# ===========================================================================
# metrics from external predictions (ModelMetricsMakerHandler)
def _h_metrics_maker(h, pred_key, act_key):
    """POST /3/ModelMetrics/predictions_frame/{p}/actuals_frame/{a}:
    compute metrics from a predictions frame + actuals frame (the
    h2o.make_metrics API)."""
    pf, af = DKV.get(pred_key), DKV.get(act_key)
    if not isinstance(pf, Frame) or not isinstance(af, Frame):
        return h._error("predictions/actuals frame not found", 404)
    from h2o3_tpu.models import metrics as M
    import jax.numpy as jnp
    n = af.nrows
    y = af.vecs[0]
    w = jnp.ones(y.padded_len, jnp.float32) \
        .at[n:].set(0.0)
    p = h._params()
    domain = p.get("domain")
    domain = json.loads(domain) if isinstance(domain, str) else domain
    if y.type == "enum" or domain:
        dom = domain or y.levels()
        yj = jnp.nan_to_num(y.as_f32())
        # predictions frame: p1 column (binomial convention: last col)
        pj = jnp.clip(jnp.nan_to_num(pf.vecs[-1].as_f32()), 1e-10,
                      1 - 1e-10)
        mm = M.binomial_metrics(yj, pj, w, domain=dom)
    else:
        mm = M.regression_metrics(jnp.nan_to_num(y.as_f32()),
                                  jnp.nan_to_num(pf.vecs[0].as_f32()), w)
    h._send({"__meta": {"schema_type": "ModelMetricsListSchemaV3"},
             "model_metrics": [mm.to_dict()]})


# ===========================================================================
# NodePersistentStorage (Flow's named-clip store)
def _nps_dir():
    d = os.path.join(os.path.expanduser("~"), ".h2o3_tpu", "nps")
    os.makedirs(d, exist_ok=True)
    return d


_NPS_SAFE = re.compile(r"[^A-Za-z0-9_.-]")


def _nps_path(categ, name=None):
    categ = _NPS_SAFE.sub("_", categ)
    d = os.path.join(_nps_dir(), categ)
    if name is None:
        return d
    return os.path.join(d, _NPS_SAFE.sub("_", name))


def _h_nps_configured(h):
    h._send({"__meta": {"schema_type": "NodePersistentStorageV3"},
             "configured": True})


def _h_nps_put(h, categ, name):
    """POST /3/NodePersistentStorage/{categ}/{name}."""
    p = h._params()
    os.makedirs(_nps_path(categ), exist_ok=True)
    with open(_nps_path(categ, name), "w") as fh:
        fh.write(p.get("value", ""))
    h._send({"__meta": {"schema_type": "NodePersistentStorageV3"},
             "category": categ, "name": name})


def _h_nps_get(h, categ, name):
    path = _nps_path(categ, name)
    if not os.path.exists(path):
        return h._error(f"NPS {categ}/{name} not found", 404)
    with open(path) as fh:
        val = fh.read()
    h._send({"__meta": {"schema_type": "NodePersistentStorageV3"},
             "category": categ, "name": name, "value": val})


def _h_nps_list(h, categ):
    d = _nps_path(categ)
    entries = []
    if os.path.isdir(d):
        for nm in sorted(os.listdir(d)):
            st = os.stat(os.path.join(d, nm))
            entries.append({"name": nm, "size": st.st_size,
                            "timestamp_millis": int(st.st_mtime * 1000)})
    h._send({"__meta": {"schema_type": "NodePersistentStorageV3"},
             "category": categ, "entries": entries})


def _h_nps_delete(h, categ, name):
    path = _nps_path(categ, name)
    if os.path.exists(path):
        os.unlink(path)
    h._send({"__meta": {"schema_type": "NodePersistentStorageV3"},
             "category": categ, "name": name})


# ===========================================================================
# Segment models (POST /99/SegmentModelsBuilders/{algo})
def _h_segment_build(h, algo):
    from h2o3_tpu.models import segments as SEG
    from h2o3_tpu.models import ESTIMATORS
    if algo not in ESTIMATORS:
        return h._error(f"unknown algo {algo}", 404)
    p = h._params()
    f = DKV.get(p.get("training_frame"))
    if not isinstance(f, Frame):
        return h._error("training_frame not found", 404)
    seg_cols = p.get("segment_columns") or p.get("segments")
    seg_cols = json.loads(seg_cols) if isinstance(seg_cols, str) else seg_cols
    y = p.get("response_column")
    params = {k: _coerce(v) for k, v in p.items()
              if k not in ("training_frame", "segment_columns", "segments",
                           "response_column", "dest")}
    sm = SEG.train_segments(ESTIMATORS[algo], params, seg_cols,
                            y=y, training_frame=f)
    dest = p.get("dest") or DKV.make_key("segment_models")
    DKV.put(dest, sm)
    h._send({"__meta": {"schema_type": "SegmentModelsV3"},
             "key": {"name": dest}, "n_segments": len(sm)})


def _h_segment_get(h, key):
    from h2o3_tpu.models import segments as SEG
    sm = DKV.get(key)
    if not isinstance(sm, SEG.SegmentModels):
        return h._error(f"segment models {key} not found", 404)
    h._send({"__meta": {"schema_type": "SegmentModelsV3"},
             "key": {"name": key},
             "segments": [
                 {k: (v if not hasattr(v, "key") else str(v.key))
                  for k, v in row.items()} for row in sm.as_list()]})


def _coerce(v):
    if isinstance(v, str):
        low = v.lower()
        if low in ("true", "false"):
            return low == "true"
        try:
            return int(v)
        except ValueError:
            pass
        try:
            return float(v)
        except ValueError:
            pass
        if v.startswith(("[", "{")):
            try:
                return json.loads(v)
            except json.JSONDecodeError:
                pass
    return v


# ===========================================================================
# Tabulate (POST /99/Tabulate — hex/Tabulate.java: 2-D preview aggregation)
def _h_tabulate(h):
    p = h._params()
    f = DKV.get(p.get("dataset") or p.get("frame"))
    if not isinstance(f, Frame):
        return h._error("dataset not found", 404)
    cp, cr = p.get("predictor"), p.get("response")
    if cp not in f.names or cr not in f.names:
        return h._error("predictor/response column not found", 400)
    nbins = int(p.get("nbins_predictor") or 20)
    n = f.nrows
    vx, vy = f.vec(cp), f.vec(cr)
    x = vx.to_numpy()[:n]
    y = vy.to_numpy()[:n]
    ok = ~(np.isnan(x) | np.isnan(y))
    x, y = x[ok], y[ok]
    if vx.type == "enum":
        edges = None
        codes = x.astype(int)
        labels = vx.levels()
    else:
        lo, hi = float(x.min()), float(x.max())
        edges = np.linspace(lo, hi, nbins + 1)
        codes = np.clip(np.digitize(x, edges) - 1, 0, nbins - 1)
        labels = [f"[{edges[i]:.4g},{edges[i+1]:.4g})"
                  for i in range(nbins)]
    counts = np.bincount(codes, minlength=len(labels)).astype(float)
    sums = np.bincount(codes, weights=y, minlength=len(labels))
    means = np.divide(sums, counts, out=np.zeros_like(sums),
                      where=counts > 0)
    h._send({"__meta": {"schema_type": "TabulateV3"},
             "count_table": {"labels": list(labels),
                             "counts": counts.tolist()},
             "response_table": {"labels": list(labels),
                                "means": means.tolist()}})


# ===========================================================================
# Leaderboards (GET /99/Leaderboards[/{automl_id}])
def _h_leaderboards(h, aml_id=None):
    from h2o3_tpu.automl.automl import H2OAutoML
    boards = []
    for k in DKV.keys():
        o = DKV.get(k)
        if isinstance(o, H2OAutoML) and (aml_id is None or k == aml_id):
            lb = o.leaderboard_obj
            boards.append({"project_name": getattr(o, "project_name", k),
                           "models": lb.as_list() if lb is not None
                           else []})
    if aml_id is not None and not boards:
        return h._error(f"AutoML {aml_id} not found", 404)
    h._send({"__meta": {"schema_type": "LeaderboardsV99"},
             "leaderboards": boards})


# ===========================================================================
# import/infra long tail
def _h_import_files_multi(h):
    """GET /3/ImportFilesMulti (ImportFilesMultiHandler): import a list of
    paths/folders through the distributed parse path."""
    p = h._params()
    paths = p.get("paths") or p.get("path")
    paths = json.loads(paths) if isinstance(paths, str) and \
        paths.startswith("[") else paths
    from h2o3_tpu.io import dparse
    try:
        files = dparse.expand_paths(paths)
    except FileNotFoundError as ex:
        return h._error(str(ex), 404)
    h._send({"__meta": {"schema_type": "ImportFilesMultiV3"},
             "files": files, "destination_frames": files})


def _h_decryption_setup(h):
    """POST /3/DecryptionSetup: encrypted-ingest keystore registration —
    fidelity loud-reject (water/parser/DecryptionTool.java)."""
    h._error("encrypted dataset ingest (DecryptionTool keystores) is not "
             "implemented in h2o3-tpu; decrypt files before import", 501)


def _h_import_hive(h):
    h._error("Hive table import requires a Hadoop/Hive deployment "
             "(h2o-hive); use JDBC-staged CSV/Parquet exports instead", 501)


def _h_export_hive(h):
    h._error("Hive table export requires a Hadoop/Hive deployment "
             "(h2o-hive); export to CSV/Parquet via /3/Frames/{id}/export "
             "instead", 501)


def _h_persist_s3(h):
    """POST /3/PersistS3 (PersistS3Handler): register S3 credentials for
    the URI loader."""
    p = h._params()
    from h2o3_tpu.utils import config as _cfg
    if p.get("secret_key_id"):
        _cfg.set_property("persist.s3.access_key", p["secret_key_id"])
    if p.get("secret_access_key"):
        _cfg.set_property("persist.s3.secret_key", p["secret_access_key"])
    if p.get("session_token"):
        _cfg.set_property("persist.s3.session_token", p["session_token"])
    h._send({"__meta": {"schema_type": "PersistS3V3"}, "status": "ok"})


def _h_steam_instances(h):
    """GET /3/steam/instances: Enterprise-Steam discovery stub — reports
    this cloud as the only instance (SteamHandler parity surface)."""
    import h2o3_tpu
    info = h2o3_tpu.cluster_info()
    h._send({"__meta": {"schema_type": "SteamV3"},
             "instances": [{"name": info["cloud_name"],
                            "status": "running",
                            "size": info["cloud_size"]}]})


def _h_kill_minus3(h):
    """GET /3/KillMinus3 (the SIGQUIT thread-dump analog): dump all stacks
    to the server log)."""
    import sys
    import threading
    import traceback
    from h2o3_tpu.utils import log as _log
    frames = sys._current_frames()
    for t in threading.enumerate():
        fr = frames.get(t.ident)
        if fr is not None:
            _log.info(f"--- thread {t.name} ---\n"
                      + "".join(traceback.format_stack(fr)))
    h._send({"__meta": {"schema_type": "KillMinus3V3"}, "dumped": True})


# ===========================================================================
# metadata / rapids / sessions / v4
def _h_metadata_schemas(h, name=None):
    """GET /3/Metadata/schemas[/{name}] (SchemaServer metadata)."""
    schemas = sorted({"CloudV3", "FrameV3", "FramesListV3", "JobsV3",
                      "ModelsV3", "ModelMetricsListSchemaV3", "RapidsV99",
                      "GridSearchV99", "AutoMLV99", "LeaderboardsV99",
                      "ParseV3", "ParseSetupV3", "SegmentModelsV3",
                      "TabulateV3", "H2OError"})
    if name:
        if name not in schemas:
            return h._error(f"schema {name} not found", 404)
        h._send({"__meta": {"schema_type": "MetadataV3"},
                 "schemas": [{"name": name, "version": 3}]})
    else:
        h._send({"__meta": {"schema_type": "MetadataV3"},
                 "schemas": [{"name": s, "version": 3} for s in schemas]})


def _h_metadata_endpoint(h, idx):
    """GET /3/Metadata/endpoints/{num-or-name}: by list index or by the
    handler name (the reference also resolves by route name)."""
    from h2o3_tpu.api import server as _srv
    if idx.isdigit():
        i = int(idx)
        if not (0 <= i < len(_srv.ROUTES)):
            return h._error(f"endpoint {i} out of range", 404)
    else:
        hits = [k for k, (p0, m0, f0) in enumerate(_srv.ROUTES)
                if f0.__name__.lstrip("_") == idx.lstrip("_")]
        if not hits:
            return h._error(f"endpoint {idx} not found", 404)
        i = hits[0]
    pat, m, fn = _srv.ROUTES[i]
    h._send({"__meta": {"schema_type": "EndpointV3"},
             "url_pattern": pat.pattern, "http_method": m,
             "handler_method": fn.__name__,
             "summary": (fn.__doc__ or "").strip().split("\n")[0]})


def _h_rapids_help(h):
    """GET /99/Rapids/help: the registered primitive table (AstRoot doc)."""
    from h2o3_tpu.rapids import rapids as _rap
    prims = sorted(_rap.PRIMS.keys())
    h._send({"__meta": {"schema_type": "RapidsHelpV99"},
             "syntax": prims, "n_prims": len(prims)})


def _h_session_get(h, sid):
    h._send({"__meta": {"schema_type": "SessionIdV4"},
             "session_key": sid})


def _h_models_info_v4(h):
    """GET /4/modelsinfo (the v4 experimental API's model catalog)."""
    from h2o3_tpu.models import ESTIMATORS
    h._send({"__meta": {"schema_type": "ModelsInfoV4"},
             "models": [{"algo": a, "maturity": "stable"}
                        for a in sorted(ESTIMATORS)]})


def _h_frames_v4(h):
    """GET /4/frames: the v4 lightweight frame listing."""
    out = [{"frame_id": {"name": k}, "rows": o.nrows, "columns": o.ncols}
           for k in DKV.keys()
           if isinstance((o := DKV.get(k)), Frame)]
    h._send({"__meta": {"schema_type": "FramesV4"}, "frames": out})


def _h_models_v4(h):
    """GET /4/models: the v4 lightweight model listing."""
    from h2o3_tpu.models.model import ModelBase
    out = [{"model_id": {"name": k}, "algo": o.algo}
           for k in DKV.keys()
           if isinstance((o := DKV.get(k)), ModelBase)]
    h._send({"__meta": {"schema_type": "ModelsV4"}, "models": out})


def _h_automl_list(h):
    """GET /99/AutoML: every AutoML run in the registry."""
    from h2o3_tpu.automl.automl import H2OAutoML
    out = [{"automl_id": {"name": k}}
           for k in DKV.keys() if isinstance(DKV.get(k), H2OAutoML)]
    h._send({"__meta": {"schema_type": "AutoMLsV99"}, "automls": out})


def _h_segment_models_list(h):
    """GET /99/SegmentModels: registry listing."""
    from h2o3_tpu.models import segments as SEG
    out = [{"key": {"name": k}, "n_segments": len(DKV.get(k))}
           for k in DKV.keys()
           if isinstance(DKV.get(k), SEG.SegmentModels)]
    h._send({"__meta": {"schema_type": "SegmentModelsListV99"},
             "segment_models": out})


def _h_drop_duplicates(h):
    """POST /3/DropDuplicates (DropDuplicateRowsHandler): de-dup rows by
    the chosen comparison columns."""
    p = h._params()
    f = DKV.get(p.get("dataset") or p.get("frame"))
    if not isinstance(f, Frame):
        return h._error("dataset not found", 404)
    cols = p.get("compare_columns") or p.get("columns")
    cols = json.loads(cols) if isinstance(cols, str) else (cols or f.names)
    keep = str(p.get("keep", "first")).lower()
    import pandas as pd
    df = pd.DataFrame({c: _col_as_values(f, c) for c in f.names})
    out_df = df.drop_duplicates(subset=cols,
                                keep="last" if keep == "last" else "first")
    dest = p.get("dest") or DKV.make_key("dedup")
    cols_out = {}
    for c in f.names:
        a = out_df[c].to_numpy()
        if f.vec(c).type in ("enum", "str"):
            a = np.asarray(a, object)
        cols_out[c] = a
    out = Frame.from_dict(cols_out, key=dest)
    DKV.put(dest, out)
    h._send({"__meta": {"schema_type": "DropDuplicatesV3"},
             "result": {"name": dest}, "rows": out.nrows})


def _col_as_values(f, c):
    v = f.vec(c)
    if v.type == "enum":
        dom = v.levels() or []
        return np.asarray([None if x != x else dom[int(x)]
                           for x in v.to_numpy()], object)
    if v.type == "str":
        return v.host_data
    return v.to_numpy()


def _h_permutation_varimp(h):
    """POST /3/PermutationVarImp (PermutationVarImpHandler): permutation
    feature importance of a model on a frame."""
    from h2o3_tpu.models.model import ModelBase
    p = h._params()
    m = DKV.get(p.get("model"))
    f = DKV.get(p.get("frame"))
    if not isinstance(m, ModelBase) or not isinstance(f, Frame):
        return h._error("model/frame not found", 404)
    from h2o3_tpu.explain_data import permutation_varimp
    rows = permutation_varimp(m, f,
                              metric=p.get("metric", "AUTO"),
                              n_repeats=int(p.get("n_repeats") or 1),
                              seed=int(p.get("seed") or 42))
    h._send({"__meta": {"schema_type": "PermutationVarImpV3"},
             "varimp": rows})


# ===========================================================================
def build_routes():
    R = re.compile
    return [
        (R(r"/3/Frames/([^/]+)/light"), "GET", _h_frame_light),
        (R(r"/3/Frames/([^/]+)/columns/([^/]+)/domain"), "GET",
         _h_frame_col_domain),
        (R(r"/3/FrameChunks/([^/]+)"), "GET", _h_frame_chunks),
        (R(r"/3/Frames"), "DELETE", _h_frames_delete_all),
        (R(r"/3/Models"), "DELETE", _h_models_delete_all),
        (R(r"/3/Rebalance"), "POST", _h_rebalance),
        (R(r"/3/Find"), "GET", _h_find),
        (R(r"/3/Jobs/([^/]+)/cancel"), "POST", _h_job_cancel),
        (R(r"/3/MakeGLMModel"), "POST", _h_make_glm_model),
        (R(r"/3/GetGLMRegPath"), "GET", _h_glm_reg_path),
        (R(r"/99/DataInfoFrame"), "POST", _h_data_info_frame),
        (R(r"/99/Models\.mojo/([^/]+)"), "POST", _h_mojo_export),
        (R(r"/3/Models\.mojo/([^/]+)"), "GET",
         _alias("/3/Models/{}/mojo")),
        (R(r"/3/Models\.java/([^/]+)/preview"), "GET", _h_pojo_preview),
        (R(r"/3/ModelMetrics/predictions_frame/([^/]+)/actuals_frame/"
           r"([^/]+)"), "POST", _h_metrics_maker),
        (R(r"/3/NodePersistentStorage/configured"), "GET",
         _h_nps_configured),
        (R(r"/3/NodePersistentStorage/([^/]+)/([^/]+)"), "POST",
         _h_nps_put),
        (R(r"/3/NodePersistentStorage/([^/]+)/([^/]+)"), "GET", _h_nps_get),
        (R(r"/3/NodePersistentStorage/([^/]+)"), "GET", _h_nps_list),
        (R(r"/3/NodePersistentStorage/([^/]+)/([^/]+)"), "DELETE",
         _h_nps_delete),
        (R(r"/99/SegmentModelsBuilders/([^/]+)"), "POST", _h_segment_build),
        (R(r"/99/SegmentModels/([^/]+)"), "GET", _h_segment_get),
        (R(r"/99/Tabulate"), "POST", _h_tabulate),
        (R(r"/99/Leaderboards"), "GET", _h_leaderboards),
        (R(r"/99/Leaderboards/([^/]+)"), "GET", _h_leaderboards),
        (R(r"/3/ImportFilesMulti"), "GET", _h_import_files_multi),
        (R(r"/3/DecryptionSetup"), "POST", _h_decryption_setup),
        (R(r"/3/ImportHiveTable"), "POST", _h_import_hive),
        (R(r"/3/SaveToHiveTable"), "POST", _h_export_hive),
        (R(r"/3/PersistS3"), "POST", _h_persist_s3),
        (R(r"/3/steam/instances"), "GET", _h_steam_instances),
        (R(r"/3/KillMinus3"), "GET", _h_kill_minus3),
        (R(r"/3/Metadata/schemas"), "GET", _h_metadata_schemas),
        (R(r"/3/Metadata/schemas/([^/]+)"), "GET", _h_metadata_schemas),
        (R(r"/3/Metadata/endpoints/([^/]+)"), "GET", _h_metadata_endpoint),
        (R(r"/99/Rapids/help"), "GET", _h_rapids_help),
        (R(r"/4/sessions/([^/]+)"), "GET", _h_session_get),
        (R(r"/4/modelsinfo"), "GET", _h_models_info_v4),
        (R(r"/4/frames"), "GET", _h_frames_v4),
        (R(r"/4/models"), "GET", _h_models_v4),
        (R(r"/99/AutoML"), "GET", _h_automl_list),
        (R(r"/99/SegmentModels"), "GET", _h_segment_models_list),
        (R(r"/3/DropDuplicates"), "POST", _h_drop_duplicates),
        (R(r"/3/PermutationVarImp"), "POST", _h_permutation_varimp),
    ]


def _alias(target_fmt):
    """Delegate an alias pattern to the canonical handler via the route
    table (reference registers several spelling variants per endpoint)."""
    def handler(h, *groups):
        from h2o3_tpu.api import server as _srv
        path = target_fmt.format(*groups)
        for pat, m, fn in _srv.ROUTES:
            if m == "GET" and pat.fullmatch(path):
                return fn(h, *pat.fullmatch(path).groups())
        h._error(f"alias target {path} unresolved", 500)
    handler.__doc__ = f"alias of GET {target_fmt}"
    return handler
