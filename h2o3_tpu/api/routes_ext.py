"""Extended REST routes — the RequestServer.java surface beyond the core
(water/api/RequestServer.java:76 registers ~150 routes; this module carries
the frame-munging, diagnostics, artifact-download, validation and codegen
routes that the core server.py doesn't).

Handlers receive the live request handler `h` (duck-typed: _send/_error/
_params) plus regex groups, exactly like server.py's own handlers.
"""

from __future__ import annotations

import json
import os
import re
import time

import numpy as np

from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.core.jobs import Job
from h2o3_tpu.core.kvstore import DKV

_T0 = time.time()


# ===========================================================================
# diagnostics
def _h_ping(h):
    """water/api/PingHandler: cloud liveness + uptime."""
    h._send({"__meta": {"schema_type": "PingV3"},
             "cloud_uptime_millis": int((time.time() - _T0) * 1000),
             "cloud_healthy": True})


def _h_capabilities(h, categ=None):
    """CapabilitiesHandler: registered extensions by category."""
    caps = [{"name": "Algos", "version": "3"},
            {"name": "AutoML", "version": "99"},
            {"name": "Core V3", "version": "3"},
            {"name": "Core V4", "version": "4"},
            {"name": "Rapids", "version": "99"},
            {"name": "TPU", "version": "1"}]
    if categ:
        caps = [c for c in caps if c["name"].lower().startswith(categ.lower())]
    h._send({"__meta": {"schema_type": "CapabilitiesV3"},
             "capabilities": caps})


# (GET /3/JStack moved to api/server._h_jstack: all-thread stacks per
# node with a cluster merge over the replay channel, plus the watchdog's
# stalled-operation report.)


def _nt_sum(a):
    return a.sum()


def _h_network_test(h):
    """NetworkTestHandler (water/init/NetworkBench.java analog): time a
    round of mesh collectives instead of UDP all-to-alls."""
    import jax.numpy as jnp
    from h2o3_tpu.parallel import mesh as MESH
    from h2o3_tpu.parallel import mrtask as _mrt
    cl = MESH.cloud()
    sizes = [1 << 10, 1 << 16, 1 << 20]
    results = []
    for sz in sizes:
        x = jnp.ones(sz // 4, jnp.float32)
        # cached_jit: the old per-call jit(lambda) timed a fresh XLA
        # compile on every scrape instead of the collective (R001)
        red = _mrt.cached_jit(_nt_sum)
        float(red(x))                        # warm: compile outside timer
        t0 = time.time()
        y = red(x)
        float(y)
        results.append({"bytes": sz, "collective": "reduce",
                        "micros": (time.time() - t0) * 1e6})
    h._send({"__meta": {"schema_type": "NetworkTestV3"},
             "nodes": cl.n_devices, "results": results})


def _h_water_meter(h, node=None):
    """WaterMeterCpuTicksHandler: per-core cpu ticks."""
    try:
        la = os.getloadavg()
    except OSError:
        la = (0.0, 0.0, 0.0)
    ncpu = os.cpu_count() or 1
    h._send({"__meta": {"schema_type": "WaterMeterCpuTicksV3"},
             "cpu_ticks": [[la[0], la[1], la[2], 0.0]] * ncpu})


def _h_log_and_echo(h):
    from h2o3_tpu.utils import log as _log
    p = h._params()
    msg = p.get("message", "")
    _log.info(f"LogAndEcho: {msg}")
    h._send({"__meta": {"schema_type": "LogAndEchoV3"}, "message": msg})


def _h_gc(h):
    """GarbageCollectHandler: host GC + device buffer stats."""
    import gc
    gc.collect()
    import jax
    try:
        n_live = len(jax.live_arrays())
    except Exception:
        n_live = -1
    h._send({"__meta": {"schema_type": "GarbageCollectV3"},
             "live_device_arrays": n_live})


def _h_unlock(h):
    """UnlockKeysHandler: single-controller registry has no write locks to
    break — reply OK for client compatibility."""
    h._send({"__meta": {"schema_type": "UnlockKeysV3"}})


def _h_dkv_remove(h, key):
    DKV.remove(key)
    h._send({"__meta": {"schema_type": "RemoveV3"}})


def _h_dkv_remove_all(h):
    p = h._params()
    retained = p.get("retained_keys")
    keep = set(json.loads(retained)) if retained else set()
    for k in list(DKV.keys()):
        if k not in keep:
            DKV.remove(k)
    h._send({"__meta": {"schema_type": "RemoveAllV3"}})


def _h_typeahead(h):
    """TypeaheadHandler: filesystem path completion for the import UI."""
    p = h._params()
    src = p.get("src") or "/"
    limit = int(p.get("limit") or 100)
    base = os.path.dirname(src) if not os.path.isdir(src) else src
    prefix = "" if os.path.isdir(src) else os.path.basename(src)
    matches = []
    try:
        for name in sorted(os.listdir(base or "/")):
            if name.startswith(prefix):
                matches.append(os.path.join(base, name))
            if len(matches) >= limit:
                break
    except OSError:
        pass
    h._send({"__meta": {"schema_type": "TypeaheadV3"}, "matches": matches})


# ===========================================================================
# sessions (v4)
_SID_COUNTER = [0]


def _h_sessions_post(h):
    from h2o3_tpu.rapids import Session
    from h2o3_tpu.api import server as _srv
    # monotonic counter only — a deleted session's id is never reissued
    # within a cloud lifetime, and the id must be DETERMINISTIC: this
    # POST is broadcast-replayed, so a wall-clock suffix minted a
    # different sid on every host and forked the session table (the
    # coordinator's reply named a key the workers never registered)
    _SID_COUNTER[0] += 1
    sid = f"_sid{_SID_COUNTER[0]}"
    _srv._sessions[sid] = Session(sid)
    h._send({"__meta": {"schema_type": "SessionIdV4"}, "session_key": sid})


def _h_sessions_delete(h, sid):
    from h2o3_tpu.api import server as _srv
    s = _srv._sessions.pop(sid, None)
    if s is not None:
        s.end()
    h._send({"__meta": {"schema_type": "SessionIdV4"}, "session_key": sid})


# ===========================================================================
# frame munging (CreateFrame / SplitFrame / Interaction / MissingInserter)
def _h_create_frame(h):
    """CreateFrameHandler (hex/createframe): random frame generation."""
    p = h._params()
    rows = int(p.get("rows") or 10000)
    cols = int(p.get("cols") or 10)
    seed = int(p.get("seed") or -1)
    cat_frac = float(p.get("categorical_fraction") or 0.2)
    int_frac = float(p.get("integer_fraction") or 0.2)
    bin_frac = float(p.get("binary_fraction") or 0.1)
    factors = int(p.get("factors") or 100)
    real_range = float(p.get("real_range") or 100.0)
    missing = float(p.get("missing_fraction") or 0.0)
    has_resp = str(p.get("has_response", "false")).lower() == "true"
    dest = p.get("dest") or p.get("destination_frame") or DKV.make_key("cf")
    rng = np.random.default_rng(seed if seed > 0 else None)
    n_cat = int(cols * cat_frac)
    n_int = int(cols * int_frac)
    n_bin = int(cols * bin_frac)
    n_real = max(0, cols - n_cat - n_int - n_bin)
    names, vecs = [], []

    def maybe_na(a):
        if missing > 0:
            a = a.astype(np.float64)
            a[rng.random(rows) < missing] = np.nan
        return a

    j = 0
    for _ in range(n_real):
        names.append(f"C{j+1}")
        vecs.append(Vec.from_numpy(
            maybe_na(rng.uniform(-real_range, real_range, rows))))
        j += 1
    for _ in range(n_int):
        names.append(f"C{j+1}")
        vecs.append(Vec.from_numpy(
            maybe_na(rng.integers(-100, 100, rows).astype(np.float64))))
        j += 1
    for _ in range(n_bin):
        names.append(f"C{j+1}")
        vecs.append(Vec.from_numpy(
            maybe_na((rng.random(rows) < 0.5).astype(np.float64))))
        j += 1
    for _ in range(n_cat):
        names.append(f"C{j+1}")
        lv = [f"c{int(v)}" for v in range(factors)]
        codes = rng.integers(0, factors, rows)
        vecs.append(Vec._from_strings(          # strings default to enum
            np.asarray([lv[c] for c in codes], object)))
        j += 1
    if has_resp:
        names.append("response")
        vecs.append(Vec.from_numpy(rng.normal(0, 1, rows)))
    f = Frame(names, vecs, key=dest)
    DKV.put(dest, f)
    job = Job(description="CreateFrame", dest=dest)
    job.start(lambda job: f)
    h._send({"__meta": {"schema_type": "CreateFrameV3"},
             "job": job.to_dict(), "dest": {"name": dest}})


def _h_split_frame(h):
    """SplitFrameHandler (hex/splitframe/ShuffleSplitFrame.java)."""
    p = h._params()
    f = DKV.get(p.get("dataset"))
    if not isinstance(f, Frame):
        return h._error("dataset not found", 404)
    ratios = p.get("ratios")
    ratios = json.loads(ratios) if isinstance(ratios, str) else ratios
    dests = p.get("destination_frames")
    if isinstance(dests, str):
        dests = json.loads(dests)
    seed = int(p.get("seed") or 1)
    n = f.nrows
    rng = np.random.default_rng(seed)
    u = rng.random(n)
    edges = np.cumsum(np.asarray(list(ratios) + [1.0 - sum(ratios)]))
    dests = dests or [f"{f.key}_part{i}" for i in range(len(edges))]
    out = []
    prev = 0.0
    for i, e in enumerate(edges):
        mask = (u >= prev) & (u < e)
        prev = e
        idx = np.nonzero(mask)[0]
        cols = {}
        for nm in f.names:
            v = f.vec(nm)
            a = v.to_numpy()[:n][idx]
            if v.type == "enum":
                dom = v.levels() or []
                a = np.asarray(
                    [dom[int(x)] if x == x and int(x) < len(dom) else None
                     for x in a], object)
            cols[nm] = a
        sub = Frame.from_dict(cols, key=dests[i])
        DKV.put(dests[i], sub)
        out.append(dests[i])
    h._send({"__meta": {"schema_type": "SplitFrameV3"},
             "destination_frames": [{"name": d} for d in out]})


def _h_interaction(h):
    """InteractionHandler (hex/Interaction.java): pairwise categorical
    interaction column."""
    p = h._params()
    f = DKV.get(p.get("source_frame"))
    if not isinstance(f, Frame):
        return h._error("source_frame not found", 404)
    factors = p.get("factor_columns")
    factors = json.loads(factors) if isinstance(factors, str) else factors
    max_factors = int(p.get("max_factors") or 100)
    dest = p.get("dest") or DKV.make_key("interaction")
    n = f.nrows
    vals = []
    for c in factors:
        v = f.vec(c)
        dom = v.levels() or []
        codes = v.to_numpy()[:n]
        vals.append([dom[int(x)] if x == x and int(x) < len(dom) else "NA"
                     for x in codes])
    combo = ["_".join(parts) for parts in zip(*vals)]
    # cap cardinality like the reference (top max_factors by frequency)
    from collections import Counter
    top = {k for k, _ in Counter(combo).most_common(max_factors)}
    combo = [c if c in top else "other" for c in combo]
    vec = Vec._from_strings(np.asarray(combo, object), force_type="enum")
    out = Frame(["_".join(factors)], [vec], key=dest)
    DKV.put(dest, out)
    job = Job(description="Interaction", dest=dest)
    job.start(lambda job: out)
    h._send({"__meta": {"schema_type": "InteractionV3"},
             "job": job.to_dict(), "dest": {"name": dest}})


def _h_missing_inserter(h):
    """MissingInserterHandler: inject NAs at a fraction (test utility the
    reference ships as a REST route)."""
    p = h._params()
    f = DKV.get(p.get("dataset"))
    if not isinstance(f, Frame):
        return h._error("dataset not found", 404)
    fraction = float(p.get("fraction") or 0.1)
    seed = int(p.get("seed") or 1)
    rng = np.random.default_rng(seed)
    n = f.nrows
    vecs, names = [], []
    for nm in f.names:
        v = f.vec(nm)
        if v.type == "str":
            vecs.append(v)
            names.append(nm)
            continue
        a = v.to_numpy()[:n].astype(np.float64)
        a[rng.random(n) < fraction] = np.nan
        nv = Vec.from_numpy(a)
        if v.type == "enum":
            nv.type = "enum"
            nv.domain = np.asarray(v.levels(), object)
        vecs.append(nv)
        names.append(nm)
    out = Frame(names, vecs, key=f.key)
    DKV.put(f.key, out)
    job = Job(description="MissingInserter", dest=f.key)
    job.start(lambda job: out)
    h._send({"__meta": {"schema_type": "MissingInserterV3"},
             "job": job.to_dict()})


# ===========================================================================
# frame details / export / download
def _frame_csv(f: Frame) -> bytes:
    n = f.nrows
    cols = []
    for nm in f.names:
        v = f.vec(nm)
        if v.type in ("str",):
            cols.append(np.asarray(v.to_numpy()[:n], object))
        elif v.type == "enum":
            dom = v.levels() or []
            codes = v.to_numpy()[:n]
            cols.append(np.asarray(
                [dom[int(x)] if x == x and int(x) < len(dom) else ""
                 for x in codes], object))
        else:
            cols.append(v.to_numpy()[:n])
    def esc(s: str) -> str:
        # RFC-4180 quoting: values with separators/quotes/newlines must be
        # quoted and inner quotes doubled, or the file re-imports shifted
        if any(ch in s for ch in ",\"\n\r"):
            return '"' + s.replace('"', '""') + '"'
        return s

    lines = [",".join(f'"{nm}"' for nm in f.names)]
    for i in range(n):
        row = []
        for c in cols:
            x = c[i]
            if isinstance(x, float) and x != x:
                row.append("")
            elif isinstance(x, str):
                row.append(esc(x))
            else:
                row.append(str(x))
        lines.append(",".join(row))
    return ("\n".join(lines) + "\n").encode()


def _send_bytes(h, body: bytes, ctype="application/octet-stream",
                filename=None):
    h.send_response(200)
    h.send_header("Content-Type", ctype)
    if filename:
        h.send_header("Content-Disposition",
                      f'attachment; filename="{filename}"')
    h.send_header("Content-Length", str(len(body)))
    h.end_headers()
    if getattr(h, "command", "") != "HEAD":      # RFC 9110: no body
        h.wfile.write(body)


def _h_download_dataset(h):
    """DownloadDataHandler: frame as CSV."""
    p = h._params()
    f = DKV.get(p.get("frame_id"))
    if not isinstance(f, Frame):
        return h._error("frame_id not found", 404)
    _send_bytes(h, _frame_csv(f), "text/csv", f"{f.key}.csv")


def _h_frame_summary(h, fid):
    f = DKV.get(fid)
    if not isinstance(f, Frame):
        return h._error(f"frame {fid} not found", 404)
    from h2o3_tpu.api.server import _frame_schema
    h._send({"__meta": {"schema_type": "FrameSummaryV3"},
             "frames": [_frame_schema(f, with_summary=True)]})


def _h_frame_columns(h, fid):
    f = DKV.get(fid)
    if not isinstance(f, Frame):
        return h._error(f"frame {fid} not found", 404)
    h._send({"__meta": {"schema_type": "FrameColumnsV3"},
             "columns": [{"label": n, "type": v.type,
                          "domain": v.levels()}
                         for n, v in zip(f.names, f.vecs)]})


def _h_frame_col_summary(h, fid, col):
    f = DKV.get(fid)
    if not isinstance(f, Frame):
        return h._error(f"frame {fid} not found", 404)
    if col not in f.names:
        return h._error(f"column {col} not found", 404)
    s = f.summary()
    h._send({"__meta": {"schema_type": "FrameColumnSummaryV3"},
             "column": col, "summary": s.get(col, {})})


def _h_frame_export(h, fid):
    """FramesHandler.export: persist a frame to a URI."""
    p = h._params()
    f = DKV.get(fid)
    if not isinstance(f, Frame):
        return h._error(f"frame {fid} not found", 404)
    path = p.get("path")
    job = Job(description=f"Export {fid}", dest=path)

    def work(job):
        if path.endswith(".hex"):
            from h2o3_tpu.io.persist import export_frame
            export_frame(f, path)
        else:
            from h2o3_tpu.io import uri as _uri
            if _uri.is_remote(path):
                import tempfile
                with tempfile.NamedTemporaryFile(delete=False) as tf:
                    tf.write(_frame_csv(f))
                _uri.push_from_local(tf.name, path)
                os.unlink(tf.name)
            else:
                with open(path, "wb") as fh:
                    fh.write(_frame_csv(f))
        return path

    job.start(work)
    h._send({"__meta": {"schema_type": "FramesV3"}, "job": job.to_dict()})


# ===========================================================================
# model builders: parameter metadata + validation
def _param_schema(cls):
    """Per-algo parameter metadata (ModelParameterSchemaV3 analog), built
    live from the estimator's defaults — the codegen input."""
    out = []
    merged = {}
    merged.update(getattr(cls, "_COMMON", {}))
    merged.update(cls._defaults)
    for name, default in sorted(merged.items()):
        t = ("boolean" if isinstance(default, bool) else
             "int" if isinstance(default, int) else
             "double" if isinstance(default, float) else
             "string[]" if isinstance(default, (list, tuple)) else
             "string")
        out.append({"name": name, "default_value": default, "type": t,
                    "level": "critical" if name in
                    ("ntrees", "max_depth", "learn_rate", "alpha", "lambda_",
                     "k", "epochs", "family") else "secondary"})
    return out


def _h_builder_info(h, algo):
    from h2o3_tpu.models import ESTIMATORS
    cls = ESTIMATORS.get(algo)
    if cls is None:
        return h._error(f"unknown algo {algo}", 404)
    h._send({"__meta": {"schema_type": "ModelBuildersV3"},
             "model_builders": {algo: {
                 "algo": algo, "algo_full_name": cls.__name__,
                 "visibility": "Stable",
                 "parameters": _param_schema(cls)}}})


def _h_validate_params(h, algo):
    """POST /3/ModelBuilders/{algo}/parameters — the validation surface
    (ModelBuilderHandler.validate_parameters): type-check + unknown-param
    detection WITHOUT training."""
    from h2o3_tpu.models import ESTIMATORS
    from h2o3_tpu.api.server import _coerce_param
    cls = ESTIMATORS.get(algo)
    if cls is None:
        return h._error(f"unknown algo {algo}", 404)
    p = h._params()
    p.pop("_rest_version", None)
    messages = []
    known = set(cls._defaults) | set(getattr(cls, "_COMMON", ()))
    special = {"training_frame", "validation_frame", "response_column", "x",
               "model_id", "ignored_columns"}
    for k, v in p.items():
        if k in special:
            if k == "training_frame" and not isinstance(DKV.get(v), Frame):
                messages.append({"message_type": "ERRR", "field_name": k,
                                 "message": f"frame {v} not found"})
            continue
        if k not in known:
            messages.append({"message_type": "ERRR", "field_name": k,
                             "message": f"unknown parameter {k}"})
            continue
        default = cls._defaults.get(k)
        cv = _coerce_param(v)
        if isinstance(default, bool) and not isinstance(cv, bool):
            messages.append({"message_type": "ERRR", "field_name": k,
                             "message": "expected boolean"})
        elif isinstance(default, (int, float)) and not isinstance(
                cv, (int, float, bool)) and default is not None:
            messages.append({"message_type": "ERRR", "field_name": k,
                             "message": "expected numeric"})
    errs = [m for m in messages if m["message_type"] == "ERRR"]
    h._send({"__meta": {"schema_type": "ModelParametersSchemaV3"},
             "messages": messages,
             "error_count": len(errs),
             "validation_error_count": len(errs)})


# ===========================================================================
# artifacts: mojo / pojo / binary save-load; tree introspection
def _h_model_mojo(h, mid):
    m = DKV.get(mid)
    if m is None:
        return h._error(f"model {mid} not found", 404)
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, f"{mid}.zip")
        m.download_mojo(path)
        with open(path, "rb") as fh:
            body = fh.read()
    _send_bytes(h, body, "application/zip", f"{mid}.zip")


def _h_model_pojo(h, mid):
    m = DKV.get(mid)
    if m is None:
        return h._error(f"model {mid} not found", 404)
    import tempfile
    from h2o3_tpu.genmodel.pojo import export_pojo
    with tempfile.TemporaryDirectory() as td:
        path = export_pojo(m, os.path.join(td, f"{mid}.java"))
        with open(path) as fh:
            src = fh.read()
    _send_bytes(h, src.encode(), "text/x-java-source", f"{mid}.java")


def _h_model_save_bin(h, mid):
    p = h._params()
    m = DKV.get(mid)
    if m is None:
        return h._error(f"model {mid} not found", 404)
    path = p.get("dir") or p.get("path")
    from h2o3_tpu.genmodel.mojo import save_model
    dest = os.path.join(path, mid) if os.path.isdir(path) else path
    save_model(m, dest)
    h._send({"__meta": {"schema_type": "ModelsV3"}, "dir": dest})


def _h_model_load_bin(h):
    p = h._params()
    path = p.get("dir") or p.get("path")
    from h2o3_tpu.genmodel.mojo import load_model
    m = load_model(path)
    h._send({"__meta": {"schema_type": "ModelsV3"},
             "models": [{"model_id": {"name": m.key}}]})


def _h_tree(h):
    """TreeHandler (hex/schemas/TreeV3): fetch one tree of a tree model as
    node arrays (heap order: children of i at 2i+1/2i+2)."""
    p = h._params()
    m = DKV.get(p.get("model"))
    if m is None:
        return h._error("model not found", 404)
    tn = int(p.get("tree_number") or 0)
    cls_name = p.get("tree_class")
    ta = getattr(m, "_trees", None)
    if ta is None and getattr(m, "_trees_k", None) is not None:
        dom = m._dinfo.response_domain or []
        ci = dom.index(cls_name) if cls_name in dom else 0
        ta = m._trees_k[ci]
    if ta is None:
        return h._error("not a tree model", 400)
    col = np.asarray(ta.col[tn])
    thr = np.asarray(ta.thr[tn])
    val = np.asarray(ta.value[tn])
    nal = np.asarray(ta.na_left[tn])
    names = m._dinfo.feature_names
    nodes = col.shape[0]
    h._send({"__meta": {"schema_type": "TreeV3"},
             "tree_number": tn,
             "left_children": [(2 * i + 1 if 2 * i + 1 < nodes and
                                col[i] >= 0 else -1)
                               for i in range(nodes)],
             "right_children": [(2 * i + 2 if 2 * i + 2 < nodes and
                                 col[i] >= 0 else -1)
                                for i in range(nodes)],
             "features": [names[c] if 0 <= c < len(names) else ""
                          for c in col],
             "thresholds": thr.tolist(),
             "nas": ["LEFT" if x else "RIGHT" for x in nal],
             "predictions": val.tolist()})


# ===========================================================================
# algo utility routes: PDP, Word2Vec, Gram, grid build
_PDP_RESULTS: dict = {}


def _h_pdp_build(h):
    """PartialDependenceHandler: compute PD profiles as a Job."""
    p = h._params()
    m = DKV.get(p.get("model_id") or p.get("model"))
    f = DKV.get(p.get("frame_id"))
    if m is None or f is None:
        return h._error("model or frame not found", 404)
    cols = p.get("cols")
    cols = json.loads(cols) if isinstance(cols, str) else (
        cols or m._dinfo.feature_names[:2])
    nbins = int(p.get("nbins") or 20)
    dest = p.get("destination_key") or DKV.make_key("pdp")
    job = Job(description="PartialDependence", dest=dest)

    def work(job):
        from h2o3_tpu.explain_data import partial_dependence
        out = []
        for c in cols:
            pd = partial_dependence(m, f, c, nbins=nbins)
            out.append({"column": c,
                        "values": np.asarray(pd["grid"]).tolist(),
                        "mean_response":
                            np.asarray(pd["mean_response"]).tolist()})
        _PDP_RESULTS[dest] = out
        return out

    job.start(work)
    h._send({"__meta": {"schema_type": "PartialDependenceV3"},
             "job": job.to_dict(), "destination_key": dest})


def _h_pdp_fetch(h, key):
    out = _PDP_RESULTS.get(key)
    if out is None:
        return h._error(f"pdp {key} not found", 404)
    h._send({"__meta": {"schema_type": "PartialDependenceV3"},
             "partial_dependence_data": out})


def _h_w2v_synonyms(h):
    p = h._params()
    m = DKV.get(p.get("model"))
    if m is None:
        return h._error("model not found", 404)
    word = p.get("word")
    count = int(p.get("count") or 20)
    syn = m.find_synonyms(word, count)
    h._send({"__meta": {"schema_type": "Word2VecSynonymsV3"},
             "synonyms": list(syn.keys()) if isinstance(syn, dict)
             else [s[0] for s in syn],
             "scores": list(syn.values()) if isinstance(syn, dict)
             else [s[1] for s in syn]})


def _h_w2v_transform(h):
    p = h._params()
    m = DKV.get(p.get("model"))
    f = DKV.get(p.get("words_frame"))
    if m is None or f is None:
        return h._error("model or frame not found", 404)
    agg = p.get("aggregate_method") or "NONE"
    out = m.transform(f, aggregate_method=agg)
    DKV.put(out.key, out)
    h._send({"__meta": {"schema_type": "Word2VecTransformV3"},
             "vectors_frame": {"name": out.key}})


def _h_compute_gram(h):
    """GramHandler (hex/api/MakeGLMModelHandler.computeGram): X'X on MXU."""
    p = h._params()
    f = DKV.get(p.get("X") or p.get("frame"))
    if not isinstance(f, Frame):
        return h._error("frame not found", 404)
    import jax.numpy as jnp
    num = [n for n, v in zip(f.names, f.vecs) if v.type == "real"
           or v.type == "int" or v.type == "num"]
    num = num or f.names
    X = f.matrix(num)[: f.nrows]
    G = np.asarray(jnp.matmul(X.T, X))
    dest = p.get("destination_frame") or DKV.make_key("gram")
    out = Frame(num, [Vec.from_numpy(G[:, j].astype(np.float64))
                      for j in range(G.shape[1])], key=dest)
    DKV.put(dest, out)
    h._send({"__meta": {"schema_type": "GramV3"},
             "destination_frame": {"name": dest}})


def _h_grid_build(h, algo):
    """POST /99/Grid/{algo} — GridSearchHandler: hyper-param search build."""
    from h2o3_tpu.models import ESTIMATORS
    from h2o3_tpu.models.grid import H2OGridSearch
    from h2o3_tpu.api.server import _coerce_param
    cls = ESTIMATORS.get(algo)
    if cls is None:
        return h._error(f"unknown algo {algo}", 404)
    p = h._params()
    hyper = p.pop("hyper_parameters", None)
    hyper = json.loads(hyper) if isinstance(hyper, str) else (hyper or {})
    crit = p.pop("search_criteria", None)
    crit = json.loads(crit) if isinstance(crit, str) else crit
    gid = p.pop("grid_id", None)
    tf = DKV.get(p.pop("training_frame", None))
    y = p.pop("response_column", None)
    p.pop("_rest_version", None)
    kw = {k: _coerce_param(v) for k, v in p.items()
          if k in cls._defaults or k in getattr(cls, "_COMMON", ())}
    grid = H2OGridSearch(cls, hyper, grid_id=gid, search_criteria=crit)
    job = Job(description=f"Grid {algo}", dest=grid.grid_id)

    def work(job):
        grid.train(y=y, training_frame=tf, **kw)
        return grid

    job.start(work)
    h._send({"__meta": {"schema_type": "GridSearchV99"},
             "job": job.to_dict(), "grid_id": {"name": grid.grid_id}})


def _h_recovery_resume(h):
    """POST /99/Recovery/resume — Recovery.autoRecover over a recovery dir."""
    p = h._params()
    d = p.get("recovery_dir")
    if not d or not os.path.isdir(d):
        return h._error("recovery_dir not found", 404)
    from h2o3_tpu.io.persist import Recovery
    out = Recovery(d).resume()
    h._send({"__meta": {"schema_type": "RecoveryV99"},
             "frames": [f.key for f in out["frames"]],
             "models": [m.key for m in out["models"]]})


def _h_import_sql(h):
    """ImportSQLTableHandler: JDBC import — explicitly unsupported on the
    TPU runtime (no JVM); fails loudly instead of pretending."""
    h._error("ImportSQLTable requires a JDBC driver; the TPU runtime has "
             "no JVM. Export your table to parquet/csv and import_file it.",
             501)


def _h_parse_svmlight(h):
    p = h._params()
    src = p.get("source_frames")
    if isinstance(src, str):
        src = json.loads(src) if src.startswith("[") else [src]
    path = src[0].strip('"')
    dest = p.get("destination_frame") or None
    from h2o3_tpu.io import parser as io_parser
    job = Job(description=f"ParseSvmLight {path}", dest=dest or "parsed")

    def work(job):
        f = io_parser.import_file(path, destination_frame=dest)
        job.dest = f.key
        return f

    job.start(work)
    h._send({"__meta": {"schema_type": "ParseV3"}, "job": job.to_dict()})


def _h_model_metrics_list(h):
    """GET /3/ModelMetrics — every stored model's metrics."""
    from h2o3_tpu.models.model import ModelBase
    ms = [DKV.get(k) for k in DKV.keys()]
    out = []
    for m in ms:
        # registry may hold constructed-but-untrained builders
        # (_output is None) — list only scored models
        if isinstance(m, ModelBase) and m._output is not None \
                and m._output.training_metrics:
            out.append(dict(m._output.training_metrics.to_dict(),
                            model={"name": m.key}))
    h._send({"__meta": {"schema_type": "ModelMetricsListSchemaV3"},
             "model_metrics": out})


# ===========================================================================

# handlers that start a background Job — quota-prepaid at the REST
# edge before the replay broadcast (see api/server.starts_job)
_h_create_frame._starts_job = True
_h_interaction._starts_job = True
_h_missing_inserter._starts_job = True
_h_frame_export._starts_job = True
_h_pdp_build._starts_job = True
_h_grid_build._starts_job = True
_h_parse_svmlight._starts_job = True

def build_routes():
    """(pattern, method, handler) rows appended to server.ROUTES."""
    R = re.compile
    return [
        (R(r"/3/Ping"), "GET", _h_ping),
        (R(r"/3/Capabilities"), "GET", _h_capabilities),
        (R(r"/3/Capabilities/([^/]+)"), "GET", _h_capabilities),
        (R(r"/3/NetworkTest"), "GET", _h_network_test),
        (R(r"/3/WaterMeterCpuTicks/([^/]+)"), "GET", _h_water_meter),
        (R(r"/3/WaterMeter/percentiles"), "GET", _h_water_meter),
        (R(r"/3/LogAndEcho"), "POST", _h_log_and_echo),
        (R(r"/3/GarbageCollect"), "POST", _h_gc),
        (R(r"/3/UnlockKeys"), "GET", _h_unlock),
        (R(r"/3/DKV/([^/]+)"), "DELETE", _h_dkv_remove),
        (R(r"/3/DKV"), "DELETE", _h_dkv_remove_all),
        (R(r"/99/Typeahead/files"), "GET", _h_typeahead),
        (R(r"/3/Typeahead/files"), "GET", _h_typeahead),
        (R(r"/4/sessions"), "POST", _h_sessions_post),
        (R(r"/4/sessions/([^/]+)"), "DELETE", _h_sessions_delete),
        (R(r"/3/CreateFrame"), "POST", _h_create_frame),
        (R(r"/3/SplitFrame"), "POST", _h_split_frame),
        (R(r"/3/Interaction"), "POST", _h_interaction),
        (R(r"/3/MissingInserter"), "POST", _h_missing_inserter),
        (R(r"/3/DownloadDataset"), "GET", _h_download_dataset),
        (R(r"/3/DownloadDataset\.bin"), "GET", _h_download_dataset),
        (R(r"/3/Frames/([^/]+)/summary"), "GET", _h_frame_summary),
        (R(r"/3/Frames/([^/]+)/columns"), "GET", _h_frame_columns),
        (R(r"/3/Frames/([^/]+)/columns/([^/]+)/summary"), "GET",
         _h_frame_col_summary),
        (R(r"/3/Frames/([^/]+)/export"), "POST", _h_frame_export),
        (R(r"/3/ModelBuilders/([^/]+)"), "GET", _h_builder_info),
        (R(r"/3/ModelBuilders/([^/]+)/parameters"), "POST",
         _h_validate_params),
        (R(r"/3/Models/([^/]+)/mojo"), "GET", _h_model_mojo),
        (R(r"/3/Models\.java/([^/]+)"), "GET", _h_model_pojo),
        (R(r"/99/Models\.bin/([^/]+)"), "POST", _h_model_save_bin),
        (R(r"/99/Models\.bin"), "POST", _h_model_load_bin),
        (R(r"/3/Tree"), "GET", _h_tree),
        (R(r"/3/PartialDependence"), "POST", _h_pdp_build),
        (R(r"/3/PartialDependence/([^/]+)"), "GET", _h_pdp_fetch),
        (R(r"/3/Word2VecSynonyms"), "POST", _h_w2v_synonyms),
        (R(r"/3/Word2VecTransform"), "POST", _h_w2v_transform),
        (R(r"/3/ComputeGram"), "POST", _h_compute_gram),
        (R(r"/99/Grid/([^/]+)"), "POST", _h_grid_build),
        (R(r"/99/Recovery/resume"), "POST", _h_recovery_resume),
        (R(r"/86/ImportSQLTable"), "POST", _h_import_sql),
        (R(r"/3/ParseSvmLight"), "POST", _h_parse_svmlight),
        (R(r"/3/ModelMetrics"), "GET", _h_model_metrics_list),
    ]
