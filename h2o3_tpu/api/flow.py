"""Flow-lite — a single-page operations UI served at `/` (the h2o-web /
Flow notebook analog, reduced to its operational core: cluster status,
frames, models with metrics, jobs, a model-build form and a Rapids
console, all driven by the same public REST routes a browser user of the
reference exercises through Flow)."""

FLOW_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>h2o3-tpu Flow</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f4f6f8;color:#1d2733}
 header{background:#123b57;color:#fff;padding:10px 18px;font-size:18px}
 main{display:grid;grid-template-columns:1fr 1fr;gap:14px;padding:14px}
 section{background:#fff;border-radius:8px;padding:12px 14px;box-shadow:0 1px 3px rgba(0,0,0,.12)}
 h2{font-size:14px;margin:0 0 8px;color:#345}
 table{width:100%;border-collapse:collapse;font-size:12px}
 td,th{padding:3px 6px;border-bottom:1px solid #e5e9ee;text-align:left}
 input,select,button,textarea{font:inherit;padding:4px 6px;margin:2px}
 button{background:#1b6ca8;color:#fff;border:0;border-radius:4px;cursor:pointer}
 pre{background:#0e1726;color:#d7e3f4;padding:8px;border-radius:6px;font-size:11px;overflow:auto;max-height:180px}
 .full{grid-column:1/3}
</style></head><body>
<header>h2o3-tpu &mdash; Flow <span id="cloud" style="font-size:12px"></span></header>
<main>
 <section><h2>Frames</h2><table id="frames"></table></section>
 <section><h2>Models</h2><table id="models"></table></section>
 <section><h2>Jobs</h2><table id="jobs"></table></section>
 <section><h2>Build model</h2>
  <select id="algo"></select>
  <input id="tf" placeholder="training_frame key">
  <input id="y" placeholder="response column">
  <input id="extra" placeholder="extra params k=v&k=v">
  <button onclick="build()">Build</button>
  <pre id="buildout"></pre></section>
 <section class="full"><h2>Rapids console</h2>
  <textarea id="ast" rows="2" style="width:90%">(+ 1 2)</textarea>
  <button onclick="rapids()">Run</button>
  <pre id="rapout"></pre></section>
</main>
<script>
const J = async (p, o) => (await fetch(p, o)).json();
async function refresh(){
  const c = await J('/3/Cloud');
  document.getElementById('cloud').textContent =
    ` ${c.cloud_name} · ${c.cloud_size} shards · v${c.version}`;
  const fr = await J('/3/Frames');
  document.getElementById('frames').innerHTML =
    '<tr><th>key</th><th>rows</th><th>cols</th></tr>' +
    fr.frames.map(f=>`<tr><td>${f.frame_id.name}</td><td>${f.rows}</td><td>${f.column_count}</td></tr>`).join('');
  const ms = await J('/3/Models');
  document.getElementById('models').innerHTML =
    '<tr><th>model</th><th>algo</th><th>metric</th></tr>' +
    ms.models.map(m=>{const t=m.training_metrics||{};
      const met = t.auc!=null?('auc '+(+t.auc).toFixed(4)):(t.rmse!=null?('rmse '+(+t.rmse).toFixed(4)):'');
      return `<tr><td>${m.model_id}</td><td>${m.algo}</td><td>${met}</td></tr>`}).join('');
  const js = await J('/3/Jobs');
  document.getElementById('jobs').innerHTML =
    '<tr><th>job</th><th>status</th><th>progress</th></tr>' +
    js.jobs.slice(-12).reverse().map(j=>`<tr><td>${j.description}</td><td>${j.status}</td><td>${Math.round(100*j.progress)}%</td></tr>`).join('');
}
async function loadAlgos(){
  const b = await J('/3/ModelBuilders');
  document.getElementById('algo').innerHTML =
    Object.keys(b.model_builders).map(a=>`<option>${a}</option>`).join('');
}
async function build(){
  const p = new URLSearchParams();
  p.set('training_frame', document.getElementById('tf').value);
  const y = document.getElementById('y').value;
  if (y) p.set('response_column', y);
  for (const kv of document.getElementById('extra').value.split('&'))
    if (kv.includes('=')) p.set(...kv.split('='));
  const algo = document.getElementById('algo').value;
  const r = await J('/3/ModelBuilders/'+algo, {method:'POST', body:p});
  document.getElementById('buildout').textContent = JSON.stringify(r, null, 1);
  setTimeout(refresh, 1200);
}
async function rapids(){
  const p = new URLSearchParams();
  p.set('ast', document.getElementById('ast').value);
  const r = await J('/99/Rapids', {method:'POST', body:p});
  document.getElementById('rapout').textContent = JSON.stringify(r, null, 1);
  refresh();
}
loadAlgos(); refresh(); setInterval(refresh, 5000);
</script></body></html>
"""


def h_flow(h):
    body = FLOW_HTML.encode()
    h.send_response(200)
    h.send_header("Content-Type", "text/html; charset=utf-8")
    h.send_header("Content-Length", str(len(body)))
    h.end_headers()
    h.wfile.write(body)
