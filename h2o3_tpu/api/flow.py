"""Flow-lite — a single-page operations UI served at `/` (the h2o-web /
Flow notebook analog, reduced to its operational core: cluster status,
frames, models with metrics, jobs, a model-build form and a Rapids
console, all driven by the same public REST routes a browser user of the
reference exercises through Flow)."""

FLOW_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>h2o3-tpu Flow</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f4f6f8;color:#1d2733}
 header{background:#123b57;color:#fff;padding:10px 18px;font-size:18px}
 main{display:grid;grid-template-columns:1fr 1fr;gap:14px;padding:14px}
 section{background:#fff;border-radius:8px;padding:12px 14px;box-shadow:0 1px 3px rgba(0,0,0,.12)}
 h2{font-size:14px;margin:0 0 8px;color:#345}
 table{width:100%;border-collapse:collapse;font-size:12px}
 td,th{padding:3px 6px;border-bottom:1px solid #e5e9ee;text-align:left}
 input,select,button,textarea{font:inherit;padding:4px 6px;margin:2px}
 button{background:#1b6ca8;color:#fff;border:0;border-radius:4px;cursor:pointer}
 pre{background:#0e1726;color:#d7e3f4;padding:8px;border-radius:6px;font-size:11px;overflow:auto;max-height:180px}
 .full{grid-column:1/3}
</style></head><body>
<header>h2o3-tpu &mdash; Flow <span id="cloud" style="font-size:12px"></span></header>
<main>
 <section><h2>Frames</h2><table id="frames"></table></section>
 <section><h2>Models</h2><table id="models"></table></section>
 <section><h2>Jobs</h2><table id="jobs"></table></section>
 <section><h2>Build model</h2>
  <select id="algo"></select>
  <input id="tf" placeholder="training_frame key">
  <input id="y" placeholder="response column">
  <input id="extra" placeholder="extra params k=v&k=v">
  <button onclick="build()">Build</button>
  <pre id="buildout"></pre></section>
 <section class="full"><h2>Rapids console</h2>
  <textarea id="ast" rows="2" style="width:90%">(+ 1 2)</textarea>
  <button onclick="rapids()">Run</button>
  <pre id="rapout"></pre></section>
</main>
<script>
const J = async (p, o) => (await fetch(p, o)).json();
async function refresh(){
  const c = await J('/3/Cloud');
  document.getElementById('cloud').textContent =
    ` ${c.cloud_name} · ${c.cloud_size} shards · v${c.version}`;
  const fr = await J('/3/Frames');
  document.getElementById('frames').innerHTML =
    '<tr><th>key</th><th>rows</th><th>cols</th></tr>' +
    fr.frames.map(f=>`<tr><td>${f.frame_id.name}</td><td>${f.rows}</td><td>${f.column_count}</td></tr>`).join('');
  const ms = await J('/3/Models');
  document.getElementById('models').innerHTML =
    '<tr><th>model</th><th>algo</th><th>metric</th></tr>' +
    ms.models.map(m=>{const t=m.training_metrics||{};
      const met = t.auc!=null?('auc '+(+t.auc).toFixed(4)):(t.rmse!=null?('rmse '+(+t.rmse).toFixed(4)):'');
      return `<tr><td>${m.model_id}</td><td>${m.algo}</td><td>${met}</td></tr>`}).join('');
  const js = await J('/3/Jobs');
  document.getElementById('jobs').innerHTML =
    '<tr><th>job</th><th>status</th><th>progress</th></tr>' +
    js.jobs.slice(-12).reverse().map(j=>`<tr><td>${j.description}</td><td>${j.status}</td><td>${Math.round(100*j.progress)}%</td></tr>`).join('');
}
async function loadAlgos(){
  const b = await J('/3/ModelBuilders');
  document.getElementById('algo').innerHTML =
    Object.keys(b.model_builders).map(a=>`<option>${a}</option>`).join('');
}
async function build(){
  const p = new URLSearchParams();
  p.set('training_frame', document.getElementById('tf').value);
  const y = document.getElementById('y').value;
  if (y) p.set('response_column', y);
  for (const kv of document.getElementById('extra').value.split('&'))
    if (kv.includes('=')) p.set(...kv.split('='));
  const algo = document.getElementById('algo').value;
  const r = await J('/3/ModelBuilders/'+algo, {method:'POST', body:p});
  document.getElementById('buildout').textContent = JSON.stringify(r, null, 1);
  setTimeout(refresh, 1200);
}
async function rapids(){
  const p = new URLSearchParams();
  p.set('ast', document.getElementById('ast').value);
  const r = await J('/99/Rapids', {method:'POST', body:p});
  document.getElementById('rapout').textContent = JSON.stringify(r, null, 1);
  refresh();
}
loadAlgos(); refresh(); setInterval(refresh, 5000);
</script></body></html>
"""


def _send_html(h, body: bytes):
    h.send_response(200)
    h.send_header("Content-Type", "text/html; charset=utf-8")
    h.send_header("Content-Length", str(len(body)))
    h.end_headers()
    h.wfile.write(body)


def h_flow(h):
    _send_html(h, FLOW_HTML.encode())


# ---------------------------------------------------------------------------
# Flow notebook (the h2o-web Flow cell model): an ordered list of cells —
# markdown | rapids | import | build | predict — executed top-to-bottom
# against the same REST surface, persisted as named documents through
# /3/NodePersistentStorage/notebooks/<name> (exactly where the reference
# Flow keeps its .flow documents).
NOTEBOOK_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>h2o3-tpu Flow notebook</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f4f6f8;color:#1d2733}
 header{background:#123b57;color:#fff;padding:10px 18px;font-size:18px;display:flex;gap:14px;align-items:center}
 header input{font:inherit;padding:3px 6px;border-radius:4px;border:0}
 header a{color:#9fc3dd;font-size:12px}
 #cells{max-width:980px;margin:16px auto;display:flex;flex-direction:column;gap:10px}
 .cell{background:#fff;border-radius:8px;box-shadow:0 1px 3px rgba(0,0,0,.12);padding:10px 12px}
 .cell .bar{display:flex;gap:6px;align-items:center;font-size:11px;color:#678}
 .cell textarea{width:100%;font:12px/1.4 ui-monospace,monospace;border:1px solid #dde;border-radius:4px;margin-top:6px;padding:6px;box-sizing:border-box}
 .cell pre{background:#0e1726;color:#d7e3f4;padding:8px;border-radius:6px;font-size:11px;overflow:auto;max-height:220px;margin:6px 0 0}
 .cell .md{padding:4px 2px}
 button{background:#1b6ca8;color:#fff;border:0;border-radius:4px;cursor:pointer;font-size:12px;padding:3px 8px}
 button.ghost{background:#e4ecf2;color:#246}
 select{font-size:12px}
</style></head><body>
<header>h2o3-tpu &mdash; Flow notebook
 <input id="nbname" value="notebook1" size="14">
 <button onclick="saveNb()">Save</button>
 <button onclick="loadNb()">Load</button>
 <button class="ghost" onclick="runAll()">Run all</button>
 <span id="status" style="font-size:12px"></span>
 <a href="/">ops dashboard</a>
</header>
<div id="cells"></div>
<div style="text-align:center;margin:12px">
 <select id="newtype"><option>rapids</option><option>markdown</option>
  <option>import</option><option>build</option><option>predict</option></select>
 <button onclick="addCell()">+ cell</button>
</div>
<script>
const J = async (p, o) => (await fetch(p, o)).json();
let cells = [
 {type:'markdown', src:'# New Flow\\nCells run top-to-bottom against the cloud.'},
 {type:'rapids', src:'(+ 1 2)'}];
const PLACEHOLDER = {
 rapids:'(rapids expression)',
 markdown:'# heading\\ntext',
 import:'source_frames=/data/train.csv&destination_frame=train',
 build:'algo=gbm&training_frame=train&response_column=y&ntrees=20',
 predict:'model=gbm_1&frame=train&predictions_frame=preds'};
function render(){
 const host = document.getElementById('cells');
 host.innerHTML='';
 cells.forEach((c,i)=>{
  const d = document.createElement('div'); d.className='cell';
  const md = c.type==='markdown';
  d.innerHTML = `<div class="bar"><b>[${i}] ${c.type}</b>
    <button onclick="runCell(${i})">Run</button>
    <button class="ghost" onclick="moveCell(${i},-1)">&uarr;</button>
    <button class="ghost" onclick="moveCell(${i},1)">&darr;</button>
    <button class="ghost" onclick="delCell(${i})">&times;</button></div>` +
   (md ? `<div class="md" id="md${i}"></div>` : '') +
   `<textarea id="src${i}" rows="${md?3:2}"
      placeholder="${PLACEHOLDER[c.type]}"
      oninput="cells[${i}].src=this.value${md?';mdRender('+i+')':''}"></textarea>` +
   `<pre id="out${i}" style="display:none"></pre>`;
  host.appendChild(d);
  document.getElementById('src'+i).value = c.src || '';
  if (md) mdRender(i);
 });
}
function mdRender(i){
 const src = cells[i].src || '';
 const esc = src.replace(/&/g,'&amp;').replace(/</g,'&lt;');
 document.getElementById('md'+i).innerHTML = esc
  .replace(/^### (.*)$/gm,'<h3>$1</h3>').replace(/^## (.*)$/gm,'<h2>$1</h2>')
  .replace(/^# (.*)$/gm,'<h1>$1</h1>')
  .replace(/\\*\\*([^*]+)\\*\\*/g,'<b>$1</b>').replace(/`([^`]+)`/g,'<code>$1</code>')
  .replace(/\\n/g,'<br>');
}
function addCell(){cells.push({type:document.getElementById('newtype').value, src:''}); render();}
function delCell(i){cells.splice(i,1); render();}
function moveCell(i,d){const j=i+d; if(j<0||j>=cells.length)return;
 [cells[i],cells[j]]=[cells[j],cells[i]]; render();}
async function runCell(i){
 const c = cells[i];
 c.src = document.getElementById('src'+i).value;
 const out = document.getElementById('out'+i);
 if (c.type==='markdown'){ mdRender(i); return; }
 out.style.display='block'; out.textContent='...';
 try {
  let r;
  if (c.type==='rapids'){
   const p=new URLSearchParams(); p.set('ast', c.src);
   r = await J('/99/Rapids',{method:'POST',body:p});
  } else if (c.type==='import'){
   const p=new URLSearchParams(c.src);
   const s=await J('/3/Parse',{method:'POST',body:p});
   r = await waitJob(s.job && s.job.key) || s;
  } else if (c.type==='build'){
   const p=new URLSearchParams(c.src);
   const algo=p.get('algo'); p.delete('algo');
   const s=await J('/3/ModelBuilders/'+algo,{method:'POST',body:p});
   r = await waitJob(s.job && s.job.key) || s;
  } else if (c.type==='predict'){
   const p=new URLSearchParams(c.src);
   r = await J(`/3/Predictions/models/${p.get('model')}/frames/${p.get('frame')}`,
     {method:'POST', body:new URLSearchParams({predictions_frame:p.get('predictions_frame')||'preds'})});
  }
  out.textContent = JSON.stringify(r, null, 1).slice(0, 4000);
 } catch(e){ out.textContent = 'ERROR ' + e; }
}
async function waitJob(key){
 if(!key) return null;
 for(let i=0;i<600;i++){
  const j=(await J('/3/Jobs/'+key)).jobs[0];
  if(['DONE','FAILED','CANCELLED'].includes(j.status)) return j;
  await new Promise(r=>setTimeout(r,400));
 }
 return {status:'TIMEOUT'};
}
async function runAll(){for(let i=0;i<cells.length;i++) await runCell(i);}
async function saveNb(){
 const name=document.getElementById('nbname').value||'notebook1';
 const p=new URLSearchParams(); p.set('value', JSON.stringify(cells));
 await J('/3/NodePersistentStorage/notebooks/'+encodeURIComponent(name),{method:'POST',body:p});
 document.getElementById('status').textContent='saved '+new Date().toLocaleTimeString();
}
async function loadNb(){
 const name=document.getElementById('nbname').value||'notebook1';
 try{
  const r=await J('/3/NodePersistentStorage/notebooks/'+encodeURIComponent(name));
  cells=JSON.parse(r.value); render();
  document.getElementById('status').textContent='loaded';
 }catch(e){document.getElementById('status').textContent='not found';}
}
render();
</script></body></html>
"""


def h_notebook(h):
    _send_html(h, NOTEBOOK_HTML.encode())
