"""Flow-lite — a single-page operations UI served at `/` (the h2o-web /
Flow notebook analog, reduced to its operational core: cluster status,
frames, models with metrics, jobs, a model-build form and a Rapids
console, all driven by the same public REST routes a browser user of the
reference exercises through Flow)."""

FLOW_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>h2o3-tpu Flow</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f4f6f8;color:#1d2733}
 header{background:#123b57;color:#fff;padding:10px 18px;font-size:18px}
 main{display:grid;grid-template-columns:1fr 1fr;gap:14px;padding:14px}
 section{background:#fff;border-radius:8px;padding:12px 14px;box-shadow:0 1px 3px rgba(0,0,0,.12)}
 h2{font-size:14px;margin:0 0 8px;color:#345}
 table{width:100%;border-collapse:collapse;font-size:12px}
 td,th{padding:3px 6px;border-bottom:1px solid #e5e9ee;text-align:left}
 input,select,button,textarea{font:inherit;padding:4px 6px;margin:2px}
 button{background:#1b6ca8;color:#fff;border:0;border-radius:4px;cursor:pointer}
 pre{background:#0e1726;color:#d7e3f4;padding:8px;border-radius:6px;font-size:11px;overflow:auto;max-height:180px}
 .full{grid-column:1/3}
</style></head><body>
<header>h2o3-tpu &mdash; Flow <span id="cloud" style="font-size:12px"></span></header>
<main>
 <section><h2>Frames</h2><table id="frames"></table></section>
 <section><h2>Models</h2><table id="models"></table></section>
 <section><h2>Jobs</h2><table id="jobs"></table></section>
 <section><h2>Build model</h2>
  <select id="algo"></select>
  <input id="tf" placeholder="training_frame key">
  <input id="y" placeholder="response column">
  <input id="extra" placeholder="extra params k=v&k=v">
  <button onclick="build()">Build</button>
  <pre id="buildout"></pre></section>
 <section class="full"><h2>Rapids console</h2>
  <textarea id="ast" rows="2" style="width:90%">(+ 1 2)</textarea>
  <button onclick="rapids()">Run</button>
  <pre id="rapout"></pre></section>
</main>
<script>
const J = async (p, o) => (await fetch(p, o)).json();
function fillTable(id, head, rows){
  // textContent-only cells: registry names are data, never markup
  const t = document.getElementById(id); t.textContent='';
  const hr = t.insertRow();
  head.forEach(h=>{const th=document.createElement('th');th.textContent=h;hr.appendChild(th);});
  rows.forEach(r=>{const tr=t.insertRow();
    r.forEach(v=>{tr.insertCell().textContent=String(v);});});
}
async function refresh(){
  const c = await J('/3/Cloud');
  document.getElementById('cloud').textContent =
    ` ${c.cloud_name} · ${c.cloud_size} shards · v${c.version}`;
  const fr = await J('/3/Frames');
  fillTable('frames', ['key','rows','cols'],
    fr.frames.map(f=>[f.frame_id.name, f.rows, f.column_count]));
  const ms = await J('/3/Models');
  fillTable('models', ['model','algo','metric'],
    ms.models.map(m=>{const t=m.training_metrics||{};
      const met = t.auc!=null?('auc '+(+t.auc).toFixed(4)):(t.rmse!=null?('rmse '+(+t.rmse).toFixed(4)):'');
      return [m.model_id, m.algo, met]}));
  const js = await J('/3/Jobs');
  fillTable('jobs', ['job','status','progress'],
    js.jobs.slice(-12).reverse().map(j=>[j.description, j.status,
      Math.round(100*j.progress)+'%']));
}
async function loadAlgos(){
  const b = await J('/3/ModelBuilders');
  document.getElementById('algo').innerHTML =
    Object.keys(b.model_builders).map(a=>`<option>${a}</option>`).join('');
}
async function build(){
  const p = new URLSearchParams();
  p.set('training_frame', document.getElementById('tf').value);
  const y = document.getElementById('y').value;
  if (y) p.set('response_column', y);
  for (const kv of document.getElementById('extra').value.split('&'))
    if (kv.includes('=')) p.set(...kv.split('='));
  const algo = document.getElementById('algo').value;
  const r = await J('/3/ModelBuilders/'+algo, {method:'POST', body:p});
  document.getElementById('buildout').textContent = JSON.stringify(r, null, 1);
  setTimeout(refresh, 1200);
}
async function rapids(){
  const p = new URLSearchParams();
  p.set('ast', document.getElementById('ast').value);
  const r = await J('/99/Rapids', {method:'POST', body:p});
  document.getElementById('rapout').textContent = JSON.stringify(r, null, 1);
  refresh();
}
loadAlgos(); refresh(); setInterval(refresh, 5000);
</script></body></html>
"""


def _send_html(h, body: bytes):
    h.send_response(200)
    h.send_header("Content-Type", "text/html; charset=utf-8")
    h.send_header("Content-Length", str(len(body)))
    h.end_headers()
    if getattr(h, "command", "") != "HEAD":      # RFC 9110: no body
        h.wfile.write(body)


def h_flow(h):
    _send_html(h, FLOW_HTML.encode())


# ---------------------------------------------------------------------------
# Flow notebook (the h2o-web Flow cell model): an ordered list of cells —
# markdown | rapids | import | build | predict — executed top-to-bottom
# against the same REST surface, persisted as named documents through
# /3/NodePersistentStorage/notebooks/<name> (exactly where the reference
# Flow keeps its .flow documents).
NOTEBOOK_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>h2o3-tpu Flow notebook</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f4f6f8;color:#1d2733}
 header{background:#123b57;color:#fff;padding:10px 18px;font-size:18px;display:flex;gap:10px;align-items:center;flex-wrap:wrap}
 header input{font:inherit;padding:3px 6px;border-radius:4px;border:0}
 header a{color:#9fc3dd;font-size:12px}
 #layout{display:grid;grid-template-columns:230px 1fr;gap:12px;max-width:1280px;margin:14px auto;padding:0 10px}
 #side{display:flex;flex-direction:column;gap:10px}
 .pane{background:#fff;border-radius:8px;box-shadow:0 1px 3px rgba(0,0,0,.12);padding:8px 10px;font-size:12px}
 .pane h3{margin:0 0 6px;font-size:12px;color:#345}
 .pane div.item{padding:2px 4px;border-radius:3px;cursor:pointer;white-space:nowrap;overflow:hidden;text-overflow:ellipsis}
 .pane div.item:hover{background:#e8f0f6}
 #cells{display:flex;flex-direction:column;gap:10px}
 .cell{background:#fff;border-radius:8px;box-shadow:0 1px 3px rgba(0,0,0,.12);padding:10px 12px}
 .cell .bar{display:flex;gap:6px;align-items:center;font-size:11px;color:#678}
 .cell textarea{width:100%;font:12px/1.4 ui-monospace,monospace;border:1px solid #dde;border-radius:4px;margin-top:6px;padding:6px;box-sizing:border-box}
 .cell pre{background:#0e1726;color:#d7e3f4;padding:8px;border-radius:6px;font-size:11px;overflow:auto;max-height:220px;margin:6px 0 0}
 .cell .md{padding:4px 2px}
 .cell svg{margin-top:6px;background:#fff}
 button{background:#1b6ca8;color:#fff;border:0;border-radius:4px;cursor:pointer;font-size:12px;padding:3px 8px}
 button.ghost{background:#e4ecf2;color:#246}
 select{font-size:12px}
</style></head><body>
<header>h2o3-tpu &mdash; Flow notebook
 <input id="nbname" value="notebook1" size="12">
 <button onclick="saveNb()">Save</button>
 <button onclick="loadNb()">Load</button>
 <button class="ghost" onclick="runAll()">Run all</button>
 <select id="assist" onchange="assist(this.value)">
  <option value="">Assist...</option>
  <option value="importFiles">importFiles</option>
  <option value="getFrames">getFrames</option>
  <option value="buildModel">buildModel</option>
  <option value="predict">predict</option>
  <option value="pipeline">parse &rarr; train &rarr; predict</option>
 </select>
 <button class="ghost" onclick="exportFlow()">Export .flow</button>
 <label class="ghost" style="background:#e4ecf2;color:#246;border-radius:4px;padding:3px 8px;font-size:12px;cursor:pointer">
  Import .flow<input id="flowfile" type="file" accept=".flow,.json" style="display:none" onchange="importFlow(this.files[0])"></label>
 <span id="status" style="font-size:12px"></span>
 <a href="/">ops dashboard</a>
</header>
<div id="layout">
<div id="side">
 <div class="pane"><h3>Frames</h3><div id="framelist"></div></div>
 <div class="pane"><h3>Models</h3><div id="modellist"></div></div>
</div>
<div>
<div id="cells"></div>
<div style="text-align:center;margin:12px">
 <select id="newtype"><option>rapids</option><option>markdown</option>
  <option>import</option><option>build</option><option>predict</option>
  <option>inspect</option></select>
 <button onclick="addCell()">+ cell</button>
</div>
</div>
</div>
<script>
const J = async (p, o) => (await fetch(p, o)).json();
let cells = [
 {type:'markdown', src:'# New Flow\\nCells run top-to-bottom against the cloud.'},
 {type:'rapids', src:'(+ 1 2)'}];
const PLACEHOLDER = {
 rapids:'(rapids expression)',
 markdown:'# heading\\ntext',
 import:'source_frames=/data/train.csv&destination_frame=train',
 build:'algo=gbm&training_frame=train&response_column=y&ntrees=20',
 predict:'model=gbm_1&frame=train&predictions_frame=preds',
 inspect:'frame-or-model key'};
function render(){
 const host = document.getElementById('cells');
 host.innerHTML='';
 cells.forEach((c,i)=>{
  const d = document.createElement('div'); d.className='cell';
  const md = c.type==='markdown';
  d.innerHTML = `<div class="bar"><b>[${i}] ${c.type}</b>
    <button onclick="runCell(${i})">Run</button>
    <button class="ghost" onclick="moveCell(${i},-1)">&uarr;</button>
    <button class="ghost" onclick="moveCell(${i},1)">&darr;</button>
    <button class="ghost" onclick="delCell(${i})">&times;</button></div>` +
   (md ? `<div class="md" id="md${i}"></div>` : '') +
   `<textarea id="src${i}" rows="${md?3:2}"
      placeholder="${PLACEHOLDER[c.type]||''}"
      oninput="cells[${i}].src=this.value${md?';mdRender('+i+')':''}"></textarea>` +
   `<div id="viz${i}"></div><pre id="out${i}" style="display:none"></pre>`;
  host.appendChild(d);
  document.getElementById('src'+i).value = c.src || '';
  if (md) mdRender(i);
 });
}
function mdRender(i){
 const src = cells[i].src || '';
 const esc = src.replace(/&/g,'&amp;').replace(/</g,'&lt;');
 document.getElementById('md'+i).innerHTML = esc
  .replace(/^### (.*)$/gm,'<h3>$1</h3>').replace(/^## (.*)$/gm,'<h2>$1</h2>')
  .replace(/^# (.*)$/gm,'<h1>$1</h1>')
  .replace(/\\*\\*([^*]+)\\*\\*/g,'<b>$1</b>').replace(/`([^`]+)`/g,'<code>$1</code>')
  .replace(/\\n/g,'<br>');
}
function addCell(t, src){
 cells.push({type: t || document.getElementById('newtype').value, src: src || ''});
 render();
}
function delCell(i){cells.splice(i,1); render();}
function moveCell(i,d){const j=i+d; if(j<0||j>=cells.length)return;
 [cells[i],cells[j]]=[cells[j],cells[i]]; render();}

// ---- assist: generate pre-filled cells from live cluster state --------
async function assist(kind){
 document.getElementById('assist').value='';
 if(!kind) return;
 const fr = (await J('/3/Frames')).frames.map(f=>f.frame_id.name);
 const ms = (await J('/3/Models')).models.map(m=>m.model_id);
 const f0 = fr[0]||'train', m0 = ms[0]||'model1';
 if(kind==='importFiles') addCell('import','source_frames=/path/to.csv&destination_frame=train');
 else if(kind==='getFrames') addCell('rapids',`(nrow ${f0})`);
 else if(kind==='buildModel') addCell('build',`algo=gbm&training_frame=${f0}&response_column=y&ntrees=20`);
 else if(kind==='predict') addCell('predict',`model=${m0}&frame=${f0}&predictions_frame=preds`);
 else if(kind==='pipeline'){
  addCell('import','source_frames=/path/to.csv&destination_frame=train');
  addCell('build','algo=gbm&training_frame=train&response_column=y&ntrees=20&model_id=flow_gbm');
  addCell('predict','model=flow_gbm&frame=train&predictions_frame=preds');
 }
}

// ---- browser panes ----------------------------------------------------
function paneItem(host, name, note){
 // DOM construction, not innerHTML: a hostile frame/model id must render
 // as TEXT, never as markup or a broken onclick (stored-XSS guard)
 const d = document.createElement('div');
 d.className = 'item';
 d.textContent = name + ' ';
 const sp = document.createElement('span');
 sp.style.color = '#9ab'; sp.textContent = note;
 d.appendChild(sp);
 d.onclick = () => addCell('inspect', name);
 host.appendChild(d);
}
async function refreshPanes(){
 try{
  const fh = document.getElementById('framelist'); fh.textContent='';
  (await J('/3/Frames')).frames.slice(0,40).forEach(f=>
   paneItem(fh, f.frame_id.name, `${f.rows}x${f.column_count}`));
  if(!fh.childElementCount) fh.textContent = 'none';
  const mh = document.getElementById('modellist'); mh.textContent='';
  (await J('/3/Models')).models.slice(0,40).forEach(m=>
   paneItem(mh, m.model_id, m.algo));
  if(!mh.childElementCount) mh.textContent = 'none';
 }catch(e){}
}

// ---- inline metric plot: scoring history as a plain SVG line ---------
function sparkline(hist){
 const key = hist[0].training_logloss!=null?'training_logloss':
             hist[0].training_rmse!=null?'training_rmse':
             Object.keys(hist[0]).find(k=>k.startsWith('training_'));
 if(!key) return '';
 const ys = hist.map(h=>h[key]).filter(v=>v!=null&&isFinite(v));
 if(ys.length<2) return '';
 const W=420,H=120,P=28;
 const lo=Math.min(...ys), hi=Math.max(...ys), span=(hi-lo)||1;
 const pts = ys.map((v,i)=>
  `${P+i*(W-2*P)/(ys.length-1)},${H-P-(v-lo)*(H-2*P)/span}`).join(' ');
 return `<svg width="${W}" height="${H}" role="img" aria-label="${key}">`+
  `<line x1="${P}" y1="${H-P}" x2="${W-P}" y2="${H-P}" stroke="#ccd" stroke-width="1"/>`+
  `<polyline points="${pts}" fill="none" stroke="#1b6ca8" stroke-width="2"/>`+
  `<text x="${P}" y="14" font-size="11" fill="#345">${key} (${ys[ys.length-1].toFixed(4)})</text>`+
  `<text x="${P}" y="${H-P+14}" font-size="10" fill="#89a">iterations &rarr;</text></svg>`;
}
function varimpBars(vi){
 // DOM construction like paneItem, not innerHTML: a hostile column name in
 // r.variable must render as TEXT inside the SVG, never as markup
 // (stored-XSS guard)
 const top = vi.slice(0,8);
 const W=420,BH=14,P=120, NS='http://www.w3.org/2000/svg';
 const svg = document.createElementNS(NS,'svg');
 svg.setAttribute('width',W); svg.setAttribute('height',top.length*(BH+4)+10);
 svg.setAttribute('role','img'); svg.setAttribute('aria-label','variable importances');
 top.forEach((r,i)=>{
  const rect = document.createElementNS(NS,'rect');
  rect.setAttribute('x',P); rect.setAttribute('y',6+i*(BH+4));
  rect.setAttribute('width',(W-P-10)*r.scaled_importance);
  rect.setAttribute('height',BH); rect.setAttribute('fill','#1b6ca8');
  svg.appendChild(rect);
  const t = document.createElementNS(NS,'text');
  t.setAttribute('x',P-6); t.setAttribute('y',17+i*(BH+4));
  t.setAttribute('font-size',10); t.setAttribute('fill','#345');
  t.setAttribute('text-anchor','end');
  t.textContent = r.variable;
  svg.appendChild(t);
 });
 return svg;
}
async function plotModel(i, modelId){
 try{
  const m = (await J('/3/Models/'+modelId)).models[0];
  const viz = document.getElementById('viz'+i);
  // sparkline interpolates only server-derived metric names, never ids
  viz.innerHTML = (m.scoring_history && m.scoring_history.length>1)
    ? sparkline(m.scoring_history) : '';
  if(m.variable_importances && m.variable_importances.length)
   viz.appendChild(varimpBars(m.variable_importances));
 }catch(e){}
}

async function runCell(i){
 const c = cells[i];
 c.src = document.getElementById('src'+i).value;
 const out = document.getElementById('out'+i);
 if (c.type==='markdown'){ mdRender(i); return; }
 out.style.display='block'; out.textContent='...';
 try {
  let r;
  if (c.type==='rapids'){
   const p=new URLSearchParams(); p.set('ast', c.src);
   r = await J('/99/Rapids',{method:'POST',body:p});
  } else if (c.type==='import'){
   const p=new URLSearchParams(c.src);
   const s=await J('/3/Parse',{method:'POST',body:p});
   r = await waitJob(s.job && s.job.key) || s;
  } else if (c.type==='build'){
   const p=new URLSearchParams(c.src);
   const algo=p.get('algo'); p.delete('algo');
   const s=await J('/3/ModelBuilders/'+algo,{method:'POST',body:p});
   r = await waitJob(s.job && s.job.key) || s;
   const mid = p.get('model_id') || (r && r.dest);
   if (mid) plotModel(i, mid);
  } else if (c.type==='predict'){
   const p=new URLSearchParams(c.src);
   r = await J(`/3/Predictions/models/${p.get('model')}/frames/${p.get('frame')}`,
     {method:'POST', body:new URLSearchParams({predictions_frame:p.get('predictions_frame')||'preds'})});
  } else if (c.type==='inspect'){
   const key = c.src.trim();
   try { r = (await J('/3/Models/'+key)).models[0]; plotModel(i, key); }
   catch(e){ r = (await J('/3/Frames/'+key+'/summary')).frames[0]; }
  }
  out.textContent = JSON.stringify(r, null, 1).slice(0, 4000);
  refreshPanes();
 } catch(e){ out.textContent = 'ERROR ' + e; }
}
async function waitJob(key){
 if(!key) return null;
 for(let i=0;i<600;i++){
  const j=(await J('/3/Jobs/'+encodeURIComponent(key))).jobs[0];
  if(['DONE','FAILED','CANCELLED'].includes(j.status)) return j;
  await new Promise(r=>setTimeout(r,400));
 }
 return {status:'TIMEOUT'};
}
async function runAll(){for(let i=0;i<cells.length;i++) await runCell(i);}

// ---- persistence: NPS documents + .flow JSON interchange -------------
async function saveNb(){
 const name=document.getElementById('nbname').value||'notebook1';
 const p=new URLSearchParams(); p.set('value', JSON.stringify(cells));
 await J('/3/NodePersistentStorage/notebooks/'+encodeURIComponent(name),{method:'POST',body:p});
 document.getElementById('status').textContent='saved '+new Date().toLocaleTimeString();
}
async function loadNb(){
 const name=document.getElementById('nbname').value||'notebook1';
 try{
  const r=await J('/3/NodePersistentStorage/notebooks/'+encodeURIComponent(name));
  cells=JSON.parse(r.value); render();
  document.getElementById('status').textContent='loaded';
 }catch(e){document.getElementById('status').textContent='not found';}
}
function exportFlow(){
 // reference .flow document shape: {version, cells:[{type:'cs'|'md', input}]}
 const doc = {version:'1.0.0', cells: cells.map(c=>(
  c.type==='markdown' ? {type:'md', input:c.src}
                      : {type:'cs', input:`${c.type} ${c.src}`}))};
 const a = document.createElement('a');
 a.href = URL.createObjectURL(new Blob([JSON.stringify(doc,null,1)],{type:'application/json'}));
 a.download = (document.getElementById('nbname').value||'notebook1')+'.flow';
 a.click();
}
function importFlow(file){
 if(!file) return;
 const rd = new FileReader();
 rd.onload = () => {
  try{
   const doc = JSON.parse(rd.result);
   const arr = doc.cells || doc;         // .flow doc or raw cell list
   cells = arr.map(c=>{
    if(c.type==='md') return {type:'markdown', src:c.input||c.src||''};
    if(c.type==='cs'){
     const inp=(c.input||'').trim();
     const sp=inp.indexOf(' ');
     const head=sp<0?inp:inp.slice(0,sp), rest=sp<0?'':inp.slice(sp+1);
     if(['rapids','import','build','predict','inspect'].includes(head))
      return {type:head, src:rest};
     return {type:'rapids', src:inp};    // foreign coffeescript cells
    }
    return {type:c.type||'rapids', src:c.src||c.input||''};
   });
   render();
   document.getElementById('status').textContent='imported '+file.name;
  }catch(e){document.getElementById('status').textContent='bad .flow: '+e;}
 };
 rd.readAsText(file);
}
render(); refreshPanes(); setInterval(refreshPanes, 7000);
</script></body></html>
"""


def h_notebook(h):
    _send_html(h, NOTEBOOK_HTML.encode())
