"""REST long tail, part 4 — the final route-diff closure against
water/api/RegisterV3Api.java + RegisterV4Api.java + RegisterAlgos.java.

Round-4 verdict asked for zero unexplained absences vs the reference
registry; this module adds every remaining route as either a real
implementation, a same-handler alias (method/path variants), or an
explicit 501 loud-reject with guidance (JVM/external-cluster-only
surfaces). The diff table lives in ROUND5_NOTES.md.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time

import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.core.kvstore import DKV


# ---------------------------------------------------------------------------
# ModelMetrics: frame-scoped listing + DELETE family
# (water/api/ModelMetricsHandler list/delete endpoints)
def _metrics_rows(model_id=None, frame_id=None):
    from h2o3_tpu.models.model import ModelBase
    rows = []
    for k in DKV.keys():
        m = DKV.get(k)
        if not isinstance(m, ModelBase) or m._output is None:
            continue
        if model_id is not None and m.key != model_id:
            continue
        for kind in ("training_metrics", "validation_metrics",
                     "cross_validation_metrics"):
            mm = getattr(m._output, kind, None)
            if mm is None:
                continue
            fr = getattr(mm, "frame_id", None)
            if frame_id is not None and fr != frame_id:
                continue
            rows.append(dict(mm.to_dict(), model={"name": m.key},
                             frame={"name": fr} if fr else None,
                             kind=kind))
    return rows


def _h_metrics_frame(h, fid, mid=None):
    """GET /3/ModelMetrics/frames/{f}[/models/{m}]."""
    rows = _metrics_rows(model_id=mid, frame_id=fid)
    h._send({"__meta": {"schema_type": "ModelMetricsListSchemaV3"},
             "model_metrics": rows})


def _h_metrics_delete(h, *ids):
    """DELETE /3/ModelMetrics[...]: metrics live inside their model's
    output here (no standalone DKV entries), so deletion clears the
    validation/CV metric slots of the matching models."""
    from h2o3_tpu.models.model import ModelBase
    model_id = frame_id = None
    # route variants bind (frame, model) or (model, frame) — resolve by key
    for i in ids:
        if isinstance(DKV.get(i), ModelBase):
            model_id = i
        else:
            frame_id = i
    n = 0
    for k in list(DKV.keys()):
        m = DKV.get(k)
        if not isinstance(m, ModelBase) or m._output is None:
            continue
        if model_id is not None and m.key != model_id:
            continue
        for kind in ("validation_metrics", "cross_validation_metrics"):
            mm = getattr(m._output, kind, None)
            if mm is None:
                continue
            if frame_id is not None and \
                    getattr(mm, "frame_id", None) != frame_id:
                continue
            setattr(m._output, kind, None)
            n += 1
    h._send({"__meta": {"schema_type": "ModelMetricsListSchemaV3"},
             "model_metrics": [], "deleted": n})


# ---------------------------------------------------------------------------
# Frames: single-column schema, GET export variant, binary save/load
def _h_frame_column(h, fid, col):
    f = DKV.get(fid)
    if not isinstance(f, Frame):
        return h._error(f"frame {fid} not found", 404)
    if col not in f.names:
        return h._error(f"column {col} not in {fid}", 404)
    from h2o3_tpu.api.server import _frame_schema
    sch = _frame_schema(f, with_summary=True)
    cols = [c for c in sch["columns"] if c["label"] == col]
    h._send({"__meta": {"schema_type": "FramesV3"},
             "frames": [{"frame_id": {"name": fid}, "columns": cols}]})


def _h_frame_export_get(h, fid, path, force):
    """GET /3/Frames/{id}/export/{path}/overwrite/{force} — the legacy
    path-segment spelling of POST /3/Frames/{id}/export."""
    f = DKV.get(fid)
    if not isinstance(f, Frame):
        return h._error(f"frame {fid} not found", 404)
    import urllib.parse
    dest = urllib.parse.unquote(path)
    if os.path.exists(dest) and force.lower() not in ("true", "1"):
        return h._error(f"{dest} exists and overwrite is false", 412)
    from h2o3_tpu.io.persist import export_frame
    export_frame(f, dest)
    h._send({"__meta": {"schema_type": "FramesV3"}, "path": dest})


def _h_frame_save(h, fid):
    """POST /3/Frames/{id}/save (FramesHandler.save): binary frame
    artifact under {dir}/{frame_id}."""
    p = h._params()
    f = DKV.get(fid)
    if not isinstance(f, Frame):
        return h._error(f"frame {fid} not found", 404)
    d = p.get("dir")
    if not d:
        return h._error("dir is required", 400)
    from h2o3_tpu.io.persist import export_frame
    os.makedirs(d, exist_ok=True)
    dest = os.path.join(d, fid + ".h2o3frame")
    export_frame(f, dest)
    h._send({"__meta": {"schema_type": "FramesV3"}, "dir": d,
             "frames": [{"frame_id": {"name": fid}}]})


def _h_frame_load(h):
    """POST /3/Frames/load: re-import a saved binary frame."""
    p = h._params()
    d, fid = p.get("dir"), p.get("frame_id")
    if not d or not fid:
        return h._error("dir and frame_id are required", 400)
    src = os.path.join(d, fid + ".h2o3frame")
    if not os.path.exists(src):
        return h._error(f"{src} not found", 404)
    from h2o3_tpu.io.persist import import_frame
    f = import_frame(src, key=fid)
    h._send({"__meta": {"schema_type": "FramesV3"},
             "job": None, "frames": [{"frame_id": {"name": f.key}}]})


# ---------------------------------------------------------------------------
# Model artifacts: fetch.bin / 99-scoped bin+mojo+json, upload.bin
def _h_model_fetch_bin(h, mid):
    """GET /3/Models.fetch.bin/{id} (+ /99/Models.bin/{id}): the binary
    model stream h2o.load_model round-trips."""
    m = DKV.get(mid)
    if m is None:
        return h._error(f"model {mid} not found", 404)
    import tempfile
    from h2o3_tpu.genmodel.mojo import save_model
    from h2o3_tpu.api.routes_ext import _send_bytes
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, mid)
        save_model(m, path)
        with open(path, "rb") as fh:
            body = fh.read()
    _send_bytes(h, body, "application/octet-stream", mid)


def _h_model_upload_bin(h, mid):
    """POST /99/Models.upload.bin/{id}: raw binary model body → registry."""
    ln = int(h.headers.get("Content-Length") or 0)
    if ln <= 0:
        return h._error("empty upload", 400)
    body = h.rfile.read(ln)
    import tempfile
    from h2o3_tpu.genmodel.mojo import load_model
    # load_model registers under the artifact's EMBEDDED key — snapshot
    # bindings so an upload can't clobber a live model with the same id
    prev = {k: DKV.get(k) for k in DKV.keys()}
    fd, path = tempfile.mkstemp(prefix="h2o3_model_")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(body)
        m = load_model(path)
    finally:
        os.unlink(path)
    if mid and mid != m.key:
        old_key = m.key
        m.key = mid
        DKV.put(mid, m)
        if old_key in prev:                 # restore the clobbered binding
            DKV.put(old_key, prev[old_key])
        else:
            DKV.remove(old_key)
    h._send({"__meta": {"schema_type": "ModelsV3"},
             "models": [{"model_id": {"name": m.key}}]})


def _h_model_json(h, mid):
    from h2o3_tpu.api.server import _h_model
    return _h_model(h, mid)


def _h_builder_model_id(h, algo):
    """POST /3/ModelBuilders/{algo}/model_id (CalcModelId): a fresh
    default model key for the Flow builder form."""
    h._send({"__meta": {"schema_type": "ModelIdV3"},
             "model_id": {"name": DKV.make_key(algo)}})


# ---------------------------------------------------------------------------
# NodePersistentStorage existence probes + category-level POST
def _h_nps_category_exists(h, categ):
    from h2o3_tpu.api.routes_ext2 import _nps_dir
    h._send({"__meta": {"schema_type": "NodePersistentStorageV3"},
             "category": categ,
             "exists": os.path.isdir(os.path.join(_nps_dir(), categ))})


def _h_nps_name_exists(h, categ, name):
    from h2o3_tpu.api.routes_ext2 import _nps_dir
    h._send({"__meta": {"schema_type": "NodePersistentStorageV3"},
             "category": categ, "name": name,
             "exists": os.path.isfile(
                 os.path.join(_nps_dir(), categ, name))})


def _h_nps_put_auto(h, categ):
    """POST /3/NodePersistentStorage/{categ}: auto-named value put."""
    from h2o3_tpu.api.routes_ext2 import _h_nps_put
    name = f"clip_{int(time.time() * 1000)}"
    return _h_nps_put(h, categ, name)


# ---------------------------------------------------------------------------
# Diagnostics: Profiler, WaterMeterIo
def _h_profiler(h):
    """GET /3/Profiler (water/util/JProfile): stack samples aggregated
    across this runtime's threads — the py analog of the JVM profile.
    Also reports the on-demand session state (obs/profiler, driven by
    POST /3/Profiler): active/kind/dir ride alongside nodes[]."""
    p = h._params()
    depth = int(p.get("depth") or 10)
    import traceback
    counts: dict = {}
    for _ in range(5):
        for tid, frm in sys._current_frames().items():
            stack = traceback.format_stack(frm)[-depth:]
            key = "".join(stack)
            counts[key] = counts.get(key, 0) + 1
        time.sleep(0.02)
    nodes = [{"node_name": "this", "entries": [
        {"stacktrace": k, "count": v}
        for k, v in sorted(counts.items(), key=lambda kv: -kv[1])[:25]]}]
    from h2o3_tpu.obs import profiler as _prof
    h._send({"__meta": {"schema_type": "ProfilerV3"}, "nodes": nodes,
             **_prof.PROFILER.status()})


def _h_watermeter_io(h, node=None):
    """GET /3/WaterMeterIo[/{node}] (water/util/WaterMeterIo): persist-
    layer IO counters; here real process IO from /proc."""
    stats = {}
    try:
        with open("/proc/self/io") as fh:
            for line in fh:
                k, v = line.split(":")
                stats[k.strip()] = int(v)
    except OSError:
        pass
    h._send({"__meta": {"schema_type": "WaterMeterIoV3"},
             "persist_stats": [{
                 "backend": "file",
                 "store_count": stats.get("syscw", 0),
                 "store_bytes": stats.get("write_bytes", 0),
                 "load_count": stats.get("syscr", 0),
                 "load_bytes": stats.get("read_bytes", 0)}]})


def _h_metadata_schemaclass(h, classname):
    """GET /3/Metadata/schemaclasses/{classname} — resolve by schema
    name through the same metadata table as /3/Metadata/schemas."""
    from h2o3_tpu.api.routes_ext2 import _h_metadata_schemas
    return _h_metadata_schemas(h, classname)


# ---------------------------------------------------------------------------
# CloudLock + Sample + v4 surface
def _h_cloud_lock(h):
    """POST /3/CloudLock: the mesh cloud is immutable after init — honor
    the call and echo the (already) locked state."""
    p = h._params()
    h._send({"__meta": {"schema_type": "CloudLockV3"}, "locked": True,
             "reason": p.get("reason") or "api"})


def _h_sample(h):
    from h2o3_tpu.api.server import _h_cloud
    return _h_cloud(h)


def _h_endpoints_v4(h):
    from h2o3_tpu.api.server import ROUTES
    eps = [{"url": f"{m} {p.pattern}", "name": fn.__name__}
           for p, m, fn in ROUTES]
    h._send({"__meta": {"schema_type": "EndpointsListV4"},
             "endpoints": eps, "__http_status": 200})


def _h_job_v4(h, jid):
    from h2o3_tpu.api.server import _h_job
    return _h_job(h, jid)


def _h_frames_simple_v4(h):
    """POST /4/Frames/$simple (CreateFrameSimpleIV4)."""
    from h2o3_tpu.api.routes_ext import _h_create_frame
    return _h_create_frame(h)


def _h_predict_v4(h, mid, fid):
    from h2o3_tpu.api.server import _h_predict
    return _h_predict(h, mid, fid)


# ---------------------------------------------------------------------------
# TargetEncoderTransform (h2o-extensions/target-encoder REST surface)
def _h_te_transform(h):
    """GET/POST /3/TargetEncoderTransform?model=...&frame=... → encoded
    frame (TargetEncoderHandler.transform)."""
    p = h._params()
    m = DKV.get(p.get("model"))
    f = DKV.get(p.get("frame"))
    if m is None or not hasattr(m, "transform"):
        return h._error("target encoder model not found", 404)
    if not isinstance(f, Frame):
        return h._error("frame not found", 404)
    out = m.transform(f, as_training=str(
        p.get("as_training") or "false").lower() == "true")
    h._send({"__meta": {"schema_type": "TargetEncoderTransformV3"},
             "name": out.key})


# ---------------------------------------------------------------------------
# Friedman-Popescu H statistic (hex/tree/FriedmansPopescusH.java):
# H²(j,k) = Σ[pd_jk - pd_j - pd_k]² / Σ pd_jk²  over joint grid values,
# PDs centered, evaluated at the observed (sampled) rows.
def _h_friedmans_h(h):
    p = h._params()
    m = DKV.get(p.get("model"))
    f = DKV.get(p.get("frame"))
    if m is None or not isinstance(f, Frame):
        return h._error("model and frame are required", 404)
    variables = p.get("variables")
    variables = json.loads(variables) if isinstance(variables, str) \
        else (variables or [])
    if len(variables) < 2:
        return h._error("need >= 2 variables", 400)
    hval = friedmans_h(m, f, variables)
    h._send({"__meta": {"schema_type": "FriedmansPopescusHV3"},
             "h": hval})


def friedmans_h(model, frame: Frame, variables, sample: int = 500,
                grid: int = 8, seed: int = 42):
    """H statistic over the joint grid of the given variables."""
    di = model._dinfo
    n = min(frame.nrows, sample)
    sampled = None
    if n < frame.nrows:
        # sample ONCE before the grid loops: the cross-grid scores the
        # design matrix len(grid)^k times — full-frame passes would do
        # millions of discarded predictions on big frames. A seeded
        # uniform draw over ALL rows, not the first n: sorted/clustered
        # frames (by time, by class) would otherwise bias the PDs.
        from h2o3_tpu.rapids.rapids import rapids_exec
        rng = np.random.default_rng(seed)
        ridx = np.sort(rng.choice(frame.nrows, size=n, replace=False))
        idx = " ".join(str(i) for i in ridx)
        frame = sampled = rapids_exec(f"(rows {frame.key} [{idx}])")
    X = di.matrix(frame)
    from h2o3_tpu.explain_data import _grid_for, _set_feature, _score_col

    def pd_over(cols_vals):
        """Mean prediction with the listed (col, value) pins applied."""
        Xg = X
        for c, g, is_cat in cols_vals:
            Xg = _set_feature(di, Xg, c, g, is_cat)
        pr = _score_col(model, Xg)
        if pr.ndim > 1:
            pr = pr[:, 1] if pr.shape[1] == 2 else pr[:, 0]
        return float(np.asarray(pr)[:n].mean())

    grids = {}
    for c in variables:
        g, is_cat = _grid_for(frame, c, grid)
        grids[c] = [(c, gv, is_cat) for gv in g]
    # joint and marginal PDs on the cross grid (centered)
    import itertools
    joint, marg = [], {c: [] for c in variables}
    for combo in itertools.product(*grids.values()):
        joint.append(pd_over(list(combo)))
    for c in variables:
        for pin in grids[c]:
            marg[c].append(pd_over([pin]))
    joint = np.array(joint) - np.mean(joint)
    margs = {c: np.array(v) - np.mean(v) for c, v in marg.items()}
    # broadcast marginals onto the cross grid
    shape = [len(grids[c]) for c in variables]
    J = joint.reshape(shape)
    S = np.zeros(shape)
    for ax, c in enumerate(variables):
        sh = [1] * len(shape)
        sh[ax] = shape[ax]
        S = S + margs[c].reshape(sh)
    if sampled is not None:
        DKV.remove(sampled.key)        # drop the sampled temp frame
    denom = float((J ** 2).sum())
    if denom <= 0:
        return 0.0
    return float(np.sqrt(max(0.0, ((J - S) ** 2).sum() / denom)))


# ---------------------------------------------------------------------------
# Grid binary import/export + resume
def _h_grid_export(h, gid):
    """POST /3/Grid.bin/{id}/export {grid_directory}: every member model
    + the grid manifest as binary artifacts."""
    p = h._params()
    g = DKV.get(gid)
    if g is None:
        return h._error(f"grid {gid} not found", 404)
    d = p.get("grid_directory") or p.get("dir")
    if not d:
        return h._error("grid_directory is required", 400)
    os.makedirs(d, exist_ok=True)
    from h2o3_tpu.genmodel.mojo import save_model
    ids = []
    for m in g.models:
        save_model(m, os.path.join(d, m.key))
        ids.append(m.key)
    with open(os.path.join(d, f"{gid}.grid.json"), "w") as fh:
        json.dump({"grid_id": gid, "model_ids": ids,
                   "hyper_params": {k: list(map(str, v))
                                    for k, v in g.hyper_params.items()}},
                  fh)
    h._send({"__meta": {"schema_type": "GridsV99"}, "grid_id": gid,
             "dir": d})


def _h_grid_import(h):
    """POST /3/Grid.bin/import {grid_path}: reload an exported grid."""
    p = h._params()
    d = p.get("grid_path") or p.get("dir")
    if not d or not os.path.isdir(d):
        return h._error("grid_path directory not found", 404)
    man_files = [x for x in os.listdir(d) if x.endswith(".grid.json")]
    if not man_files:
        return h._error("no .grid.json manifest in directory", 404)
    with open(os.path.join(d, man_files[0])) as fh:
        man = json.load(fh)
    from h2o3_tpu.genmodel.mojo import load_model
    models = []
    for mid in man["model_ids"]:
        mp = os.path.join(d, mid)
        if os.path.exists(mp):
            models.append(load_model(mp))
    from h2o3_tpu.models.grid import H2OGridSearch
    g = H2OGridSearch.__new__(H2OGridSearch)
    g.grid_id = man["grid_id"]
    g.hyper_params = man.get("hyper_params", {})
    g.models = models
    DKV.put(g.grid_id, g)
    h._send({"__meta": {"schema_type": "GridsV99"},
             "grid_id": man["grid_id"], "n_models": len(models)})


def _h_grid_resume(h, algo):
    """POST /99/Grid/{algo}/resume (GridSearchHandler.resume): re-enter
    an EXISTING recoverable grid's train loop — finished combos reload
    from recovery_dir and are skipped; only unfinished ones build."""
    p = h._params()
    gid = p.get("grid_id")
    rd = p.get("recovery_dir")
    if not gid or not rd:
        return h._error("grid_id and recovery_dir are required", 400)
    g = DKV.get(gid)
    from h2o3_tpu.models.grid import H2OGridSearch
    if not isinstance(g, H2OGridSearch):
        return h._error(
            f"grid {gid} not found; import its models first "
            "(POST /3/Grid.bin/import) or rebuild via POST /99/Grid", 404)
    g.recovery_dir = rd
    frame = DKV.get(p.get("training_frame") or "")
    if not isinstance(frame, Frame):
        return h._error("training_frame is required for resume", 400)
    from h2o3_tpu.core.jobs import Job
    job = Job(description=f"resume grid {gid}", dest=gid)

    def work(job):
        g.train(x=None, y=p.get("response_column") or p.get("y"),
                training_frame=frame)
        return g

    job.start(work)
    h._send({"__meta": {"schema_type": "GridSearchV99"},
             "job": job.to_dict(), "grid_id": gid})


# ---------------------------------------------------------------------------
# Loud rejects: external-cluster / JVM-only surfaces
def _h_xgb_executor(h, *_):
    h._error(
        "XGBoostExecutor.* is the reference's RPC seam to an external "
        "XGBoost cluster (hex/tree/xgboost/exec). This runtime trains "
        "its XGBoost emulation in-process on the TPU mesh — use "
        "POST /3/ModelBuilders/xgboost", 501)


def _h_import_sql_99(h):
    from h2o3_tpu.api.routes_ext import _h_import_sql
    return _h_import_sql(h)


# ===========================================================================

# handlers that start a background Job — quota-prepaid at the REST
# edge before the replay broadcast (see api/server.starts_job)
_h_grid_resume._starts_job = True
# scoring handler — QoS admission at the REST edge before the replay
# broadcast (see api/server.scores)
_h_predict_v4._scores = True

def build_routes():
    R = re.compile
    from h2o3_tpu.api import routes_ext as E1
    from h2o3_tpu.api import routes_ext2 as E2
    from h2o3_tpu.api import routes_ext3 as E3
    from h2o3_tpu.api import server as S
    return [
        # ModelMetrics family
        (R(r"/3/ModelMetrics/frames/([^/]+)"), "GET", _h_metrics_frame),
        (R(r"/3/ModelMetrics/frames/([^/]+)/models/([^/]+)"), "GET",
         _h_metrics_frame),
        (R(r"/3/ModelMetrics"), "DELETE", _h_metrics_delete),
        (R(r"/3/ModelMetrics/models/([^/]+)"), "DELETE", _h_metrics_delete),
        (R(r"/3/ModelMetrics/frames/([^/]+)"), "DELETE", _h_metrics_delete),
        (R(r"/3/ModelMetrics/models/([^/]+)/frames/([^/]+)"), "DELETE",
         _h_metrics_delete),
        (R(r"/3/ModelMetrics/frames/([^/]+)/models/([^/]+)"), "DELETE",
         _h_metrics_delete),
        # Frames
        (R(r"/3/Frames/([^/]+)/columns/([^/]+)"), "GET", _h_frame_column),
        (R(r"/3/Frames/([^/]+)/export/(.+)/overwrite/([^/]+)"), "GET",
         _h_frame_export_get),
        (R(r"/3/Frames/([^/]+)/save"), "POST", _h_frame_save),
        (R(r"/3/Frames/load"), "POST", _h_frame_load),
        # Model artifacts
        (R(r"/3/Models\.fetch\.bin/([^/]+)"), "GET", _h_model_fetch_bin),
        (R(r"/99/Models\.bin/([^/]+)"), "GET", _h_model_fetch_bin),
        (R(r"/99/Models\.mojo/([^/]+)"), "GET", E1._h_model_mojo),
        (R(r"/99/Models/([^/]+)/json"), "GET", _h_model_json),
        (R(r"/99/Models\.upload\.bin/([^/]*)"), "POST",
         _h_model_upload_bin),
        (R(r"/3/ModelBuilders/([^/]+)/model_id"), "POST",
         _h_builder_model_id),
        # NPS
        (R(r"/3/NodePersistentStorage/categories/([^/]+)/exists"), "GET",
         _h_nps_category_exists),
        (R(r"/3/NodePersistentStorage/categories/([^/]+)/names/([^/]+)/"
           r"exists"), "GET", _h_nps_name_exists),
        (R(r"/3/NodePersistentStorage/([^/]+)"), "POST", _h_nps_put_auto),
        # Diagnostics
        (R(r"/3/Profiler"), "GET", _h_profiler),
        (R(r"/3/WaterMeterIo"), "GET", _h_watermeter_io),
        (R(r"/3/WaterMeterIo/([^/]+)"), "GET", _h_watermeter_io),
        (R(r"/3/Metadata/schemaclasses/([^/]+)"), "GET",
         _h_metadata_schemaclass),
        # Cloud / misc
        (R(r"/3/CloudLock"), "POST", _h_cloud_lock),
        (R(r"/3/Cloud"), "HEAD", S._h_cloud),
        (R(r"/99/Sample"), "GET", _h_sample),
        (R(r"/3/UnlockKeys"), "POST", E1._h_unlock),
        # v4 API
        (R(r"/4/endpoints"), "GET", _h_endpoints_v4),
        (R(r"/4/jobs/([^/]+)"), "GET", _h_job_v4),
        (R(r"/4/Frames/\$simple"), "POST", _h_frames_simple_v4),
        (R(r"/4/Predictions/models/([^/]+)/frames/([^/]+)"), "POST",
         _h_predict_v4),
        # target encoding + H statistic
        (R(r"/3/TargetEncoderTransform"), "GET", _h_te_transform),
        (R(r"/3/TargetEncoderTransform"), "POST", _h_te_transform),
        (R(r"/3/FriedmansPopescusH"), "POST", _h_friedmans_h),
        # grid binary + resume
        (R(r"/3/Grid\.bin/import"), "POST", _h_grid_import),
        (R(r"/3/Grid\.bin/([^/]+)/export"), "POST", _h_grid_export),
        (R(r"/99/Grid/([^/]+)/resume"), "POST", _h_grid_resume),
        # method/path aliases of existing handlers
        (R(r"/3/ImportFiles"), "POST", S._h_import),
        (R(r"/3/ImportFilesMulti"), "POST", E2._h_import_files_multi),
        (R(r"/3/ParseSVMLight"), "POST", E1._h_parse_svmlight),
        (R(r"/3/PartialDependence/"), "POST", E1._h_pdp_build),
        (R(r"/3/Recovery/resume"), "POST", E1._h_recovery_resume),
        (R(r"/99/DCTTransformer"), "POST", E3._h_dct),
        (R(r"/99/ImportSQLTable"), "POST", _h_import_sql_99),
        (R(r"/3/DataInfoFrame"), "POST", E2._h_data_info_frame),
        (R(r"/3/SegmentModelsBuilders/([^/]+)"), "POST",
         E2._h_segment_build),
        (R(r"/3/ComputeGram"), "GET", E1._h_compute_gram),
        (R(r"/3/Word2VecSynonyms"), "GET", E1._h_w2v_synonyms),
        (R(r"/3/Word2VecTransform"), "GET", E1._h_w2v_transform),
        # external-cluster loud-rejects
        (R(r"/3/XGBoostExecutor\.init"), "POST", _h_xgb_executor),
        (R(r"/3/XGBoostExecutor\.setup"), "POST", _h_xgb_executor),
        (R(r"/3/XGBoostExecutor\.update"), "POST", _h_xgb_executor),
        (R(r"/3/XGBoostExecutor\.getBooster"), "POST", _h_xgb_executor),
        (R(r"/3/XGBoostExecutor\.cleanup"), "POST", _h_xgb_executor),
    ]
