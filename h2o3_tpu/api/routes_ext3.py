"""REST long tail, part 3 — upload, transforms, model insight and
pipeline routes from RequestServer.java's registry: PostFile (the
h2o.upload_file channel), DCTTransformer, FeatureInteraction,
fairness metrics, Assembly (munging pipelines), SteamMetrics, plus the
remaining alias/loud-reject entries."""

from __future__ import annotations

import json
import os
import re
import tempfile

import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.core.kvstore import DKV


# ---------------------------------------------------------------------------
def _h_post_file(h):
    """POST /3/PostFile (PostFileHandler): upload a file body and stage it
    server-side; h2o.upload_file then parses the staged key. Accepts raw
    bodies and single-part multipart/form-data."""
    if getattr(getattr(h, "server", None), "broadcaster", None) is not None:
        # multi-host cloud: the body would stage on this process only and
        # the later broadcast /3/Parse would diverge across workers
        return h._error(
            "PostFile bodies cannot ride the SPMD replay channel; "
            "stage files on shared storage and use ImportFiles", 501)
    ln = int(h.headers.get("Content-Length") or 0)
    if ln <= 0:
        return h._error("empty upload", 400)
    body = h.rfile.read(ln)
    ctype = h.headers.get("Content-Type", "")
    if "multipart/form-data" in ctype and b"\r\n\r\n" in body:
        # strip the (single) part envelope: headers end at CRLFCRLF, the
        # trailing boundary starts at the last CRLF--
        start = body.index(b"\r\n\r\n") + 4
        end = body.rfind(b"\r\n--")
        body = body[start:end if end > start else len(body)]
    import urllib.parse
    q = urllib.parse.parse_qs(urllib.parse.urlparse(h.path).query)
    dest = (q.get("destination_frame") or [None])[0] or \
        DKV.make_key("upload")
    fd, path = tempfile.mkstemp(prefix="h2o3_upload_",
                                suffix=os.path.splitext(dest)[1] or ".csv")
    with os.fdopen(fd, "wb") as fh:
        fh.write(body)
    # remember the staged path under the destination key; /3/Parse with
    # source_frames=<dest> then parses (and deletes) it (h2o-py upload
    # flow); the table is bounded against never-parsed uploads
    _evict_stale_uploads()
    _UPLOADS[dest] = path
    h._send({"__meta": {"schema_type": "PostFileV3"},
             "destination_frame": dest, "total_bytes": len(body)})


_UPLOADS: dict = {}
_UPLOADS_MAX = 64


def staged_upload_path(key: str):
    """/3/Parse hook: resolve an uploaded pseudo-key to its temp file."""
    return _UPLOADS.get(key)


def consume_upload(key: str) -> None:
    """Delete the staged temp file once its parse consumed it."""
    path = _UPLOADS.pop(key, None)
    if path:
        try:
            os.unlink(path)
        except OSError:
            pass


def _evict_stale_uploads() -> None:
    """Bound the staging table: never-parsed uploads are dropped
    oldest-first once the cap is hit (insertion-ordered dict)."""
    while len(_UPLOADS) >= _UPLOADS_MAX:
        consume_upload(next(iter(_UPLOADS)))


# ---------------------------------------------------------------------------
def _h_dct(h):
    """POST /3/DCTTransformer (util/DCTTransformer.java): DCT-II of the
    numeric columns (the deep-learning image-preprocessing transform)."""
    try:
        from scipy.fft import dct
    except ImportError:
        return h._error("DCTTransformer requires scipy, which this "
                        "deployment does not ship", 501)
    p = h._params()
    f = DKV.get(p.get("dataset") or p.get("frame"))
    if not isinstance(f, Frame):
        return h._error("dataset not found", 404)
    num_cols = [c for c in f.names if f.vec(c).type == "num"]
    X = np.column_stack([f.vec(c).to_numpy() for c in num_cols])
    Y = dct(np.nan_to_num(X), axis=1, norm="ortho")
    dest = p.get("destination_frame") or DKV.make_key("dct")
    out = Frame.from_dict(
        {f"DCT_{j}": Y[:, j] for j in range(Y.shape[1])}, key=dest)
    DKV.put(dest, out)
    h._send({"__meta": {"schema_type": "DCTTransformerV3"},
             "dest": {"name": dest}})


# ---------------------------------------------------------------------------
def _h_feature_interaction(h):
    """POST /3/FeatureInteraction (xgboost FeatureInteractions): ranked
    feature pairs from parent→child split adjacency over the ensemble,
    reporting FScore (path count) and cover; the reference additionally
    integrates per-node gain, which the packed tree arrays don't retain."""
    from h2o3_tpu.models.model import ModelBase
    p = h._params()
    m = DKV.get(p.get("model") or p.get("model_id"))
    if not isinstance(m, ModelBase):
        return h._error("model not found", 404)
    ta = getattr(m, "_trees", None)
    if ta is None:
        return h._error("model has no tree arrays", 400)
    col = np.asarray(ta.col)
    cover = np.asarray(ta.cover) if ta.cover is not None else \
        np.ones_like(col, np.float32)
    names = m._dinfo.feature_names
    pairs: dict = {}
    T, nodes = col.shape
    for t in range(T):
        for n in range((nodes - 1) // 2):
            cp = col[t, n]
            if cp < 0:
                continue
            for child in (2 * n + 1, 2 * n + 2):
                if child < nodes and col[t, child] >= 0:
                    key = (int(cp), int(col[t, child]))
                    f_cnt, c_sum = pairs.get(key, (0, 0.0))
                    pairs[key] = (f_cnt + 1,
                                  c_sum + float(cover[t, child]))
    rows = sorted(
        ({"feature_pair": f"{names[a]}|{names[b]}",
          "fscore": cnt, "cover": cov}
         for (a, b), (cnt, cov) in pairs.items()),
        key=lambda r: -r["fscore"])
    h._send({"__meta": {"schema_type": "FeatureInteractionV3"},
             "feature_interaction": rows[:int(p.get("max_interactions")
                                              or 100)]})


# ---------------------------------------------------------------------------
def _h_fairness(h):
    """POST /99/FairnessMetrics (the h2o.inspect_model_fairness surface):
    per-protected-group confusion/selection metrics + adverse impact
    ratios against a reference group."""
    from h2o3_tpu.models.model import ModelBase
    p = h._params()
    m = DKV.get(p.get("model"))
    f = DKV.get(p.get("frame"))
    if not isinstance(m, ModelBase) or not isinstance(f, Frame):
        return h._error("model/frame not found", 404)
    prot = p.get("protected_columns")
    prot = json.loads(prot) if isinstance(prot, str) else prot
    if not prot:
        return h._error("protected_columns required", 400)
    pred = m.predict(f)
    pp = pred.vecs[-1].to_numpy()          # p(positive) / prediction
    DKV.remove(pred.key)                   # scratch frame: don't leak
    di = m._dinfo
    y = np.asarray(f.vec(di.response_name).to_numpy())
    if di.response_domain is not None and y.dtype.kind == "f":
        pos = y == 1.0
    else:
        pos = y > 0.5
    groups = {}
    for c in prot:
        v = f.vec(c)
        dom = v.levels() or []
        codes = v.to_numpy()[: f.nrows]
        for li, lvl in enumerate(dom):
            mask = codes == li
            n = int(mask.sum())
            if n == 0:
                continue
            sel = pp[mask] > 0.5
            acc = float((sel == pos[mask]).mean())
            groups[f"{c}.{lvl}"] = {
                "n": n, "selection_rate": float(sel.mean()),
                "accuracy": acc,
                "tpr": float(sel[pos[mask]].mean())
                if pos[mask].any() else float("nan")}
    ref = max(groups, key=lambda g: groups[g]["n"]) if groups else None
    for g, row in groups.items():
        base = groups[ref]["selection_rate"] if ref else 0.0
        row["air"] = (row["selection_rate"] / base) if base else float("nan")
    h._send({"__meta": {"schema_type": "FairnessMetricsV99"},
             "reference_group": ref, "groups": groups})


# ---------------------------------------------------------------------------
def _h_assembly(h):
    """POST /99/Assembly (water/rapids/Assembly.java): a named pipeline of
    munging steps applied in order — steps is a JSON list of Rapids ASTs
    where `{frame}` substitutes the current frame key."""
    p = h._params()
    f = DKV.get(p.get("frame"))
    if not isinstance(f, Frame):
        return h._error("frame not found", 404)
    steps = p.get("steps")
    steps = json.loads(steps) if isinstance(steps, str) else (steps or [])
    from h2o3_tpu.rapids.rapids import rapids_exec
    cur = f
    inter: list = []
    for i, ast in enumerate(steps):
        out = rapids_exec(ast.replace("{frame}", cur.key))
        if not isinstance(out, Frame):
            return h._error(f"assembly step {i} did not produce a frame",
                            400)
        if cur is not f:
            inter.append(cur.key)     # superseded intermediate
        cur = out                     # rapids already registered its key
    dest = p.get("dest") or DKV.make_key("assembly")
    if cur is f:
        # identity pipeline: register a fresh handle under dest instead of
        # stealing the source frame's key (the old DKV binding would still
        # point at the re-keyed object)
        cur = Frame(list(f.names), list(f.vecs), key=dest)
    else:
        DKV.remove(cur.key)           # re-key the final frame cleanly
        cur.key = dest
        DKV.put(dest, cur)
    for k in inter:                   # drop step intermediates
        DKV.remove(k)
    aid = p.get("assembly_id") or DKV.make_key("assembly_def")
    DKV.put(aid, {"steps": steps})
    h._send({"__meta": {"schema_type": "AssemblyV99"},
             "assembly": {"name": aid}, "result": {"name": dest}})


def _h_assembly_pojo(h, aid, name):
    h._error(
        "Assembly-to-POJO codegen (MungeTask java emission) is not "
        "implemented; score assemblies server-side via POST /99/Assembly "
        "or export the resulting frame", 501)


def _h_scala_int(h, *_):
    h._error("the Scala REPL (h2o-scala scalaint) requires a JVM, which "
             "this runtime does not ship; use the Rapids console or the "
             "Python client", 501)


def _h_steam_metrics(h):
    """GET /3/SteamMetrics: the Enterprise-Steam keepalive metric set."""
    import time
    import h2o3_tpu
    info = h2o3_tpu.cluster_info()
    h._send({"__meta": {"schema_type": "SteamMetricsV3"},
             "cluster_size": info["cloud_size"],
             "healthy": True, "timestamp_millis": int(time.time() * 1000)})


def _h_builder_params_get(h, algo):
    """GET /3/ModelBuilders/{algo}/parameters: the builder's parameter
    schema (codegen clients read this)."""
    from h2o3_tpu.models import ESTIMATORS
    cls = ESTIMATORS.get(algo)
    if cls is None:
        return h._error(f"unknown algo {algo}", 404)
    defaults = getattr(cls, "_defaults", {})
    h._send({"__meta": {"schema_type": "ModelParametersSchemaV3"},
             "parameters": [{"name": k, "default_value": v,
                             "type": type(v).__name__}
                            for k, v in sorted(defaults.items())]})


def _h_ping99(h):
    import time
    h._send({"__meta": {"schema_type": "PingV3"},
             "status": "running",
             "timestamp_millis": int(time.time() * 1000)})


def _h_job_delete(h, key):
    """DELETE /3/Jobs/{id}: cancel alias (JobsHandler)."""
    from h2o3_tpu.core.jobs import Job
    j = DKV.get(key)
    if not isinstance(j, Job):
        return h._error(f"job {key} not found", 404)
    j.stop()
    h._send({"__meta": {"schema_type": "JobsV3"}, "jobs": [j.to_dict()]})


# ---------------------------------------------------------------------------
def build_routes():
    R = re.compile
    return [
        (R(r"/3/PostFile"), "POST", _h_post_file),
        (R(r"/3/PostFile\.bin"), "POST", _h_post_file),
        (R(r"/3/DCTTransformer"), "POST", _h_dct),
        (R(r"/3/FeatureInteraction"), "POST", _h_feature_interaction),
        (R(r"/99/FairnessMetrics"), "POST", _h_fairness),
        (R(r"/99/Assembly"), "POST", _h_assembly),
        (R(r"/99/Assembly\.java/([^/]+)/([^/]+)"), "GET",
         _h_assembly_pojo),
        (R(r"/3/scalaint"), "POST", _h_scala_int),
        (R(r"/3/scalaint/([^/]+)"), "POST", _h_scala_int),
        (R(r"/3/SteamMetrics"), "GET", _h_steam_metrics),
        (R(r"/3/ModelBuilders/([^/]+)/parameters"), "GET",
         _h_builder_params_get),
        (R(r"/99/Ping"), "GET", _h_ping99),
        (R(r"/3/Jobs/([^/]+)"), "DELETE", _h_job_delete),
    ]
