"""Explanation figure set — h2o-py/h2o/explanation/_explain.py analog.

The reference renders matplotlib figures for SHAP summaries / row
explanations, partial dependence, ICE, variable importance, learning
curves, and cross-model heatmaps, bundled by ``h2o.explain``. Same
surface here over the native artifacts: TreeSHAP contributions come from
``predict_contributions`` (native/treeshap.cpp), PDP/ICE/varimp data
from ``h2o3_tpu.explain_data``.

All functions return a ``matplotlib.figure.Figure`` and never call
``plt.show()`` (headless-safe; callers/notebooks render them).

Style: one restrained categorical blue for magnitude bars, a blue↔orange
diverging scale with a neutral gray midpoint for signed feature values,
recessive grids, horizontal bars for ranked importances.
"""

from __future__ import annotations

import numpy as np

try:
    import matplotlib
except ImportError as _e:                          # pragma: no cover
    raise ImportError(
        "the explanation figure set needs matplotlib — install "
        "h2o3-tpu[full] (data-only explanations live in "
        "h2o3_tpu.explain_data)") from _e
matplotlib.use("Agg")
import matplotlib.cm as _cm                        # noqa: E402
from matplotlib.colors import LinearSegmentedColormap  # noqa: E402
from matplotlib.figure import Figure               # noqa: E402


def _fig(figsize):
    """A Figure OUTSIDE pyplot's global registry: repeated plot calls in
    a long-lived server must not accumulate figures (review r5)."""
    fig = Figure(figsize=figsize)
    return fig, fig.add_subplot()

from h2o3_tpu import explain_data as _ex                # noqa: E402
from h2o3_tpu.core.frame import Frame              # noqa: E402
from h2o3_tpu.core.kvstore import DKV              # noqa: E402

_BLUE = "#4477aa"
_ORANGE = "#ee7733"
_GRAY = "#bbbbbb"
# diverging: two hues + neutral midpoint (never a hue at the center)
_DIVERGING = LinearSegmentedColormap.from_list(
    "h2o3_div", [_BLUE, "#c8c8c8", _ORANGE])


def _style(ax):
    ax.grid(True, axis="both", color="#e6e6e6", linewidth=0.6, zorder=0)
    for s in ("top", "right"):
        ax.spines[s].set_visible(False)


def _contributions(model, frame: Frame):
    """(n, k) contribution matrix + feature names (BiasTerm dropped)."""
    cf = model.predict_contributions(frame)
    names = [c for c in cf.names if c != "BiasTerm"]
    M = np.column_stack([cf.vec(c).to_numpy() for c in names])
    DKV.remove(cf.key)
    return M, names


def _feature_matrix(model, frame: Frame, names):
    cols = []
    for c in names:
        v = frame.vec(c)
        x = v.to_numpy().astype(np.float64)
        cols.append(x)
    return np.column_stack(cols)


def shap_summary_plot(model, frame: Frame, top_n: int = 20,
                      sample_size: int = 1000, figsize=(9, 6)):
    """Beeswarm of per-row SHAP contributions, one strip per feature,
    colored by normalized feature value (reference shap_summary_plot)."""
    n = min(frame.nrows, sample_size)
    sub = frame if frame.nrows == n else _sample_frame(frame, n)
    M, names = _contributions(model, sub)
    X = _feature_matrix(model, sub, names)
    if sub is not frame:
        DKV.remove(sub.key)
    order = np.argsort(np.abs(M).mean(0))[::-1][:top_n]
    fig, ax = _fig(figsize)
    rng = np.random.default_rng(0)
    for pos, j in enumerate(order[::-1]):
        x = M[:, j]
        fv = X[:, j]
        lo, hi = np.nanmin(fv), np.nanmax(fv)
        cv = (fv - lo) / (hi - lo) if hi > lo else np.full_like(fv, 0.5)
        cv = np.nan_to_num(cv, nan=0.5)
        jitter = rng.normal(0, 0.08, len(x))
        ax.scatter(x, pos + jitter, c=cv, cmap=_DIVERGING, s=9,
                   linewidths=0, alpha=0.8, zorder=3)
    ax.set_yticks(range(len(order)))
    ax.set_yticklabels([names[j] for j in order[::-1]])
    ax.axvline(0, color="#888888", linewidth=0.8, zorder=2)
    ax.set_xlabel("SHAP contribution")
    ax.set_title(f"SHAP summary — {model.model_id}")
    sm = _cm.ScalarMappable(cmap=_DIVERGING)
    cb = fig.colorbar(sm, ax=ax, ticks=[0, 1])
    cb.ax.set_yticklabels(["low", "high"])
    cb.set_label("feature value")
    _style(ax)
    fig.tight_layout()
    return fig


def shap_explain_row_plot(model, frame: Frame, row_index: int,
                          top_n: int = 10, figsize=(9, 5)):
    """Signed contribution bars for ONE row (reference
    shap_explain_row_plot)."""
    sub = _slice_rows(frame, [row_index])
    M, names = _contributions(model, sub)
    X = _feature_matrix(model, sub, names)
    DKV.remove(sub.key)
    vals = M[0]
    order = np.argsort(np.abs(vals))[::-1][:top_n][::-1]
    fig, ax = _fig(figsize)
    colors = [_ORANGE if vals[j] > 0 else _BLUE for j in order]
    ax.barh(range(len(order)), vals[order], color=colors, height=0.62,
            zorder=3)
    ax.set_yticks(range(len(order)))
    ax.set_yticklabels([f"{names[j]} = {X[0, j]:.4g}" for j in order])
    ax.axvline(0, color="#888888", linewidth=0.8)
    ax.set_xlabel("SHAP contribution")
    ax.set_title(f"SHAP row {row_index} — {model.model_id}")
    _style(ax)
    fig.tight_layout()
    return fig


def pd_plot(model, frame: Frame, column: str, nbins: int = 20,
            figsize=(8, 5)):
    """Partial-dependence line (numeric) or bars (categorical) with the
    mean-response reference line (reference pd_plot)."""
    pd_data = _ex.partial_dependence(model, frame, column, nbins)
    grid, pd_vals = pd_data["grid"], pd_data["mean_response"]
    fig, ax = _fig(figsize)
    if isinstance(grid[0], str):
        ax.bar(range(len(grid)), pd_vals, color=_BLUE, width=0.62, zorder=3)
        ax.set_xticks(range(len(grid)))
        ax.set_xticklabels(grid, rotation=30, ha="right")
    else:
        ax.plot(grid, pd_vals, color=_BLUE, linewidth=2, zorder=3)
        # data-density rug
        x = frame.vec(column).to_numpy()
        x = x[~np.isnan(x)][:1000]
        ax.plot(x, np.full(len(x), ax.get_ylim()[0]), "|",
                color="#888888", markersize=5, alpha=0.4)
    ax.set_xlabel(column)
    ax.set_ylabel("mean response")
    ax.set_title(f"Partial dependence — {column}")
    _style(ax)
    fig.tight_layout()
    return fig


def ice_plot(model, frame: Frame, column: str, nbins: int = 20,
             n_rows: int = 30, figsize=(8, 5)):
    """Individual conditional expectation curves + the PD centerline."""
    frac = min(1.0, n_rows / max(frame.nrows, 1))
    grid, curves = _ex.ice(model, frame, column, nbins, frac)
    fig, ax = _fig(figsize)
    for c in curves:
        ax.plot(grid, c, color=_GRAY, linewidth=0.7, alpha=0.6, zorder=2)
    ax.plot(grid, np.mean(curves, axis=0), color=_ORANGE, linewidth=2.4,
            zorder=3, label="mean (PD)")
    ax.legend(frameon=False)
    ax.set_xlabel(column)
    ax.set_ylabel("response")
    ax.set_title(f"ICE — {column}")
    _style(ax)
    fig.tight_layout()
    return fig


def varimp_plot(model, num_of_features: int = 10, figsize=(8, 5)):
    """Ranked scaled-importance bars (reference varimp_plot)."""
    vi = model.varimp()
    if not vi:
        raise ValueError(f"{model.algo} has no variable importances")
    vi = vi[:num_of_features][::-1]
    fig, ax = _fig(figsize)
    ax.barh([r["variable"] for r in vi],
            [r["scaled_importance"] for r in vi],
            color=_BLUE, height=0.62, zorder=3)
    ax.set_xlabel("scaled importance")
    ax.set_title(f"Variable importance — {model.model_id}")
    _style(ax)
    fig.tight_layout()
    return fig


def learning_curve_plot(model, metric: str = "AUTO", figsize=(8, 5)):
    """Training/validation series from the scoring history."""
    data = _ex.learning_curve(model)
    if not data:
        raise ValueError("model has no scoring history")
    fig, ax = _fig(figsize)
    series = data["series"]
    if metric != "AUTO":
        series = {k: v for k, v in series.items() if k.endswith(metric)}
    palette = [_BLUE, _ORANGE, "#228833", "#aa3377"]
    for i, (k, v) in enumerate(sorted(series.items())):
        vals = [np.nan if x is None else x for x in v]
        ax.plot(data["x"], vals, label=k,
                color=palette[i % len(palette)], linewidth=2)
    if len(series) > 1:
        ax.legend(frameon=False, fontsize=8)
    ax.set_xlabel("iterations")
    ax.set_title(f"Learning curve — {model.model_id}")
    _style(ax)
    fig.tight_layout()
    return fig


def varimp_heatmap(models, figsize=(8, 5)):
    """Feature × model heatmap of scaled importances (sequential, one
    hue light→dark)."""
    feats, names, M = _ex.varimp_heatmap(models)
    fig, ax = _fig(figsize)
    im = ax.imshow(M, cmap="Blues", aspect="auto", vmin=0, vmax=1)
    ax.set_xticks(range(len(names)))
    ax.set_xticklabels(names, rotation=30, ha="right", fontsize=8)
    ax.set_yticks(range(len(feats)))
    ax.set_yticklabels(feats, fontsize=8)
    fig.colorbar(im, ax=ax, label="scaled importance")
    ax.set_title("Variable importance heatmap")
    fig.tight_layout()
    return fig


def model_correlation_heatmap(models, frame: Frame, figsize=(7, 6)):
    """Model × model prediction-correlation heatmap."""
    names, C = _ex.model_correlation(models, frame)
    fig, ax = _fig(figsize)
    im = ax.imshow(C, cmap=_DIVERGING, vmin=-1, vmax=1)
    ax.set_xticks(range(len(names)))
    ax.set_xticklabels(names, rotation=30, ha="right", fontsize=8)
    ax.set_yticks(range(len(names)))
    ax.set_yticklabels(names, fontsize=8)
    for i in range(len(names)):
        for j in range(len(names)):
            ax.text(j, i, f"{C[i, j]:.2f}", ha="center", va="center",
                    fontsize=7, color="#333333")
    fig.colorbar(im, ax=ax, label="prediction correlation")
    ax.set_title("Model correlation")
    fig.tight_layout()
    return fig


# ---------------------------------------------------------------------------
def explain(models, frame: Frame, columns: int = 3,
            include_explanations=None, render: bool = False):
    """h2o.explain analog: ordered dict of figures (and data) per the
    reference's explanation plan — leaderboard-style correlation + varimp
    heatmap for multi-model input; SHAP summary, varimp, PDP and learning
    curve for a single model. ``render=False`` returns the figures."""
    models = models if isinstance(models, (list, tuple)) else [models]
    out = {}
    m0 = models[0]
    if len(models) > 1:
        out["model_correlation_heatmap"] = model_correlation_heatmap(
            models, frame)
        with_vi = [m for m in models if m.varimp()]
        if len(with_vi) > 1:
            out["varimp_heatmap"] = varimp_heatmap(with_vi)
    if m0.varimp():
        out["varimp_plot"] = varimp_plot(m0)
        top = [r["variable"] for r in m0.varimp()[:columns]]
    else:
        top = list(m0._dinfo.feature_names[:columns])
    if hasattr(m0, "predict_contributions"):
        try:
            out["shap_summary_plot"] = shap_summary_plot(m0, frame)
        except Exception:        # noqa: BLE001 — SHAP needs tree models
            pass
    out["pd_plots"] = {
        c: pd_plot(m0, frame, c)
        for c in top if c in m0._dinfo.predictors}
    try:
        out["learning_curve_plot"] = learning_curve_plot(m0)
    except ValueError:
        pass
    return out


def explain_row(models, frame: Frame, row_index: int, columns: int = 3):
    """h2o.explain_row analog: per-row SHAP bars + ICE curves."""
    models = models if isinstance(models, (list, tuple)) else [models]
    m0 = models[0]
    out = {}
    if hasattr(m0, "predict_contributions"):
        try:
            out["shap_explain_row_plot"] = shap_explain_row_plot(
                m0, frame, row_index)
        except Exception:        # noqa: BLE001
            pass
    if m0.varimp():
        top = [r["variable"] for r in m0.varimp()[:columns]]
    else:
        top = list(m0._dinfo.feature_names[:columns])
    out["ice_plots"] = {c: ice_plot(m0, frame, c)
                        for c in top if c in m0._dinfo.predictors}
    return out


# ---------------------------------------------------------------------------
def _sample_frame(frame: Frame, n: int) -> Frame:
    idx = np.random.default_rng(0).choice(frame.nrows, n, replace=False)
    return _slice_rows(frame, np.sort(idx))


def _slice_rows(frame: Frame, rows) -> Frame:
    from h2o3_tpu.rapids.rapids import rapids_exec
    lst = " ".join(str(int(i)) for i in rows)
    out = rapids_exec(f"(rows {frame.key} [{lst}])")
    return out
