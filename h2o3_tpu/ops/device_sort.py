"""Device-resident sort / merge / group-by — the Rapids munger hot path.

Reference design: water/rapids/Merge.java + RadixOrder.java +
SplitByMSBLocal.java (distributed MSB-radix order of the key columns, then
per-partition binary merge) and ast/prims/mungers/AstGroup.java (per-group
aggregates via one MRTask).

TPU-native: XLA's bitonic sort IS the radix order (jnp.lexsort over the key
columns, measured ~50ms for 11M i32 on one v5e chip); the reduce tree is a
device segment-sum. Everything up to the final Frame construction stays in
HBM — join sizes (data-dependent) are read back as ONE scalar to size the
output gathers, matching the reference's two-phase count-then-fill merge.
NaN keys sort last and never match (SQL join semantics).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame, Vec, T_CAT, T_STR

_BIG = jnp.float32(3.0e38)


# ===========================================================================
def _key_matrix(f: Frame, idxs, nrows: int):
    """(n, k) f32 device key matrix (NaN -> +BIG so NAs group last)."""
    cols = [f.names[j] for j in idxs]
    M = f.matrix(cols)[:nrows]
    return jnp.where(jnp.isnan(M), _BIG, M)


def device_order(f: Frame, idxs, ascending=None) -> jnp.ndarray:
    """Row order by key columns (RadixOrder analog): device lexsort.
    NAs sort LAST in either direction (np.lexsort parity)."""
    n = f.nrows
    cols = [f.names[j] for j in idxs]
    M = f.matrix(cols)[:n]
    isna = jnp.isnan(M)
    if ascending is not None:
        sign = jnp.asarray([1.0 if a else -1.0 for a in ascending],
                           jnp.float32)
        M = M * sign[None, :]
    K = jnp.where(isna, _BIG, M)
    keys = tuple(K[:, j] for j in range(K.shape[1] - 1, -1, -1))
    return jnp.lexsort(keys)


def take_rows_device(f: Frame, order) -> Frame:
    """Materialize a row permutation: per-column device gather; string
    columns (host-side by design) gather on host."""
    order_h = None
    names, vecs = [], []
    n = f.nrows
    for c, v in zip(f.names, f.vecs):
        if v.type == T_STR:
            if order_h is None:
                order_h = np.asarray(order)
            vecs.append(Vec.from_numpy(v.host_data[order_h], type=T_STR))
        else:
            col = f.matrix([c])[:n, 0]
            out = jnp.take(col, order)
            vecs.append(Vec.from_device_floats(out, vtype=v.type,
                                               domain=v.domain))
        names.append(c)
    return Frame(names, vecs)


def sort_frame(f: Frame, idxs, ascending=None) -> Frame:
    return take_rows_device(f, device_order(f, idxs, ascending))


# ===========================================================================
def _group_ids(K: jnp.ndarray):
    """Sorted order + per-row group ids + unique count for a key matrix."""
    n = K.shape[0]
    keys = tuple(K[:, j] for j in range(K.shape[1] - 1, -1, -1))
    order = jnp.lexsort(keys)
    Ks = jnp.take(K, order, axis=0)
    new = jnp.any(Ks[1:] != Ks[:-1], axis=1)
    new = jnp.concatenate([jnp.ones(1, bool), new])
    gid_sorted = jnp.cumsum(new.astype(jnp.int32)) - 1
    gid = jnp.zeros(n, jnp.int32).at[order].set(gid_sorted)
    return order, gid, gid_sorted, Ks, new


def group_by_device(f: Frame, by_idxs, aggs):
    """Per-group aggregates on device (AstGroup analog).

    aggs: list of (fn_name, col_idx) with fn in
    sum/mean/min/max/var/sd/nrow/count. Returns (out_names, out_cols_np,
    key_domains) — the caller builds the Frame.
    """
    n = f.nrows
    K = _key_matrix(f, by_idxs, n)
    order, gid, gid_sorted, Ks, new = _group_ids(K)
    ng = int(jnp.max(gid)) + 1 if n else 0

    # representative key rows: first sorted row of each group
    starts = jnp.nonzero(new, size=ng)[0]
    key_rows = np.asarray(jnp.take(Ks, starts, axis=0), np.float64)
    key_rows = np.where(key_rows >= 3.0e38, np.nan, key_rows)

    out_names = [f.names[j] for j in by_idxs]
    out_cols = [key_rows[:, k] for k in range(len(by_idxs))]

    @jax.jit
    def aggregate(col, gid):
        ok = ~jnp.isnan(col)
        w = ok.astype(jnp.float32)
        x = jnp.where(ok, col, 0.0)
        size = jax.ops.segment_sum(jnp.ones_like(w), gid, num_segments=ng)
        cnt = jax.ops.segment_sum(w, gid, num_segments=ng)
        s = jax.ops.segment_sum(x, gid, num_segments=ng)
        s2 = jax.ops.segment_sum(x * x, gid, num_segments=ng)
        mn = jax.ops.segment_min(jnp.where(ok, col, jnp.inf), gid,
                                 num_segments=ng)
        mx = jax.ops.segment_max(jnp.where(ok, col, -jnp.inf), gid,
                                 num_segments=ng)
        empty = cnt == 0
        nan = jnp.float32(jnp.nan)
        mean = jnp.where(empty, nan, s / jnp.maximum(cnt, 1.0))
        mn = jnp.where(empty, nan, mn)
        mx = jnp.where(empty, nan, mx)
        var = jnp.where(cnt > 1,
                        (s2 - cnt * mean * mean)
                        / jnp.maximum(cnt - 1.0, 1.0), nan)
        return size, cnt, s, mn, mx, mean, var

    cache = {}
    for fn_name, cj in aggs:
        col = f.matrix([f.names[cj]])[:n, 0]
        if cj not in cache:
            cache[cj] = aggregate(col, gid)
        size, cnt, s, mn, mx, mean, var = cache[cj]
        pick = {"sum": s, "mean": mean, "min": mn, "max": mx,
                "var": var, "sd": jnp.sqrt(jnp.maximum(var, 0.0)),
                "nrow": size, "count": size}
        if fn_name not in pick:
            return None                      # caller falls back (median…)
        out_names.append(f"{fn_name}_{f.names[cj]}")
        out_cols.append(np.asarray(pick[fn_name], np.float64))

    doms = {}
    for kd, j in enumerate(by_idxs):
        if f.vecs[j].type == T_CAT:
            doms[kd] = f.vecs[j].levels()
    return out_names, out_cols, doms


# ===========================================================================
def merge_frames(lf: Frame, rf: Frame, by_l, by_r, all_l=False) -> Frame:
    """Sort-merge join on device (Merge.java's radix design): order both
    sides by key, match key groups via shared group ids, expand pairs with
    one scalar readback for the (data-dependent) output size. Inner and
    left joins; the rarely-used right/outer variants stay on the host
    fallback in the Rapids prim."""
    nl, nr = lf.nrows, rf.nrows
    if nr == 0 or nl == 0:
        # degenerate joins fall back to the host path (pandas handles the
        # empty-side column typing)
        return None
    KL = _key_matrix(lf, by_l, nl)
    KR = _key_matrix(rf, by_r, nr)
    # categorical keys join by LEVEL, not by code: remap the right side's
    # codes onto the left's domain (unmatched levels get distinct
    # never-matching ids) — ParseDataset's cluster-wide categorical
    # renumbering analog for the join path
    for k, (il, ir) in enumerate(zip(by_l, by_r)):
        vl, vr = lf.vecs[il], rf.vecs[ir]
        if vl.type == T_CAT or vr.type == T_CAT:
            ldom = list(vl.domain) if vl.domain is not None else []
            rdom = list(vr.domain) if vr.domain is not None else []
            # default = never-matching sentinel (covers empty rdom: a
            # cat-vs-numeric key mismatch joins nothing, like the host path)
            lut = np.full(max(len(rdom), 1), 2e9, np.float32)
            pos = {lv: i for i, lv in enumerate(ldom)}
            nxt = float(len(ldom))
            for j, lv in enumerate(rdom):
                if lv in pos:
                    lut[j] = pos[lv]
                else:
                    lut[j] = 1e9 + nxt
                    nxt += 1.0
            codes = jnp.clip(KR[:, k].astype(jnp.int32), 0,
                             max(len(rdom) - 1, 0))
            remapped = jnp.take(jnp.asarray(lut), codes)
            # NAs stayed _BIG in the key matrix: keep them unmatched
            remapped = jnp.where(KR[:, k] >= _BIG, _BIG, remapped)
            KR = KR.at[:, k].set(remapped)
    K = jnp.concatenate([KL, KR], axis=0)
    _, gid, _, _, _ = _group_ids(K)
    gl, gr = gid[:nl], gid[nl:]
    ng = int(jnp.max(gid)) + 1

    @jax.jit
    def counts(gl, gr):
        cr = jax.ops.segment_sum(jnp.ones_like(gr, jnp.int32), gr,
                                 num_segments=ng)
        # right rows in sorted-by-gid order + group start offsets
        r_order = jnp.argsort(gr)
        r_start = jnp.cumsum(cr) - cr
        match = cr[gl]                      # matches per left row
        return cr, r_order, r_start, match

    cr, r_order, r_start, match = counts(gl, gr)
    out_per_left = jnp.maximum(match, 1) if all_l else match
    total = int(jnp.sum(out_per_left))

    # expand (left_idx, right_idx) pairs — concrete total, device arithmetic
    reps = np.asarray(out_per_left)
    li = np.repeat(np.arange(nl), reps)
    offs = np.concatenate([[0], np.cumsum(reps)[:-1]])
    within = np.arange(total) - np.repeat(offs, reps)
    rs = np.asarray(r_start)[np.asarray(gl)[li]]
    ro = np.asarray(r_order)
    has = np.asarray(match)[li] > 0
    ri = np.where(has, ro[np.minimum(rs + within, nr - 1 if nr else 0)], -1)

    names, vecs = [], []
    li_j = jnp.asarray(li)
    ri_ok = jnp.asarray(np.where(has, ri, 0))
    has_j = jnp.asarray(has)
    rkey_names = {rf.names[j] for j in by_r}
    for c, v in zip(lf.names, lf.vecs):
        if v.type == T_STR:
            vecs.append(Vec.from_numpy(v.host_data[li], type=T_STR))
        else:
            col = jnp.take(lf.matrix([c])[:nl, 0], li_j)
            vecs.append(Vec.from_device_floats(col, vtype=v.type,
                                               domain=v.domain))
        names.append(c)
    for c, v in zip(rf.names, rf.vecs):
        if c in rkey_names:
            continue                        # join keys come from the left
        nm = c if c not in names else c + "_y"
        if v.type == T_STR:
            s = v.host_data[np.where(has, ri, 0)]
            s = np.where(has, s, None)
            vecs.append(Vec.from_numpy(s, type=T_STR))
        else:
            col = jnp.take(rf.matrix([c])[:nr, 0], ri_ok)
            col = jnp.where(has_j, col, jnp.nan)
            vecs.append(Vec.from_device_floats(col, vtype=v.type,
                                               domain=v.domain))
        names.append(nm)
    return Frame(names, vecs)
