"""Pallas TPU kernels for the binned tree engine — the rebuild of H2O's
ScoreBuildHistogram2 hot loop (SURVEY §2.4 row 1).

Reference semantics: hex/tree/ScoreBuildHistogram2.java:20-60 — ONE fused
pass per level that (phase 1) routes each row to its current leaf by applying
the previous level's split decisions and (phase 2) accumulates per-
(leaf, column) histograms of {w, wY, wYY} over binned rows
(DHistogram.java:59-70, :338). The reference avoids CAS by giving each
(column, row-range) task a private histogram copy merged in reduce.

TPU-native design (measured on v5e): random gathers/scatters run at only
~50-100M elem/s on TPU, so the engine NEVER physically reorders rows
(an explicit leaf-partition + gather design measured ~10x slower than the
kernels it fed). Rows stay in original order; per-row state is ONE int32
`heap` (node id in the 2^(D+1)-1 heap; a row whose node did not split keeps
its heap id and freezes). Codes are stored COLUMN-major (C_pad, n_pad) —
the natural layout for both kernels (rows ride the 128-wide lane dimension)
and the only one whose column blocks satisfy Mosaic's lane-tiling rules.

Two kernels per level:

  * sbh_route — phase 1. Applies the previous level's splits: the per-leaf
    split metadata lives in small VMEM tables and every per-row lookup is a
    one-hot matmul / compare-select (there is no vector gather on TPU).
    The full (numeric threshold / categorical SET / NA direction) decision
    is precompiled by the split search into a per-leaf
    `route[leaf, code] -> goes-right` table, so the kernel is decision-
    agnostic. Optionally fuses the margin update F += eta*val[heap] (the
    terminal-pass variant) — ComputePredAndRes's gather folded into the
    same stream.

  * sbh_hist — phase 2. Grid (pass, col-block, row-tile); output block
    (CB cols, nb bins, GW*S lanes) stays VMEM-resident across the whole
    row sweep (the grouped-matmul revisiting pattern) and accumulates
    onehot(codes) @ A where A packs (leaf-slot x {w,wg,wh}) into exactly
    GW*S_STATS = 128 MXU lanes. No CAS, no private copies, no reduce tree:
    cross-shard merging is one psum over the mesh row axis by the caller.

Stats panel rows (S_STATS=4): 0=w, 1=w*grad, 2=w*hess, 3=spare(0) —
(w, wg, wh) feed split gain, min_rows and Newton leaf values
(hex/tree/DHistogram.java _vals packing analog).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

try:  # Pallas import is deferred-safe: exotic envs may lack Mosaic
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

# Rows per kernel grid step. n_pad must be a multiple of this.
BLOCK_ROWS = 4096
# Stats panel sublane count; GW * S_STATS = 128 lanes exactly.
S_STATS = 4
# Leaf-window width per histogram pass (M = GW*S_STATS lanes, max 512).
GW = 128
# Column tile per histogram grid step.
COL_TILE = 8


def use_pallas() -> bool:
    return _HAVE_PALLAS and jax.default_backend() == "tpu"


_I8_OK: bool | None = None


def i8_supported() -> bool:
    """True when the int8 histogram kernel compiles + runs on this chip.
    Auto-enabling int8 stats must not brick training (or the bench) on a
    TPU generation whose Mosaic rejects the int8 tiling — probe once with
    a tiny shape and cache the answer."""
    global _I8_OK
    if _I8_OK is None:
        if not use_pallas():
            _I8_OK = False
        else:
            try:
                c = jnp.zeros((COL_TILE, BLOCK_ROWS), jnp.int32)
                h = jnp.zeros(BLOCK_ROWS, jnp.int32)
                s = jnp.ones((S_STATS, BLOCK_ROWS), jnp.int32)
                out = sbh_hist_pallas_i8(c, h, s, base=0, L=1, n_bins=128)
                _I8_OK = int(jnp.sum(out[0, 0, 0])) == BLOCK_ROWS
            except Exception:  # pragma: no cover - chip-specific
                _I8_OK = False
    return _I8_OK


# ===========================================================================
# Phase 1: route rows by the previous level's splits
def _route_kernel(codesT_ref, heap_ref, tbl_ref, route_ref, valtab_ref,
                  f_ref, heap_out_ref, f_out_ref, *, base, L, n_cols,
                  n_bins, eta, emit_f, any_cat, na_code):
    """One row tile: apply splits of the level whose leaves sit at heap ids
    [base, base+L); optionally add eta*val[newheap] into F.

    codesT_ref: (C_pad, R) i32    heap_ref/heap_out_ref: (1, R) i32
    tbl_ref:    (8, Lp) f32 — row 0 = split col, row 1 = did (0/1)
    route_ref:  (Lp, n_bins) f32 — 1.0 = code goes right
    valtab_ref: (8, NODES_P) f32 — row 0 = leaf value table (terminal pass)
    f_ref/f_out_ref: (1, R) f32 margins
    """
    R = BLOCK_ROWS
    heap = heap_ref[0, :]                                     # (R,)
    leaf = heap - base
    active = (leaf >= 0) & (leaf < L)
    leaf_c = jnp.where(active, leaf, 0)
    # one-hot over the level's leaves — per-row table lookups are matmuls
    Lp = tbl_ref.shape[1]
    iota_l = lax.broadcasted_iota(jnp.int32, (R, Lp), 1)
    active_f = active.astype(jnp.float32)
    ohl_f = ((iota_l == leaf_c[:, None]).astype(jnp.float32)
             * active_f[:, None])                             # (R, Lp) f32
    ohl = ohl_f.astype(jnp.bfloat16)
    # props lookup stays f32: bf16 cannot represent col ids > 256 or split
    # bins > 256 exactly, which would silently misroute wide frames
    props = lax.dot_general(ohl_f, tbl_ref[...],
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (R, 8)
    col_r = props[:, 0]
    did_r = props[:, 1] > 0.5
    # code of the split column: compare-select over the column sublanes
    codes_f = codesT_ref[...].astype(jnp.float32)             # (C, R)
    iota_c = lax.broadcasted_iota(jnp.int32, (n_cols, R), 0) \
        .astype(jnp.float32)
    csel = (iota_c == col_r[None, :]).astype(jnp.float32)     # (C, R)
    code_sel = jnp.sum(codes_f * csel, axis=0)                # (R,)
    if any_cat:
        # goes-right bit via the full route table: route[leaf, code]
        rowroute = lax.dot_general(
            ohl, route_ref[...].astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (R, BP)
        iota_b = lax.broadcasted_iota(jnp.int32, (R, n_bins), 1) \
            .astype(jnp.float32)
        bsel = (iota_b == code_sel[:, None]).astype(jnp.float32)
        go = jnp.sum(rowroute * bsel, axis=1) > 0.5           # (R,)
    else:
        # numeric-only fast path: threshold compare + NA direction from the
        # props table (rows 2 = split bin, 3 = na-goes-left). All-f32
        # arithmetic — Mosaic rejects mixed i1 selects here.
        bin_r = props[:, 2]
        nal_f = props[:, 3]
        isna_f = (code_sel == jnp.float32(na_code)).astype(jnp.float32)
        gt_f = (code_sel > bin_r).astype(jnp.float32)
        go = (isna_f * (1.0 - nal_f) + (1.0 - isna_f) * gt_f) > 0.5
    splits = active & did_r
    newheap = jnp.where(splits, 2 * heap + 1 + go.astype(jnp.int32), heap)
    heap_out_ref[0, :] = newheap
    if emit_f:
        nodes_p = valtab_ref.shape[1]
        iota_n = lax.broadcasted_iota(jnp.int32, (R, nodes_p), 1)
        # f32 one-hot x f32 table: leaf values must reach F at full
        # precision (scoring reads the same values as f32)
        ohn = (iota_n == newheap[:, None]).astype(jnp.float32)
        val_r = lax.dot_general(
            ohn, valtab_ref[...],
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)[:, 0]
        f_out_ref[0, :] = f_ref[0, :] + eta * val_r
    else:
        f_out_ref[0, :] = f_ref[0, :]


@functools.partial(jax.jit,
                   static_argnames=("base", "L", "eta", "emit_f",
                                    "any_cat", "na_code"))
def sbh_route_pallas(codesT, heap, tbl, route_f, valtab, F, *, base, L,
                     eta=0.0, emit_f=False, any_cat=True, na_code=255):
    """codesT (C_pad, n_pad) i32; heap (n_pad,) i32; tbl (8, Lp) f32;
    route_f (Lp, n_bins) f32; valtab (8, NODES_P) f32; F (n_pad,) f32.
    Returns (newheap, newF)."""
    c_pad, n_pad = codesT.shape
    nblk = n_pad // BLOCK_ROWS
    n_bins = route_f.shape[1]
    kernel = functools.partial(_route_kernel, base=base, L=L, n_cols=c_pad,
                               n_bins=n_bins, eta=eta, emit_f=emit_f,
                               any_cat=any_cat, na_code=na_code)
    newheap, newF = pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((c_pad, BLOCK_ROWS), lambda j: (0, j)),
            pl.BlockSpec((1, BLOCK_ROWS), lambda j: (0, j)),
            pl.BlockSpec(tbl.shape, lambda j: (0, 0)),
            pl.BlockSpec(route_f.shape, lambda j: (0, 0)),
            pl.BlockSpec(valtab.shape, lambda j: (0, 0)),
            pl.BlockSpec((1, BLOCK_ROWS), lambda j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, BLOCK_ROWS), lambda j: (0, j)),
            pl.BlockSpec((1, BLOCK_ROWS), lambda j: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
            jax.ShapeDtypeStruct((1, n_pad), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(codesT, heap.reshape(1, n_pad), tbl, route_f, valtab,
      F.reshape(1, n_pad))
    return newheap[0], newF[0]


def sbh_route_xla(codesT, heap, tbl, route_f, valtab, F, *, base, L,
                  eta=0.0, emit_f=False, any_cat=True, na_code=255):
    """Pure-XLA fallback: same contract (CPU scatter/gather is fast)."""
    leaf = heap - base
    active = (leaf >= 0) & (leaf < L)
    leaf_c = jnp.where(active, leaf, 0)
    col_r = tbl[0, leaf_c].astype(jnp.int32)
    did_r = (tbl[1, leaf_c] > 0.5) & active
    code_sel = jnp.take_along_axis(
        codesT, jnp.clip(col_r, 0, codesT.shape[0] - 1)[None, :],
        axis=0)[0]
    n_bins = route_f.shape[1]
    go = route_f.reshape(-1)[leaf_c * n_bins + code_sel] > 0.5
    splits = active & did_r
    newheap = jnp.where(splits, 2 * heap + 1 + go.astype(jnp.int32), heap)
    newF = F + eta * valtab[0, newheap] if emit_f else F
    return newheap, newF


def sbh_route(codesT, heap, tbl, route_f, valtab, F, *, base, L,
              eta=0.0, emit_f=False, any_cat=True, na_code=255):
    if use_pallas():
        return sbh_route_pallas(codesT, heap, tbl, route_f, valtab, F,
                                base=base, L=L, eta=eta, emit_f=emit_f,
                                any_cat=any_cat, na_code=na_code)
    return sbh_route_xla(codesT, heap, tbl, route_f, valtab, F,
                         base=base, L=L, eta=eta, emit_f=emit_f,
                         any_cat=any_cat, na_code=na_code)


# ===========================================================================
# Phase 2: leaf-window histogram accumulation
def _hist_kernel(codesT_ref, heap_ref, stats_ref, out_ref, *, base, L,
                 n_bins, gwe, r_blk, half):
    """Grid (pass, col-block, row-tile): accumulate the (CB, gwe*S, nb)
    window block over the row sweep; gwe = min(L_eff, GW) leaves per pass.

    With half=True only EVEN leaf indices (left children) are accumulated —
    window slot = leaf >> 1 — and the caller derives right children by
    sibling subtraction (parent histogram minus left child; the same trick
    xgboost/lightgbm use — valid because routing moves EVERY row of a split
    leaf to a child, so parent = left + right exactly).

    codesT_ref: (COL_TILE, R) i32 — this col-block's codes
    heap_ref:   (1, R) i32        stats_ref: (S_STATS, R) f32
    out_ref:    (1, COL_TILE, gwe*S_STATS, n_bins) f32
    """
    R = r_blk
    p = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    heap = heap_ref[0, :]                                  # (R,) lanes
    leaf = heap - base
    if half:
        slot = (leaf >> 1) - p * gwe
        inw = (leaf >= 0) & (leaf < L) & ((leaf & 1) == 0)
    else:
        slot = leaf - p * gwe
        inw = (leaf >= 0) & (leaf < L)
    inw = inw & (slot >= 0) & (slot < gwe)
    slot_c = jnp.where(inw, slot, 0)
    # A ((gwe*S), R): row (slot, s); rows of the tile ride the lanes — the
    # measured-fast dot orientation is (M, R) @ (R, nb)
    iota_s = lax.broadcasted_iota(jnp.int32, (gwe, R), 0)
    inw_f = inw.astype(jnp.float32)
    ohs = ((iota_s == slot_c[None, :]).astype(jnp.float32)
           * inw_f[None, :])                               # (gwe, R)
    stats = stats_ref[...]                                 # (S, R) f32
    A = (ohs[:, None, :] * stats[None, :, :]) \
        .reshape(gwe * S_STATS, R).astype(jnp.bfloat16)    # (M, R)

    acc = out_ref[...]
    # one-hot built TRANSPOSED (nb, R): bins on sublanes, rows on lanes.
    # Measured 1.9x faster than the (R, nb) orientation — the compare
    # broadcast is a major-dim insert (free) instead of a minor-dim
    # relayout, and the dot contracts the rhs on dim 1 directly.
    iota_b = lax.broadcasted_iota(jnp.int32, (n_bins, R), 0)
    parts = []
    for c in range(COL_TILE):
        code_c = codesT_ref[c, :]                          # (R,) static c
        ohT = (iota_b == code_c[None, :]).astype(jnp.bfloat16)  # (nb, R)
        h = lax.dot_general(A, ohT, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (M, nb)
        parts.append(h)
    out_ref[...] = acc + jnp.stack(parts)[None]            # (1, CB, M, nb)


@functools.partial(jax.jit, static_argnames=("base", "L", "n_bins", "half"))
def sbh_hist_pallas(codesT, heap, stats, *, base, L, n_bins, half=False):
    """codesT (C_pad, n_pad) i32; heap (n_pad,) i32; stats (S, n_pad) f32.
    Returns (L_pad, C_pad, S_STATS, n_bins) f32 with L_pad = npass*gwe:
    hist[l] = per-(col, stat, bin) sums over rows with heap == base + l
    (half=True: over rows with heap == base + 2l — left children only)."""
    c_pad, n_pad = codesT.shape
    l_eff = (L + 1) // 2 if half else L
    gwe = min(l_eff, GW)
    npass = max(1, -(-l_eff // gwe))
    ncb = c_pad // COL_TILE
    # VMEM budget: A (M, R) bf16 + oh (R, nb) bf16 + out (CB, M, nb) f32
    # hit the 16MB limit at M=512, so deep levels run narrower row tiles
    r_blk = BLOCK_ROWS if gwe * S_STATS <= 256 else BLOCK_ROWS // 2
    nblk = n_pad // r_blk
    kernel = functools.partial(_hist_kernel, base=base, L=L, n_bins=n_bins,
                               gwe=gwe, r_blk=r_blk, half=half)
    out = pl.pallas_call(
        kernel,
        grid=(npass, ncb, nblk),
        in_specs=[
            pl.BlockSpec((COL_TILE, r_blk), lambda p, g, j: (g, j)),
            pl.BlockSpec((1, r_blk), lambda p, g, j: (0, j)),
            pl.BlockSpec((S_STATS, r_blk), lambda p, g, j: (0, j)),
        ],
        out_specs=pl.BlockSpec(
            (1, COL_TILE, gwe * S_STATS, n_bins),
            lambda p, g, j: (p * ncb + g, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (npass * ncb, COL_TILE, gwe * S_STATS, n_bins), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
    )(codesT, heap.reshape(1, n_pad), stats)
    # (npass*ncb, CB, gwe*S, nb) -> (L_pad, C_pad, S, nb)
    out = out.reshape(npass, ncb, COL_TILE, gwe, S_STATS, n_bins)
    return out.transpose(0, 3, 1, 2, 4, 5).reshape(
        npass * gwe, c_pad, S_STATS, n_bins)


def sbh_hist_xla(codesT, heap, stats, *, base, L, n_bins, half=False):
    """Pure-XLA fallback via segment-sum (CPU tests / non-TPU backends)."""
    c_pad, n_pad = codesT.shape
    l_eff = (L + 1) // 2 if half else L
    gwe = min(l_eff, GW)
    npass = max(1, -(-l_eff // gwe))
    L_pad = npass * gwe
    leaf = heap - base
    ok = (leaf >= 0) & (leaf < L)
    if half:
        ok = ok & ((leaf & 1) == 0)
        leaf = leaf >> 1
    lf = jnp.where(ok, leaf, L_pad)

    def one_col(c):
        idx = lf * n_bins + codesT[c]
        return jax.ops.segment_sum(stats.T, idx,
                                   num_segments=(L_pad + 1) * n_bins)

    hs = lax.map(one_col, jnp.arange(c_pad))       # (C, (L+1)*B, S)
    return hs.reshape(c_pad, L_pad + 1, n_bins, S_STATS)[:, :L_pad] \
             .transpose(1, 0, 3, 2)


def sbh_hist(codesT, heap, stats, *, base, L, n_bins, half=False):
    if use_pallas():
        if _radix_applicable(L, n_bins, half):
            return sbh_hist_radix(codesT, heap, stats, base=base, L=L,
                                  n_bins=n_bins, half=half, int8=False)
        return sbh_hist_pallas(codesT, heap, stats, base=base, L=L,
                               n_bins=n_bins, half=half)
    return sbh_hist_xla(codesT, heap, stats, base=base, L=L, n_bins=n_bins,
                        half=half)


def sbh_hist_i8(codesT, heap, stats_i8, *, base, L, n_bins, half=False):
    """int8-stats histogram: i32 in [-127,127] per stat row, i32 out (exact
    accumulation). The XLA fallback is the same segment-sum with integer
    dtype passthrough — bit-identical semantics for the CPU tests."""
    if use_pallas():
        if _radix_applicable(L, n_bins, half):
            return sbh_hist_radix(codesT, heap, stats_i8, base=base, L=L,
                                  n_bins=n_bins, half=half, int8=True)
        return sbh_hist_pallas_i8(codesT, heap, stats_i8, base=base, L=L,
                                  n_bins=n_bins, half=half)
    return sbh_hist_xla(codesT, heap, stats_i8, base=base, L=L,
                        n_bins=n_bins, half=half)


# ===========================================================================
# int8 histogram variant: one-hot (exact in i8) x per-stat-quantized stats
# on the v5e's 2x-rate int8 MXU path, int32 accumulation (exact: 127 * 11M
# rows < 2^31), dequantized by the caller. Same grid/window structure as
# the bf16 kernel.
def _hist_kernel_i8(codesT_ref, heap_ref, stats_ref, out_ref, *, base, L,
                    n_bins, gwe, r_blk, half=False):
    R = r_blk
    p = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    heap = heap_ref[0, :]
    leaf = heap - base
    if half:
        # left children only (even leaf index): window slot = leaf >> 1;
        # the caller derives right = parent - left EXACTLY (i32 arithmetic
        # makes sibling subtraction lossless, unlike bf16)
        slot = (leaf >> 1) - p * gwe
        inw = (leaf >= 0) & (leaf < L) & ((leaf & 1) == 0)
    else:
        slot = leaf - p * gwe
        inw = (leaf >= 0) & (leaf < L)
    inw = inw & (slot >= 0) & (slot < gwe)
    slot_c = jnp.where(inw, slot, 0)
    iota_s = lax.broadcasted_iota(jnp.int32, (gwe, R), 0)
    sel = (iota_s == slot_c[None, :]) & inw[None, :]          # (gwe, R)
    stats = stats_ref[...]                                    # (S, R) i32
    A = (jnp.where(sel[:, None, :], stats[None, :, :], 0)
         .reshape(gwe * S_STATS, R)).astype(jnp.int8)

    acc = out_ref[...]
    iota_b = lax.broadcasted_iota(jnp.int32, (R, n_bins), 1)
    parts = []
    for c in range(COL_TILE):
        code_c = codesT_ref[c, :]
        oh = (iota_b == code_c[:, None]).astype(jnp.int8)
        h = lax.dot_general(A, oh, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.int32)
        parts.append(h)
    out_ref[...] = acc + jnp.stack(parts)[None]


@functools.partial(jax.jit, static_argnames=("base", "L", "n_bins", "half"))
def sbh_hist_pallas_i8(codesT, heap, stats_i8, *, base, L, n_bins,
                       half=False):
    """stats_i8 (S, n_pad) int32 holding values in [-127, 127] (i32 input
    dtype: Mosaic's (1, R) int8 blocks don't meet the 32-sublane granule;
    the kernel casts to i8 in VMEM). Returns int32 histogram."""
    c_pad, n_pad = codesT.shape
    l_eff = (L + 1) // 2 if half else L
    gwe = min(l_eff, GW)
    npass = max(1, -(-l_eff // gwe))
    ncb = c_pad // COL_TILE
    r_blk = BLOCK_ROWS if gwe * S_STATS <= 256 else BLOCK_ROWS // 2
    nblk = n_pad // r_blk
    kernel = functools.partial(_hist_kernel_i8, base=base, L=L,
                               n_bins=n_bins, gwe=gwe, r_blk=r_blk,
                               half=half)
    out = pl.pallas_call(
        kernel,
        grid=(npass, ncb, nblk),
        in_specs=[
            pl.BlockSpec((COL_TILE, r_blk), lambda p, g, j: (g, j)),
            pl.BlockSpec((1, r_blk), lambda p, g, j: (0, j)),
            pl.BlockSpec((S_STATS, r_blk), lambda p, g, j: (0, j)),
        ],
        out_specs=pl.BlockSpec(
            (1, COL_TILE, gwe * S_STATS, n_bins),
            lambda p, g, j: (p * ncb + g, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (npass * ncb, COL_TILE, gwe * S_STATS, n_bins), jnp.int32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
    )(codesT, heap.reshape(1, n_pad), stats_i8)
    out = out.reshape(npass, ncb, COL_TILE, gwe, S_STATS, n_bins)
    return out.transpose(0, 3, 1, 2, 4, 5).reshape(
        npass * gwe, c_pad, S_STATS, n_bins)


# ===========================================================================
# Radix-factored shallow-window histogram (PERF_NOTES item 1, measured-win
# regime only). The dense kernel's shallow-level floor is VPU one-hot
# generation: a 256-wide (iota == code) compare per (row, col). Factor
# code = hi*16 + lo and fuse the leaf slot into the hi key:
#
#     key[r]        = slot[r]*16 + hi[r,c]           (i32 VPU)
#     J[(l,hi), r]  = (iota == key)                  (gwe*16-wide compare)
#     A[(l,hi,s),r] = J ? stats[s,r] : 0             (select)
#     H[(l,hi,s),lo]= A @ onehot_lo.T                (16-wide lo one-hot)
#
# VPU element-ops per (row, col): gwe*16*(1+S) + 16 vs dense 256 + gwe*S:
# 2.7x at window 1, 1.5x at window 2, WORSE at window 4 — so the dispatch
# (`_radix_applicable`) engages only for effective windows <= 2, i.e.
# levels 0-2 once sibling subtraction halves the window. Reference
# semantics unchanged: identical histograms to sbh_hist (parity-gated).
RADIX_NH = 16
RADIX_MAX_WINDOW = 2

_RADIX_OK: bool | None = None


def radix_supported() -> bool:
    """Probe-compile the radix kernel once (never brick a TPU gen whose
    Mosaic rejects the (gwe*16*S, 16) tiling)."""
    global _RADIX_OK
    if _RADIX_OK is None:
        if not use_pallas():
            _RADIX_OK = False
        else:
            try:
                c = jnp.zeros((COL_TILE, BLOCK_ROWS), jnp.int32)
                h = jnp.zeros(BLOCK_ROWS, jnp.int32)
                s = jnp.ones((S_STATS, BLOCK_ROWS), jnp.float32)
                out = sbh_hist_radix(c, h, s, base=0, L=1, n_bins=256,
                                     half=False, int8=False)
                _RADIX_OK = abs(float(out[0, 0, 0, 0])
                                - BLOCK_ROWS) < 0.5
            except Exception:  # pragma: no cover - chip-specific
                _RADIX_OK = False
    return _RADIX_OK


def _radix_applicable(L, n_bins, half) -> bool:
    l_eff = (L + 1) // 2 if half else L
    return (l_eff <= RADIX_MAX_WINDOW and n_bins % RADIX_NH == 0
            and n_bins // RADIX_NH >= 8 and radix_supported())


def _radix_kernel(codesT_ref, heap_ref, stats_ref, out_ref, *, base, L,
                  n_bins, gwe, half, int8):
    R = BLOCK_ROWS
    NH = RADIX_NH
    nl = n_bins // NH
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    heap = heap_ref[0, :]                                  # (R,)
    leaf = heap - base
    if half:
        # left children only; caller derives right = parent - left
        slot = leaf >> 1
        inw = (leaf >= 0) & (leaf < L) & ((leaf & 1) == 0)
    else:
        slot = leaf
        inw = (leaf >= 0) & (leaf < L)
    slot_c = jnp.where(inw, slot, gwe)     # dead rows -> key out of range
    stats = stats_ref[...]                                 # (S, R)
    acc = out_ref[...]
    iota_k = lax.broadcasted_iota(jnp.int32, (gwe * NH, R), 0)
    iota_lo = lax.broadcasted_iota(jnp.int32, (nl, R), 0)
    parts = []
    for c in range(COL_TILE):
        code = codesT_ref[c, :]                            # (R,)
        key = slot_c * NH + code // nl
        lo = code % nl
        J = iota_k == key[None, :]                         # (gwe*NH, R)
        if int8:
            A = jnp.where(J[:, None, :], stats[None, :, :], 0) \
                .reshape(gwe * NH * S_STATS, R).astype(jnp.int8)
            ohlo = (iota_lo == lo[None, :]).astype(jnp.int8)
            h = lax.dot_general(A, ohlo, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.int32)
        else:
            A = jnp.where(J[:, None, :], stats[None, :, :], 0.0) \
                .reshape(gwe * NH * S_STATS, R).astype(jnp.bfloat16)
            ohlo = (iota_lo == lo[None, :]).astype(jnp.bfloat16)
            h = lax.dot_general(A, ohlo, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        parts.append(h)                                    # (gwe*NH*S, nl)
    out_ref[...] = acc + jnp.stack(parts)[None]


@functools.partial(jax.jit,
                   static_argnames=("base", "L", "n_bins", "half", "int8"))
def sbh_hist_radix(codesT, heap, stats, *, base, L, n_bins, half=False,
                   int8=False):
    """Radix-factored histogram for effective windows <= RADIX_MAX_WINDOW.
    Same contract as sbh_hist_pallas but returns exactly (l_eff, C_pad,
    S_STATS, n_bins); f32 out (bf16 accumulation) or i32 when int8."""
    c_pad, n_pad = codesT.shape
    l_eff = (L + 1) // 2 if half else L
    gwe = max(1, l_eff)
    NH = RADIX_NH
    nl = n_bins // NH
    ncb = c_pad // COL_TILE
    nblk = n_pad // BLOCK_ROWS
    kernel = functools.partial(_radix_kernel, base=base, L=L, n_bins=n_bins,
                               gwe=gwe, half=half, int8=int8)
    out = pl.pallas_call(
        kernel,
        grid=(ncb, nblk),
        in_specs=[
            pl.BlockSpec((COL_TILE, BLOCK_ROWS), lambda g, j: (g, j)),
            pl.BlockSpec((1, BLOCK_ROWS), lambda g, j: (0, j)),
            pl.BlockSpec((S_STATS, BLOCK_ROWS), lambda g, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((1, COL_TILE, gwe * NH * S_STATS, nl),
                               lambda g, j: (g, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (ncb, COL_TILE, gwe * NH * S_STATS, nl),
            jnp.int32 if int8 else jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(codesT, heap.reshape(1, n_pad), stats)
    # (ncb, CB, gwe, NH, S, nl) -> (gwe, C_pad, S, NH*nl = n_bins)
    out = out.reshape(ncb, COL_TILE, gwe, NH, S_STATS, nl)
    return out.transpose(2, 0, 1, 4, 3, 5).reshape(
        gwe, c_pad, S_STATS, n_bins)
