"""Pallas TPU kernels for the binned tree engine — the rebuild of H2O's
ScoreBuildHistogram2 hot loop (SURVEY §2.4 row 1).

Reference semantics: hex/tree/ScoreBuildHistogram2.java:20-60 — ONE fused
pass per level that (phase 1) routes each row to its current leaf by applying
the previous level's split decisions and (phase 2) accumulates per-
(leaf, column) histograms of {w, wY, wYY} over binned rows
(DHistogram.java:59-70, :338). The reference avoids CAS by giving each
(column, row-range) task a private histogram copy merged in reduce.

TPU-native design (measured on v5e): random gathers/scatters run at only
~50-100M elem/s on TPU, so the engine NEVER physically reorders rows
(an explicit leaf-partition + gather design measured ~10x slower than the
kernels it fed). Rows stay in original order; per-row state is ONE int32
`heap` (node id in the 2^(D+1)-1 heap; a row whose node did not split keeps
its heap id and freezes). Codes are stored COLUMN-major — the natural
layout for both kernels (rows ride the 128-wide lane dimension).

CODE PLANES (round 4): bins are <= 255+NA so a code needs ONE byte, and the
HBM code stream at 150-200 GB/s effective is the measured per-level
bandwidth floor (ops/PERF_NOTES.md). The binner therefore emits codes as
uint8 (C_pad, n_pad); for the TPU kernels `pack_codes` packs FOUR uint8
codes per int32 word along the COLUMN axis into a (W_pad, n_pad) i32
"packed plane" — 1 byte/code in HBM (4x less code traffic than the old i32
planes) while every Pallas block stays an i32 tile that satisfies Mosaic's
sublane granule (a raw uint8 (8, R) block would violate the (32, 128) int8
tile; the i32 word is the legal carrier and bytes are extracted INSIDE the
kernel tile, never widened in HBM). The XLA fallbacks (CPU tests, exotic
backends) consume the uint8 plane directly — dtype-agnostic segment sums,
bit-identical to the old i32 planes.

Kernels per level:

  * sbh_route — phase 1. Applies the previous level's splits: the per-leaf
    split metadata lives in small VMEM tables and every per-row lookup is a
    one-hot matmul / compare-select (there is no vector gather on TPU).
    The split column's code comes from a word compare-select over the
    packed plane's sublanes plus a per-lane variable shift (byte extract).
    The full (numeric threshold / categorical SET / NA direction) decision
    is precompiled by the split search into a per-leaf
    `route[leaf, code] -> goes-right` table, so the kernel is decision-
    agnostic. Non-terminal levels no longer stream F through the kernel
    (8 bytes/row/level saved); the terminal pass fuses the margin update
    F += eta*val[heap] (ComputePredAndRes's gather folded into the stream).

  * sbh_hist — phase 2. Grid (pass, word-block, row-tile); output block
    (32 cols, gwe*S lanes, nb bins) stays VMEM-resident across the whole
    row sweep (the grouped-matmul revisiting pattern) and accumulates
    onehot(codes) @ A where A packs (leaf-slot x {w,wg,wh}) MXU lanes.
    No CAS, no private copies, no reduce tree: cross-shard merging is one
    psum over the mesh row axis by the caller.

  * sbh_route_hist — the LEVEL-FUSED pass (PERF_NOTES item 4, the
    ScoreBuildHistogram2 shape itself): ONE kernel reads the code tile
    once, routes the rows, and accumulates the histogram over the UPDATED
    heap — halving code traffic again at the shallow levels where the
    histogram is bandwidth-floor (not dot) bound. Auto-on only where the
    fused program compiles (`fused_supported` probe) and the whole-level
    histogram fits VMEM (`_fused_applicable`); the unfused route+hist
    pair is always the fallback and the XLA path.

  * sbh_hist_radix — radix-factored shallow-window histogram (PERF_NOTES
    item 1): code = hi*16+lo with the leaf slot fused into the hi key
    kills the 256-wide VPU one-hot floor at effective windows <= 2.

Stats panel rows (S_STATS=4): 0=w, 1=w*grad, 2=w*hess, 3=spare(0) —
(w, wg, wh) feed split gain, min_rows and Newton leaf values
(hex/tree/DHistogram.java _vals packing analog).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

try:  # Pallas import is deferred-safe: exotic envs may lack Mosaic
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

# Rows per kernel grid step. n_pad must be a multiple of this.
BLOCK_ROWS = 4096
# Stats panel sublane count.
S_STATS = 4
# Leaf-window width per histogram pass. 64 (not 128): the packed kernels
# sweep 32 columns per grid step, and a 128-leaf window's output block
# (32 x 512 x 256 f32) would blow the 16MB VMEM budget; 64 keeps the
# resident block at 8MB and only doubles npass at l_eff >= 128 — where
# the packed plane already cut the re-streamed code bytes 4x.
GW = 64
# Column tile of the LEGACY (unpacked) layout; kept for the XLA fallbacks'
# callers and the padded-column contract (c_pad is a COL_TILE multiple).
COL_TILE = 8
# uint8 codes per packed i32 word (column-axis packing).
PACK = 4
# Packed words per histogram grid step (PACK*WORD_TILE = 32 columns).
WORD_TILE = 8


def use_pallas() -> bool:
    return _HAVE_PALLAS and jax.default_backend() == "tpu"


def is_packed(codes) -> bool:
    """True when `codes` is a packed i32 plane for the Pallas kernels (the
    TPU layout produced by pack_codes); uint8/int32-unpacked planes run
    the XLA fallbacks. The dtype IS the layout tag: prepare_codes only
    ever emits i32 on the Pallas backend."""
    return use_pallas() and codes.dtype == jnp.int32


# ===========================================================================
# Packed code planes
def packed_words(c_pad: int) -> int:
    """Words per packed plane for a c_pad-column code plane: ceil(C/4),
    padded to a WORD_TILE multiple once it exceeds one tile (sub-tile
    planes ride a single full-dim block, like the (S, R) stats panel)."""
    w = -(-c_pad // PACK)
    return w if w <= WORD_TILE else -(-w // WORD_TILE) * WORD_TILE


@jax.jit
def pack_codes(codes_u8):
    """(C_pad, n_pad) uint8 -> (W_pad, n_pad) int32 packed plane: little-
    endian bytes, 4 codes/word along the COLUMN axis (dummy columns pack
    as code 0 = zero-stat rows' bin). The row axis is untouched, so row
    sharding specs carry over unchanged."""
    c_pad, n_pad = codes_u8.shape
    w_pad = packed_words(c_pad)
    c = jnp.pad(codes_u8, ((0, w_pad * PACK - c_pad), (0, 0))) \
        .astype(jnp.int32).reshape(w_pad, PACK, n_pad)
    return c[:, 0] | (c[:, 1] << 8) | (c[:, 2] << 16) | (c[:, 3] << 24)


@functools.partial(jax.jit, static_argnames=("c_pad",))
def unpack_codes(packed, *, c_pad):
    """Inverse of pack_codes (tests + reference math)."""
    w_pad, n_pad = packed.shape
    parts = [(packed >> (8 * k)) & 255 for k in range(PACK)]
    u = jnp.stack(parts, axis=1).reshape(w_pad * PACK, n_pad)
    return u[:c_pad].astype(jnp.uint8)


def prepare_codes(codes_u8):
    """Backend-appropriate kernel layout for a quantized uint8 plane:
    packed i32 words on the Pallas backend, the uint8 plane itself (the
    XLA fallbacks' input) everywhere else."""
    if use_pallas():
        return pack_codes(codes_u8)
    return codes_u8


# ===========================================================================
# Probes: auto-enabling a kernel family must never brick training (or the
# bench) on a TPU generation whose Mosaic rejects its tiling — compile each
# once with a tiny shape and cache the answer.
_I8_OK: bool | None = None
_RADIX_OK: bool | None = None
_FUSED_OK: bool | None = None


def _probe_plane():
    u8 = jnp.zeros((COL_TILE, BLOCK_ROWS), jnp.uint8)
    return pack_codes(u8)


def i8_supported() -> bool:
    """True when the int8-stats histogram kernel compiles + runs here."""
    global _I8_OK
    if _I8_OK is None:
        if not use_pallas():
            _I8_OK = False
        else:
            try:
                cp = _probe_plane()
                h = jnp.zeros(BLOCK_ROWS, jnp.int32)
                s = jnp.ones((S_STATS, BLOCK_ROWS), jnp.int32)
                out = sbh_hist_pallas_i8(cp, h, s, base=0, L=1, n_bins=128)
                _I8_OK = int(jnp.sum(out[0, 0, 0])) == BLOCK_ROWS
            except Exception:  # pragma: no cover - chip-specific
                _I8_OK = False
    return _I8_OK


def radix_supported() -> bool:
    """Probe-compile the radix shallow-window kernel once."""
    global _RADIX_OK
    if _RADIX_OK is None:
        if not use_pallas():
            _RADIX_OK = False
        else:
            try:
                cp = _probe_plane()
                h = jnp.zeros(BLOCK_ROWS, jnp.int32)
                s = jnp.ones((S_STATS, BLOCK_ROWS), jnp.float32)
                out = sbh_hist_radix(cp, h, s, base=0, L=1, n_bins=256)
                _RADIX_OK = abs(float(out[0, 0, 0, 0])
                                - BLOCK_ROWS) < 0.5
            except Exception:  # pragma: no cover - chip-specific
                _RADIX_OK = False
    return _RADIX_OK


def fused_supported() -> bool:
    """Probe-compile the level-fused route+hist kernel once."""
    global _FUSED_OK
    if _FUSED_OK is None:
        if not use_pallas():
            _FUSED_OK = False
        else:
            try:
                cp = _probe_plane()
                heap = jnp.zeros(BLOCK_ROWS, jnp.int32)
                tbl = jnp.zeros((8, 8), jnp.float32).at[1, 0].set(1.0)
                route_f = jnp.zeros((8, 256), jnp.float32)
                s = jnp.ones((S_STATS, BLOCK_ROWS), jnp.float32)
                nh, hist = sbh_route_hist_fused_pallas(
                    cp, heap, tbl, route_f, s, base_r=0, L_r=1, base_h=1,
                    L_h=2, n_bins=256, any_cat=True, na_code=255)
                # every row splits left (route table all-zero): heap 0 -> 1,
                # leaf 0 (even) lands in window slot 0, bin 0
                _FUSED_OK = (int(nh[0]) == 1
                             and abs(float(hist[0, 0, 0, 0])
                                     - BLOCK_ROWS) < 0.5)
            except Exception:  # pragma: no cover - chip-specific
                _FUSED_OK = False
    return _FUSED_OK


# ===========================================================================
# Shared kernel bodies (route math / stats panel / per-column accumulation)
# — one definition each so the standalone kernels and the fused kernel
# cannot drift semantically.
def _route_math(words, heap, tbl, route, *, base, L, n_bins, any_cat,
                na_code):
    """New heap ids for one row tile. `words` is the loaded packed-plane
    tile (W_pad, R); `tbl`/`route` the loaded split tables."""
    R = heap.shape[0]
    leaf = heap - base
    active = (leaf >= 0) & (leaf < L)
    leaf_c = jnp.where(active, leaf, 0)
    # one-hot over the level's leaves — per-row table lookups are matmuls
    Lp = tbl.shape[1]
    iota_l = lax.broadcasted_iota(jnp.int32, (R, Lp), 1)
    active_f = active.astype(jnp.float32)
    ohl_f = ((iota_l == leaf_c[:, None]).astype(jnp.float32)
             * active_f[:, None])                             # (R, Lp) f32
    # props lookup stays f32: bf16 cannot represent col ids > 256 or split
    # bins > 256 exactly, which would silently misroute wide frames
    props = lax.dot_general(ohl_f, tbl,
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (R, 8)
    did_r = props[:, 1] > 0.5
    # split column's code: word compare-select over the packed sublanes
    # (exact i32 sum — a one-hot f32 dot would round packed words > 2^24),
    # then a per-lane variable shift extracts the byte
    col_i = props[:, 0].astype(jnp.int32)
    wi = col_i >> 2
    shift = (col_i & 3) * 8
    w_pad = words.shape[0]
    iota_w = lax.broadcasted_iota(jnp.int32, (w_pad, R), 0)
    wsel = jnp.sum(jnp.where(iota_w == wi[None, :], words, 0), axis=0)
    code_i = (wsel >> shift) & 255                            # (R,) i32
    code_sel = code_i.astype(jnp.float32)
    if any_cat:
        # goes-right bit via the full route table: route[leaf, code]
        rowroute = lax.dot_general(
            ohl_f.astype(jnp.bfloat16), route.astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (R, BP)
        iota_b = lax.broadcasted_iota(jnp.int32, (R, n_bins), 1) \
            .astype(jnp.float32)
        bsel = (iota_b == code_sel[:, None]).astype(jnp.float32)
        go = jnp.sum(rowroute * bsel, axis=1) > 0.5           # (R,)
    else:
        # numeric-only fast path: threshold compare + NA direction from the
        # props table (rows 2 = split bin, 3 = na-goes-left). All-f32
        # arithmetic — Mosaic rejects mixed i1 selects here.
        bin_r = props[:, 2]
        nal_f = props[:, 3]
        isna_f = (code_sel == jnp.float32(na_code)).astype(jnp.float32)
        gt_f = (code_sel > bin_r).astype(jnp.float32)
        go = (isna_f * (1.0 - nal_f) + (1.0 - isna_f) * gt_f) > 0.5
    splits = active & did_r
    return jnp.where(splits, 2 * heap + 1 + go.astype(jnp.int32), heap)


def _stats_panel(heap, stats, *, base, L, gwe, p, half, int8):
    """The (gwe*S_STATS, R) MXU lhs panel A: row (slot, s) holds stat s of
    rows whose leaf sits in window slot `slot` of pass `p`. With half=True
    only EVEN leaf indices (left children) are accumulated — window slot =
    leaf >> 1 — and the caller derives right children by sibling
    subtraction (parent minus left; the same trick xgboost/lightgbm use —
    valid because routing moves EVERY row of a split leaf to a child, so
    parent = left + right exactly; i32 accumulation makes it lossless on
    the int8-stats path)."""
    R = heap.shape[0]
    leaf = heap - base
    if half:
        slot = (leaf >> 1) - p * gwe
        inw = (leaf >= 0) & (leaf < L) & ((leaf & 1) == 0)
    else:
        slot = leaf - p * gwe
        inw = (leaf >= 0) & (leaf < L)
    inw = inw & (slot >= 0) & (slot < gwe)
    slot_c = jnp.where(inw, slot, 0)
    iota_s = lax.broadcasted_iota(jnp.int32, (gwe, R), 0)
    if int8:
        sel = (iota_s == slot_c[None, :]) & inw[None, :]      # (gwe, R)
        return (jnp.where(sel[:, None, :], stats[None, :, :], 0)
                .reshape(gwe * S_STATS, R)).astype(jnp.int8)
    inw_f = inw.astype(jnp.float32)
    ohs = ((iota_s == slot_c[None, :]).astype(jnp.float32)
           * inw_f[None, :])                                  # (gwe, R)
    return (ohs[:, None, :] * stats[None, :, :]) \
        .reshape(gwe * S_STATS, R).astype(jnp.bfloat16)


def _dense_parts(words, A, *, n_bins, int8):
    """Per-column histogram dots for one packed-word tile: byte-extract
    each code INSIDE the tile (never widened in HBM), one-hot it, dot
    against the stats panel. Returns 4*W parts of (M, nb)."""
    R = words.shape[1]
    if int8:
        iota_b = lax.broadcasted_iota(jnp.int32, (R, n_bins), 1)
    else:
        # one-hot built TRANSPOSED (nb, R): bins on sublanes, rows on
        # lanes. Measured 1.9x faster than the (R, nb) orientation — the
        # compare broadcast is a major-dim insert (free) instead of a
        # minor-dim relayout, and the dot contracts the rhs on dim 1.
        iota_b = lax.broadcasted_iota(jnp.int32, (n_bins, R), 0)
    parts = []
    for w in range(words.shape[0]):
        word = words[w, :]                                    # (R,) static w
        for k in range(PACK):
            code = (word >> (8 * k)) & 255
            if int8:
                oh = (iota_b == code[:, None]).astype(jnp.int8)
                h = lax.dot_general(A, oh, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.int32)
            else:
                ohT = (iota_b == code[None, :]).astype(jnp.bfloat16)
                h = lax.dot_general(A, ohT, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            parts.append(h)                                   # (M, nb)
    return parts


def _radix_parts(words, slot_c, stats, *, gwe, n_bins, int8):
    """Radix-factored per-column accumulation: code = hi*16 + lo with the
    leaf slot fused into the hi key — a gwe*16-wide joint compare plus a
    16-wide lo one-hot replaces the 256-wide dense compare (2.7x fewer
    VPU element-ops at window 1; see PERF_NOTES item 1). `slot_c` is the
    window slot with dead rows already pushed out of range (>= gwe)."""
    NH = RADIX_NH
    nl = n_bins // NH
    R = words.shape[1]
    iota_k = lax.broadcasted_iota(jnp.int32, (gwe * NH, R), 0)
    iota_lo = lax.broadcasted_iota(jnp.int32, (nl, R), 0)
    parts = []
    for w in range(words.shape[0]):
        word = words[w, :]
        for k in range(PACK):
            code = (word >> (8 * k)) & 255
            key = slot_c * NH + code // nl
            lo = code % nl
            J = iota_k == key[None, :]                        # (gwe*NH, R)
            if int8:
                A = jnp.where(J[:, None, :], stats[None, :, :], 0) \
                    .reshape(gwe * NH * S_STATS, R).astype(jnp.int8)
                ohlo = (iota_lo == lo[None, :]).astype(jnp.int8)
                h = lax.dot_general(A, ohlo, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.int32)
            else:
                A = jnp.where(J[:, None, :], stats[None, :, :], 0.0) \
                    .reshape(gwe * NH * S_STATS, R).astype(jnp.bfloat16)
                ohlo = (iota_lo == lo[None, :]).astype(jnp.bfloat16)
                h = lax.dot_general(A, ohlo, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            parts.append(h)                                   # (gwe*NH*S, nl)
    return parts


# ===========================================================================
# Phase 1: route rows by the previous level's splits
def _route_kernel(codesP_ref, heap_ref, tbl_ref, route_ref,
                  heap_out_ref, *, base, L, n_bins, any_cat, na_code):
    """Non-terminal route: heap update only — F is NOT streamed through
    the kernel (it is untouched between terminal passes)."""
    heap_out_ref[0, :] = _route_math(
        codesP_ref[...], heap_ref[0, :], tbl_ref[...], route_ref[...],
        base=base, L=L, n_bins=n_bins, any_cat=any_cat, na_code=na_code)


def _route_kernel_f(codesP_ref, heap_ref, tbl_ref, route_ref, valtab_ref,
                    f_ref, heap_out_ref, f_out_ref, *, base, L, n_bins,
                    eta, any_cat, na_code):
    """Terminal route: heap update + fused margin update F += eta*val[heap]
    (ComputePredAndRes's gather folded into the same stream)."""
    R = f_ref.shape[1]
    newheap = _route_math(
        codesP_ref[...], heap_ref[0, :], tbl_ref[...], route_ref[...],
        base=base, L=L, n_bins=n_bins, any_cat=any_cat, na_code=na_code)
    heap_out_ref[0, :] = newheap
    nodes_p = valtab_ref.shape[1]
    iota_n = lax.broadcasted_iota(jnp.int32, (R, nodes_p), 1)
    # f32 one-hot x f32 table: leaf values must reach F at full precision
    # (scoring reads the same values as f32)
    ohn = (iota_n == newheap[:, None]).astype(jnp.float32)
    val_r = lax.dot_general(
        ohn, valtab_ref[...],
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)[:, 0]
    f_out_ref[0, :] = f_ref[0, :] + eta * val_r


@functools.partial(jax.jit,
                   static_argnames=("base", "L", "eta", "emit_f",
                                    "any_cat", "na_code"))
def sbh_route_pallas(codesP, heap, tbl, route_f, valtab=None, F=None, *,
                     base, L, eta=0.0, emit_f=False, any_cat=True,
                     na_code=255):
    """codesP (W_pad, n_pad) i32 packed plane; heap (n_pad,) i32;
    tbl (8, Lp) f32 (row 0 = split col, 1 = did, 2 = split bin,
    3 = na-goes-left); route_f (Lp, n_bins) f32 (1.0 = code goes right);
    valtab (8, NODES_P) f32 / F (n_pad,) f32 only with emit_f.
    Returns (newheap, newF) — newF is None when emit_f=False."""
    w_pad, n_pad = codesP.shape
    nblk = n_pad // BLOCK_ROWS
    n_bins = route_f.shape[1]
    if not emit_f:
        kernel = functools.partial(_route_kernel, base=base, L=L,
                                   n_bins=n_bins, any_cat=any_cat,
                                   na_code=na_code)
        newheap = pl.pallas_call(
            kernel,
            grid=(nblk,),
            in_specs=[
                pl.BlockSpec((w_pad, BLOCK_ROWS), lambda j: (0, j)),
                pl.BlockSpec((1, BLOCK_ROWS), lambda j: (0, j)),
                pl.BlockSpec(tbl.shape, lambda j: (0, 0)),
                pl.BlockSpec(route_f.shape, lambda j: (0, 0)),
            ],
            out_specs=pl.BlockSpec((1, BLOCK_ROWS), lambda j: (0, j)),
            out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",)),
        )(codesP, heap.reshape(1, n_pad), tbl, route_f)
        return newheap[0], None
    kernel = functools.partial(_route_kernel_f, base=base, L=L,
                               n_bins=n_bins, eta=eta, any_cat=any_cat,
                               na_code=na_code)
    newheap, newF = pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((w_pad, BLOCK_ROWS), lambda j: (0, j)),
            pl.BlockSpec((1, BLOCK_ROWS), lambda j: (0, j)),
            pl.BlockSpec(tbl.shape, lambda j: (0, 0)),
            pl.BlockSpec(route_f.shape, lambda j: (0, 0)),
            pl.BlockSpec(valtab.shape, lambda j: (0, 0)),
            pl.BlockSpec((1, BLOCK_ROWS), lambda j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, BLOCK_ROWS), lambda j: (0, j)),
            pl.BlockSpec((1, BLOCK_ROWS), lambda j: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
            jax.ShapeDtypeStruct((1, n_pad), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(codesP, heap.reshape(1, n_pad), tbl, route_f, valtab,
      F.reshape(1, n_pad))
    return newheap[0], newF[0]


def sbh_route_xla(codesT, heap, tbl, route_f, valtab=None, F=None, *,
                  base, L, eta=0.0, emit_f=False, any_cat=True,
                  na_code=255):
    """Pure-XLA fallback: same contract (CPU scatter/gather is fast).
    codesT is the UNPACKED (C_pad, n_pad) plane — uint8 or legacy i32;
    the integer arithmetic below is dtype-agnostic and bit-identical."""
    leaf = heap - base
    active = (leaf >= 0) & (leaf < L)
    leaf_c = jnp.where(active, leaf, 0)
    col_r = tbl[0, leaf_c].astype(jnp.int32)
    did_r = (tbl[1, leaf_c] > 0.5) & active
    code_sel = jnp.take_along_axis(
        codesT, jnp.clip(col_r, 0, codesT.shape[0] - 1)[None, :],
        axis=0)[0].astype(jnp.int32)
    n_bins = route_f.shape[1]
    go = route_f.reshape(-1)[leaf_c * n_bins + code_sel] > 0.5
    splits = active & did_r
    newheap = jnp.where(splits, 2 * heap + 1 + go.astype(jnp.int32), heap)
    newF = F + eta * valtab[0, newheap] if emit_f else F
    return newheap, newF


def sbh_route(codes, heap, tbl, route_f, valtab=None, F=None, *, base, L,
              eta=0.0, emit_f=False, any_cat=True, na_code=255):
    if is_packed(codes):
        return sbh_route_pallas(codes, heap, tbl, route_f, valtab, F,
                                base=base, L=L, eta=eta, emit_f=emit_f,
                                any_cat=any_cat, na_code=na_code)
    return sbh_route_xla(codes, heap, tbl, route_f, valtab, F,
                         base=base, L=L, eta=eta, emit_f=emit_f,
                         any_cat=any_cat, na_code=na_code)


# ===========================================================================
# Phase 2: leaf-window histogram accumulation
def _hist_kernel(codesP_ref, heap_ref, stats_ref, out_ref, *, base, L,
                 n_bins, gwe, half, int8):
    """Grid (pass, word-block, row-tile): accumulate the (4*W, gwe*S, nb)
    window block over the row sweep; gwe = min(l_eff, GW) leaves/pass."""
    p = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    A = _stats_panel(heap_ref[0, :], stats_ref[...], base=base, L=L,
                     gwe=gwe, p=p, half=half, int8=int8)
    parts = _dense_parts(codesP_ref[...], A, n_bins=n_bins, int8=int8)
    out_ref[...] = out_ref[...] + jnp.stack(parts)[None]


def _hist_pallas(codesP, heap, stats, *, base, L, n_bins, half, int8):
    w_pad, n_pad = codesP.shape
    cw = min(w_pad, WORD_TILE)
    ncw = w_pad // cw
    cc = cw * PACK
    l_eff = (L + 1) // 2 if half else L
    gwe = min(l_eff, GW)
    npass = max(1, -(-l_eff // gwe))
    # VMEM budget: out (cc, gwe*S, nb) f32 + A (gwe*S, R) + ohT (nb, R);
    # at gwe*S = 256 the 8MB out block forces a narrower row tile
    r_blk = BLOCK_ROWS if gwe * S_STATS <= 128 else BLOCK_ROWS // 2
    nblk = n_pad // r_blk
    kernel = functools.partial(_hist_kernel, base=base, L=L, n_bins=n_bins,
                               gwe=gwe, half=half, int8=int8)
    out = pl.pallas_call(
        kernel,
        grid=(npass, ncw, nblk),
        in_specs=[
            pl.BlockSpec((cw, r_blk), lambda p, g, j: (g, j)),
            pl.BlockSpec((1, r_blk), lambda p, g, j: (0, j)),
            pl.BlockSpec((S_STATS, r_blk), lambda p, g, j: (0, j)),
        ],
        out_specs=pl.BlockSpec(
            (1, cc, gwe * S_STATS, n_bins),
            lambda p, g, j: (p * ncw + g, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (npass * ncw, cc, gwe * S_STATS, n_bins),
            jnp.int32 if int8 else jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
    )(codesP, heap.reshape(1, n_pad), stats)
    # (npass*ncw, cc, gwe*S, nb) -> (L_pad, c_pack, S, nb)
    out = out.reshape(npass, ncw, cc, gwe, S_STATS, n_bins)
    return out.transpose(0, 3, 1, 2, 4, 5).reshape(
        npass * gwe, ncw * cc, S_STATS, n_bins)


@functools.partial(jax.jit, static_argnames=("base", "L", "n_bins", "half"))
def sbh_hist_pallas(codesP, heap, stats, *, base, L, n_bins, half=False):
    """codesP (W_pad, n_pad) i32 packed plane; heap (n_pad,) i32;
    stats (S, n_pad) f32. Returns (L_pad, c_pack, S_STATS, n_bins) f32
    with L_pad = npass*gwe and c_pack = 4*W_pad:
    hist[l] = per-(col, stat, bin) sums over rows with heap == base + l
    (half=True: over rows with heap == base + 2l — left children only)."""
    return _hist_pallas(codesP, heap, stats, base=base, L=L, n_bins=n_bins,
                        half=half, int8=False)


@functools.partial(jax.jit, static_argnames=("base", "L", "n_bins", "half"))
def sbh_hist_pallas_i8(codesP, heap, stats_i8, *, base, L, n_bins,
                       half=False):
    """int8-stats variant: stats (S, n_pad) int32 holding [-127, 127]
    (i32 input dtype: Mosaic's (S, R) int8 blocks don't meet the
    32-sublane granule; the kernel casts to i8 in VMEM), exact i32
    accumulation on the 2x-rate int8 MXU path (127 * 11M rows < 2^31)."""
    return _hist_pallas(codesP, heap, stats_i8, base=base, L=L,
                        n_bins=n_bins, half=half, int8=True)


@functools.partial(jax.jit, static_argnames=("base", "L", "n_bins", "half"))
def sbh_hist_xla(codesT, heap, stats, *, base, L, n_bins, half=False):
    """Pure-XLA fallback via segment-sum (CPU tests / non-TPU backends).
    codesT is the UNPACKED (C_pad, n_pad) plane — uint8 or legacy i32
    (bit-identical: the segment indices agree element-for-element).
    Jitted with static config: the lax.map below is a fresh-closure scan
    that would otherwise recompile on EVERY eager call (the per-level
    dispatch-count guard in tests/test_compile_guard.py watches this)."""
    c_pad, n_pad = codesT.shape
    l_eff = (L + 1) // 2 if half else L
    gwe = min(l_eff, GW)
    npass = max(1, -(-l_eff // gwe))
    L_pad = npass * gwe
    leaf = heap - base
    ok = (leaf >= 0) & (leaf < L)
    if half:
        ok = ok & ((leaf & 1) == 0)
        leaf = leaf >> 1
    lf = jnp.where(ok, leaf, L_pad)

    def one_col(c):
        idx = lf * n_bins + codesT[c].astype(jnp.int32)
        return jax.ops.segment_sum(stats.T, idx,
                                   num_segments=(L_pad + 1) * n_bins)

    hs = lax.map(one_col, jnp.arange(c_pad))       # (C, (L+1)*B, S)
    return hs.reshape(c_pad, L_pad + 1, n_bins, S_STATS)[:, :L_pad] \
             .transpose(1, 0, 3, 2)


def sbh_hist(codes, heap, stats, *, base, L, n_bins, half=False,
             radix=None):
    """Histogram dispatch. `radix`: None = auto (engage the radix
    shallow-window kernel wherever its probe compiled and the window
    qualifies), False = never, True = same as auto (the factorization
    only exists for qualifying windows)."""
    if is_packed(codes):
        if radix is not False and _radix_applicable(L, n_bins, half):
            return sbh_hist_radix(codes, heap, stats, base=base, L=L,
                                  n_bins=n_bins, half=half, int8=False)
        return sbh_hist_pallas(codes, heap, stats, base=base, L=L,
                               n_bins=n_bins, half=half)
    return sbh_hist_xla(codes, heap, stats, base=base, L=L, n_bins=n_bins,
                        half=half)


def sbh_hist_i8(codes, heap, stats_i8, *, base, L, n_bins, half=False,
                radix=None):
    """int8-stats histogram dispatch: i32 in [-127,127] per stat row, i32
    out (exact accumulation). The XLA fallback is the same segment-sum
    with integer dtype passthrough — bit-identical for the CPU tests."""
    if is_packed(codes):
        if radix is not False and _radix_applicable(L, n_bins, half):
            return sbh_hist_radix(codes, heap, stats_i8, base=base, L=L,
                                  n_bins=n_bins, half=half, int8=True)
        return sbh_hist_pallas_i8(codes, heap, stats_i8, base=base, L=L,
                                  n_bins=n_bins, half=half)
    return sbh_hist_xla(codes, heap, stats_i8, base=base, L=L,
                        n_bins=n_bins, half=half)


# ===========================================================================
# Radix-factored shallow-window histogram (PERF_NOTES item 1, measured-win
# regime only). VPU element-ops per (row, col): gwe*16*(1+S) + 16 vs dense
# 256 + gwe*S: 2.7x at window 1, 1.5x at window 2, WORSE at window 4 — so
# the dispatch engages only for effective windows <= 2, i.e. levels 0-2
# once sibling subtraction halves the window. Reference semantics
# unchanged: identical histograms to sbh_hist (parity-gated).
RADIX_NH = 16
RADIX_MAX_WINDOW = 2


def _radix_shape_ok(l_eff: int, n_bins: int) -> bool:
    return (l_eff <= RADIX_MAX_WINDOW and n_bins % RADIX_NH == 0
            and n_bins // RADIX_NH >= 8)


def _radix_applicable(L, n_bins, half) -> bool:
    l_eff = (L + 1) // 2 if half else L
    return _radix_shape_ok(l_eff, n_bins) and radix_supported()


def _radix_kernel(codesP_ref, heap_ref, stats_ref, out_ref, *, base, L,
                  n_bins, gwe, half, int8):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    heap = heap_ref[0, :]
    leaf = heap - base
    if half:
        # left children only; caller derives right = parent - left
        slot = leaf >> 1
        inw = (leaf >= 0) & (leaf < L) & ((leaf & 1) == 0)
    else:
        slot = leaf
        inw = (leaf >= 0) & (leaf < L)
    slot_c = jnp.where(inw, slot, gwe)     # dead rows -> key out of range
    parts = _radix_parts(codesP_ref[...], slot_c, stats_ref[...],
                         gwe=gwe, n_bins=n_bins, int8=int8)
    out_ref[...] = out_ref[...] + jnp.stack(parts)[None]


@functools.partial(jax.jit,
                   static_argnames=("base", "L", "n_bins", "half", "int8"))
def sbh_hist_radix(codesP, heap, stats, *, base, L, n_bins, half=False,
                   int8=False):
    """Radix-factored histogram for effective windows <= RADIX_MAX_WINDOW.
    Same contract as sbh_hist_pallas but returns exactly (l_eff, c_pack,
    S_STATS, n_bins); f32 out (bf16 accumulation) or i32 when int8."""
    w_pad, n_pad = codesP.shape
    cw = min(w_pad, WORD_TILE)
    ncw = w_pad // cw
    cc = cw * PACK
    l_eff = (L + 1) // 2 if half else L
    gwe = max(1, l_eff)
    NH = RADIX_NH
    nl = n_bins // NH
    nblk = n_pad // BLOCK_ROWS
    kernel = functools.partial(_radix_kernel, base=base, L=L, n_bins=n_bins,
                               gwe=gwe, half=half, int8=int8)
    out = pl.pallas_call(
        kernel,
        grid=(ncw, nblk),
        in_specs=[
            pl.BlockSpec((cw, BLOCK_ROWS), lambda g, j: (g, j)),
            pl.BlockSpec((1, BLOCK_ROWS), lambda g, j: (0, j)),
            pl.BlockSpec((S_STATS, BLOCK_ROWS), lambda g, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((1, cc, gwe * NH * S_STATS, nl),
                               lambda g, j: (g, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (ncw, cc, gwe * NH * S_STATS, nl),
            jnp.int32 if int8 else jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(codesP, heap.reshape(1, n_pad), stats)
    # (ncw, cc, gwe, NH, S, nl) -> (gwe, c_pack, S, NH*nl = n_bins)
    out = out.reshape(ncw, cc, gwe, RADIX_NH, S_STATS, nl)
    return out.transpose(2, 0, 1, 4, 3, 5).reshape(
        gwe, ncw * cc, S_STATS, n_bins)


# ===========================================================================
# Level-fused route+hist (PERF_NOTES item 4 — the last big code-stream
# saving: route and hist were TWO full streams of the code plane per
# level; one kernel reads the tile once, updates the heap, and
# accumulates the histogram over the UPDATED heap).
#
# Applicability is VMEM-bound: the WHOLE level's histogram block
# (c_pack, l_eff*S, nb) must stay resident across the single row sweep
# (there is no col-block grid dimension — the route phase needs every
# column's words in the tile anyway). That caps fusion at shallow levels
# (l_eff <= FUSE_MAX_WINDOW), exactly where the histogram is bandwidth-
# floor bound and the saving is real; deep (dot-bound) levels keep the
# tiled unfused kernels.
FUSE_MAX_WINDOW = 16
_FUSE_VMEM_OUT = 6 * 2 ** 20


def _fused_applicable(L_h: int, n_bins: int, c_pack: int) -> bool:
    l_eff = (L_h + 1) // 2
    return (l_eff <= FUSE_MAX_WINDOW
            and c_pack * l_eff * S_STATS * n_bins * 4 <= _FUSE_VMEM_OUT
            and fused_supported())


def _fused_kernel(codesP_ref, heap_ref, tbl_ref, route_ref, stats_ref,
                  heap_out_ref, hist_ref, *, base_r, L_r, base_h, L_h,
                  n_bins, any_cat, na_code, gwe, int8, radix):
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _():
        hist_ref[...] = jnp.zeros_like(hist_ref)

    words = codesP_ref[...]                                   # (W_pad, R)
    newheap = _route_math(words, heap_ref[0, :], tbl_ref[...],
                          route_ref[...], base=base_r, L=L_r,
                          n_bins=n_bins, any_cat=any_cat, na_code=na_code)
    heap_out_ref[0, :] = newheap
    # histogram over the UPDATED heap: left children of [base_h, base_h+L_h)
    stats = stats_ref[...]
    if radix:
        leaf = newheap - base_h
        slot = leaf >> 1
        inw = (leaf >= 0) & (leaf < L_h) & ((leaf & 1) == 0)
        slot_c = jnp.where(inw, slot, gwe)
        parts = _radix_parts(words, slot_c, stats, gwe=gwe,
                             n_bins=n_bins, int8=int8)
    else:
        A = _stats_panel(newheap, stats, base=base_h, L=L_h, gwe=gwe,
                         p=0, half=True, int8=int8)
        parts = _dense_parts(words, A, n_bins=n_bins, int8=int8)
    hist_ref[...] = hist_ref[...] + jnp.stack(parts)


@functools.partial(jax.jit,
                   static_argnames=("base_r", "L_r", "base_h", "L_h",
                                    "n_bins", "any_cat", "na_code", "int8",
                                    "radix"))
def sbh_route_hist_fused_pallas(codesP, heap, tbl, route_f, stats, *,
                                base_r, L_r, base_h, L_h, n_bins,
                                any_cat=True, na_code=255, int8=False,
                                radix=False):
    """ONE kernel: route splits of [base_r, base_r+L_r), then accumulate
    the half (left-children) histogram of [base_h, base_h+L_h) over the
    updated heap. Returns (newheap, hist (l_eff, c_pack, S, n_bins))."""
    w_pad, n_pad = codesP.shape
    c_pack = w_pad * PACK
    l_eff = (L_h + 1) // 2
    gwe = max(1, l_eff)
    nblk = n_pad // BLOCK_ROWS
    n_bins_rf = route_f.shape[1]
    assert n_bins_rf == n_bins
    if radix:
        NH = RADIX_NH
        nl = n_bins // NH
        hist_shape = (c_pack, gwe * NH * S_STATS, nl)
    else:
        hist_shape = (c_pack, gwe * S_STATS, n_bins)
    kernel = functools.partial(_fused_kernel, base_r=base_r, L_r=L_r,
                               base_h=base_h, L_h=L_h, n_bins=n_bins,
                               any_cat=any_cat, na_code=na_code, gwe=gwe,
                               int8=int8, radix=radix)
    newheap, hist = pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((w_pad, BLOCK_ROWS), lambda j: (0, j)),
            pl.BlockSpec((1, BLOCK_ROWS), lambda j: (0, j)),
            pl.BlockSpec(tbl.shape, lambda j: (0, 0)),
            pl.BlockSpec(route_f.shape, lambda j: (0, 0)),
            pl.BlockSpec((S_STATS, BLOCK_ROWS), lambda j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, BLOCK_ROWS), lambda j: (0, j)),
            pl.BlockSpec(hist_shape, lambda j: (0, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
            jax.ShapeDtypeStruct(hist_shape,
                                 jnp.int32 if int8 else jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(codesP, heap.reshape(1, n_pad), tbl, route_f, stats)
    if radix:
        nl = n_bins // RADIX_NH
        hist = hist.reshape(c_pack, gwe, RADIX_NH, S_STATS, nl) \
            .transpose(1, 0, 3, 2, 4).reshape(gwe, c_pack, S_STATS, n_bins)
    else:
        hist = hist.reshape(c_pack, gwe, S_STATS, n_bins) \
            .transpose(1, 0, 2, 3)
    return newheap[0], hist


def sbh_route_hist(codes, heap, tbl, route_f, stats, *, base_r, L_r,
                   base_h, L_h, n_bins, any_cat=True, na_code=255,
                   int8=False, fused=None, radix=None):
    """Fused-or-sequential level pass: route the previous level's splits,
    then accumulate the new level's half (left-children) histogram over
    the updated heap. `fused`: None = auto (engage the fused Pallas
    program wherever its probe compiled and the level qualifies), False =
    always sequential; the sequential path is also the XLA/CPU path and
    is semantically identical (tier-1 gated). Returns (newheap, hist)."""
    if (is_packed(codes) and fused is not False
            and _fused_applicable(L_h, n_bins, codes.shape[0] * PACK)):
        l_eff = (L_h + 1) // 2
        use_radix = (radix is not False and _radix_shape_ok(l_eff, n_bins)
                     and radix_supported())
        return sbh_route_hist_fused_pallas(
            codes, heap, tbl, route_f, stats, base_r=base_r, L_r=L_r,
            base_h=base_h, L_h=L_h, n_bins=n_bins, any_cat=any_cat,
            na_code=na_code, int8=int8, radix=use_radix)
    newheap, _ = sbh_route(codes, heap, tbl, route_f, base=base_r, L=L_r,
                           any_cat=any_cat, na_code=na_code)
    hist_fn = sbh_hist_i8 if int8 else sbh_hist
    hist = hist_fn(codes, newheap, stats, base=base_h, L=L_h,
                   n_bins=n_bins, half=True, radix=radix)
    return newheap, hist
