"""Pallas TPU kernel: leaf-partitioned histogram accumulation — the rebuild
of H2O's ScoreBuildHistogram2 hot loop (SURVEY §2.4 row 1).

Reference semantics: hex/tree/ScoreBuildHistogram2.java:20-60 accumulates
per-(leaf, column) histograms of {w, wY, wYY} over binned rows, with private
per-thread copies merged in reduce (DHistogram.java:59-70, :338). The
reference avoids CAS by giving each (column, row-range) task a private copy.

TPU-native design: rows are kept PARTITIONED by leaf (leaf-aligned blocks of
R rows, maintained by the grower's stable-partition step), so a histogram is
a sequence of per-block accumulations that all land in the SAME output tile
while consecutive grid steps visit the same leaf — Pallas keeps the output
block resident in VMEM across those steps (the grouped-matmul revisiting
pattern) and flushes once per (leaf, column-tile). The per-block compute is
a one-hot expansion of the bin codes (VPU compare against a broadcasted
iota) contracted with the per-row stats panel on the MXU:

    hist[s, b] += stats[s, r] @ onehot[r, b]      (8, R) x (R, B) -> (8, B)

There is no CAS, no private copies, and no reduce tree: cross-shard merging
is a single psum over the mesh row axis done by the caller.

Stats panel rows (sublane dim, padded to 8): 0=row count, 1=weight w,
2=w*grad, 3=w*hess — count feeds the partition bookkeeping, w feeds
min_rows, (wg, wh) feed split gain and Newton leaf values
(hex/tree/DHistogram.java _vals packing analog).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

try:  # Pallas import is deferred-safe: exotic envs may lack Mosaic
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

# Rows per partition block == rows per kernel grid step. Must divide n_pad.
BLOCK_ROWS = 1024
# Stats panel sublane count (f32 tile granule).
N_STATS = 8
# Column tile per grid step.
COL_TILE = 8


def _hist_kernel(bl_ref, codes_ref, stats_ref, out_ref, *, n_cols, n_bins):
    """One grid step: accumulate one (leaf, column-tile) partial histogram.

    codes_ref: (BLOCK_ROWS, COL_PAD) int32 — bin codes for this row block
    stats_ref: (N_STATS, BLOCK_ROWS) f32 — stats panel (already permuted)
    out_ref:   (1, COL_TILE, N_STATS, n_bins) f32 — hist[leaf, ct] tile
    bl_ref:    scalar-prefetch (NBLK,) int32 — block -> leaf id
    """
    j = pl.program_id(1)
    first = jnp.logical_or(j == 0, bl_ref[j] != bl_ref[jnp.maximum(j - 1, 0)])

    stats = stats_ref[...]                                    # (8, R)
    iota = lax.broadcasted_iota(jnp.int32, (BLOCK_ROWS, n_bins), 1)

    parts = []
    for c in range(COL_TILE):
        code_c = codes_ref[:, c][:, None]                     # (R, 1)
        oh = (iota == code_c).astype(jnp.float32)             # (R, B)
        h = lax.dot_general(stats, oh, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
        parts.append(h)                                       # (8, B)
    h_tile = jnp.stack(parts)[None]                           # (1, CT, 8, B)

    @pl.when(first)
    def _init():
        out_ref[...] = h_tile

    @pl.when(jnp.logical_not(first))
    def _acc():
        out_ref[...] = out_ref[...] + h_tile


@functools.partial(jax.jit, static_argnames=("n_leaves", "n_bins"))
def hist_pallas(codes_p, stats_p, block_leaf, *, n_leaves, n_bins):
    """hist (n_leaves, C_pad, N_STATS, n_bins) f32 from partitioned codes.

    codes_p: (n_pad, C_pad) int32, rows grouped by leaf in BLOCK_ROWS-aligned
             segments (pad rows carry zero stats); C_pad multiple of COL_TILE
    stats_p: (N_STATS, n_pad) f32 stats panel in the same row order
    block_leaf: (n_pad // BLOCK_ROWS,) int32 — leaf owning each block,
             non-decreasing
    """
    n_pad, c_pad = codes_p.shape
    nblk = n_pad // BLOCK_ROWS
    n_ct = c_pad // COL_TILE

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_ct, nblk),
        in_specs=[
            pl.BlockSpec((BLOCK_ROWS, COL_TILE),
                         lambda ct, j, bl: (j, ct)),
            pl.BlockSpec((N_STATS, BLOCK_ROWS),
                         lambda ct, j, bl: (0, j)),
        ],
        out_specs=pl.BlockSpec((1, COL_TILE, N_STATS, n_bins),
                               lambda ct, j, bl: (bl[j], ct, 0, 0)),
    )
    kernel = functools.partial(_hist_kernel, n_cols=c_pad, n_bins=n_bins)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (n_leaves, c_pad, N_STATS, n_bins), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(block_leaf, codes_p, stats_p)
    return out


@functools.partial(jax.jit, static_argnames=("n_leaves", "n_bins"))
def hist_segsum(codes_p, stats_p, block_leaf, *, n_leaves, n_bins):
    """Reference/CPU fallback: same contract via segment-sum (scatter-add is
    fast on CPU, where the virtual-mesh tests run)."""
    n_pad, c_pad = codes_p.shape
    leaf_of_slot = jnp.repeat(block_leaf, BLOCK_ROWS)          # (n_pad,)
    base = leaf_of_slot * n_bins

    def one_col(c):
        idx = base + codes_p[:, c]
        return jax.ops.segment_sum(stats_p.T, idx,
                                   num_segments=n_leaves * n_bins)

    hs = lax.map(one_col, jnp.arange(c_pad))       # (C, L*B, 8)
    return hs.reshape(c_pad, n_leaves, n_bins, N_STATS) \
             .transpose(1, 0, 3, 2)


def build_hist(codes_p, stats_p, block_leaf, *, n_leaves, n_bins):
    """Dispatch: Pallas on TPU, segment-sum elsewhere."""
    if _HAVE_PALLAS and jax.default_backend() == "tpu":
        return hist_pallas(codes_p, stats_p, block_leaf,
                           n_leaves=n_leaves, n_bins=n_bins)
    return hist_segsum(codes_p, stats_p, block_leaf,
                       n_leaves=n_leaves, n_bins=n_bins)
