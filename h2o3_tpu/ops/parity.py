"""Kernel parity gate — the Pallas TPU kernels vs their XLA twins.

The CPU test suite only exercises the `_xla` fallbacks (`use_pallas()` is
False off-TPU), so a misrouting Pallas kernel could ship behind a good
throughput number. `kernel_parity_check` runs the real kernels against the
fallbacks on random numeric + categorical + NA inputs and asserts
bit-tolerance — the analog of the reference's POJO/MOJO parity discipline
(h2o-py/tests/testdir_javapredict). Called as a bench.py pre-step on TPU
and by tests/test_kernel_parity.py.

Round-4 shape: the Pallas kernels consume PACKED code planes (4 uint8
codes per i32 word, HP.pack_codes) while the XLA twins consume the uint8
plane — every check below therefore also proves the pack/extract round
trip on-chip, and the new level-fused route+hist kernel is checked
against the sequential pair in both dense and radix windows.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from h2o3_tpu.ops import hist_pallas as HP


def _rand_inputs(seed=0, n_pad=2 * HP.BLOCK_ROWS, c_pad=16, b_val=64,
                 n_bins=128, L=8):
    """Random uint8 codes incl. NA codes + their packed plane + heap
    spread over [base, base+L)."""
    rng = np.random.default_rng(seed)
    codes = rng.integers(0, b_val, (c_pad, n_pad)).astype(np.uint8)
    codes[rng.random((c_pad, n_pad)) < 0.05] = b_val          # NA code
    base = L - 1
    heap = rng.integers(base, base + L, n_pad).astype(np.int32)
    stats = rng.normal(0, 1, (HP.S_STATS, n_pad)).astype(np.float32)
    stats[3] = 0.0
    u8 = jnp.asarray(codes)
    return (u8, HP.pack_codes(u8), jnp.asarray(heap), jnp.asarray(stats),
            base, L, n_bins, b_val)


def _route_tables(rng, L, n_bins, b_val, c_pad):
    """Random split tables incl. categorical SET routing + NA dir. The
    pallas numeric fast path reads tbl rows 2/3 while the xla fallback
    always reads route_f — route_num is built consistent with both."""
    Lp = max(8, L)
    tbl = np.zeros((8, Lp), np.float32)
    tbl[0, :L] = rng.integers(0, c_pad, L)
    tbl[1, :L] = rng.random(L) < 0.8
    tbl[2, :L] = rng.integers(0, b_val - 1, L)       # numeric split bin
    tbl[3, :L] = rng.random(L) < 0.5                 # NA goes left
    route_cat = (rng.random((Lp, n_bins)) < 0.5).astype(np.float32)
    route_num = np.zeros((Lp, n_bins), np.float32)
    code_ids = np.arange(n_bins)[None, :]
    route_num[:L] = (code_ids > tbl[2, :L, None]).astype(np.float32)
    route_num[:L, b_val] = 1.0 - tbl[3, :L]
    return jnp.asarray(tbl), jnp.asarray(route_cat), jnp.asarray(route_num)


def kernel_parity_check(seed=0):
    """Assert pallas == xla for hist (full + half), i8 hist, radix, route
    (with and without the F stream) and the level-fused route+hist.
    Returns a dict of max deviations."""
    u8, packed, heap, stats, base, L, n_bins, b_val = _rand_inputs(seed)
    c_pad = u8.shape[0]
    devs = {}

    for half in (False, True):
        hp = HP.sbh_hist_pallas(packed, heap, stats, base=base, L=L,
                                n_bins=n_bins, half=half)
        hx = HP.sbh_hist_xla(u8, heap, stats, base=base, L=L,
                             n_bins=n_bins, half=half)
        l_eff = (L + 1) // 2 if half else L
        d = float(jnp.max(jnp.abs(hp[:l_eff, :c_pad] - hx[:l_eff])))
        devs[f"hist_half={half}"] = d
        assert d < 1e-2, (half, d)     # bf16 accumulation vs f32 segment-sum

    si = jnp.asarray(
        np.random.default_rng(seed + 1).integers(
            -127, 128, stats.shape).astype(np.int32))
    for half in (False, True):
        ip = HP.sbh_hist_pallas_i8(packed, heap, si, base=base, L=L,
                                   n_bins=n_bins, half=half)
        ix = HP.sbh_hist_xla(u8, heap, si, base=base, L=L,
                             n_bins=n_bins, half=half)
        l_eff = (L + 1) // 2 if half else L
        d = int(jnp.max(jnp.abs(ip[:l_eff, :c_pad] - ix[:l_eff])))
        devs[f"i8_half={half}"] = d
        assert d == 0, (half, d)       # i32 accumulation is exact

    # radix shallow-window kernel: parity at its whole dispatch regime
    # (windows 1 and 2, full + half, f32 + i8, n_bins % 16 == 0)
    if HP.radix_supported():
        u82, packed2, heap2, stats2, _, _, _, bv2 = _rand_inputs(
            seed + 3, b_val=255, n_bins=256, L=4)
        si2 = jnp.asarray(np.random.default_rng(seed + 4).integers(
            -127, 128, stats2.shape).astype(np.int32))
        for Lw, half in ((1, False), (2, False), (2, True), (4, True)):
            basew = Lw - 1
            hw = heap2 % Lw + basew
            l_eff = (Lw + 1) // 2 if half else Lw
            rp = HP.sbh_hist_radix(packed2, hw, stats2,
                                   base=basew, L=Lw, n_bins=256, half=half)
            rx = HP.sbh_hist_xla(u82, hw, stats2,
                                 base=basew, L=Lw, n_bins=256, half=half)
            d = float(jnp.max(jnp.abs(rp[:l_eff, :c_pad] - rx[:l_eff])))
            devs[f"radix_L={Lw}_half={half}"] = d
            assert d < 1e-2, (Lw, half, d)
            ri = HP.sbh_hist_radix(packed2, hw, si2, base=basew, L=Lw,
                                   n_bins=256, half=half, int8=True)
            rxi = HP.sbh_hist_xla(u82, hw, si2, base=basew, L=Lw,
                                  n_bins=256, half=half)
            di = int(jnp.max(jnp.abs(ri[:l_eff, :c_pad] - rxi[:l_eff])))
            devs[f"radix_i8_L={Lw}_half={half}"] = di
            assert di == 0, (Lw, half, di)

    rng = np.random.default_rng(seed + 2)
    tbl, route_cat, route_num = _route_tables(rng, L, n_bins, b_val, c_pad)
    valtab = jnp.asarray(
        np.concatenate([rng.normal(0, 1, (1, 128)),
                        np.zeros((7, 128))]).astype(np.float32))
    F = jnp.asarray(rng.normal(0, 1, u8.shape[1]).astype(np.float32))
    for any_cat in (True, False):
        route_f = route_cat if any_cat else route_num
        kw = dict(base=base, L=L, any_cat=any_cat, na_code=b_val)
        # terminal variant: heap + fused F update
        h_p, f_p = HP.sbh_route_pallas(packed, heap, tbl, route_f,
                                       valtab, F, eta=0.1, emit_f=True,
                                       **kw)
        h_x, f_x = HP.sbh_route_xla(u8, heap, tbl, route_f, valtab, F,
                                    eta=0.1, emit_f=True, **kw)
        dh = int(jnp.max(jnp.abs(h_p - h_x)))
        df = float(jnp.max(jnp.abs(f_p - f_x)))
        devs[f"route_cat={any_cat}_heap"] = dh
        devs[f"route_cat={any_cat}_F"] = df
        assert dh == 0, (any_cat, dh)  # routing must be bit-identical
        assert df < 1e-5, (any_cat, df)
        # non-terminal variant: heap only, no F stream
        h_p2, fnone = HP.sbh_route_pallas(packed, heap, tbl, route_f, **kw)
        dh2 = int(jnp.max(jnp.abs(h_p2 - h_x)))
        devs[f"route_cat={any_cat}_noF_heap"] = dh2
        assert fnone is None and dh2 == 0, (any_cat, dh2)

    # level-fused route+hist vs the sequential XLA pair, dense and radix
    # windows, f32 and i8 stats (the exact grow() level-d contract:
    # route [base_r, base_r+L_r) then half-hist [base_h, base_h+L_h))
    if HP.fused_supported():
        for L_h, radix in ((2, False), (2, True), (8, False), (32, False)):
            L_r = L_h >> 1
            base_r, base_h = L_r - 1, L_h - 1
            hw = heap % L_r + base_r
            tblr, rcat, _ = _route_tables(rng, L_r, n_bins, b_val, c_pad)
            if radix and not HP.radix_supported():
                continue
            nh_p, hist_p = HP.sbh_route_hist_fused_pallas(
                packed, hw, tblr, rcat, stats, base_r=base_r, L_r=L_r,
                base_h=base_h, L_h=L_h, n_bins=n_bins, any_cat=True,
                na_code=b_val, radix=radix)
            nh_x, _ = HP.sbh_route_xla(u8, hw, tblr, rcat,
                                       base=base_r, L=L_r, na_code=b_val)
            hist_x = HP.sbh_hist_xla(u8, nh_x, stats, base=base_h, L=L_h,
                                     n_bins=n_bins, half=True)
            l_eff = (L_h + 1) // 2
            dh = int(jnp.max(jnp.abs(nh_p - nh_x)))
            dv = float(jnp.max(jnp.abs(hist_p[:l_eff, :c_pad]
                                       - hist_x[:l_eff])))
            devs[f"fused_L={L_h}_radix={radix}_heap"] = dh
            devs[f"fused_L={L_h}_radix={radix}_hist"] = dv
            assert dh == 0, (L_h, radix, dh)
            assert dv < 1e-2, (L_h, radix, dv)
            sii = jnp.asarray(np.random.default_rng(seed + 5).integers(
                -127, 128, stats.shape).astype(np.int32))
            nh_i, hist_i = HP.sbh_route_hist_fused_pallas(
                packed, hw, tblr, rcat, sii, base_r=base_r, L_r=L_r,
                base_h=base_h, L_h=L_h, n_bins=n_bins, any_cat=True,
                na_code=b_val, int8=True, radix=radix)
            hist_xi = HP.sbh_hist_xla(u8, nh_x, sii, base=base_h, L=L_h,
                                      n_bins=n_bins, half=True)
            dvi = int(jnp.max(jnp.abs(hist_i[:l_eff, :c_pad]
                                      - hist_xi[:l_eff])))
            devs[f"fused_i8_L={L_h}_radix={radix}_hist"] = dvi
            assert int(jnp.max(jnp.abs(nh_i - nh_x))) == 0
            assert dvi == 0, (L_h, radix, dvi)
    return devs
