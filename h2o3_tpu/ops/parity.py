"""Kernel parity gate — the Pallas TPU kernels vs their XLA twins.

The CPU test suite only exercises the `_xla` fallbacks (`use_pallas()` is
False off-TPU), so a misrouting Pallas kernel could ship behind a good
throughput number. `kernel_parity_check` runs the real kernels against the
fallbacks on random numeric + categorical + NA inputs and asserts
bit-tolerance — the analog of the reference's POJO/MOJO parity discipline
(h2o-py/tests/testdir_javapredict). Called as a bench.py pre-step on TPU
and by tests/test_kernel_parity.py.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from h2o3_tpu.ops import hist_pallas as HP


def _rand_inputs(seed=0, n_pad=2 * HP.BLOCK_ROWS, c_pad=16, b_val=64,
                 n_bins=128, L=8):
    """Random codes incl. NA codes + heap spread over [base, base+L)."""
    rng = np.random.default_rng(seed)
    codes = rng.integers(0, b_val, (c_pad, n_pad)).astype(np.int32)
    codes[rng.random((c_pad, n_pad)) < 0.05] = b_val          # NA code
    base = L - 1
    heap = rng.integers(base, base + L, n_pad).astype(np.int32)
    stats = rng.normal(0, 1, (HP.S_STATS, n_pad)).astype(np.float32)
    stats[3] = 0.0
    return (jnp.asarray(codes), jnp.asarray(heap), jnp.asarray(stats),
            base, L, n_bins, b_val)


def kernel_parity_check(seed=0):
    """Assert pallas == xla for hist (full + half), i8 hist and route.
    Returns a dict of max deviations."""
    codes, heap, stats, base, L, n_bins, b_val = _rand_inputs(seed)
    devs = {}

    for half in (False, True):
        hp = HP.sbh_hist_pallas(codes, heap, stats, base=base, L=L,
                                n_bins=n_bins, half=half)
        hx = HP.sbh_hist_xla(codes, heap, stats, base=base, L=L,
                             n_bins=n_bins, half=half)
        d = float(jnp.max(jnp.abs(hp - hx)))
        devs[f"hist_half={half}"] = d
        assert d < 1e-2, (half, d)     # bf16 accumulation vs f32 segment-sum

    si = jnp.asarray(
        np.random.default_rng(seed + 1).integers(
            -127, 128, stats.shape).astype(np.int32))
    for half in (False, True):
        ip = HP.sbh_hist_pallas_i8(codes, heap, si, base=base, L=L,
                                   n_bins=n_bins, half=half)
        ix = HP.sbh_hist_xla(codes, heap, si, base=base, L=L,
                             n_bins=n_bins, half=half)
        d = int(jnp.max(jnp.abs(ip - ix)))
        devs[f"i8_half={half}"] = d
        assert d == 0, (half, d)       # i32 accumulation is exact

    # radix shallow-window kernel: parity at its whole dispatch regime
    # (windows 1 and 2, full + half, f32 + i8, n_bins % 16 == 0)
    if HP.radix_supported():
        codes2, heap2, stats2, _, _, _, bv2 = _rand_inputs(
            seed + 3, b_val=255, n_bins=256, L=4)
        si2 = jnp.asarray(np.random.default_rng(seed + 4).integers(
            -127, 128, stats2.shape).astype(np.int32))
        for Lw, half in ((1, False), (2, False), (2, True), (4, True)):
            basew = Lw - 1
            l_eff = (Lw + 1) // 2 if half else Lw
            rp = HP.sbh_hist_radix(codes2, heap2 % Lw + basew, stats2,
                                   base=basew, L=Lw, n_bins=256, half=half)
            rx = HP.sbh_hist_xla(codes2, heap2 % Lw + basew, stats2,
                                 base=basew, L=Lw, n_bins=256, half=half)
            d = float(jnp.max(jnp.abs(rp - rx[:l_eff])))
            devs[f"radix_L={Lw}_half={half}"] = d
            assert d < 1e-2, (Lw, half, d)
            ri = HP.sbh_hist_radix(codes2, heap2 % Lw + basew, si2,
                                   base=basew, L=Lw, n_bins=256,
                                   half=half, int8=True)
            rxi = HP.sbh_hist_xla(codes2, heap2 % Lw + basew, si2,
                                  base=basew, L=Lw, n_bins=256, half=half)
            di = int(jnp.max(jnp.abs(ri - rxi[:l_eff])))
            devs[f"radix_i8_L={Lw}_half={half}"] = di
            assert di == 0, (Lw, half, di)

    # route: random split tables incl. categorical SET routing + NA dir
    rng = np.random.default_rng(seed + 2)
    Lp = max(8, L)
    tbl = np.zeros((8, Lp), np.float32)
    tbl[0, :L] = rng.integers(0, codes.shape[0], L)
    tbl[1, :L] = rng.random(L) < 0.8
    tbl[2, :L] = rng.integers(0, b_val - 1, L)       # numeric split bin
    tbl[3, :L] = rng.random(L) < 0.5                 # NA goes left
    # categorical variant: arbitrary per-code SET routing.  numeric
    # variant: the pallas fast path reads tbl rows 2/3 while the xla
    # fallback always reads route_f — build route_f consistent with them.
    route_cat = (rng.random((Lp, n_bins)) < 0.5).astype(np.float32)
    route_num = np.zeros((Lp, n_bins), np.float32)
    code_ids = np.arange(n_bins)[None, :]
    route_num[:L] = (code_ids > tbl[2, :L, None]).astype(np.float32)
    route_num[:L, b_val] = 1.0 - tbl[3, :L]
    valtab = np.zeros((8, 128), np.float32)
    valtab[0] = rng.normal(0, 1, 128)
    F = jnp.asarray(rng.normal(0, 1, codes.shape[1]).astype(np.float32))
    for any_cat in (True, False):
        route_f = route_cat if any_cat else route_num
        args = (codes, heap, jnp.asarray(tbl), jnp.asarray(route_f),
                jnp.asarray(valtab), F)
        kw = dict(base=base, L=L, eta=0.1, emit_f=True, any_cat=any_cat,
                  na_code=b_val)
        h_p, f_p = HP.sbh_route_pallas(*args, **kw)
        h_x, f_x = HP.sbh_route_xla(*args, **kw)
        dh = int(jnp.max(jnp.abs(h_p - h_x)))
        df = float(jnp.max(jnp.abs(f_p - f_x)))
        devs[f"route_cat={any_cat}_heap"] = dh
        devs[f"route_cat={any_cat}_F"] = df
        assert dh == 0, (any_cat, dh)  # routing must be bit-identical
        assert df < 1e-5, (any_cat, df)
    return devs
