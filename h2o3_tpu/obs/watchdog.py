"""Stall watchdog — turns hangs into diagnostics.

The worst failure class in this runtime is the silent hang: an XLA:CPU
collective rendezvous deadlock (two concurrent multi-replica programs
starving each other's thread-pool slots), a replay-channel peer that
stopped acking, a micro-batch leader that died between registration and
dispatch. A hung process stops emitting metrics AND traces — the two
pillars that exist to explain it — so the only artifact a hang used to
produce was a frozen terminal and a human running py-spy after the fact.

The watchdog closes that gap. Code that is about to perform a wait that
CAN wedge wraps it in `watch(kind, ...)`:

  * REST handler dispatch            (api/server._route)
  * micro-batch follower waits       (serving/microbatch)
  * replay-channel broadcast barrier (deploy/multihost.Broadcaster)
  * device dispatches                (parallel/mrtask._traced_dispatch —
                                      the rendezvous-deadlock shape)

A daemon sentinel thread scans the live entries; one older than
H2O3_WATCHDOG_STALL_S (or its explicit per-watch deadline) trips the
watchdog, which — from its own, unstalled thread — captures a cluster
JStack (local all-thread dump + every worker's over the replay-channel
`jstack` collect op), the recent structured log tail, and the stalled
operations' descriptions, and writes it all into a PINNED flight-recorder
trace (`watchdog.trip` root span). It also logs a structured ERROR
correlated to that trace and bumps `h2o3_watchdog_trips_total{kind}`.
The next hang therefore produces a durable postmortem artifact readable
from a FRESH process via GET /3/Trace/{id} — instead of nothing.

Env surface:
  H2O3_WATCHDOG          "0" disables the sentinel (default on)
  H2O3_WATCHDOG_STALL_S  seconds a watched op may run before it is a
                         stall (default 300; per-watch deadline_s wins)
  H2O3_WATCHDOG_POLL_S   sentinel scan period (default min(stall/4, 5))
"""

from __future__ import annotations

import contextlib
import itertools
import os
import sys
import threading
import time
import traceback

from h2o3_tpu.analysis.lockdep import make_lock
from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.obs import tracing as _tracing
from h2o3_tpu.utils.env import env_bool, env_float

TRIPS = _om.counter(
    "h2o3_watchdog_trips_total",
    "watchdog trips — a watched operation (rest handler, micro-batch "
    "wait, replay ack barrier, device dispatch) ran past its stall "
    "deadline and a pinned diagnostic trace was captured, labeled by "
    "the stalled operation's kind")


# cached enable flag: watch() wraps EVERY device dispatch, and an
# os.environ read per call is measurable there (the utils/log _LEVEL
# discipline). Tests that flip H2O3_WATCHDOG reset the cache to None
# (monkeypatch.setattr restores it on teardown).
_ENABLED = None

# nullcontext carries no per-use state: one shared instance serves every
# disabled watch() call
_NULL = contextlib.nullcontext()


def enabled() -> bool:
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = env_bool("H2O3_WATCHDOG", True)
    return _ENABLED


def _stall_s() -> float:
    return env_float("H2O3_WATCHDOG_STALL_S", 300.0)


def _poll_s() -> float:
    v = env_float("H2O3_WATCHDOG_POLL_S", 0.0)
    return v if v > 0 else min(max(_stall_s() / 4.0, 0.05), 5.0)


# ---------------------------------------------------------------------------
# JStack — water/util/JStack + water/api/JStackHandler analog
def thread_dump() -> list:
    """Every live thread's stack as [{name, ident, daemon, stack}] —
    this process's half of GET /3/JStack and the watchdog's capture."""
    frames = sys._current_frames()
    out = []
    for t in threading.enumerate():
        fr = frames.get(t.ident)
        out.append({
            "name": t.name, "ident": t.ident,
            "daemon": bool(t.daemon),
            "alive": t.is_alive(),
            "stack": "".join(traceback.format_stack(fr)) if fr else "",
        })
    return out


def format_dump(threads: list) -> str:
    parts = []
    for t in threads:
        parts.append(f'--- thread "{t.get("name")}"'
                     f'{" daemon" if t.get("daemon") else ""} ---\n'
                     f'{t.get("stack") or "<no frame>"}')
    return "\n".join(parts)


class _Watch:
    """Slotted context manager for one watched operation — dispatch-path
    cheap: no generator frame, one dict insert/remove under a leaf lock.
    (mrtask calls this per device dispatch; a @contextmanager generator
    plus per-call imports was measurable there.)"""

    __slots__ = ("_wd", "_ent", "_token")

    def __init__(self, wd, kind, desc, deadline_s, trace):
        self._wd = wd
        self._token = next(wd._ids)
        self._ent = {"kind": kind, "desc": desc,
                     "thread": threading.current_thread().name,
                     "ident": threading.get_ident(),
                     "t0": time.monotonic(),
                     "deadline_s": deadline_s,
                     "trace": trace if trace is not None
                     else _tracing.current(),
                     "tripped": False}

    def __enter__(self):
        wd = self._wd
        with wd._lock:
            wd._entries[self._token] = self._ent
        if not wd._started:
            wd._ensure_thread()
        return self._ent

    def __exit__(self, *exc):
        with self._wd._lock:
            self._wd._entries.pop(self._token, None)
        return False


class Watchdog:
    """Registry of in-flight watched operations + the sentinel thread."""

    def __init__(self):
        self._lock = make_lock("watchdog")
        self._entries: dict = {}     # token -> entry dict
        self._ids = itertools.count(1)
        self._thread = None
        self._started = False        # fast-path flag: is_alive() per
        #                              watch is measurable on hot paths
        self._collector = None       # fn(op, timeout) -> [worker replies]
        self._trips: list = []       # recent trip summaries (diagnostics)

    # ---- wiring ---------------------------------------------------------
    def set_collector(self, fn):
        """Give the watchdog a cluster fan-out: the coordinator passes
        `lambda op, t: broadcaster.collect(op, timeout=t)` so a trip's
        JStack covers every host, not just this one."""
        self._collector = fn

    # ---- watched-operation registry -------------------------------------
    def watch(self, kind: str, desc: str = "", deadline_s=None,
              trace=None):
        """Context manager: register the calling thread's operation for
        the duration of the block. Near-free (one dict insert/remove
        under a leaf lock); the sentinel thread pays the scan cost."""
        if not enabled():
            return _NULL
        return _Watch(self, kind, desc, deadline_s, trace)

    def stalled(self) -> list:
        """Currently-stalled entries (sentinel's view; also the
        stalled-ops gauge and the /3/JStack `stalled` report)."""
        now = time.monotonic()
        default = _stall_s()
        with self._lock:
            return [dict(e, stalled_s=round(now - e["t0"], 3))
                    for e in self._entries.values()
                    if now - e["t0"] >= (e["deadline_s"] or default)]

    def trips(self) -> list:
        with self._lock:
            return list(self._trips)

    # ---- sentinel --------------------------------------------------------
    def _ensure_thread(self):
        if self._thread is not None and self._thread.is_alive():
            return
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            t = threading.Thread(target=self._run, daemon=True,
                                 name="h2o3-watchdog")
            self._thread = t
            self._started = True   # h2o3-ok: R003 under self._lock (the with-block above)
        t.start()

    def start(self):
        """Explicit start (the API server calls this; watch() also
        starts lazily so bare library use is covered)."""
        if enabled():
            self._ensure_thread()

    def _run(self):
        while True:
            time.sleep(_poll_s())
            if self._thread is not threading.current_thread():
                return               # a newer sentinel owns the scan
            try:
                self._scan()
            except Exception:   # noqa: BLE001 — the sentinel must survive
                traceback.print_exc()

    def _scan(self):
        now = time.monotonic()
        default = _stall_s()
        fresh = []
        with self._lock:
            for e in self._entries.values():
                limit = e["deadline_s"] or default
                if now - e["t0"] >= limit and not e["tripped"]:
                    e["tripped"] = True
                    fresh.append(dict(e, stalled_s=round(now - e["t0"], 3)))
        if fresh:
            # capture OUTSIDE the registry lock: the dump walks every
            # thread and the cluster collect does network waits
            self.trip(fresh)

    # ---- the trip --------------------------------------------------------
    def trip(self, stalls: list) -> str:
        """Capture a diagnostic artifact for the given stalled entries:
        one pinned flight-recorder trace holding a cluster JStack, the
        recent log tail and the stall descriptions. Returns the trace
        id. Runs on the sentinel thread (or a test's thread) — NEVER on
        a stalled one."""
        import secrets
        from h2o3_tpu.obs import recorder as _rec
        from h2o3_tpu.obs import timeline as _tl
        from h2o3_tpu.utils import log as _log

        tid = f"watchdog-{secrets.token_hex(4)}"
        _rec.RECORDER.pin(tid)
        local = thread_dump()
        cluster = [{"host": _tl.host_id(), "n_threads": len(local)}]
        remote_dumps = []
        # when the REPLAY CHANNEL is what stalled, its broadcast lock is
        # held by the stuck thread — a cluster collect would queue behind
        # it until the (much longer) ack deadline. Ship the local dump
        # promptly instead; the channel being wedged IS the finding.
        channel_stalled = any(s["kind"] == "replay" for s in stalls)
        if self._collector is not None and not channel_stalled:
            try:
                from h2o3_tpu.api.server import _collect_timeout
                timeout = _collect_timeout()
            except Exception:   # noqa: BLE001
                timeout = 2.0
            try:
                for i, remote in enumerate(self._collector("jstack",
                                                           timeout)):
                    if isinstance(remote, dict):
                        cluster.append({"host": remote.get("host", i + 1),
                                        "n_threads":
                                        len(remote.get("threads") or [])})
                        remote_dumps.append(remote)
                    else:
                        cluster.append({"host": i + 1, "lagging": True})
            except Exception:   # noqa: BLE001 — a wedged channel IS the
                pass            # incident; capture what we have locally
        kinds = sorted({s["kind"] for s in stalls})
        with _tracing.trace(tid):
            with _tl.span("watchdog.trip", kinds=",".join(kinds)) as sp:
                sp.parent_id = 0     # always a root: the episode is its
                #                      own trace, never a child of the
                #                      sentinel's ambient context
                sp.attrs["stalls"] = [
                    {k: s.get(k) for k in ("kind", "desc", "thread",
                                           "stalled_s", "trace")}
                    for s in stalls]
                # bounded attrs: segments are JSONL — a runaway dump must
                # not turn one span into a multi-MB line
                sp.attrs["jstack"] = format_dump(local)[:200_000]
                for r in remote_dumps:
                    sp.attrs[f"jstack_host{r.get('host')}"] = \
                        format_dump(r.get("threads") or [])[:200_000]
                sp.attrs["hosts"] = cluster
                if channel_stalled:
                    sp.attrs["cluster_jstack_skipped"] = \
                        "replay channel stalled: collect would queue " \
                        "behind the stuck broadcast lock"
                sp.attrs["logs"] = _log.records(100)
            # the ERROR record is trace-correlated (and itself a keep-rule
            # producer, so the trip trace is doubly retained)
            _log.err("watchdog: %s stalled past deadline — diagnostic "
                     "trace %s (stalls: %s)", ",".join(kinds), tid,
                     "; ".join(f'{s["kind"]}:{s["desc"]} '
                               f'{s["stalled_s"]}s' for s in stalls))
        for k in kinds:
            TRIPS.inc(kind=k)
        with self._lock:
            self._trips.append({"trace": tid, "t": time.time(),
                                "kinds": kinds,
                                "stalls": [s["desc"] for s in stalls]})
            del self._trips[:-32]
        return tid


WATCHDOG = Watchdog()


def watch(kind: str, desc: str = "", deadline_s=None, trace=None):
    """Module-level convenience: `with watchdog.watch("rest", path): ...`"""
    return WATCHDOG.watch(kind, desc=desc, deadline_s=deadline_s,
                          trace=trace)


def _stalled_series():
    from collections import Counter as _Counter
    counts = _Counter(e["kind"] for e in WATCHDOG.stalled())
    return [({"kind": k}, float(v)) for k, v in sorted(counts.items())]


_om.gauge("h2o3_watchdog_stalled_ops",
          "watched operations currently past their stall deadline, by "
          "kind — nonzero means a hang is IN PROGRESS right now",
          fn=_stalled_series)
