"""Observability subsystem — metrics registry + span timeline.

The reference ships water/util/WaterMeterCpuTicks + WaterMeterIo (counters
scraped over REST), water.TimeLine (per-node event ring assembled
cloud-wide via TimelineSnapshot at /3/Timeline) and per-job progress. This
package is the TPU-native rebuild: a process-global metrics registry
(Prometheus text at GET /metrics, JSON at GET /3/WaterMeter) and a bounded
ring of timed spans (GET /3/Timeline, merged across hosts through the
deploy/multihost replay channel).

Env surface:
  H2O3_OBS_TIMELINE_CAPACITY  span ring size (default 4096)
  H2O3_OBS_TRACE_DIR          xprof bridge: jax.profiler trace output dir
  H2O3_OBS_TRACE_SPAN         span-name prefix that triggers the capture
"""

from h2o3_tpu.obs.metrics import (REGISTRY, Counter, Gauge, Histogram,
                                  MetricsRegistry, counter, gauge, histogram)
from h2o3_tpu.obs.timeline import SPANS, Span, SpanTimeline, span

__all__ = ["REGISTRY", "Counter", "Gauge", "Histogram", "MetricsRegistry",
           "counter", "gauge", "histogram",
           "SPANS", "Span", "SpanTimeline", "span"]
