"""Observability subsystem — metrics registry + span timeline.

The reference ships water/util/WaterMeterCpuTicks + WaterMeterIo (counters
scraped over REST), water.TimeLine (per-node event ring assembled
cloud-wide via TimelineSnapshot at /3/Timeline) and per-job progress. This
package is the TPU-native rebuild: a process-global metrics registry
(Prometheus text at GET /metrics, JSON at GET /3/WaterMeter) and a bounded
ring of timed spans (GET /3/Timeline, merged across hosts through the
deploy/multihost replay channel).

Distributed additions (ISSUE 5): `tracing` mints Dapper-style trace ids
at the REST boundary and threads them through spans, jobs, the
micro-batcher and the multihost replay channel (`GET /3/Trace/{id}`
stitches them cloud-wide); `profiler` drives on-demand jax.profiler /
sampling captures behind `POST /3/Profiler`; the metrics registry gains
cluster federation (`GET /metrics?scope=cluster` merges every host's
snapshot under a per-host `host=` label).

Hang diagnostics (ISSUE 8): `watchdog` watches REST dispatch,
micro-batch waits, replay ack barriers and device dispatches for stalls
past H2O3_WATCHDOG_STALL_S and turns a hang into a pinned diagnostic
trace (cluster JStack + log tail, durable under ice_root); the
structured logger (utils/log) correlates every record to the active
trace/span and marks ERROR-logged traces for recorder retention.

Elastic membership (ISSUE 10): `h2o3_cloud_epoch` /
`h2o3_cloud_live_workers` gauges, excision/join/re-home/epoch-retry
counters and the `membership.*` spans live in deploy/membership.py and
core/kvstore.py; the membership env surface (H2O3_HEARTBEAT_S,
H2O3_REPLAY_RECONNECT_S, H2O3_DRAIN_TIMEOUT_S, H2O3_CHAOS, …) is
documented in the README "Elastic cloud & chaos testing" section.

Env surface:
  H2O3_OBS_TIMELINE_CAPACITY  span ring size (default 4096)
  H2O3_WATCHDOG               "0" disables the stall sentinel
  H2O3_WATCHDOG_STALL_S       stall deadline for watched ops (300)
  H2O3_WATCHDOG_POLL_S        sentinel scan period (stall/4, max 5)
  H2O3_OBS_TRACE_DIR          xprof bridge: jax.profiler trace output dir
  H2O3_OBS_TRACE_SPAN         span-name prefix that triggers the capture
  H2O3_TRACING                "0" disables REST trace-id minting
  H2O3_OBS_COLLECT_TIMEOUT_S  per-host deadline for cluster-wide
                              timeline/trace/metrics collects (default 2)
  H2O3_PROFILE_DIR            default artifact dir for /3/Profiler
"""

from h2o3_tpu.obs.metrics import (REGISTRY, Counter, Gauge, Histogram,
                                  MetricsRegistry, counter, gauge, histogram)
from h2o3_tpu.obs.timeline import SPANS, Span, SpanTimeline, span
from h2o3_tpu.obs import tracing

__all__ = ["REGISTRY", "Counter", "Gauge", "Histogram", "MetricsRegistry",
           "counter", "gauge", "histogram",
           "SPANS", "Span", "SpanTimeline", "span", "tracing"]
