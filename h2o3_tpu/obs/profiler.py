"""On-demand profiling — POST /3/Profiler start/stop.

The reference exposes /3/Profiler (water/api/ProfilerHandler.java): every
node stack-samples itself and ships the hot stacks back over REST. The
TPU-native rebuild drives `jax.profiler.start_trace`/`stop_trace`, which
captures device traces (XLA ops, HLO, host callbacks) into a TensorBoard-
readable artifact dir. When the JAX profiler is unavailable (no backend,
already-active capture, stripped build), a pure-Python sampling profiler
stands in: a daemon thread samples every live thread's stack via
`sys._current_frames()` and writes a flamegraph-ready collapsed-stack
file — the ProfilerHandler behavior, minus the JVM.

At most ONE session runs at a time (the jax profiler is process-global
and two overlapping captures corrupt both); a second start answers 409.

Env surface:
  H2O3_PROFILE_DIR  default artifact directory (else a fresh tempdir)
"""

from __future__ import annotations

import os
import sys
import threading
import time

from h2o3_tpu.analysis.lockdep import make_lock
from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.utils.env import env_str

SESSIONS = _om.counter(
    "h2o3_profiler_sessions_total",
    "profiler sessions started via /3/Profiler, labeled by kind "
    "(jax = device trace, sampling = pure-Python stack sampler)")


class ProfilerBusy(RuntimeError):
    """A session is already running — the jax profiler is process-global,
    so concurrent captures are refused (HTTP 409)."""


class ProfilerIdle(RuntimeError):
    """stop() without a running session (HTTP 400)."""


class _SamplingProfiler:
    """Stack sampler: every `interval_s`, collapse each live thread's
    frame stack to "file:func;file:func;..." and count it. stop() writes
    the counts in flamegraph collapsed-stack format."""

    def __init__(self, interval_s: float = 0.01, max_depth: int = 64):
        self.interval_s = interval_s
        self.max_depth = max_depth
        self.samples: dict = {}
        self.n_samples = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="h2o3-pyprof")

    def start(self):
        self._thread.start()

    def _run(self):
        me = threading.get_ident()
        while not self._stop.wait(self.interval_s):
            for tid, frame in list(sys._current_frames().items()):
                if tid == me:
                    continue
                stack = []
                f = frame
                while f is not None and len(stack) < self.max_depth:
                    code = f.f_code
                    fname = code.co_filename.rsplit("/", 1)[-1]
                    stack.append(f"{fname}:{code.co_name}")
                    f = f.f_back
                key = ";".join(reversed(stack))
                self.samples[key] = self.samples.get(key, 0) + 1
            self.n_samples += 1

    def stop(self, out_dir: str) -> str:
        self._stop.set()
        self._thread.join(timeout=2.0)
        # snapshot: if a huge sampling pass outlives the bounded join,
        # the thread may still be inserting — iterate a copy, never the
        # live dict
        samples = dict(self.samples)
        path = os.path.join(out_dir, "pyprof.collapsed")
        with open(path, "w") as fh:
            for stack, cnt in sorted(samples.items(),
                                     key=lambda kv: -kv[1]):
                fh.write(f"{stack} {cnt}\n")
        return path


class ProfilerManager:
    """One-session-at-a-time gate around the two capture backends."""

    def __init__(self):
        self._lock = make_lock("profiler")
        self._active: dict | None = None

    def _artifact_dir(self, trace_dir) -> str:
        d = trace_dir or env_str("H2O3_PROFILE_DIR", "")
        if not d:
            import tempfile
            d = tempfile.mkdtemp(prefix="h2o3-profile-")
        os.makedirs(d, exist_ok=True)
        return d

    def start(self, trace_dir=None, kind: str = "auto") -> dict:
        """Start a capture. kind: "auto" (jax, falling back to sampling),
        "jax" (fail if unavailable), "sampling" (force the fallback)."""
        if kind not in ("auto", "jax", "sampling"):
            raise ValueError(f"profiler kind {kind!r} "
                             "(want auto|jax|sampling)")
        with self._lock:
            if self._active is not None:
                raise ProfilerBusy(
                    f"a {self._active['kind']} profiler session is already "
                    f"running (dir {self._active['dir']}) — stop it first")
            d = self._artifact_dir(trace_dir)
            used = None
            if kind in ("auto", "jax"):
                try:
                    import jax
                    jax.profiler.start_trace(d)
                    used = "jax"
                except Exception:   # noqa: BLE001 — fall back to sampling
                    if kind == "jax":
                        raise
            sampler = None
            if used is None:
                sampler = _SamplingProfiler()
                sampler.start()
                used = "sampling"
            self._active = {"kind": used, "dir": d, "sampler": sampler,
                            "t_start": time.time()}
            SESSIONS.inc(kind=used)
            return {"status": "started", "kind": used, "dir": d}

    def stop(self) -> dict:
        with self._lock:
            if self._active is None:
                raise ProfilerIdle("no profiler session is running")
            sess = self._active
            self._active = None
            out = {"status": "stopped", "kind": sess["kind"],
                   "dir": sess["dir"],
                   "seconds": round(time.time() - sess["t_start"], 3)}
            if sess["kind"] == "jax":
                try:
                    import jax
                    jax.profiler.stop_trace()
                except Exception as ex:   # noqa: BLE001 — report, don't 500
                    out["error"] = repr(ex)
            else:
                out["artifact"] = sess["sampler"].stop(sess["dir"])
                out["samples"] = sess["sampler"].n_samples
            return out

    def status(self) -> dict:
        with self._lock:
            if self._active is None:
                return {"active": False}
            return {"active": True, "kind": self._active["kind"],
                    "dir": self._active["dir"],
                    "seconds": round(time.time()
                                     - self._active["t_start"], 3)}


PROFILER = ProfilerManager()


# ---------------------------------------------------------------------------
# Cluster-wide capture (ISSUE 7). POST /3/Profiler?cluster=1 fans
# start/stop over the replay channel's collect op; each worker runs its
# own PROFILER session and ships its sampling flamegraph back as text
# (bounded), and the coordinator merges every host's collapsed stacks —
# each line prefixed host<N>; — into ONE flamegraph-ready file.
_MAX_COLLAPSED_BYTES = 256 * 1024


def read_collapsed(path: str, max_bytes: int = _MAX_COLLAPSED_BYTES) -> str:
    """A pyprof.collapsed artifact as text, truncated at a line boundary
    so it can ride a JSON collect ack without blowing the frame bound."""
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read(max_bytes + 1)
    except OSError:
        return ""
    if len(text) > max_bytes:
        text = text[:max_bytes]
        text = text[: text.rfind("\n") + 1]
    return text


def collect_op(op: str):
    """Worker-side handler for the profiler collect ops
    ("profiler:start:<kind>" / "profiler:stop") — runs inside
    _collect_local on the replay channel, so errors answer as data, never
    as a dead worker slot."""
    try:
        if op.startswith("profiler:start:"):
            kind = op[len("profiler:start:"):] or "auto"
            return PROFILER.start(kind=kind)
        if op == "profiler:stop":
            out = PROFILER.stop()
            if out.get("artifact"):
                out["collapsed"] = read_collapsed(out["artifact"])
            return out
    except (ProfilerBusy, ProfilerIdle, ValueError) as ex:
        return {"status": "error", "error": str(ex)}
    return {"status": "error", "error": f"unknown profiler op {op!r}"}


def merge_collapsed(parts, out_dir: str) -> str | None:
    """[(host, collapsed_text)] → one host-prefixed flamegraph file
    (`pyprof.merged.collapsed` under out_dir — a distinct name, so the
    coordinator's raw `pyprof.collapsed` capture survives): every stack
    line becomes
    `host<N>;<stack> <count>`, so one flamegraph shows where each host
    spent its samples side by side. Returns the path, or None when no
    host produced sampling output (pure jax captures have no collapsed
    text — their artifacts stay host-local TensorBoard dirs)."""
    merged: dict = {}
    for host, text in parts:
        for line in (text or "").splitlines():
            stack, _, cnt = line.rpartition(" ")
            if not stack or not cnt.isdigit():
                continue
            key = f"host{host};{stack}"
            merged[key] = merged.get(key, 0) + int(cnt)
    if not merged:
        return None
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "pyprof.merged.collapsed")
    with open(path, "w", encoding="utf-8") as fh:
        for stack, cnt in sorted(merged.items(), key=lambda kv: -kv[1]):
            fh.write(f"{stack} {cnt}\n")
    return path
