"""On-demand profiling — POST /3/Profiler start/stop.

The reference exposes /3/Profiler (water/api/ProfilerHandler.java): every
node stack-samples itself and ships the hot stacks back over REST. The
TPU-native rebuild drives `jax.profiler.start_trace`/`stop_trace`, which
captures device traces (XLA ops, HLO, host callbacks) into a TensorBoard-
readable artifact dir. When the JAX profiler is unavailable (no backend,
already-active capture, stripped build), a pure-Python sampling profiler
stands in: a daemon thread samples every live thread's stack via
`sys._current_frames()` and writes a flamegraph-ready collapsed-stack
file — the ProfilerHandler behavior, minus the JVM.

At most ONE session runs at a time (the jax profiler is process-global
and two overlapping captures corrupt both); a second start answers 409.

Env surface:
  H2O3_PROFILE_DIR  default artifact directory (else a fresh tempdir)
"""

from __future__ import annotations

import os
import sys
import threading
import time

from h2o3_tpu.analysis.lockdep import make_lock
from h2o3_tpu.obs import metrics as _om

SESSIONS = _om.counter(
    "h2o3_profiler_sessions_total",
    "profiler sessions started via /3/Profiler, labeled by kind "
    "(jax = device trace, sampling = pure-Python stack sampler)")


class ProfilerBusy(RuntimeError):
    """A session is already running — the jax profiler is process-global,
    so concurrent captures are refused (HTTP 409)."""


class ProfilerIdle(RuntimeError):
    """stop() without a running session (HTTP 400)."""


class _SamplingProfiler:
    """Stack sampler: every `interval_s`, collapse each live thread's
    frame stack to "file:func;file:func;..." and count it. stop() writes
    the counts in flamegraph collapsed-stack format."""

    def __init__(self, interval_s: float = 0.01, max_depth: int = 64):
        self.interval_s = interval_s
        self.max_depth = max_depth
        self.samples: dict = {}
        self.n_samples = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="h2o3-pyprof")

    def start(self):
        self._thread.start()

    def _run(self):
        me = threading.get_ident()
        while not self._stop.wait(self.interval_s):
            for tid, frame in list(sys._current_frames().items()):
                if tid == me:
                    continue
                stack = []
                f = frame
                while f is not None and len(stack) < self.max_depth:
                    code = f.f_code
                    fname = code.co_filename.rsplit("/", 1)[-1]
                    stack.append(f"{fname}:{code.co_name}")
                    f = f.f_back
                key = ";".join(reversed(stack))
                self.samples[key] = self.samples.get(key, 0) + 1
            self.n_samples += 1

    def stop(self, out_dir: str) -> str:
        self._stop.set()
        self._thread.join(timeout=2.0)
        # snapshot: if a huge sampling pass outlives the bounded join,
        # the thread may still be inserting — iterate a copy, never the
        # live dict
        samples = dict(self.samples)
        path = os.path.join(out_dir, "pyprof.collapsed")
        with open(path, "w") as fh:
            for stack, cnt in sorted(samples.items(),
                                     key=lambda kv: -kv[1]):
                fh.write(f"{stack} {cnt}\n")
        return path


class ProfilerManager:
    """One-session-at-a-time gate around the two capture backends."""

    def __init__(self):
        self._lock = make_lock("profiler")
        self._active: dict | None = None

    def _artifact_dir(self, trace_dir) -> str:
        d = trace_dir or os.environ.get("H2O3_PROFILE_DIR")
        if not d:
            import tempfile
            d = tempfile.mkdtemp(prefix="h2o3-profile-")
        os.makedirs(d, exist_ok=True)
        return d

    def start(self, trace_dir=None, kind: str = "auto") -> dict:
        """Start a capture. kind: "auto" (jax, falling back to sampling),
        "jax" (fail if unavailable), "sampling" (force the fallback)."""
        if kind not in ("auto", "jax", "sampling"):
            raise ValueError(f"profiler kind {kind!r} "
                             "(want auto|jax|sampling)")
        with self._lock:
            if self._active is not None:
                raise ProfilerBusy(
                    f"a {self._active['kind']} profiler session is already "
                    f"running (dir {self._active['dir']}) — stop it first")
            d = self._artifact_dir(trace_dir)
            used = None
            if kind in ("auto", "jax"):
                try:
                    import jax
                    jax.profiler.start_trace(d)
                    used = "jax"
                except Exception:   # noqa: BLE001 — fall back to sampling
                    if kind == "jax":
                        raise
            sampler = None
            if used is None:
                sampler = _SamplingProfiler()
                sampler.start()
                used = "sampling"
            self._active = {"kind": used, "dir": d, "sampler": sampler,
                            "t_start": time.time()}
            SESSIONS.inc(kind=used)
            return {"status": "started", "kind": used, "dir": d}

    def stop(self) -> dict:
        with self._lock:
            if self._active is None:
                raise ProfilerIdle("no profiler session is running")
            sess = self._active
            self._active = None
            out = {"status": "stopped", "kind": sess["kind"],
                   "dir": sess["dir"],
                   "seconds": round(time.time() - sess["t_start"], 3)}
            if sess["kind"] == "jax":
                try:
                    import jax
                    jax.profiler.stop_trace()
                except Exception as ex:   # noqa: BLE001 — report, don't 500
                    out["error"] = repr(ex)
            else:
                out["artifact"] = sess["sampler"].stop(sess["dir"])
                out["samples"] = sess["sampler"].n_samples
            return out

    def status(self) -> dict:
        with self._lock:
            if self._active is None:
                return {"active": False}
            return {"active": True, "kind": self._active["kind"],
                    "dir": self._active["dir"],
                    "seconds": round(time.time()
                                     - self._active["t_start"], 3)}


PROFILER = ProfilerManager()
