"""Flight recorder — durable trace retention with tail-based sampling.

The span timeline (obs/timeline) is the water.TimeLine analog: a fixed
ring that forgets everything under load, so the one trace you need after
an incident — the slow or failed request — is exactly the one that's
gone. The recorder closes that gap the Dapper way (Sigelman et al.):
completed spans stream into bounded on-disk SEGMENT files under the ice
root, and the keep/drop decision is made at TRACE COMPLETION (tail-based
sampling), when the outcome is known:

  * error traces (a span with an `error` attr, or a 5xx `status`),
  * slow traces (any span over H2O3_OBS_SLOW_MS),
  * explicitly-sampled traces (`X-H2O3-Sample: 1` → a `sampled` attr)

are ALWAYS retained; everything else is probabilistically downsampled
(H2O3_OBS_SAMPLE) so a flood of fast-OK traffic cannot evict the
interesting tail. Segments are append-only JSON lines (crash-safe: a
torn final line is skipped on read), written into a per-process file —
the io/spill.py discipline, so two processes sharing an ice root never
clobber each other — and garbage-collected oldest-first against the
H2O3_OBS_RETAIN_MB budget. Any process (including a FRESH one after a
restart) can search the shared segment directory: GET /3/Traces and the
GET /3/Trace/{id} disk read-through both land here.

Env surface:
  H2O3_OBS_RECORDER        "0" disables the recorder (default on)
  H2O3_OBS_RETAIN_MB       total on-disk segment budget (default 64)
  H2O3_OBS_SEGMENT_MB      roll the active segment past this (default 4)
  H2O3_OBS_SLOW_MS         always retain traces with a span over this
                           (default 1000)
  H2O3_OBS_SAMPLE          retention probability for fast-OK traces
                           (default 0.01)
  H2O3_OBS_TRACE_LINGER_S  finalize traces IDLE this long with the root
                           span still open (default 30) — a leaked span
                           or a thread that died mid-request; a trace
                           still streaming spans never expires
  H2O3_OBS_TRACE_MAX_SPANS finalize a trace early once it buffers this
                           many spans (default 512) — a traced training
                           loop cannot grow an unbounded buffer

Fragments: a trace can be finalized in PIECES — the buffer overflows
max-spans mid-request, or the linger timer expires while the root span is
still open. A fragment's outcome is unknowable (the `status`/`sampled`
attrs live on the still-open root), so overflow and linger-expired
fragments are always retained, explicitly-pinned traces are registered
with pin() at request ENTRY (before any outcome exists), and once any
fragment of a trace is durable the rest of that trace is kept too — the
head of an error trace must never lose the downsample lottery that its
tail would have won. The reverse ordering is covered as well: a fast-OK
fragment that DID lose the lottery (the request root closes 200 before
its background job errors) is stashed in a bounded in-memory buffer and
written retroactively — disposition "healed" — when a later fragment of
its trace is retained.
"""

from __future__ import annotations

import json
import os
import random
import time

from h2o3_tpu.analysis.lockdep import make_lock
from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.obs import segments as _segs
from h2o3_tpu.utils.env import env_bool, env_float, env_int

SPANS_SEEN = _om.counter(
    "h2o3_recorder_spans_total",
    "spans reaching the flight recorder at trace completion, labeled by "
    "disposition (retained = written to a durable segment, downsampled = "
    "dropped by tail-based sampling, healed = downsampled earlier but "
    "written retroactively when a later fragment of the trace was "
    "retained — healed spans were also counted downsampled)")


def enabled() -> bool:
    return env_bool("H2O3_OBS_RECORDER", True)


def _slow_ms() -> float:
    return env_float("H2O3_OBS_SLOW_MS", 1000.0)


def _sample_rate() -> float:
    return min(1.0, max(0.0, env_float("H2O3_OBS_SAMPLE", 0.01)))


def _retain_bytes() -> int:
    return int(env_float("H2O3_OBS_RETAIN_MB", 64.0) * 1e6)


def _segment_bytes() -> int:
    return int(env_float("H2O3_OBS_SEGMENT_MB", 4.0) * 1e6)


def _linger_s() -> float:
    return env_float("H2O3_OBS_TRACE_LINGER_S", 30.0)


def _max_trace_spans() -> int:
    return env_int("H2O3_OBS_TRACE_MAX_SPANS", 512)


def default_root() -> str:
    """Shared segment directory under the ice root. Every process READS
    the whole directory; each process WRITES only its own p<pid>-* files
    (the io/spill.py per-process discipline, relaxed to a name prefix so
    a fresh process can still search a dead one's segments)."""
    from h2o3_tpu.io import spill as _spill
    return os.path.join(_spill.get_ice_root(), "obs", "segments")


def _must_retain(spans: list) -> str | None:
    """The tail-sampling keep reasons, checked over the COMPLETED trace:
    returns "error" | "slow" | "sampled", or None (downsample lottery)."""
    slow = _slow_ms()
    reason = None
    for s in spans:
        attrs = s.get("attrs") or {}
        if attrs.get("error"):
            return "error"
        try:
            if int(attrs.get("status") or 0) >= 500:
                return "error"
        except (TypeError, ValueError):
            pass
        if attrs.get("sampled"):
            reason = "sampled"
        d = s.get("duration_ms")
        if reason is None and d is not None and d >= slow:
            reason = "slow"
    return reason


class FlightRecorder:
    """Per-trace span buffer + segment writer + retention GC."""

    def __init__(self, root: str | None = None):
        # one leaf lock: buffer mutations and segment appends are both
        # small host-side operations (json dumps + file write), never a
        # device sync or a network wait
        self._lock = make_lock("recorder")
        self._root = root
        self._buf: dict = {}        # trace_id -> {"spans": [...], "t0": mono}
        # FIFO-bounded id sets (insertion-ordered dicts): traces pinned
        # keep-always before their outcome exists, and traces with a
        # fragment already durable (the rest must follow it to disk)
        self._pinned: dict = {}
        self._sticky: dict = {}
        # traces a structured ERROR log record was correlated to (the
        # utils/log keep-rule producer): retained like error spans even
        # when every span in them closed fast and 2xx
        self._errored: dict = {}
        # recently-downsampled fragments, kept briefly in memory: a
        # LATER fragment of the same trace may yet error (fast-OK
        # request root closes before its background job fails) and must
        # be able to resurrect the head it would otherwise have lost
        self._dropped: dict = {}    # trace_id -> [span dicts]
        self._dropped_n = 0         # total stashed spans (bounds memory)
        self._fh = None             # active segment file handle
        self._path = None
        self._seq = 0
        self._last_scan = 0.0       # last ingest-path expiry scan (mono)
        self._written = 0           # bytes in the active segment

    # ---- wiring ---------------------------------------------------------
    def root(self) -> str:
        return self._root or default_root()

    def set_root(self, root: str | None):
        """Point the recorder elsewhere (tests use tmp dirs); closes the
        active segment so the next retained trace opens under the new
        root."""
        with self._lock:
            self._close_locked()
            self._root = root
            self._buf.clear()
            self._pinned.clear()
            self._sticky.clear()
            self._errored.clear()
            self._dropped.clear()
            self._dropped_n = 0

    def _close_locked(self):
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
        self._fh = None
        self._path = None
        self._written = 0

    _ID_SET_CAP = 4096

    @staticmethod
    def _remember(store: dict, tid):
        store[tid] = True
        while len(store) > FlightRecorder._ID_SET_CAP:
            store.pop(next(iter(store)))

    def pin(self, trace_id):
        """Mark a trace keep-always BEFORE its outcome is known
        (X-H2O3-Sample at request entry; the flag also rides the replay
        broadcast for worker fragments). Without this, a fragment
        finalized early — buffer overflow, linger expiry — enters the
        downsample lottery because the `sampled` attr lives on the
        still-open root span."""
        if trace_id is None or not enabled():
            return
        with self._lock:
            self._remember(self._pinned, trace_id)

    def mark_error(self, trace_id):
        """Mark a trace errored from OUTSIDE the span path — the
        structured logger calls this for every ERROR-level record that
        carries a trace id, so "request logged an error" is a keep rule
        even when no span recorded a 5xx status or an `error` attr.
        Fragments of the trace already downsampled are healed to disk
        immediately (the ERROR may arrive after a fast-OK root closed)."""
        if trace_id is None or not enabled():
            return
        with self._lock:
            self._remember(self._errored, trace_id)
            prior = self._dropped.pop(trace_id, None)
            if prior:
                self._dropped_n -= len(prior)   # h2o3-ok: R003 under self._lock — the with-block two lines up
                SPANS_SEEN.inc(len(prior), disposition="healed")
                self._remember(self._sticky, trace_id)
                self._append_locked(prior)

    # ---- ingest (called by SpanTimeline.end, outside the ring lock) -----
    def on_span_end(self, sp):
        """Buffer one completed span under its trace; when the trace's
        ROOT span closes, the whole trace is finalized (tail decision +
        optional durable write). Untraced spans cost one attribute read."""
        tid = getattr(sp, "trace", None)
        if tid is None or not enabled():
            return
        done = []
        with self._lock:
            ent = self._buf.get(tid)
            if ent is None:
                ent = self._buf[tid] = {"spans": [], "t0": 0.0}
            ent["spans"].append(sp.to_dict())
            # t0 = LAST activity: linger expires idle traces (leaked
            # span, thread died mid-request), never one still streaming
            ent["t0"] = time.monotonic()
            if sp.parent_id == 0:
                self._buf.pop(tid, None)
                done.append((tid, ent["spans"], False))
            elif len(ent["spans"]) >= _max_trace_spans():
                self._buf.pop(tid, None)
                done.append((tid, ent["spans"], True))
            # the expiry scan is O(live traces) under this lock: gate it
            # to a fraction of the linger window so a hot span path with
            # thousands of in-flight traces doesn't pay it per span end
            # (sweep() on the read paths / metrics scrape also expires)
            now_m = time.monotonic()
            if now_m - self._last_scan >= min(1.0, _linger_s() / 4):
                self._last_scan = now_m
                for k in self._expired_locked():
                    done.append((k, self._buf.pop(k)["spans"], True))
            for t, spans, overflow in done:
                self._finalize_locked(t, spans, overflow)

    def _expired_locked(self) -> list:
        """Trace ids idle past the linger window. Idle-expired traces
        are FRAGMENTS (the root never closed), so like overflow their
        outcome is unknowable: finalize retains them."""
        cutoff = time.monotonic() - _linger_s()
        return [k for k, e in self._buf.items() if e["t0"] < cutoff]

    def sweep(self):
        """Finalize idle-expired fragments. Span ingest sweeps on every
        end; the read paths and the h2o3_recorder_bytes gauge call this
        too, so a dead thread's open-rooted fragment becomes durable
        even if no traced span ever ends again in this process."""
        if not enabled():
            return
        with self._lock:
            for k in self._expired_locked():
                self._finalize_locked(k, self._buf.pop(k)["spans"], True)

    def _finalize_locked(self, tid, spans: list, overflow: bool = False):
        reason = _must_retain(spans)
        if reason is None and tid in self._errored:
            reason = "error"        # an ERROR log record named this trace
        if reason is None and tid in self._pinned:
            reason = "sampled"
        if reason is None and tid in self._sticky:
            reason = "sticky"       # a fragment is already durable: the
            #                         rest of the trace follows it
        if reason is None and overflow:
            reason = "overflow"     # mid-trace fragment, outcome
            #                         unknowable: never drop the head
        if reason is None and random.random() >= _sample_rate():
            SPANS_SEEN.inc(len(spans), disposition="downsampled")
            self._stash_dropped_locked(tid, spans)
            return
        SPANS_SEEN.inc(len(spans), disposition="retained")
        self._remember(self._sticky, tid)
        # heal the head: fragments of THIS trace dropped earlier (their
        # own roots closed fast-OK before this one erred) go to disk too
        prior = self._dropped.pop(tid, None)   # h2o3-ok: R003 _locked helper — every caller holds self._lock
        if prior:
            self._dropped_n -= len(prior)   # h2o3-ok: R003 _locked helper — every caller holds self._lock
            SPANS_SEEN.inc(len(prior), disposition="healed")
            self._append_locked(prior)
        self._append_locked(spans)

    _DROPPED_SPAN_CAP = 4096

    def _stash_dropped_locked(self, tid, spans: list):
        """Remember a downsampled fragment for a while (bounded FIFO by
        total span count) so a later error fragment can resurrect it."""
        self._dropped.setdefault(tid, []).extend(spans)   # h2o3-ok: R003 _locked helper — every caller holds self._lock
        self._dropped_n += len(spans)   # h2o3-ok: R003 _locked helper — every caller holds self._lock
        while self._dropped_n > self._DROPPED_SPAN_CAP and self._dropped:
            old = self._dropped.pop(next(iter(self._dropped)))   # h2o3-ok: R003 _locked helper — every caller holds self._lock
            self._dropped_n -= len(old)   # h2o3-ok: R003 _locked helper — every caller holds self._lock

    # ---- segment writing ------------------------------------------------
    def _open_segment_locked(self):
        d = self.root()
        os.makedirs(d, exist_ok=True)
        self._seq += 1
        self._path = os.path.join(
            d, f"p{os.getpid()}-{int(time.time())}-{self._seq:06d}.jsonl")
        self._fh = open(self._path, "a", encoding="utf-8")
        self._written = 0

    def _segment_alive_locked(self) -> bool:
        """True while the active segment path still names our open file
        (obs/segments.alive — the shared overlayfs-safe inode check)."""
        return _segs.alive(self._path, self._fh)

    def _append_locked(self, spans: list):
        try:
            if self._fh is None:
                self._open_segment_locked()
            elif not self._segment_alive_locked():
                # another process's GC unlinked our open segment (oldest
                # mtime wins regardless of owner): appends to the dead
                # inode would be invisible to every reader, silently
                # losing retained traces until the size roll — roll now
                self._close_locked()
                self._open_segment_locked()
            for s in spans:
                line = json.dumps(s, separators=(",", ":"),
                                  default=str) + "\n"
                self._fh.write(line)
                self._written += len(line)
            # flush per trace: a process crash loses at most the trace
            # being appended (torn lines are skipped on read)
            self._fh.flush()
            if self._written >= _segment_bytes():
                self._close_locked()
                self._gc_locked()
        except OSError:
            # a full/readonly disk must never take down the span path —
            # drop the active segment and keep serving from memory
            self._close_locked()

    def _segments(self) -> list:
        """All segment files under the root, oldest first."""
        return _segs.list_segments(self.root())

    def _gc_locked(self):
        _segs.gc(self.root(), _retain_bytes(), keep_path=self._path)

    def disk_bytes(self) -> int:
        # gauge callback: every /metrics scrape doubles as the periodic
        # linger sweep, so idle fragments drain on scrape cadence
        self.sweep()
        return sum(sz for _, _, sz in self._segments())

    def flush(self):
        """Close the active segment (tests; also makes its bytes visible
        to other processes' GC accounting immediately)."""
        with self._lock:
            self._close_locked()

    # ---- reading --------------------------------------------------------
    def _iter_disk_spans(self, newest_first: bool = True,
                         contains: str | None = None):
        """Yield span dicts from every segment under the root — including
        other processes' — tolerating torn trailing lines. `contains`
        prefilters raw lines by substring before the (much costlier)
        JSON parse: any span carrying a trace id as its own or a link
        contains it literally, so the filter is exact for that use."""
        segs = self._segments()
        with self._lock:
            fh = self._fh
            if fh is not None:
                try:
                    fh.flush()
                except OSError:
                    pass
        yield from _segs.iter_jsonl(segs, newest_first=newest_first,
                                    contains=contains)

    def load_trace(self, trace_id: str, limit: int = 2048) -> list:
        """Every durably-retained span of one trace (the GET /3/Trace/{id}
        disk read-through), including spans that LINK the trace."""
        self.sweep()
        out = []
        for s in self._iter_disk_spans(contains=trace_id):
            if s.get("trace") == trace_id \
                    or trace_id in ((s.get("attrs") or {}).get("links")
                                    or ()):
                out.append(s)
                if len(out) >= limit:
                    break
        out.sort(key=lambda s: s.get("start") or 0.0)
        return out

    def read_through(self, trace_id: str, ring_spans: list,
                     limit: int = 2048) -> tuple:
        """Ring → disk read-through for one trace: `ring_spans` plus
        every durably-retained span not already among them, deduped by
        (host, id) — the ONE definition of span identity both the
        GET /3/Trace/{id} handler and the worker's trace: collect op
        use. Returns (spans, n_from_disk)."""
        spans = list(ring_spans)
        seen = {(s.get("host"), s.get("id")) for s in spans}
        n_disk = 0
        for s in self.load_trace(trace_id, limit=limit):
            key = (s.get("host"), s.get("id"))
            if key not in seen:
                seen.add(key)
                spans.append(s)
                n_disk += 1
        return spans, n_disk

    def search(self, name=None, route=None, status=None, min_ms=None,
               since=None, until=None, limit=50, extra_spans=()) -> list:
        """Trace summaries matching the filters, newest first — the
        GET /3/Traces body. Scans the in-memory extras (the caller passes
        the timeline ring) plus the durable segments, newest first,
        stopping once the bounded working set fills. Worst case (few
        huge traces) this parses the whole retention dir — acceptable
        for an ops endpoint bounded by H2O3_OBS_RETAIN_MB, not a hot
        path; a per-segment trace index is the upgrade if it ever is.

        Filters: `name` substring on span names; `route` substring on the
        rest.request route attr; `status` "error" (5xx / error attr) or an
        exact status code; `min_ms` minimum span duration inside the
        trace; `since`/`until` bound the trace start (unix seconds)."""
        self.sweep()
        traces: dict = {}
        order: list = []
        bound = max(limit * 8, 256)

        def _match(t) -> bool:
            if name and not any(name in n for n in t["names"]):
                return False
            if route and not (t["route"] and route in t["route"]):
                return False
            if status == "error":
                if not t["error"]:
                    return False
            elif status not in (None, "", "all"):
                if str(t["status"]) != str(status):
                    return False
            if min_ms is not None and t["max_ms"] < float(min_ms):
                return False
            if since is not None and (t["start"] or 0) < float(since):
                return False
            if until is not None and (t["start"] or 0) > float(until):
                return False
            return True

        saturated = False           # every working-set slot matches the
        #                             filters: scanning further is futile

        def _feed(s):
            nonlocal saturated
            tid = s.get("trace")
            if not tid:
                return
            t = traces.get(tid)
            if t is None:
                if len(traces) >= bound:
                    # working set full: evict a non-matching candidate —
                    # a flood of fast-OK traces must not lock a durable
                    # error trace out of a filtered search
                    victim = next((v for v in order
                                   if not _match(traces[v])), None)
                    if victim is None:
                        saturated = True
                        return
                    order.remove(victim)
                    del traces[victim]
                t = traces[tid] = {"trace": tid, "n_spans": 0,
                                   "start": None, "end": None,
                                   "root": None, "route": None,
                                   "status": None, "max_ms": 0.0,
                                   "error": False, "names": set(),
                                   "seen": set()}
                order.append(tid)
            # a retained trace's spans are usually ALSO still in the ring
            # — count each (host, id) once, not once per source
            key = (s.get("host"), s.get("id"))
            if key in t["seen"]:
                return
            t["seen"].add(key)
            t["n_spans"] += 1
            t["names"].add(s.get("name") or "")
            st, en = s.get("start"), s.get("end")
            if st is not None and (t["start"] is None or st < t["start"]):
                t["start"] = st
            if en is not None and (t["end"] is None or en > t["end"]):
                t["end"] = en
            d = s.get("duration_ms")
            if d is not None:
                t["max_ms"] = max(t["max_ms"], d)
            attrs = s.get("attrs") or {}
            if s.get("parent") == 0 and t["root"] is None:
                t["root"] = s.get("name")
            if attrs.get("route"):
                t["route"] = attrs["route"]
            if attrs.get("status"):
                t["status"] = attrs["status"]
            if attrs.get("error") or \
                    str(attrs.get("status") or "").startswith("5"):
                t["error"] = True

        # the timeline ring snapshot arrives oldest-first; admit newest
        # traces into the bounded working set first, or under load the
        # ring alone fills it and the most recent incident never matches
        for s in reversed(list(extra_spans)):
            _feed(s)
        # keep scanning disk while eviction can still admit candidates —
        # a full working set of ring traces must not end the scan before
        # an on-disk (ring-evicted) trace matching the filters is read;
        # stop only when every slot already matches (more can't rank in)
        for s in self._iter_disk_spans():
            _feed(s)
            if saturated:
                break

        out = []
        for tid in order:
            t = traces[tid]
            if not _match(t):
                continue
            dur = None
            if t["start"] is not None and t["end"] is not None:
                dur = 1000.0 * (t["end"] - t["start"])
            out.append({"trace": tid, "n_spans": t["n_spans"],
                        "root": t["root"], "route": t["route"],
                        "status": t["status"], "start": t["start"],
                        "duration_ms": dur, "max_span_ms": t["max_ms"],
                        "error": t["error"]})
        out.sort(key=lambda t: t.get("start") or 0.0, reverse=True)
        return out[:limit]


RECORDER = FlightRecorder()

_om.gauge("h2o3_recorder_bytes",
          "durable trace segment bytes on disk under the ice root "
          "(bounded by H2O3_OBS_RETAIN_MB)",
          fn=lambda: float(RECORDER.disk_bytes()))
