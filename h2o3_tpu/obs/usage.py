"""Usage attribution & capacity observability — the device-time ledger,
per-request stage waterfall, and the cloud pressure model.

Three jobs (ISSUE 16):

  * **Device-time attribution** — the dispatch funnel (compat's
    collective guard, mrtask's traced dispatch, the scorer cache) wraps
    every device execution in `meter(kind, ...)`, which charges the
    elapsed wall seconds to the ambient (principal, model, kind) read
    from the obs TLS that QoS already stamps. Charges land in
    `h2o3_device_seconds_total{principal,kind}` plus a per-model series
    (`h2o3_model_device_seconds_total{model,kind}`, capped by
    H2O3_USAGE_MAX_MODELS the way QoS caps principals) and in an
    in-memory ledger `GET /3/Usage` renders per-tenant/per-model —
    merged cluster-wide over the `usage` collect op. Nested meters never
    double-charge: the OUTERMOST meter on a thread wins (a scorer
    dispatch contains a guarded jit launch; only the scorer charges).

  * **Per-request latency decomposition** — a TLS stage recorder the
    REST layer opens per request (`begin_request`) and the serving path
    feeds (`stage(name)` blocks around edge admission, queue wait, fair-
    gate wait, decode/staging, device, readback). The micro-batcher
    times its shared dispatch stages once per chunk (`capture_stages`)
    and stamps them onto every coalesced request, so followers get the
    same waterfall the leader measured. `finish_request` folds the
    un-attributed remainder into an `app` stage, feeds
    `h2o3_request_stage_seconds{stage}`, and the server returns the
    breakdown as a standard `Server-Timing` response header.

  * **Pressure** — `evaluate_pressure()` fuses SLO burn rates, queue
    depths, device utilization (device-seconds rate over wall), tier-
    pager occupancy + fault rate, and watchdog stalls into one
    HPA-external-metric-shaped document per host (`GET /3/CloudHealth`
    merges the cloud over the `cloudhealth` collect op), cached for the
    `h2o3_pressure{dimension}` gauges — the sensor the ROADMAP
    autoscaling item consumes.

Import discipline: this module imports only metrics/tracing/env at the
top so the parallel layer can reach it lazily without cycles; QoS (for
principal folding) and the serving/tiering/SLO subsystems are imported
at call time, by which point the import graph is settled.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager

from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.obs import tracing as _tracing
from h2o3_tpu.utils.env import env_bool, env_float, env_int

DEVICE_SECONDS = _om.counter(
    "h2o3_device_seconds_total",
    "device execution wall seconds charged to the requesting tenant "
    "(obs-TLS principal) per op kind — the accelerator analog of "
    "WaterMeter's per-core CPU ticks")
MODEL_DEVICE_SECONDS = _om.counter(
    "h2o3_model_device_seconds_total",
    "device execution wall seconds per model key and op kind; models "
    "past H2O3_USAGE_MAX_MODELS fold into the _other series")
STAGE_SECONDS = _om.histogram(
    "h2o3_request_stage_seconds",
    "per-request latency decomposition: wall seconds spent in each "
    "serving stage (edge admission, queue wait, gate wait, "
    "decode/staging, device, readback, app remainder) — the same "
    "breakdown the Server-Timing response header returns to callers")

# canonical waterfall order — `app` is the computed remainder so the
# emitted stages always sum to the request's measured wall time
STAGE_ORDER = ("edge", "queue", "gate", "decode", "device", "readback",
               "app")

# fold target for per-model series past the cardinality cap (the QoS
# principal-folding discipline applied to model keys)
OTHER_MODEL = "_other"

_TLS = threading.local()
_LOCK = threading.Lock()          # leaf lock: ledger + model census
_LEDGER: dict = {}                # (principal, model, kind) -> [s, calls, rows]
_TOTAL = [0.0]                    # cumulative device seconds, all series
_RATE: deque = deque(maxlen=4096)   # (monotonic, cumulative) rate samples
_KNOWN_MODELS: set = set()
_OVERRIDE: list = [None]          # set_enabled() override (None = env)
_TIER_PREV = [None]               # (monotonic, faults) for the fault rate
_TIER_RATE = [0.0]                # last fault rate over a full interval
_LAST_PRESSURE: dict = {}         # last evaluate_pressure() doc (gauge feed)

# burn rate at which the fast-burn multi-window alert pages (obs/slo.py
# default windows): pressure 1.0 on the slo_burn dimension = paging
_SLO_PAGE_BURN = 14.4
# tier faults/second treated as saturation on the tier_faults dimension
_TIER_FAULT_SATURATION = 100.0
# floor on the fault-rate interval: concurrent evaluations (a client GET
# racing a cluster collect) must not amplify a few faults over near-zero dt
_TIER_MIN_INTERVAL_S = 0.25


def _env_enabled() -> bool:
    """H2O3_USAGE master switch (attribution + stage recording)."""
    return env_bool("H2O3_USAGE", True)


def _max_models() -> int:
    return env_int("H2O3_USAGE_MAX_MODELS", 64)


def _rate_window_s() -> float:
    """Trailing window for the device-seconds rate → utilization."""
    return env_float("H2O3_USAGE_RATE_WINDOW_S", 60.0)


def enabled() -> bool:
    ov = _OVERRIDE[0]
    return _env_enabled() if ov is None else bool(ov)


def set_enabled(on):
    """Override the H2O3_USAGE switch from code (None restores the env
    reading) — the bench's ledger on/off A-B loop."""
    _OVERRIDE[0] = on


# ---------------------------------------------------------------------------
# device-time attribution


def _fold_principal(p) -> str:
    """The QoS principal discipline (sanitize + cardinality fold) owns
    principal naming; reuse it so usage series can never exceed the
    cardinality /metrics already admits."""
    try:
        from h2o3_tpu.serving import qos as _qos
        return _qos.resolve_principal(p or "")
    except Exception:   # noqa: BLE001 — attribution must never break dispatch
        return p or "anonymous"


def _fold_model(key) -> str:
    k = str(key)[:128]
    with _LOCK:
        if k in _KNOWN_MODELS:
            return k
        if len(_KNOWN_MODELS) < _max_models():
            _KNOWN_MODELS.add(k)
            return k
    return OTHER_MODEL


def charge(kind: str, seconds: float, model=None, rows: int = 0,
           principal=None):
    """Charge `seconds` of device time to (principal, model, kind).
    The principal defaults to the obs-TLS principal QoS stamped for the
    current request (anonymous otherwise)."""
    if not enabled():
        return
    s = max(0.0, float(seconds))
    p = _fold_principal(principal if principal is not None
                        else _tracing.principal())
    m = _fold_model(model) if model else ""
    DEVICE_SECONDS.inc(s, principal=p, kind=kind)
    if m:
        MODEL_DEVICE_SECONDS.inc(s, model=m, kind=kind)
    now = time.monotonic()
    with _LOCK:
        ent = _LEDGER.setdefault((p, m, kind), [0.0, 0, 0])
        ent[0] += s
        ent[1] += 1
        ent[2] += int(rows)
        _TOTAL[0] += s
        # rate samples keep a minimum spacing so a hot dispatch loop
        # updates the newest sample in place instead of churning the ring;
        # the retained timestamp must NOT advance, or sustained load pins
        # the ring to one ever-fresh sample and device_rate reads 0
        if _RATE and now - _RATE[-1][0] < 0.05:
            _RATE[-1] = (_RATE[-1][0], _TOTAL[0])
        else:
            _RATE.append((now, _TOTAL[0]))


class _Meter:
    """Outermost-wins device-time meter: a scorer dispatch CONTAINS a
    guarded jit launch, and both funnel layers are instrumented — the
    TLS flag makes the inner meter a no-op so the seconds charge once,
    at the layer that knows the model and row count."""

    __slots__ = ("kind", "model", "rows", "t0", "active")

    def __init__(self, kind, model, rows):
        self.kind = kind
        self.model = model
        self.rows = rows
        self.active = False

    def __enter__(self):
        if enabled() and not getattr(_TLS, "metering", False):
            self.active = True
            _TLS.metering = True
            self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self.active:
            _TLS.metering = False
            # an erroring dispatch still spent the device time it spent
            charge(self.kind, time.perf_counter() - self.t0,
                   model=self.model, rows=self.rows)
        return False


def meter(kind: str, model=None, rows: int = 0) -> _Meter:
    """Context manager metering device wall seconds into `charge()`."""
    return _Meter(kind, model, rows)


def device_seconds_total() -> float:
    with _LOCK:
        return _TOTAL[0]


def device_rate(window_s=None) -> float:
    """Trailing device-seconds per wall second over `window_s`."""
    window = _rate_window_s() if window_s is None else float(window_s)
    now = time.monotonic()
    with _LOCK:
        cum = _TOTAL[0]
        base_t, base_c = None, None
        for t, c in reversed(_RATE):
            base_t, base_c = t, c
            if now - t >= window:
                break
        if base_t is None or now - base_t <= 0.0:
            return 0.0
        return max(0.0, (cum - base_c) / (now - base_t))


def _device_count() -> int:
    try:
        import jax
        return max(1, jax.local_device_count())
    except Exception:   # noqa: BLE001 — chip-less containers still report
        return 1


# ---------------------------------------------------------------------------
# per-request stage waterfall


def begin_request():
    """Open the calling thread's stage recorder (REST entry)."""
    _TLS.stages = {} if enabled() else None


def clear_request():
    _TLS.stages = None


def stage_active() -> bool:
    return getattr(_TLS, "stages", None) is not None \
        or getattr(_TLS, "capture", None) is not None


def add_stage(name: str, seconds: float):
    """Add wall seconds to stage `name`. A capture (micro-batch shared
    dispatch timing) takes precedence over the request recorder so the
    leader's own request is stamped via the shared dict like every
    follower's — never twice."""
    s = max(0.0, float(seconds))
    cap = getattr(_TLS, "capture", None)
    if cap is not None:
        cap[name] = cap.get(name, 0.0) + s
        return
    st = getattr(_TLS, "stages", None)
    if st is not None:
        st[name] = st.get(name, 0.0) + s


@contextmanager
def stage(name: str):
    """Time a block into stage `name` (no-op when nobody is recording)."""
    if not stage_active():
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        add_stage(name, time.perf_counter() - t0)


@contextmanager
def capture_stages():
    """Collect stage() recordings into a plain dict regardless of the
    request recorder — the micro-batch leader times gate/decode/device/
    readback ONCE per coalesced chunk and stamps the dict onto every
    request it served."""
    prev = getattr(_TLS, "capture", None)
    cap: dict = {}
    _TLS.capture = cap
    try:
        yield cap
    finally:
        _TLS.capture = prev


def merge_stages(d):
    """Fold a stamped stage dict (micro-batch shared timings) into the
    calling thread's request recorder."""
    st = getattr(_TLS, "stages", None)
    if st is None or not d:
        return
    for k, v in d.items():
        st[k] = st.get(k, 0.0) + float(v)


def finish_request(wall=None):
    """Close the recorder: fold the un-attributed remainder of `wall`
    into `app`, feed the per-stage histograms, return the breakdown
    (None when nothing was recorded)."""
    st = getattr(_TLS, "stages", None)
    _TLS.stages = None
    if st is None:
        return None
    if wall is not None:
        rest = float(wall) - sum(st.values())
        if rest > 0.0:
            st["app"] = st.get("app", 0.0) + rest
    for k, v in st.items():
        STAGE_SECONDS.observe(v, stage=k)
    return st


def server_timing(stages: dict) -> str:
    """RFC Server-Timing header value: `name;dur=<ms>` entries in
    waterfall order."""
    order = {n: i for i, n in enumerate(STAGE_ORDER)}
    items = sorted(stages.items(),
                   key=lambda kv: (order.get(kv[0], len(order)), kv[0]))
    return ", ".join(f"{k};dur={v * 1e3:.3f}" for k, v in items)


# ---------------------------------------------------------------------------
# /3/Usage — the per-tenant/per-model cost table


def usage_snapshot() -> dict:
    """This host's attribution ledger + HBM occupancy (tier pager,
    ParamStore) — the `usage` collect op's payload."""
    from h2o3_tpu.obs import timeline as _tl
    with _LOCK:
        rows = [{"principal": p, "model": m, "kind": k,
                 "device_seconds": round(e[0], 6), "calls": e[1],
                 "rows": e[2]}
                for (p, m, k), e in sorted(_LEDGER.items())]
        total = _TOTAL[0]
    hbm: dict = {}
    try:
        from h2o3_tpu.serving.params import PARAMS
        hbm["params_by_model"] = PARAMS.by_model()
        hbm["params_total_bytes"] = PARAMS.total_bytes()
        hbm["params_tier_bytes"] = PARAMS.tier_bytes()
        hbm["params_serving"] = PARAMS.stats()
    except Exception:   # noqa: BLE001 — a probe error must not kill the snapshot
        pass
    try:
        from h2o3_tpu.core.tiering import PAGER
        hbm["tier"] = PAGER.stats()
    except Exception:   # noqa: BLE001
        pass
    return {"host": _tl.host_id(), "device_seconds_total": round(total, 6),
            "ledger": rows, "hbm": hbm}


def merge_usage(snaps) -> dict:
    """Cluster merge of usage_snapshot() payloads: ledger entries sum
    across hosts, HBM byte maps sum, per-host tier stats ride along."""
    agg: dict = {}
    hosts, tier_by_host = [], {}
    total = 0.0
    params_by_model: dict = {}
    params_total = 0
    params_tier: dict = {}
    for s in snaps:
        if not isinstance(s, dict):
            continue
        hosts.append(s.get("host"))
        total += float(s.get("device_seconds_total") or 0.0)
        for r in s.get("ledger") or []:
            k = (r.get("principal"), r.get("model"), r.get("kind"))
            e = agg.setdefault(k, [0.0, 0, 0])
            e[0] += float(r.get("device_seconds") or 0.0)
            e[1] += int(r.get("calls") or 0)
            e[2] += int(r.get("rows") or 0)
        hb = s.get("hbm") or {}
        for m, b in (hb.get("params_by_model") or {}).items():
            params_by_model[m] = params_by_model.get(m, 0) + int(b)
        params_total += int(hb.get("params_total_bytes") or 0)
        for t, b in (hb.get("params_tier_bytes") or {}).items():
            params_tier[t] = params_tier.get(t, 0) + int(b)
        if hb.get("tier") is not None:
            tier_by_host[str(s.get("host"))] = hb["tier"]
    ledger = [{"principal": p, "model": m, "kind": k,
               "device_seconds": round(e[0], 6), "calls": e[1],
               "rows": e[2]}
              for (p, m, k), e in agg.items()]
    ledger.sort(key=lambda r: -r["device_seconds"])
    return {"hosts": hosts, "device_seconds_total": round(total, 6),
            "ledger": ledger,
            "hbm": {"params_by_model": params_by_model,
                    "params_total_bytes": params_total,
                    "params_tier_bytes": params_tier,
                    "tier_by_host": tier_by_host}}


# ---------------------------------------------------------------------------
# /3/CloudHealth — the pressure model


def _pressure_series():
    """h2o3_pressure{dimension} gauge callback: reads ONLY the cached
    last evaluation (the registry lock forbids subsystem locks here)."""
    doc = _LAST_PRESSURE
    dims = doc.get("dimensions") or {}
    out = [({"dimension": k}, float(v)) for k, v in sorted(dims.items())]
    if "overall" in doc:
        out.append(({"dimension": "overall"}, float(doc["overall"])))
    return out


PRESSURE = _om.gauge(
    "h2o3_pressure",
    "synthesized capacity pressure per dimension (1.0 = saturated): "
    "slo_burn, queue, utilization, tier_occupancy, tier_faults, stalls, "
    "drift, and the overall max — refreshed by GET /3/CloudHealth "
    "evaluations",
    fn=_pressure_series)


def evaluate_pressure(window_s=None) -> dict:
    """Compute this host's pressure document and cache it for the
    h2o3_pressure gauges. Every dimension is normalized so 1.0 means
    saturated (HPA external-metric shape: scale out when overall
    approaches 1)."""
    global _LAST_PRESSURE
    window = _rate_window_s() if window_s is None else float(window_s)
    dims: dict = {}
    detail: dict = {}
    # queue: global depth against the micro-batch bound, and the worst
    # tenant against its share cap; the fair gate's waiter count rides
    # the detail for the autoscaler's drain decision
    try:
        from h2o3_tpu.serving import microbatch as _mb
        from h2o3_tpu.serving import qos as _qos
        limit = _mb._queue_depth_limit()
        queued = _mb.BATCHER.queued_by_principal()
        depth = _mb.BATCHER._depth
        share_cap = _qos.tenant_share_cap(limit)
        q = depth / limit if limit > 0 else 0.0
        if share_cap > 0:
            for held in queued.values():
                q = max(q, held / share_cap)
        dims["queue"] = round(q, 4)
        detail["queue"] = {"depth": depth, "limit": limit,
                           "by_principal": queued,
                           "share_cap": share_cap,
                           "gate_depth": _qos.GATE.depth()}
    except Exception:   # noqa: BLE001 — a probe error zeroes one dimension
        pass
    # utilization: device-seconds accumulation rate over wall, per chip
    rate = device_rate(window)
    ndev = _device_count()
    dims["utilization"] = round(rate / ndev, 4)
    detail["device"] = {"device_seconds_rate": round(rate, 6),
                        "devices": ndev,
                        "device_seconds_total":
                            round(device_seconds_total(), 6),
                        "window_s": window}
    # SLO burn: fresh evaluation (like GET /3/Alerts), normalized so 1.0
    # is the fast-burn paging threshold
    try:
        from h2o3_tpu.obs import slo as _slo
        alerts = _slo.ENGINE.evaluate()
        max_burn = max((b for a in alerts
                        for b in (a.get("burn") or {}).values()),
                       default=0.0)
        dims["slo_burn"] = round(max_burn / _SLO_PAGE_BURN, 4)
        detail["slo"] = {"max_burn": round(max_burn, 4),
                         "firing": [a["slo"] for a in alerts
                                    if a.get("firing")]}
    except Exception:   # noqa: BLE001
        pass
    # tier pager: HBM budget occupancy + fault rate since the previous
    # evaluation
    try:
        from h2o3_tpu.core import tiering as _tiering
        stats = _tiering.PAGER.stats()
        tb = stats.get("tier_bytes") or {}
        hbm_budget = stats.get("hbm_budget") or 0
        hbm_bytes = max((v for k, v in tb.items()
                         if "hbm" in str(k).lower()
                         or "device" in str(k).lower()), default=0)
        dims["tier_occupancy"] = \
            round(hbm_bytes / hbm_budget, 4) if hbm_budget else 0.0
        now_m = time.monotonic()
        faults = float(stats.get("faults") or 0)
        with _LOCK:
            prev = _TIER_PREV[0]
            if prev is None:
                _TIER_PREV[0] = (now_m, faults)
            elif now_m - prev[0] >= _TIER_MIN_INTERVAL_S:
                _TIER_RATE[0] = max(0.0, (faults - prev[1])
                                    / (now_m - prev[0]))
                _TIER_PREV[0] = (now_m, faults)
            # a sub-floor re-evaluation reuses the last full-interval rate
            fault_rate = _TIER_RATE[0]
        dims["tier_faults"] = round(fault_rate / _TIER_FAULT_SATURATION, 4)
        detail["tier"] = {"stats": stats,
                          "fault_rate": round(fault_rate, 4)}
    except Exception:   # noqa: BLE001
        pass
    # watchdog: any currently-stalled operation saturates the dimension
    try:
        from h2o3_tpu.obs import watchdog as _wd
        stalled = _wd.WATCHDOG.stalled()
        dims["stalls"] = 1.0 if stalled else 0.0
        detail["stalls"] = {"stalled": stalled,
                            "trips": len(_wd.WATCHDOG.trips())}
    except Exception:   # noqa: BLE001
        pass
    # model drift: worst monitored model's PSI/prediction drift against
    # its training baseline, saturated at H2O3_MODELMON_PSI_SAT — a
    # drifting fleet is a capacity problem for the RETRAIN pipeline even
    # when serving latency looks healthy
    try:
        from h2o3_tpu.obs import modelmon as _mm
        _mm.evaluate()
        drift, ddetail = _mm.pressure()
        dims["drift"] = round(drift, 4)
        detail["drift"] = ddetail
    except Exception:   # noqa: BLE001
        pass
    epoch = 0
    try:
        from h2o3_tpu.deploy import membership as _mbr
        epoch = _mbr.MEMBERSHIP.epoch
    except Exception:   # noqa: BLE001
        pass
    from h2o3_tpu.obs import timeline as _tl
    doc = {"host": _tl.host_id(), "epoch": epoch,
           "overall": round(max(dims.values(), default=0.0), 4),
           "dimensions": dims, "detail": detail, "ts": time.time()}
    _LAST_PRESSURE = doc
    return doc


def merge_cloudhealth(snaps) -> dict:
    """Cluster merge of evaluate_pressure() documents: each dimension is
    the MAX across hosts (pressure is a weakest-link signal — one
    saturated host gates the cloud), per-host docs ride along."""
    docs = [s for s in snaps if isinstance(s, dict)]
    dims: dict = {}
    for d in docs:
        for k, v in (d.get("dimensions") or {}).items():
            dims[k] = max(dims.get(k, 0.0), float(v))
    return {"overall": round(max(dims.values(), default=0.0), 4),
            "dimensions": dims,
            "epoch": max((int(d.get("epoch") or 0) for d in docs),
                         default=0),
            "hosts": [{"host": d.get("host"),
                       "overall": d.get("overall", 0.0),
                       "dimensions": d.get("dimensions") or {},
                       "detail": d.get("detail") or {}} for d in docs]}


def last_pressure() -> dict:
    return _LAST_PRESSURE


def forget_model(key):
    """Model DELETE hygiene: drop the model's attribution state — ledger
    rows, the fold census slot, and every {model=…} series on the
    device-seconds counter — exactly once (the ISSUE-11 Gauge.remove
    discipline applied to usage). Idempotent; never raises."""
    k = str(key)[:128]
    try:
        with _LOCK:
            for lk in [lk for lk in _LEDGER if lk[1] == k]:
                del _LEDGER[lk]
            _KNOWN_MODELS.discard(k)
        for row in MODEL_DEVICE_SECONDS._json():
            lbl = row.get("labels") or {}
            if lbl.get("model") == k:
                MODEL_DEVICE_SECONDS.remove(**lbl)
    except Exception:   # noqa: BLE001 — hygiene must not fail the DKV op
        pass


def reset():
    """Test isolation: drop the ledger, rate samples, model census,
    cached pressure, and the calling thread's recorder state."""
    global _LAST_PRESSURE
    with _LOCK:
        _LEDGER.clear()
        _TOTAL[0] = 0.0
        _RATE.clear()
        _KNOWN_MODELS.clear()
    _TIER_PREV[0] = None
    _TIER_RATE[0] = 0.0
    _LAST_PRESSURE = {}
    _TLS.stages = None
    _TLS.capture = None
    _TLS.metering = False
