"""Metrics registry — water/util/WaterMeter* rebuilt as a Prometheus-style
process registry.

Reference: WaterMeterCpuTicks.java / WaterMeterIo.java expose per-node
counters over REST for external scrapers; H2O has no first-class metric
types. Here the registry is explicit — counters, gauges and fixed-bucket
histograms with label support — because the TPU runtime's interesting
numbers (HBM in use, compile-cache hits, rows·trees/s) don't fall out of
/proc the way CPU ticks do.

Exposed at GET /metrics (text exposition format 0.0.4) and GET
/3/WaterMeter (JSON) by api/server.py. One registry per process; workers
in a multi-host cloud serve their own /metrics, and the span timeline (not
the registry) is what gets merged cloud-wide.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Callable, Optional

# Default latency buckets (seconds): sub-ms dispatches up to multi-minute
# jobs — one decade finer at the low end than Prometheus' defaults because
# device-program enqueues sit in the 0.1-10ms range.
DEFAULT_BUCKETS = (0.0001, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(key: tuple, extra: tuple = ()) -> str:
    items = list(key) + list(extra)
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in items) + "}"


def _fmt_num(v: float) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 2 ** 53 else repr(f)


class _Metric:
    kind = ""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: dict = {}

    def clear(self):
        with self._lock:
            self._series.clear()


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative increment")
        k = _label_key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(_label_key(labels), 0.0)

    def remove(self, **labels):
        """Drop one label series — the per-entity hygiene discipline
        (see Gauge.remove): a deleted model's counters must leave
        /metrics entirely, not linger as frozen series. Scrapers see a
        counter reset, which Prometheus-style rate() already handles."""
        with self._lock:
            self._series.pop(_label_key(labels), None)

    def _expose(self) -> list:
        with self._lock:
            items = sorted(self._series.items())
        return [f"{self.name}{_fmt_labels(k)} {_fmt_num(v)}"
                for k, v in items]

    def _json(self):
        with self._lock:
            return [{"labels": dict(k), "value": v}
                    for k, v in sorted(self._series.items())]


class Gauge(_Metric):
    """Settable gauge, or a callback gauge when `fn` is given: fn() returns
    a scalar or a {labels_dict: value}-style list of (labels, value) pairs,
    evaluated at scrape time (WaterMeter's read-on-request semantics)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 fn: Optional[Callable] = None):
        super().__init__(name, help)
        self._fn = fn

    def set(self, value: float, **labels):
        with self._lock:
            self._series[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels):
        k = _label_key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + amount

    def remove(self, **labels):
        """Drop one label series — for per-entity gauges (per-model HBM
        occupancy) whose entity was deleted: a freed model must leave
        /metrics entirely, not linger as a forever-zero series."""
        with self._lock:
            self._series.pop(_label_key(labels), None)

    def value(self, **labels) -> float:
        for k, v in self._collect():
            if k == _label_key(labels):
                return v
        return 0.0

    def _collect(self) -> list:
        if self._fn is not None:
            try:
                out = self._fn()
            except Exception:   # noqa: BLE001 — a dead probe must not 500 /metrics
                # the scrape stays alive (this gauge just emits no
                # series), but the failure is COUNTED — a silently dead
                # probe looks exactly like a healthy zero otherwise
                _note_collect_error(self.name)
                return []
            if isinstance(out, (int, float)):
                return [((), float(out))]
            return [(_label_key(dict(lbl)), float(v)) for lbl, v in out]
        with self._lock:
            return sorted(self._series.items())

    def _expose(self) -> list:
        return [f"{self.name}{_fmt_labels(k)} {_fmt_num(v)}"
                for k, v in self._collect()]

    def _json(self):
        return [{"labels": dict(k), "value": v} for k, v in self._collect()]


class Histogram(_Metric):
    """Fixed-bucket cumulative histogram (Prometheus semantics: _bucket
    series are cumulative counts with a +Inf catch-all, plus _sum/_count)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "", buckets=None):
        super().__init__(name, help)
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))

    def observe(self, value: float, exemplar: Optional[str] = None,
                **labels):
        """Record one observation. `exemplar` is NOT a label: it is an
        OpenMetrics exemplar — typically the observing request's trace id
        — remembered per bucket and emitted by openmetrics_text() so a
        latency spike on a dashboard clicks through to a stored trace."""
        k = _label_key(labels)
        v = float(value)
        with self._lock:
            st = self._series.get(k)
            if st is None:
                st = self._series[k] = {
                    "counts": [0] * (len(self.buckets) + 1),
                    "sum": 0.0, "count": 0}
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    st["counts"][i] += 1
                    break
            else:
                i = len(self.buckets)
                st["counts"][-1] += 1
            st["sum"] += v
            st["count"] += 1
            if exemplar:
                # last-write-wins per bucket: the freshest exemplar is
                # the most likely to still be in the flight recorder
                st.setdefault("exemplars", {})[i] = (
                    str(exemplar), v, _time.time())

    def time(self, **labels):
        """Context manager: observe the block's wall time in seconds."""
        import contextlib
        import time as _time

        @contextlib.contextmanager
        def _cm():
            t0 = _time.perf_counter()
            try:
                yield
            finally:
                self.observe(_time.perf_counter() - t0, **labels)
        return _cm()

    def snapshot(self, **labels) -> dict:
        with self._lock:
            st = self._series.get(_label_key(labels))
            if st is None:
                return {"sum": 0.0, "count": 0,
                        "counts": [0] * (len(self.buckets) + 1)}
            return {"sum": st["sum"], "count": st["count"],
                    "counts": list(st["counts"])}

    def series_snapshots(self) -> list:
        """[(labels_dict, {"sum","count","counts"})] for every live
        series — the SLO engine's window sampler walks this."""
        with self._lock:
            return [(dict(k), {"sum": s["sum"], "count": s["count"],
                               "counts": list(s["counts"])})
                    for k, s in sorted(self._series.items())]

    def _expose(self, exemplars: bool = False) -> list:
        """Cumulative-bucket text exposition; with `exemplars` (the
        OpenMetrics renderer) each bucket a stored exemplar covers gets
        `... # {trace_id="<id>"} <value> <unix_ts>` appended."""
        with self._lock:
            items = sorted((k, {"counts": list(s["counts"]),
                                "sum": s["sum"], "count": s["count"],
                                "ex": dict(s.get("exemplars") or {})
                                if exemplars else {}})
                           for k, s in self._series.items())
        lines = []
        for k, st in items:
            cum = 0
            bounds = [(_fmt_num(ub), c)
                      for ub, c in zip(self.buckets, st["counts"])]
            bounds.append(("+Inf", st["counts"][-1]))
            for i, (le, c) in enumerate(bounds):
                cum += c
                line = (f"{self.name}_bucket"
                        f"{_fmt_labels(k, (('le', le),))} {cum}")
                ex = st["ex"].get(i)
                if ex is not None:
                    tid, v, ts = ex
                    line += (f' # {{trace_id="{_escape(tid)}"}} '
                             f"{_fmt_num(v)} {ts:.3f}")
                lines.append(line)
            lines.append(f"{self.name}_sum{_fmt_labels(k)}"
                         f" {_fmt_num(st['sum'])}")
            lines.append(f"{self.name}_count{_fmt_labels(k)} {st['count']}")
        return lines

    def _json(self):
        bounds = [_fmt_num(b) for b in self.buckets] + ["+Inf"]
        with self._lock:
            out = []
            for k, s in sorted(self._series.items()):
                d = {"labels": dict(k), "sum": s["sum"],
                     "count": s["count"],
                     "buckets": dict(zip(bounds, s["counts"]))}
                ex = s.get("exemplars")
                if ex:
                    # exemplars ride the JSON snapshot so the CLUSTER
                    # merge can re-emit them host-tagged (the ISSUE-7
                    # gap: the federated scrape used to strip them)
                    d["exemplars"] = [
                        {"le": bounds[i], "trace_id": tid,
                         "value": v, "ts": ts}
                        for i, (tid, v, ts) in sorted(ex.items())]
                out.append(d)
            return out


class MetricsRegistry:
    def __init__(self):
        # lockdep-instrumented (lock class "metrics.registry"): the
        # registry nests under every subsystem that declares or scrapes.
        # Local import — lockdep's own counters import THIS module, so a
        # top-level import would cycle; per-series _Metric._lock objects
        # stay plain threading.Lock (leaf locks on the counter hot path).
        from h2o3_tpu.analysis.lockdep import make_lock
        self._lock = make_lock("metrics.registry")
        self._metrics: dict[str, _Metric] = {}

    def _get_or_make(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise TypeError(f"metric {name!r} already registered "
                                    f"as {m.kind}")
                return m
            m = cls(name, help, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_make(Counter, name, help)

    def gauge(self, name: str, help: str = "",
              fn: Optional[Callable] = None) -> Gauge:
        return self._get_or_make(Gauge, name, help, fn=fn)

    def histogram(self, name: str, help: str = "",
                  buckets=None) -> Histogram:
        return self._get_or_make(Histogram, name, help, buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name: str):
        with self._lock:
            self._metrics.pop(name, None)

    def metrics(self) -> list:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    # ---- exposition -----------------------------------------------------
    def prometheus_text(self) -> str:
        """Text exposition format 0.0.4 (the GET /metrics body)."""
        out = []
        for m in self.metrics():
            out.append(f"# HELP {m.name} {_escape(m.help)}")
            out.append(f"# TYPE {m.name} {m.kind}")
            out.extend(m._expose())
        return "\n".join(out) + "\n"

    def openmetrics_text(self) -> str:
        """OpenMetrics 1.0 exposition — what Prometheus negotiates (via
        Accept) when --enable-feature=exemplar-storage wants exemplars.
        Differences from 0.0.4 that matter here: counter families drop
        the _total suffix in metadata (samples keep it), histogram
        _bucket samples may carry `# {trace_id="..."} value ts`
        exemplars, and the body terminates with `# EOF`."""
        out = []
        for m in self.metrics():
            family = m.name
            if m.kind == "counter" and family.endswith("_total"):
                family = family[: -len("_total")]
            out.append(f"# HELP {family} {_escape(m.help)}")
            out.append(f"# TYPE {family} {m.kind}")
            if isinstance(m, Histogram):
                out.extend(m._expose(exemplars=True))
            else:
                out.extend(m._expose())
        out.append("# EOF")
        return "\n".join(out) + "\n"

    def to_dict(self) -> dict:
        """JSON exposition (the GET /3/WaterMeter body)."""
        return {m.name: {"kind": m.kind, "help": m.help,
                         "series": m._json()}
                for m in self.metrics()}


REGISTRY = MetricsRegistry()

COLLECT_ERRORS = REGISTRY.counter(
    "h2o3_metric_collect_errors_total",
    "gauge callback exceptions swallowed during a scrape (the scrape "
    "stays alive; the failing gauge emits no series)")


def _note_collect_error(gauge_name: str):
    """Count a gauge callback exception (Gauge._collect swallowed it so
    the scrape survives). A function, not an inline emit: Gauge is
    defined before the module-level REGISTRY/COLLECT_ERRORS exist."""
    COLLECT_ERRORS.inc(metric=gauge_name)


def counter(name: str, help: str = "") -> Counter:
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "", fn: Optional[Callable] = None) -> Gauge:
    return REGISTRY.gauge(name, help, fn=fn)


def histogram(name: str, help: str = "", buckets=None) -> Histogram:
    return REGISTRY.histogram(name, help, buckets=buckets)


# ---------------------------------------------------------------------------
# Cluster metrics federation (ISSUE 5). Workers ship REGISTRY.to_dict()
# snapshots over the replay channel (deploy/multihost._collect_local); the
# coordinator merges them here with a per-host `host=` label. Counters and
# histograms stay summable downstream (Prometheus `sum without (host)`);
# gauges stay per-host by construction — HBM on host 2 is not HBM on
# host 0. A host that outwaits the collect deadline is simply absent from
# the merge, counted in h2o3_cluster_scrape_timeouts_total by the caller.
CLUSTER_SCRAPE_TIMEOUTS = REGISTRY.counter(
    "h2o3_cluster_scrape_timeouts_total",
    "hosts absent from a cluster-scope metrics scrape — they outwaited "
    "the collect deadline (H2O3_OBS_COLLECT_TIMEOUT_S) or answered with "
    "an error; their series are missing from that merge")


def merge_cluster_snapshots(snapshots: list) -> dict:
    """[(host, REGISTRY.to_dict()-shaped dict)] → one merged dict of the
    same shape, every series labeled host=<id>. Kind/help come from the
    first host that declares the metric (hosts run the same code, so
    drift here would be a deploy skew, not a merge concern)."""
    merged: dict = {}
    for host, snap in snapshots:
        for name, m in (snap or {}).items():
            dst = merged.setdefault(name, {"kind": m.get("kind", "gauge"),
                                           "help": m.get("help", ""),
                                           "series": []})
            for s in m.get("series") or []:
                s2 = dict(s)
                s2["labels"] = dict(s.get("labels") or {}, host=str(host))
                if s.get("exemplars"):
                    # host-tag each exemplar too: the trace id resolves
                    # at GET /3/Trace/{id} on the coordinator either
                    # way, but Grafana shows WHICH host observed it
                    s2["exemplars"] = [dict(e, host=str(host))
                                       for e in s["exemplars"]]
                dst["series"].append(s2)
    return merged


def _exemplar_suffix(exemplars: list, le: str) -> str:
    """OpenMetrics exemplar suffix for one merged bucket line, or ""."""
    for e in exemplars or ():
        if e.get("le") == le and e.get("trace_id"):
            lbls = f'trace_id="{_escape(str(e["trace_id"]))}"'
            if e.get("host") is not None:
                lbls += f',host="{_escape(str(e["host"]))}"'
            return (f" # {{{lbls}}} {_fmt_num(e.get('value', 0.0))}"
                    f" {float(e.get('ts', 0.0)):.3f}")
    return ""


def _render_series(name: str, kind: str, series: list,
                   exemplars: bool = False) -> list:
    """Exposition lines for one metric's merged JSON series (the
    registry's _expose over live objects, re-done over snapshots that
    crossed the wire as JSON). With `exemplars` (the cluster OpenMetrics
    renderer) histogram bucket lines re-emit the host-tagged exemplars
    the snapshots carried."""
    lines = []
    for s in series:
        key = _label_key(s.get("labels") or {})
        ex = s.get("exemplars") if exemplars else None
        if kind == "histogram":
            buckets = s.get("buckets") or {}
            cum = 0
            for ub, c in buckets.items():
                if ub == "+Inf":
                    continue
                cum += int(c)
                lines.append(f"{name}_bucket"
                             f"{_fmt_labels(key, (('le', ub),))} {cum}"
                             + _exemplar_suffix(ex, ub))
            cum += int(buckets.get("+Inf", 0))
            lines.append(f"{name}_bucket"
                         f"{_fmt_labels(key, (('le', '+Inf'),))} {cum}"
                         + _exemplar_suffix(ex, "+Inf"))
            lines.append(f"{name}_sum{_fmt_labels(key)}"
                         f" {_fmt_num(s.get('sum', 0.0))}")
            lines.append(f"{name}_count{_fmt_labels(key)}"
                         f" {int(s.get('count', 0))}")
        else:
            lines.append(f"{name}{_fmt_labels(key)}"
                         f" {_fmt_num(s.get('value', 0.0))}")
    return lines


def cluster_prometheus_text(snapshots: list) -> str:
    """Text exposition 0.0.4 of the merged cluster view (the
    GET /metrics?scope=cluster body)."""
    merged = merge_cluster_snapshots(snapshots)
    out = []
    for name in sorted(merged):
        m = merged[name]
        out.append(f"# HELP {name} {_escape(m['help'])}")
        out.append(f"# TYPE {name} {m['kind']}")
        out.extend(_render_series(name, m["kind"], m["series"]))
    return "\n".join(out) + "\n"


def cluster_openmetrics_text(snapshots: list) -> str:
    """OpenMetrics 1.0 exposition of the merged cluster view — the
    GET /metrics?scope=cluster body when the scraper negotiates
    OpenMetrics: same merge as cluster_prometheus_text, but histogram
    buckets keep their (host-tagged) exemplars so Grafana click-through
    works on the federated scrape too."""
    merged = merge_cluster_snapshots(snapshots)
    out = []
    for name in sorted(merged):
        m = merged[name]
        family = name
        if m["kind"] == "counter" and family.endswith("_total"):
            family = family[: -len("_total")]
        out.append(f"# HELP {family} {_escape(m['help'])}")
        out.append(f"# TYPE {family} {m['kind']}")
        out.extend(_render_series(name, m["kind"], m["series"],
                                  exemplars=True))
    out.append("# EOF")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# Runtime gauges: JAX device memory, DKV census, XLA compile cache.
def _device_memory_series():
    import jax
    out = []
    for d in jax.local_devices():
        stats = d.memory_stats() if hasattr(d, "memory_stats") else None
        if not stats:
            continue
        lbl = {"device": str(d.id)}
        if "bytes_in_use" in stats:
            out.append((dict(lbl, kind="bytes_in_use"),
                        stats["bytes_in_use"]))
        if "peak_bytes_in_use" in stats:
            out.append((dict(lbl, kind="peak_bytes_in_use"),
                        stats["peak_bytes_in_use"]))
        if "bytes_limit" in stats:
            out.append((dict(lbl, kind="bytes_limit"),
                        stats["bytes_limit"]))
    return out


def _dkv_series():
    from h2o3_tpu.core.kvstore import DKV
    st = DKV.stats()
    return [({"what": "keys"}, st["keys"]),
            ({"what": "frames"}, st["frames"]),
            ({"what": "frame_bytes"}, st["frame_bytes"]),
            ({"what": "write_locked"}, st["write_locked"])]


_JAX_LISTENERS_INSTALLED = False


def _install_jax_listeners():
    """Count XLA compile-cache traffic via jax.monitoring events. Safe to
    call before the backend initializes (listener registration imports jax
    but touches no devices)."""
    global _JAX_LISTENERS_INSTALLED
    if _JAX_LISTENERS_INSTALLED:
        return
    _JAX_LISTENERS_INSTALLED = True
    try:
        import jax.monitoring as _mon
    except Exception:   # noqa: BLE001 — no jax, no compile metrics
        return
    hits = counter("h2o3_xla_compile_cache_hits_total",
                   "persistent XLA compilation cache hits")
    misses = counter("h2o3_xla_compile_cache_misses_total",
                     "persistent XLA compilation cache misses")
    compiles = counter("h2o3_xla_compiles_total",
                       "XLA backend compilations in this process (every "
                       "new program x shape signature costs one)")
    compile_secs = counter("h2o3_xla_compile_seconds_total",
                           "cumulative wall time spent in XLA backend "
                           "compilation")

    def _on_event(event: str, **kw):
        if event == "/jax/compilation_cache/cache_hits":
            hits.inc()
        elif event == "/jax/compilation_cache/cache_misses":
            misses.inc()

    def _on_duration(event: str, duration: float, **kw):
        if event == "/jax/core/compile/backend_compile_duration":
            compiles.inc()
            compile_secs.inc(max(duration, 0.0))

    try:
        _mon.register_event_listener(_on_event)
        _mon.register_event_duration_secs_listener(_on_duration)
    except Exception:   # noqa: BLE001
        pass


def xla_compile_count() -> float:
    """Current process-wide XLA backend-compile count — the serving fast
    path's regression metric (tests assert a warm bucket adds zero).
    Reads via get(): counter() here would be a second declaration site
    for the name (R005), racing the listener's help text."""
    m = REGISTRY.get("h2o3_xla_compiles_total")
    return m.value() if m is not None else 0.0


_BUILD_INFO = None


def _build_info_series():
    """h2o3_build_info callback: the identity labels are immutable for
    the process lifetime, so they resolve once (lazily — at the first
    scrape, never at import, where jax may still be initializing)."""
    global _BUILD_INFO
    if _BUILD_INFO is None:
        import h2o3_tpu as _pkg
        try:
            import jax as _jax
            backend = str(_jax.default_backend())
            jaxv = str(getattr(_jax, "__version__", "unknown"))
        except Exception:   # noqa: BLE001 — chip-less container: still expose
            backend, jaxv = "none", "none"
        _BUILD_INFO = ({"version": str(getattr(_pkg, "__version__", "0")),
                        "backend": backend, "jax": jaxv}, 1.0)
    return [_BUILD_INFO]


def install_runtime_gauges():
    """Register the default runtime gauges (idempotent; called by the API
    server at start and by /metrics scrapes)."""
    gauge("h2o3_device_memory_bytes",
          "JAX per-device HBM usage from device.memory_stats()",
          fn=_device_memory_series)
    gauge("h2o3_dkv_objects",
          "DKV registry census: live keys, frames, frame bytes",
          fn=_dkv_series)
    gauge("h2o3_build_info",
          "build/runtime identity info-gauge (value always 1): package "
          "version, JAX backend and jax version — correlates dashboards "
          "and bench trajectories across container/backend changes",
          fn=_build_info_series)
    # the usage ledger's pressure/attribution metrics register at its
    # import; pulling it in here makes them scrapeable even when the
    # serving path was never touched (bench, notebooks)
    try:
        from h2o3_tpu.obs import usage  # noqa: F401
    except ImportError:
        pass
    _install_jax_listeners()


# Registered at import: the registry must answer a scrape even if the
# server never called install explicitly (bench.py, tests, notebooks).
install_runtime_gauges()
