"""Shared append-only segment-directory discipline.

Both durable observability tiers — the flight recorder's trace segments
(obs/recorder.py) and the structured log's JSONL segments (utils/log.py)
— follow the same rules over a directory under the ice root:

  * per-process file names (writers sharing an ice root never clobber);
  * append-only JSON lines, crash-safe (a torn trailing line from a
    crashed writer is skipped on read);
  * size-triggered roll + oldest-first GC against a byte budget, where
    GC may delete OTHER processes' files — so every writer must detect
    its open segment being unlinked out from under it and roll;
  * readers scan the WHOLE directory (any process, including a fresh
    one after a restart, can read a dead one's segments).

The subtle pieces live here exactly once so the two tiers cannot drift:
the overlayfs-safe liveness check, the listing order, the GC sweep, and
the torn-line-tolerant JSONL iterator.
"""

from __future__ import annotations

import json
import os


def alive(path, fh) -> bool:
    """True while `path` still names the open file `fh` — checked by
    PATH + inode, not fstat st_nlink: overlayfs (the usual container
    fs) keeps nlink at 1 on an fd whose upper-layer file was unlinked.
    False means another process's GC deleted the segment: appends would
    land in a dead inode invisible to every reader — roll immediately."""
    if path is None or fh is None:
        return False
    try:
        return os.stat(path).st_ino == os.fstat(fh.fileno()).st_ino
    except OSError:
        return False


def list_segments(d: str, suffix: str = ".jsonl") -> list:
    """(mtime, path, size) for every segment under `d`, oldest first
    (mtime, then name for stability) — every process's files."""
    try:
        names = [n for n in os.listdir(d) if n.endswith(suffix)]
    except OSError:
        return []
    out = []
    for n in names:
        p = os.path.join(d, n)
        try:
            st = os.stat(p)
        except OSError:
            continue
        out.append((st.st_mtime, p, st.st_size))
    out.sort()
    return out


def gc(d: str, budget: int, keep_path=None, suffix: str = ".jsonl"):
    """Delete oldest segments first until the directory fits `budget`
    bytes. `keep_path` (the caller's ACTIVE segment) is never deleted;
    undeletable files (perms/ro-fs) still count — their bytes are on
    disk either way. Racing GCs are fine: a FileNotFoundError means the
    other one won."""
    segs = list_segments(d, suffix)
    total = sum(sz for _, _, sz in segs)
    for _, p, sz in segs:
        if total <= budget:
            break
        if p == keep_path:
            continue
        try:
            os.unlink(p)
        except FileNotFoundError:
            pass
        except OSError:
            continue
        total -= sz


def iter_jsonl(segs: list, newest_first: bool = True,
               contains: str | None = None):
    """Yield parsed JSON objects from (mtime, path, size) segments,
    tolerating torn trailing lines (a crashed writer's last append).
    `contains` prefilters raw lines by substring before the (much
    costlier) JSON parse — exact for ids that appear literally in the
    line."""
    if newest_first:
        segs = list(reversed(segs))
    for _, p, _sz in segs:
        try:
            with open(p, encoding="utf-8") as fh:
                lines = fh.readlines()
        except OSError:
            continue
        if newest_first:
            lines = reversed(lines)
        for line in lines:
            if contains is not None and contains not in line:
                continue
            try:
                yield json.loads(line)
            except (json.JSONDecodeError, ValueError):
                continue        # torn append from a crashed writer
