"""Model & data drift observability (ISSUE 20).

The obs stack watches the SYSTEM (latency, pressure, usage); nothing
watches the statistical behavior of the models themselves — the signal
the streaming/hot-swap roadmap item needs ("refresh when, rollback
why"). This module closes that loop natively, because every prediction
already funnels through `serving/scorer_cache.score_rows`:

  * **Baseline profile** — at train time every `_serving_params` family
    stamps a per-feature mergeable sketch of its TRAINING distribution
    into the model: fixed-bin histograms over quantile edges for
    numerics (the binner's global-quantile discipline, `tree/binned.py
    make_bins`), top-K + other for categoricals, NA rates, plus the
    prediction distribution. One host-side pass over the staged raw
    columns; stored in DKV beside the model (npz-serializable, rides
    re-home like any plane).
  * **Streaming live sketches** — a low-overhead tap in `score_rows`
    folds each scored batch into a per-(model, generation) sketch of
    the SAME shape, host-side on the already-staged decode buffer:
    zero extra device work. Integer counts make the merge associative
    and commutative by construction, so cluster merge order can never
    change a drift score bit-for-bit.
  * **Drift evaluation** — a background evaluator computes PSI per
    feature and Jensen-Shannon divergence for the prediction
    distribution, exported as `h2o3_model_drift{model,feature_kind}` /
    `h2o3_model_prediction_drift{model}` gauges +
    `h2o3_model_scored_rows_total{model}`.
  * **Generation shadow-compare** — a retrain over the same key
    retains the previous generation's live sketch; traffic still
    scoring the OLD model object (per-object scorer tokens) keeps
    folding into it, and `h2o3_model_generation_skew{model}` compares
    the two generations' prediction distributions — the rollback
    signal.
  * `GET /3/ModelMonitor/{model}` merges every host's sketches over
    the `modelmon:` collect op; the SLO engine's `drift` SLI kind and
    the /3/CloudHealth `drift` pressure dimension read the gauges.

Cardinality rides the ISSUE-16/17 fold discipline: at most
H2O3_MODELMON_MAX_MODELS models are monitored; later trains are
skipped (counted), never unbounded label churn. All per-model series
are removed exactly once on model DELETE (`forget`).

Env surface:
  H2O3_MODELMON            master switch (default on)
  H2O3_MODELMON_BINS       numeric histogram bins (default 20)
  H2O3_MODELMON_TOPK       categorical top-K levels (default 32)
  H2O3_MODELMON_SAMPLE     max training rows for quantile edges
                           (default 65536)
  H2O3_MODELMON_EVAL_S     background drift evaluation period
                           (default 30; 0 = evaluate only on demand)
  H2O3_MODELMON_MAX_MODELS monitored-model cardinality cap (default 64)
  H2O3_MODELMON_PSI_SAT    PSI score treated as saturated pressure
                           (default 0.5)
"""

from __future__ import annotations

import io
import threading
import time

import numpy as np

from h2o3_tpu.analysis.lockdep import make_lock
from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.utils.env import env_bool, env_float, env_int

# ---------------------------------------------------------------------------
# metrics (one declaration site each — R005)

DRIFT = _om.gauge(
    "h2o3_model_drift",
    "population-stability-index drift of live scoring traffic against "
    "the model's training baseline, per model and feature kind "
    "(numeric|categorical|na): the worst feature of that kind")
PRED_DRIFT = _om.gauge(
    "h2o3_model_prediction_drift",
    "Jensen-Shannon divergence between the live prediction "
    "distribution and the training-time prediction distribution")
GEN_SKEW = _om.gauge(
    "h2o3_model_generation_skew",
    "Jensen-Shannon divergence between the current generation's live "
    "prediction distribution and the PREVIOUS generation's (retained "
    "across a retrain/hot-swap) — the rollback signal")
SCORED = _om.counter(
    "h2o3_model_scored_rows_total",
    "rows seen by the model's serving drift tap (batches deferred by "
    "the duty-cycle throttle count too; the live sketch holds the "
    "folded sample)")
SKIPPED = _om.counter(
    "h2o3_modelmon_skipped_models_total",
    "trained models NOT monitored because the "
    "H2O3_MODELMON_MAX_MODELS cardinality cap was reached")

_LOCK = make_lock("modelmon")
_TLS = threading.local()        # .suppress: tap off for baseline scoring
_STATE: dict = {}               # model key -> _ModelState
_OVERRIDE = [None]              # set_enabled override (None = env)
_EVAL_THREAD = [None]
_LAST_EVAL: dict = {}           # model key -> last drift document

_KINDS = ("numeric", "categorical", "na")
_LAPLACE = 0.5                  # add-half count smoothing: an empty bin
                                # must not blow PSI up at small samples


# ---------------------------------------------------------------------------
# env surface


def _env_enabled() -> bool:
    return env_bool("H2O3_MODELMON", True)


def enabled() -> bool:
    ov = _OVERRIDE[0]
    return _env_enabled() if ov is None else bool(ov)


def set_enabled(on):
    """Override the H2O3_MODELMON switch from code (None restores the
    env reading) — the bench's monitor on/off A-B loop."""
    _OVERRIDE[0] = on


def _n_bins() -> int:
    # 10 equal-population bins is the standard PSI discipline — small
    # live samples stay quiet in-distribution, real shift still screams
    return max(2, env_int("H2O3_MODELMON_BINS", 10))


def _top_k() -> int:
    return max(1, env_int("H2O3_MODELMON_TOPK", 32))


def _sample_rows() -> int:
    return max(256, env_int("H2O3_MODELMON_SAMPLE", 65536))


def _tap_rows() -> int:
    # per-fold row cap: a serving batch bigger than this is stride-
    # sampled before folding, so one fold's cost stays bounded no
    # matter how large the micro-batches coalesce. Deterministic
    # (every step-th row), and drift statistics don't need every row —
    # 512 per batch converges the same PSI within noise. 0 disables
    # the cap (fold everything).
    return env_int("H2O3_MODELMON_TAP_ROWS", 512)


def _tap_pct() -> float:
    # duty-cycle budget for the tap, percent of serving wall time: each
    # fold is timed, and the next fold is deferred until the fold's own
    # duration amortizes below this fraction (0.4ms fold at 0.5% ->
    # ~80ms gap). Overhead is bounded BY CONSTRUCTION instead of hoping
    # per-batch numpy stays cheap; skipped batches still count into
    # h2o3_model_scored_rows_total. >=100 folds every batch (tests);
    # <=0 disables the tap's folding entirely.
    return env_float("H2O3_MODELMON_TAP_PCT", 0.5)


def _eval_period_s() -> float:
    return env_float("H2O3_MODELMON_EVAL_S", 30.0)


def _max_models() -> int:
    return env_int("H2O3_MODELMON_MAX_MODELS", 64)


def _psi_saturation() -> float:
    return env_float("H2O3_MODELMON_PSI_SAT", 0.5)


def monitor_key(model_key: str) -> str:
    """DKV key of the model's baseline profile (beside the params)."""
    return f"{model_key}__modelmon_baseline"


# ---------------------------------------------------------------------------
# divergence math — pure float64 over summed int64 counts, so a merge
# in ANY order (associative/commutative integer addition) yields the
# identical score bit-for-bit


def _proportions(counts: np.ndarray) -> np.ndarray:
    c = np.asarray(counts, np.float64)
    total = float(c.sum())
    k = len(c)
    return (c + _LAPLACE) / (total + _LAPLACE * k)


def psi(base_counts, live_counts) -> float:
    """Population stability index between two count vectors."""
    live = np.asarray(live_counts, np.float64)
    if float(live.sum()) <= 0.0:
        return 0.0
    p = _proportions(base_counts)
    q = _proportions(live_counts)
    return float(np.sum((q - p) * np.log(q / p)))


def js_divergence(p_counts, q_counts) -> float:
    """Jensen-Shannon divergence (natural log, in [0, ln 2])."""
    pc = np.asarray(p_counts, np.float64)
    qc = np.asarray(q_counts, np.float64)
    if float(pc.sum()) <= 0.0 or float(qc.sum()) <= 0.0:
        return 0.0
    p = _proportions(pc)
    q = _proportions(qc)
    m = 0.5 * (p + q)
    return float(0.5 * np.sum(p * np.log(p / m))
                 + 0.5 * np.sum(q * np.log(q / m)))


# ---------------------------------------------------------------------------
# baseline profile


class BaselineProfile:
    """Training-time distribution profile: per-feature binning spec +
    baseline counts + the prediction distribution. Mergeable shape —
    live sketches bin against the SAME edges/slots, so baseline vs live
    is a straight count comparison. Deterministic (no wall clock, no
    host id): the profile is DKV-replicated state and must be
    bit-identical on every host (the R019 divergence contract)."""

    def __init__(self, features, counts, na, pred_kind, pred_edges,
                 pred_counts, resp_counts=None, n_rows=0):
        # features: [{"name", "kind", "edges"|("codes","levels","card")}]
        self.features = features
        self.counts = counts            # list of int64 arrays
        self.na = na                    # int64 array, one per feature
        self.pred_kind = pred_kind      # "class" | "reg" | "none"
        self.pred_edges = pred_edges    # f64 array for "reg", else None
        self.pred_counts = pred_counts  # int64 array
        self.resp_counts = resp_counts  # int64 array or None
        self.n_rows = int(n_rows)

    def n_slots(self, j: int) -> int:
        return len(self.counts[j])

    # ---- npz wire form (rides DKV re-home / disk tiering) ---------------
    def to_npz_bytes(self) -> bytes:
        import json as _json
        arrs = {"na": self.na, "pred_counts": self.pred_counts,
                "meta": np.frombuffer(_json.dumps({
                    "features": [
                        {k: (v.tolist() if isinstance(v, np.ndarray)
                             else v) for k, v in f.items()}
                        for f in self.features],
                    "pred_kind": self.pred_kind,
                    "n_rows": self.n_rows,
                }).encode(), np.uint8)}
        if self.pred_edges is not None:
            arrs["pred_edges"] = self.pred_edges
        if self.resp_counts is not None:
            arrs["resp_counts"] = self.resp_counts
        for j, c in enumerate(self.counts):
            arrs[f"counts_{j}"] = c
        buf = io.BytesIO()
        np.savez(buf, **arrs)
        return buf.getvalue()

    @classmethod
    def from_npz_bytes(cls, data: bytes) -> "BaselineProfile":
        import json as _json
        with np.load(io.BytesIO(data), allow_pickle=False) as z:
            meta = _json.loads(bytes(z["meta"].tobytes()).decode())
            feats = []
            for f in meta["features"]:
                if "edges" in f:
                    f = dict(f, edges=np.asarray(f["edges"], np.float64))
                feats.append(f)
            counts = [np.asarray(z[f"counts_{j}"], np.int64)
                      for j in range(len(feats))]
            return cls(
                feats, counts, np.asarray(z["na"], np.int64),
                meta["pred_kind"],
                (np.asarray(z["pred_edges"], np.float64)
                 if "pred_edges" in z.files else None),
                np.asarray(z["pred_counts"], np.int64),
                (np.asarray(z["resp_counts"], np.int64)
                 if "resp_counts" in z.files else None),
                meta["n_rows"])

    @property
    def nbytes(self) -> int:
        return (sum(int(c.nbytes) for c in self.counts)
                + int(self.na.nbytes) + int(self.pred_counts.nbytes))


def _quantile_edges(col: np.ndarray, nbins: int) -> np.ndarray:
    """Global quantile cut points (the tree binner's make_bins shape):
    nbins-1 ascending edges; duplicate edges simply leave empty bins."""
    ok = col[np.isfinite(col)]
    if len(ok) == 0:
        return np.zeros(nbins - 1, np.float64)
    qs = np.arange(1, nbins, dtype=np.float64) / nbins
    return np.quantile(ok, qs).astype(np.float64)


def _bin_numeric(col: np.ndarray, edges: np.ndarray, nbins: int):
    """(counts[nbins], n_na) for one numeric column."""
    finite = np.isfinite(col)
    idx = np.searchsorted(edges, col[finite], side="right")
    return (np.bincount(idx, minlength=nbins).astype(np.int64),
            int(len(col) - int(finite.sum())))


def _cat_slots(card: int, codes: np.ndarray) -> np.ndarray:
    """code -> slot lookup: tracked top-K codes get 0..K-1, everything
    else folds into slot K ("other")."""
    lut = np.full(card + 1, len(codes), np.int64)
    lut[codes] = np.arange(len(codes), dtype=np.int64)
    return lut


def _bin_categorical(col: np.ndarray, lut: np.ndarray, nslots: int):
    finite = np.isfinite(col)
    codes = col[finite].astype(np.int64)
    # out-of-domain codes (adapted frames clamp, but stay defensive)
    codes = np.clip(codes, 0, len(lut) - 1)
    return (np.bincount(lut[codes], minlength=nslots).astype(np.int64),
            int(len(col) - int(finite.sum())))


def build_baseline(dinfo, raw: np.ndarray, preds, resp=None,
                   nbins=None, topk=None) -> BaselineProfile:
    """Profile the training distribution from the staged raw-column
    matrix (cat codes + numerics, NaN NAs — `stage_frame`'s layout) and
    the training predictions. Pure numpy, deterministic."""
    nbins = nbins or _n_bins()
    topk = topk or _top_k()
    names = dinfo.raw_columns()
    cat = set(dinfo.cat_cols)
    n = raw.shape[0]
    features, counts, na = [], [], []
    sample = raw[:min(n, _sample_rows())]
    for j, name in enumerate(names):
        col = raw[:, j]
        if name in cat:
            card = int(dinfo.cardinalities[name])
            full = np.zeros(card, np.int64)
            finite = np.isfinite(col)
            cc = np.clip(col[finite].astype(np.int64), 0, card - 1)
            full += np.bincount(cc, minlength=card).astype(np.int64)
            order = np.argsort(-full, kind="stable")[:topk]
            tracked = np.sort(order).astype(np.int64)
            lut = _cat_slots(card, tracked)
            c, nna = _bin_categorical(col, lut, len(tracked) + 1)
            features.append({
                "name": name, "kind": "categorical",
                "codes": tracked.tolist(), "card": card,
                "levels": [dinfo.domains[name][k] for k in tracked]})
            counts.append(c)
            na.append(nna)
        else:
            edges = _quantile_edges(sample[:, j], nbins)
            c, nna = _bin_numeric(col, edges, nbins)
            features.append({"name": name, "kind": "numeric",
                             "edges": edges})
            counts.append(c)
            na.append(nna)
    pred_kind, pred_edges, pred_counts = "none", None, \
        np.zeros(1, np.int64)
    if preds is not None:
        preds = np.asarray(preds)
        if preds.ndim == 2 and preds.shape[1] > 1:
            pred_kind = "class"
            cls = preds.argmax(axis=1)
            pred_counts = np.bincount(
                cls, minlength=preds.shape[1]).astype(np.int64)
        else:
            pred_kind = "reg"
            flat = preds.reshape(len(preds), -1)[:, 0].astype(np.float64)
            pred_edges = _quantile_edges(
                flat[:min(len(flat), _sample_rows())], nbins)
            pc, _ = _bin_numeric(flat, pred_edges, nbins)
            pred_counts = pc
    resp_counts = None
    if resp is not None:
        r = np.asarray(resp, np.float64)
        if dinfo.response_domain is not None:
            k = len(dinfo.response_domain)
            finite = np.isfinite(r)
            resp_counts = np.bincount(
                np.clip(r[finite].astype(np.int64), 0, k - 1),
                minlength=k).astype(np.int64)
        elif pred_edges is not None:
            resp_counts, _ = _bin_numeric(r[np.isfinite(r)], pred_edges,
                                          len(pred_counts))
    return BaselineProfile(features, counts, np.asarray(na, np.int64),
                           pred_kind, pred_edges, pred_counts,
                           resp_counts, n_rows=n)


# ---------------------------------------------------------------------------
# live sketches


class LiveSketch:
    """Streaming counts in the baseline's shape. fold() is the serving
    hot-path cost: one searchsorted/bincount per feature on the staged
    host buffer. Counts are int64 — merge is plain addition."""

    __slots__ = ("counts", "na", "pred_counts", "rows", "batches",
                 "_luts", "_edges")

    def __init__(self, profile: BaselineProfile):
        self.counts = [np.zeros(profile.n_slots(j), np.int64)
                       for j in range(len(profile.features))]
        self.na = np.zeros(len(profile.features), np.int64)
        self.pred_counts = np.zeros(len(profile.pred_counts), np.int64)
        self.rows = 0
        self.batches = 0
        # fold plans, prebuilt once per generation
        self._luts = {}
        self._edges = {}
        for j, f in enumerate(profile.features):
            if f["kind"] == "categorical":
                self._luts[j] = _cat_slots(
                    int(f["card"]), np.asarray(f["codes"], np.int64))
            else:
                self._edges[j] = np.asarray(f["edges"], np.float64)

    def fold(self, profile: BaselineProfile, raw: np.ndarray,
             preds, n: int):
        for j in range(len(profile.features)):
            col = raw[:n, j]
            edges = self._edges.get(j)
            if edges is not None:
                c, nna = _bin_numeric(col, edges, len(self.counts[j]))
            else:
                c, nna = _bin_categorical(col, self._luts[j],
                                          len(self.counts[j]))
            self.counts[j] += c
            self.na[j] += nna
        if preds is not None and profile.pred_kind != "none":
            p = np.asarray(preds)[:n]
            if profile.pred_kind == "class" and p.ndim == 2:
                cls = p.argmax(axis=1)
                self.pred_counts += np.bincount(
                    cls, minlength=len(self.pred_counts)).astype(np.int64)
            elif profile.pred_kind == "reg":
                flat = p.reshape(len(p), -1)[:, 0].astype(np.float64)
                c, _ = _bin_numeric(flat[np.isfinite(flat)],
                                    profile.pred_edges,
                                    len(self.pred_counts))
                self.pred_counts += c
        self.rows += int(n)
        self.batches += 1

    def merge_doc(self, doc: dict):
        """Fold a snapshot document (another host's counts) in."""
        for j, c in enumerate(doc.get("counts") or []):
            if j < len(self.counts) and len(c) == len(self.counts[j]):
                self.counts[j] += np.asarray(c, np.int64)
        na = doc.get("na") or []
        for j, v in enumerate(na):
            if j < len(self.na):
                self.na[j] += int(v)
        pc = doc.get("pred_counts") or []
        if len(pc) == len(self.pred_counts):
            self.pred_counts += np.asarray(pc, np.int64)
        self.rows += int(doc.get("rows") or 0)
        self.batches += int(doc.get("batches") or 0)

    def to_doc(self) -> dict:
        """JSON-serializable counts (the collect-op wire form)."""
        return {"counts": [c.tolist() for c in self.counts],
                "na": self.na.tolist(),
                "pred_counts": self.pred_counts.tolist(),
                "rows": self.rows, "batches": self.batches}


class _ModelState:
    """Per-monitored-model registry entry: baseline + current live
    sketch + the retained previous generation."""

    __slots__ = ("key", "baseline", "live", "prev", "prev_baseline",
                 "gen", "token", "prev_token", "lock", "next_fold")

    def __init__(self, key, baseline, token):
        self.key = key
        self.baseline = baseline
        self.live = LiveSketch(baseline)
        self.prev = None
        self.prev_baseline = None
        self.gen = 1
        self.token = token
        self.prev_token = None
        self.lock = make_lock("modelmon.state")
        # duty-cycle throttle (see observe): perf_counter time before
        # which incoming batches are counted but not folded
        self.next_fold = 0.0


# ---------------------------------------------------------------------------
# lifecycle: install (train), rotate (retrain), forget (DELETE)


def install_baseline(model, frame):
    """Train-time hook (ModelBase.train, before DKV.put): profile the
    training frame + predictions, stamp the profile into DKV beside the
    model, and (re)register the model for live monitoring. A retrain
    over the same key ROTATES: the old generation's live sketch is
    retained for shadow-compare. Never raises — monitoring must not
    fail training."""
    if not enabled():
        return None
    try:
        if model._serving_params() is None:
            return None
        from h2o3_tpu.core.kvstore import DKV
        from h2o3_tpu.serving import scorer_cache as _sc
        di = model._dinfo
        af = di.adapt(frame)
        raw = _sc.stage_frame(di, af, frame.nrows)
        preds = None
        # the serving tap must not see the baseline pass itself: on a
        # same-object retrain the training predictions would otherwise
        # fold into the outgoing generation's live sketch
        _TLS.suppress = True
        try:
            out = _sc.score_frame(model, frame)
        finally:
            _TLS.suppress = False
        if out is not None:
            preds = np.asarray(out)[:frame.nrows]
        resp = None
        if di.response_name and di.response_name in af.names:
            y, _w = _sc.stage_response(di, af, frame.nrows)
            resp = y
        profile = build_baseline(di, raw, preds, resp)
        token = _sc.model_token(model)
        with _LOCK:
            st = _STATE.get(model.key)
            if st is None:
                if len(_STATE) >= _max_models():
                    SKIPPED.inc()
                    return None
                _STATE[model.key] = _ModelState(model.key, profile,
                                                token)
            else:
                with st.lock:
                    st.prev = st.live
                    st.prev_baseline = st.baseline
                    st.prev_token = st.token
                    st.baseline = profile
                    st.live = LiveSketch(profile)
                    st.token = token
                    st.gen += 1
        DKV.put(monitor_key(model.key), profile)
        _ensure_evaluator()
        return profile
    except Exception:   # noqa: BLE001 — baseline capture must never fail train
        from h2o3_tpu.utils import log as _log
        import traceback
        _log.warn("modelmon baseline capture failed for %r: %s",
                  getattr(model, "key", None),
                  traceback.format_exc(limit=3))
        return None


def forget(model_key: str):
    """Model DELETE: drop sketches and remove every per-model metric
    series exactly once (the ISSUE-11 Gauge.remove discipline).
    Idempotent — a second call is a no-op."""
    with _LOCK:
        st = _STATE.pop(model_key, None)
    _LAST_EVAL.pop(model_key, None)
    if st is None:
        return False
    for kind in _KINDS:
        DRIFT.remove(model=model_key, feature_kind=kind)
    PRED_DRIFT.remove(model=model_key)
    GEN_SKEW.remove(model=model_key)
    SCORED.remove(model=model_key)
    try:
        from h2o3_tpu.core.kvstore import DKV
        DKV.remove(monitor_key(model_key))
    except Exception:   # noqa: BLE001 — series removal must not fail the op
        pass
    return True


def monitored(model_key: str) -> bool:
    with _LOCK:
        return model_key in _STATE


# ---------------------------------------------------------------------------
# the serving tap


def observe(model, raw: np.ndarray, preds, n: int):
    """score_rows tap: fold one scored batch into the model's live
    sketch (or the RETAINED previous generation's, when the caller is
    still holding the pre-swap model object — that is exactly the
    shadow-compare traffic). Host-side numpy on the already-staged
    buffer; must never break scoring."""
    if n <= 0 or not enabled() or getattr(_TLS, "suppress", False):
        return
    key = getattr(model, "key", None)
    if key is None:
        return
    with _LOCK:
        st = _STATE.get(key)
    if st is None:
        return
    try:
        from h2o3_tpu.serving import scorer_cache as _sc
        token = _sc.model_token(model)
        pct = _tap_pct()
        now = time.perf_counter()
        with st.lock:
            if token == st.token:
                sk, profile = st.live, st.baseline
            elif st.prev is not None and token == st.prev_token:
                sk, profile = st.prev, st.prev_baseline
            else:
                return
            # duty-cycle throttle: inside the deferral window the batch
            # is counted (SCORED below) but not folded — the sketch is
            # a sample of the stream, which is all PSI/JS need
            if pct > 0.0 and now >= st.next_fold:
                cap = _tap_rows()
                if 0 < cap < n:
                    # deterministic stride sample bounds ONE fold's cost
                    step = -(-n // cap)
                    raw, preds = raw[:n:step], preds[:n:step]
                    n_fold = raw.shape[0]
                else:
                    n_fold = n
                sk.fold(profile, raw, preds, n_fold)
                if pct < 100.0:
                    df = time.perf_counter() - now
                    st.next_fold = now + df * (100.0 - pct) / pct
        SCORED.inc(n, model=key)
    except Exception:   # noqa: BLE001 — the tap must never break scoring
        pass


# ---------------------------------------------------------------------------
# drift evaluation


def _feature_doc(profile, sketch):
    feats = []
    for j, f in enumerate(profile.features):
        base = profile.counts[j]
        live = sketch.counts[j]
        base_n = int(base.sum()) + int(profile.na[j])
        live_n = int(live.sum()) + int(sketch.na[j])
        base_na = (profile.na[j] / base_n) if base_n else 0.0
        live_na = (sketch.na[j] / live_n) if live_n else 0.0
        feats.append({
            "name": f["name"], "kind": f["kind"],
            "psi": round(psi(base, live), 6),
            "na_rate_baseline": round(float(base_na), 6),
            "na_rate_live": round(float(live_na), 6),
            "baseline_counts": base.tolist(),
            "live_counts": live.tolist()})
    return feats


def _drift_doc(st: "_ModelState") -> dict:
    """One model's drift document from ITS OWN host-local sketches
    (the background evaluator / gauge feed); the REST handler builds
    the same shape from cluster-merged sketches."""
    with st.lock:
        return drift_from_sketches(st.key, st.baseline, st.live,
                                   st.prev, st.gen)


def drift_from_sketches(key, baseline, live, prev, gen) -> dict:
    feats = _feature_doc(baseline, live)
    worst = {"numeric": 0.0, "categorical": 0.0}
    worst_na = 0.0
    for f in feats:
        worst[f["kind"]] = max(worst[f["kind"]], f["psi"])
        worst_na = max(worst_na,
                       abs(f["na_rate_live"] - f["na_rate_baseline"]))
    pred_drift = js_divergence(baseline.pred_counts, live.pred_counts)
    gen_skew = None
    if prev is not None and prev.rows > 0 and live.rows > 0:
        gen_skew = js_divergence(prev.pred_counts, live.pred_counts)
    return {"model": key, "generation": gen,
            "rows": live.rows, "batches": live.batches,
            "drift": {"numeric": round(worst["numeric"], 6),
                      "categorical": round(worst["categorical"], 6),
                      "na": round(worst_na, 6)},
            "prediction_drift": round(pred_drift, 6),
            "generation_skew": (round(gen_skew, 6)
                                if gen_skew is not None else None),
            "prev_rows": prev.rows if prev is not None else 0,
            "features": feats,
            "prediction": {
                "kind": baseline.pred_kind,
                "baseline_counts": baseline.pred_counts.tolist(),
                "live_counts": live.pred_counts.tolist()}}


def evaluate() -> dict:
    """Refresh the drift gauges for every monitored model from this
    host's sketches; returns {model_key: drift document}. Called by the
    background evaluator, the SLO drift SLI, the pressure model and
    GET /3/ModelMonitor."""
    with _LOCK:
        states = list(_STATE.values())
    out = {}
    for st in states:
        doc = _drift_doc(st)
        for kind in _KINDS:
            DRIFT.set(doc["drift"][kind], model=st.key,
                      feature_kind=kind)
        PRED_DRIFT.set(doc["prediction_drift"], model=st.key)
        if doc["generation_skew"] is not None:
            GEN_SKEW.set(doc["generation_skew"], model=st.key)
        out[st.key] = doc
        _LAST_EVAL[st.key] = doc
    return out


def pressure() -> tuple:
    """(drift pressure in [0,1], detail dict) from the LAST evaluation
    — 1.0 when any model's worst PSI (or prediction drift) reaches
    H2O3_MODELMON_PSI_SAT."""
    sat = max(_psi_saturation(), 1e-9)
    worst = 0.0
    worst_model = None
    for key, doc in list(_LAST_EVAL.items()):
        score = max(max(doc["drift"].values()), doc["prediction_drift"])
        if score > worst:
            worst, worst_model = score, key
    return (min(1.0, worst / sat),
            {"worst_model": worst_model, "worst_score": round(worst, 6),
             "saturation_psi": sat, "monitored": len(_LAST_EVAL)})


# ---------------------------------------------------------------------------
# cluster merge (the `modelmon:` collect op)


def snapshot(model_key: str):
    """This host's sketches for ONE model, JSON-serializable — the
    worker-side answer to the `modelmon:<key>` collect op. None when
    the model is not monitored here."""
    with _LOCK:
        st = _STATE.get(model_key)
    if st is None:
        return None
    from h2o3_tpu.obs import timeline as _tl
    with st.lock:
        doc = {"host": _tl.host_id(), "model": model_key,
               "generation": st.gen, "live": st.live.to_doc(),
               "prev": st.prev.to_doc() if st.prev is not None else None}
    return doc


def merged_report(model_key: str, snaps) -> dict:
    """Cluster-merged drift report: fold every host's live (and prev)
    counts into this host's shape, then score ONCE over the sums —
    integer merge, so host count and arrival order never change the
    result bit-for-bit. Local sketches must NOT appear in `snaps` (the
    local host contributes via its own snapshot like any other)."""
    with _LOCK:
        st = _STATE.get(model_key)
    if st is None:
        return {"model": model_key, "monitored": False}
    with st.lock:
        baseline, prev_baseline = st.baseline, st.prev_baseline
        gen = st.gen
    live = LiveSketch(baseline)
    prev = LiveSketch(prev_baseline) if prev_baseline is not None \
        else None
    hosts = []
    for s in snaps:
        if not isinstance(s, dict) or s.get("model") != model_key:
            continue
        if s.get("live") is None:
            # a host that answered but does not monitor this model
            # (trained elsewhere, or over its cardinality cap)
            hosts.append({"host": s.get("host"), "monitored": False})
            continue
        if s.get("generation") != gen:
            hosts.append({"host": s.get("host"), "stale_generation":
                          s.get("generation")})
            continue
        live.merge_doc(s.get("live") or {})
        if prev is not None and s.get("prev"):
            prev.merge_doc(s["prev"])
        hosts.append({"host": s.get("host"),
                      "rows": (s.get("live") or {}).get("rows", 0)})
    doc = drift_from_sketches(model_key, baseline, live, prev, gen)
    doc["monitored"] = True
    doc["hosts"] = hosts
    return doc


# ---------------------------------------------------------------------------
# background evaluator


def _ensure_evaluator():
    period = _eval_period_s()
    if period <= 0:
        return
    with _LOCK:
        t = _EVAL_THREAD[0]
        if t is not None and t.is_alive():
            return
        t = threading.Thread(target=_eval_loop, args=(period,),
                             daemon=True, name="h2o3-modelmon-eval")
        _EVAL_THREAD[0] = t
    t.start()


def _eval_loop(period: float):
    while True:
        time.sleep(period)
        if _EVAL_THREAD[0] is not threading.current_thread():
            return              # reconfigured: a newer loop owns this
        try:
            if _STATE:
                evaluate()
        except Exception:   # noqa: BLE001 — the evaluator must survive
            import traceback
            traceback.print_exc()


def reset():
    """Test isolation: drop all monitored state and the per-model
    series; restore the env-driven enable switch."""
    with _LOCK:
        keys = list(_STATE.keys())
    for k in keys:
        forget(k)
    _LAST_EVAL.clear()
    _OVERRIDE[0] = None
    DRIFT.clear()
    PRED_DRIFT.clear()
    GEN_SKEW.clear()
    SCORED.clear()
