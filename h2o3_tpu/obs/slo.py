"""Declarative SLOs with multi-window burn-rate alerting.

Specs (deploy/slo.json, pointed at by H2O3_SLO_FILE) declare objectives
over the registry's latency histograms — "99% of /3/Predictions requests
under 250ms" — and the engine evaluates them the Site Reliability
Workbook way (Beyer et al., ch. 5): the ERROR BUDGET is 1-objective, the
BURN RATE is the fraction of bad events over a trailing window divided
by the budget, and an alert fires only when BOTH a short and a long
window exceed the same burn factor — fast-burn pages fire in minutes
(14.4x over 5m AND 1h), slow burns surface in hours (6x over 30m AND 6h)
— so a single outlier scrape can't page and a slow leak can't hide.

The registry's histograms are cumulative since process start; windowed
rates come from the engine's own sample ring: every evaluate() appends
(timestamp, total, bad) per SLO and window deltas are taken against the
newest sample at least `window` old (the oldest available while history
is still shorter than the window — burn converges as the ring fills).

Outputs:
  * h2o3_slo_burn_rate{slo,window} gauges — the Grafana "SLO & alerts"
    row reads these;
  * h2o3_slo_alert_active{slo} + h2o3_slo_alert_transitions_total;
  * GET /3/Alerts (api/server) — specs, live burn rates, alert states;
  * every firing/resolve transition is recorded as a `slo.alert`
    timeline span under its own trace id with a `sampled` attr, so the
    flight recorder retains it and the alert episode is itself a trace.

SLO spec fields (JSON object per SLO):
  name          unique id (required)
  kind          "" (infer latency/availability from threshold_ms) or
                "drift" — a model-drift SLI over the modelmon gauges
  metric        histogram name (default "h2o3_rest_request_seconds");
                for kind=drift a GAUGE name (default "h2o3_model_drift",
                also works against h2o3_model_prediction_drift /
                h2o3_model_generation_skew)
  route         regex matched against the series' route label ("" = all)
  model         drift SLOs: regex over the series' model label ("" = all)
  objective     good-event fraction target, e.g. 0.99 (required)
  threshold_ms  latency SLO: observations over this are bad; omit for an
                availability SLO (bad = series with a 5xx status label)
  threshold     drift SLO: gauge value (PSI/JS) above which an
                evaluation tick is bad (default 0.2)
  windows       [[short_s, long_s, burn_factor], ...] (default
                [[300, 3600, 14.4], [1800, 21600, 6.0]])

A drift SLI reads the modelmon gauges through the same sample ring as
every other SLI: the gauges are LEVELS, not event counts, so each
evaluation tick contributes one synthetic observation per matching
series (bad when the level exceeds `threshold`) to an engine-held
cumulative counter — the multi-window burn machinery then applies
unchanged, and a drifting model fires at GET /3/Alerts with a pinned
flight-recorder trace exactly like a latency breach.

Durability: the sample ring is periodically persisted to
`<ice_root>/obs/slo/samples-h<host>.json` and reloaded on start, so
multi-window burn HISTORY survives a process restart — the warm-up
coverage scaling then applies only to genuinely unseen history, not to
history the previous process already observed. Restored rings carry the
old process's cumulative totals; fresh totals (which restart at zero)
are rebased onto them so deltas stay monotone across the boundary.

Env surface:
  H2O3_SLO_FILE       path to the spec file (unset = engine idle)
  H2O3_SLO_EVAL_S     background evaluation period (default 30; 0 = only
                      evaluate on GET /3/Alerts)
  H2O3_SLO_PERSIST_S  min seconds between sample-ring persists
                      (default 30; 0 disables persistence)
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque

from h2o3_tpu.analysis.lockdep import make_lock
from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.utils.env import env_float, env_str

DEFAULT_WINDOWS = ((300.0, 3600.0, 14.4), (1800.0, 21600.0, 6.0))


def _window_label(seconds: float) -> str:
    s = int(seconds)
    if s % 86400 == 0:
        return f"{s // 86400}d"
    if s % 3600 == 0:
        return f"{s // 3600}h"
    if s % 60 == 0:
        return f"{s // 60}m"
    return f"{s}s"


class SLOSpec:
    def __init__(self, d: dict):
        self.name = str(d["name"])
        self.kind = str(d.get("kind") or "")
        if self.kind not in ("", "drift"):
            raise ValueError(f"slo {self.name}: unknown kind "
                             f"{self.kind!r} (expected '' or 'drift')")
        self.metric = str(d.get("metric") or (
            "h2o3_model_drift" if self.kind == "drift"
            else "h2o3_rest_request_seconds"))
        self.route = str(d.get("route") or "")
        # drift SLOs: scope to models whose key matches, and call a tick
        # bad when the drift gauge exceeds `threshold` (PSI/JS units)
        self.model = str(d.get("model") or "")
        self.threshold = float(d["threshold"]) if "threshold" in d \
            else (0.2 if self.kind == "drift" else None)
        # per-tenant SLOs (multi-tenant QoS): a `principal` regex scopes
        # the SLI to series whose principal label matches — point the
        # spec at h2o3_qos_request_seconds{principal,status} and the
        # burn-rate engine answers "is THIS tenant inside its SLO"
        self.principal = str(d.get("principal") or "")
        self.objective = float(d["objective"])
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"slo {self.name}: objective must be in "
                             f"(0,1), got {self.objective}")
        self.threshold_ms = d.get("threshold_ms")
        if self.threshold_ms is not None:
            self.threshold_ms = float(self.threshold_ms)
        self.windows = tuple(
            (float(w[0]), float(w[1]), float(w[2]))
            for w in (d.get("windows") or DEFAULT_WINDOWS))
        self._route_re = re.compile(self.route) if self.route else None
        self._principal_re = re.compile(self.principal) \
            if self.principal else None
        self._model_re = re.compile(self.model) if self.model else None

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    def to_dict(self) -> dict:
        return {"name": self.name, "metric": self.metric,
                "route": self.route, "principal": self.principal,
                "model": self.model,
                "objective": self.objective,
                "threshold_ms": self.threshold_ms,
                "threshold": self.threshold,
                "windows": [list(w) for w in self.windows],
                "kind": self.kind or
                        ("latency" if self.threshold_ms is not None
                         else "availability")}


def load_specs(path: str) -> list:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if isinstance(data, dict):
        data = data.get("slos") or []
    specs = [SLOSpec(d) for d in data]
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate slo names in {path}: {names}")
    return specs


def _alert_span(spec: SLOSpec, state: str, burn: float, window: str,
                trace_id: str):
    """One firing/resolve transition as a (root) timeline span under the
    alert episode's own trace id: `sampled` forces the flight recorder to
    retain it, so GET /3/Trace/{episode} replays the alert's history."""
    from h2o3_tpu.obs import tracing as _tracing
    from h2o3_tpu.obs import timeline as _timeline
    with _tracing.trace(trace_id):
        with _timeline.span("slo.alert", slo=spec.name, state=state,
                            burn=round(burn, 3), window=window,
                            sampled=1) as sp:
            # evaluate() usually runs inside a GET /3/Alerts request span:
            # detach, or the episode's root would point into the polling
            # request's (unrelated) trace and never close the episode
            sp.parent_id = 0


class SLOEngine:
    """Spec store + window sampler + alert state machine. One instance
    per process (module-level ENGINE); tests construct their own with an
    isolated registry."""

    def __init__(self, specs=None, registry=None):
        self._lock = make_lock("slo")
        self._registry = registry or _om.REGISTRY
        self._specs: list = list(specs or [])
        self._samples: dict = {}    # name -> deque[(ts, total, bad)]
        self._state: dict = {}      # name -> alert state dict
        self._drift_counts: dict = {}   # name -> [ticks, bad_ticks]
        self._offset: dict = {}     # name -> (total0, bad0): restored
        #                             history's final cumulative counts,
        #                             added to fresh post-restart totals
        self._last_persist = 0.0
        self._thread = None
        # output metrics live on THIS engine's registry: a scratch
        # engine over an isolated registry (tests) must not publish
        # into — or configure()-clear — the process ENGINE's series
        with self._lock:
            self._burn = self._registry.gauge(
                "h2o3_slo_burn_rate",
                "error-budget burn rate per SLO and trailing window "
                "(1.0 = burning exactly the budget; a fast-burn alert "
                "fires at 14.4x over 5m+1h)")
            self._active = self._registry.gauge(
                "h2o3_slo_alert_active",
                "1 while the SLO's multi-window burn-rate alert is "
                "firing")
            self._transitions = self._registry.counter(
                "h2o3_slo_alert_transitions_total",
                "SLO alert state transitions, labeled "
                "state=firing|resolved")

    # ---- configuration --------------------------------------------------
    def configure(self, specs, registry=None):
        with self._lock:
            self._specs = list(specs or [])
            if registry is not None and registry is not self._registry:
                self._registry = registry
                self._burn = registry.gauge(self._burn.name,
                                            self._burn.help)
                self._active = registry.gauge(self._active.name,
                                              self._active.help)
                self._transitions = registry.counter(
                    self._transitions.name, self._transitions.help)
            self._samples.clear()
            self._state.clear()
            self._offset.clear()
            self._drift_counts.clear()
            self._burn.clear()
            self._active.clear()

    def load(self, path: str):
        self.configure(load_specs(path))

    def specs(self) -> list:
        with self._lock:
            return list(self._specs)

    # ---- sample-ring durability -----------------------------------------
    @staticmethod
    def persist_path() -> str:
        """Per-host state file under the ice root (two processes sharing
        an ice root in tests must not clobber each other's history)."""
        from h2o3_tpu.io import spill as _spill
        from h2o3_tpu.obs import timeline as _tl
        return os.path.join(_spill.get_ice_root(), "obs", "slo",
                            f"samples-h{_tl.host_id()}.json")

    @staticmethod
    def _persist_min_s() -> float:
        return env_float("H2O3_SLO_PERSIST_S", 30.0)

    def persist(self):
        """Write the sample rings (and alert states) atomically. The
        snapshot is taken under the lock; the file write happens outside
        it (the R008 discipline: no disk I/O while locked)."""
        path = self.persist_path()
        with self._lock:
            state = {
                "version": 1,
                "saved_at": time.time(),
                "samples": {name: [list(s) for s in ring]
                            for name, ring in self._samples.items()},
                "firing": {name: {k: v for k, v in st.items()
                                  if k in ("firing", "since", "trace",
                                           "window")}
                           for name, st in self._state.items()},
            }
        tmp = path + f".tmp{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(state, fh)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def restore(self) -> bool:
        """Reload persisted burn history for the CONFIGURED specs and
        rebase the registry's CURRENT totals onto each ring's final
        cumulative counts, so the first post-restore delta is the real
        traffic since the save — not a negative (fresh process) and not
        a double count (an in-process re-install over a registry that
        already holds live totals). Returns True when any history was
        restored."""
        path = self.persist_path()
        try:
            with open(path, encoding="utf-8") as fh:
                state = json.load(fh)
        except (OSError, json.JSONDecodeError, ValueError):
            return False
        got = False
        with self._lock:
            by_name = {s.name: s for s in self._specs}
            for name, samples in (state.get("samples") or {}).items():
                spec = by_name.get(name)
                if spec is None or not samples:
                    continue
                ring = deque((float(t), float(tot), float(bad))
                             for t, tot, bad in samples)
                self._samples[name] = ring
                # offset = persisted_last - current: future totals read
                # persisted_last + (traffic since this restore), whether
                # the registry restarted at zero or kept counting
                cur_total, cur_bad = self._totals(spec)
                self._offset[name] = (ring[-1][1] - cur_total,
                                      ring[-1][2] - cur_bad)
                st = self._state.setdefault(
                    name, {"slo": name, "firing": False, "since": None,
                           "trace": None, "burn": {}, "window": None})
                st.update({k: v for k, v in
                           (state.get("firing") or {}).get(name, {}).items()
                           if k in ("firing", "since", "trace", "window")})
                got = True
        return got

    # ---- SLI extraction -------------------------------------------------
    def _drift_totals(self, spec: SLOSpec):
        """Cumulative (ticks, bad_ticks) for a drift SLI. The drift
        metric is a gauge — a LEVEL, not an event stream — so each call
        (one per evaluate) counts one synthetic observation per matching
        {model=…} series, bad when the level exceeds spec.threshold, and
        accumulates them engine-side. The counts are monotone, so the
        sample ring and burn-rate deltas apply unchanged."""
        ent = self._drift_counts.setdefault(  # h2o3-ok: R003 every caller
            spec.name, [0, 0])  # (_totals via evaluate/_restore) holds
        #                         self._lock; never called bare
        g = self._registry.get(spec.metric)
        if isinstance(g, _om.Gauge):
            thr = spec.threshold if spec.threshold is not None else 0.2
            for lkey, val in g._collect():
                labels = dict(lkey)
                if spec._model_re is not None and \
                        not spec._model_re.search(labels.get("model", "")):
                    continue
                ent[0] += 1
                if val > thr:
                    ent[1] += 1
        return ent[0], ent[1]

    def _totals(self, spec: SLOSpec):
        """(total, bad) cumulative event counts for one SLO, summed over
        the matching histogram series. Latency SLOs count observations
        over threshold_ms as bad via the cumulative buckets (a threshold
        between bucket bounds rounds the GOOD side down — conservative);
        availability SLOs count series with a 5xx status label; drift
        SLOs tick against the modelmon gauges (_drift_totals)."""
        if spec.kind == "drift":
            return self._drift_totals(spec)
        h = self._registry.get(spec.metric)
        if not isinstance(h, _om.Histogram):
            return 0, 0
        total = bad = 0
        thr = None if spec.threshold_ms is None \
            else spec.threshold_ms / 1000.0
        for labels, snap in h.series_snapshots():
            if spec._route_re is not None and \
                    not spec._route_re.search(labels.get("route", "")):
                continue
            if spec._principal_re is not None and \
                    not spec._principal_re.search(
                        labels.get("principal", "")):
                continue
            c = snap["count"]
            total += c
            if thr is not None:
                good = sum(cnt for ub, cnt in zip(h.buckets, snap["counts"])
                           if ub <= thr * (1 + 1e-9))
                bad += c - good
            elif str(labels.get("status", "")).startswith("5"):
                bad += c
        return total, bad

    def _burn_rate(self, spec: SLOSpec, ring, window_s: float, now: float):
        """Burn rate over one trailing window from the sample ring: the
        bad fraction of events since the newest sample at least
        `window_s` old, over the error budget. While history is still
        shorter than the window the unobserved remainder is assumed
        CLEAN traffic at the observed rate (burn scales by
        coverage/window): without that, every window clamps to the same
        short history after a restart, short == long burn, and the
        multi-window guard ("one outlier scrape never pages") is
        defeated exactly when deploy rollouts make blips likeliest."""
        if not ring:
            return 0.0
        cur_ts, cur_total, cur_bad = ring[-1]
        base = ring[0]
        for s in ring:
            if s[0] <= now - window_s:
                base = s
            else:
                break
        d_total = cur_total - base[1]
        d_bad = cur_bad - base[2]
        if d_total <= 0:
            return 0.0
        burn = (d_bad / d_total) / spec.budget
        coverage = now - ring[0][0]
        if coverage < window_s:
            burn *= max(coverage, 0.0) / window_s
        return burn

    # ---- evaluation -----------------------------------------------------
    def evaluate(self, now: float | None = None) -> list:
        """Sample every SLO, publish burn-rate gauges, advance the alert
        state machine. Returns the alert list (the GET /3/Alerts body)."""
        now = time.time() if now is None else now
        transitions = []
        with self._lock:
            for spec in self._specs:
                total, bad = self._totals(spec)
                off = self._offset.get(spec.name)
                if off:
                    # restored history: fresh totals restart at zero —
                    # rebase onto the persisted cumulative counts so the
                    # cross-restart delta is traffic, not a negative
                    total += off[0]
                    bad += off[1]
                ring = self._samples.setdefault(spec.name, deque())
                max_w = max((w[1] for w in spec.windows),
                            default=3600.0)
                # bound the ring by COUNT as well as time: persisted
                # samples keep a minimum spacing, so a dashboard polling
                # /3/Alerts every second can't grow the ring (or the
                # per-evaluate window scan) past ~4096 entries — the
                # newest sample is instead updated in place
                spacing = max(1.0, 1.5 * max_w / 4096.0)
                if len(ring) >= 2 and now - ring[-2][0] < spacing:
                    ring[-1] = (now, total, bad)
                else:
                    ring.append((now, total, bad))
                while len(ring) > 2 and ring[1][0] < now - 1.5 * max_w:
                    ring.popleft()
                st = self._state.setdefault(
                    spec.name, {"slo": spec.name, "firing": False,
                                "since": None, "trace": None,
                                "burn": {}, "window": None})
                firing_pair = None
                short_ok = True
                burns = {}
                for short_s, long_s, factor in spec.windows:
                    b_short = self._burn_rate(spec, ring, short_s, now)
                    b_long = self._burn_rate(spec, ring, long_s, now)
                    wl_s = _window_label(short_s)
                    wl_l = _window_label(long_s)
                    burns[wl_s] = b_short
                    burns[wl_l] = b_long
                    self._burn.set(b_short, slo=spec.name, window=wl_s)
                    self._burn.set(b_long, slo=spec.name, window=wl_l)
                    if b_short > factor and b_long > factor:
                        firing_pair = (wl_s, wl_l, factor,
                                       max(b_short, b_long))
                    if b_short > factor:
                        short_ok = False
                st["burn"] = {k: round(v, 4) for k, v in burns.items()}
                if not st["firing"] and firing_pair is not None:
                    import secrets
                    st["firing"] = True
                    st["since"] = now
                    st["trace"] = f"slo-{spec.name}-{secrets.token_hex(4)}"
                    st["window"] = f"{firing_pair[0]}+{firing_pair[1]}"
                    transitions.append((spec, "firing", firing_pair[3],
                                        st["window"], st["trace"]))
                elif st["firing"] and firing_pair is None and short_ok:
                    st["firing"] = False
                    transitions.append((spec, "resolved",
                                        max(burns.values(), default=0.0),
                                        st["window"] or "",
                                        st["trace"] or ""))
                self._active.set(1.0 if st["firing"] else 0.0, slo=spec.name)
            alerts = [dict(st) for st in self._state.values()]
        # transitions emit OUTSIDE the engine lock: span recording takes
        # the timeline ring + recorder locks
        for spec, state, burn, window, trace_id in transitions:
            self._transitions.inc(slo=spec.name, state=state)
            _alert_span(spec, state, burn, window, trace_id)
        # periodic durability (gated, outside the lock): burn history
        # survives a restart instead of resetting with the process
        min_s = self._persist_min_s()
        if self._specs and min_s > 0 and now - self._last_persist >= min_s:
            self._last_persist = now
            self.persist()
        return alerts

    def alerts(self) -> list:
        with self._lock:
            return [dict(st) for st in self._state.values()]

    # ---- background evaluation ------------------------------------------
    def start(self):
        """Start the periodic evaluator (idempotent; daemon thread). No
        specs or H2O3_SLO_EVAL_S=0 → nothing to do."""
        period = env_float("H2O3_SLO_EVAL_S", 30.0)
        if not self._specs or period <= 0:
            return None
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self._thread
            t = threading.Thread(target=self._run, args=(period,),
                                 daemon=True, name="h2o3-slo-eval")
            self._thread = t
        t.start()
        return t

    def _run(self, period: float):
        while True:
            time.sleep(period)
            if self._thread is not threading.current_thread():
                return              # reconfigured: a newer loop owns this
            try:
                self.evaluate()
            except Exception:   # noqa: BLE001 — the evaluator must survive
                import traceback
                traceback.print_exc()


ENGINE = SLOEngine()


def install_from_env():
    """Server-start hook: load H2O3_SLO_FILE into the process ENGINE and
    start the background evaluator. Unset env — or a pointed-at file
    that is absent (the k8s ConfigMap mount is optional) — leaves the
    engine idle; the /3/Alerts route still answers with an empty spec
    list. A file that EXISTS but fails to parse raises: a deployment
    that ships broken SLOs should fail loudly at start, not alert on
    nothing."""
    path = env_str("H2O3_SLO_FILE", "")
    # isfile, not exists: with an absent optional ConfigMap the mount
    # materializes as an empty directory (or the pointed-at file simply
    # never appears), and a directory path must idle, not raise
    if not path or not os.path.isfile(path):
        return None
    ENGINE.load(path)
    # reload persisted burn history (multi-window history survives the
    # restart; warm-up scaling then covers only genuinely unseen time)
    ENGINE.restore()
    return ENGINE.start()
