"""Distributed request tracing — Dapper-style trace ids for the cloud.

A trace id is minted at the REST boundary (`X-H2O3-Trace-Id` request
header, auto-generated when absent) and carried in a per-thread context:
every `timeline.span` opened while a trace is current tags itself with
the id, jobs inherit the trace of the thread that started them, and the
deploy/multihost replay channel forwards the id so remote hosts tag their
replayed spans with the ORIGINATING request's trace. `GET /3/Trace/{id}`
stitches the fragments back together cloud-wide.

This module is intentionally dependency-free (stdlib only): it is
imported by the span timeline, the REST layer, the micro-batcher, mrtask
and bench.py, and must never pull jax or the metrics registry in.

Env surface:
  H2O3_TRACING  "0" disables trace-id minting at the REST layer (spans
                still record, untagged). Default on.
"""

from __future__ import annotations

import contextlib
import re
import secrets
import threading

from h2o3_tpu.utils.env import env_bool

_TLS = threading.local()

# ids cross the REST boundary and the replay channel as free text: bound
# the charset + length so a hostile header can't smuggle exposition-format
# or JSON structure into merged outputs
_SAFE_ID = re.compile(r"[0-9a-zA-Z_.\-]{1,64}")


def enabled() -> bool:
    """Trace-id minting at the REST layer (H2O3_TRACING, default on)."""
    return env_bool("H2O3_TRACING", True)


def new_trace_id() -> str:
    return secrets.token_hex(8)


def current():
    """The calling thread's current trace id, or None."""
    return getattr(_TLS, "trace_id", None)


def set_current(trace_id):
    """Set the thread's trace id; returns the previous value so callers
    can restore it (prefer the `trace()` context manager)."""
    prev = getattr(_TLS, "trace_id", None)
    _TLS.trace_id = trace_id
    return prev


@contextlib.contextmanager
def trace(trace_id):
    """Run a block under `trace_id` (None = explicitly untraced)."""
    prev = set_current(trace_id)
    try:
        yield trace_id
    finally:
        set_current(prev)


def sanitize(trace_id):
    """A caller-supplied id, validated — or None when unusable."""
    if not trace_id:
        return None
    tid = str(trace_id).strip()
    return tid if _SAFE_ID.fullmatch(tid) else None


# ---------------------------------------------------------------------------
# Request context beyond the trace id (multi-tenant QoS, serving/qos.py):
# the REST layer resolves every request to a PRINCIPAL (authenticated
# user, else the stable "anonymous" bucket) and an optional DEADLINE
# (X-H2O3-Deadline-Ms, stored as an absolute time.monotonic() instant),
# and stamps both here alongside the trace id — the micro-batcher, the
# job system and the QoS admission layer all read them from the same TLS
# the spans already use. Kept in this module so the context stays
# dependency-free (core/jobs and parallel/mrtask must not import the
# serving package just to read who is asking).

def principal():
    """The calling thread's resolved principal, or None (no request
    context — internal work, tests, library use)."""
    return getattr(_TLS, "principal", None)


def set_principal(name):
    """Set the thread's principal; returns the previous value."""
    prev = getattr(_TLS, "principal", None)
    _TLS.principal = name
    return prev


def deadline():
    """The request's absolute deadline (time.monotonic() seconds), or
    None when the caller sent no X-H2O3-Deadline-Ms."""
    return getattr(_TLS, "deadline", None)


def set_deadline(when):
    """Set the thread's deadline instant; returns the previous value."""
    prev = getattr(_TLS, "deadline", None)
    _TLS.deadline = when
    return prev


@contextlib.contextmanager
def request_context(principal_name, deadline_at=None):
    """Run a block as `principal_name` with an optional absolute
    deadline — the REST dispatch wraps every handler in this; Job.start
    re-enters it on the worker thread (principal only: a build outlives
    its launching request's deadline)."""
    prev_p = set_principal(principal_name)
    prev_d = set_deadline(deadline_at)
    try:
        yield
    finally:
        set_principal(prev_p)
        set_deadline(prev_d)
