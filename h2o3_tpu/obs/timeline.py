"""Span timeline — water.TimeLine rebuilt as a ring of timed spans.

Reference: TimeLine.java:22 keeps a lock-free per-node ring of every
UDP/TCP packet; TimelineSnapshot assembles the rings cloud-wide for
/3/Timeline. A single-controller TPU runtime has no packets — the unit of
"what happened" is a timed SPAN (a job phase, a tree level dispatch, an
IRLSM iteration), nested via a per-thread stack so /3/Timeline can show
the call tree of a model build.

The ring holds COMPLETED spans (recorded at exit, like TimeLine records a
packet once sent); `snapshot()` is the per-host view, and api/server.py
merges snapshots across hosts through the deploy/multihost channel — the
TimelineSnapshot analog.

xprof bridge: when H2O3_OBS_TRACE_DIR is set and a span's name starts with
H2O3_OBS_TRACE_SPAN, the span also starts/stops a jax.profiler trace —
deep kernel-level visibility for exactly the region you care about.
"""

from __future__ import annotations

import contextlib
import itertools
import random
import threading
import time
from dataclasses import dataclass, field
from collections import deque

from h2o3_tpu.analysis.lockdep import make_lock
from h2o3_tpu.utils import env as _env
from h2o3_tpu.obs import tracing as _tracing


def _dropped_counter():
    """Ring-overflow counter, declared lazily: the flight recorder (and
    through it the metrics registry) imports this module, so a top-level
    metrics import here would cycle."""
    from h2o3_tpu.obs import metrics as _om
    return _om.counter(
        "h2o3_timeline_dropped_spans_total",
        "completed spans pushed out of the bounded timeline ring by "
        "overflow (H2O3_OBS_TIMELINE_CAPACITY) — under load the ring "
        "forgets; the flight recorder (obs/recorder) is the durable tier")


def host_id() -> int:
    """This process' rank in the cloud. Env-derived (the multihost
    bootstrap wires H2O3_PROCESS_ID via utils.env.process_id) so reading
    it never initializes the JAX backend."""
    return _env.process_id()


@dataclass
class Span:
    name: str
    t_start: float
    span_id: int
    parent_id: int = 0           # 0 = root (no parent)
    t_end: float | None = None
    host: int = 0
    attrs: dict = field(default_factory=dict)
    # originating request's trace id (obs/tracing), None when untraced
    trace: str | None = None

    @property
    def duration_ms(self) -> float | None:
        if self.t_end is None:
            return None
        return 1000.0 * (self.t_end - self.t_start)

    def event(self, name: str, **attrs):
        """Record a point-in-time event on this span (the OpenTelemetry
        span-event analog): lands in attrs["events"] and is rendered by
        /3/Timeline and GET /3/Trace/{id}. The DKV pager uses this to
        mark chunk faults/evictions inside MRTask spans. Call from the
        span's owning thread (same contract as mutating attrs)."""
        self.attrs.setdefault("events", []).append(
            dict({"name": name, "t": time.time()}, **attrs))

    def to_dict(self) -> dict:
        return {"name": self.name, "id": self.span_id,
                "parent": self.parent_id, "host": self.host,
                "start": self.t_start, "end": self.t_end,
                "duration_ms": self.duration_ms, "attrs": self.attrs,
                "trace": self.trace}


class SpanTimeline:
    """Bounded ring of completed spans + per-thread open-span stack."""

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            capacity = _env.env_int("H2O3_OBS_TIMELINE_CAPACITY", 4096)
        self.capacity = capacity
        self._ring: deque = deque(maxlen=capacity)
        self._lock = make_lock("timeline.ring")
        # span ids start at a random per-process base (not 1): the
        # recorder's durability story spans restarts, and the (host, id)
        # dedup keys in /3/Trace/{id} + recorder.search would otherwise
        # collide a fresh process's ring spans 1..N with a dead process's
        # on-disk spans for the same reused trace id, silently hiding the
        # stored ones. Base < 2^52 keeps ids exact in JSON doubles.
        self._ids = itertools.count(
            (random.getrandbits(31) << 20) + 1)
        self._tls = threading.local()

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    # ---- span lifecycle -------------------------------------------------
    def begin(self, name: str, **attrs) -> Span:
        st = self._stack()
        sp = Span(name=name, t_start=time.time(),
                  span_id=next(self._ids),
                  parent_id=st[-1].span_id if st else 0,
                  host=host_id(), attrs=attrs,
                  trace=_tracing.current())
        st.append(sp)
        return sp

    def end(self, sp: Span):
        sp.t_end = time.time()
        st = self._stack()
        if st and st[-1] is sp:
            st.pop()
        elif sp in st:           # mis-nested exit: unwind through it
            while st and st.pop() is not sp:
                pass
        with self._lock:
            # deque(maxlen) overflow is SILENT — count the span the
            # append is about to push out, so ring data loss is a signal
            # (h2o3_timeline_dropped_spans_total), not a mystery
            dropped = (self.capacity is not None
                       and len(self._ring) == self.capacity)
            self._ring.append(sp)
        if dropped:
            _dropped_counter().inc()
        # durable tier: traced spans stream to the flight recorder, which
        # makes the keep/drop call at trace completion (tail sampling).
        # Untraced spans return after one attribute read. Lazy import —
        # the recorder imports the metrics registry; this module must
        # stay importable underneath both.
        if sp.trace is not None:
            from h2o3_tpu.obs import recorder as _recorder
            _recorder.RECORDER.on_span_end(sp)

    def current(self) -> Span | None:
        st = self._stack()
        return st[-1] if st else None

    # ---- views ----------------------------------------------------------
    def snapshot(self, limit: int = 0) -> list:
        """Completed spans, oldest first (the /3/Timeline per-host body)."""
        with self._lock:
            spans = list(self._ring)
        if limit and len(spans) > limit:
            spans = spans[-limit:]
        return [s.to_dict() for s in spans]

    def trace_snapshot(self, trace_id: str, limit: int = 0) -> list:
        """Completed spans belonging to one trace: tagged with the id, or
        LINKING it via attrs["links"] (a coalesced micro-batch dispatch
        serving N parent traces records every parent there)."""
        with self._lock:
            spans = list(self._ring)
        out = [s for s in spans
               if s.trace == trace_id
               or trace_id in (s.attrs.get("links") or ())]
        if limit and len(out) > limit:
            out = out[-limit:]
        return [s.to_dict() for s in out]

    def clear(self):
        with self._lock:
            self._ring.clear()


SPANS = SpanTimeline()


# ---------------------------------------------------------------------------
# xprof bridge (env-gated; one capture at a time)
_TRACE_LOCK = make_lock("timeline.trace")
_TRACE_ACTIVE = False


def _xprof_trace_dir() -> str:
    """H2O3_OBS_TRACE_DIR declaration site ("" = xprof bridge off)."""
    return _env.env_str("H2O3_OBS_TRACE_DIR", "")


def _maybe_start_trace(name: str) -> bool:
    trace_dir = _xprof_trace_dir()
    want = _env.env_str("H2O3_OBS_TRACE_SPAN", "")
    if not trace_dir or not want or not name.startswith(want):
        return False
    global _TRACE_ACTIVE
    with _TRACE_LOCK:
        if _TRACE_ACTIVE:
            return False        # nested match: outer capture already running
        try:
            import jax
            jax.profiler.start_trace(trace_dir)
        except Exception:   # noqa: BLE001 — profiler trouble must not kill the span
            return False
        _TRACE_ACTIVE = True
        return True


def _stop_trace():
    global _TRACE_ACTIVE
    with _TRACE_LOCK:
        if not _TRACE_ACTIVE:
            return
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:   # noqa: BLE001
            pass
        _TRACE_ACTIVE = False


@contextlib.contextmanager
def span(name: str, **attrs):
    """Time a block as one span: `with span("gbm.histogram", job=k): ...`.
    Nesting is tracked per thread; attrs land in the /3/Timeline record."""
    sp = SPANS.begin(name, **attrs)
    traced = _maybe_start_trace(name)
    if traced:
        sp.attrs["xprof"] = _xprof_trace_dir()
    try:
        yield sp
    finally:
        if traced:
            _stop_trace()
        SPANS.end(sp)
