"""h2o-py-style client surface — the h2o-py/h2o package rebuilt thin.

Reference: h2o-py's H2OFrame compiles every dataframe operation into a Rapids
expression sent over REST (`h2o-py/h2o/expr.py` lazy ExprNode DAG). Here the
controller IS the cluster, so the client evaluates the SAME Rapids expressions
in-process (the REST path in api/server.py exposes the identical surface for
out-of-process clients). Lazy DAG batching is unnecessary — dispatch is
already async on device.

Usage mirrors h2o-py:

    from h2o3_tpu import client as h2o
    h2o.init()
    fr = h2o.import_file("x.csv")
    fr["d"] = fr["a"] + fr["b"] * 2
    sub = fr[fr["a"] > 0.5]
    print(sub["d"].mean())
"""

from __future__ import annotations

import numpy as np

import h2o3_tpu
from h2o3_tpu.core.frame import Frame
from h2o3_tpu.rapids.rapids import rapids_exec


def init(**kw):
    return h2o3_tpu.init(**kw)


def import_file(path, **kw):
    return H2OFrame._wrap(h2o3_tpu.import_file(path, **kw))


def get_frame(key):
    return H2OFrame._wrap(h2o3_tpu.get_frame(key))


def H2OFrame_from(obj, destination_frame=None):
    from h2o3_tpu.io.parser import upload_frame
    return H2OFrame._wrap(upload_frame(obj, destination_frame))


class H2OFrame:
    """Operator-overloaded view over a server Frame (h2o-py H2OFrame)."""

    def __init__(self, python_obj=None, destination_frame=None):
        if python_obj is not None:
            from h2o3_tpu.io.parser import upload_frame
            self._fr = upload_frame(python_obj, destination_frame)
        else:
            self._fr = None

    @staticmethod
    def _wrap(fr: Frame) -> "H2OFrame":
        o = H2OFrame()
        o._fr = fr
        return o

    # ---- metadata -------------------------------------------------------
    @property
    def frame_id(self):
        return self._fr.key

    @property
    def names(self):
        return list(self._fr.names)

    @property
    def columns(self):
        return list(self._fr.names)

    @property
    def shape(self):
        return self._fr.shape

    @property
    def nrows(self):
        return self._fr.nrows

    @property
    def ncols(self):
        return self._fr.ncols

    @property
    def types(self):
        return self._fr.types

    @property
    def frame(self) -> Frame:
        return self._fr

    def __len__(self):
        return self._fr.nrows

    def head(self, rows=10):
        return self._fr.head(rows)

    def as_data_frame(self, use_pandas=True):
        return self._fr.as_data_frame()

    def summary(self):
        return self._fr.summary()

    def refresh(self):
        return self

    # ---- rapids plumbing -------------------------------------------------
    def _x(self, expr: str):
        out = rapids_exec(expr)
        return H2OFrame._wrap(out) if isinstance(out, Frame) else out

    @staticmethod
    def _ref(v):
        if isinstance(v, H2OFrame):
            return v._fr.key
        if isinstance(v, str):
            return f'"{v}"'
        if isinstance(v, bool):
            return "True" if v else "False"
        return repr(v)

    def _binop(self, op, rhs, reverse=False):
        a, b = (self._ref(rhs), self._fr.key) if reverse \
            else (self._fr.key, self._ref(rhs))
        return self._x(f"({op} {a} {b})")

    # ---- operators -------------------------------------------------------
    def __add__(self, o): return self._binop("+", o)
    def __radd__(self, o): return self._binop("+", o, True)
    def __sub__(self, o): return self._binop("-", o)
    def __rsub__(self, o): return self._binop("-", o, True)
    def __mul__(self, o): return self._binop("*", o)
    def __rmul__(self, o): return self._binop("*", o, True)
    def __truediv__(self, o): return self._binop("/", o)
    def __rtruediv__(self, o): return self._binop("/", o, True)
    def __pow__(self, o): return self._binop("^", o)
    def __mod__(self, o): return self._binop("%", o)
    def __eq__(self, o): return self._binop("==", o)    # noqa: E501 — frame semantics
    def __ne__(self, o): return self._binop("!=", o)
    def __gt__(self, o): return self._binop(">", o)
    def __ge__(self, o): return self._binop(">=", o)
    def __lt__(self, o): return self._binop("<", o)
    def __le__(self, o): return self._binop("<=", o)
    def __and__(self, o): return self._binop("&", o)
    def __or__(self, o): return self._binop("|", o)
    def __invert__(self): return self._x(f"(! {self._fr.key})")
    def __hash__(self):
        return id(self)

    # ---- selection -------------------------------------------------------
    def __getitem__(self, sel):
        if isinstance(sel, str):
            return H2OFrame._wrap(self._fr[sel])
        if isinstance(sel, list):
            if all(isinstance(s, str) for s in sel):
                return H2OFrame._wrap(self._fr[sel])
            idx = " ".join(str(int(i)) for i in sel)
            return self._x(f"(cols {self._fr.key} [{idx}])")
        if isinstance(sel, H2OFrame):  # boolean mask
            return self._x(f"(rows {self._fr.key} {sel._fr.key})")
        if isinstance(sel, int):
            return self._x(f"(cols {self._fr.key} [{sel}])")
        if isinstance(sel, slice):
            idx = list(range(*sel.indices(self.nrows)))
            lst = " ".join(str(i) for i in idx)
            return self._x(f"(rows {self._fr.key} [{lst}])")
        if isinstance(sel, tuple) and len(sel) == 2:
            rows, cols = sel
            sub = self[cols] if not isinstance(cols, tuple) else self
            return sub[rows] if not isinstance(rows, slice) or \
                rows != slice(None) else sub
        raise KeyError(sel)

    def __setitem__(self, name, value):
        if isinstance(value, H2OFrame):
            self._fr[name] = value._fr.vecs[0]
        else:
            self._fr[name] = value

    # ---- math / reducers -------------------------------------------------
    def _reduce(self, op):
        return rapids_exec(f"({op} {self._fr.key})")

    def sum(self, **kw): return self._reduce("sum")
    def mean(self, **kw): return self._reduce("mean")
    def min(self): return self._reduce("min")
    def max(self): return self._reduce("max")
    def sd(self): return self._reduce("sd")
    def var(self): return self._reduce("var")
    def median(self): return self._reduce("median")

    def isna(self):
        return self._x(f"(is.na {self._fr.key})")

    def log(self): return self._x(f"(log {self._fr.key})")
    def exp(self): return self._x(f"(exp {self._fr.key})")
    def sqrt(self): return self._x(f"(sqrt {self._fr.key})")
    def abs(self): return self._x(f"(abs {self._fr.key})")
    def floor(self): return self._x(f"(floor {self._fr.key})")
    def ceil(self): return self._x(f"(ceiling {self._fr.key})")

    # ---- munging ---------------------------------------------------------
    def asfactor(self):
        return self._x(f"(as.factor {self._fr.key})")

    def asnumeric(self):
        return self._x(f"(as.numeric {self._fr.key})")

    def ascharacter(self):
        return self._x(f"(as.character {self._fr.key})")

    def levels(self):
        return [v.levels() or [] for v in self._fr.vecs]

    def unique(self):
        return self._x(f"(unique {self._fr.key})")

    def table(self):
        return self._x(f"(table {self._fr.key})")

    def cbind(self, other):
        return self._x(f"(cbind {self._fr.key} {other._fr.key})")

    def rbind(self, other):
        return self._x(f"(rbind {self._fr.key} {other._fr.key})")

    def merge(self, other, all_x=False, all_y=False):
        return self._x(f"(merge {self._fr.key} {other._fr.key} "
                       f"{all_x} {all_y} [] [] 'auto')")

    def sort(self, by, ascending=True):
        cols = by if isinstance(by, list) else [by]
        idx = " ".join(str(self._fr.col_idx(c) if isinstance(c, str) else c)
                       for c in cols)
        asc = " ".join("1" if ascending else "0" for _ in cols)
        return self._x(f"(sort {self._fr.key} [{idx}] [{asc}])")

    def group_by(self, by):
        return GroupBy(self, by)

    def split_frame(self, ratios=(0.75,), seed=-1):
        rng = np.random.default_rng(seed if seed > 0 else None)
        n = self.nrows
        u = rng.random(n)
        edges = np.cumsum(list(ratios))
        outs = []
        prev = 0.0
        for e in list(edges) + [1.0]:
            idx = np.nonzero((u >= prev) & (u < e))[0]
            lst = " ".join(str(i) for i in idx)
            outs.append(self._x(f"(rows {self._fr.key} [{lst}])"))
            prev = e
        return outs

    def impute(self, column=0, method="mean"):
        ci = self._fr.col_idx(column) if isinstance(column, str) else column
        return self._x(f'(h2o.impute {self._fr.key} {ci} "{method}")')

    def scale(self, center=True, scale=True):
        return self._x(f"(scale {self._fr.key} {center} {scale})")

    def runif(self, seed=-1):
        return self._x(f"(h2o.runif {self._fr.key} {seed})")

    def __repr__(self):
        return f"<H2OFrame {self._fr!r}>"


class GroupBy:
    """h2o-py GroupBy builder → one (GB …) rapids call on .get_frame()."""

    def __init__(self, frame: H2OFrame, by):
        self._frame = frame
        by = by if isinstance(by, list) else [by]
        self._by = [frame._fr.col_idx(c) if isinstance(c, str) else c
                    for c in by]
        self._aggs = []

    def _add(self, op, col):
        ci = self._frame._fr.col_idx(col) if isinstance(col, str) else col
        self._aggs.append((op, ci))
        return self

    def sum(self, col): return self._add("sum", col)
    def mean(self, col): return self._add("mean", col)
    def count(self): return self._add("nrow", 0)
    def min(self, col): return self._add("min", col)
    def max(self, col): return self._add("max", col)
    def sd(self, col): return self._add("sd", col)
    def var(self, col): return self._add("var", col)
    def median(self, col): return self._add("median", col)

    def get_frame(self):
        by = " ".join(str(b) for b in self._by)
        aggs = " ".join(f'"{op}" {ci} "rm"' for op, ci in self._aggs)
        return self._frame._x(f"(GB {self._frame._fr.key} [{by}] {aggs})")


# ---------------------------------------------------------------------------
# h2o-py H2OFrame surface, continued: string ops, time ops, statistics,
# cumulative/rank transforms — each a thin AST builder over the same
# Rapids prims the reference client emits (h2o-py/h2o/frame.py).
def _extend_h2oframe():
    F = H2OFrame

    def _qstr(v):
        """Rapids string literal: the parser unescapes backslash
        sequences, so literal backslashes and quotes must be escaped or
        regex patterns like \\d+ silently lose their backslash."""
        return '"' + str(v).replace("\\", "\\\\").replace('"', '\\"') + '"'

    def _unary(op):
        def m(self):
            return self._x(f"({op} {self._fr.key})")
        m.__name__ = op
        return m

    # string munging (h2o-py frame.py gsub/sub/trim/... emit these ASTs)
    for name, op in [("tolower", "tolower"), ("toupper", "toupper"),
                     ("trim", "trim"), ("lstrip", "lstrip"),
                     ("rstrip", "rstrip"), ("nchar", "strlen")]:
        setattr(F, name, _unary(op))

    def gsub(self, pattern, replacement, ignore_case=False):
        return self._x(f'(replaceall {self._fr.key} {_qstr(pattern)} '
                       f'{_qstr(replacement)} {ignore_case})')

    def sub(self, pattern, replacement, ignore_case=False):
        return self._x(f'(replacefirst {self._fr.key} {_qstr(pattern)} '
                       f'{_qstr(replacement)} {ignore_case})')

    def strsplit(self, pattern):
        return self._x(f'(strsplit {self._fr.key} {_qstr(pattern)})')

    def substring(self, start_index, end_index=1000000):
        return self._x(f"(substring {self._fr.key} {start_index} "
                       f"{end_index})")

    def countmatches(self, pattern):
        pats = pattern if isinstance(pattern, list) else [pattern]
        lst = " ".join(_qstr(p) for p in pats)
        return self._x(f"(countmatches {self._fr.key} [{lst}])")

    def grep(self, pattern, ignore_case=False, invert=False,
             output_logical=False):
        return self._x(f'(grep {self._fr.key} {_qstr(pattern)} '
                       f"{ignore_case} {invert} {output_logical})")

    F.gsub, F.sub, F.strsplit = gsub, sub, strsplit
    F.substring, F.countmatches, F.grep = substring, countmatches, grep

    # time accessors (AstTime family)
    for name in ("year", "month", "day", "hour", "minute", "second",
                 "week", "dayOfWeek"):
        setattr(F, name, _unary(name))

    # cumulative + rounding (AstCumu / AstRound)
    for name in ("cumsum", "cumprod", "cummax", "cummin"):
        setattr(F, name, _unary(name))

    def round(self, digits=0):
        return self._x(f"(round {self._fr.key} {digits})")

    def signif(self, digits=6):
        return self._x(f"(signif {self._fr.key} {digits})")

    F.round, F.signif = round, signif

    # statistics
    def cor(self, y=None, use="complete.obs", method="Pearson"):
        other = y._fr.key if isinstance(y, H2OFrame) else self._fr.key
        return self._x(f'(cor {self._fr.key} {other} "{use}" '
                       f'"{method}")')

    def entropy(self):
        # per-row Shannon entropy of string values (AstEntropy)
        return self._x(f"(entropy {self._fr.key})")

    def kurtosis(self, na_rm=True):
        return rapids_exec(f"(kurtosis {self._fr.key} {na_rm})")

    def skewness(self, na_rm=True):
        return rapids_exec(f"(skewness {self._fr.key} {na_rm})")

    def hist(self, breaks="sturges", plot=False):
        if isinstance(breaks, str):
            b = f'"{breaks}"'
        elif isinstance(breaks, (list, tuple)):
            b = "[" + " ".join(str(float(x)) for x in breaks) + "]"
        else:
            b = str(breaks)
        return self._x(f"(hist {self._fr.key} {b})")

    def na_omit(self):
        return self._x(f"(na.omit {self._fr.key})")

    def nacnt(self):
        out = rapids_exec(f"(naCnt {self._fr.key})")
        return out if isinstance(out, list) else [out]

    def match(self, table):
        vals = " ".join(_qstr(v) if isinstance(v, str) else str(v)
                        for v in table)
        return self._x(f"(match {self._fr.key} [{vals}])")

    def cut(self, breaks, labels=None, include_lowest=False, right=True,
            dig_lab=3):
        bs = " ".join(str(float(b)) for b in breaks)
        # prim signature: (cut fr breaks labels include.lowest right digits)
        lab = ("[" + " ".join(_qstr(v) for v in labels) + "]"
               if labels else "[]")
        return self._x(f"(cut {self._fr.key} [{bs}] {lab} "
                       f"{include_lowest} {right} {dig_lab})")

    def which(self):
        return self._x(f"(which {self._fr.key})")

    def any_na(self):
        return bool(rapids_exec(f"(any.na {self._fr.key})"))

    def t(self):
        return self._x(f"(t {self._fr.key})")

    F.cor, F.entropy, F.kurtosis, F.skewness = cor, entropy, kurtosis, skewness
    F.hist, F.na_omit, F.nacnt, F.match = hist, na_omit, nacnt, match
    F.cut, F.which, F.any_na, F.t = cut, which, any_na, t

    def rep_len(self, length_out):
        return self._x(f"(rep_len {self._fr.key} {length_out})")

    def topn(self, column=0, nPercent=10, grabTopN=-1):
        """h2o-py semantics: grabTopN=-1 -> top N%, 1 -> bottom N%;
        the prim's flag is bottom=truthy, hence the inversion."""
        ci = self._fr.col_idx(column) if isinstance(column, str) else column
        bottom = 1 if grabTopN > 0 else 0
        return self._x(f"(topn {self._fr.key} {ci} {nPercent} {bottom})")

    F.rep_len, F.topn = rep_len, topn


_extend_h2oframe()
del _extend_h2oframe
