"""Deterministic fault injection — the standing chaos layer.

Elastic membership (deploy/membership.py) only counts as robustness if a
fault can be produced ON DEMAND, at a deterministic point, in a test that
runs on every commit. This module is that lever: a small rule engine that
injects failures at named points in the replay channel, the worker loop
and the serving dispatch path. It ships in the tree (not in tests/) so a
staging cloud can run the same faults via env.

Spec grammar (env `H2O3_CHAOS`, or `install()` from a test):

    rule[;rule...]
    rule  := key=value[,key=value...]
    keys  := point   (required: where to fire, see POINTS below)
             action  (required: drop | delay | sever | kill | fail)
             worker  (optional int: only when the point names this worker)
             after   (skip the first N matching hits; default 0)
             times   (fire at most N times; default 1)
             delay_s (sleep length for action=delay; default 0.2)

Example: `H2O3_CHAOS="point=replay.send,worker=1,after=3,action=sever"`
severs worker 1's replay socket immediately before the 4th frame the
coordinator would send it.

Points wired in the tree (each caller documents its own semantics):
  replay.send        coordinator, before sending a broadcast/collect frame
                       (sever closes the socket, drop skips the send,
                        delay sleeps first)
  collect.ack        worker, before answering a collect op (delay/drop)
  worker.replay      worker, before replaying a request (kill = hard
                       process exit — the "lost pod")
  microbatch.dispatch  serving, inside the coalesced dispatch (fail
                       raises EpochChanged so the epoch-retry path runs)
  mrtask.dispatch    parallel, inside a device dispatch (fail as above)

Determinism: rules carry no randomness — `after`/`times` counters make
the Nth hit fire, every run. The spec is parsed once at install; when no
rules are installed every hook is one module-global read.
"""

from __future__ import annotations

import os
import threading
import time

from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.utils.env import env_str

ACTIONS = ("drop", "delay", "sever", "kill", "fail")

INJECTIONS = _om.counter(
    "h2o3_chaos_injections_total",
    "faults the chaos layer actually injected, by point and action "
    "(zero outside chaos runs — a nonzero rate in production means "
    "H2O3_CHAOS leaked into a real deployment)")


class ChaosFault(RuntimeError):
    """Raised by action=fail at points whose caller did not map the
    failure to a domain exception."""


class _Rule:
    __slots__ = ("point", "action", "worker", "after", "times",
                 "delay_s", "_hits", "_fired")

    def __init__(self, point, action, worker=None, after=0, times=1,
                 delay_s=0.2):
        if action not in ACTIONS:
            raise ValueError(f"chaos action {action!r} not in {ACTIONS}")
        self.point = point
        self.action = action
        self.worker = worker
        self.after = int(after)
        self.times = int(times)
        self.delay_s = float(delay_s)
        self._hits = 0
        self._fired = 0

    def match(self, point: str, worker) -> bool:
        if point != self.point:
            return False
        if self.worker is not None and worker != self.worker:
            return False
        self._hits += 1
        if self._hits <= self.after or self._fired >= self.times:
            return False
        self._fired += 1
        return True

    def to_dict(self) -> dict:
        return {"point": self.point, "action": self.action,
                "worker": self.worker, "after": self.after,
                "times": self.times, "fired": self._fired}


_RULES: list = []
_LOCK = threading.Lock()


def parse(spec: str) -> list:
    """Parse a spec string into rules (see module grammar)."""
    rules = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        kv = {}
        for item in part.split(","):
            k, _, v = item.partition("=")
            kv[k.strip()] = v.strip()
        if "point" not in kv or "action" not in kv:
            raise ValueError(f"chaos rule needs point= and action=: {part!r}")
        rules.append(_Rule(
            kv["point"], kv["action"],
            worker=int(kv["worker"]) if kv.get("worker") else None,
            after=int(kv.get("after") or 0),
            times=int(kv.get("times") or 1),
            delay_s=float(kv.get("delay_s") or 0.2)))
    return rules


def _chaos_spec() -> str:
    """The H2O3_CHAOS rule spec ("" = chaos disabled) — declaration
    site for the variable; install()/install_from_env() both read it."""
    return env_str("H2O3_CHAOS", "")


def install(spec: str | None = None):
    """(Re)install rules from `spec` (or H2O3_CHAOS when None). The test
    API: install at setup, reset() at teardown."""
    global _RULES
    rules = parse(spec if spec is not None else _chaos_spec())
    with _LOCK:
        _RULES = rules
    return rules


def reset():
    global _RULES
    with _LOCK:
        _RULES = []


def active() -> bool:
    return bool(_RULES)


def rules() -> list:
    with _LOCK:
        return [r.to_dict() for r in _RULES]


def _fire(rule: _Rule, point: str):
    INJECTIONS.inc(point=point, action=rule.action)
    from h2o3_tpu.utils import log as _ulog
    _ulog.warn("chaos: injecting %s at %s (worker=%s)", rule.action,
               point, rule.worker)


def at(point: str, worker=None):
    """The coordinator-side hook: returns the matched rule's action dict
    ({"action": ..., "delay_s": ...}) or None. `delay` sleeps HERE so
    simple callers need no handling; drop/sever/kill/fail are returned
    for the caller to apply (it owns the socket / process / exception)."""
    if not _RULES:
        return None
    with _LOCK:
        hit = next((r for r in _RULES if r.match(point, worker)), None)
    if hit is None:
        return None
    _fire(hit, point)
    if hit.action == "delay":
        time.sleep(hit.delay_s)
        return None
    return {"action": hit.action, "delay_s": hit.delay_s}


def maybe_raise(point: str, worker=None, exc=None):
    """Dispatch-path hook: action=fail raises (`exc` factory result, or
    ChaosFault); kill hard-exits the process; delay sleeps. One global
    read when chaos is idle — safe on hot paths."""
    if not _RULES:
        return
    act = at(point, worker=worker)
    if act is None:
        return
    if act["action"] == "kill":
        os._exit(17)
    if act["action"] == "fail":
        raise (exc() if exc is not None
               else ChaosFault(f"chaos fail at {point}"))


def install_from_env():
    """Called at server/worker start: arms H2O3_CHAOS when present."""
    if _chaos_spec():
        install()
