"""Deployment — h2o-k8s / h2o-helm / h2o-hadoop analog for TPU pods."""
