"""Multi-host bootstrap — the h2o-k8s/H2OCluster + hadoop driver analog.

The reference forms a multi-node cloud via UDP gossip inside a k8s
StatefulSet (h2o-k8s/) or a YARN application (h2o-hadoop-common/). A TPU
pod slice is simpler and stricter: every host runs the SAME program,
`jax.distributed.initialize` wires the hosts into one runtime (GKE/TPU-VM
environments inject the coordinator automatically), and the global device
mesh spans all chips; collectives ride ICI within a slice and DCN across
slices — no gossip, no Paxos, membership is fixed by the slice topology.

Call `bootstrap()` first thing on every host of a multi-host deployment
(deploy/k8s/*.yaml does it via the container entrypoint). On a single
host it is a no-op, so the same entrypoint serves laptops and v5p-32 pods.
"""

from __future__ import annotations

import io
import os

from h2o3_tpu.utils import env as _uenv
from h2o3_tpu.utils.env import (env_bool, env_float, env_int, env_str,
                                process_id)


def _coordinator_address() -> str:
    """host:port of process 0 ("" when unset — single-host / TPU-env
    autodetection). The one H2O3_COORDINATOR_ADDRESS declaration site."""
    return env_str("H2O3_COORDINATOR_ADDRESS", "")


def _num_processes() -> int:
    """World size for explicit (non-autodetected) multi-host wiring.
    0 = unset: bootstrap() raises rather than silently forming a
    1-process cloud with a coordinator address configured."""
    return env_int("H2O3_NUM_PROCESSES", 0)


def is_multihost() -> bool:
    """True when a multi-host launch environment is detected (TPU pod
    env vars or explicit coordinator address)."""
    return bool(
        _coordinator_address()
        or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
        or (os.environ.get("TPU_WORKER_HOSTNAMES")
            and int(os.environ.get("TPU_WORKER_COUNT", "1") or 1) > 1))


def assisted_clustering_env() -> dict:
    """h2o-k8s assisted clustering analog (H2OAssistedClusteringEndpoint):
    inside a k8s StatefulSet, derive the coordinator address, world size
    and this pod's rank from the headless-service DNS convention instead
    of requiring the manifest to wire H2O3_* explicitly.

    Uses the downward-API hostname `<set>-<ordinal>` plus
    H2O3_K8S_SERVICE (headless service name) and H2O3_K8S_REPLICAS:
    coordinator = <set>-0.<service>:8476, process_id = <ordinal>.
    Returns {} when not running under that convention."""
    svc = env_str("H2O3_K8S_SERVICE", "")
    replicas = env_str("H2O3_K8S_REPLICAS", "").strip()
    host = os.environ.get("HOSTNAME", "")
    if not (svc and replicas.isdigit() and "-" in host):
        return {}
    base, _, ordinal = host.rpartition("-")
    if not ordinal.isdigit():
        return {}
    # 8476 matches the StatefulSet/Service declared coordinator port
    port = env_str("H2O3_COORDINATOR_PORT", "8476")
    ns = env_str("H2O3_K8S_NAMESPACE", "")
    fqdn = f"{base}-0.{svc}" + (f".{ns}.svc.cluster.local" if ns else "")
    return {"H2O3_COORDINATOR_ADDRESS": f"{fqdn}:{port}",
            "H2O3_NUM_PROCESSES": replicas,
            "H2O3_PROCESS_ID": ordinal}


def bootstrap(n_rows_shards=None, n_model_shards: int = 1):
    """Initialize the distributed runtime (when applicable) and form the
    global cloud over every visible chip on every host.

    Env (k8s manifests set these from the StatefulSet):
      H2O3_COORDINATOR_ADDRESS  host:port of process 0
      H2O3_NUM_PROCESSES        world size
      H2O3_PROCESS_ID           this host's rank
    GKE TPU slices need none of them — jax.distributed.initialize()
    autodetects from the TPU metadata the same way MEGASCALE jobs do.
    """
    import jax

    # assisted clustering: fill the H2O3_* wiring from StatefulSet DNS
    # when the manifest didn't set it explicitly
    if not _coordinator_address():
        # plain assignment: a present-but-EMPTY manual override means
        # "use assisted mode", and setdefault would leave it empty
        for k, v in assisted_clustering_env().items():
            os.environ[k] = v

    if is_multihost():
        addr = _coordinator_address()
        if addr:
            nproc = _num_processes()
            if nproc <= 0:
                raise RuntimeError(
                    "H2O3_COORDINATOR_ADDRESS is set but "
                    "H2O3_NUM_PROCESSES is not — explicit multi-host "
                    "wiring needs the world size")
            if not _uenv.is_set("H2O3_PROCESS_ID"):
                # keep the old KeyError's loudness: four pods all
                # defaulting to rank 0 fail far from the root cause
                raise RuntimeError(
                    "H2O3_COORDINATOR_ADDRESS is set but "
                    "H2O3_PROCESS_ID is not — every pod of an explicit "
                    "multi-host wiring must declare its rank")
            jax.distributed.initialize(
                coordinator_address=addr,
                num_processes=nproc,
                process_id=process_id())
        else:
            jax.distributed.initialize()   # TPU-env autodetection
    import h2o3_tpu
    cloud = h2o3_tpu.init(n_rows_shards=n_rows_shards,
                          n_model_shards=n_model_shards)
    return cloud


# ---------------------------------------------------------------------------
# SPMD request replay. A multi-controller JAX runtime requires EVERY process
# to issue the same computations in the same order — a worker that idles
# would deadlock the first collective process 0 launches. So process 0
# broadcasts each mutating REST request (path, method, params) to the
# workers BEFORE handling it locally, and each worker replays the identical
# request against the same route table. Identical requests → identical API
# calls → identical jitted programs → matching collectives. (The reference
# has no analog: its nodes exchange data via RPC; SPMD replicates control.)
# Requests replay serially in arrival order; concurrent builds are
# serialized by the broadcast lock.
#
# Channel security: frames are JSON (never pickle — a spoofed peer must not
# get arbitrary-object deserialization) authenticated with HMAC-SHA256 under
# a shared secret (H2O3_CLUSTER_SECRET, injected by the StatefulSet secret).
# Connection setup is a mutual challenge-response — the coordinator proves
# freshness to the worker and vice versa — and subsequent frames are keyed
# by a per-session key derived from both nonces with a monotone sequence
# number, so neither a rogue pod that races a worker's slot nor a replayed
# capture of an earlier session is accepted.
_BCAST_PORT_OFFSET = 2
_MAX_FRAME = 64 * 1024 * 1024


def _ack_timeout() -> float:
    """Upper bound (seconds) on any single wait the Broadcaster performs
    under its lock. The broadcast ack barrier is intentionally lockstep —
    but an UNBOUNDED lockstep wait means one wedged worker freezes every
    REST thread behind the broadcast lock forever (the R008 class the
    static analyzer flags). Bounded, the failure is a loud RuntimeError
    after this deadline instead of a silent server freeze."""
    return env_float("H2O3_REPLAY_ACK_TIMEOUT_S", 120.0)


def _ack_timeouts_counter():
    from h2o3_tpu.obs import metrics as _om
    return _om.counter("h2o3_replay_ack_timeouts_total",
                       "replay-channel ack waits that hit the "
                       "H2O3_REPLAY_ACK_TIMEOUT_S deadline (a worker "
                       "stopped acking: SPMD replay is wedged)")


def _cluster_secret() -> bytes:
    s = env_str("H2O3_CLUSTER_SECRET", "")
    if not s:
        raise RuntimeError(
            "H2O3_CLUSTER_SECRET is required for the multi-host replay "
            "channel (the k8s chart injects it from a Secret; for local "
            "clouds export any shared random string)")
    return s.encode()


def _send_frame(sock, key: bytes, obj, timeout=None) -> None:
    """Send one HMAC frame. `timeout` bounds the send: a peer that
    stopped reading (full TCP window) raises socket.timeout instead of
    blocking the caller — required wherever the caller holds a lock."""
    import hashlib
    import hmac
    import json
    import struct
    payload = json.dumps(obj).encode()
    tag = hmac.new(key, payload, hashlib.sha256).digest()
    if timeout is not None:
        sock.settimeout(timeout)
    try:
        sock.sendall(struct.pack("!I", len(payload)) + tag + payload)
    finally:
        if timeout is not None:
            sock.settimeout(None)


def _decode_frame(buf: bytes, key: bytes):
    """Decode one length-prefixed HMAC frame from `buf`. Returns
    (message, remaining_bytes) once a whole frame is present, None while
    more bytes are needed — the single source of truth for the wire
    format, shared by the blocking and buffered/resumable readers."""
    import hashlib
    import hmac
    import json
    import struct
    if len(buf) < 4:
        return None
    (ln,) = struct.unpack("!I", buf[:4])
    if ln > _MAX_FRAME:
        raise RuntimeError(f"replay channel: oversized frame ({ln} bytes)")
    need = 4 + 32 + ln
    if len(buf) < need:
        return None
    tag, payload = buf[4:36], buf[36:need]
    want = hmac.new(key, payload, hashlib.sha256).digest()
    if not hmac.compare_digest(tag, want):
        raise RuntimeError("replay channel: HMAC mismatch (untrusted peer?)")
    return json.loads(payload), buf[need:]


def _recv_frame(sock, key: bytes):
    buf = b""
    while True:
        out = _decode_frame(buf, key)
        if out is not None:
            return out[0]
        part = sock.recv(65536)
        if not part:
            return None       # EOF (possibly mid-frame)
        buf += part


def _session_key(secret: bytes, nonce_c: str, nonce_w: str) -> bytes:
    import hashlib
    import hmac
    return hmac.new(secret, f"{nonce_c}:{nonce_w}".encode(),
                    hashlib.sha256).digest()


def _form_timeout_s() -> float:
    """Bound on the coordinator's initial cloud-formation accept loop —
    a missing worker pod must surface as a loud error, not an accept()
    parked forever (the R013 unbounded-network-wait class)."""
    return env_float("H2O3_CLOUD_FORM_TIMEOUT_S", 600.0)


def _reconnect_window_s() -> float:
    """How long a worker whose coordinator socket dropped keeps retrying
    the handshake before exiting nonzero. 0 disables reconnection (the
    pre-elastic behavior: an orphaned worker exits its loop cleanly).
    The old read had two defaults (unset → 60, empty → 0); the typed
    accessor collapses both to the documented 60."""
    return env_float("H2O3_REPLAY_RECONNECT_S", 60.0)


def _challenge_peer(conn, secret: bytes):
    """Coordinator side of the mutual challenge-response on one fresh
    connection (no welcome — the caller validates the peer id and sends
    it under the session key). Returns (hello, session_key)."""
    import secrets as _secrets
    conn.settimeout(10.0)
    nonce_c = _secrets.token_hex(16)
    _send_frame(conn, secret, {"challenge": nonce_c})
    hello = _recv_frame(conn, secret)
    if (not hello or hello.get("echo") != nonce_c
            or not isinstance(hello.get("hello"), int)):
        raise RuntimeError("bad hello")
    key = _session_key(secret, nonce_c, str(hello.get("nonce", "")))
    return hello, key


class _ReplayHandler:
    """Duck-typed stand-in for the HTTP handler. Routes need
    _params/_send/_error; byte-streaming routes (DownloadDataset, mojo /
    POJO downloads) additionally drive the raw http.server surface, so
    those are no-ops writing to a sink — on workers the device readback
    is the collective part, the bytes only matter on process 0."""

    server = None          # workers hold no HTTP server / broadcaster:
    #                        handlers must getattr their way to both

    def __init__(self, params):
        self._p = dict(params)
        self.out = None
        self.wfile = io.BytesIO()
        self.headers: dict = {}

    def _params(self):
        return dict(self._p)

    def _send(self, obj, code=200, extra_headers=None):
        self.out = obj

    def _error(self, msg, code=400):
        self.out = {"error": str(msg), "code": code}

    def _unavailable(self, qf):
        self.out = {"error": str(qf), "code": 503}

    def send_response(self, code):
        pass

    def send_header(self, k, v):
        pass

    def end_headers(self):
        pass


def replay_request(method: str, path: str, params: dict):
    """Execute a REST request against the local route table (worker side)."""
    from h2o3_tpu.api import server as _srv
    h = _ReplayHandler(params)
    for pat, m, fn in _srv.ROUTES:
        if m != method:
            continue
        mm = pat.fullmatch(path)
        if mm:
            fn(h, *mm.groups())
            return h.out
    return {"error": f"no route {method} {path}"}


class Broadcaster:
    """Process-0 side: fan each request out to every worker and wait for
    receipt acks (ordering barrier) before local dispatch. Accepts only
    peers that pass the mutual challenge-response under the cluster
    secret; unauthenticated connections are dropped and the slot re-armed."""

    def __init__(self, n_workers: int, port: int, keep_listener=False):
        import socket
        import time as _time
        from h2o3_tpu.analysis.lockdep import make_lock
        secret = _cluster_secret()
        self._secret = secret
        self._lock = make_lock("replay_channel")
        self._conns = []          # [(sock, session_key)]
        self._owed: list = []     # per-conn acks abandoned by a timed-out
        self._bufs: list = []     # collect; drained before the next send
        self._dead: list = []     # peers that errored: excluded from
        self._pids: list = []     # worker process ids, by slot
        self._seq = 0             # collects (broadcast still fails loudly)
        self._closed = False
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", port))
        srv.listen(max(n_workers, 1))
        # polling accept with an overall formation deadline: a worker pod
        # that never comes up is a loud error within
        # H2O3_CLOUD_FORM_TIMEOUT_S, not an accept() parked forever
        srv.settimeout(1.0)
        form_deadline = _time.monotonic() + _form_timeout_s()
        seen = set()
        while len(self._conns) < n_workers:
            if _time.monotonic() > form_deadline:
                srv.close()
                raise RuntimeError(
                    f"replay channel: only {len(self._conns)} of "
                    f"{n_workers} workers joined within "
                    f"{_form_timeout_s():g}s (H2O3_CLOUD_FORM_TIMEOUT_S)")
            try:
                conn, addr = srv.accept()
            except socket.timeout:
                continue
            try:
                hello, key = _challenge_peer(conn, secret)
                if hello["hello"] in seen:
                    raise RuntimeError(f"bad hello from {addr}")
                _send_frame(conn, key, {"welcome": hello["hello"]})
                conn.settimeout(None)
                seen.add(hello["hello"])
                self._conns.append((conn, key))
                self._owed.append(0)
                self._bufs.append(b"")
                self._dead.append(False)
                self._pids.append(hello["hello"])
            except Exception as ex:  # noqa: BLE001 — drop peer, re-arm slot
                from h2o3_tpu.utils import log as _ulog
                _ulog.warn("replay channel: rejected peer %s: %s",
                           addr, ex)
                conn.close()
        # elastic membership (deploy/membership.ElasticBroadcaster) keeps
        # the listener open to admit joining/replacement workers; the
        # fixed-membership base closes it — the reference's
        # Paxos.lockCloud() moment
        if keep_listener:
            self._srv = srv
        else:
            srv.close()
            self._srv = None

    def live_pids(self) -> list:
        """Process ids of workers still in the broadcast set — the
        candidate share-holders for a distributed-parse fan-out."""
        with self._lock:
            return [p for i, p in enumerate(self._pids)
                    if not self._dead[i]]

    def _recv_frame_at(self, i: int, timeout=None):
        """Like _recv_frame but RESUMABLE: bytes consumed before a timeout
        stay in the per-conn buffer, so abandoning a slow ack mid-frame
        never desyncs the stream (a later drain re-enters and finishes
        the same frame). `timeout` is a whole-frame DEADLINE, not a
        per-recv idle limit — a worker trickling a large frame cannot
        hold the caller past it. Raises socket.timeout on expiry."""
        import socket as _socket
        import time as _time
        c, key = self._conns[i]
        deadline = None if timeout is None else _time.monotonic() + timeout
        try:
            while True:
                out = _decode_frame(self._bufs[i], key)
                if out is not None:
                    msg, self._bufs[i] = out
                    if isinstance(msg, dict) and "div" in msg:
                        # divergence-sanitizer digests riding the ack
                        # (analysis/divergence): peel off and compare —
                        # never let a sanitizer fault break the channel
                        try:
                            from h2o3_tpu.analysis import \
                                divergence as _dvg
                            pid = self._pids[i] \
                                if i < len(self._pids) else i
                            _dvg.note_remote(pid, msg.get("div"))
                        except Exception:   # noqa: BLE001
                            pass
                    return msg
                if deadline is not None:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        raise _socket.timeout("collect deadline")
                    c.settimeout(remaining)
                part = c.recv(65536)
                if not part:
                    return None           # peer gone
                self._bufs[i] = self._bufs[i] + part
        finally:
            c.settimeout(None)

    def _drain_owed(self, i: int, deadline: float):
        """Consume acks a timed-out collect left in flight, so the next
        broadcast's ack barrier lines up with its own sequence number.
        Used by the (intentionally lockstep) broadcast path only; collect
        absorbs stale acks inside its own bounded recv loop. `deadline`
        (monotonic) bounds the whole drain: the caller holds the
        broadcast lock, so spinning past it would wedge every thread."""
        import time as _time
        while self._owed[i] > 0:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                self._raise_wedged(i, "owed-ack drain")
            if self._recv_frame_at(i, timeout=remaining) is None:
                break                            # peer gone: stop spinning
            # h2o3-ok: R003 only reachable from broadcast(), which holds self._lock for the whole send+drain sequence
            self._owed[i] -= 1

    def _raise_wedged(self, i: int, what: str):
        """A worker blew the ack deadline while the broadcast lock is
        held: count it and fail LOUDLY. SPMD replay cannot continue with
        a desynced worker, and an unbounded wait here would freeze every
        REST thread — a RuntimeError surfaces as a 500 on this request
        while /metrics keeps answering."""
        _ack_timeouts_counter().inc()
        raise RuntimeError(
            f"replay channel: worker {i} unresponsive for "
            f"{_ack_timeout():g}s during {what} — SPMD replay is wedged "
            "(H2O3_REPLAY_ACK_TIMEOUT_S bounds this wait)")

    def broadcast(self, method: str, path: str, params: dict, trace=None,
                  sampled=False):
        import socket as _socket
        import time as _time
        # watchdog: the ack barrier is the classic wedge point — a worker
        # that stopped acking stalls every REST thread behind this lock.
        # The watch deadline must undercut H2O3_REPLAY_ACK_TIMEOUT_S (the
        # wait's own bound, after which the context EXITS): at half the
        # ack timeout the sentinel captures the cluster JStack while the
        # barrier is still stuck, not after it already raised
        from h2o3_tpu.obs import watchdog as _wd
        with _wd.watch("replay", desc=f"broadcast {method} {path}",
                       deadline_s=min(_ack_timeout() / 2,
                                      _wd._stall_s()),
                       trace=trace), \
                self._lock:
            self._seq += 1
            deadline = _time.monotonic() + _ack_timeout()
            msg = {"seq": self._seq, "method": method, "path": path,
                   "params": params}
            if trace:
                # originating request's trace id: workers replay under it
                # so their spans stitch into GET /3/Trace/{id}
                msg["trace"] = trace
            if sampled:
                # X-H2O3-Sample pin travels too: each worker's flight
                # recorder retains its fragment of the pinned trace
                msg["sampled"] = 1
            try:
                for i, (c, key) in enumerate(self._conns):
                    self._drain_owed(i, deadline)
                    # deduct from the SHARED deadline: N workers each
                    # granted a fresh full timeout would stretch the
                    # lock-hold bound to N×timeout
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        self._raise_wedged(i, "broadcast send")
                    _send_frame(c, key, msg, timeout=remaining)
                for i in range(len(self._conns)):
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        self._raise_wedged(i, "broadcast ack barrier")
                    # receipt ack: order barrier. Explicit check, not an
                    # assert: a peer dying mid-broadcast (EOF → None) or
                    # answering the wrong seq must fail identically under
                    # python -O, and desynced replay may not continue
                    ack = self._recv_frame_at(i, timeout=remaining)
                    if not ack or ack.get("ack") != self._seq:
                        raise RuntimeError(
                            f"replay channel: bad broadcast ack from "
                            f"worker {i} (got {ack!r}, want seq "
                            f"{self._seq}) — SPMD replay is desynced")
            except (_socket.timeout, TimeoutError):
                _ack_timeouts_counter().inc()
                raise RuntimeError(
                    f"replay channel: broadcast seq {self._seq} not "
                    f"acked within {_ack_timeout():g}s — SPMD replay is "
                    "wedged (H2O3_REPLAY_ACK_TIMEOUT_S bounds this "
                    "wait)") from None
            # the seq identifies this request to the divergence
            # sanitizer: the dispatcher scopes the local execution under
            # it and workers stamp their replay digests with it
            return self._seq

    def collect(self, op: str, timeout: float = 2.0) -> list:
        """Gather per-worker observability state (TimelineSnapshot's
        cloud-wide assembly): a collect frame replaces the request replay
        and the worker answers its ack WITH the data — same socket, same
        sequence numbers, so ordering against replayed requests holds.

        Bounded wait: a worker stuck inside a long request replay won't
        read the collect frame until it finishes, and /3/Timeline is
        exactly the endpoint needed while something is slow — so each
        worker gets `timeout` seconds, after which its slot returns None
        and its still-owed ack is drained before the next send. A peer
        that errors (EOF, HMAC, bad seq) is marked dead and excluded from
        future collects WITHOUT touching the other workers' ack
        accounting — one broken worker plus a scrape must not poison the
        replay channel for the healthy ones."""
        import socket as _socket
        import time as _time
        with self._lock:
            self._seq += 1
            msg = {"seq": self._seq, "op": op}
            sent = [False] * len(self._conns)
            for i, (c, key) in enumerate(self._conns):
                if self._dead[i]:
                    continue
                # ALWAYS send to live peers — a skipped send would leave a
                # hole in that worker's sequence stream and kill it on the
                # next frame ("bad seq"). Stale owed acks from earlier
                # timed-out collects are absorbed in the recv phase below,
                # inside this round's deadline.
                try:
                    # bounded send: a peer that stopped reading must not
                    # block the scrape (we hold the broadcast lock here)
                    _send_frame(c, key, msg, timeout=timeout)
                    sent[i] = True
                except Exception:   # noqa: BLE001 — peer broken, isolate it
                    self._dead[i] = True
            out = []
            for i in range(len(self._conns)):
                if not sent[i]:
                    out.append(None)
                    continue
                deadline = _time.monotonic() + timeout
                try:
                    while True:
                        remaining = deadline - _time.monotonic()
                        if remaining <= 0:
                            raise _socket.timeout("collect deadline")
                        ack = self._recv_frame_at(i, timeout=remaining)
                        if ack and self._owed[i] > 0 \
                                and ack.get("ack") != self._seq:
                            # stale ack from an earlier timed-out collect:
                            # retire the debt, keep waiting for ours
                            self._owed[i] -= 1
                            continue
                        break
                    if not ack or ack.get("ack") != self._seq:
                        raise RuntimeError(
                            f"replay channel: bad collect ack from {i}")
                    out.append(ack.get("data"))
                except (_socket.timeout, TimeoutError):
                    self._owed[i] += 1    # lagging worker: ack still due
                    out.append(None)
                except Exception:   # noqa: BLE001 — dead peer: isolate, keep going
                    self._dead[i] = True
                    out.append(None)
            return out


def _collect_local(op: str):
    """Worker-side observability snapshot for Broadcaster.collect."""
    try:
        if op == "ping":
            # membership heartbeat: liveness + this worker's view of the
            # cloud epoch (deploy/membership heartbeat loop)
            from h2o3_tpu.deploy import membership as _mb
            from h2o3_tpu.obs import timeline as _tl
            return {"host": _tl.host_id(), "ok": True,
                    "epoch": _mb.MEMBERSHIP.epoch}
        if op == "timeline":
            from h2o3_tpu.obs import timeline as _tl
            return {"host": _tl.host_id(),
                    "spans": _tl.SPANS.snapshot(limit=512)}
        if op == "metrics":
            from h2o3_tpu.obs import metrics as _m
            from h2o3_tpu.obs import timeline as _tl
            return {"host": _tl.host_id(),
                    "metrics": _m.REGISTRY.to_dict()}
        if op == "usage":
            # GET /3/Usage cluster merge: this host's attribution ledger
            # + HBM occupancy (the snapshot carries its own host id)
            from h2o3_tpu.obs import usage as _us
            return _us.usage_snapshot()
        if op == "cloudhealth":
            # GET /3/CloudHealth cluster merge: a FRESH local pressure
            # evaluation per collect, so the merged document never
            # reports a stale worker dimension
            from h2o3_tpu.obs import usage as _us
            return _us.evaluate_pressure()
        if op.startswith("trace:"):
            # GET /3/Trace/{id} read-through: this host's ring spans for
            # ONE trace plus whatever its flight recorder retained, plus
            # the trace-correlated structured log records (the
            # interleaved `logs` view on the coordinator)
            from h2o3_tpu.obs import recorder as _rec
            from h2o3_tpu.obs import timeline as _tl
            from h2o3_tpu.utils import log as _ulog
            tid = op[len("trace:"):]
            spans, _n = _rec.RECORDER.read_through(
                tid, _tl.SPANS.trace_snapshot(tid, limit=512), limit=512)
            return {"host": _tl.host_id(), "spans": spans,
                    "logs": _ulog.trace_records(tid, limit=256)}
        if op == "jstack":
            # GET /3/JStack cluster merge + the watchdog's cluster
            # capture: this host's all-thread dump
            from h2o3_tpu.obs import timeline as _tl
            from h2o3_tpu.obs import watchdog as _wd
            return {"host": _tl.host_id(), "threads": _wd.thread_dump()}
        if op.startswith("logs:search:"):
            # GET /3/Logs cluster search: same filters, this host's
            # ring + durable segments
            import json as _json
            from h2o3_tpu.obs import timeline as _tl
            from h2o3_tpu.utils import log as _ulog
            filters = _json.loads(op[len("logs:search:"):])
            return {"host": _tl.host_id(),
                    "records": _ulog.search(**filters),
                    "files": [f["name"] for f in _ulog.list_files()]}
        if op.startswith("logs:file:"):
            # GET /3/Logs/nodes/{node}/files/{name}: only the NAMED node
            # ships content; everyone else acks with a bare host marker
            from h2o3_tpu.obs import timeline as _tl
            from h2o3_tpu.utils import log as _ulog
            node, _, name = op[len("logs:file:"):].partition(":")
            me = _tl.host_id()
            if node not in (str(me), "any"):
                return {"host": me}
            return {"host": me, "name": name,
                    "log": _ulog.read_file(name)}
        if op.startswith("parse:"):
            # distributed-ingest fan-out (io/dparse): tokenize THIS
            # host's chunk share and ack with compact codec-byte planes
            # (the re-home wire format) — phase B of the cloud-wide
            # parse runs as pure host work on every member
            import json as _json
            from h2o3_tpu.io import dparse as _dp
            from h2o3_tpu.obs import timeline as _tl
            spec = _json.loads(op[len("parse:"):])
            share = (spec.get("shares") or {}).get(str(_tl.host_id()))
            return {"host": _tl.host_id(),
                    "parse": _dp.worker_parse_chunks(
                        {"sep": spec.get("sep", ","),
                         "header": spec.get("header", True),
                         "chunks": share})}
        if op.startswith("profiler:"):
            # cluster-wide capture fan-out (POST /3/Profiler?cluster=1):
            # start/stop this host's profiler session; a sampling stop
            # ships the collapsed flamegraph text back in the ack
            from h2o3_tpu.obs import profiler as _prof
            from h2o3_tpu.obs import timeline as _tl
            return {"host": _tl.host_id(), **(_prof.collect_op(op) or {})}
        if op.startswith("modelmon:"):
            # GET /3/ModelMonitor/{model} cluster merge: this host's
            # live drift sketches for ONE model (integer counts — the
            # coordinator's fold is order-independent). A host that
            # does not monitor the model answers a bare marker so it
            # is never mistaken for a lagging worker.
            from h2o3_tpu.obs import modelmon as _mm
            from h2o3_tpu.obs import timeline as _tl
            mid = op[len("modelmon:"):]
            return _mm.snapshot(mid) or {"host": _tl.host_id(),
                                         "model": mid, "live": None}
    except Exception:   # noqa: BLE001 — a worker probe error must not kill the loop
        import traceback
        traceback.print_exc()
    return None


def _worker_connect(coordinator_host: str, port: int, pid: int,
                    secret: bytes, join=False, connect_wait_s=120.0):
    """Worker side of one connection: reach the coordinator (bounded by
    `connect_wait_s`), run the mutual challenge-response, return
    (sock, key, welcome). `join=True` marks the hello as an elastic
    (re)join so the coordinator's acceptor syncs epoch + snapshot."""
    import secrets as _secrets
    import socket
    import time as _time
    deadline = _time.monotonic() + connect_wait_s
    while True:                           # wait for process 0 to listen
        try:
            sock = socket.create_connection((coordinator_host, port),
                                            timeout=10.0)
            break
        except OSError:
            if _time.monotonic() >= deadline:
                raise RuntimeError("broadcast coordinator unreachable") \
                    from None
            _time.sleep(0.5)
    sock.settimeout(30.0)                 # handshake is bounded; replay
    #                                       waits below are not (heartbeat
    #                                       pings arrive as collect ops)
    try:
        chal = _recv_frame(sock, secret)
        if not chal or "challenge" not in chal:
            raise RuntimeError(
                "replay channel: no challenge from coordinator")
        nonce_w = _secrets.token_hex(16)
        hello = {"hello": pid, "echo": chal["challenge"], "nonce": nonce_w}
        if join:
            hello["join"] = 1
        _send_frame(sock, secret, hello)
        key = _session_key(secret, chal["challenge"], nonce_w)
        welcome = _recv_frame(sock, key)  # proves coordinator freshness too
        if not welcome or welcome.get("welcome") != pid:
            raise RuntimeError("replay channel: coordinator failed "
                               "handshake")
    except Exception:
        sock.close()
        raise
    sock.settimeout(None)
    return sock, key, welcome


def _observe_epoch(e):
    """Track the coordinator's cloud epoch on this worker (rides every
    elastic broadcast frame + the join welcome)."""
    if e is None:
        return
    from h2o3_tpu.deploy import membership as _mb
    _mb.MEMBERSHIP.observe_epoch(int(e))


def worker_loop(coordinator_host: str, port: int, pid=None, join=False):
    """Worker side: authenticate the coordinator, then block on the
    broadcast socket and replay each request in sequence order.

    Elastic additions: a dropped coordinator socket no longer orphans
    the worker permanently — it retries the handshake (as a re-join,
    syncing the current epoch + replayed-state snapshot) with bounded
    backoff for H2O3_REPLAY_RECONNECT_S before raising, logging a
    structured WARN per attempt. A `leave` op (coordinator-driven
    drain) exits cleanly."""
    import time as _time
    from h2o3_tpu.utils import log as _ulog
    secret = _cluster_secret()
    if pid is None:
        import jax
        pid = jax.process_index()
    sock, key, welcome = _worker_connect(coordinator_host, port, pid,
                                         secret, join=join)
    while True:
        reason = _replay_session(sock, key, welcome)
        if reason == "leave":
            return
        window = _reconnect_window_s()
        if window <= 0:
            return                        # legacy: orphaned worker exits
        give_up = _time.monotonic() + window
        attempt = 0
        sock = None
        while sock is None:
            attempt += 1
            try:
                sock, key, welcome = _worker_connect(
                    coordinator_host, port, pid, secret, join=True,
                    connect_wait_s=min(5.0, window))
            except (OSError, RuntimeError) as ex:
                remaining = give_up - _time.monotonic()
                _ulog.warn("replay channel: reconnect attempt %s failed: "
                           "%r (giving up in %.0fs)", attempt, ex,
                           max(remaining, 0.0))
                if remaining <= 0:
                    raise RuntimeError(
                        "replay channel: coordinator gone and re-join "
                        f"failed for {window:g}s "
                        "(H2O3_REPLAY_RECONNECT_S)") from ex
                _time.sleep(min(0.2 * 2 ** (attempt - 1), 2.0))


def _replay_session(sock, key, welcome) -> str:
    """Drive one authenticated replay connection until it ends. Returns
    "leave" (clean coordinator-driven exit) or "eof" (socket dropped —
    the caller decides whether to re-join)."""
    from h2o3_tpu.deploy import chaos as _chaos
    _observe_epoch(welcome.get("epoch"))
    # join-sync: replay the coordinator's state snapshot (its bounded
    # log of already-broadcast mutating requests) BEFORE entering the
    # live stream, so a replacement worker converges on the same DKV /
    # model state the survivors hold
    if welcome.get("snapshot_truncated"):
        from h2o3_tpu.utils import log as _ulog
        _ulog.err("join-sync snapshot TRUNCATED (coordinator's request "
                  "log overflowed H2O3_REPLAY_LOG_MAX): replayed state "
                  "may trail the survivors — this worker serves, but "
                  "/3/Cloud marks it unsynced")
    for req in welcome.get("snapshot") or []:
        try:
            replay_request(req["method"], req["path"], req["params"])
        except Exception as ex:  # noqa: BLE001 — snapshot best-effort
            from h2o3_tpu.utils import log as _ulog
            _ulog.warn("join-sync replay %s %s failed: %r",
                       req.get("method"), req.get("path"), ex)
    if welcome.get("snapshot") is not None:
        # replacement-worker warm start (H2O3_SCORER_PREWARM=1): the
        # joiner just converged on the survivors' model state — place
        # each model's shared sharded params and compile the smallest
        # row bucket NOW, in the background, so its first live request
        # warm-hits instead of paying placement + XLA compile
        from h2o3_tpu import serving as _serving
        if _serving.prewarm_enabled():
            n = _serving.prewarm_all()
            if n:
                from h2o3_tpu.utils import log as _ulog
                _ulog.info("join-sync: pre-warming %d model scorers", n)
    expect = int(welcome.get("seq", 1))
    while True:
        try:
            msg = _recv_frame(sock, key)
        except OSError:
            return "eof"
        if msg is None:
            return "eof"
        if msg.get("op") == "leave":      # drain completed: clean exit.
            # OUT-OF-BAND control frame (seq -1, checked BEFORE the
            # continuity guard): it goes only to the drained worker, so
            # consuming a shared sequence number here would leave a hole
            # that kills every SURVIVOR on its next frame
            try:
                _send_frame(sock, key, {"ack": msg.get("seq", -1)})
            except OSError:
                pass
            return "leave"
        if msg.get("seq") != expect:      # replayed/reordered frame
            raise RuntimeError(f"replay channel: bad seq {msg.get('seq')}"
                               f" (expected {expect})")
        expect += 1
        _observe_epoch(msg.get("epoch"))
        if "op" in msg:                   # observability collect: the data
            # chaos: a delayed/dropped collect ack at a seeded point (the
            # lagging-worker shape membership detection must absorb)
            act = _chaos.at("collect.ack")
            if act is not None and act["action"] == "drop":
                continue
            try:
                from h2o3_tpu.analysis import divergence as _dvg
                _send_frame(sock, key,    # rides the ack, no route replay
                            _dvg.attach_riders(
                                {"ack": msg["seq"],
                                 "data": _collect_local(msg["op"])}))
            except OSError:
                return "eof"
            continue
        # chaos: kill the worker process at a seeded replay point — the
        # "lost pod" the membership layer must excise and replace
        _chaos.maybe_raise("worker.replay")
        try:
            # ack, then execute; digests from ALREADY-replayed requests
            # ride out here (this request's own digest rides the next
            # frame — the sanitizer stashes whichever side arrives first)
            from h2o3_tpu.analysis import divergence as _dvg
            _send_frame(sock, key,
                        _dvg.attach_riders({"ack": msg["seq"]}))
        except OSError:
            return "eof"
        _dvg.replay_begin(msg["seq"], msg["path"])
        try:
            # replay under the ORIGINATING request's trace id (when the
            # coordinator attached one): every span this replay opens —
            # mrtask map/reduce phases, job phases, host fetches — tags
            # itself with it, so GET /3/Trace/{id} on process 0 stitches
            # this host's fragment in
            from h2o3_tpu.obs import tracing as _tr
            from h2o3_tpu.obs.timeline import span as _span
            from h2o3_tpu.utils import log as _ulog
            with _tr.trace(msg.get("trace")), \
                    _span("replay.request", path=msg["path"],
                          method=msg["method"]) as _sp:
                if msg.get("sampled"):
                    # attr marks the fragment root; pin() covers pieces
                    # finalized before it closes (linger, span overflow)
                    _sp.attrs["sampled"] = 1
                    from h2o3_tpu.obs import recorder as _rec
                    _rec.RECORDER.pin(msg.get("trace"))
                # structured + trace-correlated: this record is what the
                # coordinator's GET /3/Trace/{id} interleaves for the
                # worker's fragment, and what GET /3/Logs?trace= finds
                _ulog.info("replay %s %s seq=%s", msg["method"],
                           msg["path"], msg["seq"])
                try:
                    replay_request(msg["method"], msg["path"],
                                   msg["params"])
                except Exception as e:
                    # the error attr makes THIS host's recorder retain
                    # its fragment of the failed trace — the 5xx status
                    # lives only on the coordinator's root span; the
                    # ERROR record marks the trace for retention too
                    _sp.attrs["error"] = repr(e)
                    _ulog.err("replay %s %s failed: %r", msg["method"],
                              msg["path"], e)
                    raise
        except Exception:                 # keep replaying; process 0 owns
            import traceback              # error reporting to the client
            traceback.print_exc()
        finally:
            _dvg.replay_end()             # queue this replay's digest


def serve(port: int = 54321, n_rows_shards=None, n_model_shards: int = 1):
    """Container entrypoint: bootstrap the (possibly multi-host) cloud;
    process 0 serves REST and broadcasts mutating requests, workers replay
    them so every host issues the same device programs.

    H2O3_ELASTIC (default on) runs the replay channel under the
    deploy/membership epoch state machine: a dead worker is excised
    instead of wedging the cloud, and replacements may join."""
    import jax
    from h2o3_tpu.deploy import chaos as _chaos
    cloud = bootstrap(n_rows_shards=n_rows_shards,
                      n_model_shards=n_model_shards)
    _chaos.install_from_env()
    nproc = jax.process_count()
    bport = port + _BCAST_PORT_OFFSET
    if jax.process_index() == 0:
        from h2o3_tpu.api.server import H2OServer
        from h2o3_tpu.utils import config as _cfg
        _cfg.set_property("api.bind_all", True)
        # H2OServer enforces the bind-all-requires-auth posture itself
        srv = H2OServer(port)
        if nproc > 1:
            if env_bool("H2O3_ELASTIC", True):
                from h2o3_tpu.deploy.membership import ElasticBroadcaster
                srv.httpd.broadcaster = ElasticBroadcaster(nproc - 1, bport)
            else:
                srv.httpd.broadcaster = Broadcaster(nproc - 1, bport)
        from h2o3_tpu.utils import log as _ulog
        _ulog.info("h2o3-tpu cloud: %s chips over %s hosts; REST on :%s",
                   cloud.n_devices, nproc, port)
        srv.start(background=False)
    else:
        host = (_coordinator_address() or "127.0.0.1:0").split(":")[0]
        worker_loop(host, bport)


def join_cloud(coordinator_host: str, rest_port: int, pid: int):
    """Replacement-worker entrypoint: skip jax.distributed formation
    (the dead worker's slot in the fixed device runtime is gone) and
    join the REPLAY CHANNEL as an elastic member — handshake, sync the
    current epoch + replayed-state snapshot, then serve replays. This is
    the `kubectl` / StatefulSet-restart path: a new pod replaces a lost
    one without reforming the whole cloud."""
    from h2o3_tpu.deploy import chaos as _chaos
    _chaos.install_from_env()
    worker_loop(coordinator_host, rest_port + _BCAST_PORT_OFFSET,
                pid=pid, join=True)


if __name__ == "__main__":
    import sys
    serve(int(sys.argv[1]) if len(sys.argv) > 1 else 54321)
