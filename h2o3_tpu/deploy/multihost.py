"""Multi-host bootstrap — the h2o-k8s/H2OCluster + hadoop driver analog.

The reference forms a multi-node cloud via UDP gossip inside a k8s
StatefulSet (h2o-k8s/) or a YARN application (h2o-hadoop-common/). A TPU
pod slice is simpler and stricter: every host runs the SAME program,
`jax.distributed.initialize` wires the hosts into one runtime (GKE/TPU-VM
environments inject the coordinator automatically), and the global device
mesh spans all chips; collectives ride ICI within a slice and DCN across
slices — no gossip, no Paxos, membership is fixed by the slice topology.

Call `bootstrap()` first thing on every host of a multi-host deployment
(deploy/k8s/*.yaml does it via the container entrypoint). On a single
host it is a no-op, so the same entrypoint serves laptops and v5p-32 pods.
"""

from __future__ import annotations

import os


def is_multihost() -> bool:
    """True when a multi-host launch environment is detected (TPU pod
    env vars or explicit coordinator address)."""
    return bool(
        os.environ.get("H2O3_COORDINATOR_ADDRESS")
        or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
        or (os.environ.get("TPU_WORKER_HOSTNAMES")
            and int(os.environ.get("TPU_WORKER_COUNT", "1") or 1) > 1))


def bootstrap(n_rows_shards=None, n_model_shards: int = 1):
    """Initialize the distributed runtime (when applicable) and form the
    global cloud over every visible chip on every host.

    Env (k8s manifests set these from the StatefulSet):
      H2O3_COORDINATOR_ADDRESS  host:port of process 0
      H2O3_NUM_PROCESSES        world size
      H2O3_PROCESS_ID           this host's rank
    GKE TPU slices need none of them — jax.distributed.initialize()
    autodetects from the TPU metadata the same way MEGASCALE jobs do.
    """
    import jax

    if is_multihost():
        addr = os.environ.get("H2O3_COORDINATOR_ADDRESS")
        if addr:
            jax.distributed.initialize(
                coordinator_address=addr,
                num_processes=int(os.environ["H2O3_NUM_PROCESSES"]),
                process_id=int(os.environ["H2O3_PROCESS_ID"]))
        else:
            jax.distributed.initialize()   # TPU-env autodetection
    import h2o3_tpu
    cloud = h2o3_tpu.init(n_rows_shards=n_rows_shards,
                          n_model_shards=n_model_shards)
    return cloud


# ---------------------------------------------------------------------------
# SPMD request replay. A multi-controller JAX runtime requires EVERY process
# to issue the same computations in the same order — a worker that idles
# would deadlock the first collective process 0 launches. So process 0
# broadcasts each mutating REST request (path, method, params) to the
# workers BEFORE handling it locally, and each worker replays the identical
# request against the same route table. Identical requests → identical API
# calls → identical jitted programs → matching collectives. (The reference
# has no analog: its nodes exchange data via RPC; SPMD replicates control.)
# Requests replay serially in arrival order; concurrent builds are
# serialized by the broadcast lock.
_BCAST_PORT_OFFSET = 2


class _ReplayHandler:
    """Duck-typed stand-in for the HTTP handler: routes need only
    _params/_send/_error (+ raw send for byte routes, unused in replay)."""

    def __init__(self, params):
        self._p = dict(params)
        self.out = None

    def _params(self):
        return dict(self._p)

    def _send(self, obj, code=200):
        self.out = obj

    def _error(self, msg, code=400):
        self.out = {"error": str(msg), "code": code}


def replay_request(method: str, path: str, params: dict):
    """Execute a REST request against the local route table (worker side)."""
    from h2o3_tpu.api import server as _srv
    h = _ReplayHandler(params)
    for pat, m, fn in _srv.ROUTES:
        if m != method:
            continue
        mm = pat.fullmatch(path)
        if mm:
            fn(h, *mm.groups())
            return h.out
    return {"error": f"no route {method} {path}"}


class Broadcaster:
    """Process-0 side: fan each mutating request out to every worker and
    wait for receipt acks (ordering barrier) before local dispatch."""

    def __init__(self, n_workers: int, port: int):
        import socket
        import threading
        self._lock = threading.Lock()
        self._conns = []
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", port))
        srv.listen(n_workers)
        for _ in range(n_workers):
            conn, _addr = srv.accept()
            self._conns.append(conn)
        srv.close()

    def broadcast(self, method: str, path: str, params: dict):
        import pickle
        import struct
        payload = pickle.dumps((method, path, params))
        with self._lock:
            for c in self._conns:
                c.sendall(struct.pack("!I", len(payload)) + payload)
            for c in self._conns:
                ack = c.recv(1)           # receipt ack: ordering barrier
                assert ack == b"\x01"


def worker_loop(coordinator_host: str, port: int):
    """Worker side: block on the broadcast socket, replay each request."""
    import pickle
    import socket
    import struct
    import time as _time
    for _ in range(120):                  # wait for process 0 to listen
        try:
            sock = socket.create_connection((coordinator_host, port))
            break
        except OSError:
            _time.sleep(1)
    else:
        raise RuntimeError("broadcast coordinator unreachable")
    while True:
        hdr = sock.recv(4, socket.MSG_WAITALL)
        if not hdr:
            return
        (ln,) = struct.unpack("!I", hdr)
        method, path, params = pickle.loads(
            sock.recv(ln, socket.MSG_WAITALL))
        sock.sendall(b"\x01")             # ack receipt, then execute
        try:
            replay_request(method, path, params)
        except Exception:                 # keep replaying; process 0 owns
            import traceback              # error reporting to the client
            traceback.print_exc()


def serve(port: int = 54321):
    """Container entrypoint: bootstrap the (possibly multi-host) cloud;
    process 0 serves REST and broadcasts mutating requests, workers replay
    them so every host issues the same device programs."""
    import jax
    cloud = bootstrap()
    nproc = jax.process_count()
    bport = port + _BCAST_PORT_OFFSET
    if jax.process_index() == 0:
        from h2o3_tpu.api.server import H2OServer
        from h2o3_tpu.utils import config as _cfg
        _cfg.set_property("api.bind_all", True)
        srv = H2OServer(port)
        if nproc > 1:
            srv.httpd.broadcaster = Broadcaster(nproc - 1, bport)
        print(f"h2o3-tpu cloud: {cloud.n_devices} chips over "
              f"{nproc} hosts; REST on :{port}")
        srv.start(background=False)
    else:
        host = os.environ.get("H2O3_COORDINATOR_ADDRESS",
                              "127.0.0.1:0").split(":")[0]
        worker_loop(host, bport)


if __name__ == "__main__":
    import sys
    serve(int(sys.argv[1]) if len(sys.argv) > 1 else 54321)
