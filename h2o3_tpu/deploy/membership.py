"""Elastic cloud membership — the epoch state machine over the replay channel.

The reference freezes membership at `Paxos.lockCloud()` (water/Paxos.java:145):
after formation a lost node kills the cloud. That is fatal for a serving
deployment — one evicted pod must not wedge every REST thread behind the
broadcast ack barrier (the pre-elastic Broadcaster raised "SPMD replay is
wedged" and stayed wedged). This module makes membership a STATE MACHINE:

  * The cloud has an integer **epoch**, bumped on every membership change
    (excision, join, drain-leave). Workers are tracked per-epoch with a
    state (`active` → `draining` → `left`, or `active` → `dead`).
  * `ElasticBroadcaster` replaces the fixed-membership Broadcaster on the
    coordinator: a worker that blows the ack deadline, drops its socket or
    misses heartbeats is **excised** — marked dead, epoch bumped, replay
    resumed over the surviving set — instead of failing the request.
  * A joining/replacement worker handshakes on the still-open listener,
    receives the current epoch + a replayed-state snapshot (the bounded
    log of already-broadcast mutating requests), replays it to converge,
    and enters the broadcast set.
  * `POST /3/Cloud/drain` finishes in-flight jobs and micro-batches, then
    sends the worker a clean `leave` op before excising it.
  * Every epoch bump re-homes DKV keys through the consistent-hash ring
    (core/kvstore.set_membership — bounded key movement, background
    migration, read-through while it runs).

Detection bounds: the broadcast ack deadline (H2O3_REPLAY_ACK_TIMEOUT_S)
for workers that wedge mid-request, plus a heartbeat loop
(H2O3_HEARTBEAT_S, excise after H2O3_HEARTBEAT_MISSES consecutive
misses) for workers that die while the channel is idle.

Serving-path degradation: `retry_once` retries an operation that failed
while the epoch moved under it (or raised EpochChanged) exactly once,
with jittered backoff — wired into micro-batch dispatch and MRTask
device dispatch so a request straddling an excision succeeds against the
new epoch instead of surfacing a 5xx.
"""

from __future__ import annotations

import random
import socket as _socket_mod
import threading
import time

from h2o3_tpu.analysis.lockdep import make_lock
from h2o3_tpu.deploy import chaos as _chaos
from h2o3_tpu.deploy import multihost as _mh
from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.obs import watchdog as _wd
from h2o3_tpu.obs.timeline import span as _span
from h2o3_tpu.utils.env import env_float, env_int

ACTIVE = "active"
DRAINING = "draining"
DEAD = "dead"
LEFT = "left"

EXCISIONS = _om.counter(
    "h2o3_cloud_excisions_total",
    "workers excised from the cloud, by reason (ack_timeout/send_error/"
    "bad_ack/recv_error/heartbeat/eof/drain/error) — each excision bumps "
    "h2o3_cloud_epoch and re-homes DKV keys")
JOINS = _om.counter(
    "h2o3_cloud_joins_total",
    "workers that joined (or re-joined) the elastic cloud after "
    "formation, each syncing the current epoch + state snapshot")
EPOCH_RETRIES = _om.counter(
    "h2o3_epoch_retries_total",
    "serving/dispatch operations retried once against a new cloud epoch "
    "after straddling a membership change, by op "
    "(microbatch/mrtask)")


class EpochChanged(RuntimeError):
    """An operation straddled a cloud-epoch bump (membership changed
    under it). retry_once treats this as always retryable."""

    def __init__(self, msg="cloud epoch changed", old=None, new=None):
        super().__init__(msg)
        self.old = old
        self.new = new


class Membership:
    """Per-epoch worker tracking. One per process; the coordinator's is
    authoritative, workers mirror the epoch off the broadcast frames."""

    def __init__(self):
        self._lock = make_lock("membership")
        self.epoch = 1
        self.multi = False        # any worker ever registered (fast path
        #                           gate for the per-dispatch retry hook)
        self._workers: dict = {}  # pid -> {"state", "epoch", "reason"}
        self._listeners: list = []

    def reset(self):
        """Test harness: back to a fresh single-host cloud."""
        with self._lock:
            self.epoch = 1
            self.multi = False
            self._workers = {}
            self._listeners = []

    def add_listener(self, fn):
        """fn(epoch, alive_worker_pids) after every membership change —
        called OUTSIDE the membership lock (listeners may take dkv)."""
        with self._lock:
            self._listeners.append(fn)

    def register(self, pid: int):
        """Record a formation-time worker (no epoch bump: formation IS
        epoch 1)."""
        with self._lock:
            self._workers[pid] = {"state": ACTIVE, "epoch": self.epoch,
                                  "reason": None}
            self.multi = True

    def observe_epoch(self, e: int):
        """Worker side: adopt the coordinator's epoch from a broadcast
        frame / join welcome (monotone)."""
        with self._lock:
            if e > self.epoch:
                self.epoch = e

    def _change_locked(self, pid, state, reason):
        self._workers[pid] = {"state": state, "epoch": self.epoch + 1,   # h2o3-ok: R003 _locked helper — every caller holds self._lock
                              "reason": reason}
        self.epoch += 1   # h2o3-ok: R003 _locked helper — every caller holds self._lock
        return self.epoch

    def excise(self, pid: int, reason: str) -> int:
        """A dead/unresponsive worker leaves the broadcast set; the epoch
        bumps and survivors carry on. Returns the new epoch."""
        with self._lock:
            ep = self._change_locked(pid, DEAD, reason)
            alive = self._alive_locked()
        EXCISIONS.inc(reason=reason)
        with _span("membership.excise", node=pid, reason=reason, epoch=ep):
            from h2o3_tpu.utils import log as _ulog
            _ulog.err("membership: excised worker %s (%s) -> epoch %s, "
                      "%s live workers", pid, reason, ep, len(alive))
        self._notify(ep, alive)
        return ep

    def leave(self, pid: int) -> int:
        """Clean drain-initiated departure (state `left`, reason drain)."""
        with self._lock:
            ep = self._change_locked(pid, LEFT, "drain")
            alive = self._alive_locked()
        EXCISIONS.inc(reason="drain")
        from h2o3_tpu.utils import log as _ulog
        _ulog.info("membership: worker %s drained and left -> epoch %s",
                   pid, ep)
        self._notify(ep, alive)
        return ep

    def join(self, pid: int, synced: bool = True) -> int:
        """A joining/replacement worker enters the set. Returns the new
        epoch (which the welcome frame carries to the joiner).
        `synced=False` records that the join-sync snapshot was TRUNCATED
        (the mutating-request log overflowed H2O3_REPLAY_LOG_MAX before
        this worker joined) — the worker serves, but its replayed state
        may trail the survivors'; /3/Cloud exposes the flag and both
        sides log it loudly."""
        with self._lock:
            ep = self._change_locked(pid, ACTIVE, None)
            self._workers[pid]["synced"] = synced   # h2o3-ok: R003 under self._lock
            self.multi = True
            alive = self._alive_locked()
        JOINS.inc()
        with _span("membership.join", node=pid, epoch=ep):
            from h2o3_tpu.utils import log as _ulog
            if synced:
                _ulog.info("membership: worker %s joined -> epoch %s, "
                           "%s live workers", pid, ep, len(alive))
            else:
                _ulog.err("membership: worker %s joined UNSYNCED -> "
                          "epoch %s (snapshot log overflowed "
                          "H2O3_REPLAY_LOG_MAX; its replayed state may "
                          "diverge — prefer draining and re-parsing, or "
                          "raise the log bound)", pid, ep)
        self._notify(ep, alive)
        return ep

    def start_drain(self, pid: int):
        with self._lock:
            w = self._workers.get(pid)
            if w is None or w["state"] not in (ACTIVE, DRAINING):
                raise ValueError(f"node {pid} is not an active worker")
            w["state"] = DRAINING

    def state(self, pid: int):
        with self._lock:
            w = self._workers.get(pid)
            return w["state"] if w else None

    def _alive_locked(self) -> list:
        return sorted(p for p, w in self._workers.items()
                      if w["state"] in (ACTIVE, DRAINING))

    def alive(self) -> list:
        with self._lock:
            return self._alive_locked()

    def active(self) -> list:
        """Workers eligible for NEW work (distributed-parse fan-out
        shares): ACTIVE only — a DRAINING worker finishes its in-flight
        replays and leaves, so handing it a fresh chunk share would
        race the drain's quiesce wait."""
        with self._lock:
            return sorted(p for p, w in self._workers.items()
                          if w["state"] == ACTIVE)

    def nodes(self) -> list:
        """Per-worker view for GET /3/Cloud."""
        with self._lock:
            return [dict(pid=p, **w)
                    for p, w in sorted(self._workers.items())]

    def _notify(self, epoch: int, alive: list):
        # built-in first: the mesh rebuild is part of the epoch contract
        # (not a removable listener — reset() must not detach it)
        _mesh_epoch_listener(epoch, alive)
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(epoch, alive)
            except Exception:   # noqa: BLE001 — a listener error must not
                from h2o3_tpu.utils import log as _ulog  # kill the channel
                _ulog.err("membership listener failed for epoch %s", epoch)


MEMBERSHIP = Membership()


def _mesh_epoch_listener(epoch: int, alive: list):
    """Every membership change rebuilds the host mesh for the new epoch
    (parallel.mesh.note_epoch): the jax device runtime is fixed-size, so
    the mesh keeps its shape, but the fresh Mesh object makes placement
    caches (the serving param store) re-place instead of dispatching
    against arrays laid out for a dead membership."""
    del alive
    try:
        from h2o3_tpu.parallel import mesh as _pmesh
        _pmesh.note_epoch(epoch)
    except Exception:   # noqa: BLE001 — a mesh rebuild failure must not
        from h2o3_tpu.utils import log as _ulog   # kill the channel
        _ulog.err("mesh rebuild for epoch %s failed", epoch)


# module-level gauges reading the module global (the microbatch pattern:
# bound to whatever MEMBERSHIP currently is, resilient to reset())
_om.gauge("h2o3_cloud_epoch",
          "current cloud membership epoch (bumps on every excision, "
          "join and drain-leave)",
          fn=lambda: float(MEMBERSHIP.epoch))
_om.gauge("h2o3_cloud_live_workers",
          "workers currently in the broadcast set (active or draining)",
          fn=lambda: float(len(MEMBERSHIP.alive())))


def current_epoch() -> int:
    return MEMBERSHIP.epoch


def _retry_backoff_s() -> float:
    """Jittered backoff before the one epoch retry: base from
    H2O3_EPOCH_RETRY_BACKOFF_S (default 50ms), uniform jitter in
    [0.5x, 1.5x] so a thundering herd of straddled requests doesn't
    re-dispatch in lockstep."""
    base = env_float("H2O3_EPOCH_RETRY_BACKOFF_S", 0.05)
    return base * (0.5 + random.random())


def retry_once(fn, op: str = "op"):
    """Run `fn()`; when it raises EpochChanged — or any exception while
    the cloud epoch moved under it — back off (jittered) and retry
    exactly once against the new epoch. Exceptions with a stable epoch
    propagate unchanged: a real bug must not get a free second attempt
    that hides it."""
    e0 = MEMBERSHIP.epoch
    try:
        return fn()
    except EpochChanged:
        pass
    except Exception:
        if MEMBERSHIP.epoch == e0:
            raise
    EPOCH_RETRIES.inc(op=op)
    time.sleep(_retry_backoff_s())
    return fn()


def _heartbeat_s() -> float:
    return env_float("H2O3_HEARTBEAT_S", 10.0)


def _heartbeat_misses() -> int:
    return env_int("H2O3_HEARTBEAT_MISSES", 3)


def _drain_timeout_s() -> float:
    return env_float("H2O3_DRAIN_TIMEOUT_S", 30.0)


def _replay_log_max() -> int:
    return env_int("H2O3_REPLAY_LOG_MAX", 256)


class ElasticBroadcaster(_mh.Broadcaster):
    """The elastic coordinator: the fixed-membership Broadcaster plus the
    epoch state machine. Differences from the base:

      * `broadcast` excises a failing worker (ack timeout, send error,
        bad ack) and finishes over the survivors instead of raising.
      * The formation listener stays open; an acceptor thread admits
        joining/replacement workers (handshake → epoch + snapshot
        welcome → broadcast set).
      * A heartbeat loop (`ping` collect op) excises workers that die
        while the channel is idle.
      * `drain` quiesces in-flight jobs + micro-batches, sends the
        worker a clean `leave`, and excises it with reason `drain`.
    """

    def __init__(self, n_workers: int, port: int, membership=None):
        from collections import deque
        super().__init__(n_workers, port, keep_listener=True)
        self.membership = membership if membership is not None \
            else MEMBERSHIP
        self._replay_log = deque(maxlen=_replay_log_max())
        self._log_total = 0
        self._hb_misses: dict = {}
        for pid in self._pids:
            self.membership.register(pid)
        # every membership change re-homes DKV keys over the new ring
        # (node 0 = the coordinator itself, always a member)
        from h2o3_tpu.core.kvstore import DKV as _dkv
        self.membership.add_listener(
            lambda epoch, alive, _d=_dkv: _d.set_membership(
                [0] + list(alive), epoch=epoch))
        _dkv.set_membership([0] + self.membership.alive(),
                            epoch=self.membership.epoch)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="h2o3-membership-accept")
        self._accept_thread.start()
        if _heartbeat_s() > 0:
            self._hb_thread = threading.Thread(
                target=self._hb_loop, daemon=True, name="h2o3-heartbeat")
            self._hb_thread.start()

    # ---- lifecycle -------------------------------------------------------
    def close(self):
        self._closed = True
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        with self._lock:
            for c, _k in self._conns:
                try:
                    c.close()
                except OSError:
                    pass

    # ---- excision --------------------------------------------------------
    def _excise_locked(self, i: int, reason: str, state: str = DEAD):
        """Caller holds self._lock. Marks the slot dead, closes its
        socket, and advances the membership epoch."""
        if self._dead[i]:
            return
        self._dead[i] = True   # h2o3-ok: R003 only reachable with self._lock held (broadcast/collect/drain paths)
        try:
            self._conns[i][0].close()
        except OSError:
            pass
        if reason in ("ack_timeout",):
            _mh._ack_timeouts_counter().inc()
        if state == LEFT:
            self.membership.leave(self._pids[i])
        else:
            self.membership.excise(self._pids[i], reason)

    def _reconcile_dead(self):
        """Lift slots the BASE collect path marked dead (send/recv
        errors) into proper excisions with an epoch bump."""
        with self._lock:
            stale = [i for i in range(len(self._conns))
                     if self._dead[i]
                     and self.membership.state(self._pids[i])
                     in (ACTIVE, DRAINING)]
            for i in stale:
                try:
                    self._conns[i][0].close()
                except OSError:
                    pass
        for i in stale:
            self.membership.excise(self._pids[i], "error")

    # ---- replay ----------------------------------------------------------
    def _drain_owed_elastic(self, i: int, deadline: float):
        """Bounded owed-ack drain that signals failure by exception (the
        caller excises) instead of wedging the whole broadcast."""
        import time as _time
        while self._owed[i] > 0:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                raise TimeoutError("owed-ack drain deadline")
            if self._recv_frame_at(i, timeout=remaining) is None:
                break                        # peer gone: excised below
            self._owed[i] -= 1   # h2o3-ok: R003 only reachable from broadcast(), which holds self._lock

    def broadcast(self, method: str, path: str, params: dict, trace=None,
                  sampled=False):
        """Fan out + ack barrier over the LIVE set; a worker that fails
        any step is excised (epoch bump) and the broadcast completes
        over the survivors — replay resumes instead of raising."""
        import time as _time
        with _wd.watch("replay", desc=f"broadcast {method} {path}",
                       deadline_s=min(_mh._ack_timeout() / 2,
                                      _wd._stall_s()),
                       trace=trace), \
                self._lock:
            self._seq += 1
            msg = {"seq": self._seq, "method": method, "path": path,
                   "params": params, "epoch": self.membership.epoch}
            if trace:
                msg["trace"] = trace
            if sampled:
                msg["sampled"] = 1
            # the join-sync snapshot: a bounded log of MUTATING requests a
            # replacement worker replays to converge (GETs are broadcast
            # for SPMD lockstep but change no state worth syncing)
            if method != "GET":
                self._replay_log.append({"method": method, "path": path,
                                         "params": params})
                self._log_total += 1   # h2o3-ok: R003 only reachable from broadcast(), which holds self._lock
            deadline = _time.monotonic() + _mh._ack_timeout()
            failed: list = []
            awaiting: list = []
            for i in range(len(self._conns)):
                if self._dead[i]:
                    continue
                c, key = self._conns[i]
                act = _chaos.at("replay.send", worker=self._pids[i])
                if act is not None and act["action"] == "sever":
                    try:
                        c.close()            # fault: cut the socket NOW
                    except OSError:
                        pass
                dropped = act is not None and act["action"] == "drop"
                try:
                    # grace floor mirrors the recv phase: a wedged worker
                    # ahead of us consuming the shared deadline must not
                    # cascade healthy peers (whose sends are instant and
                    # owed-ack queues empty) into excisions
                    remaining = max(deadline - _time.monotonic(), 0.25)
                    self._drain_owed_elastic(
                        i, _time.monotonic() + remaining)
                    remaining = max(deadline - _time.monotonic(), 0.25)
                    if not dropped:
                        _mh._send_frame(c, key, msg, timeout=remaining)
                    awaiting.append(i)
                except TimeoutError:
                    failed.append((i, "ack_timeout"))
                except Exception:   # noqa: BLE001 — peer broken: excise
                    failed.append((i, "send_error"))
            for i in awaiting:
                try:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        # a wedged worker ahead of us consumed the shared
                        # budget; healthy peers' acks are (almost always)
                        # already in their buffers — a small grace keeps
                        # ONE dead worker from cascading the whole
                        # barrier into excisions, while still bounding
                        # the total hold at deadline + grace×workers
                        remaining = 0.25
                    ack = self._recv_frame_at(i, timeout=remaining)
                    if not ack or ack.get("ack") != self._seq:
                        failed.append((i, "bad_ack"))
                except (_socket_mod.timeout, TimeoutError):
                    failed.append((i, "ack_timeout"))
                except Exception:   # noqa: BLE001 — peer broken: excise
                    failed.append((i, "recv_error"))
            for i, reason in failed:
                self._excise_locked(i, reason)
            # seq hands the divergence sanitizer this request's identity
            # (mismatches must never raise in here: an exception in the
            # send/ack loops above reads as a broken peer and excises it
            # — the dispatcher's raise_if_pending owns surfacing them)
            return self._seq

    def collect(self, op: str, timeout: float = 2.0) -> list:
        """Base collect, then lift peers it found broken into proper
        excisions (epoch bump). Lagging-but-alive workers still just owe
        an ack — laggards are a heartbeat concern, not a collect one."""
        out = super().collect(op, timeout=timeout)
        self._reconcile_dead()
        return out

    def live_pids(self) -> list:
        """Fan-out share-holders: the base live set minus DRAINING
        workers (a drain must not be handed fresh parse chunks)."""
        active = set(self.membership.active())
        return [p for p in super().live_pids() if p in active]

    # ---- joins -----------------------------------------------------------
    def _accept_loop(self):
        """Admit joining/replacement workers on the still-open listener.
        The 1s accept timeout keeps shutdown prompt (R013 bound)."""
        from h2o3_tpu.utils import log as _ulog
        while not self._closed:
            try:
                conn, addr = self._srv.accept()
            except _socket_mod.timeout:
                continue
            except OSError:
                return                       # listener closed: shutting down
            try:
                self._admit(conn, addr)
            except Exception as ex:  # noqa: BLE001 — reject peer, keep serving
                _ulog.warn("membership: rejected joining peer %s: %s",
                           addr, ex)
                try:
                    conn.close()
                except OSError:
                    pass

    def _admit(self, conn, addr):
        """Handshake a joiner, sync epoch + snapshot, enter the set."""
        hello, key = _mh._challenge_peer(conn, self._secret)
        pid = hello["hello"]
        with self._lock:
            for i, known in enumerate(self._pids):
                if known == pid and not self._dead[i]:
                    raise RuntimeError(
                        f"worker id {pid} is still live (rejoin requires "
                        "the old connection dead)")
            truncated = self._log_total > len(self._replay_log)
            # send the welcome BEFORE committing the join: a joiner whose
            # socket dies mid-handshake must not become a ghost ACTIVE
            # member (epoch bumped, keys re-homed onto a node with no
            # connection, un-excisable because it never entered _pids).
            # Every membership change happens under self._lock, so the
            # epoch the join WILL produce is deterministic here.
            welcome = {"welcome": pid, "epoch": self.membership.epoch + 1,
                       "seq": self._seq + 1,
                       "snapshot": list(self._replay_log),
                       "snapshot_truncated": truncated}
            _mh._send_frame(conn, key, welcome, timeout=10.0)
            self.membership.join(pid, synced=not truncated)
            conn.settimeout(None)
            self._conns.append((conn, key))
            self._owed.append(0)
            self._bufs.append(b"")
            self._dead.append(False)
            self._pids.append(pid)
            self._hb_misses.pop(pid, None)

    # ---- heartbeat -------------------------------------------------------
    def _hb_loop(self):
        """Idle-channel liveness: a `ping` collect every H2O3_HEARTBEAT_S;
        H2O3_HEARTBEAT_MISSES consecutive silent rounds excise the worker
        — bounded detection even when no requests are flowing."""
        while not self._closed:
            time.sleep(_heartbeat_s())
            if self._closed:
                return
            try:
                res = self.collect("ping",
                                   timeout=min(_heartbeat_s() / 2, 2.0))
            except Exception:   # noqa: BLE001 — next round retries
                continue
            lagging = []
            with self._lock:
                for i, r in enumerate(res):
                    if i >= len(self._pids) or self._dead[i]:
                        continue
                    pid = self._pids[i]
                    if r is None:
                        n = self._hb_misses.get(pid, 0) + 1
                        self._hb_misses[pid] = n
                        if n >= _heartbeat_misses():
                            lagging.append(i)
                    else:
                        self._hb_misses[pid] = 0
                for i in lagging:
                    self._excise_locked(i, "heartbeat")

    # ---- drain -----------------------------------------------------------
    def drain(self, pid: int) -> dict:
        """Graceful departure: finish in-flight jobs and micro-batches
        (bounded by H2O3_DRAIN_TIMEOUT_S), send the worker a clean
        `leave` op, then excise it with an epoch bump."""
        with self._lock:
            slot = next((i for i, p in enumerate(self._pids)
                         if p == pid and not self._dead[i]), None)
        if slot is None:
            raise ValueError(f"node {pid} is not a live worker")
        with _span("membership.drain", node=pid):
            self.membership.start_drain(pid)
            quiesced = self._wait_quiesce(_drain_timeout_s())
            with self._lock:
                if not self._dead[slot]:
                    # OUT-OF-BAND leave (seq -1): this frame goes to ONE
                    # worker only, so it must not consume a shared
                    # sequence number — a hole in the survivors' streams
                    # would kill them at their next continuity check
                    try:
                        c, key = self._conns[slot]
                        _mh._send_frame(c, key,
                                        {"seq": -1, "op": "leave"},
                                        timeout=5.0)
                        # absorb any owed acks ahead of the leave ack
                        deadline = time.monotonic() + 5.0
                        left_ok = False
                        while time.monotonic() < deadline:
                            ack = self._recv_frame_at(
                                slot,
                                timeout=deadline - time.monotonic())
                            if ack is None:
                                break
                            if ack.get("ack") == -1:
                                left_ok = True
                                break
                            if self._owed[slot] > 0:
                                self._owed[slot] -= 1   # h2o3-ok: R003 under self._lock (drain holds it)
                    except Exception:   # noqa: BLE001 — leave is best-effort
                        left_ok = False
                    self._excise_locked(slot, "drain", state=LEFT)
                else:
                    left_ok = False
        return {"node": pid, "epoch": self.membership.epoch,
                "quiesced": quiesced, "left_cleanly": left_ok}

    @staticmethod
    def _wait_quiesce(timeout_s: float) -> bool:
        """Poll until no job is RUNNING and the micro-batch queue is
        empty, bounded by `timeout_s`. Returns whether it quiesced."""
        from h2o3_tpu.core.jobs import jobs_list
        from h2o3_tpu.serving.microbatch import BATCHER
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                busy_jobs = any(j.get("status") == "RUNNING"
                                for j in jobs_list())
            except Exception:   # noqa: BLE001 — job census best-effort
                busy_jobs = False
            if not busy_jobs and BATCHER._depth == 0:
                return True
            time.sleep(0.05)
        return False
