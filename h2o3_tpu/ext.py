"""Extension SPI — water/ExtensionManager.java + AbstractH2OExtension +
water/api/RestApiExtension rebuilt for the single-controller runtime.

The reference discovers extensions via ServiceLoader on the classpath and
gives them lifecycle hooks (onLocalNodeStarted) plus registration points
(new algos, new REST routes). Here registration is explicit Python —
`register_extension` — plus optional discovery through the
`ai.h2o.extensions` config property (comma-separated module paths imported
at init; each module calls register_extension at import time).

An extension may contribute:
  * estimators: {algo_name: EstimatorClass} merged into models.ESTIMATORS
    (and therefore the REST ModelBuilders surface + bindings codegen)
  * routes: [(regex_str, method, handler)] appended to api.server.ROUTES
  * rapids:  {prim_name: fn} merged into rapids.PRIMS
  * init(cloud) lifecycle hook (onLocalNodeStarted analog)
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field


@dataclass
class H2OExtension:
    name: str
    estimators: dict = field(default_factory=dict)
    routes: list = field(default_factory=list)
    rapids: dict = field(default_factory=dict)
    init: object = None          # callable(cloud) | None


_EXTENSIONS: dict[str, H2OExtension] = {}


def register_extension(ext: H2OExtension) -> H2OExtension:
    """Idempotent by name (re-registering replaces — module reloads)."""
    _EXTENSIONS[ext.name] = ext
    # estimators → model registry (+ REST builders + codegen, live)
    if ext.estimators:
        from h2o3_tpu import models as _m
        _m.ESTIMATORS.update(ext.estimators)
    if ext.routes:
        from h2o3_tpu.api import server as _srv
        existing = {(p.pattern, m) for p, m, _ in _srv.ROUTES}
        for pat, method, fn in ext.routes:
            if (pat, method) not in existing:
                _srv.ROUTES.append((re.compile(pat), method, fn))
    if ext.rapids:
        from h2o3_tpu.rapids.rapids import PRIMS
        PRIMS.update(ext.rapids)
    return ext


def extensions() -> list[H2OExtension]:
    return list(_EXTENSIONS.values())


_INIT_FIRED: set = set()


def load_configured_extensions(cloud=None):
    """Import modules named in `ai.h2o.extensions` (ServiceLoader analog)
    and fire init hooks ONCE per extension (onLocalNodeStarted fires once
    in the reference; mesh re-init must not duplicate extension
    resources). Called from h2o3_tpu.init()."""
    import importlib
    from h2o3_tpu.utils import config as _cfg
    spec = _cfg.get_property("extensions", "") or ""
    for mod in [m.strip() for m in str(spec).split(",") if m.strip()]:
        importlib.import_module(mod)
    for ext in _EXTENSIONS.values():
        if callable(ext.init) and ext.name not in _INIT_FIRED:
            _INIT_FIRED.add(ext.name)
            ext.init(cloud)
