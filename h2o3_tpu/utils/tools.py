"""Maintenance-tool registry — the analog of the reference's AstRunTool
(water/rapids/ast/prims/internal/AstRunTool.java), which dispatches to
`water.tools.*` classes by name (e.g. the XGBoostLibExtractTool)."""

from __future__ import annotations

_TOOLS: dict = {}


def register_tool(name: str):
    def deco(fn):
        _TOOLS[name] = fn
        return fn
    return deco


def run_tool(name: str, args: list):
    fn = _TOOLS.get(name)
    if fn is None:
        raise ValueError(
            f"unknown tool {name!r}; registered: {sorted(_TOOLS)}")
    return fn(*args)


@register_tool("GarbageCollect")
def _gc_tool():
    import gc
    gc.collect()
    return 0.0


@register_tool("MemoryInfo")
def _meminfo_tool():
    from h2o3_tpu.core.memory import MANAGER
    st = MANAGER.stats()
    return float(st.get("resident_bytes", 0))
