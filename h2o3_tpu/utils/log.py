"""Structured logging — water/util/Log.java rebuilt as JSON lines.

Reference: Log.java keeps log4j-backed per-node rolling files plus an
in-memory buffer that GET /3/Logs serves; every node owns its own files
and the REST layer routes `/3/Logs/nodes/{node}/files/{name}` to the
node that has them. Here the same pillar is structured from the start:

  * every record is a JSON object carrying host rank, thread, level,
    logger, message, source site, and the active **trace/span ids** from
    obs/tracing + obs/timeline TLS — so a log line correlates to the
    distributed trace that produced it with zero parsing;
  * records land in a bounded in-memory ring (the GET /3/Logs working
    set) AND in durable per-process JSONL segment files under
    `<ice_root>/obs/logs` — the obs/recorder.py segment discipline:
    append-only, per-process file names prefixed with the host rank
    (processes sharing an ice root never clobber each other and the
    node-file surface stays exact), torn trailing lines skipped on
    read, GC'd oldest-first against H2O3_LOG_RETAIN_MB;
  * an ERROR-level record marks its trace for flight-recorder retention
    (a keep-rule producer: the trace of a request that logged an error
    is never lost to the downsample lottery, even when every span in it
    closed fast and 2xx);
  * `search()` answers the GET /3/Logs filters (level/since/trace/grep)
    over ring + disk, and `read_file()`/`list_files()` back the
    node-routed file download.

Hot-path design (the log4j2 async-appender analog — Log.java buffers
too): the EMITTING thread only builds the record dict, appends it to the
ring, registers the error keep-rule, and enqueues — all rendering
(stderr console line, durable JSONL, the optional H2O3_LOG_DIR rotating
text file) and the per-level counter run on one daemon drain thread, so
a record on the warm scoring path costs microseconds, not a disk flush.
WARNING-and-above records drain SYNCHRONOUSLY on the emitting thread
(they are the crash-postmortem tier: durable before the next statement
runs); `flush()` drains everything.

Env surface:
  H2O3_LOG_LEVEL         root level (default INFO)
  H2O3_LOG_STDERR_LEVEL  console line threshold (default = root level)
  H2O3_LOG_DIR           also write a classic rotating text log here
  H2O3_LOG_RING          in-memory record ring size (default 2000)
  H2O3_LOG_RETAIN_MB     durable JSONL budget under <ice_root>/obs/logs
                         (default 32; 0 disables the durable tier)
  H2O3_LOG_SEGMENT_MB    roll the active segment past this (default 4)
"""

from __future__ import annotations

import atexit
import itertools
import json
import logging
import logging.handlers
import os
import random
import sys
import threading
import time
from collections import deque

# the shared append-only segment-directory discipline (liveness check,
# listing, GC, torn-line-tolerant reads) — one implementation for the
# flight recorder and this module (json/os only: no import cycle)
from h2o3_tpu.obs import segments as _segments_mod
from h2o3_tpu.utils import env as _uenv

_LOGGER = None
_INIT_LOCK = threading.Lock()

_LEVELS = {"DEBUG": 10, "INFO": 20, "WARN": 30, "WARNING": 30,
           "ERROR": 40, "CRITICAL": 50}
# cached effective levels (refreshed by reinit): the fast-path shims
# must not pay an os.environ read per call
_LEVEL = 20
_STDERR_LEVEL = 20


def _retain_bytes() -> int:
    return int(_uenv.env_float("H2O3_LOG_RETAIN_MB", 32.0) * 1e6)


def _segment_bytes() -> int:
    return int(_uenv.env_float("H2O3_LOG_SEGMENT_MB", 4.0) * 1e6)


_HOST = None


def _host_id() -> int:
    global _HOST
    if _HOST is None:
        _HOST = _uenv.process_id()
    return _HOST


def log_root() -> str:
    """Durable log directory under the ice root — computed per call so a
    test repointing the ice root (io/spill.set_ice_root) takes effect on
    the next record, same as the flight recorder's default_root()."""
    from h2o3_tpu.io import spill as _spill
    return os.path.join(_spill.get_ice_root(), "obs", "logs")


# ---------------------------------------------------------------------------
# in-memory ring of structured records (the GET /3/Logs working set)
_RING: deque = deque(maxlen=_uenv.env_int("H2O3_LOG_RING", 2000))

# per-record ids start at a random per-process base (the obs/timeline
# span-id discipline): ring records are usually ALSO on disk, and the
# (host, id) dedup in search() must not collide a fresh process's ids
# 1..N with a dead process's durable records
_IDS = itertools.count((random.getrandbits(31) << 20) + 1)

# records emitted while a handler itself is emitting (a callee of the
# drain that logs) must not recurse through the chain. (The hot-path
# shims below bypass stdlib LogRecord construction entirely — we do NOT
# flip logging.logProcesses globally, which would blank %(process)d for
# every other library in an embedding application.)
_TLS = threading.local()

_COUNTER = None


def _records_counter():
    """h2o3_log_records_total{level} — declared lazily (the metrics
    registry is a much later import than this module) and cached."""
    global _COUNTER
    if _COUNTER is None:
        from h2o3_tpu.obs import metrics as _om
        _COUNTER = _om.counter(
            "h2o3_log_records_total",
            "structured log records emitted, labeled by level — the "
            "Grafana log-rate-by-level panel reads this")
    return _COUNTER


_DROPPED = None


def _dropped_counter():
    global _DROPPED
    if _DROPPED is None:
        from h2o3_tpu.obs import metrics as _om
        _DROPPED = _om.counter(
            "h2o3_log_dropped_records_total",
            "structured log records dropped by sink-queue overload (the "
            "drain thread fell >65536 records behind) — nonzero means "
            "the durable tier and console have gaps the ring may not")
    return _DROPPED


class _DurableWriter:
    """Per-process JSONL segment writer + oldest-first retention GC —
    the obs/recorder.py segment discipline applied to log records.
    Driven by the sink's drain thread (plus synchronous urgent drains),
    serialized by the sink lock; internal state needs no lock of its
    own."""

    def __init__(self):
        self._fh = None
        self._path = None
        self._dir = None
        self._seq = 0
        self._written = 0

    def _open(self):
        d = log_root()
        os.makedirs(d, exist_ok=True)
        self._seq += 1
        self._dir = d
        # host rank leads the name: on a SHARED ice root (dev clouds,
        # tests) every process writes into one dir, and the node-routed
        # file surface (list_files/read_file) must serve only the files
        # this node owns
        self._path = os.path.join(
            d, f"h{_host_id()}-p{os.getpid()}"
               f"-{int(time.time())}-{self._seq:06d}.jsonl")
        self._fh = open(self._path, "a", encoding="utf-8")
        self._written = 0

    def _close(self):
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
        self._fh = None
        self._path = None
        self._written = 0

    def begin_batch(self) -> bool:
        """Per-DRAIN-BATCH validity check (not per record: the liveness
        probe is two stat() syscalls and log_root() resolves the ice
        root — a 65k-record backlog must not pay that 65k times): roll
        when the ice root was repointed (tests) or a sibling process's
        GC unlinked our open segment (appends to the dead inode would be
        invisible to every reader). Returns False when the durable tier
        is disabled (H2O3_LOG_RETAIN_MB <= 0)."""
        if _retain_bytes() <= 0:
            return False
        if self._fh is not None and \
                (self._dir != log_root()
                 or not _segments_mod.alive(self._path, self._fh)):
            self._close()
        return True

    def append(self, rec: dict):
        line = json.dumps(rec, separators=(",", ":"), default=str) + "\n"
        try:
            if self._fh is None:
                self._open()
            self._fh.write(line)
            self._written += len(line)
            if self._written >= _segment_bytes():
                self._close()
                self._gc()
        except OSError:
            # full/read-only disk must never take down the caller —
            # drop the durable tier, keep the ring + stderr alive
            self._close()

    def flush(self):
        if self._fh is not None:
            try:
                self._fh.flush()
            except OSError:
                pass

    def _segments(self) -> list:
        """(mtime, path, size) for every segment under the root, oldest
        first — every process's files, not just ours."""
        return _segments_mod.list_segments(log_root())

    def _gc(self):
        _segments_mod.gc(log_root(), _retain_bytes(),
                         keep_path=self._path)

    def disk_bytes(self) -> int:
        return sum(sz for _, _, sz in self._segments())


class _Sink:
    """Async record pipeline: enqueue() is the (cheap) hot-path entry;
    one daemon drain thread renders the console line, the durable JSONL
    append, the optional rotating text file, and the level counter.
    WARNING+ records drain synchronously."""

    _Q_CAP = 65536

    def __init__(self):
        self._q: deque = deque()
        self._lock = threading.Lock()   # serializes drains (thread +
        #                                 urgent/flush callers)
        self._thread = None
        self._started = False           # fast-path flag: is_alive() per
        #                                 record is measurable on a
        #                                 saturated host
        self._writer = _DurableWriter()
        self._rotating = None           # H2O3_LOG_DIR handler (reinit)
        self._dropped = 0

    # ---- hot path -------------------------------------------------------
    def enqueue(self, rec: dict, urgent: bool):
        _RING.append(rec)
        if rec["level"] in ("ERROR", "CRITICAL") and rec.get("trace"):
            # keep-rule producer, SYNCHRONOUS on purpose: the recorder
            # may finalize this trace before the drain thread runs
            try:
                from h2o3_tpu.obs import recorder as _rec
                _rec.RECORDER.mark_error(rec["trace"])
            except Exception:   # noqa: BLE001 — best-effort correlation
                pass
        # deque append/popleft are atomic (CPython GIL): the hot path
        # must not take the drain lock per record
        self._q.append(rec)   # h2o3-ok: R003 deque ops are GIL-atomic; the drain lock serializes RENDERING, not the queue
        if len(self._q) > self._Q_CAP:
            try:
                self._q.popleft()   # h2o3-ok: R003 deque ops are GIL-atomic; worst case a drop statistic races
                self._dropped += 1   # h2o3-ok: R003 rare overload path; a lost count under race is acceptable for a drop STATISTIC
            except IndexError:
                pass
        if urgent:
            self.drain()
            with self._lock:
                self._writer.flush()
        else:
            # no per-record wake: on a CPU-saturated host, signaling the
            # drain thread per record costs two scheduler round-trips
            # that steal cycles from the device dispatch it rode along
            # with — the drain's own 0.5s poll batches instead (flush()
            # and urgent records still drain immediately)
            if not self._started:
                self._ensure_thread()

    # ---- drain side -----------------------------------------------------
    def _ensure_thread(self):
        t = self._thread
        if t is not None and t.is_alive():
            return
        with self._lock:
            t = self._thread
            if t is not None and t.is_alive():
                return
            t = threading.Thread(target=self._run, daemon=True,
                                 name="h2o3-log-drain")
            self._thread = t
            self._started = True   # h2o3-ok: R003 under self._lock (the with-block above)
        t.start()

    def _run(self):
        # plain sleep, not an event wait: enqueue() deliberately never
        # signals this thread (per-record wakes thrash the scheduler on
        # saturated hosts); urgent records and flush() drain INLINE on
        # the caller, so nothing ever needs to wake us early
        while True:
            time.sleep(0.5)
            if self._thread is not threading.current_thread():
                return              # reinit started a newer drain
            try:
                self.drain()
                with self._lock:
                    self._writer.flush()
            except Exception:   # noqa: BLE001 — the drain must survive
                pass

    def drain(self):
        """Render every queued record (console + durable + counter).
        Callable from any thread; serialized by the sink lock."""
        with self._lock:
            stderr_lines = []
            durable = self._writer.begin_batch()
            if self._dropped:
                # overload drops must not be silent (the ring-overflow
                # lesson): publish, then reset the running count
                n, self._dropped = self._dropped, 0
                try:
                    _dropped_counter().inc(n)
                except Exception:   # noqa: BLE001 — metrics optional here
                    pass
            while True:
                try:
                    rec = self._q.popleft()
                except IndexError:
                    break
                if durable:
                    self._writer.append(rec)
                try:
                    # emit on the module-level var (not the helper's
                    # return value) so R005 sees the `level` label set
                    # and the census gates drift on it
                    _records_counter()
                    _COUNTER.inc(level=rec["level"])
                except Exception:   # noqa: BLE001 — metrics optional here
                    pass
                if _LEVELS.get(rec["level"], 0) >= _STDERR_LEVEL:
                    stderr_lines.append(_fmt(rec))
                if self._rotating is not None:
                    try:
                        self._rotating.emit(logging.makeLogRecord({
                            "name": rec.get("logger", "h2o3_tpu"),
                            "levelname": rec["level"],
                            "levelno": _LEVELS.get(rec["level"], 20),
                            "msg": rec.get("msg", ""),
                            "created": rec.get("t", 0.0)}))
                    except Exception:   # noqa: BLE001
                        pass
            if stderr_lines:
                try:
                    sys.stderr.write("\n".join(stderr_lines) + "\n")
                    sys.stderr.flush()
                except (OSError, ValueError):
                    pass

    def flush(self):
        self.drain()
        with self._lock:
            self._writer.flush()


_SINK = _Sink()
atexit.register(lambda: _SINK.flush())


def _src(pathname: str, lineno) -> str:
    return f"{os.path.basename(pathname)}:{lineno}"


# cached module references for the record hot path: a `from h2o3_tpu.obs
# import tracing` per record costs a sys.modules lookup + binding that a
# CPU-saturated host turns into real microseconds
_TR = None      # h2o3_tpu.obs.tracing
_TL = None      # h2o3_tpu.obs.timeline


def _context():
    """(trace_id, span_id) from the calling thread's obs TLS."""
    global _TR, _TL
    trace = span_id = None
    try:
        if _TR is None:
            from h2o3_tpu.obs import tracing as _tracing
            _TR = _tracing
        trace = getattr(_TR._TLS, "trace_id", None)
        if trace is not None:
            if _TL is None:
                from h2o3_tpu.obs import timeline as _timeline
                _TL = _timeline
            st = getattr(_TL.SPANS._tls, "stack", None)
            if st:
                span_id = st[-1].span_id
    except Exception:   # noqa: BLE001 — context is best-effort
        pass
    return trace, span_id


def _thread_name() -> str:
    name = getattr(_TLS, "tname", None)
    if name is None:
        name = _TLS.tname = threading.current_thread().name
    return name


def _make_rec(level: str, logger: str, msg: str, src: str,
              exc: str | None = None) -> dict:
    trace, span_id = _context()
    rec = {"t": time.time(), "id": next(_IDS), "host": _host_id(),
           "level": level, "logger": logger,
           "thread": _thread_name(),
           "src": src, "msg": msg}
    if exc:
        rec["exc"] = exc[-4000:]
    if trace:
        rec["trace"] = trace
    if span_id:
        rec["span"] = span_id
    return rec


class _StructuredHandler(logging.Handler):
    """Bridges stdlib-logging records (named child loggers, third-party
    emitters on the h2o3_tpu tree) into the sink."""

    def emit(self, record):
        if getattr(_TLS, "emitting", False):
            return                    # a callee of ours logged: drop, do
        _TLS.emitting = True          # not recurse through the chain
        try:
            exc = None
            if record.exc_info and record.exc_info[0] is not None:
                import traceback as _tb
                exc = "".join(_tb.format_exception(*record.exc_info))
            rec = _make_rec(record.levelname, record.name,
                            record.getMessage(),
                            _src(record.pathname, record.lineno), exc)
            rec["t"] = record.created
            _SINK.enqueue(rec, urgent=record.levelno >= logging.WARNING)
        except Exception:   # noqa: BLE001 — logging must never raise
            pass
        finally:
            _TLS.emitting = False


def _build_logger() -> logging.Logger:
    global _LEVEL, _STDERR_LEVEL
    lg = logging.getLogger("h2o3_tpu")   # h2o3-ok: R012 the structured logger's own root — every other module goes through get_logger()
    level = _uenv.env_str("H2O3_LOG_LEVEL", "INFO").upper()
    lg.setLevel(level)
    _LEVEL = _LEVELS.get(level, 20)
    _STDERR_LEVEL = _LEVELS.get(
        (_uenv.env_str("H2O3_LOG_STDERR_LEVEL", "") or level).upper(),
        _LEVEL)
    for h in list(lg.handlers):          # reinit(): drop stale handlers
        lg.removeHandler(h)
    lg.addHandler(_StructuredHandler())
    # classic rotating text log (-log_dir analog), rendered by the sink
    # drain so shim-path records land in it too
    rotating = None
    log_dir = _uenv.env_str("H2O3_LOG_DIR", "")
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        rotating = logging.handlers.RotatingFileHandler(
            os.path.join(log_dir, "h2o3_tpu.log"),
            maxBytes=50 << 20, backupCount=3)
        rotating.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    with _SINK._lock:
        old = _SINK._rotating
        _SINK._rotating = rotating   # h2o3-ok: R003 under _SINK._lock — the with-block above
        if old is not None:
            try:
                old.close()
            except Exception:   # noqa: BLE001
                pass
    return lg


def get_logger(name: str | None = None) -> logging.Logger:
    """The package logger (or a named child: `get_logger("serving")` →
    "h2o3_tpu.serving"). Children propagate into the structured
    handler, so per-subsystem loggers cost nothing to adopt."""
    global _LOGGER
    if _LOGGER is None:
        with _INIT_LOCK:
            if _LOGGER is None:
                _LOGGER = _build_logger()
    return _LOGGER.getChild(name) if name else _LOGGER


def reinit():
    """Rebuild the handler chain + cached levels from the current env
    (tests flip H2O3_LOG_DIR/H2O3_LOG_LEVEL and need the change to
    take)."""
    global _LOGGER, _HOST
    with _INIT_LOCK:
        _HOST = None
        _LOGGER = _build_logger()
    return _LOGGER


# ---------------------------------------------------------------------------
# fast-path shims: build the record directly (no stdlib LogRecord, no
# findCaller frame walk) — this is what hot paths and the bench pay
def _shim(level: str, lvl_no: int, msg, args):
    if _LOGGER is None:
        get_logger()                  # ensure handlers/levels configured
    if lvl_no < _LEVEL:
        return
    if args:
        try:
            msg = str(msg) % args
        except (TypeError, ValueError):
            msg = f"{msg} {args!r}"
    f = sys._getframe(2)
    _SINK.enqueue(_make_rec(level, "h2o3_tpu", str(msg),
                           _src(f.f_code.co_filename, f.f_lineno)),
                 urgent=lvl_no >= 30)


def info(msg, *a):
    _shim("INFO", 20, msg, a)


def warn(msg, *a):
    _shim("WARNING", 30, msg, a)


def err(msg, *a):
    _shim("ERROR", 40, msg, a)


def debug(msg, *a):
    _shim("DEBUG", 10, msg, a)


def flush():
    _SINK.flush()


def disk_bytes() -> int:
    return _SINK._writer.disk_bytes()


# ---------------------------------------------------------------------------
# reading — ring + durable segments (GET /3/Logs and friends)
def _fmt(rec: dict) -> str:
    ts = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(rec.get("t", 0)))
    return (f"{ts} {rec.get('level', '?')} {rec.get('logger', '?')} "
            f"[{rec.get('thread', '?')}]"
            + (f" trace={rec['trace']}" if rec.get("trace") else "")
            + f": {rec.get('msg', '')}")


def recent(n: int = 200) -> list:
    """Last n formatted log lines (water/util/GetLogsFromNode analog —
    the legacy GET /3/Logs/download body)."""
    return [_fmt(r) for r in list(_RING)[-n:]]


def records(n: int = 200) -> list:
    """Last n structured records from the ring, oldest first."""
    return [dict(r) for r in list(_RING)[-n:]]


def _iter_disk_records(newest_first: bool = True,
                       contains: str | None = None,
                       min_mtime: float | None = None):
    """Structured records from every durable segment under the log root
    — including other processes' — torn trailing lines tolerated.
    `contains` prefilters raw lines by substring before the JSON parse
    (exact for trace ids: a record carrying one contains it literally);
    `min_mtime` skips whole segments last written before it — a segment
    holds only records with t <= its mtime, so a `since` query never
    parses segments that cannot match."""
    _SINK.flush()
    segs = _SINK._writer._segments()
    if min_mtime is not None:
        segs = [s for s in segs if s[0] >= min_mtime]
    yield from _segments_mod.iter_jsonl(segs, newest_first=newest_first,
                                        contains=contains)


def search(level=None, since=None, trace=None, grep=None,
           limit: int = 200) -> list:
    """Records matching the GET /3/Logs filters, newest first, deduped
    by (host, id) across ring + disk. `level` is a minimum severity
    ("WARN" matches WARN+ERROR), `since` a unix-seconds lower bound,
    `trace` an exact trace id, `grep` a substring over the message."""
    min_lvl = _LEVELS.get(str(level).upper(), None) if level else None

    def _match(r: dict) -> bool:
        if min_lvl is not None and \
                _LEVELS.get(str(r.get("level", "")).upper(), 0) < min_lvl:
            return False
        if since is not None and float(r.get("t") or 0) < float(since):
            return False
        if trace and r.get("trace") != trace:
            return False
        if grep and grep not in str(r.get("msg", "")):
            return False
        return True

    out = []
    seen = set()
    for r in reversed(list(_RING)):
        if _match(r):
            seen.add((r.get("host"), r.get("id")))
            out.append(dict(r))
            if len(out) >= limit:
                return out
    for r in _iter_disk_records(contains=trace or None,
                                min_mtime=since):
        key = (r.get("host"), r.get("id"))
        if key in seen or not _match(r):
            continue
        seen.add(key)
        out.append(r)
        if len(out) >= limit:
            break
    return out


def trace_records(trace_id: str, limit: int = 256) -> list:
    """All records correlated to one trace, oldest first — what
    GET /3/Trace/{id} interleaves into the span view."""
    out = search(trace=trace_id, limit=limit)
    out.sort(key=lambda r: r.get("t") or 0.0)
    return out


# ---------------------------------------------------------------------------
# node-local file surface (GET /3/Logs/nodes/{node}/files/{name})
def _own_segments() -> list:
    """(mtime, path, size) of THIS node's files only: on a shared ice
    root the dir holds every host's segments, but the node-file surface
    must serve only what this node wrote."""
    prefix = f"h{_host_id()}-"
    return [(mt, p, sz) for mt, p, sz in _SINK._writer._segments()
            if os.path.basename(p).startswith(prefix)]


def list_files() -> list:
    """This node's durable log files: [{name, bytes, mtime}], newest
    first — the names `read_file` accepts."""
    _SINK.flush()
    out = [{"name": os.path.basename(p), "bytes": sz, "mtime": mt}
           for mt, p, sz in _own_segments()]
    out.reverse()
    return out


def read_file(name: str, max_bytes: int = 4 << 20) -> str | None:
    """One durable log file's content by basename ("default" = the
    newest). The name is resolved against the log dir's own listing —
    never joined from caller input — so a hostile {name} path segment
    cannot escape the directory. Returns None when absent."""
    _SINK.flush()
    segs = _own_segments()
    if not segs:
        return None
    if name in ("default", "LOG", ""):
        path = segs[-1][1]
    else:
        by_name = {os.path.basename(p): p for _, p, _sz in segs}
        path = by_name.get(os.path.basename(str(name)))
        if path is None:
            return None
    try:
        with open(path, encoding="utf-8") as fh:
            return fh.read()[-max_bytes:]
    except OSError:
        return None
