"""Logging — water/util/Log.java (log4j-backed per-node rolling files,
buffered pre-init, -log_level) on stdlib logging; one controller process."""

from __future__ import annotations

import logging
import os
import sys

_LOGGER = None


def get_logger() -> logging.Logger:
    global _LOGGER
    if _LOGGER is None:
        lg = logging.getLogger("h2o3_tpu")
        lg.setLevel(os.environ.get("H2O3_LOG_LEVEL", "INFO").upper())
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s"))
        lg.addHandler(h)
        log_dir = os.environ.get("H2O3_LOG_DIR")
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            fh = logging.handlers.RotatingFileHandler(
                os.path.join(log_dir, "h2o3_tpu.log"),
                maxBytes=50 << 20, backupCount=3)
            lg.addHandler(fh)
        _LOGGER = lg
    return _LOGGER


def info(msg, *a):
    get_logger().info(msg, *a)


def warn(msg, *a):
    get_logger().warning(msg, *a)


def err(msg, *a):
    get_logger().error(msg, *a)


def debug(msg, *a):
    get_logger().debug(msg, *a)


# ---- in-memory ring of recent records (GET /3/Logs analog) ---------------
from collections import deque as _deque

_RING: "_deque[str]" = _deque(maxlen=2000)


class _RingHandler(logging.Handler):
    def emit(self, record):
        try:
            _RING.append(self.format(record))
        except Exception:
            pass


_rh = _RingHandler()
_rh.setFormatter(logging.Formatter(
    "%(asctime)s %(levelname)s %(name)s: %(message)s"))
get_logger().addHandler(_rh)


def recent(n: int = 200) -> list:
    """Last n log lines (water/util/GetLogsFromNode analog)."""
    return list(_RING)[-n:]
