"""Pluggable REST authentication — the h2o-security login-module surface
(water/H2OSecurityManager.java + h2o-security/'s JAAS LoginModules:
-basic_auth, -ldap_login, -kerberos_login, -pam_login, -spnego_login).

Methods:
  * basic  — user:password file / dict, constant-time compare (default).
  * ldap   — REAL simple-bind against an LDAP server, implemented on the
             stdlib socket with minimal BER encoding (no ldap3 in this
             image): each login binds as `bind_template.format(user=…)`
             with the presented password; resultCode 0 = authenticated.
  * custom — a Python module exposing authenticate(user, password) (the
             generic LoginModule SPI).
  * kerberos / spnego / pam — loud-reject with guidance: these need a
             KDC/system-PAM stack that is not available here.

Selection via config (utils/config): ai.h2o.api.auth_method plus
ai.h2o.api.ldap_host / ldap_port / ldap_bind_template / ldap_use_ssl or
ai.h2o.api.auth_module. Successful logins are cached per (user, password
hash) for ldap/custom so each REST call doesn't re-bind.
"""

from __future__ import annotations

import hashlib
import hmac
import socket
import ssl as _ssl
from typing import Optional


# ---------------------------------------------------------------------------
# minimal BER/DER for the LDAPv3 simple bind (RFC 4511 §4.2)
def _ber_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(body)]) + body


def _tlv(tag: int, payload: bytes) -> bytes:
    return bytes([tag]) + _ber_len(len(payload)) + payload


def _ber_int(v: int) -> bytes:
    body = v.to_bytes(max(1, (v.bit_length() + 8) // 8), "big")
    return _tlv(0x02, body)


def bind_request(msg_id: int, dn: str, password: str) -> bytes:
    """LDAPMessage { messageID, [APPLICATION 0] BindRequest {version=3,
    name, simple[0] password} }"""
    bind = (_ber_int(3)
            + _tlv(0x04, dn.encode())
            + _tlv(0x80, password.encode()))       # [0] simple
    return _tlv(0x30, _ber_int(msg_id) + _tlv(0x60, bind))


def _read_tlv(buf: bytes, off: int):
    tag = buf[off]
    ln = buf[off + 1]
    off += 2
    if ln & 0x80:
        n = ln & 0x7F
        ln = int.from_bytes(buf[off:off + n], "big")
        off += n
    return tag, buf[off:off + ln], off + ln


def parse_bind_response(data: bytes) -> int:
    """→ resultCode (0 = success; RFC 4511 §4.2.2)."""
    _tag, msg, _ = _read_tlv(data, 0)              # LDAPMessage SEQUENCE
    _t, _mid, off = _read_tlv(msg, 0)              # messageID
    tag, resp, _ = _read_tlv(msg, off)             # [APPLICATION 1]
    if tag != 0x61:
        raise ValueError(f"not a BindResponse (tag 0x{tag:x})")
    _t, code, _ = _read_tlv(resp, 0)               # resultCode ENUMERATED
    return int.from_bytes(code, "big")


# ---------------------------------------------------------------------------
class BasicAuthenticator:
    """user:password dict with constant-time compares (-basic_auth)."""

    def __init__(self, creds: dict):
        self.creds = dict(creds)

    def authenticate(self, user: str, password: str) -> bool:
        ub, pb = user.encode(), password.encode()
        ok = False
        for u, p in self.creds.items():
            if hmac.compare_digest(ub, u.encode()) and \
                    hmac.compare_digest(pb, p.encode()):
                ok = True
        return ok


def _recv_tlv(sock) -> bytes:
    """Read one complete outer TLV (the LDAPMessage) — responses may
    arrive fragmented across TCP segments."""
    head = b""
    while len(head) < 2:
        part = sock.recv(2 - len(head))
        if not part:
            return head
        head += part
    ln = head[1]
    if ln & 0x80:
        n = ln & 0x7F
        while len(head) < 2 + n:
            part = sock.recv(2 + n - len(head))
            if not part:
                return head
            head += part
        total = 2 + n + int.from_bytes(head[2:2 + n], "big")
    else:
        total = 2 + ln
    buf = head
    while len(buf) < total:
        part = sock.recv(total - len(buf))
        if not part:
            break
        buf += part
    return buf


class LdapAuthenticator:
    """Per-login LDAP simple bind (-ldap_login). A successful bind as the
    templated DN with the presented password authenticates the user.
    Only SUCCESSES are cached (bounded, with a TTL) — failures always
    retry the directory, so transient outages cannot lock a user out and
    a revoked account ages out within `cache_ttl` seconds."""

    CACHE_MAX = 1024

    def __init__(self, host: str, port: int = 389,
                 bind_template: str = "uid={user}",
                 use_ssl: bool = False, timeout: float = 5.0,
                 cache_ttl: float = 300.0):
        import threading
        self.host = host
        self.port = int(port)
        self.bind_template = bind_template
        self.use_ssl = use_ssl
        self.timeout = timeout
        self.cache_ttl = float(cache_ttl)
        self._cache: dict = {}      # key -> expiry monotonic time
        self._lock = threading.Lock()   # handlers run on server threads

    @staticmethod
    def _escape_dn(value: str) -> str:
        """RFC 4514 attribute-value escaping: without it a username like
        'x,ou=admins' would inject extra RDNs into the templated DN."""
        out = []
        for i, ch in enumerate(value):
            if ch in ',+"\\<>;=' or (ch == "#" and i == 0) or \
                    (ch == " " and i in (0, len(value) - 1)):
                out.append("\\" + ch)
            elif ord(ch) < 0x20:
                out.append("\\%02x" % ord(ch))
            else:
                out.append(ch)
        return "".join(out)

    def authenticate(self, user: str, password: str) -> bool:
        import time
        if not password:
            return False            # RFC 4513 §5.1.2: no unauthenticated bind
        key = (user, hashlib.sha256(password.encode()).hexdigest())
        now = time.monotonic()
        with self._lock:
            exp = self._cache.get(key)
        if exp is not None and now < exp:
            return True
        dn = self.bind_template.format(user=self._escape_dn(user))
        try:
            sock = socket.create_connection((self.host, self.port),
                                            timeout=self.timeout)
            if self.use_ssl:
                ctx = _ssl.create_default_context()
                sock = ctx.wrap_socket(sock, server_hostname=self.host)
            try:
                sock.sendall(bind_request(1, dn, password))
                data = _recv_tlv(sock)
                ok = bool(data) and parse_bind_response(data) == 0
            finally:
                sock.close()
        except (OSError, ValueError, IndexError):
            ok = False
        if ok:
            with self._lock:
                if len(self._cache) >= self.CACHE_MAX:
                    self._cache = {k: e for k, e in self._cache.items()
                                   if e > now}
                    while len(self._cache) >= self.CACHE_MAX:
                        self._cache.pop(next(iter(self._cache)))
                self._cache[key] = now + self.cache_ttl
        return ok


class CustomAuthenticator:
    """Generic LoginModule SPI: a module with authenticate(user, pw)."""

    def __init__(self, module_path: str):
        import importlib
        self.mod = importlib.import_module(module_path)
        if not callable(getattr(self.mod, "authenticate", None)):
            raise ValueError(
                f"auth module {module_path!r} has no authenticate(user, "
                "password) callable")

    def authenticate(self, user: str, password: str) -> bool:
        return bool(self.mod.authenticate(user, password))


def resolve_authenticator(creds: Optional[dict] = None):
    """Build the configured authenticator (None → no auth required)."""
    from h2o3_tpu.utils import config as _cfg
    method = str(_cfg.get_property("api.auth_method", "") or "").lower()
    if method in ("", "basic"):
        return BasicAuthenticator(creds) if creds else None
    if method == "ldap":
        host = _cfg.get_property("api.ldap_host", None)
        if not host:
            raise ValueError("auth_method=ldap requires "
                             "ai.h2o.api.ldap_host")
        return LdapAuthenticator(
            host, int(_cfg.get_property("api.ldap_port", 389) or 389),
            str(_cfg.get_property("api.ldap_bind_template",
                                  "uid={user}")),
            _cfg.get_bool("api.ldap_use_ssl", False))
    if method == "custom":
        mod = _cfg.get_property("api.auth_module", None)
        if not mod:
            raise ValueError("auth_method=custom requires "
                             "ai.h2o.api.auth_module")
        return CustomAuthenticator(str(mod))
    if method in ("kerberos", "spnego", "pam"):
        raise NotImplementedError(
            f"auth_method={method} needs a KDC / system PAM stack that "
            "is not available in this runtime (the reference wires these "
            "through JAAS LoginModules); use basic, ldap or custom")
    raise ValueError(f"unknown auth_method {method!r} "
                     "(basic|ldap|custom|kerberos|spnego|pam)")
