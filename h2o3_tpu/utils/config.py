"""Runtime property store — the analog of the reference's flag/property
system (water/H2O.java:327 OptArgs; every CLI flag is also settable as a
java system property with the ``ai.h2o.`` prefix, H2O.java:2253-2264).

Properties come from three layers, later wins:
  1. defaults registered by subsystems (`register_default`)
  2. environment variables (``H2O3_TPU_<UPPER_SNAKE>``)
  3. runtime `set_property` (the Rapids ``setproperty`` prim /
     ``/3/SetProperty``-style admin calls)
"""

from __future__ import annotations

import os
import threading

_LOCK = threading.Lock()
_PROPS: dict = {}
_DEFAULTS: dict = {}

PREFIX = "ai.h2o."          # reference property prefix, accepted verbatim
ENV_PREFIX = "H2O3_TPU_"


def _norm(name: str) -> str:
    if name.startswith(PREFIX):
        name = name[len(PREFIX):]
    return name.replace("-", ".").lower()


def register_default(name: str, value) -> None:
    with _LOCK:
        _DEFAULTS[_norm(name)] = value


def set_property(name: str, value) -> None:
    with _LOCK:
        _PROPS[_norm(name)] = value


def get_property(name: str, default=None):
    key = _norm(name)
    with _LOCK:
        if key in _PROPS:
            return _PROPS[key]
    # h2o3-ok: R017 layered property store — names are dynamic ai.h2o.* properties mapped to H2O3_TPU_*; the census covers the typed-accessor surface, properties are censused via register_default
    env = os.environ.get(ENV_PREFIX + key.replace(".", "_").upper())
    if env is not None:
        return env
    with _LOCK:
        return _DEFAULTS.get(key, default)


def get_bool(name: str, default: bool = False) -> bool:
    v = get_property(name, default)
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


def all_properties() -> dict:
    with _LOCK:
        out = dict(_DEFAULTS)
        out.update(_PROPS)
    return out
