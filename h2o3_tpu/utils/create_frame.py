"""h2o.create_frame — random frame generator (water/rapids CreateFrame /
h2o-py create_frame): synthesizes mixed-type frames for tests/demos."""

from __future__ import annotations

import numpy as np

from h2o3_tpu.core.frame import Frame


def create_frame(rows: int = 10000, cols: int = 10, randomize: bool = True,
                 categorical_fraction: float = 0.2, factors: int = 5,
                 integer_fraction: float = 0.2, binary_fraction: float = 0.1,
                 time_fraction: float = 0.0, string_fraction: float = 0.0,
                 real_range: float = 100.0, integer_range: float = 100.0,
                 missing_fraction: float = 0.01, has_response: bool = False,
                 response_factors: int = 2, seed: int = -1,
                 frame_id: str | None = None) -> Frame:
    rng = np.random.default_rng(seed if seed and seed > 0 else None)
    n_cat = int(cols * categorical_fraction)
    n_int = int(cols * integer_fraction)
    n_bin = int(cols * binary_fraction)
    n_time = int(cols * time_fraction)
    n_str = int(cols * string_fraction)
    n_real = max(0, cols - n_cat - n_int - n_bin - n_time - n_str)
    data = {}
    types = {}
    i = 0

    def miss(col):
        if missing_fraction > 0:
            m = rng.random(rows) < missing_fraction
            col = col.astype(object) if col.dtype == object else col
            if col.dtype == object:
                col[m] = None
            else:
                col = col.astype(np.float64)
                col[m] = np.nan
        return col

    for _ in range(n_real):
        data[f"C{i+1}"] = miss(rng.uniform(-real_range, real_range, rows))
        i += 1
    for _ in range(n_int):
        data[f"C{i+1}"] = miss(rng.integers(
            -int(integer_range), int(integer_range), rows).astype(np.float64))
        i += 1
    for _ in range(n_bin):
        data[f"C{i+1}"] = miss(rng.integers(0, 2, rows).astype(np.float64))
        i += 1
    for _ in range(n_cat):
        lv = np.array([f"c{i}.l{j}" for j in range(factors)], object)
        data[f"C{i+1}"] = miss(lv[rng.integers(0, factors, rows)])
        i += 1
    for _ in range(n_time):
        base = np.datetime64("2020-01-01").astype("datetime64[ms]").astype(np.int64)
        data[f"C{i+1}"] = miss((base + rng.integers(0, 365 * 86400000, rows))
                               .astype(np.float64))
        types[f"C{i+1}"] = "time"
        i += 1
    for _ in range(n_str):
        words = np.array(["".join(rng.choice(list("abcdefgh"), 8))
                          for _ in range(rows)], object)
        data[f"C{i+1}"] = miss(words)
        types[f"C{i+1}"] = "str"
        i += 1
    if has_response:
        if response_factors > 1:
            lv = np.array([f"resp{j}" for j in range(response_factors)], object)
            data["response"] = lv[rng.integers(0, response_factors, rows)]
        else:
            data["response"] = rng.normal(0, 1, rows)
    return Frame.from_dict(data, key=frame_id, column_types=types)
