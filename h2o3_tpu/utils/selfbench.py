"""In-product self-benchmarks — water/init/{NetworkBench,Linpack,
MemoryBandwidth}.java rebuilt for TPU hardware.

Reference: NetworkBench.java:16-18 (all-to-all + MRTask message
latency/throughput across the cloud), Linpack.java (per-node FLOPS),
MemoryBandwidth.java (per-node memory bandwidth), exposed over REST and used
to sanity-check a cluster before long jobs.

TPU equivalents: the "network" is ICI — measured with psum/all_gather
round-trips over the mesh; "Linpack" is an MXU matmul FLOPs probe in
bfloat16 and float32; "memory bandwidth" is an HBM triad stream. CLI:
`python -m h2o3_tpu.utils.selfbench`."""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def _timeit(fn, *args, repeats=5):
    fn(*args)  # compile
    jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(repeats):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / repeats


def network_bench(sizes=(1 << 10, 1 << 16, 1 << 22)) -> list:
    """ICI collective latency/bandwidth: psum + all_gather per payload size
    (NetworkBench's all-to-all matrix collapses to mesh collectives)."""
    from h2o3_tpu.parallel import mesh as M
    cloud = M.cloud()
    mesh = cloud.mesh
    axis = M.ROWS
    n_dev = cloud.n_rows_shards
    from jax.sharding import NamedSharding, PartitionSpec as P
    results = []
    for size in sizes:
        n = size // 4  # f32 elements per device
        x = jax.device_put(
            jnp.ones((n_dev, max(n, 1)), jnp.float32),
            NamedSharding(mesh, P(axis, None)))

        @jax.jit
        def allreduce(x):
            from jax.experimental.shard_map import shard_map
            return shard_map(
                lambda s: jax.lax.psum(s, axis),
                mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None)
            )(x)

        dt = _timeit(allreduce, x)
        results.append({
            "op": "psum", "payload_bytes_per_device": int(n * 4),
            "latency_us": dt * 1e6,
            "algo_bw_gbps": (n * 4 * 2 * (n_dev - 1) / max(n_dev, 1))
                            / max(dt, 1e-12) / 1e9,
        })
    return results


def linpack(n: int = 4096, dtype="bfloat16") -> dict:
    """MXU FLOPs probe (Linpack.java analog): C = A @ B throughput."""
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    a = jnp.ones((n, n), dt)
    b = jnp.ones((n, n), dt)

    @jax.jit
    def mm(a, b):
        return jnp.dot(a, b, preferred_element_type=jnp.float32)

    t = _timeit(mm, a, b)
    flops = 2.0 * n * n * n
    return {"n": n, "dtype": dtype, "seconds": t,
            "gflops": flops / max(t, 1e-12) / 1e9}


def memory_bandwidth(n: int = 1 << 24) -> dict:
    """HBM stream triad (MemoryBandwidth.java analog): a = b + 2·c."""
    b = jnp.ones(n, jnp.float32)
    c = jnp.ones(n, jnp.float32)

    @jax.jit
    def triad(b, c):
        return b + 2.0 * c

    t = _timeit(triad, b, c)
    bytes_moved = n * 4 * 3
    return {"elements": n, "seconds": t,
            "gbps": bytes_moved / max(t, 1e-12) / 1e9}


def publish(results: dict) -> dict:
    """Emit selfbench numbers into the obs registry so /metrics and
    bench.py report the same hardware facts (the WaterMeter contract:
    one source of truth for scrapers and humans)."""
    from h2o3_tpu.obs import metrics as om
    g = om.gauge("h2o3_selfbench", "in-product hardware self-benchmarks "
                 "(linpack gflops, HBM triad GB/s, ICI collectives)")
    # one label schema for every probe (R005): absent dimensions are "",
    # so the series aggregate instead of splitting per probe family
    lp = results.get("linpack")
    if lp:
        g.set(lp["gflops"], probe="linpack_gflops", dtype=lp["dtype"],
              payload_bytes="")
    mb = results.get("memory_bandwidth")
    if mb:
        g.set(mb["gbps"], probe="hbm_triad_gbps", dtype="",
              payload_bytes="")
    for row in results.get("network") or []:
        pb = str(row["payload_bytes_per_device"])
        g.set(row["latency_us"], probe="ici_latency_us", dtype="",
              payload_bytes=pb)
        g.set(row["algo_bw_gbps"], probe="ici_bw_gbps", dtype="",
              payload_bytes=pb)
    return results


def run_all() -> dict:
    return publish({"network": network_bench(), "linpack": linpack(),
                    "memory_bandwidth": memory_bandwidth(),
                    "backend": jax.default_backend(),
                    "n_devices": len(jax.devices())})


if __name__ == "__main__":
    import json
    import h2o3_tpu
    h2o3_tpu.init()
    print(json.dumps(run_all(), indent=2, default=float))   # h2o3-ok: R012 `python -m ...selfbench` CLI: the JSON report on stdout IS the interface
