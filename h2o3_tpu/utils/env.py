"""Typed environment accessors — the one sanctioned way to read H2O3_*
configuration.

The config surface grew to 60+ `H2O3_*` variables read through scattered
`os.environ.get(...)` + ad-hoc `int()`/`float()` parses, with three
recurring defects this module retires:

  * crash-at-read: ``int(os.environ.get("H2O3_SCORER_CACHE_SIZE", "64"))``
    raises ValueError on a typo'd value — at import time or mid-request;
  * inconsistent defaults: ``float(os.environ.get(NAME, "60") or 0)``
    means unset → 60 but empty → 0, two defaults for one variable;
  * no census: nothing enumerated the config surface, so renames and
    drift were invisible (the failure mode METRICS.md/SPANS.md already
    gate for metric and span names).

Contract, enforced package-wide by analyzer rule R017:

  * every H2O3_* read goes through ``env_str``/``env_int``/``env_float``/
    ``env_bool`` with a LITERAL variable name and a LITERAL default;
  * each variable has exactly ONE accessor call site package-wide (its
    declaration site) — modules that share a variable import the owning
    module's helper instead of re-reading;
  * the generated census ``h2o3_tpu/analysis/ENV.md`` (``python -m
    h2o3_tpu.analysis --write-census``) is therefore the complete,
    committed config surface, freshness-gated in pre-commit/tier-1.

Parse semantics: unset and empty-string both yield the default (an empty
export is "not configured", not "zero"); an unparseable value warns once
per (name, value) and yields the default instead of crashing.
"""

from __future__ import annotations

import os
import warnings

_TRUE = {"1", "true", "yes", "on"}
_FALSE = {"0", "false", "no", "off"}
_warned: set = set()


def _raw(name: str):
    """The package's single os.environ touchpoint for H2O3_* reads."""
    return os.environ.get(name)


def _bad(name: str, raw: str, kind: str, default):
    key = (name, raw)
    if key in _warned:
        return
    _warned.add(key)
    warnings.warn(
        f"{name}={raw!r} is not a valid {kind}; using default {default!r}",
        RuntimeWarning, stacklevel=3)


def env_str(name: str, default: str = "") -> str:
    """String config var; unset/empty → default."""
    v = _raw(name)
    if v is None or v == "":
        return default
    return v


def env_int(name: str, default: int) -> int:
    v = _raw(name)
    if v is None or v.strip() == "":
        return default
    try:
        return int(v.strip())
    except ValueError:
        _bad(name, v, "int", default)
        return default


def env_float(name: str, default: float) -> float:
    v = _raw(name)
    if v is None or v.strip() == "":
        return default
    try:
        return float(v.strip())
    except ValueError:
        _bad(name, v, "float", default)
        return default


def env_bool(name: str, default: bool = False) -> bool:
    """Boolean config var: 1/true/yes/on and 0/false/no/off (any case);
    unset/empty → default; anything else warns and yields the default
    (the old ``!= "0"`` idiom silently read "flase" as enabled)."""
    v = _raw(name)
    if v is None or v.strip() == "":
        return default
    s = v.strip().lower()
    if s in _TRUE:
        return True
    if s in _FALSE:
        return False
    _bad(name, v, "bool", default)
    return default


def is_set(name: str) -> bool:
    """Presence check (set to anything, even empty) — for call sites
    whose failure mode must stay LOUD when a variable is missing (the
    explicit multi-host bootstrap). Value reads still go through the
    typed accessors; this never parses."""
    return _raw(name) is not None


def process_id() -> int:
    """This process' rank in the cloud — H2O3_PROCESS_ID, wired by the
    multihost bootstrap. Declared here (not per-reader) because the
    timeline, the structured logger and jax.distributed init all need
    it and R017 allows one declaration site per variable."""
    return env_int("H2O3_PROCESS_ID", 0)
