"""Small host-side statistical helpers (no scipy in the image)."""

from __future__ import annotations

import math


def norm_ppf(p: float) -> float:
    """Inverse standard-normal CDF — Acklam's rational approximation
    (|rel err| < 1.15e-9), used for iSAX Gaussian breakpoints
    (reference: timeseries/AstIsax.java uses a breakpoint table)."""
    if p <= 0.0:
        return -math.inf
    if p >= 1.0:
        return math.inf
    a = (-3.969683028665376e+01, 2.209460984245205e+02,
         -2.759285104469687e+02, 1.383577518672690e+02,
         -3.066479806614716e+01, 2.506628277459239e+00)
    b = (-5.447609879822406e+01, 1.615858368580409e+02,
         -1.556989798598866e+02, 6.680131188771972e+01,
         -1.328068155288572e+01)
    c = (-7.784894002430293e-03, -3.223964580411365e-01,
         -2.400758277161838e+00, -2.549732539343734e+00,
         4.374664141464968e+00, 2.938163982698783e+00)
    d = (7.784695709041462e-03, 3.224671290700398e-01,
         2.445134137142996e+00, 3.754408661907416e+00)
    plow, phigh = 0.02425, 1 - 0.02425
    if p < plow:
        q = math.sqrt(-2 * math.log(p))
        return (((((c[0] * q + c[1]) * q + c[2]) * q + c[3]) * q + c[4])
                * q + c[5]) / \
               ((((d[0] * q + d[1]) * q + d[2]) * q + d[3]) * q + 1)
    if p > phigh:
        q = math.sqrt(-2 * math.log(1 - p))
        return -(((((c[0] * q + c[1]) * q + c[2]) * q + c[3]) * q + c[4])
                 * q + c[5]) / \
               ((((d[0] * q + d[1]) * q + d[2]) * q + d[3]) * q + 1)
    q = p - 0.5
    r = q * q
    return (((((a[0] * r + a[1]) * r + a[2]) * r + a[3]) * r + a[4])
            * r + a[5]) * q / \
           (((((b[0] * r + b[1]) * r + b[2]) * r + b[3]) * r + b[4])
            * r + 1)


def norm_cdf(x: float) -> float:
    return 0.5 * (1.0 + math.erf(x / math.sqrt(2.0)))
