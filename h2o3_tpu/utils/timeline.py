"""Tracing/profiling — water/TimeLine + MRTask.profile rebuilt for a
single-controller device runtime.

Reference: water.TimeLine (TimeLine.java:22) is a lock-free ring buffer of
every UDP/TCP packet on every node, snapshotted cluster-wide via
/3/Timeline; MRTask.profile() (MRTask.java:190-378) times each phase of a
distributed task.

TPU-native: the packet flight recorder becomes a DISPATCH recorder — a ring
buffer of device-program launches (name, args-bytes, enqueue time, completion
time when measured) — and deep kernel-level tracing delegates to jax.profiler
(XLA's own tracer; the TPU equivalent of reading the wire). `profile(fn)`
wraps any jitted step the way MRTask.profile wrapped a task.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from dataclasses import dataclass, field


@dataclass
class DispatchEvent:
    name: str
    t_enqueue: float
    t_done: float | None = None
    arg_bytes: int = 0
    note: str = ""


class Timeline:
    """Ring buffer of device dispatches (TimeLine's 2048-event ring)."""

    CAPACITY = 2048

    def __init__(self):
        self._ring: deque = deque(maxlen=self.CAPACITY)
        self._lock = threading.Lock()

    def record(self, name: str, arg_bytes: int = 0, note: str = "") -> DispatchEvent:
        ev = DispatchEvent(name=name, t_enqueue=time.time(),
                           arg_bytes=arg_bytes, note=note)
        with self._lock:
            self._ring.append(ev)
        return ev

    def snapshot(self) -> list:
        """/3/Timeline: most-recent dispatches, oldest first."""
        with self._lock:
            return [
                {"name": e.name, "enqueue": e.t_enqueue, "done": e.t_done,
                 "duration_ms": None if e.t_done is None
                 else 1000 * (e.t_done - e.t_enqueue),
                 "arg_bytes": e.arg_bytes, "note": e.note}
                for e in self._ring
            ]

    def clear(self):
        with self._lock:
            self._ring.clear()


TIMELINE = Timeline()


@contextlib.contextmanager
def span(name: str, note: str = ""):
    """Record one controller-side span into the timeline."""
    ev = TIMELINE.record(name, note=note)
    try:
        yield ev
    finally:
        ev.t_done = time.time()


def profile(fn, *args, sync=True, name=None, **kwargs):
    """MRTask.profile analog: run a (jitted) step, return (result, timing).

    Timing splits enqueue (controller→device dispatch) from completion
    (device execution + transfer), the moral split of MRProfile's
    {RPC fan-out, map, reduce} phases.
    """
    import jax
    nm = name or getattr(fn, "__name__", "step")
    ev = TIMELINE.record(nm)
    t0 = time.time()
    out = fn(*args, **kwargs)
    t_enq = time.time()
    if sync:
        out = jax.block_until_ready(out)
    ev.t_done = time.time()
    return out, {"name": nm, "enqueue_ms": 1000 * (t_enq - t0),
                 "total_ms": 1000 * (ev.t_done - t0)}


@contextlib.contextmanager
def xla_trace(logdir: str):
    """Deep tracing via the XLA profiler (xprof) — the /3/Timeline of the
    device itself. View with tensorboard or xprof."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
