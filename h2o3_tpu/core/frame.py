"""Columnar Frame/Vec data plane — H2O's "Fluid Vectors" rebuilt for TPU HBM.

Reference: water/fvec/Frame.java:64 (named set of Vecs), water/fvec/Vec.java:157
(typed distributed column; ESPC row layout Vec.java:163-171; type system
Vec.java:207-212), water/fvec/Chunk.java + ~20 compression codecs
(C0D/C0L/C1/C1S/C2/C2S/C4/C8/CBS/CStr/CXI/…), water/fvec/NewChunk.java (write
buffer that picks the best codec on close), water/fvec/RollupStats.java:30
(lazy per-Vec min/max/mean/sigma/NA stats).

TPU-native design:
  * A Vec is ONE row-sharded, padded jax.Array in HBM, dtype-packed by a codec
    chosen at ingest (const / int8 / int16 / int32 / float32, with integer
    bias), plus an optional uint8 NA mask side-plane. This keeps the codec
    benefits of Chunk compression (HBM footprint, bandwidth) while staying a
    dense static-shape array XLA can tile.  Decoding (cast·scale+bias, NA→NaN)
    happens inside consumer jits, where XLA fuses it into the first kernel
    for free — the moral equivalent of Chunk.atd() inlined into the map loop.
  * Rows are padded to a multiple of (row-shards × 8) — H2O's uneven ESPC
    chunking becomes even tiling + a padding mask.
  * Strings live on DEVICE as a dictionary-coded plane (StrVec below:
    int32 codes in HBM + a host-side unique-string table), so string
    munging (strlen/toupper/substring/…) runs O(unique) host-side and
    O(rows) on device; UUIDs remain host numpy object arrays (C16Chunk
    has no device analog yet); numeric / categorical / time columns live
    in HBM.
  * Rollups are computed lazily in one fused jit pass and cached, invalidated
    on write — same contract as RollupStats.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.analysis.lockdep import make_lock
from h2o3_tpu.core.kvstore import DKV
from h2o3_tpu.core import tiering as _tiering
from h2o3_tpu.parallel import mesh as _mesh
from h2o3_tpu.parallel import mrtask as _mr

# ---------------------------------------------------------------------------
# Vec types (Vec.java:207-212)
T_NUM = "num"
T_CAT = "enum"
T_TIME = "time"
T_STR = "str"
T_UUID = "uuid"
T_BAD = "bad"  # all-NA column


# ---------------------------------------------------------------------------
# Codecs (the NewChunk "pick best compression on close" logic)
@dataclasses.dataclass(frozen=True)
class Codec:
    kind: str           # "const" | "i8" | "i16" | "i32" | "f32"
    bias: float = 0.0   # value = stored + bias   (integer kinds)
    const_val: float = float("nan")  # for kind == "const"

    @property
    def np_dtype(self):
        return {"i8": np.int8, "i16": np.int16, "i32": np.int32,
                "f32": np.float32, "const": np.int8}[self.kind]


def _choose_codec(col: np.ndarray, mask: np.ndarray):
    """Pick the narrowest storage for a float64 host column (NewChunk.close).

    Returns (packed ndarray, Codec). NAs are stored as 0 in packed form; the
    mask side-plane is authoritative. Pass-frugal (this is the ingest
    pack hot path): the masked-value copy is skipped when there are no
    NAs, and scalar min/max pre-checks short-circuit the all-integral
    scan for ordinary float columns — results are identical.
    """
    has_na = bool(mask.any())
    valid = col[mask == 0] if has_na else col
    if valid.size == 0:
        return np.zeros(col.shape, np.int8), Codec("const", const_val=float("nan"))
    vmin, vmax = float(valid.min()), float(valid.max())
    if vmin == vmax:  # constant col; NAs (incl. padding) live in the mask
        return np.zeros(col.shape, np.int8), Codec("const", const_val=vmin)
    filled = np.where(mask, 0.0, col) if has_na else col
    is_int = math.isfinite(vmin) and math.isfinite(vmax) \
        and math.floor(vmin) == vmin and math.floor(vmax) == vmax \
        and bool(np.all(np.floor(valid) == valid))
    if is_int:
        span = vmax - vmin
        for kind, lim, dt in (("i8", 254, np.int8), ("i16", 65534, np.int16)):
            if span <= lim:
                bias = math.floor(vmin + span // 2 + 1)  # center into signed range
                packed = np.where(mask, 0, filled - bias).astype(dt)
                return packed, Codec(kind, bias=bias)
        if -2**31 < vmin and vmax < 2**31 - 1:
            packed = np.where(mask, 0, filled).astype(np.int32)
            return packed, Codec("i32")
    packed = filled.astype(np.float32)  # NAs already zeroed in filled
    return packed, Codec("f32")


def _decode_f32(data: jax.Array, codec: Codec, mask: Optional[jax.Array]):
    """Decode packed storage to f32 with NaN NAs. Call inside jit; fuses."""
    if codec.kind == "const":
        x = jnp.full(data.shape, codec.const_val, jnp.float32)
    else:
        x = data.astype(jnp.float32)
        if codec.bias:
            x = x + jnp.float32(codec.bias)
    if mask is not None:
        x = jnp.where(mask != 0, jnp.float32(jnp.nan), x)
    return x


# one resident wrapper: a per-call jax.jit(_decode_f32) in as_f32 rebuilt
# the wrapper on every decoded read (R001)
_DECODE_F32_JIT = jax.jit(_decode_f32, static_argnums=1)


# ---------------------------------------------------------------------------
@dataclasses.dataclass
class Rollups:
    """RollupStats.java:30 — cached per-Vec stats."""
    min: float
    max: float
    mean: float
    sigma: float
    nas: int
    zeros: int
    is_int: bool


# one rollup device dispatch in flight at a time, process-wide: leaf
# lock (nothing else is acquired under it; mrtask's jit-wrapper cache
# lock below it is itself a leaf)
_ROLLUP_LOCK = make_lock("vec.rollups")


class Vec:
    """A typed, row-sharded, dtype-packed column resident in TPU HBM."""

    def __init__(self, data, codec: Codec, mask, nrows: int, type: str = T_NUM,
                 domain: Optional[np.ndarray] = None, host_data=None,
                 packed_host=None, packed_mask=None):
        # the packed planes live behind the DKV tier pager: `data`/`mask`
        # are fault-on-read properties over one TierChunk (HBM → host
        # codec bytes → disk), None for str/uuid/sparse layouts. A chunk
        # may be born cold (data=None + packed_host): budgeted ingest
        # parks codec bytes in the host tier and faults on first access.
        if data is not None or packed_host is not None:
            host = (packed_host, packed_mask) \
                if packed_host is not None else None
            self._chunk = _tiering.PAGER.new_chunk(data, mask, host=host,
                                                   label=type)
        else:
            self._chunk = None
        self.codec = codec
        self.nrows = nrows
        self.type = type
        self.domain = domain        # np.ndarray[str] for T_CAT
        self.host_data = host_data  # np object array for T_STR/T_UUID
        self._rollups: Optional[Rollups] = None

    @property
    def data(self):
        """Packed jax.Array (padded,) — faults the chunk to HBM."""
        ch = self._chunk
        return ch.device()[0] if ch is not None else None

    @property
    def mask(self):
        """uint8 NA plane (padded,) or None — faults alongside data."""
        ch = self._chunk
        return ch.device()[1] if ch is not None else None

    # ---- construction ---------------------------------------------------
    @staticmethod
    def from_numpy(col: np.ndarray, type: Optional[str] = None,
                   domain=None) -> "Vec":
        """Ingest one host column, inferring type (ParseSetup column typing)."""
        c = _mesh.cloud()
        if col.dtype == object or col.dtype.kind in "US":
            return Vec._from_strings(col, force_type=type, domain=domain)
        if np.issubdtype(col.dtype, np.datetime64):
            ms = col.astype("datetime64[ms]").astype(np.int64).astype(np.float64)
            nat = np.isnat(col.astype("datetime64[ms]"))
            return Vec._from_floats(np.where(nat, 0.0, ms), nat, T_TIME)
        if col.dtype == bool:
            col = col.astype(np.float64)
        col = col.astype(np.float64, copy=False)
        mask = np.isnan(col)
        vtype = type or (T_CAT if domain is not None else T_NUM)
        return Vec._from_floats(col, mask, vtype, domain)

    @staticmethod
    def _from_floats(col, mask, vtype, domain=None) -> "Vec":
        c = _mesh.cloud()
        n = len(col)
        pad = c.padded_rows(n)
        colp = np.zeros(pad, np.float64)
        colp[:n] = np.where(mask, 0.0, col) if mask.any() else col
        maskp = np.ones(pad, bool)       # padding rows are NA
        maskp[:n] = mask
        packed, codec = _choose_codec(colp, maskp)
        mask_np = maskp.astype(np.uint8) if maskp.any() else None
        if mask_np is None and n < pad:  # padding must always be masked
            mask_np = np.zeros(pad, np.uint8)
            mask_np[n:] = 1
        dom = np.asarray(domain, dtype=object) if domain is not None else None
        if _tiering.PAGER.ingest_cold:
            # budgeted/cold ingest: park the codec bytes in the HOST
            # tier and let first access fault them — an eager device_put
            # here would spike HBM past the budget before the pager
            # could act (H2O3_TPU_INGEST_COLD forces this without a
            # budget for spike-free bulk ingest)
            return Vec(None, codec, None, n, vtype, dom,
                       packed_host=packed, packed_mask=mask_np)
        data = _mr.device_put_rows(packed)
        dmask = _mr.device_put_rows(mask_np) if mask_np is not None else None
        # packed/mask_np are the codec bytes the pager's host tier keeps
        return Vec(data, codec, dmask, n, vtype, dom,
                   packed_host=packed, packed_mask=mask_np)

    @staticmethod
    def from_device_floats(col_j, vtype=T_NUM, domain=None) -> "Vec":
        """Device-resident construction — the hand-off point for device
        mungers (sort/merge/group_by): no host round trip. Stores with the
        f32 codec (re-running the codec chooser would need host stats)."""
        c = _mesh.cloud()
        n = int(col_j.shape[0])
        pad = c.padded_rows(n)

        def pack(col_j):
            full = jnp.full(pad, jnp.nan, jnp.float32) \
                .at[:n].set(col_j.astype(jnp.float32))
            mask = jnp.isnan(full)
            return jnp.where(mask, 0.0, full), mask.astype(jnp.uint8)

        sh = c.rows_sharding(1)
        # cached_jit: pack's closure is (pad, n) ints, so repeated
        # device-munger hand-offs at one size reuse one program
        packed, dmask = _mr.cached_jit(pack, out_shardings=(sh, sh))(col_j)
        dom = np.asarray(domain, dtype=object) if domain is not None else None
        return Vec(packed, Codec("f32"), dmask, n, vtype, dom)

    @staticmethod
    def _from_strings(col: np.ndarray, force_type=None, domain=None) -> "Vec":
        """Strings parse to categorical by default (CsvParser enum detection);
        T_STR keeps raw host strings."""
        n = len(col)
        sarr = np.asarray(col, dtype=object)
        na = np.array([s is None or (isinstance(s, float) and math.isnan(s))
                       or (isinstance(s, str) and s == "") for s in sarr])
        if force_type == T_STR:
            # device string plane: dictionary codes on device (CStrChunk
            # analog; see StrVec) — no n-sized host object array retained
            return StrVec.encode(sarr)
        if domain is None:
            uniq = sorted({str(s) for s, bad in zip(sarr, na) if not bad})
            domain = np.asarray(uniq, dtype=object)
        lookup = {s: i for i, s in enumerate(domain)}
        codes = np.array([-1 if bad else lookup.get(str(s), -1)
                          for s, bad in zip(sarr, na)], np.float64)
        mask = codes < 0
        return Vec._from_floats(np.where(mask, 0.0, codes), mask, T_CAT, domain)

    # ---- access ---------------------------------------------------------
    @property
    def padded_len(self) -> int:
        # chunk metadata, NOT .data: reading the shape must never fault a
        # demoted chunk back into HBM
        if self._chunk is not None:
            return self._chunk.rows
        return len(self.host_data)

    def as_f32(self) -> jax.Array:
        """Decoded f32 view (NaN NAs, padding = NaN). Materializes; prefer
        Frame.matrix() for multi-column consumers."""
        if self.type == T_STR:
            raise TypeError("string Vec has no numeric view")
        return _DECODE_F32_JIT(self.data, self.codec, self.mask)

    def to_numpy(self) -> np.ndarray:
        if self.type == T_STR:
            return self.host_data.copy()
        # host_fetch: in a multi-controller cloud the decoded column spans
        # every process's shards — gather before fetching
        x = _mr.host_fetch(self.as_f32())[: self.nrows]
        return x

    def levels(self):
        return list(self.domain) if self.domain is not None else None

    @property
    def cardinality(self) -> int:
        return len(self.domain) if self.domain is not None else 0

    # ---- rollups (lazy, cached) -----------------------------------------
    def rollups(self) -> Rollups:
        r = self._rollups
        if r is None:
            # compute-once, process-wide: parallel model builds (grid
            # search) all roll up the shared training frame's vecs at
            # the same instant, and N simultaneous dispatches of the
            # same sharded program can rendezvous-deadlock XLA:CPU on
            # small hosts — at most one rollup kernel may be in flight,
            # and N-1 of the stampede's results were discarded anyway
            with _ROLLUP_LOCK:
                r = self._rollups
                if r is None:
                    r = self._rollups = self._compute_rollups()  # h2o3-ok: R008 intentional: the whole point of the lock is one rollup device dispatch in flight at a time
        return r

    def _compute_rollups(self) -> Rollups:
        if self.type == T_STR:
            na = sum(1 for s in self.host_data if s is None)
            return Rollups(math.nan, math.nan, math.nan, math.nan, na, 0, False)
        stats = _rollup_kernel(self.data, self.codec, self.mask)
        cnt, s, s2, mn, mx, nas, zeros, frac = (float(v) for v in stats)
        n_real_na = int(nas) - (self.padded_len - self.nrows)
        mean = s / cnt if cnt else math.nan
        var = max(0.0, s2 / cnt - mean * mean) if cnt > 1 else 0.0
        # sample sigma like RollupStats (n-1)
        sigma = math.sqrt(var * cnt / (cnt - 1)) if cnt > 1 else 0.0
        return Rollups(mn if cnt else math.nan, mx if cnt else math.nan,
                       mean, sigma, n_real_na, int(zeros), frac == 0.0)

    def invalidate_rollups(self):
        with _ROLLUP_LOCK:
            self._rollups = None

    # convenience accessors (Vec.min()/max()/mean()/sigma()/naCnt())
    def min(self): return self.rollups().min
    def max(self): return self.rollups().max
    def mean(self): return self.rollups().mean
    def sigma(self): return self.rollups().sigma
    def na_cnt(self): return self.rollups().nas
    def is_int(self): return self.rollups().is_int

    def __len__(self):
        return self.nrows


@jax.jit
def _rollup_kernel_impl(x):
    """One fused pass: count, sum, sum², min, max, NA count, zeros, frac-part."""
    isna = jnp.isnan(x)
    w = (~isna).astype(jnp.float32)
    xz = jnp.where(isna, 0.0, x)
    cnt = w.sum()
    s = xz.sum()
    s2 = (xz * xz).sum()
    mn = jnp.where(isna, jnp.inf, x).min()
    mx = jnp.where(isna, -jnp.inf, x).max()
    nas = isna.sum()
    zeros = ((xz == 0.0) & ~isna).sum()
    frac = jnp.abs(xz - jnp.round(xz)).sum()
    return jnp.stack([cnt, s, s2, mn, mx, nas.astype(jnp.float32),
                      zeros.astype(jnp.float32), frac])


def _rollup_kernel(data, codec, mask):
    # cached_jit: the closures capture only the (frozen, hashable) codec,
    # so every vec sharing a codec replays one resident program per shape
    def f(d, m):
        return _rollup_kernel_impl(_decode_f32(d, codec, m))
    if mask is None:
        return _mr.cached_jit(
            lambda d: _rollup_kernel_impl(_decode_f32(d, codec, None)))(data)
    return _mr.cached_jit(f)(data, mask)


@functools.partial(jax.jit, static_argnames=("pad", "n"))
def _sparse_densify(rows, vals, *, pad, n):
    """One cached program per (pad, n): a fresh closure here would
    recompile per call and per column."""
    base = jnp.where(jnp.arange(pad) < n, 0.0, jnp.nan)
    return base.at[rows].set(vals, mode="drop")


# ---------------------------------------------------------------------------
class StrVec(Vec):
    """Device-resident string column — the CStrChunk analog
    (water/fvec/CStrChunk.java stores string bytes + per-row offsets in the
    chunk; string Rapids prims are MRTasks over those chunks,
    water/rapids/ast/prims/string/).

    TPU-native representation: DICTIONARY ENCODING. Rows live on device as
    int32 dictionary codes (row-sharded over the mesh; -1 = NA/padding);
    the dictionary of unique strings is host metadata, typically ≪ n.
    The op classes map as:
      * value transforms (toupper/trim/gsub/substring/…): applied to the
        DICTIONARY — O(unique) host work — then codes remap through one
        device gather. A 2M-row gsub with 1k unique values costs 1k regex
        calls + one (n,)-gather, never an n-sized host object array.
      * per-row measures (strlen, countmatches): per-level table built
        host-side (O(unique)), then one device gather codes→value.
      * predicates (grep/match/==): per-level bool mask → device gather.
    The legacy n-sized host object array materializes ONLY if a consumer
    explicitly asks (`to_numpy`/`host_data`)."""

    def __init__(self, codes_dev, levels, nrows: int, host_codes=None):
        # the (padded,) i32 code plane (-1 = NA) lives behind its own
        # TierChunk, so string-heavy frames demote exactly like numeric
        # planes: HBM → host i32 bytes → disk spill file. `codes_dev`
        # may be None for a chunk born cold with `host_codes` (budgeted
        # ingest); passing BOTH gives the pager a free demote (the host
        # mirror is already canonical).
        host = (host_codes, None) if host_codes is not None else None
        self._codes_chunk = _tiering.PAGER.new_chunk(
            codes_dev, None, host=host, label="strcodes")
        self._levels = np.asarray(levels, dtype=object)
        super().__init__(None, Codec("const"), None, nrows, T_STR)

    @property
    def codes(self):
        """(padded,) i32 device codes — faults the plane to HBM."""
        return self._codes_chunk.device()[0]

    @staticmethod
    def encode(col: np.ndarray) -> "StrVec":
        """Dictionary-encode a host object array into device codes."""
        c = _mesh.cloud()
        n = len(col)
        na = np.array([s is None or (isinstance(s, float) and math.isnan(s))
                       for s in col])
        strs = np.asarray(["" if bad else str(s)
                           for s, bad in zip(col, na)], dtype=object)
        levels, inv = np.unique(strs[~na], return_inverse=True)
        codes = np.full(n, -1, np.int64)
        codes[~na] = inv
        pad = c.padded_rows(n)
        cp = np.full(pad, -1, np.int32)
        cp[:n] = codes
        if _tiering.PAGER.ingest_cold:
            # budgeted/cold ingest: park the codes in the host tier and
            # fault on first access (same contract as Vec._from_floats)
            return StrVec(None, levels, n, host_codes=cp)
        return StrVec(_mr.device_put_rows(cp), levels, n, host_codes=cp)

    # ---- Vec surface -----------------------------------------------------
    @property
    def padded_len(self) -> int:
        return int(self._codes_chunk.rows)   # shape read must not fault

    @property
    def levels_arr(self) -> np.ndarray:
        return self._levels

    @property
    def host_data(self):
        """Back-compat decode: n-sized object array ON DEMAND only."""
        codes = _mr.host_fetch(self.codes)[: self.nrows]
        out = np.empty(self.nrows, object)
        ok = codes >= 0
        out[ok] = self._levels[codes[ok]]
        return out

    @host_data.setter
    def host_data(self, v):  # Vec.__init__ assigns None; ignore
        if v is not None:
            raise AttributeError("StrVec host_data is derived")

    def to_numpy(self) -> np.ndarray:
        return self.host_data

    # ---- device string ops ----------------------------------------------
    def map_values(self, fn) -> "StrVec":
        """Value transform through the dictionary: O(unique) host calls,
        one device gather to remap codes (levels may merge)."""
        mapped = np.asarray([fn(s) for s in self._levels], dtype=object)
        new_levels, remap = (np.unique(mapped, return_inverse=True)
                             if len(mapped) else (mapped, mapped))
        tbl = jnp.asarray(np.asarray(remap, np.int32).reshape(-1)
                          if len(mapped) else np.zeros(1, np.int32))
        codes2 = _remap_codes(self.codes, tbl)
        return StrVec(codes2, new_levels, self.nrows)

    def map_values_opt(self, fn) -> "StrVec":
        """Like map_values but fn may return None (→ NA), e.g. a strsplit
        part a level doesn't have."""
        mapped = [fn(s) for s in self._levels]
        keep = [m for m in mapped if m is not None]
        new_levels, inv = (np.unique(np.asarray(keep, object),
                                     return_inverse=True)
                           if keep else (np.asarray([], object), []))
        lut = {s: i for i, s in enumerate(new_levels)}
        remap = np.asarray([-1 if m is None else lut[m] for m in mapped]
                           or [-1], np.int32)
        codes2 = _remap_codes(self.codes, jnp.asarray(remap))
        return StrVec(codes2, new_levels, self.nrows)

    def per_level_f32(self, fn) -> jax.Array:
        """(padded,) f32 measure: per-level host table + device gather
        (NaN at NA/padding rows)."""
        tbl = jnp.asarray(np.asarray(
            [float(fn(s)) for s in self._levels] or [0.0], np.float32))
        return _gather_level_f32(self.codes, tbl)

    def level_mask(self, pred) -> jax.Array:
        """(padded,) f32 0/1 predicate through the dictionary."""
        return self.per_level_f32(lambda s: 1.0 if pred(s) else 0.0)

    def _compute_rollups(self) -> Rollups:
        codes = _mr.host_fetch(self.codes)[: self.nrows]
        nas = int((codes < 0).sum())
        return Rollups(min=math.nan, max=math.nan, mean=math.nan,
                       sigma=math.nan, nas=nas, zeros=0, is_int=False)


@jax.jit
def _remap_codes(codes, tbl):
    safe = jnp.clip(codes, 0, tbl.shape[0] - 1)
    return jnp.where(codes >= 0, jnp.take(tbl, safe), -1)


@jax.jit
def _gather_level_f32(codes, tbl):
    safe = jnp.clip(codes, 0, tbl.shape[0] - 1)
    return jnp.where(codes >= 0, jnp.take(tbl, safe), jnp.nan)


# ---------------------------------------------------------------------------
class UuidVec(Vec):
    """Device-resident UUID column — the C16Chunk analog
    (water/fvec/C16Chunk.java stores each UUID as two longs in the chunk).

    TPU-native representation: the 128-bit value lives ON DEVICE as four
    row-sharded int32 lanes (padded, 4) — XLA has no native u128 and TPU
    x64 is off by default, so the C16 "two longs" become four words. NA is
    a separate device i32 mask lane (C16's NA sentinel is a reserved
    bit-pattern; a mask lane avoids stealing one of the 2^128 values).
    Supported compute is what the reference supports on UUIDs: equality /
    NA predicates (device-side lane compares) and pass-through storage;
    arithmetic intentionally raises, as in water.fvec.Vec."""

    def __init__(self, words, na, nrows: int):
        # both lanes ride ONE TierChunk (data=(padded,4) word lanes,
        # mask=(padded,) NA lane) so a UUID column demotes HBM → host
        # i32 bytes → disk as a unit, like dense planes. "flat"
        # placement: the (padded, 4) word matrix is not a 1-D packed
        # plane, so the row-shard put does not apply; consumers compare
        # whole rows and a default-device placement keeps the four
        # lanes of each row colocated.
        words_host = np.ascontiguousarray(np.asarray(words, np.int32))
        na_host = np.ascontiguousarray(np.asarray(na, np.int32))
        if _tiering.PAGER.ingest_cold:
            words_dev = na_dev = None    # born cold: fault on first use
        else:
            words_dev = jnp.asarray(words_host)
            na_dev = jnp.asarray(na_host)
        self._uuid_chunk = _tiering.PAGER.new_chunk(
            words_dev, na_dev, host=(words_host, na_host),
            label="uuid_words", put="flat")
        super().__init__(None, Codec("const"), None, nrows, T_UUID)

    @property
    def words(self):
        """(padded, 4) i32 device word lanes — faults the chunk to HBM."""
        return self._uuid_chunk.device()[0]

    @property
    def na(self):
        """(padded,) i32 NA lane (1 = NA/padding) — faults with words."""
        return self._uuid_chunk.device()[1]

    @staticmethod
    def encode(col: np.ndarray) -> "UuidVec":
        """Host UUID strings/objects -> device word lanes."""
        import uuid as _uuidlib
        c = _mesh.cloud()
        n = len(col)
        pad = c.padded_rows(n)
        words = np.zeros((pad, 4), np.int32)
        na = np.ones(pad, np.int32)
        for i, s in enumerate(col):
            if s is None or (isinstance(s, float) and math.isnan(s)) \
                    or (isinstance(s, str) and not s.strip()):
                continue
            try:
                v = (_uuidlib.UUID(str(s).strip()).int
                     if not isinstance(s, _uuidlib.UUID) else s.int)
            except (ValueError, AttributeError):
                continue                 # malformed token -> NA (C16 NA)
            for w in range(4):
                u = (v >> (32 * (3 - w))) & 0xFFFFFFFF
                words[i, w] = np.int64(u - (1 << 32) if u >= (1 << 31)
                                       else u)
            na[i] = 0
        return UuidVec(words, na, n)

    # ---- Vec surface -----------------------------------------------------
    @property
    def padded_len(self) -> int:
        return int(self._uuid_chunk.rows)   # shape read must not fault

    @property
    def host_data(self):
        """Decode to an object array of uuid.UUID (on demand only).
        staging_view: decoding a demoted column must not promote it."""
        import uuid as _uuidlib
        words_np, na_np = self._uuid_chunk.staging_view()
        W = np.asarray(words_np)[: self.nrows]
        na = np.asarray(na_np)[: self.nrows]
        out = np.empty(self.nrows, object)
        for i in range(self.nrows):
            if na[i]:
                continue
            v = 0
            for w in range(4):
                v = (v << 32) | (int(W[i, w]) & 0xFFFFFFFF)
            out[i] = _uuidlib.UUID(int=v)
        return out

    @host_data.setter
    def host_data(self, v):
        if v is not None:
            raise AttributeError("UuidVec host_data is derived")

    def to_numpy(self) -> np.ndarray:
        return self.host_data

    def as_f32(self):
        raise TypeError("UUID Vec has no numeric view (C16Chunk atd "
                        "throws in the reference too)")

    def eq(self, other: "UuidVec") -> jax.Array:
        """(padded,) f32 0/1 row equality, computed on device."""
        return _uuid_eq(self.words, self.na, other.words, other.na)

    def isna_f32(self) -> jax.Array:
        return jnp.asarray(self.na, jnp.float32)

    def na_cnt(self) -> int:
        # staging_view: rollups on a demoted column must not promote it
        na_np = self._uuid_chunk.staging_view()[1]
        return int(np.asarray(na_np)[: self.nrows].sum())

    def _compute_rollups(self) -> Rollups:
        return Rollups(min=math.nan, max=math.nan, mean=math.nan,
                       sigma=math.nan, nas=self.na_cnt(), zeros=0,
                       is_int=False)


@jax.jit
def _uuid_eq(wa, na_a, wb, na_b):
    same = jnp.all(wa == wb, axis=1)
    ok = (na_a == 0) & (na_b == 0)
    return jnp.where(ok & same, 1.0, 0.0).astype(jnp.float32)


# ---------------------------------------------------------------------------
class SparseVec(Vec):
    """Sparse numeric column — the CXIChunk/CXFChunk analog
    (water/fvec/CXIChunk.java: compressed sparse chunks storing only
    nonzero (offset, value) pairs; the overwhelming majority of values are
    an implicit zero).

    Device representation: sorted nonzero row indices (i32) + values (f32).
    NAs are stored as explicit NaN values at their rows. `as_f32()`
    densifies on demand (small frames / fallback consumers); wide-sparse
    compute paths (GLM sparse rows, hex/DataInfo.java:23) consume
    (nz_rows, nz_vals) directly via Frame.sparse_coo and never densify.
    """

    def __init__(self, nz_rows, nz_vals, nrows: int, type: str = T_NUM):
        c = _mesh.cloud()
        # both nz planes live behind TierChunks (the StrVec code-plane
        # pattern), so wide-sparse frames demote HBM → host i32/f32
        # bytes → disk exactly like dense planes. Construction sites
        # pass host arrays (npz import, parser CSC split), so the host
        # mirror is canonical for free and demote never re-fetches.
        rows_host = np.ascontiguousarray(np.asarray(nz_rows, np.int32))
        vals_host = np.ascontiguousarray(np.asarray(nz_vals, np.float32))
        if _tiering.PAGER.ingest_cold:
            rows_dev = vals_dev = None    # born cold: fault on first use
        else:
            rows_dev = jnp.asarray(rows_host)
            vals_dev = jnp.asarray(vals_host)
        self._nzr_chunk = _tiering.PAGER.new_chunk(
            rows_dev, None, host=(rows_host, None), label="sparse_rows",
            put="flat")
        self._nzv_chunk = _tiering.PAGER.new_chunk(
            vals_dev, None, host=(vals_host, None), label="sparse_vals",
            put="flat")
        self._pad = c.padded_rows(nrows)
        super().__init__(None, Codec("const", const_val=0.0), None,
                         nrows, type)

    # ---- Vec surface -----------------------------------------------------
    @property
    def nz_rows(self):
        """(nnz,) i32 device row indices — faults the plane to HBM."""
        return self._nzr_chunk.device()[0]

    @property
    def nz_vals(self):
        """(nnz,) f32 device values — faults the plane to HBM."""
        return self._nzv_chunk.device()[0]

    @property
    def nnz(self) -> int:
        return int(self._nzr_chunk.rows)   # shape read must not fault

    @property
    def padded_len(self) -> int:
        return self._pad

    def as_f32(self) -> jax.Array:
        return _sparse_densify(self.nz_rows, self.nz_vals,
                               pad=self._pad, n=self.nrows)

    def _compute_rollups(self) -> Rollups:
        # staging_view: rollups on a demoted column must not promote it
        v = np.asarray(self._nzv_chunk.staging_view()[0])
        ok = v[~np.isnan(v)]
        n = self.nrows
        nas = int(np.isnan(v).sum())
        implicit_zeros = n - len(v)          # rows absent from nz storage
        zeros = implicit_zeros + int((ok == 0).sum())
        cnt = max(n - nas, 1)
        mean = ok.sum() / cnt
        var = (ok * ok).sum() / cnt - mean * mean
        var *= cnt / max(cnt - 1, 1)         # sample sigma like RollupStats
        if len(ok) == 0:
            mn = mx = 0.0
        elif implicit_zeros > 0:             # implicit zeros exist only
            mn = float(min(ok.min(), 0.0))   # when some row is absent
            mx = float(max(ok.max(), 0.0))
        else:
            mn, mx = float(ok.min()), float(ok.max())
        return Rollups(
            min=mn, max=mx,
            mean=float(mean), sigma=float(math.sqrt(max(var, 0.0))),
            nas=nas, zeros=int(zeros),
            is_int=bool(len(ok) == 0 or np.all(ok == np.floor(ok))))


# ---------------------------------------------------------------------------
class Frame:
    """A named, ordered set of equal-length Vecs (Frame.java:64)."""

    def __init__(self, names: Sequence[str], vecs: Sequence[Vec],
                 key: Optional[str] = None):
        assert len(names) == len(vecs)
        ns = {v.nrows for v in vecs}
        assert len(ns) <= 1, f"ragged frame: row counts {ns}"
        self.names = list(names)
        self.vecs = list(vecs)
        self.key = key or DKV.make_key("frame")
        self._matrix_cache: dict = {}
        DKV.put(self.key, self)
        # Cleaner wakeup point: account this frame, spill cold ones if the
        # HBM budget is exceeded (water/Cleaner.java:11)
        from h2o3_tpu.core.memory import MANAGER
        MANAGER.touch(self.key)
        MANAGER.maybe_clean()

    # ---- construction ---------------------------------------------------
    @staticmethod
    def from_dict(cols: dict, key: Optional[str] = None,
                  column_types: Optional[dict] = None) -> "Frame":
        names, vecs = [], []
        for name, col in cols.items():
            t = (column_types or {}).get(name)
            names.append(str(name))
            vecs.append(Vec.from_numpy(np.asarray(col), type=t))
        return Frame(names, vecs, key)

    @staticmethod
    def from_numpy(mat: np.ndarray, names: Optional[Sequence[str]] = None,
                   key: Optional[str] = None) -> "Frame":
        mat = np.asarray(mat)
        if mat.ndim == 1:
            mat = mat[:, None]
        names = list(names) if names else [f"C{i+1}" for i in range(mat.shape[1])]
        return Frame(names, [Vec.from_numpy(mat[:, j]) for j in range(mat.shape[1])], key)

    @staticmethod
    def from_pandas(df, key=None) -> "Frame":
        return Frame.from_dict({c: df[c].to_numpy() for c in df.columns}, key)

    # ---- shape ----------------------------------------------------------
    @property
    def nrows(self) -> int:
        return self.vecs[0].nrows if self.vecs else 0

    @property
    def ncols(self) -> int:
        return len(self.vecs)

    @property
    def padded_len(self) -> int:
        return self.vecs[0].padded_len if self.vecs else 0

    @property
    def shape(self):
        return (self.nrows, self.ncols)

    @property
    def types(self) -> dict:
        return {n: v.type for n, v in zip(self.names, self.vecs)}

    def vec(self, name) -> Vec:
        """Column by name or positional index (h2o-py frames accept both)."""
        if isinstance(name, (int, np.integer)):
            return self.vecs[int(name)]
        return self.vecs[self.names.index(name)]

    def col_idx(self, name: str) -> int:
        return self.names.index(name)

    # ---- column select / mutation ---------------------------------------
    def __getitem__(self, sel):
        if isinstance(sel, str):
            return Frame([sel], [self.vec(sel)])
        if isinstance(sel, (list, tuple)):
            if all(isinstance(s, str) for s in sel):
                return Frame(list(sel), [self.vec(s) for s in sel])
            return Frame([self.names[i] for i in sel], [self.vecs[i] for i in sel])
        raise KeyError(sel)

    def __setitem__(self, name: str, value):
        if isinstance(value, Frame):
            value = value.vecs[0]
        if isinstance(value, np.ndarray):
            value = Vec.from_numpy(value)
        if not isinstance(value, Vec):
            value = Vec.from_numpy(np.asarray(value))
        assert value.nrows == self.nrows or self.ncols == 0
        if name in self.names:
            self.vecs[self.names.index(name)] = value
        else:
            self.names.append(name)
            self.vecs.append(value)
        self._matrix_cache.clear()

    def drop(self, names) -> "Frame":
        if isinstance(names, str):
            names = [names]
        keep = [n for n in self.names if n not in names]
        return self[keep]

    # ---- dense matrix view (the DataInfo feed) --------------------------
    def matrix(self, cols: Optional[Sequence[str]] = None,
               dtype=jnp.float32) -> jax.Array:
        """(padded_rows, k) row-sharded dense matrix; NAs/padding → NaN.

        Cached per column-tuple. This is the hand-off point from the packed
        columnar store to MXU-shaped compute.
        """
        cols = tuple(cols if cols is not None else self.names)
        ck = (cols, str(dtype))
        hit = self._matrix_cache.get(ck)
        if hit is not None:
            return hit
        vs = [self.vec(c) for c in cols]
        # bounded-lookahead faulting, ONE device() per column (both
        # planes from a single fault — touching .data then .mask would
        # fault a demoted chunk twice): the I/O worker tiers up the next
        # couple of columns while the main thread faults the current one.
        # sparse columns densify through as_f32 (already decoded f32 with
        # NaN padding) — _decode_f32 cannot read their data=None layout
        planes = _mr.map_chunked(
            lambda v: (v.as_f32(), None) if isinstance(v, SparseVec)
            else v._chunk.device(),
            vs, lookahead=2)
        datas = [p[0] for p in planes]
        masks = [p[1] for p in planes]
        codecs = tuple(Codec("f32") if isinstance(v, SparseVec) else v.codec
                       for v in vs)

        def build(datas, masks):
            cols_f32 = [_decode_f32(d, c, m)
                        for d, c, m in zip(datas, codecs, masks)]
            return jnp.stack(cols_f32, axis=1).astype(dtype)

        out_sh = _mesh.cloud().rows_sharding(2)
        # cached_jit: build captures (codecs, dtype) — both hashable — so
        # re-materializing a same-schema matrix reuses one program
        m = _mr.cached_jit(build, out_shardings=out_sh)(datas, masks)
        self._matrix_cache[ck] = m
        return m

    def is_sparse(self, cols=None) -> bool:
        cols = cols if cols is not None else self.names
        return all(isinstance(self.vec(c), SparseVec) for c in cols)

    def sparse_coo(self, cols=None):
        """Global COO of sparse columns: (row_idx, col_idx, vals, (n, C))
        device arrays — the hand-off to sparse-rows compute (the
        hex/DataInfo.java:23 sparse iterator analog). NaN values mean NA;
        consumers decide their NA policy (GLM's sparse mode zero-imputes,
        matching its implicit zeros; mean-centering would densify)."""
        cols = list(cols if cols is not None else self.names)
        rows_l, cols_l, vals_l = [], [], []
        for j, c in enumerate(cols):
            v = self.vec(c)
            assert isinstance(v, SparseVec), f"{c} is not sparse"
            rows_l.append(v.nz_rows)
            cols_l.append(jnp.full(v.nnz, j, jnp.int32))
            vals_l.append(v.nz_vals)
        return (jnp.concatenate(rows_l), jnp.concatenate(cols_l),
                jnp.concatenate(vals_l), (self.nrows, len(cols)))

    # ---- host round-trip -------------------------------------------------
    def to_numpy(self, cols=None) -> np.ndarray:
        cols = cols if cols is not None else self.names
        return np.column_stack([self.vec(c).to_numpy() for c in cols])

    def as_data_frame(self):
        import pandas as pd
        out = {}
        for n, v in zip(self.names, self.vecs):
            x = v.to_numpy()
            if v.type == T_CAT:
                dom = v.domain
                x = np.array([None if np.isnan(c) else dom[int(c)] for c in x],
                             dtype=object)
            out[n] = x
        return pd.DataFrame(out)

    def head(self, n=10):
        return self.as_data_frame().head(n)

    # ---- summary (REST /3/Frames summary) --------------------------------
    def summary(self) -> dict:
        # chunked iteration with lookahead: rollups fault one column at a
        # time, so the pager tiers up column j+1 while j's kernel runs
        rolls = _mr.map_chunked(
            lambda v: None if v.type == T_STR else v.rollups(),
            self.vecs, lookahead=2)
        out = {}
        for n, v, r in zip(self.names, self.vecs, rolls):
            if r is None:
                out[n] = {"type": v.type}
                continue
            out[n] = {"type": v.type, "min": r.min, "max": r.max,
                      "mean": r.mean, "sigma": r.sigma, "missing": r.nas,
                      "zeros": r.zeros,
                      "cardinality": v.cardinality}
        return out

    def _tier_on_get(self):
        """DKV.get hook: LRU-touch this frame's chunks — numeric planes,
        StrVec dictionary code planes, SparseVec nz planes and UuidVec
        word lanes alike; a whole-frame spill (every chunk on disk)
        promotes its codec bytes back to host RAM, HBM faults stay lazy
        (raw_get never calls this)."""
        chunks = []
        for v in self.vecs:
            for attr in ("_chunk", "_codes_chunk", "_nzr_chunk",
                         "_nzv_chunk", "_uuid_chunk"):
                ch = getattr(v, attr, None)
                if ch is not None:
                    chunks.append(ch)
        _tiering.PAGER.on_frame_get(chunks)

    def _on_remove(self):
        # Vecs may be shared with other frames (column slices, adapted test
        # frames) — drop only our caches; device arrays (and their pager
        # chunks + spill files) are freed by refcount/GC.
        self._matrix_cache.clear()

    def __repr__(self):
        return f"<Frame {self.key} {self.nrows}x{self.ncols} {self.names[:8]}>"


# ---------------------------------------------------------------------------
def rebalance_frame(frame: "Frame", key: Optional[str] = None) -> "Frame":
    """RebalanceDataSet.java analog: rebuild every Vec against the CURRENT
    cloud sharding/padding. H2O re-chunks to re-spread work across nodes;
    here re-sharding matters after the mesh shape changed (frames created
    under an old mesh keep their old layout) or to defragment after slicing."""
    names, vecs = [], []
    for n, v in zip(frame.names, frame.vecs):
        if v.type == T_STR:
            vecs.append(Vec.from_numpy(v.host_data, type=T_STR))
        else:
            col = v.to_numpy()
            mask = np.isnan(col) if v.type != T_CAT else np.isnan(col)
            vecs.append(Vec._from_floats(np.where(mask, 0.0, col), mask,
                                         v.type, v.domain))
        names.append(n)
    return Frame(names, vecs, key)
