"""Temp-key tracking: automatic cleanup of intermediate frames/models.

Reference: water/Scope.java — a per-thread stack of "tracked" keys; everything
tracked inside enter()/exit() that isn't explicitly kept is removed, so
MRTask-heavy algorithms don't leak Vecs. The test harness leak-checker
(water/runner/CheckKeysTask.java) is built on the same idea.

Here: a context manager; on exit every key created inside (and not kept) is
dropped from the registry, freeing its HBM-backed arrays.
"""

from __future__ import annotations

import contextlib
import threading

from h2o3_tpu.core.kvstore import DKV

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


def track(key: str) -> str:
    if _stack():
        _stack()[-1].add(key)
    return key


def untrack(key: str):
    for fr in _stack():
        fr.discard(key)


@contextlib.contextmanager
def scope(keep=()):
    """with scope(keep=[model.key]): ... — everything else created is freed."""
    before = set(DKV.keys())
    frame: set = set()
    _stack().append(frame)
    try:
        yield frame
    finally:
        _stack().pop()
        created = (set(DKV.keys()) - before) | frame
        keepset = set(keep if not isinstance(keep, str) else [keep])
        for k in created - keepset:
            DKV.remove(k)
