from h2o3_tpu.core.kvstore import DKV
from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.core.jobs import Job
